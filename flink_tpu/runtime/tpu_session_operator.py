"""Device-path session windows: per-slice fragments + vectorized gap-merge.

The reference merges session windows per record through MergingWindowSet
(WindowOperator.java:303-403, EventTimeSessionWindows.java): each element's
[ts, ts+gap) window is merged with intersecting in-flight windows, state
namespaces are merged, and the merged window's trigger fires when the
watermark passes its end.

The TPU-native re-design exploits one invariant: with slice width == gap,
ALL events that land in the same slice belong to the same session (any two
timestamps in a slice differ by < gap). Ingest therefore needs no merge
logic at all — it is the same columnar scatter as the sliced aggregates,
accumulating per-(key, slice) *fragments*:

    count[k, s], min_rel[k, s], max_rel[k, s], field[k, s]...

(min/max are stored slice-relative so int32 device arithmetic never
overflows millisecond timestamps). Merging collapses to a LINEAR SCAN over
the slice axis: fragment s+i joins the current session iff
``min_ts(frag) - max_ts(session) < gap``; the scan is vectorized over the
whole key dimension at once (numpy [K]-wide ops per slice column, ~S tiny
ops per watermark instead of per-record hash-map surgery). A session is
emitted when a later fragment proves a gap, or when the watermark passes
``max_ts + gap - 1``; emitted cells purge, open sessions stay resident.

Late contract: a record whose standalone session is already expired
(ts + gap - 1 <= watermark) is dropped and counted, matching the oracle
whenever the stream's out-of-orderness is below the session gap (the
merging analogue of isWindowLate, WindowOperator.java:609). Streams with
out-of-orderness >= gap should use the oracle operator, which implements
the order-dependent late-merge semantics exactly.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.api.windowing.assigners import EventTimeSessionWindows
from flink_tpu.core.time import MIN_WATERMARK, TimeWindow
from flink_tpu.ops.aggregators import DeviceAggregator, VALUE, resolve
from flink_tpu.state.columnar import KeyDictionary

_NP_COMBINE = {"add": np.add, "min": np.minimum, "max": np.maximum}


@functools.lru_cache(maxsize=None)
def _build_ingest(K: int, S: int, B: int, vfields: tuple):
    import jax
    import jax.numpy as jnp

    def run(cnt, mn, mx, fields, kid, spos, rel, vals):
        flat = jnp.where(kid >= 0, kid * S + spos, K * S)
        cnt = cnt.reshape(-1).at[flat].add(1, mode="drop").reshape(K, S)
        mn = mn.reshape(-1).at[flat].min(rel, mode="drop").reshape(K, S)
        mx = mx.reshape(-1).at[flat].max(rel, mode="drop").reshape(K, S)
        new_fields = []
        for (name, dt, scatter), f in zip(vfields, fields):
            upd = getattr(f.reshape(-1).at[flat], scatter)
            new_fields.append(
                upd(vals.astype(dt), mode="drop").reshape(K, S)
            )
        return cnt, mn, mx, tuple(new_fields)

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _build_take(nf: int):
    """Gather the resident span's columns: one int stack + field tuple."""
    import jax
    import jax.numpy as jnp

    def run(cnt, mn, mx, fields, pos):
        ints = jnp.stack([cnt[:, pos], mn[:, pos], mx[:, pos]])
        return ints, tuple(f[:, pos] for f in fields)

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _build_precheck(g: int):
    """Scalar 'any session closable at wm?' test — a fragment whose
    max_ts + g - 1 <= wm must exist for any emission to be possible, so the
    expensive span pull + merge scan is skipped (one bool crosses the link)
    while every resident session is provably still open."""
    import jax
    import jax.numpy as jnp

    def run(cnt, mx, pos, s_rel, wm_rel):
        c = cnt[:, pos]
        m = mx[:, pos] + s_rel[None, :] * g
        return jnp.any((c > 0) & (m + g - 1 <= wm_rel))

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _build_purge(K: int, S: int, nf: int, idents: tuple, dts: tuple, g: int):
    import jax
    import jax.numpy as jnp

    def run(cnt, mn, mx, fields, keep_mask):
        cnt = jnp.where(keep_mask, cnt, 0)
        mn = jnp.where(keep_mask, mn, g)
        mx = jnp.where(keep_mask, mx, -1)
        new_fields = tuple(
            jnp.where(keep_mask, f, jnp.asarray(ident, dt))
            for f, ident, dt in zip(fields, idents, dts)
        )
        return cnt, mn, mx, new_fields

    return jax.jit(run)


class TpuSessionWindowOperator:
    """One shard's keyed session-window aggregation on one device."""

    def __init__(
        self,
        assigner: EventTimeSessionWindows,
        aggregate,
        *,
        key_capacity: int = 1 << 12,
        num_slices: int = 64,
        batch_pad: int = 256,
    ):
        agg = resolve(aggregate)
        if agg is None:
            raise ValueError(f"aggregate {aggregate!r} has no device form")
        for f in agg.fields:
            if f.source == VALUE and f.scatter not in ("add", "min", "max"):
                raise ValueError(f"unsupported scatter {f.scatter!r}")
        if not assigner.is_event_time:
            raise ValueError("TpuSessionWindowOperator is event-time only")
        self.agg: DeviceAggregator = agg
        self.g = assigner.gap
        self.S = num_slices
        self.batch_pad = batch_pad
        self.keydict = KeyDictionary()
        self.K = key_capacity

        self._vfields = tuple(
            (f.name, np.dtype(f.dtype).name, f.scatter)
            for f in agg.fields
            if f.source == VALUE
        )
        self._idents = tuple(
            f.identity for f in agg.fields if f.source == VALUE
        )
        self._init_state()

        self.current_watermark = MIN_WATERMARK
        self.ring_lo: Optional[int] = None     # lowest resident slice
        self.max_used: Optional[int] = None
        self._future: List[Tuple[Any, float, int]] = []
        self.output: List[Tuple[Any, Any, Any, int]] = []
        self.num_late_records_dropped = 0

    # ------------------------------------------------------------------
    def _init_state(self) -> None:
        import jax.numpy as jnp

        K, S = self.K, self.S
        self._cnt = jnp.zeros((K, S), jnp.int32)
        self._mn = jnp.full((K, S), self.g, jnp.int32)    # identity: > any rel
        self._mx = jnp.full((K, S), -1, jnp.int32)
        self._fields = tuple(
            jnp.full((K, S), ident, jnp.dtype(dt))
            for (_n, dt, _s), ident in zip(self._vfields, self._idents)
        )

    def ensure_key_capacity(self, required: int) -> None:
        if required <= self.K:
            return
        import jax.numpy as jnp

        new_k = 1 << (required - 1).bit_length()
        pad = new_k - self.K

        def grow(arr, fill, dt):
            return jnp.concatenate(
                [arr, jnp.full((pad, self.S), fill, dt)]
            )

        self._cnt = grow(self._cnt, 0, jnp.int32)
        self._mn = grow(self._mn, self.g, jnp.int32)
        self._mx = grow(self._mx, -1, jnp.int32)
        self._fields = tuple(
            grow(f, ident, f.dtype)
            for f, ident in zip(self._fields, self._idents)
        )
        self.K = new_k

    # ------------------------------------------------------------------
    def process_record(self, key, value, timestamp: int) -> None:
        self.process_batch(
            np.asarray([key]), np.asarray([value], dtype=np.float32),
            np.asarray([timestamp], dtype=np.int64),
        )

    def process_batch(self, keys: np.ndarray, vals: np.ndarray,
                      ts: np.ndarray) -> None:
        ts = np.asarray(ts, dtype=np.int64)
        if len(ts) == 0:
            return
        if getattr(self, "_dense", False):
            raise ValueError(
                "process_batch (keydict path) cannot be mixed with "
                "process_batch_staged dense ids on one operator"
            )
        vals = np.asarray(vals, dtype=np.float32)
        wm = self.current_watermark

        # standalone-expired records are late (see module docstring)
        late = ts + self.g - 1 <= wm
        if late.any():
            self.num_late_records_dropped += int(late.sum())
            keep = ~late
            keys, vals, ts = keys[keep], vals[keep], ts[keep]
            if len(ts) == 0:
                return

        s_abs = ts // self.g
        # the ring floor this batch will actually occupy: its own lowest
        # slice can move ring_lo DOWN, so the overflow check must use the
        # post-batch floor or aliased positions corrupt state
        lo = int(s_abs.min())
        if self.ring_lo is not None:
            lo = min(self.ring_lo, lo)
        if self.max_used is not None and self.max_used - lo >= self.S:
            # a record this far BELOW resident fragments cannot be ingested
            # (existing cells cannot be held back retroactively) — the
            # resident span must fit the ring, same contract as the fused
            # pipeline's inverted-skew check
            raise ValueError(
                f"session slice ring too small for this skew: batch slice "
                f"{lo} is {self.max_used - lo} gap-slices below resident "
                f"slice {self.max_used}, ring holds num_slices={self.S}. "
                f"Raise num_slices above the expected out-of-orderness "
                f"(in units of the session gap)."
            )
        # ring overflow: far-future records wait on host until purge opens
        # space (same hold-back contract as TpuWindowOperator._future)
        over = s_abs >= lo + self.S
        if over.any():
            for i in np.flatnonzero(over):
                self._future.append((keys[i], float(vals[i]), int(ts[i])))
            keep = ~over
            keys, vals, ts, s_abs = keys[keep], vals[keep], ts[keep], s_abs[keep]
            if len(ts) == 0:
                return

        ids, required = self.keydict.lookup_or_insert(keys)
        self.ensure_key_capacity(required)

        n = len(ts)
        padded = self.batch_pad
        while padded < n:
            padded *= 2
        kid = np.full(padded, -1, dtype=np.int32)
        kid[:n] = ids.astype(np.int32)
        spos = np.zeros(padded, dtype=np.int32)
        spos[:n] = (s_abs % self.S).astype(np.int32)
        rel = np.zeros(padded, dtype=np.int32)
        rel[:n] = (ts - s_abs * self.g).astype(np.int32)
        v = np.zeros(padded, dtype=np.float32)
        v[:n] = vals

        run = _build_ingest(self.K, self.S, padded, self._vfields)
        self._cnt, self._mn, self._mx, self._fields = run(
            self._cnt, self._mn, self._mx, self._fields, kid, spos, rel, v,
        )

        smin, smax = int(s_abs.min()), int(s_abs.max())
        self.ring_lo = smin if self.ring_lo is None else min(self.ring_lo, smin)
        self.max_used = smax if self.max_used is None else max(self.max_used, smax)

    def process_batch_staged(self, kid, spos, rel, vals,
                             smin: int, smax: int) -> None:
        """Device-staged dense-key ingest: `kid`/`spos`/`rel`/`vals` are
        device int32/float32 arrays already in ring coordinates (kid < the
        declared key capacity or -1 to drop, spos = abs_slice % S, rel =
        ts - abs_slice*gap). The caller guarantees no record is late and
        that [smin, smax] keeps the resident span inside the ring — this is
        the zero-host-copy path for device-side sources (the session
        analogue of FusedWindowPipeline.plan_superbatch staging)."""
        lo = smin if self.ring_lo is None else min(self.ring_lo, smin)
        if (self.max_used is not None and self.max_used - lo >= self.S) or (
                smax - lo >= self.S):
            raise ValueError(
                f"session slice ring too small: span [{lo}, "
                f"{max(smax, self.max_used or smax)}] exceeds num_slices={self.S}"
            )
        if len(self.keydict) > 0:
            raise ValueError(
                "process_batch_staged (dense ids) cannot be mixed with the "
                "keydict-backed process_batch path on one operator"
            )
        self._dense = True
        run = _build_ingest(self.K, self.S, int(kid.shape[0]), self._vfields)
        self._cnt, self._mn, self._mx, self._fields = run(
            self._cnt, self._mn, self._mx, self._fields, kid, spos, rel, vals,
        )
        self.ring_lo = lo
        self.max_used = smax if self.max_used is None else max(self.max_used, smax)

    def _key_of(self, kid: int):
        return kid if getattr(self, "_dense", False) else self.keydict.key_at(kid)

    # ------------------------------------------------------------------
    def process_watermark(self, watermark: int) -> None:
        if watermark <= self.current_watermark:
            return
        self.current_watermark = watermark
        if self.ring_lo is None:
            self._drain_future()
            return

        g, S = self.g, self.S
        lo, hi = self.ring_lo, self.max_used
        K = self.K
        span = hi - lo + 1
        pos_arr = np.asarray([(s % S) for s in range(lo, hi + 1)],
                             dtype=np.int32)
        import jax.numpy as jnp

        # cheap closable test before the span pull: while no fragment's
        # standalone window has expired, nothing can emit (break-closed
        # sessions wait for the watermark to pass their end — exactly the
        # oracle's trigger time)
        wm_rel = watermark - lo * g
        if wm_rel < (1 << 62) and (span + 2) * g < (1 << 31):
            pre = _build_precheck(g)
            wm_c = int(np.clip(wm_rel, -(1 << 31) + 1, (1 << 31) - 1))
            closable = pre(
                self._cnt, self._mx, jnp.asarray(pos_arr),
                jnp.arange(span, dtype=jnp.int32), jnp.int32(wm_c),
            )
            if not bool(closable):
                self._drain_future()
                return

        # pull only the resident span's columns (one gather + two transfers
        # instead of the full [K, S] state)
        take = _build_take(len(self._vfields))

        ints_d, flds_d = take(self._cnt, self._mn, self._mx, self._fields,
                              jnp.asarray(pos_arr))
        ints = np.asarray(ints_d)
        cnt = ints[0]
        mn = ints[1].astype(np.int64)
        mx = ints[2].astype(np.int64)
        fields = [np.asarray(f) for f in flds_d]

        # vectorized gap-merge scan over the resident slice span
        cur_open = np.zeros(K, dtype=bool)
        cur_min = np.zeros(K, dtype=np.int64)
        cur_max = np.zeros(K, dtype=np.int64)
        cur_cnt = np.zeros(K, dtype=np.int64)
        cur_fld = [np.full(K, ident) for ident in self._idents]
        cells = np.zeros((K, span), dtype=bool)   # current session's cells
        purge = np.zeros((K, span), dtype=bool)   # cells of emitted sessions
        emitted: List[Tuple[int, int, int, int, list]] = []  # per emit row

        def emit(mask: np.ndarray) -> None:
            for k in np.flatnonzero(mask):
                emitted.append((
                    int(cur_min[k]), int(cur_max[k]), k, int(cur_cnt[k]),
                    [f[k] for f in cur_fld],
                ))
            purge[mask] |= cells[mask]
            cells[mask] = False
            cur_open[mask] = False

        for i, s in enumerate(range(lo, hi + 1)):
            frag = cnt[:, i] > 0
            if not frag.any():
                continue
            fmn = s * g + mn[:, i]
            fmx = s * g + mx[:, i]
            # touching windows merge: [a, b) and [b, b+g) intersect per the
            # reference's TimeWindow.intersects ("just after or before"),
            # so the merge condition is gap <= g, strict only beyond it
            joins = cur_open & frag & (fmn - cur_max <= g)
            breaks = cur_open & frag & ~joins
            # a later fragment with gap >= g proves the session closed
            emit(breaks)
            starts = frag & ~joins
            cur_min[starts] = fmn[starts]
            cur_cnt[starts] = 0
            for cf, ident in zip(cur_fld, self._idents):
                cf[starts] = ident
            cur_open |= frag
            cur_max[frag] = fmx[frag]
            cur_cnt[frag] += cnt[:, i][frag]
            for cf, f, (_n, _dt, scatter) in zip(cur_fld, fields, self._vfields):
                cf[frag] = _NP_COMBINE[scatter](cf[frag], f[:, i][frag])
            cells[frag, i] = True

        # sessions whose gap the watermark itself proves
        emit(cur_open & (cur_max + g - 1 <= watermark))

        if emitted:
            # fire order: by merged-window end then key id (deterministic,
            # matching the oracle's timer ordering)
            emitted.sort(key=lambda e: (e[1] + g, e[2]))
            names = [n for n, _dt, _s in self._vfields]
            one_names = [
                f.name for f in self.agg.fields if f.source != VALUE
            ]
            for mn_ts, mx_ts, k, c, fvals in emitted:
                window = TimeWindow(mn_ts, mx_ts + g)
                fdict = dict(zip(names, fvals))
                for n in one_names:  # ONE-source fields carry the count
                    fdict[n] = c
                result = self.agg.extract(fdict)
                self.output.append(
                    (self._key_of(k), window,
                     np.asarray(result).item(), window.max_timestamp())
                )
            # scatter the span purge back to ring coordinates (span <= S so
            # each position appears once)
            keep_full = np.ones((K, S), dtype=bool)
            keep_full[:, pos_arr] = ~purge
            run = _build_purge(
                self.K, S, len(self._vfields), self._idents,
                tuple(dt for _n, dt, _s in self._vfields), g,
            )
            self._cnt, self._mn, self._mx, self._fields = run(
                self._cnt, self._mn, self._mx, self._fields, keep_full,
            )
            cnt = np.where(purge, 0, cnt)

        # advance the resident span to the surviving fragments
        live_cols = cnt.any(axis=0)
        alive_abs = [s for i, s in enumerate(range(lo, hi + 1)) if live_cols[i]]
        if alive_abs:
            self.ring_lo = min(alive_abs)
            self.max_used = max(alive_abs)
        else:
            self.ring_lo = None
            self.max_used = None
        self._drain_future()

    def _drain_future(self) -> None:
        if not self._future:
            return
        lo = self.ring_lo
        pending, self._future = self._future, []
        ready_k, ready_v, ready_t = [], [], []
        for k, v, t in pending:
            s = t // self.g
            if lo is None or s < lo + self.S:
                ready_k.append(k)
                ready_v.append(v)
                ready_t.append(t)
                if lo is None:
                    lo = s
            else:
                self._future.append((k, v, t))
        if ready_k:
            self.process_batch(
                np.asarray(ready_k), np.asarray(ready_v, dtype=np.float32),
                np.asarray(ready_t, dtype=np.int64),
            )

    def advance_processing_time(self, time: int) -> None:  # pragma: no cover
        raise NotImplementedError("event-time only")

    def drain_output(self) -> List[Tuple[Any, Any, Any, int]]:
        out = self.output
        self.output = []
        return out

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "cnt": np.asarray(self._cnt),
            "mn": np.asarray(self._mn),
            "mx": np.asarray(self._mx),
            "fields": [np.asarray(f) for f in self._fields],
            "keys": self.keydict.snapshot(),
            "watermark": self.current_watermark,
            "ring_lo": self.ring_lo,
            "max_used": self.max_used,
            "future": [(k, float(v), int(t)) for k, v, t in self._future],
            "num_late_dropped": self.num_late_records_dropped,
            "dense": getattr(self, "_dense", False),
        }

    def restore(self, snap: dict) -> None:
        import jax.numpy as jnp

        self._cnt = jnp.asarray(snap["cnt"])
        self._mn = jnp.asarray(snap["mn"])
        self._mx = jnp.asarray(snap["mx"])
        self._fields = tuple(jnp.asarray(f) for f in snap["fields"])
        self.K = int(self._cnt.shape[0])
        self.keydict = KeyDictionary.restore(snap["keys"])
        self.current_watermark = snap["watermark"]
        self.ring_lo = snap["ring_lo"]
        self.max_used = snap["max_used"]
        self._future = list(snap["future"])
        self.num_late_records_dropped = snap["num_late_dropped"]
        self._dense = snap.get("dense", False)
