"""TpuWindowOperator: batched device execution of keyed window aggregation.

The third sibling of the reference's WindowOperator / AsyncWindowOperator
(WindowOperatorBuilder.java:79 chooses among operator variants behind the
factory boundary; SURVEY.md §2.11): it accumulates a batch of
(key, window-slice, value) triples and runs ONE fused device program per
step instead of per-record state mutation, preserving the reference's
semantic contracts:

- window start/assignment math (TimeWindow.getWindowStartWithOffset,
  SlidingEventTimeWindows.assignWindows) via the slice decomposition:
  slice granule g = gcd(size, slide); window j covers slices
  [j·(slide/g), j·(slide/g) + size/g).
- EventTimeTrigger firing: window j fires when watermark >= end(j) - 1,
  emitting every key with data in the window (empty windows emit nothing —
  equivalent to "no timer was registered").
- allowed lateness: slices are retained until
  cleanup(j) = end(j) - 1 + lateness for the newest window containing them;
  late elements within lateness trigger a *masked re-fire* of each affected
  already-fired window — one re-fire per (window × batch), covering exactly
  the keys updated in the batch (per-record parity when batches are
  per-record; documented batching deviation otherwise: K intra-batch late
  updates to one key+window coalesce into one emission with the final ACC).
- too-late elements (newest containing window already cleaned) are dropped or
  side-output, matching isWindowLate/isElementLate (:609/:440-446).

Watermarks gate everything; the operator is event-time only (processing-time
windows run on the oracle operator).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.api.windowing.assigners import WindowAssigner
from flink_tpu.api.functions import LATE_DATA_TAG
from flink_tpu.core.time import MIN_WATERMARK, TimeWindow
from flink_tpu.lint.contracts import inflight_ring
from flink_tpu.ops import segment_ops
from flink_tpu.ops.aggregators import DeviceAggregator, resolve
from flink_tpu.state.columnar import ColumnarWindowState


def _ceil_div(a: int, b: int) -> int:
    return -((-a) // b)


@inflight_ring("_pending", drained_by="flush")
class TpuWindowOperator:
    """One operator instance (one shard's key space) on one device."""

    def __init__(
        self,
        assigner: WindowAssigner,
        aggregate,
        *,
        allowed_lateness: int = 0,
        key_capacity: int = 1 << 12,
        num_slices: Optional[int] = None,
        dense_int_keys: bool = False,
        emit_late_to_side_output: bool = False,
        batch_pad: int = 256,
        columnar_output: bool = False,
        ingest_kernel: str = "scatter",
        hot_key_capacity: Optional[int] = None,
        cold_tier_dir: Optional[str] = None,
    ):
        agg = resolve(aggregate)
        if agg is None:
            raise ValueError(
                f"Aggregate {aggregate!r} has no device form; use the oracle operator"
            )
        if assigner.slice_ms is None:
            raise ValueError(f"{assigner!r} is not sliceable; use the oracle operator")
        if not assigner.is_event_time:
            raise ValueError("TpuWindowOperator is event-time only")
        self.assigner = assigner
        self.agg: DeviceAggregator = agg
        self.allowed_lateness = allowed_lateness
        self.emit_late_to_side_output = emit_late_to_side_output
        self.columnar_output = columnar_output
        self.batch_pad = batch_pad

        # slice geometry (all host ints)
        self.g = assigner.slice_ms
        self.sl = assigner.slide_slices          # slices between window starts
        self.spw = assigner.slices_per_window    # slices per window
        self.offset = assigner.offset_ms
        self.size_ms = self.spw * self.g
        self.slide_ms = self.sl * self.g
        self.lateness_slices = _ceil_div(allowed_lateness, self.g)

        if num_slices is None:
            # live span ≈ window + lateness + out-of-orderness headroom
            need = self.spw + self.lateness_slices + 2 * self.spw + 16
            num_slices = 1 << (need - 1).bit_length()
        self.S = num_slices

        # hot/cold key split (S3/S4 analogue): dense ids below
        # hot_key_capacity live as device columns; the overflow aggregates
        # into the native spill store (state/cold_tier.py)
        self.hot_key_capacity = hot_key_capacity
        self.cold_tier = None
        if hot_key_capacity is not None:
            if allowed_lateness > 0:
                raise ValueError(
                    "hot/cold key tiering does not support allowed_lateness yet"
                )
            from flink_tpu.state.cold_tier import ColdKeyTier

            self.cold_tier = ColdKeyTier(agg, self.S, directory=cold_tier_dir)
            key_capacity = min(key_capacity, hot_key_capacity)

        self.state = ColumnarWindowState(
            agg,
            key_capacity=key_capacity,
            num_slices=num_slices,
            dense_int_keys=dense_int_keys,
            ingest_kernel=ingest_kernel,
        )

        self.current_watermark = MIN_WATERMARK
        self.fire_cursor: Optional[int] = None  # next window index to fire
        self._pending: List[Tuple[Any, Any, int]] = []  # (key, value, ts)
        self._future: List[Tuple[Any, Any, int]] = []   # beyond-ring records
        self.output: List[Tuple[Any, Any, Any, int]] = []
        self.side_output: Dict[str, List] = {}
        self.num_late_records_dropped = 0

    # ------------------------------------------------------------------
    # window/slice math
    # ------------------------------------------------------------------
    def slice_of(self, ts: int) -> int:
        return (ts - self.offset) // self.g

    def slice_of_np(self, ts: np.ndarray) -> np.ndarray:
        return (ts - np.int64(self.offset)) // np.int64(self.g)

    def j_newest(self, s: int) -> int:
        """Newest window index containing slice s."""
        return s // self.sl

    def j_oldest(self, s: int) -> int:
        """Oldest window index containing slice s."""
        return _ceil_div(s - self.spw + 1, self.sl)

    def window_of(self, j: int) -> TimeWindow:
        start = self.offset + j * self.slide_ms
        return TimeWindow(start, start + self.size_ms)

    def end_ms(self, j: int) -> int:
        return self.offset + j * self.slide_ms + self.size_ms

    def cleanup_of(self, j: int) -> int:
        return self.end_ms(j) - 1 + self.allowed_lateness

    def j_fired_upto(self, wm: int) -> int:
        """Largest window index whose fire time (end-1) has passed at wm."""
        return (wm + 1 - self.offset - self.size_ms) // self.slide_ms

    def j_min_live(self, wm: int) -> int:
        """Smallest window index whose cleanup time has NOT passed at wm."""
        return (wm + 1 - self.offset - self.size_ms - self.allowed_lateness) // self.slide_ms + 1

    def min_live_slice(self, wm: int) -> int:
        return self.j_min_live(wm) * self.sl

    # ------------------------------------------------------------------
    # ingestion
    # ------------------------------------------------------------------
    def process_record(self, key, value, timestamp: int) -> None:
        self._pending.append((key, value, timestamp))

    def process_batch(self, keys: np.ndarray, values: np.ndarray, timestamps: np.ndarray) -> None:
        """Columnar ingest (the executor hot path)."""
        self.flush()
        self._ingest_arrays(keys, values, np.asarray(timestamps, dtype=np.int64))

    def flush(self) -> None:
        if not self._pending:
            return
        pend, self._pending = self._pending, []
        keys = np.asarray([p[0] for p in pend], dtype=object)
        vals = np.asarray([p[1] for p in pend], dtype=np.float32)
        ts = np.asarray([p[2] for p in pend], dtype=np.int64)
        self._ingest_arrays(keys, vals, ts)

    def _ring_floor(self, batch_min_slice: int) -> int:
        f = self.state.frontiers
        floor = batch_min_slice
        if f.min_used is not None:
            floor = min(floor, f.min_used)
        if f.purged_to is not None:
            floor = max(floor, f.purged_to)
        return floor

    def _ingest_arrays(self, keys: np.ndarray, vals: np.ndarray, ts: np.ndarray) -> None:
        if len(ts) == 0:
            return
        wm = self.current_watermark
        s_abs = self.slice_of_np(ts)

        # 1. too-late drop (isWindowLate over the newest containing window)
        if wm > MIN_WATERMARK:
            min_live = self.min_live_slice(wm)
            late = s_abs < min_live
        else:
            late = np.zeros(len(ts), dtype=bool)
        if late.any():
            if self.emit_late_to_side_output:
                lt = self.side_output.setdefault(LATE_DATA_TAG.tag_id, [])
                for i in np.flatnonzero(late):
                    lt.append((keys[i], vals[i].item(), int(ts[i])))
            else:
                self.num_late_records_dropped += int(late.sum())

        keep = ~late
        if not keep.any():
            return

        # 2. ring-overflow: records too far in the future wait on host
        batch_min = int(s_abs[keep].min())
        floor = self._ring_floor(batch_min)
        over = keep & (s_abs >= floor + self.S)
        if over.any():
            for i in np.flatnonzero(over):
                self._future.append((keys[i], vals[i], int(ts[i])))
            keep = keep & ~over
            if not keep.any():
                return

        # 3. dense key ids (grow capacity first so the scatter shape is right)
        kid = np.full(len(ts), segment_ops.INVALID_INDEX, dtype=np.int64)
        ids, required = self.state.keydict.lookup_or_insert(keys[keep])
        if self.cold_tier is not None and required > self.hot_key_capacity:
            # overflow ids take the host/LSM path with ABSOLUTE slices
            cold = ids >= self.hot_key_capacity
            if cold.any():
                keep_idx = np.flatnonzero(keep)
                cold_rows = keep_idx[cold]
                self.cold_tier.ingest(
                    (ids[cold] - self.hot_key_capacity).astype(np.int64),
                    s_abs[cold_rows],
                    np.asarray(vals[cold_rows], dtype=np.float32),
                )
                ids = np.where(cold, segment_ops.INVALID_INDEX, ids)
            required = min(required, self.hot_key_capacity)
        self.state.ensure_key_capacity(required)
        kid[keep] = ids

        # 4. pad to bucketed batch size (bounds jit re-compilation)
        n = len(ts)
        padded = self.batch_pad
        while padded < n:
            padded *= 2
        if padded != n:
            kid = np.concatenate([kid, np.full(padded - n, segment_ops.INVALID_INDEX, dtype=np.int64)])
            s_abs = np.concatenate([s_abs, np.zeros(padded - n, dtype=np.int64)])
            vals = np.concatenate([vals, np.zeros(padded - n, dtype=vals.dtype)])

        kid32 = np.where(
            kid == segment_ops.INVALID_INDEX, segment_ops.INVALID_INDEX, kid
        ).astype(np.int32)
        self.state.ingest(kid32, s_abs, vals)
        if self.cold_tier is not None:
            # cold-only slices must still advance the used-slice frontiers
            f = self.state.frontiers
            lo, hi = int(s_abs[:n][keep].min()), int(s_abs[:n][keep].max())
            f.min_used = lo if f.min_used is None else min(f.min_used, lo)
            f.max_used = hi if f.max_used is None else max(f.max_used, hi)

        # 5. fire-cursor init/advance bookkeeping
        live_slices = s_abs[:n][keep]
        cand = self.j_oldest(int(live_slices.min()))
        if wm > MIN_WATERMARK:
            cand = max(cand, self.j_fired_upto(wm) + 1)
        self.fire_cursor = cand if self.fire_cursor is None else min(self.fire_cursor, cand)

        # 6. late re-fires: already-fired live windows touched by this batch
        if wm > MIN_WATERMARK:
            fired_hi = self.j_fired_upto(wm)
            lo = self.j_oldest(int(live_slices.min()))
            hi = min(self.j_newest(int(live_slices.max())), fired_hi)
            lo = max(lo, self.j_min_live(wm))
            for j in range(lo, hi + 1):
                self._emit_window(j, touch_mask=True)

    # ------------------------------------------------------------------
    # watermark advance: fire then purge (mirrors onEventTime: trigger fire
    # precedes cleanup at the same timestamp)
    # ------------------------------------------------------------------
    def process_watermark(self, watermark: int) -> None:
        self.flush()
        if watermark <= self.current_watermark:
            return
        # Staged advance: beyond-ring records buffered on host must be
        # ingested BEFORE the watermark passes their windows' fire times
        # (in the reference they were processed in stream order; the ring is
        # our resource limit, so we open ring space first, then continue).
        while True:
            step_target = watermark
            if self._future:
                min_s = min(self.slice_of(ts) for _, _, ts in self._future)
                wm_open = self._ring_opening_watermark(min_s)
                if wm_open > self.current_watermark:
                    step_target = min(watermark, wm_open)
            self._advance_to(step_target)
            self._drain_future()
            if step_target >= watermark:
                break

    def _ring_opening_watermark(self, s: int) -> int:
        """Smallest watermark at which slice s fits in the ring (i.e. the
        purge frontier has advanced past s - S)."""
        q = (s - self.S) // self.sl + 1  # need j_min_live > (s - S) / sl
        return q * self.slide_ms + self.offset + self.size_ms + self.allowed_lateness - 1

    def _advance_to(self, watermark: int) -> None:
        if watermark <= self.current_watermark:
            return
        f = self.state.frontiers
        # 1. fire newly-eligible windows in time order
        if self.fire_cursor is not None and f.max_used is not None:
            hi = min(self.j_fired_upto(watermark), self.j_newest(f.max_used))
            for j in range(self.fire_cursor, hi + 1):
                self._emit_window(j, touch_mask=False)
            if self.j_fired_upto(watermark) >= self.fire_cursor:
                self.fire_cursor = self.j_fired_upto(watermark) + 1

        # 2. purge expired slices (cleanup frontier)
        new_min_live = self.min_live_slice(watermark)
        if f.min_used is not None:
            purge_from = f.min_used if f.purged_to is None else max(f.purged_to, f.min_used)
            purge_to = min(new_min_live, f.max_used + 1)
            if purge_to - purge_from >= self.S:
                self.state.reset_all()  # entire used range expired
            elif purge_to > purge_from:
                self.state.purge_slices(list(range(purge_from, purge_to)))
        f.purged_to = new_min_live if f.purged_to is None else max(f.purged_to, new_min_live)
        if self.cold_tier is not None:
            # same retention cut for spilled rows: without it the LSM keeps
            # every (key, slice) cell ever written and grows without bound
            self.cold_tier.purge_below_slice(new_min_live)

        self.current_watermark = watermark

    def _drain_future(self) -> None:
        if not self._future:
            return
        fut, self._future = self._future, []
        keys = np.asarray([p[0] for p in fut], dtype=object)
        vals = np.asarray([p[1] for p in fut], dtype=np.float32)
        ts = np.asarray([p[2] for p in fut], dtype=np.int64)
        self._ingest_arrays(keys, vals, ts)  # unfit records re-buffer themselves

    def advance_processing_time(self, time: int) -> None:
        pass  # event-time only

    # ------------------------------------------------------------------
    # emission
    # ------------------------------------------------------------------
    # emission-latency plane: set by the runner; _emit_window is where this
    # operator's fires become host-visible (np.asarray readback below)
    emission_tracker = None

    def _emit_window(self, j: int, *, touch_mask: bool) -> None:
        window = self.window_of(j)
        if self.emission_tracker is not None:
            self.emission_tracker.record_fire(
                window.end, lateness_ms=self.allowed_lateness)
        start_slice = j * self.sl
        result, cnt, mask = self.state.fire(
            range(start_slice, start_slice + self.spw), touch_mask=touch_mask
        )
        mask_np = np.asarray(mask)
        ts = window.max_timestamp()
        keydict = self.state.keydict

        cold_emit = None
        if self.cold_tier is not None:
            n_cold = max(0, keydict.num_ids - self.hot_key_capacity)
            if n_cold:
                c_res, c_cnt = self.cold_tier.fire(
                    n_cold, range(start_slice, start_slice + self.spw)
                )
                live = np.flatnonzero(c_cnt > 0)
                if live.size:
                    cold_emit = (live, c_res[live])

        if not mask_np.any() and cold_emit is None:
            return
        idxs = np.flatnonzero(mask_np)
        result_np = np.asarray(result)
        if self.columnar_output:
            if cold_emit is not None:
                idxs = np.concatenate([idxs, cold_emit[0] + self.hot_key_capacity])
                result_np = np.concatenate(
                    [result_np[np.flatnonzero(mask_np)], cold_emit[1]]
                ) if mask_np.any() else cold_emit[1]
                self.output.append((None, window, (idxs, result_np), ts))
                return
            self.output.append((None, window, (idxs, result_np[idxs]), ts))
            return
        for i in idxs:
            self.output.append((keydict.key_at(int(i)), window, result_np[i].item(), ts))
        if cold_emit is not None:
            for ci, cv in zip(cold_emit[0], cold_emit[1]):
                self.output.append(
                    (keydict.key_at(int(ci) + self.hot_key_capacity), window,
                     cv.item(), ts)
                )

    def drain_output(self) -> List[Tuple[Any, Any, Any, int]]:
        out = self.output
        self.output = []
        return out

    # -- observability gauges ------------------------------------------
    def state_bytes(self) -> int:
        return self.state.state_bytes()

    def state_key_count(self) -> int:
        return len(self.state.keydict)

    def key_loads(self):
        """Device-resident per-key record counts ([K] int32) for the
        key-stats fold (metrics/key_stats.py) — one segment-sum over the
        count ring already in HBM."""
        count = getattr(self.state, "count", None)
        return None if count is None else count.sum(axis=1)

    def key_stats_ready(self) -> bool:
        """O(1) host probe: any slice ever written to the device ring."""
        return self.state.frontiers.max_used is not None

    def state_row_bytes(self) -> int:
        """HBM bytes per key row across count + accumulator columns."""
        import numpy as _np

        n = 4 * self.state.S
        for a in self.state.acc.values():
            n += _np.dtype(getattr(a, "dtype", _np.float32)).itemsize \
                * self.state.S
        return n

    # ------------------------------------------------------------------
    # snapshot / restore
    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        self.flush()
        return {
            "columnar": self.state.snapshot(),
            "watermark": self.current_watermark,
            "fire_cursor": self.fire_cursor,
            "future": [(k, float(v), int(t)) for k, v, t in self._future],
            "num_late_dropped": self.num_late_records_dropped,
            "side_output": {k: list(v) for k, v in self.side_output.items()},
            "cold": self.cold_tier.snapshot() if self.cold_tier is not None else None,
        }

    def restore(self, snap: dict) -> None:
        self.state.restore(snap["columnar"])
        self.current_watermark = snap["watermark"]
        self.fire_cursor = snap["fire_cursor"]
        self._future = list(snap["future"])
        self.num_late_records_dropped = snap["num_late_dropped"]
        self.side_output = {
            k: list(v) for k, v in snap.get("side_output", {}).items()
        }
        if snap.get("cold") is not None and self.cold_tier is not None:
            self.cold_tier.restore(snap["cold"])
        self._pending = []
        self.output = []
