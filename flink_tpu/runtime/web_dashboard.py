"""Single-file web dashboard served by the REST server at '/'.

A deliberately dependency-free stand-in for the reference's Angular SPA
(flink-runtime-web/web-dashboard): one HTML document with inline JS that
polls the same public REST endpoints a human or script would use
(/overview, /jobs, /jobs/<id>, /jobs/<id>/metrics, /jobs/<id>/traces) and
renders job state, throughput, busy ratio, checkpoints, restarts, and the
checkpoint span feed. Deep links: Prometheus text at /metrics, flame graphs
at /flamegraph.
"""

DASHBOARD_HTML = """<!DOCTYPE html>
<html><head><title>flink-tpu dashboard</title>
<meta charset="utf-8">
<style>
 :root { --bg:#101418; --panel:#161b22; --line:#2e3440; --fg:#d8dee9;
         --dim:#7a8494; --ok:#a3be8c; --info:#81a1c1; --bad:#bf616a;
         --warn:#ebcb8b; }
 body { font-family: ui-monospace, Menlo, monospace; margin: 0;
        background: var(--bg); color: var(--fg); }
 header { padding: 14px 22px; border-bottom: 1px solid var(--line);
          display: flex; gap: 18px; align-items: baseline; }
 h1 { font-size: 1.05rem; margin: 0; }
 h3 { font-size: 0.82rem; margin: 14px 0 4px; color: var(--dim);
      font-weight: normal; text-transform: uppercase; letter-spacing: 1px; }
 #overview { color: var(--dim); }
 header a { color: var(--info); text-decoration: none; margin-left: 10px; }
 main { padding: 18px 22px; }
 table { border-collapse: collapse; width: 100%; }
 td, th { border-bottom: 1px solid var(--line); padding: 7px 12px;
          text-align: left; font-size: 0.86rem; }
 th { color: var(--dim); font-weight: normal; }
 tr.job { cursor: pointer; }
 tr.job:hover { background: var(--panel); }
 .RUNNING { color: var(--ok); } .FINISHED { color: var(--info); }
 .FAILED { color: var(--bad); }
 .CANCELED, .RESTARTING, .RESCALING, .CREATED { color: var(--warn); }
 .detail { background: var(--panel); }
 .detail td { padding: 12px 16px; }
 .kv { display: grid; grid-template-columns: repeat(auto-fill, minmax(210px, 1fr));
       gap: 6px 18px; margin-bottom: 8px; }
 .kv div span { color: var(--dim); margin-right: 6px; }
 .spans { margin-top: 8px; color: var(--dim); font-size: 0.8rem;
          max-height: 140px; overflow-y: auto; }
 .empty { color: var(--dim); padding: 30px 0; }
</style></head>
<body>
<header>
  <h1>flink-tpu &mdash; streaming on TPU</h1>
  <div id="overview">loading&hellip;</div>
  <nav>
    <a href="/metrics">prometheus</a>
    <a href="/flamegraph">flamegraph</a>
  </nav>
</header>
<main>
  <table id="jobs"><thead>
    <tr><th>job id</th><th>name</th><th>status</th><th>records in</th>
        <th>rec/s</th><th>busy</th><th>restarts</th><th>checkpoints</th></tr>
  </thead><tbody id="rows"></tbody></table>
  <div id="none" class="empty" hidden>no jobs submitted yet</div>
</main>
<script>
const open = new Set();
const esc = (v) => String(v).replace(/[&<>"']/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const fmt = (v, d=1) => v == null ? "-" :
  (typeof v === "number" ? (v >= 1e6 ? (v/1e6).toFixed(d)+"M"
   : v >= 1e3 ? (v/1e3).toFixed(d)+"k" : (Number.isInteger(v) ? v : v.toFixed(d))) : v);
async function j(url) { const r = await fetch(url); return r.json(); }

function kv(obj) {
  return '<div class="kv">' + Object.entries(obj).map(
    ([k, v]) => `<div><span>${k}</span>${v}</div>`).join("") + "</div>";
}

const bpClass = (r) => r > 0.5 ? "FAILED" : (r > 0.1 ? "CANCELED" : "RUNNING");
const cpClass = (s) => s === "COMPLETED" ? "RUNNING"
  : (s === "FAILED" ? "FAILED" : "CREATED");

function checkpointSection(cps) {
  // checkpoint & recovery observability: lifetime counts, last restore,
  // and the bounded per-checkpoint history ring (/jobs/:id/checkpoints)
  if (!cps || !cps.counts || !(cps.counts.total || cps.counts.failed)) return "";
  const rows = (cps.history || []).slice(0, 8).map(c => `<tr>
    <td>${esc(c.id)}</td>
    <td class="${cpClass(c.status)}">${esc(c.status)}${c.is_savepoint ? " (sp)" : ""}</td>
    <td>${fmt(c.end_to_end_duration_ms)}</td>
    <td>${fmt(c.sync_duration_ms)} / ${fmt(c.async_duration_ms)}</td>
    <td>${fmt(c.state_size_bytes)}</td>
    <td>${esc((c.failure_cause ?? "").slice(0, 60))}</td></tr>`);
  const r = cps.latest?.restored;
  return "<h3>checkpoints</h3>" + kv({
    "completed": fmt(cps.counts.completed),
    "failed": fmt(cps.counts.failed),
    "in progress": fmt(cps.counts.in_progress),
    "last restore": r == null ? "-" :
      `chk ${r.checkpoint_id ?? "?"} in ${fmt(r.restore_duration_ms)}ms`,
  }) + (rows.length ? `<table><thead><tr><th>id</th><th>status</th>
    <th>e2e ms</th><th>sync/async ms</th><th>bytes</th><th>failure</th></tr>
    </thead><tbody>${rows.join("")}</tbody></table>` : "");
}

function exceptionSection(exc) {
  // bounded exception history + recovery timeline (/jobs/:id/exceptions)
  if (!exc || !(exc.entries ?? []).length) return "";
  const entries = exc.entries.slice(0, 6).map(e => esc(
    `#${e.restart_number} ${new Date(e.timestamp_ms).toISOString()} ` +
    `[${e.task ?? "?"}${e.task_manager ? " @ " + e.task_manager : ""}] ` +
    e.exception)).join("<br>");
  const recs = (exc.recoveries ?? []).slice(0, 4).map(r => esc(
    `${r.kind === "rescale" ? "rescale" : "restart"} #${r.restart_number}: ` +
    `rewound to chk ${r.restored_checkpoint_id ?? "none"}, ` +
    `restore ${fmt(r.restore_duration_ms)}ms, downtime ${fmt(r.downtime_ms)}ms` +
    (r.steps_replayed != null ? `, ${r.steps_replayed} steps replayed` : "") +
    (r.events_replayed != null ? `, ${fmt(r.events_replayed)} events replayed` : "")
  )).join("<br>");
  return `<h3>exceptions</h3><div class="spans">${entries}</div>` +
    (recs ? `<div class="spans">${recs}</div>` : "");
}

function autoscalerSection(a) {
  // elastic autoscaler (/jobs/:id/autoscaler): parallelism, rescale
  // counters and the bounded decision log (signals seen -> action ->
  // outcome); hidden for jobs with no autoscaler and no decisions
  if (!a || (!a.enabled && !(a.decisions ?? []).length && !a.num_rescales))
    return "";
  const actClass = (d) => d.outcome === "executed" ? "RUNNING"
    : (String(d.outcome).startsWith("rejected") ? "FAILED" : "CREATED");
  const rows = (a.decisions ?? []).slice(0, 8).map(d => `<tr>
    <td>${new Date(d.timestamp_ms).toISOString().slice(11, 19)}</td>
    <td>${esc(d.action)} ${d.parallelism}&rarr;${d.target}</td>
    <td>${fmt(d.signals?.utilization, 2)}</td>
    <td class="${actClass(d)}">${esc(d.outcome)}</td>
    <td>${fmt(d.duration_ms)}</td>
    <td>${esc(String(d.reason).slice(0, 70))}</td></tr>`);
  return "<h3>autoscaler</h3>" + kv({
    "policy": esc(a.policy ?? "off"),
    "parallelism": fmt(a.parallelism),
    "rescales": fmt(a.num_rescales),
    "last rescale ms": fmt(a.last_rescale_duration_ms),
  }) + (rows.length ? `<table><thead><tr><th>at</th><th>action</th>
    <th>util</th><th>outcome</th><th>rescale ms</th><th>reason</th></tr>
    </thead><tbody>${rows.join("")}</tbody></table>` : "");
}

function deviceSection(dev) {
  // device plane (/jobs/:id/device): compile/recompile counters with the
  // bounded cause-attributed event ring, per-operator roofline %, phase
  // counters and key-skew telemetry; hidden when the plane is off
  if (!dev || !dev.enabled) return "";
  const c = dev.compile ?? {};
  const storm = c.recompileStorm
    ? '<span class="FAILED">STORM</span>' : "ok";
  const evs = (c.events ?? []).slice(-8).reverse().map(e => esc(
    `${e.program} [${e.cause}] ${fmt(e.duration_ms)}ms ` +
    `${e.signature ?? ""}`)).join("<br>");
  const ops = Object.entries(dev.operators ?? {}).map(([uid, o]) => `<tr>
    <td>${esc(uid)}</td>
    <td>${fmt(o.compile?.numCompiles)} / ${fmt(o.compile?.numRecompiles)}</td>
    <td>${fmt(o.hbmUtilizationPct, 2)} / ${fmt(o.flopsUtilizationPct, 2)}</td>
    <td>${fmt(o.phases?.ingestRecords)} / ${fmt(o.phases?.fireSteps)}
        / ${fmt(o.phases?.purgeSteps)}</td>
    <td>${fmt(o.keys?.keySkew, 2)}</td>
    <td>${fmt(o.keys?.activeKeys)}</td>
    <td>${esc((o.keys?.hotKeys ?? []).slice(0, 3)
        .map(h => h[0] + ":" + fmt(h[1])).join(" "))}</td></tr>`);
  const prof = dev.profiler ?? {};
  return "<h3>device plane</h3>" + kv({
    "compiles": fmt(c.numCompiles),
    "recompiles": fmt(c.numRecompiles),
    "compile ms": fmt(c.compileTimeMsTotal),
    "recompile storm": storm,
    "profiler captures": prof.enabled
      ? `${fmt(prof.captures)} &rarr; ${esc(prof.last_capture_dir ?? "-")}`
      : "off",
  }) + (ops.length ? `<table><thead><tr><th>operator</th>
    <th>compiles/re</th><th>hbm/flops %</th><th>ingest/fire/purge</th>
    <th>key skew</th><th>active keys</th><th>hot keys</th></tr></thead>
    <tbody>${ops.join("")}</tbody></table>` : "")
    + skewTable(dev)
    + tierTable(dev)
    + (evs ? `<div class="spans">${evs}</div>` : "");
}

function skewTable(dev) {
  // mesh skew panel (parallel.mesh.*): per-device resident load from the
  // key-stats fold, plus the skew-rebalance routing table when
  // parallel.mesh.skew-rebalance drives placement — an imbalanced mesh
  // must be visible as its worst device AND as what the rebalancer last
  // did about it
  const rows = Object.entries(dev.operators ?? {})
    .filter(([, o]) => (o.keys?.perDevice ?? []).length || o.routing)
    .map(([uid, o]) => {
      const per = (o.keys?.perDevice ?? [])
        .map(d => `${d.device}:${fmt(d.records)}`).join(" ");
      const r = o.routing ?? {};
      return `<tr><td>${esc(uid)}</td>
        <td>${fmt(o.keys?.meshLoadSkew, 2)}</td>
        <td>${esc(per)}</td>
        <td>${r.version !== undefined ? fmt(r.version) : "static"}</td>
        <td>${fmt(r.movedGroups)} / ${fmt(r.numKeyGroups)}</td></tr>`;
    });
  if (!rows.length) return "";
  return `<h3>mesh skew</h3><table><thead><tr><th>operator</th>
    <th>mesh load skew</th><th>per-device records</th>
    <th>routing version</th><th>moved/groups</th></tr></thead>
    <tbody>${rows.join("")}</tbody></table>`;
}

function tierTable(dev) {
  // million-key state plane (state.tier.*): vocabulary size vs resident
  // HBM rows, eviction/promotion churn, cold-tier footprint and the
  // incremental-checkpoint delta size per operator
  const rows = Object.entries(dev.operators ?? {})
    .filter(([, o]) => o.tier)
    .map(([uid, o]) => `<tr><td>${esc(uid)}</td>
      <td>${fmt(o.tier.vocabSize)}</td>
      <td>${fmt(o.tier.residentKeys)} / ${fmt(o.tier.hotKeyCapacity)}</td>
      <td>${fmt(o.tier.evictions)} / ${fmt(o.tier.promotions)}</td>
      <td>${fmt(o.tier.spilledBytes)}</td>
      <td>${o.tier.changelogEnabled ? fmt(o.tier.changelogBytes) : "off"}</td>
      <td>${esc(o.tier.evictionPolicy ?? "")}</td></tr>`);
  if (!rows.length) return "";
  return `<h3>state tier</h3><table><thead><tr><th>operator</th>
    <th>vocab</th><th>resident/cap</th><th>evict/promote</th>
    <th>spilled bytes</th><th>changelog bytes</th><th>policy</th>
    </tr></thead><tbody>${rows.join("")}</tbody></table>`;
}

function latencySection(lat) {
  // emission-latency plane (/jobs/:id/latency): event-time tail per
  // operator (window close -> host-visible) plus the stall-attribution
  // report mapping tail outliers onto concurrent control-plane spans
  // (checkpoint, restore/rescale, compile); hidden with no samples
  if (!lat || !(lat.samples > 0)) return "";
  const ops = Object.entries(lat.operators ?? {}).map(([uid, o]) => {
    const h = o.emissionLatencyMs ?? {};
    return `<tr><td>${esc(uid)}</td>
    <td>${fmt(h.p50, 1)} / ${fmt(h.p99, 1)} / ${fmt(h.p999, 1)}</td>
    <td>${fmt(h.max, 1)}</td>
    <td>${fmt(h.count)}</td>
    <td>${fmt(o.watermarkLagMs, 1)}</td></tr>`;
  });
  const att = lat.attribution ?? {};
  const owners = Object.entries(att.attributed ?? {}).map(([k, v]) => `<tr>
    <td>${esc(k)}</td><td>${fmt(v.count)}</td>
    <td>${fmt(v.maxLatencyMs, 1)}</td></tr>`);
  return "<h3>emission latency</h3>" + kv({
    "p50 / p99 / p999 ms": `${fmt(lat.p50_ms, 1)} / ${fmt(lat.p99_ms, 1)}` +
      ` / ${fmt(lat.p999_ms, 1)}`,
    "samples": fmt(lat.samples),
    "watermark lag ms": fmt(lat.watermarkLagMs, 1),
    "stall outliers": fmt(att.outliers),
    "unattributed": fmt(att.unattributed),
  }) + (ops.length ? `<table><thead><tr><th>operator</th>
    <th>p50/p99/p999 ms</th><th>max ms</th><th>samples</th>
    <th>wm lag ms</th></tr></thead><tbody>${ops.join("")}</tbody>
    </table>` : "")
    + (owners.length ? `<table><thead><tr><th>stall owner span</th>
    <th>outliers</th><th>max ms</th></tr></thead>
    <tbody>${owners.join("")}</tbody></table>` : "");
}

function sparkline(points, w = 180, h = 26) {
  // inline-SVG sparkline over [t, v] pairs from the history rings —
  // dependency-free, one polyline per series
  if (!points || points.length < 2) return "";
  const ts = points.map(p => p[0]), vs = points.map(p => p[1]);
  const t0 = Math.min(...ts), t1 = Math.max(...ts);
  const v0 = Math.min(...vs), v1 = Math.max(...vs);
  const sx = t => t1 > t0 ? (t - t0) / (t1 - t0) * (w - 2) + 1 : w / 2;
  const sy = v => v1 > v0 ? h - 2 - (v - v0) / (v1 - v0) * (h - 4) : h / 2;
  const pts = points.map(p => `${sx(p[0]).toFixed(1)},${sy(p[1]).toFixed(1)}`);
  return `<svg width="${w}" height="${h}" style="vertical-align:middle">
    <polyline fill="none" stroke="#81a1c1" stroke-width="1.2"
      points="${pts.join(" ")}"/></svg>`;
}

function historySection(hist) {
  // metrics history plane (/jobs/:id/history): per-key bounded rings —
  // counters shown as rates, histograms as their p50/p99 sub-series;
  // hidden until the first sampling tick lands
  if (!hist || !hist.series || !Object.keys(hist.series).length) return "";
  const rows = Object.entries(hist.series)
    .filter(([, s]) => (s.points ?? []).length >= 2)
    .slice(0, 14)
    .map(([key, s]) => {
      const last = s.points[s.points.length - 1][1];
      return `<tr><td>${esc(key)}</td>
        <td>${esc(s.kind)}</td>
        <td>${sparkline(s.points)}</td>
        <td>${fmt(last, 2)}</td></tr>`;
    });
  if (!rows.length) return "";
  return `<h3>history (${fmt(hist.sample_count)} samples @ ` +
    `${fmt(hist.interval_ms)}ms)</h3>` +
    `<table><thead><tr><th>metric</th><th>kind</th><th>trend</th>
    <th>last</th></tr></thead><tbody>${rows.join("")}</tbody></table>`;
}

function doctorSection(doc) {
  // job doctor (/jobs/:id/doctor): ranked, evidence-attributed bottleneck
  // diagnosis over the recent history window joined with the span stream
  if (!doc || !(doc.diagnoses ?? []).length && doc.verdict === "unknown")
    return "";
  const vClass = doc.verdict === "healthy" ? "RUNNING"
    : (doc.verdict === "unknown" ? "CREATED" : "FAILED");
  const rows = (doc.diagnoses ?? []).slice(0, 6).map(d => `<tr>
    <td class="${d.score >= 0.5 ? "FAILED" : "CREATED"}">${esc(d.family)}</td>
    <td>${fmt(d.score, 2)}</td>
    <td>${esc(String(d.summary ?? "").slice(0, 90))}</td>
    <td>${esc(Object.entries(d.evidence ?? {}).slice(0, 4)
        .map(([k, v]) => `${k}=${fmt(v, 2)}`).join(" "))}</td></tr>`);
  return `<h3>doctor: <span class="${vClass}">${esc(doc.verdict)}</span>` +
    ` (${fmt(doc.watchdog_events)} watchdog events)</h3>` +
    (rows.length ? `<table><thead><tr><th>family</th><th>score</th>
    <th>summary</th><th>evidence</th></tr></thead>
    <tbody>${rows.join("")}</tbody></table>` : "");
}

function operatorTable(metrics) {
  // per-operator observability: latency-marker percentiles, device time,
  // HBM state footprint — parsed from the job.operator.<uid>.* scope
  const ops = {};
  for (const [k, v] of Object.entries(metrics)) {
    const m = k.match(/^job\\.operator\\.([^.]+)\\.(.+)$/);
    if (m) (ops[m[1]] ??= {})[m[2]] = v;
  }
  const rows = Object.entries(ops).map(([uid, m]) => {
    const lat = m["latencyMs"] || {};
    const disp = m["deviceDispatchMs"] || {};
    return `<tr><td>${esc(uid)}</td>
      <td>${fmt(lat.p50)} / ${fmt(lat.p99)}</td>
      <td>${fmt(disp.p50)} / ${fmt(disp.p99)}</td>
      <td>${fmt(m["deviceTimeMsTotal"])}</td>
      <td>${fmt(m["stateBytes"])}</td>
      <td>${fmt(m["stateKeyCount"])}</td>
      <td>${fmt(m["numLateRecordsDropped"])}</td></tr>`;
  });
  if (!rows.length) return "";
  return `<table><thead><tr><th>operator</th><th>latency p50/p99 ms</th>
    <th>dispatch p50/p99 ms</th><th>device ms</th><th>state bytes</th>
    <th>keys</th><th>late dropped</th></tr></thead>
    <tbody>${rows.join("")}</tbody></table>`;
}

async function detailRow(id) {
  const [info, metrics, traces, cps, exc, auto, dev, lat, hist, doc] =
    await Promise.all([
    j(`/jobs/${id}`), j(`/jobs/${id}/metrics`),
    j(`/jobs/${id}/traces`).catch(() => ({resourceSpans: []})),
    j(`/jobs/${id}/checkpoints`).catch(() => null),
    j(`/jobs/${id}/exceptions`).catch(() => null),
    j(`/jobs/${id}/autoscaler`).catch(() => null),
    j(`/jobs/${id}/device`).catch(() => null),
    j(`/jobs/${id}/latency`).catch(() => null),
    j(`/jobs/${id}/history`).catch(() => null),
    j(`/jobs/${id}/doctor`).catch(() => null),
  ]);
  const spans = (traces.resourceSpans[0]?.scopeSpans[0]?.spans ?? []);
  const spanRows = spans.slice(-12).reverse().map(s => {
    const ms = (Number(s.endTimeUnixNano) - Number(s.startTimeUnixNano)) / 1e6;
    const at = Object.fromEntries(
      s.attributes.map(a => [a.key, Object.values(a.value)[0]]));
    return esc(`${s.name} #${at.checkpointId ?? ""} ${ms.toFixed(1)}ms ` +
               `${at.status ?? ""} ${fmt(Number(at.stateSizeBytes))}B ` +
               `trace:${(s.traceId ?? "").slice(0, 8)}`);
  }).join("<br>");
  const latency = metrics["job.stepLatencyMs"] || {};
  const bp = metrics["job.backPressuredTimeRatio"] ?? 0;
  // per-channel exchange byte rates (job.exchange.numBytes{In,Out}PerSecond.<ch>)
  // summed to task totals — nonzero only for jobs with cross-host exchanges
  const exch = dir => Object.entries(metrics)
    .filter(([k]) => k.includes(`exchange.numBytes${dir}PerSecond`))
    .reduce((a, [, v]) => a + (Number(v) || 0), 0);
  const exchOut = exch("Out"), exchIn = exch("In");
  const idle = metrics["job.idleTimeRatio"] ?? 0;
  return kv({
    "records/s": fmt(metrics["job.numRecordsInPerSecond"]),
    "busy ratio": fmt(metrics["job.busyTimeRatio"], 2),
    // idle-subtask indicator: a (sub)task coasting at >=95% idle is
    // starved — skewed keys or a slow upstream, not healthy headroom
    "idle ratio": idle >= 0.95
      ? `<span class="CANCELED">${fmt(idle, 2)} idle</span>` : fmt(idle, 2),
    "backpressured": `<span class="${bpClass(bp)}">${fmt(bp, 2)}</span>`,
    "wm skew ms": fmt(metrics["job.watermarkSkewMs"]),
    "step p50 ms": fmt(latency.p50), "step p99 ms": fmt(latency.p99),
    "device ms total": fmt(metrics["job.deviceTimeMsTotal"]),
    "exchange out B/s": fmt(exchOut), "exchange in B/s": fmt(exchIn),
    "late dropped": fmt(Object.entries(metrics).find(
        ([k]) => k.endsWith("numLateRecordsDropped"))?.[1]),
    "error": esc(info.error ?? "none"),
  }) + operatorTable(metrics)
    + doctorSection(doc)
    + historySection(hist)
    + latencySection(lat)
    + deviceSection(dev)
    + autoscalerSection(auto)
    + checkpointSection(cps) + exceptionSection(exc)
    + (spanRows ? `<div class="spans">${spanRows}</div>` : "");
}

async function refresh() {
  const [ov, jobs] = await Promise.all([j("/overview"), j("/jobs")]);
  document.getElementById("overview").textContent =
    `${ov.jobs} jobs ` + Object.entries(ov.by_status ?? {})
      .map(([s, n]) => `${s.toLowerCase()}:${n}`).join(" ");
  const tbody = document.getElementById("rows");
  document.getElementById("none").hidden = jobs.jobs.length > 0;
  const rows = [];
  const fetched = await Promise.all(jobs.jobs.map(job => Promise.all([
    j(`/jobs/${job.id}`),
    j(`/jobs/${job.id}/metrics`).catch(() => ({})),
  ])));
  for (const [i, job] of jobs.jobs.entries()) {
    const [d, m] = fetched[i];
    rows.push(`<tr class="job" onclick="toggle('${esc(job.id)}')">
      <td>${esc(job.id)}</td><td>${esc(job.name)}</td>
      <td class="${esc(job.status)}">${esc(job.status)}</td>
      <td>${fmt(d.records_in)}</td>
      <td>${fmt(m["job.numRecordsInPerSecond"])}</td>
      <td>${fmt(m["job.busyTimeRatio"], 2)}</td>
      <td>${d.num_restarts ?? 0}</td>
      <td>${d.num_checkpoints ?? 0}</td></tr>`);
    if (open.has(job.id)) {
      rows.push(`<tr class="detail"><td colspan="8">` +
                await detailRow(job.id) + `</td></tr>`);
    }
  }
  tbody.innerHTML = rows.join("");
}
function toggle(id) { open.has(id) ? open.delete(id) : open.add(id); refresh(); }
// self-rescheduling: a slow refresh never overlaps the next one
async function tick() {
  try { await refresh(); } finally { setTimeout(tick, 2000); }
}
tick();
</script>
</body></html>"""
