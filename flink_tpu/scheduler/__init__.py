"""Elastic autoscaling: the AdaptiveScheduler analogue.

A JM-side reactive controller that closes the loop from the
observability plane back into scheduling (ROADMAP item 2 — SURVEY §2.7
"Rescaling"): `signals` turns the JM-aggregated gauges into windowed
per-vertex utilization estimates, `policy` decides scale-up/down
(threshold rule, or the learning rule that damps rescales which
previously failed to help — PAPERS.md "Learning from the Past"), and
`autoscaler.AutoscalerCoordinator` drives the loop and keeps the
decision log served at /jobs/:id/autoscaler.

The rescale itself — rewind to the latest completed checkpoint, remap
key-groups onto the new slot set (state/key_groups.py), redeploy — is
executed by the runtime through an injected callable; this package
imports metrics/state/config shapes only, never the runtime.
"""

from flink_tpu.scheduler.autoscaler import (
    AutoscalerCoordinator,
    empty_autoscaler_payload,
)
from flink_tpu.scheduler.latency_controller import (
    LatencySpec,
    SuperbatchController,
    build_rung_ladder,
)
from flink_tpu.scheduler.rebalancer import (
    RebalanceDecision,
    SkewRebalancer,
)
from flink_tpu.scheduler.policy import (
    LearningPolicy,
    RescaleOutcome,
    ScalingDecision,
    ScalingPolicy,
    ThresholdPolicy,
    build_policy,
)
from flink_tpu.scheduler.signals import (
    SignalAggregator,
    SignalEstimate,
    SignalSample,
    SignalWindow,
    extract_signals,
)

__all__ = [
    "AutoscalerCoordinator",
    "empty_autoscaler_payload",
    "LatencySpec",
    "SuperbatchController",
    "build_rung_ladder",
    "RebalanceDecision",
    "SkewRebalancer",
    "LearningPolicy",
    "RescaleOutcome",
    "ScalingDecision",
    "ScalingPolicy",
    "ThresholdPolicy",
    "build_policy",
    "SignalAggregator",
    "SignalEstimate",
    "SignalSample",
    "SignalWindow",
    "extract_signals",
]
