"""AutoscalerCoordinator: the control loop between signals and rescales.

The JM-side half of the AdaptiveScheduler analogue (ROADMAP item 2):
feeds the per-job signal windows (signals.py), asks the configured policy
(policy.py) for a decision, executes it through an injected rescale
executor (the JobManager's checkpoint-rewind + key-group-remap path —
injected as a callable so this layer never imports the runtime), and
keeps the bounded per-job decision log served at /jobs/:id/autoscaler:
signals seen, action taken, outcome, rescale duration, and the observed
before/after throughput that feeds the learning policy.

Thread model: one lock guards all mutable coordinator state. `observe`
is driven either by the JM's autoscaler tick (on the endpoint main
thread) or by a MiniCluster run loop (observe-only); the executor
callback runs inside `observe`, so JM callers must already be on their
mutation thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Optional

from flink_tpu.scheduler.policy import (
    ScalingDecision,
    ScalingPolicy,
    build_policy,
)
from flink_tpu.scheduler.signals import SignalAggregator, SignalEstimate

#: rescale_executor(job_id, target_parallelism, reason)
#: -> (accepted: bool, detail: str)
RescaleExecutor = Callable[[str, int, str], "tuple[bool, str]"]


def empty_autoscaler_payload() -> Dict[str, Any]:
    """REST /jobs/:id/autoscaler body for a job with no autoscaler."""
    return {
        "enabled": False,
        "policy": None,
        "min_parallelism": None,
        "max_parallelism": None,
        "stabilization_interval_ms": None,
        "num_rescales": 0,
        "last_rescale_duration_ms": 0.0,
        "decisions": [],
    }


class _JobScalingState:
    """Per-job bookkeeping (guarded by the coordinator lock)."""

    def __init__(self, decision_log_size: int):
        self.decisions: Deque[Dict[str, Any]] = deque(
            maxlen=max(int(decision_log_size), 1))
        self.first_seen: Optional[float] = None
        self.last_action_at: Optional[float] = None
        self.last_parallelism: Optional[int] = None
        # executed rescale awaiting its post-stabilization throughput
        # measurement (feeds policy.record_outcome)
        self.pending_outcome: Optional[Dict[str, Any]] = None
        # a rescale_completed that fired before its decision entry landed
        # (the executor redeploys synchronously inside observe): the
        # duration parks here until the entry is logged
        self.unclaimed_duration: Optional[float] = None


class AutoscalerCoordinator:
    def __init__(
        self,
        policy: Optional[ScalingPolicy] = None,
        *,
        min_parallelism: int = 1,
        max_parallelism: int = 0,
        stabilization_interval_ms: int = 30_000,
        interval_ms: int = 1000,
        signal_window: int = 6,
        decision_log_size: int = 32,
        rescale_executor: Optional[RescaleExecutor] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.policy = policy if policy is not None else build_policy("threshold")
        self.min_parallelism = max(int(min_parallelism), 1)
        self.max_parallelism = int(max_parallelism)   # 0 = unbounded here
        self.stabilization_s = max(int(stabilization_interval_ms), 0) / 1000.0
        self.interval_s = max(int(interval_ms), 1) / 1000.0
        self.decision_log_size = decision_log_size
        self.rescale_executor = rescale_executor
        self._clock = clock
        # outcome settling wants a multi-sample window (one stalled
        # snapshot pair reads ~0 throughput), but must stay reachable
        # when the configured window itself is smaller than 3
        self._settle_min_samples = min(3, max(int(signal_window), 1))
        self._signals = SignalAggregator(window=signal_window)
        self._jobs: Dict[str, _JobScalingState] = {}
        self._last_observed: Dict[str, float] = {}
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, config, *,
                    rescale_executor: Optional[RescaleExecutor] = None,
                    clock: Callable[[], float] = time.monotonic,
                    ) -> "AutoscalerCoordinator":
        from flink_tpu.config import AutoscalerOptions as A

        window = max(int(config.get(A.SIGNAL_WINDOW)), 1)
        policy = build_policy(
            config.get(A.POLICY),
            scale_up_threshold=config.get(A.SCALE_UP_THRESHOLD),
            scale_down_threshold=config.get(A.SCALE_DOWN_THRESHOLD),
            # the warm-up bar must fit inside the configured window, or a
            # signal-window below 3 would leave the policy "warming up
            # (2/3 samples)" forever — a silently inert autoscaler
            min_samples=min(3, window),
            min_gain=config.get(A.LEARNING_MIN_GAIN),
            patience=config.get(A.LEARNING_PATIENCE),
        )
        return cls(
            policy,
            min_parallelism=config.get(A.MIN_PARALLELISM),
            max_parallelism=config.get(A.MAX_PARALLELISM),
            stabilization_interval_ms=config.get(A.STABILIZATION_INTERVAL_MS),
            interval_ms=config.get(A.INTERVAL_MS),
            signal_window=window,
            decision_log_size=config.get(A.DECISION_HISTORY_SIZE),
            rescale_executor=rescale_executor,
            clock=clock,
        )

    # -- observation --------------------------------------------------------
    def maybe_observe(self, job_id: str, parallelism: int,
                      metrics_fn: Callable[[], Dict[str, object]],
                      max_slots: Optional[int] = None,
                      ) -> Optional[ScalingDecision]:
        """Throttled observe for callers on a hot loop (MiniCluster run
        loop): snapshots are only built when a sampling tick is due."""
        now = self._clock()
        with self._lock:
            last = self._last_observed.get(job_id)
            if last is not None and now - last < self.interval_s:
                return None
            self._last_observed[job_id] = now
        return self.observe(job_id, parallelism, metrics_fn(),
                            max_slots=max_slots)

    def observe(self, job_id: str, parallelism: int,
                metrics: Dict[str, object],
                max_slots: Optional[int] = None,
                ) -> Optional[ScalingDecision]:
        """Feed one metric snapshot; returns the decision when the policy
        proposed an action this tick (executed or not), else None."""
        now = self._clock()
        estimate = self._signals.observe(job_id, metrics, now)
        with self._lock:
            state = self._jobs.get(job_id)
            if state is None:
                state = self._jobs[job_id] = _JobScalingState(
                    self.decision_log_size)
            if state.first_seen is None:
                state.first_seen = now
            if (state.last_parallelism is not None
                    and state.last_parallelism != parallelism):
                # attempt changed shape under us (rescale or failover):
                # old-window samples describe the previous deployment
                self._signals.reset(job_id)
                estimate = self._signals.estimate(job_id)
            state.last_parallelism = parallelism
            pending = state.pending_outcome
            if pending is not None and pending["to"] != parallelism:
                # the job is not running at this rescale's target: a
                # failover landed somewhere else mid-stabilization, or a
                # failed redeploy restarted the OLD shape (no parallelism
                # change, so the shape guard above never fires). Either
                # way, measuring THIS deployment's throughput as the
                # rescale's outcome would poison the learning history
                state.pending_outcome = None
            self._settle_outcome(job_id, state, estimate, now)
            if state.pending_outcome is not None:
                # the last executed rescale hasn't settled its
                # post-stabilization throughput yet: a new decision now
                # would overwrite the pending measurement, so back-to-back
                # rescales would never feed the learning history
                return None
            since = now - (state.last_action_at
                           if state.last_action_at is not None
                           else state.first_seen)
            if since < self.stabilization_s:
                return None
        # policy evaluation outside the lock: pure function over the
        # estimate, and the executor below may re-enter JM state
        effective_max = self._effective_max(parallelism, max_slots)
        decision = self.policy.decide(
            estimate, parallelism, self.min_parallelism, effective_max)
        if not decision.is_action:
            return None
        entry: Dict[str, Any] = {
            "timestamp_ms": time.time() * 1000.0,
            "parallelism": parallelism,
            "action": decision.action,
            "target": decision.target,
            "reason": decision.reason,
            "signals": estimate.as_dict(),
            "outcome": "observe-only",
            "duration_ms": None,
            "throughput_before": estimate.throughput_per_s,
            "throughput_after": None,
            "repeats": 1,
        }
        if self.rescale_executor is not None:
            with self._lock:
                # a duration parked before this call belongs to some
                # foreign rescale (e.g. a manual RPC with no decision
                # entry) — it must not be claimed as this decision's
                state.unclaimed_duration = None
            accepted, detail = self.rescale_executor(
                job_id, decision.target, decision.reason)
            if accepted:
                entry["outcome"] = "executed"
            else:
                entry["outcome"] = f"rejected: {detail}"
        with self._lock:
            newest = state.decisions[0] if state.decisions else None
            if (entry["outcome"] != "executed" and newest is not None
                    and newest["outcome"] == entry["outcome"]
                    and newest["action"] == entry["action"]
                    and newest["target"] == entry["target"]
                    and newest["parallelism"] == entry["parallelism"]):
                # a decision the executor keeps refusing (or observe-only
                # mode) refires every tick by design — coalesce identical
                # repeats in place so they cannot churn real rescale
                # history out of the bounded log
                newest.update(
                    timestamp_ms=entry["timestamp_ms"],
                    signals=entry["signals"], reason=entry["reason"],
                    throughput_before=entry["throughput_before"],
                    repeats=newest.get("repeats", 1) + 1,
                )
            else:
                state.decisions.appendleft(entry)
            if entry["outcome"] == "executed":
                state.last_action_at = self._clock()
                if state.unclaimed_duration is not None:
                    entry["duration_ms"] = state.unclaimed_duration
                    state.unclaimed_duration = None
                state.pending_outcome = {
                    "entry": entry,
                    "action": decision.action,
                    "from": parallelism,
                    "to": decision.target,
                    "before": estimate.throughput_per_s,
                }
                self._signals.reset(job_id)
        return decision

    def _effective_max(self, parallelism: int,
                       max_slots: Optional[int]) -> int:
        bounds = [b for b in (self.max_parallelism or None, max_slots)
                  if b is not None]
        return max(min(bounds), 1) if bounds else max(parallelism * 2, 1)

    def _settle_outcome(self, job_id: str, state: _JobScalingState,
                        estimate: SignalEstimate, now: float) -> None:
        """Post-stabilization throughput of an executed rescale closes the
        loop into the policy (LearningPolicy damping). Lock held.

        The window accumulated samples DURING stabilization — including
        the redeploy's restore dead time, when the counter sits flat. A
        window half-filled with that dead time reads half the steady
        rate, so the first tick past the stabilization interval only ARMS
        the measurement (clears the window); the outcome settles on
        samples taken wholly after it."""
        pending = state.pending_outcome
        if pending is None or state.last_action_at is None:
            return
        if now - state.last_action_at < self.stabilization_s:
            return
        if not pending.get("armed"):
            pending["armed"] = True
            self._signals.reset(job_id)
            return
        if estimate.samples < self._settle_min_samples:
            # a short window is one shipping stall wide — a stalled
            # snapshot pair would record ~0 throughput into the learning
            # history for a perfectly healthy rescale
            return
        state.pending_outcome = None
        after = estimate.throughput_per_s
        pending["entry"]["throughput_after"] = after
        self.policy.record_outcome(
            pending["action"], pending["from"], pending["to"],
            pending["before"], after)

    # -- executor feedback ---------------------------------------------------
    def rescale_completed(self, job_id: str, duration_ms: float,
                          target: Optional[int] = None) -> None:
        """Called by the executor when the redeploy finished: stamps the
        decision entry and restarts the stabilization window from
        completion (not decision) time. The rescale counters themselves
        live with the executor (the JM's per-job state), which also sees
        rescales this coordinator never initiated (manual RPC calls) —
        `target` (the completed parallelism) keeps such a completion from
        stamping a pending decision for a different parallelism."""
        with self._lock:
            state = self._jobs.get(job_id)
            if state is None:
                return
            state.last_action_at = self._clock()
            for entry in state.decisions:
                if (entry["outcome"] == "executed"
                        and entry["duration_ms"] is None
                        and (target is None or entry["target"] == target)):
                    entry["duration_ms"] = float(duration_ms)
                    break
            else:
                # redeploy finished inside the executor call, before the
                # decision entry landed — observe() claims it on append
                state.unclaimed_duration = float(duration_ms)

    # -- exposure ------------------------------------------------------------
    def payload(self, job_id: str, *, num_rescales: int = 0,
                last_rescale_duration_ms: float = 0.0) -> Dict[str, Any]:
        """REST /jobs/:id/autoscaler body: config, counters, decision log
        (newest first) with the signals each decision saw. The rescale
        counters are the caller's (the JM counts every rescale, including
        manual ones this coordinator never saw); the observe-only default
        of 0 is exact — nothing rescales without an executor."""
        with self._lock:
            state = self._jobs.get(job_id)
            return {
                "enabled": True,
                "policy": self.policy.name,
                "min_parallelism": self.min_parallelism,
                "max_parallelism": self.max_parallelism or None,
                "stabilization_interval_ms": int(self.stabilization_s * 1000),
                "num_rescales": int(num_rescales),
                "last_rescale_duration_ms": float(last_rescale_duration_ms),
                "decisions": ([dict(d) for d in state.decisions]
                              if state else []),
            }
