"""Adaptive superbatch sizing for latency-mode execution (ROADMAP item 4).

The fused window path amortizes host/device round trips by buffering T
steps per compiled dispatch; T is also the floor of emission latency —
a fired window is host-visible only after the dispatch that contains it
launches and resolves. `SuperbatchController` closes that trade-off
under an explicit target (`execution.latency.target-ms`): it estimates
the step arrival rate over a bounded window of samples (the autoscaler's
windowed-signal discipline — never one instantaneous reading, see
signals.py), and picks the largest rung of a pow2 ladder whose fill time
still fits the target. Geometry is snapped to the ladder so adaptation
can only ever compile the ladder's shapes — the same pow2 shapes the
operator's tail-pad path already compiles — never a recompile storm.

Stability discipline (mirrors AutoscalerCoordinator/ThresholdPolicy):

- warm-up: below ``min_samples`` observations the controller refuses to
  adapt and holds the FULL span — cold starts run throughput geometry,
  so an unwarmed estimate can never cost peak throughput;
- hysteresis: the windowed rate must overshoot a rung boundary by the
  configured margin before the rung changes, so a rate oscillating
  across a boundary never flaps geometries;
- min-dwell: a non-escalation move waits out a dwell interval after the
  previous change (the stabilization-interval idea applied to batch
  geometry). The one exception is a rate spike that demands the full
  span: falling behind is strictly worse than a dwell violation, so
  escalation to the top rung applies immediately.

Layering (ARCH001): scheduler sits above metrics/state/config and below
the runtime — this module imports neither jax nor the runtime; the
operator consumes it through plain numbers.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Deque, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class LatencySpec:
    """The execution.latency.* option bundle threaded to the fused
    operator (executor._latency_kwargs). target_ms > 0 is the mode
    switch; everything else tunes the controller and the dispatch ring."""

    target_ms: int
    max_inflight: int = 1
    floor_steps: int = 2
    readback_steps: int = 8
    min_dwell_ms: int = 500
    hysteresis_pct: int = 25


def build_rung_ladder(floor_steps: int, full_steps: int) -> Tuple[int, ...]:
    """Pow2 rungs from the latency floor up to the full span. The full
    span itself is always the top rung even when it is not a power of
    two (it is the one geometry the throughput path compiles anyway)."""
    full = max(int(full_steps), 1)
    floor = min(max(int(floor_steps), 1), full)
    rungs = []
    r = 1 << (floor - 1).bit_length()     # next pow2 >= floor
    while r < full:
        rungs.append(r)
        r <<= 1
    rungs.append(full)
    return tuple(rungs)


class SuperbatchController:
    """Windowed step-rate estimator + rung ladder policy.

    ``observe(n_steps)`` feeds arrivals; ``steps()`` returns the depth
    the next dispatch should cut at. Both are O(1) and host-only — the
    controller sits on the ingest hot path.
    """

    def __init__(
        self,
        *,
        full_steps: int,
        target_ms: int,
        floor_steps: int = 2,
        min_dwell_ms: int = 500,
        hysteresis_pct: int = 25,
        window: int = 8,
        min_samples: int = 3,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.ladder = build_rung_ladder(floor_steps, full_steps)
        self.target_s = max(int(target_ms), 1) / 1000.0
        self.min_dwell_s = max(int(min_dwell_ms), 0) / 1000.0
        self.hysteresis = max(int(hysteresis_pct), 0) / 100.0
        self.min_samples = max(int(min_samples), 1)
        self._clock = clock
        # (timestamp, n_steps) arrival samples; the bounded deque IS the
        # window — one stalled or bursty reading cannot dominate the rate
        self._samples: Deque[Tuple[float, int]] = deque(
            maxlen=max(int(window), 2))
        # cold start holds the TOP rung (full span): an unwarmed estimate
        # must never cost peak throughput or compile an extra shape
        self._rung = len(self.ladder) - 1
        self._last_change: Optional[float] = None

    # -- observation -----------------------------------------------------
    def observe(self, n_steps: int, now: Optional[float] = None) -> None:
        """Record that `n_steps` planner steps just arrived."""
        if n_steps <= 0:
            return
        self._samples.append(
            (self._clock() if now is None else now, int(n_steps)))

    def step_rate(self) -> Optional[float]:
        """Steps/second over the sample window; None while warming up
        (below min_samples, or a window too narrow to difference)."""
        if len(self._samples) < self.min_samples:
            return None
        t0 = self._samples[0][0]
        t1 = self._samples[-1][0]
        if t1 <= t0:
            return None
        # arrivals BETWEEN the first and last stamps: the first sample's
        # own steps predate the measured interval
        n = sum(s for _t, s in self._samples) - self._samples[0][1]
        return n / (t1 - t0)

    # -- policy ----------------------------------------------------------
    def _ideal_rung(self, budget_steps: float) -> int:
        """Largest rung whose depth fits the step budget (floor rung when
        even the floor does not fit — the ladder never goes below it)."""
        ideal = 0
        for i, steps in enumerate(self.ladder):
            if steps <= budget_steps:
                ideal = i
        return ideal

    def steps(self, now: Optional[float] = None) -> int:
        """The superbatch depth the next dispatch should cut at."""
        return self.ladder[self.decide(now)]

    def decide(self, now: Optional[float] = None) -> int:
        """Current rung index after applying warm-up, hysteresis,
        min-dwell, and spike escalation to the windowed estimate."""
        rate = self.step_rate()
        if rate is None:
            return self._rung          # warming up: hold (full span)
        now = self._clock() if now is None else now
        budget = rate * self.target_s  # steps arriving within the target
        top = len(self.ladder) - 1
        cur = self._rung
        ideal = self._ideal_rung(budget)
        if ideal == cur:
            return cur
        if ideal > cur:
            # moving up: the budget must overshoot the TARGET rung's
            # boundary by the hysteresis margin, not merely touch it
            if budget < self.ladder[ideal] * (1.0 + self.hysteresis):
                ideal = max(self._ideal_rung(
                    budget / (1.0 + self.hysteresis)), cur)
            if ideal == cur:
                return cur
            if ideal == top and self.ladder[cur] < self.ladder[top]:
                # rate spike demanding the full span: falling behind is
                # strictly worse than a dwell violation — escalate now
                self._rung = top
                self._last_change = now
                return self._rung
        else:
            # moving down: the budget must UNDERSHOOT the current rung's
            # own boundary by the margin (a rate oscillating across the
            # boundary reads as "still fits" and never flaps)
            if budget > self.ladder[cur] * (1.0 - self.hysteresis):
                return cur
        if (self._last_change is not None
                and now - self._last_change < self.min_dwell_s):
            return cur                 # min-dwell: hold the rung
        self._rung = ideal
        self._last_change = now
        return self._rung

    # -- exposure --------------------------------------------------------
    def current_steps(self) -> int:
        """The held rung's depth without re-evaluating the policy (gauge
        reads must not advance controller state)."""
        return self.ladder[self._rung]

    def reset(self) -> None:
        """Forget the window and re-hold the full span (restore/rescale:
        old-deployment samples describe a stream position that no longer
        exists — the autoscaler's shape-change reset discipline)."""
        self._samples.clear()
        self._rung = len(self.ladder) - 1
        self._last_change = None
