"""Pluggable scaling policies for the adaptive scheduler.

Two engines:

- `ThresholdPolicy` — the classic reactive rule (AdaptiveScheduler /
  Flink-autoscaler style): windowed utilization above the scale-up
  threshold doubles parallelism, below the scale-down threshold halves
  it, clamped to [min, max].

- `LearningPolicy` — the "Learning from the Past: Adaptive Parallelism
  Tuning for Stream Processing Systems" (PAPERS.md) blueprint: wrap a
  base policy, record every executed rescale's observed before/after
  throughput in a bounded history, and DAMP decisions that previously
  failed to help — a transition whose recorded gain stayed below
  `min_gain` is suppressed for `patience` further triggers before being
  retried (conditions may have changed), and a later good outcome clears
  the damping. This replaces fixed thresholds blindly re-firing a rescale
  that demonstrably bought nothing (each rescale costs a checkpoint
  rewind + replay).

Policies are pure decision functions over `SignalEstimate`s — no clocks,
no runtime imports — so they unit-test deterministically.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from flink_tpu.scheduler.signals import SignalEstimate

SCALE_UP = "scale-up"
SCALE_DOWN = "scale-down"
NONE = "none"


@dataclasses.dataclass(frozen=True)
class ScalingDecision:
    action: str                  # SCALE_UP / SCALE_DOWN / NONE
    target: int                  # proposed parallelism (== current for NONE)
    reason: str

    @property
    def is_action(self) -> bool:
        return self.action != NONE


def _none(parallelism: int, reason: str) -> ScalingDecision:
    return ScalingDecision(NONE, parallelism, reason)


class ScalingPolicy:
    """Base interface: decide() proposes, record_outcome() feeds back what
    an executed rescale actually bought (throughput before vs after)."""

    name = "base"

    def decide(self, estimate: SignalEstimate, parallelism: int,
               min_parallelism: int, max_parallelism: int) -> ScalingDecision:
        raise NotImplementedError

    def record_outcome(self, action: str, from_parallelism: int,
                       to_parallelism: int, throughput_before: float,
                       throughput_after: float) -> None:
        pass


class ThresholdPolicy(ScalingPolicy):
    name = "threshold"

    def __init__(self, scale_up_threshold: float = 0.85,
                 scale_down_threshold: float = 0.3, min_samples: int = 3):
        self.scale_up_threshold = float(scale_up_threshold)
        self.scale_down_threshold = float(scale_down_threshold)
        # decisions wait for a warm window: a single sample after a deploy
        # or a load step is exactly the noise the window exists to damp
        self.min_samples = max(int(min_samples), 1)

    def decide(self, estimate: SignalEstimate, parallelism: int,
               min_parallelism: int, max_parallelism: int) -> ScalingDecision:
        if estimate.samples < self.min_samples:
            return _none(parallelism,
                         f"warming up ({estimate.samples}/{self.min_samples} "
                         f"samples)")
        util = estimate.utilization
        if util >= self.scale_up_threshold:
            target = min(max(parallelism * 2, parallelism + 1), max_parallelism)
            if target <= parallelism:
                return _none(parallelism,
                             f"utilization {util:.2f} >= "
                             f"{self.scale_up_threshold} but already at max "
                             f"parallelism {max_parallelism}")
            return ScalingDecision(
                SCALE_UP, target,
                f"utilization {util:.2f} >= {self.scale_up_threshold}")
        if util <= self.scale_down_threshold:
            if estimate.peak_utilization > self.scale_down_threshold:
                # a mean dragged down by stalled ticks around a genuinely
                # busy one is a transient hiccup, not idle capacity —
                # halving here would churn rescales under load jitter
                return _none(parallelism,
                             f"utilization {util:.2f} <= "
                             f"{self.scale_down_threshold} but peak "
                             f"{estimate.peak_utilization:.2f} within the "
                             f"window — transient stall, not idle capacity")
            target = max(parallelism // 2, min_parallelism)
            if target >= parallelism:
                return _none(parallelism,
                             f"utilization {util:.2f} <= "
                             f"{self.scale_down_threshold} but already at min "
                             f"parallelism {min_parallelism}")
            return ScalingDecision(
                SCALE_DOWN, target,
                f"utilization {util:.2f} <= {self.scale_down_threshold}")
        return _none(parallelism,
                     f"utilization {util:.2f} within "
                     f"[{self.scale_down_threshold}, {self.scale_up_threshold}]")


@dataclasses.dataclass(frozen=True)
class RescaleOutcome:
    """One executed rescale and what it bought."""

    action: str
    from_parallelism: int
    to_parallelism: int
    throughput_before: float
    throughput_after: float

    @property
    def gain(self) -> float:
        return self.throughput_after / max(self.throughput_before, 1e-9)


class LearningPolicy(ScalingPolicy):
    name = "learning"

    def __init__(self, base: Optional[ScalingPolicy] = None,
                 history_size: int = 32, min_gain: float = 1.1,
                 patience: int = 4):
        self.base = base if base is not None else ThresholdPolicy()
        self.history: Deque[RescaleOutcome] = deque(
            maxlen=max(int(history_size), 1))
        self.min_gain = float(min_gain)
        self.patience = max(int(patience), 1)
        # (action, from_p) -> triggers suppressed since the bad outcome
        self._suppressed: Dict[Tuple[str, int], int] = {}

    def _recorded_gain(self, action: str, from_p: int) -> Optional[float]:
        """Mean observed gain for this transition shape (same direction
        from the same parallelism) over the bounded ring — old outcomes
        age out by eviction, all retained ones weigh equally."""
        gains = [o.gain for o in self.history
                 if o.action == action and o.from_parallelism == from_p]
        return sum(gains) / len(gains) if gains else None

    def decide(self, estimate: SignalEstimate, parallelism: int,
               min_parallelism: int, max_parallelism: int) -> ScalingDecision:
        decision = self.base.decide(
            estimate, parallelism, min_parallelism, max_parallelism)
        if not decision.is_action:
            return decision
        # scale-down is damped only by a past scale-down that LOST
        # throughput; scale-up by one that failed to add any
        gain = self._recorded_gain(decision.action, parallelism)
        bar = self.min_gain if decision.action == SCALE_UP else 1.0 / self.min_gain
        if gain is None or gain >= bar:
            return decision
        key = (decision.action, parallelism)
        n = self._suppressed.get(key, 0) + 1
        if n <= self.patience:
            self._suppressed[key] = n
            return _none(
                parallelism,
                f"damped: past {decision.action} from p={parallelism} gained "
                f"only {gain:.2f}x (< {bar:.2f}x); suppressing "
                f"{n}/{self.patience} before retrying — {decision.reason}")
        # patience exhausted: forget the grudge and try again
        self._suppressed.pop(key, None)
        return dataclasses.replace(
            decision, reason=f"{decision.reason} (retry after "
                             f"{self.patience} damped triggers)")

    def record_outcome(self, action: str, from_parallelism: int,
                       to_parallelism: int, throughput_before: float,
                       throughput_after: float) -> None:
        outcome = RescaleOutcome(action, from_parallelism, to_parallelism,
                                 throughput_before, throughput_after)
        self.history.append(outcome)
        bar = self.min_gain if action == SCALE_UP else 1.0 / self.min_gain
        if outcome.gain >= bar:
            self._suppressed.pop((action, from_parallelism), None)


def build_policy(name: str, *, scale_up_threshold: float = 0.85,
                 scale_down_threshold: float = 0.3, min_samples: int = 3,
                 history_size: int = 32, min_gain: float = 1.1,
                 patience: int = 4) -> ScalingPolicy:
    """Policy factory for the `autoscaler.policy` config value."""
    base = ThresholdPolicy(scale_up_threshold, scale_down_threshold,
                           min_samples)
    if name == "threshold":
        return base
    if name == "learning":
        return LearningPolicy(base, history_size=history_size,
                              min_gain=min_gain, patience=patience)
    raise ValueError(
        f"unknown autoscaler.policy {name!r} (expected 'threshold' or "
        f"'learning')")
