"""Skew rebalancer: closes the loop from key-skew telemetry to key-group
placement (ROADMAP item 4a, parallel.mesh.skew-rebalance).

PR 8 made skew measurable (keySkew / keyGroupLoad / meshLoadSkew ride the
heartbeats and `scheduler/signals.py` carries key_skew); PR 10 measured
meshLoadSkew ~1.9 on the 8-device mesh under zipf(1.0) — the static
contiguous owner function piles the hot key-groups onto whichever devices
own the hot ranges. This module is the DECISION side of fixing that: it
watches per-key-group loads, and when the per-device skew a placement
produces crosses the configured threshold AND a replanned balanced
assignment (parallel/routing.plan_balanced_assignment — capacity-bounded
LPT) would improve it by a meaningful margin, it hands the new assignment
to the runtime, which applies it at a step-aligned boundary through the
mesh-rescale capture/restore machinery (exactly-once; checkpoints stay
canonical [K, S]).

Like the autoscaler, this package never touches the runtime: the runtime
polls `maybe_decide` with plain arrays and executes the move itself
(ARCH001 — scheduler imports metrics/state/config/parallel shapes only).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Deque, List, Optional

import numpy as np

from flink_tpu.parallel.routing import (
    plan_balanced_assignment,
    predicted_skew,
)

#: a replan must beat the current placement's predicted skew by this
#: factor to be worth a stop-the-world table swap — one unsplittable hot
#: group (skew high, replan identical) must never cause rebuild churn
MIN_IMPROVEMENT = 0.9


@dataclasses.dataclass
class RebalanceDecision:
    """One decision-log entry (mirrors the autoscaler's ScalingDecision
    shape: every poll that got past the throttle leaves a trace)."""

    timestamp: float
    action: str                  # "rebalance" | "hold"
    reason: str
    skew_before: float
    skew_after: Optional[float] = None
    moved_groups: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class SkewRebalancer:
    """Throttled skew-threshold policy over per-key-group loads.

    `maybe_decide(group_loads, current_assign, n_shards)` returns a new
    [G] assignment when a rebalance should happen NOW, else None. The
    caller owns execution; `rebalance_completed()` (mis)fires the
    cooldown clock so a just-applied table gets `interval_ms` of fresh
    traffic before it is judged again.

    Decisions run on a WINDOWED SUM of load snapshots, never one
    instantaneous reading — the autoscaler's lesson applied to
    placement: the resident ring right after a purge holds a handful of
    records, and the group of the freshest-admitted dense ids always
    reads as "hot" in a single snapshot (a moving target no placement
    can balance). Summing `window` samples time-integrates the load, so
    the stable hot groups accumulate while one-snapshot spikes dilute;
    `min_samples` due ticks must accumulate after a (re)start or a
    completed rebalance before the policy will judge the placement."""

    def __init__(self, *, skew_threshold: float = 1.25,
                 interval_ms: int = 1000, max_decisions: int = 64,
                 window: int = 8, min_samples: int = 4,
                 clock: Callable[[], float] = time.monotonic):
        self.skew_threshold = max(float(skew_threshold), 1.0)
        self.interval_s = max(int(interval_ms), 0) / 1000.0
        self._clock = clock
        self._last_t: Optional[float] = None
        self.num_rebalances = 0
        self.decisions: List[RebalanceDecision] = []
        self._max_decisions = max_decisions
        self.min_samples = max(int(min_samples), 1)
        self._window: Deque[np.ndarray] = deque(maxlen=max(int(window), 1))

    # ------------------------------------------------------------------
    def _log(self, d: RebalanceDecision) -> None:
        self.decisions.append(d)
        if len(self.decisions) > self._max_decisions:
            del self.decisions[:-self._max_decisions]

    def due(self, now: Optional[float] = None) -> bool:
        """True when the interval throttle would let a decision run —
        callers gate the (device-readback) load collection on this, so a
        per-step poll costs one clock read, not a device sync."""
        now = self._clock() if now is None else now
        return self._last_t is None or now - self._last_t >= self.interval_s

    def rebalance_completed(self) -> None:
        """The runtime applied an assignment: restart the interval clock
        AND the evidence window, so the new placement is judged only on
        traffic it actually served."""
        self.num_rebalances += 1
        self._last_t = self._clock()
        self._window.clear()

    def maybe_decide(self, group_loads, current_assign,
                     n_shards: int,
                     now: Optional[float] = None) -> Optional[np.ndarray]:
        """One throttled decision: None = hold (throttled, warming up,
        below threshold, or no worthwhile improvement); an [G] int32
        array = rebalance to this assignment now."""
        now = self._clock() if now is None else now
        if self._last_t is not None and now - self._last_t < self.interval_s:
            return None
        self._last_t = now
        sample = np.asarray(group_loads, np.float64)
        if sample.size == 0 or sample.sum() <= 0:
            return None
        if self._window and self._window[0].shape != sample.shape:
            # group count changed (capacity growth rebuilt the table):
            # stale-geometry evidence is meaningless, start fresh
            self._window.clear()
        self._window.append(sample)
        if len(self._window) < self.min_samples:
            return None
        loads = np.sum(self._window, axis=0)
        current = np.asarray(current_assign, np.int64)
        n = int(n_shards)
        before = predicted_skew(loads, current, n)
        if before < self.skew_threshold:
            self._log(RebalanceDecision(
                now, "hold",
                f"skew {before:.3f} below threshold "
                f"{self.skew_threshold:.2f}", before))
            return None
        assign = plan_balanced_assignment(loads, n, current)
        after = predicted_skew(loads, assign, n)
        moved = int(np.sum(assign != current))
        if moved == 0 or after > before * MIN_IMPROVEMENT:
            self._log(RebalanceDecision(
                now, "hold",
                f"replan does not improve enough ({before:.3f} -> "
                f"{after:.3f}, {moved} group(s) moved)", before, after,
                moved))
            return None
        self._log(RebalanceDecision(
            now, "rebalance",
            f"skew {before:.3f} >= {self.skew_threshold:.2f}; balanced "
            f"replan predicts {after:.3f} moving {moved} group(s)",
            before, after, moved))
        return assign

    def payload(self) -> dict:
        """JSON-safe decision log + counters."""
        return {
            "numRebalances": self.num_rebalances,
            "skewThreshold": self.skew_threshold,
            "intervalMs": int(self.interval_s * 1000),
            "decisions": [d.as_dict() for d in self.decisions[-16:]],
        }
