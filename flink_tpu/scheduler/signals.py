"""Signal aggregation for the adaptive scheduler.

The observability plane already measures everything a scaler needs — the
run loops feed busy/idle/backPressured accounting (metrics/task_io.py),
the exchange rings expose inPoolUsage, the JM aggregates watermark skew
and checkpoint durations. This module turns those JM-aggregated gauges
into per-vertex *windowed* utilization estimates the policy engine can
act on: instantaneous ratios are too noisy to rescale a job over (one
slow sample must not double a cluster), so each signal is averaged over a
bounded window of samples and decisions see the window, not the tick.

Layering: scheduler sits above metrics/state/config and below runtime —
this module consumes plain metric-snapshot dicts (whatever
`aggregate_shard_metrics` / `metrics_snapshot` produced) and never
imports the runtime.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, Optional


@dataclasses.dataclass(frozen=True)
class SignalSample:
    """One reading of the scaling-relevant gauges for one vertex/job.

    busy/backpressured/idle prefer the windowed `*TimeMsPerSecond` gauges
    (recent state, sampled every observability.sampling.interval-ms) and
    fall back to the lifetime ratios for snapshots that lack them."""

    timestamp: float                     # seconds (coordinator clock)
    busy: float = 0.0                    # fraction of recent wall time busy
    backpressured: float = 0.0
    idle: float = 0.0
    in_pool_usage: float = 0.0           # mean exchange ring occupancy
    watermark_skew_ms: float = 0.0
    checkpoint_duration_ms: float = 0.0  # last completed checkpoint e2e
    records_in: float = 0.0              # cumulative counter (resets on redeploy)
    # device-plane signals (PR 8) — OPTIONAL, unlike the gauges above: a
    # build/job without device stats simply lacks them, and treating the
    # absence as 0.0 would feed "zero skew, zero utilization" into the
    # learning policy and bias it toward jobs that merely lack the gauge.
    # None means "not measured" and is EXCLUDED from window means.
    key_skew: Optional[float] = None           # max/mean key-group load
    device_utilization: Optional[float] = None  # worst roofline fraction 0..1

    @property
    def utilization(self) -> float:
        """Busy + backpressured fraction: the share of wall time this
        vertex either worked or was blocked by downstream backlog — both
        argue for more parallelism, idle argues for less."""
        return min(self.busy + self.backpressured, 1.0)


def _ratio(metrics: Dict[str, float], leaf: str) -> float:
    """Windowed ms-per-second gauge as a fraction, else the lifetime ratio.

    A PRESENT windowed gauge is authoritative even at 0.0 — a fully idle
    vertex legitimately reads busy 0, and falling back to a stale lifetime
    ratio there would invert the signal (a long-busy job going idle would
    keep reading ~0.9 and could never scale down). The warm-up guards
    (stabilization + min_samples) cover the instant after a deploy when
    the gauge exists but has not sampled yet."""
    rate = metrics.get(f"job.{leaf}TimeMsPerSecond")
    if isinstance(rate, (int, float)):
        return min(max(float(rate), 0.0) / 1000.0, 1.0)
    return float(metrics.get(f"job.{leaf}TimeRatio", 0.0) or 0.0)


def _optional(metrics: Dict[str, object], key: str,
              scale: float = 1.0) -> Optional[float]:
    """A gauge that is ABSENT stays None (excluded from window means) —
    only a present numeric value is a measurement."""
    v = metrics.get(key)
    return float(v) * scale if isinstance(v, (int, float)) else None


def extract_signals(metrics: Dict[str, object],
                    now: Optional[float] = None) -> SignalSample:
    """Pull the scaling signals out of a metric snapshot (JM-aggregated
    per-job dict, or a MiniCluster registry snapshot — same key space)."""
    pool = [float(v) for k, v in metrics.items()
            if "inPoolUsage" in k and isinstance(v, (int, float))]
    hbm = _optional(metrics, "job.device.hbmUtilizationPct", 0.01)
    flops = _optional(metrics, "job.device.flopsUtilizationPct", 0.01)
    present = [u for u in (hbm, flops) if u is not None]
    return SignalSample(
        timestamp=time.monotonic() if now is None else now,
        busy=_ratio(metrics, "busy"),
        backpressured=_ratio(metrics, "backPressured"),
        idle=_ratio(metrics, "idle"),
        in_pool_usage=sum(pool) / len(pool) if pool else 0.0,
        watermark_skew_ms=float(metrics.get("job.watermarkSkewMs", 0.0) or 0.0),
        checkpoint_duration_ms=float(
            metrics.get("job.lastCheckpointDuration", 0.0) or 0.0),
        records_in=float(metrics.get("job.numRecordsIn", 0.0) or 0.0),
        key_skew=_optional(metrics, "job.keySkew"),
        # roofline fraction: the binding resource (worst of HBM/FLOPs)
        device_utilization=max(present) if present else None,
    )


@dataclasses.dataclass(frozen=True)
class SignalEstimate:
    """Windowed view the policy decides on."""

    utilization: float
    busy: float
    backpressured: float
    idle: float
    in_pool_usage: float
    watermark_skew_ms: float
    checkpoint_duration_ms: float
    throughput_per_s: float      # records/s over the window
    samples: int                 # window fill — policies gate on warm-up
    # max single-sample utilization in the window: scale-down wants the
    # WHOLE window idle, not a mean dragged down by a few stalled ticks
    peak_utilization: float = 0.0
    # device-plane estimates: mean over the samples that MEASURED them;
    # None when no sample in the window carried the gauge (a build
    # without device stats must not read as "zero skew / zero
    # utilization" to the learning policy)
    key_skew: Optional[float] = None
    device_utilization: Optional[float] = None

    def as_dict(self) -> Dict[str, float]:
        return dataclasses.asdict(self)


class SignalWindow:
    """Bounded window of samples for one vertex with mean estimates.

    The records_in counter resets when an attempt redeploys (fresh task
    registries); a backwards step clears the window so a rescale never
    reports negative throughput or mixes attempts."""

    def __init__(self, size: int = 6):
        self.size = max(int(size), 1)
        self._samples: Deque[SignalSample] = deque(maxlen=self.size)

    def observe(self, sample: SignalSample) -> SignalEstimate:
        if self._samples and sample.records_in < self._samples[-1].records_in:
            self._samples.clear()    # counter reset: new attempt
        self._samples.append(sample)
        return self.estimate()

    def clear(self) -> None:
        self._samples.clear()

    def estimate(self) -> SignalEstimate:
        n = len(self._samples)
        if n == 0:
            return SignalEstimate(0, 0, 0, 0, 0, 0, 0, 0.0, 0)

        def mean(attr: str) -> float:
            return sum(getattr(s, attr) for s in self._samples) / n

        def optional_mean(attr: str) -> Optional[float]:
            # mean over the samples that MEASURED the signal; None when
            # none did — absence must never read as 0.0 downstream
            vals = [getattr(s, attr) for s in self._samples
                    if getattr(s, attr) is not None]
            return sum(vals) / len(vals) if vals else None

        first, last = self._samples[0], self._samples[-1]
        dt = max(last.timestamp - first.timestamp, 1e-9)
        tput = ((last.records_in - first.records_in) / dt) if n >= 2 else 0.0
        return SignalEstimate(
            utilization=mean("utilization"),
            peak_utilization=max(s.utilization for s in self._samples),
            busy=mean("busy"),
            backpressured=mean("backpressured"),
            idle=mean("idle"),
            in_pool_usage=mean("in_pool_usage"),
            watermark_skew_ms=mean("watermark_skew_ms"),
            checkpoint_duration_ms=last.checkpoint_duration_ms,
            throughput_per_s=max(tput, 0.0),
            samples=n,
            key_skew=optional_mean("key_skew"),
            device_utilization=optional_mean("device_utilization"),
        )


class SignalAggregator:
    """Per-vertex signal windows (today: one vertex per keyed job; the
    per-vertex shape is what a multi-vertex graph scaler will key on)."""

    def __init__(self, window: int = 6):
        self.window = window
        self._vertices: Dict[str, SignalWindow] = {}

    def observe(self, vertex: str, metrics: Dict[str, object],
                now: Optional[float] = None) -> SignalEstimate:
        win = self._vertices.get(vertex)
        if win is None:
            win = self._vertices[vertex] = SignalWindow(self.window)
        return win.observe(extract_signals(metrics, now))

    def reset(self, vertex: str) -> None:
        win = self._vertices.get(vertex)
        if win is not None:
            win.clear()

    def estimate(self, vertex: str) -> SignalEstimate:
        win = self._vertices.get(vertex)
        return win.estimate() if win is not None else SignalWindow().estimate()
