"""Authenticated, pickle-free transport for the runtime's network planes.

- `framing`: restricted (allowlisted) deserialization + per-frame HMACs.
- `transport`: cluster secret resolution, connection handshake, TLS.

See the module docstrings for the threat model; runtime/rpc.py,
runtime/dataplane.py, and runtime/rest.py are the integration points.
"""

from flink_tpu.security.framing import (
    FrameAuthError,
    FrameCodec,
    RestrictedUnpicklingError,
    restricted_loads,
    trusted_loads,
)
from flink_tpu.security.transport import (
    SecurityConfig,
    bearer_header_equal,
    rest_bearer_token,
)

__all__ = [
    "FrameAuthError",
    "FrameCodec",
    "RestrictedUnpicklingError",
    "SecurityConfig",
    "bearer_header_equal",
    "rest_bearer_token",
    "restricted_loads",
    "trusted_loads",
]
