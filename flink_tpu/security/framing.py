"""Restricted serialization + authenticated frame discipline for the wire.

Every network plane of the runtime (RPC control plane, dataplane exchange,
blob fetches riding RPC) historically did `pickle.loads` straight off the
socket — i.e. remote code execution for anyone who could reach a port. The
reference guards exactly these planes with mutual-auth SSL
(flink-runtime/.../io/network/netty/SSLHandlerFactory.java and
runtime/security/); this module is the equivalent trust boundary for the
stepped runtime, in two layers:

1. **Restricted unpickling** (`restricted_loads`): an allowlisted
   `pickle.Unpickler` that resolves globals only from flink_tpu modules,
   the numpy array-reconstruction machinery, and a safe subset of stdlib
   container/scalar constructors. A crafted `__reduce__` payload that
   references `os.system`, `subprocess.Popen`, `builtins.eval`, ... raises
   `RestrictedUnpicklingError` instead of executing. Used for every frame
   that crosses a socket, even on authenticated connections
   (defense in depth: a compromised peer still cannot name arbitrary
   callables through the transport envelope).

2. **Per-frame MACs** (`FrameCodec`): after the connection handshake
   (security/transport.py) derives a session key, every frame is
   `HMAC-SHA256(session_key, direction || seq || payload)`-signed. The
   receiver MAC-verifies (constant-time) BEFORE any byte of the payload is
   deserialized; per-direction sequence counters reject replay and
   reordering within a connection, and the direction byte rejects
   reflection of a peer's own frames back at it.

Job *specs* (closures/UDFs — the user-JAR analogue) are exempt from the
allowlist by design: they only ever deserialize AFTER the transport has
authenticated the peer, via `trusted_loads` — the one sanctioned
full-pickle entry point, kept here so the architecture lint can ban bare
`pickle.loads` everywhere under `flink_tpu/runtime/` and `flink_tpu/fs/`.
"""

from __future__ import annotations

import hashlib
import hmac
import io
import pickle
import struct
from typing import Optional

__all__ = [
    "FrameAuthError",
    "FrameCodec",
    "RestrictedUnpicklingError",
    "dumps",
    "restricted_loads",
    "trusted_loads",
]


class RestrictedUnpicklingError(pickle.UnpicklingError):
    """A frame named a global outside the transport allowlist."""


class FrameAuthError(ConnectionError):
    """Frame failed MAC verification (tampered, unsigned, or replayed)."""


# ---------------------------------------------------------------------------
# restricted unpickling
# ---------------------------------------------------------------------------

# builtins reachable through REDUCE/find_class: pure constructors only —
# nothing that evaluates, imports, reflects, or touches the OS
_SAFE_BUILTINS = frozenset({
    "bool", "bytearray", "bytes", "complex", "dict", "float", "frozenset",
    "int", "list", "range", "set", "slice", "str", "tuple",
})

# numpy's pickle protocol names these to rebuild arrays/dtypes/scalars
# (module moved core -> _core across numpy 2.x, so match by name)
_SAFE_NUMPY = frozenset({
    "_reconstruct", "ndarray", "dtype", "scalar", "_frombuffer",
    "frombuffer", "matrix",
})

_SAFE_COLLECTIONS = frozenset({"OrderedDict", "deque", "defaultdict", "Counter"})


class RestrictedUnpickler(pickle.Unpickler):
    """Allowlist resolver: flink_tpu message/record/snapshot types, numpy
    reconstruction, stdlib scalars/containers — nothing else.

    flink_tpu resolution is CLASSES ONLY, and flink_tpu.security itself is
    excluded: a module-level *function* reachable through REDUCE is an
    arbitrary-call gadget (most directly `trusted_loads`, which would
    re-enter full pickle on attacker bytes and defeat this allowlist)."""

    def find_class(self, module: str, name: str):
        if module == "flink_tpu" or module.startswith("flink_tpu."):
            if module == "flink_tpu.security" or module.startswith("flink_tpu.security."):
                raise RestrictedUnpicklingError(
                    "transport frames may not reference flink_tpu.security "
                    f"({module}.{name}): deserializer re-entry is a "
                    "restricted-unpickling bypass"
                )
            obj = super().find_class(module, name)
            if not isinstance(obj, type):
                raise RestrictedUnpicklingError(
                    f"transport frames may only reference flink_tpu CLASSES, "
                    f"not {module}.{name} (module-level callables are "
                    "arbitrary-call gadgets under REDUCE)"
                )
            return obj
        if module == "numpy" or module.startswith("numpy."):
            if name in _SAFE_NUMPY:
                return super().find_class(module, name)
        elif module == "builtins":
            if name in _SAFE_BUILTINS:
                return super().find_class(module, name)
        elif module == "collections":
            if name in _SAFE_COLLECTIONS:
                return super().find_class(module, name)
        elif module == "copyreg" and name == "_reconstructor":
            # classic-protocol object reconstruction; the class it rebuilds
            # still has to pass find_class itself
            return super().find_class(module, name)
        raise RestrictedUnpicklingError(
            f"transport frames may not reference {module}.{name} "
            "(allowlist: flink_tpu types, numpy arrays, stdlib scalars)"
        )


def restricted_loads(data: bytes):
    """`pickle.loads` through the transport allowlist."""
    return RestrictedUnpickler(io.BytesIO(data)).load()


def dumps(obj) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def trusted_loads(data: bytes):
    """Full (cloudpickle-aware) deserialization of a job-spec payload.

    ONLY for bytes received over an already-authenticated channel — the
    user-JAR analogue: specs carry arbitrary closures, so they are code by
    definition and authentication, not allowlisting, is the boundary."""
    return pickle.loads(data)


# ---------------------------------------------------------------------------
# per-frame MAC discipline
# ---------------------------------------------------------------------------

MAC_LEN = hashlib.sha256().digest_size  # 32


class FrameCodec:
    """Seals/opens frames on one established connection.

    `seal` prepends `HMAC-SHA256(session_key, dir || seq_be8 || payload)`;
    `open` recomputes and compares constant-time BEFORE the payload is
    handed to any deserializer. Sequence counters are per direction, so a
    recorded frame cannot be replayed later in the stream, and the
    direction byte keeps a peer's own frames from being reflected back."""

    def __init__(self, session_key: bytes, is_client: bool):
        self._key = session_key
        self._send_dir = b"C" if is_client else b"S"
        self._recv_dir = b"S" if is_client else b"C"
        self._send_seq = 0
        self._recv_seq = 0

    def _mac(self, direction: bytes, seq: int, parts) -> bytes:
        h = hmac.new(self._key, direction + struct.pack(">Q", seq),
                     hashlib.sha256)
        for p in parts:
            h.update(p)
        return h.digest()

    def seal(self, payload: bytes) -> bytes:
        return self.seal_parts((payload,)) + payload

    def seal_parts(self, parts) -> bytes:
        """MAC for a scatter-gather frame: HMAC(session_key, dir || seq ||
        part0 || part1 || ...) computed INCREMENTALLY (`hmac.update` per
        part), so a multi-buffer frame — e.g. the binary columnar wire's
        header + sidecar + array buffers (security/wire.py) — is
        authenticated without ever materializing the concatenated copy.
        Consumes one send-sequence slot; the caller transmits the returned
        MAC alongside the same parts in the same order."""
        mac = self._mac(self._send_dir, self._send_seq, parts)
        self._send_seq += 1
        return mac

    def open(self, frame: bytes) -> bytes:
        if len(frame) < MAC_LEN:
            raise FrameAuthError("frame shorter than its MAC")
        mac, payload = frame[:MAC_LEN], frame[MAC_LEN:]
        self.open_parts(mac, (payload,))
        return payload

    def open_parts(self, mac: bytes, parts) -> None:
        """Verify a scatter-gather frame's MAC (constant-time compare)
        BEFORE any part is parsed or deserialized; consumes one
        recv-sequence slot. Raises FrameAuthError on mismatch."""
        want = self._mac(self._recv_dir, self._recv_seq, parts)
        if not hmac.compare_digest(mac, want):
            raise FrameAuthError("frame MAC verification failed")
        self._recv_seq += 1
