"""Transport security: cluster secret, connection handshake, optional TLS.

The runtime's network planes (runtime/rpc.py control plane, the
runtime/dataplane.py exchange, and the blob endpoint riding RPC) share one
trust model, mirroring the reference's internal-connectivity security
(`security.ssl.internal.*`, SSLHandlerFactory.java):

- A **per-cluster shared secret** (config > secret file > environment >
  auto-generated per-user file) authenticates both ends.
- A **handshake** at connection open: the server sends a random challenge
  nonce; the client answers with protocol version + cluster id + its own
  nonce + an HMAC over both nonces; the server verifies constant-time and
  proves itself back with an HMAC over the reversed transcript. Anything
  that fails — wrong magic, wrong version, wrong cluster, wrong secret —
  is disconnected BEFORE any payload byte is deserialized.
- A per-connection **session key** `HMAC(secret, nonces)` then MAC-signs
  every frame (security/framing.py), so post-handshake tampering or
  injection is detected frame-by-frame.
- **TLS** (stdlib `ssl`) can be layered UNDER the HMAC framing with the
  `security.ssl.internal.*` options — certificates give you wire privacy
  and PKI-rooted peer identity; the HMAC layer still provides cluster
  membership + per-frame integrity even where TLS terminates early (a
  sidecar, an lb).

`security.transport.enabled: false` restores the legacy plaintext-pickle
protocol for local debugging.
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac
import os
import secrets as _secrets
import socket
import struct
import tempfile
import threading
from typing import Optional

from flink_tpu.security.framing import (
    MAC_LEN,
    FrameAuthError,
    FrameCodec,
    dumps,
    restricted_loads,
)
from flink_tpu.security import wire
from flink_tpu.chaos import plan as _chaos   # leaf module (stdlib-only)

MAGIC = b"FTPU"
PROTOCOL_VERSION = 1
NONCE_LEN = 16
_HS_CLIENT = b"flink-tpu-hs-client-v1"
_HS_SERVER = b"flink-tpu-hs-server-v1"
_HS_SESSION = b"flink-tpu-session-v1"
_REST_BEARER = b"flink-tpu-rest-bearer-v1"

ENV_ENABLED = "FLINK_TPU_SECURITY_TRANSPORT_ENABLED"
ENV_SECRET = "FLINK_TPU_SECURITY_TRANSPORT_SECRET"
ENV_SECRET_FILE = "FLINK_TPU_SECURITY_TRANSPORT_SECRET_FILE"
ENV_CLUSTER_ID = "FLINK_TPU_SECURITY_TRANSPORT_CLUSTER_ID"


# ---------------------------------------------------------------------------
# byte-level framing shared by every plane (moved from runtime/rpc.py so the
# security layer does not depend on the runtime it guards)
# ---------------------------------------------------------------------------

def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _read_n(sock: socket.socket, n: int) -> Optional[bytes]:
    """Exactly n bytes off the socket, or None if the peer closes first."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_into_exact(sock: socket.socket, buf) -> bool:
    """Fill `buf` straight from the kernel (`recv_into`, no intermediate
    chunk accumulation); False if the peer closes mid-frame."""
    view = memoryview(buf)
    got = 0
    while got < len(buf):
        r = sock.recv_into(view[got:])
        if r == 0:
            return False
        got += r
    return True


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    hdr = _read_n(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack(">I", hdr)
    return _read_n(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Fixed-size read used ONLY during the handshake: an unauthenticated
    peer never gets to pick an allocation size."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise FrameAuthError("peer closed during handshake")
        buf += chunk
    return bytes(buf)


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SecurityConfig:
    """Resolved transport-security settings for one process/cluster."""

    enabled: bool = True
    secret: bytes = b""
    cluster_id: str = "flink-tpu"
    handshake_timeout_s: float = 10.0
    ssl_enabled: bool = False
    ssl_cert: Optional[str] = None
    ssl_key: Optional[str] = None
    ssl_ca: Optional[str] = None

    # -- construction ------------------------------------------------------
    @staticmethod
    def disabled() -> "SecurityConfig":
        return SecurityConfig(enabled=False)

    @staticmethod
    def with_secret(secret, cluster_id: str = "flink-tpu", **kw) -> "SecurityConfig":
        if isinstance(secret, str):
            secret = secret.encode()
        return SecurityConfig(enabled=True, secret=secret, cluster_id=cluster_id, **kw)

    @staticmethod
    def from_config(config) -> "SecurityConfig":
        """Resolve from a Configuration's `security.*` option group."""
        from flink_tpu.config import SecurityOptions

        if not config.get(SecurityOptions.TRANSPORT_ENABLED):
            return SecurityConfig.disabled()
        secret = config.get(SecurityOptions.TRANSPORT_SECRET)
        secret_file = config.get(SecurityOptions.TRANSPORT_SECRET_FILE)
        if secret is not None:
            key = secret.encode() if isinstance(secret, str) else bytes(secret)
        elif secret_file:
            key = _read_secret_file(secret_file)
        else:
            key = _env_or_default_secret()
        return SecurityConfig(
            enabled=True,
            secret=key,
            cluster_id=config.get(SecurityOptions.TRANSPORT_CLUSTER_ID),
            ssl_enabled=config.get(SecurityOptions.SSL_INTERNAL_ENABLED),
            ssl_cert=config.get(SecurityOptions.SSL_INTERNAL_CERT),
            ssl_key=config.get(SecurityOptions.SSL_INTERNAL_KEY),
            ssl_ca=config.get(SecurityOptions.SSL_INTERNAL_CA),
        )

    @staticmethod
    def resolve(config=None) -> "SecurityConfig":
        """The entry point every plane uses: explicit Configuration if
        given, else the cached process default (env > per-user secret
        file). Every process of one user on one host resolves the same
        default secret, so local multi-process clusters (and the e2e
        subprocess tests) authenticate out of the box."""
        if config is not None:
            return SecurityConfig.from_config(config)
        return _process_default()


_default_lock = threading.Lock()
_default: Optional[SecurityConfig] = None


def _process_default() -> SecurityConfig:
    global _default
    with _default_lock:
        if _default is None:
            if os.environ.get(ENV_ENABLED, "true").strip().lower() in (
                    "false", "0", "no", "off"):
                _default = SecurityConfig.disabled()
            else:
                _default = SecurityConfig(
                    enabled=True,
                    secret=_env_or_default_secret(),
                    cluster_id=os.environ.get(ENV_CLUSTER_ID, "flink-tpu"),
                )
        return _default


def _set_process_default(sec: Optional[SecurityConfig]) -> Optional[SecurityConfig]:
    """Swap the cached process default (testing hook; see
    flink_tpu.testing.harness.transport_security). Returns the previous
    value so callers can restore it."""
    global _default
    with _default_lock:
        prev, _default = _default, sec
        return prev


def _read_secret_file(path: str) -> bytes:
    with open(path, "rb") as f:
        data = f.read().strip()
    if not data:
        raise ValueError(f"transport secret file {path!r} is empty")
    return data


def _default_secret_path() -> str:
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"flink-tpu-{uid}.transport.secret")


def _read_default_secret(path: str) -> bytes:
    """Read the auto-provisioned secret ONLY if we own it and nobody else
    can touch it: the default path lives in a world-writable tmpdir, so a
    local attacker could pre-create it with a value they know (secret
    squatting) — trusting such a file would hand them the cluster."""
    st = os.lstat(path)
    uid = os.getuid() if hasattr(os, "getuid") else st.st_uid
    if st.st_uid != uid or (st.st_mode & 0o077) or not os.path.isfile(path):
        raise PermissionError(
            f"refusing auto-provisioned transport secret {path!r}: it must "
            f"be a regular file owned by uid {uid} with mode 0600 (found "
            f"uid {st.st_uid}, mode {oct(st.st_mode & 0o777)}). Remove it, "
            "or set security.transport.secret-file / "
            f"{ENV_SECRET} explicitly."
        )
    return _read_secret_file(path)


def _env_or_default_secret() -> bytes:
    env = os.environ.get(ENV_SECRET)
    if env:
        return env.encode()
    env_file = os.environ.get(ENV_SECRET_FILE)
    if env_file:
        return _read_secret_file(env_file)
    # auto-provisioned per-user secret (the Jupyter-token pattern): 0600 so
    # other local users cannot read it; same-user processes share it
    path = _default_secret_path()
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    except FileExistsError:
        return _read_default_secret(path)
    try:
        os.write(fd, _secrets.token_hex(32).encode())
    finally:
        os.close(fd)
    return _read_default_secret(path)


def rest_bearer_token(sec: SecurityConfig) -> str:
    """REST API bearer token derived from the cluster secret (one secret to
    provision; the REST plane authenticates with its HMAC-derived form so
    the raw transport secret never appears in HTTP headers)."""
    return hmac.new(sec.secret, _REST_BEARER, hashlib.sha256).hexdigest()


def bearer_header_equal(header_value: str, token: str) -> bool:
    """Constant-time check of an Authorization header against
    `Bearer <token>` — THE comparison both HTTP planes (runtime/rest.py
    and the SQL gateway) use. Compares latin-1 BYTES: compare_digest
    raises TypeError on non-ASCII str input, and http.server decodes
    headers as latin-1, so a garbage header must read as unauthorized,
    never kill the handler thread."""
    return hmac.compare_digest(
        (header_value or "").encode("latin-1", "replace"),
        f"Bearer {token}".encode("latin-1", "replace"),
    )


# ---------------------------------------------------------------------------
# TLS layering (security.ssl.internal.* analogue)
# ---------------------------------------------------------------------------

def validate_server_config(sec: SecurityConfig) -> None:
    """Fail FAST on server-side misconfiguration. Called at
    RpcService/ExchangeServer construction: inside a connection handler the
    same error would be swallowed by the unauthenticated-peer drop and
    surface only as every client timing out with no server diagnostic."""
    if sec.enabled and sec.ssl_enabled and not (sec.ssl_cert and sec.ssl_key):
        raise ValueError(
            "security.ssl.internal.enabled requires security.ssl.internal.cert "
            "and security.ssl.internal.key"
        )


def wrap_server_socket(sock: socket.socket, sec: SecurityConfig) -> socket.socket:
    if not sec.ssl_enabled:
        return sock
    import ssl

    if not sec.ssl_cert or not sec.ssl_key:
        raise ValueError(
            "security.ssl.internal.enabled requires security.ssl.internal.cert "
            "and security.ssl.internal.key"
        )
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(sec.ssl_cert, sec.ssl_key)
    if sec.ssl_ca:
        # mutual TLS: require a peer certificate from the cluster CA
        ctx.load_verify_locations(sec.ssl_ca)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx.wrap_socket(sock, server_side=True)


def wrap_client_socket(sock: socket.socket, sec: SecurityConfig) -> socket.socket:
    if not sec.ssl_enabled:
        return sock
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    # cluster-internal certs are addressed by ip:port, not DNS names — peer
    # identity comes from the CA + the HMAC handshake (the reference's
    # internal SSL likewise pins a fingerprint rather than hostnames)
    ctx.check_hostname = False
    if sec.ssl_ca:
        ctx.load_verify_locations(sec.ssl_ca)
        ctx.verify_mode = ssl.CERT_REQUIRED
    else:
        ctx.verify_mode = ssl.CERT_NONE
    if sec.ssl_cert and sec.ssl_key:
        ctx.load_cert_chain(sec.ssl_cert, sec.ssl_key)
    return ctx.wrap_socket(sock)


# ---------------------------------------------------------------------------
# handshake
# ---------------------------------------------------------------------------

def _hs_mac(sec: SecurityConfig, label: bytes, *parts: bytes) -> bytes:
    msg = label + bytes([PROTOCOL_VERSION]) + sec.cluster_id.encode() + b"".join(parts)
    return hmac.new(sec.secret, msg, hashlib.sha256).digest()


def _session_key(sec: SecurityConfig, server_nonce: bytes, client_nonce: bytes) -> bytes:
    return hmac.new(sec.secret, _HS_SESSION + server_nonce + client_nonce,
                    hashlib.sha256).digest()


def server_handshake(sock: socket.socket, sec: SecurityConfig) -> FrameCodec:
    """Challenge the connecting peer; raises FrameAuthError before ANY
    variable-length/deserializable byte is accepted from an
    unauthenticated source."""
    server_nonce = _secrets.token_bytes(NONCE_LEN)
    sock.sendall(MAGIC + bytes([PROTOCOL_VERSION]) + server_nonce)
    head = _recv_exact(sock, len(MAGIC) + 2)
    if head[:len(MAGIC)] != MAGIC:
        raise FrameAuthError("bad handshake magic")
    if head[len(MAGIC)] != PROTOCOL_VERSION:
        raise FrameAuthError(f"unsupported protocol version {head[len(MAGIC)]}")
    cid_len = head[len(MAGIC) + 1]
    cid = _recv_exact(sock, cid_len)
    client_nonce = _recv_exact(sock, NONCE_LEN)
    proof = _recv_exact(sock, 32)
    want = _hs_mac(sec, _HS_CLIENT, server_nonce, client_nonce)
    # one combined constant-time verdict: cluster-id mismatch and secret
    # mismatch are indistinguishable to the peer
    cid_ok = hmac.compare_digest(cid, sec.cluster_id.encode())
    if not (hmac.compare_digest(proof, want) and cid_ok):
        raise FrameAuthError("handshake authentication failed")
    sock.sendall(_hs_mac(sec, _HS_SERVER, client_nonce, server_nonce))
    return FrameCodec(_session_key(sec, server_nonce, client_nonce), is_client=False)


def client_handshake(sock: socket.socket, sec: SecurityConfig) -> FrameCodec:
    """Answer the server's challenge and verify the server's proof (mutual
    authentication: a rogue listener without the secret is detected)."""
    head = _recv_exact(sock, len(MAGIC) + 1 + NONCE_LEN)
    if head[:len(MAGIC)] != MAGIC:
        raise FrameAuthError(
            "peer did not offer the secured handshake (is "
            "security.transport.enabled false on the server?)"
        )
    if head[len(MAGIC)] != PROTOCOL_VERSION:
        raise FrameAuthError(f"unsupported protocol version {head[len(MAGIC)]}")
    server_nonce = head[len(MAGIC) + 1:]
    client_nonce = _secrets.token_bytes(NONCE_LEN)
    cid = sec.cluster_id.encode()
    if len(cid) > 255:
        raise ValueError("security.transport.cluster-id longer than 255 bytes")
    sock.sendall(
        MAGIC + bytes([PROTOCOL_VERSION, len(cid)]) + cid + client_nonce
        + _hs_mac(sec, _HS_CLIENT, server_nonce, client_nonce)
    )
    proof = _recv_exact(sock, 32)
    if not hmac.compare_digest(proof, _hs_mac(sec, _HS_SERVER, client_nonce, server_nonce)):
        raise FrameAuthError("server failed to prove the cluster secret")
    return FrameCodec(_session_key(sec, server_nonce, client_nonce), is_client=True)


# ---------------------------------------------------------------------------
# object send/recv used by every plane
# ---------------------------------------------------------------------------

def send_obj(sock: socket.socket, obj, codec: Optional[FrameCodec]) -> int:
    """Restricted-pickle frame send; returns bytes written to the wire (the
    dataplane's numBytesOut accounting reads this)."""
    hook = _chaos.HOOK   # chaos seam: one is-None check when chaos is off
    if hook is not None:
        # port-qualified site so a rule can target ONE peer: "send_obj"
        # alone matches every plane's sends process-wide
        try:
            peer = sock.getpeername()[1]
        except OSError:
            peer = 0
        if hook("transport", f"send_obj:{peer}") == "drop":
            return 0      # frame silently lost pre-wire (peer sees silence)
    payload = dumps(obj)
    mac_len = MAC_LEN if codec is not None else 0
    if len(payload) + mac_len >= wire.DATA_FLAG:
        # bit 31 of the length prefix marks binary data frames on the
        # dataplane; a >= 2 GiB pickled frame would be misparsed there, so
        # fail loudly at the sender (any plane: a single 2 GiB frame means
        # something upstream is already deeply wrong). Checked BEFORE
        # seal(): a refused frame must not consume a codec sequence slot.
        raise ValueError(
            f"frame too large ({len(payload) + mac_len} bytes >= 2 GiB)")
    frame = codec.seal(payload) if codec is not None else payload
    send_frame(sock, frame)
    return 4 + len(frame)


def recv_obj(sock: socket.socket, codec: Optional[FrameCodec]):
    """Next message, or None at EOF. With a codec: MAC-verify first, then
    restricted-deserialize. Without (security disabled): legacy pickle."""
    frame = recv_frame(sock)
    if frame is None:
        return None
    if codec is not None:
        return restricted_loads(codec.open(frame))
    import pickle

    return pickle.loads(frame)


# ---------------------------------------------------------------------------
# binary columnar data frames (dataplane only; see security/wire.py)
# ---------------------------------------------------------------------------

def _advance(views, n: int):
    """Drop the first n bytes from a scatter-gather view list (partial
    sendmsg): fully-sent views fall off, the boundary view is sliced."""
    for i, v in enumerate(views):
        if n >= len(v):
            n -= len(v)
            continue
        rest = views[i:] if n == 0 else [v[n:]] + views[i + 1:]
        return rest
    return []


try:
    _IOV_MAX = os.sysconf("SC_IOV_MAX")
except (AttributeError, OSError, ValueError):
    _IOV_MAX = 1024
if _IOV_MAX <= 0:
    _IOV_MAX = 1024  # sysconf may report 'indeterminate' as -1, not raise


def _send_parts(sock: socket.socket, parts) -> None:
    """Scatter-gather send (`socket.sendmsg`) of a list of buffers without
    concatenating them, at most IOV_MAX buffers per call (a many-column
    payload can exceed the kernel's iovec limit — EMSGSIZE — so the send
    loops in capped groups); falls back to one joined `sendall` where
    sendmsg is unavailable (TLS sockets raise NotImplementedError before
    any byte is written, so the fallback never double-sends)."""
    views = [memoryview(p) for p in parts if len(p)]
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is not None:
        try:
            while views:
                views = _advance(views, sendmsg(views[:_IOV_MAX]))
            return
        except NotImplementedError:
            pass  # ssl.SSLSocket: refuses up front, nothing sent yet
    sock.sendall(b"".join(views))


def send_data_frame(sock: socket.socket, channel: str, seq: int, cols,
                    sidecar: bytes, codec: Optional[FrameCodec]) -> int:
    """One binary columnar data frame: length prefix with the DATA_FLAG bit,
    optional incremental MAC over every part, then the parts themselves via
    scatter-gather I/O — contiguous numeric columns leave the process with
    zero copies. Returns bytes written."""
    parts, body_len = wire.encode_frame(channel, seq, cols, sidecar)
    mac_len = MAC_LEN if codec is not None else 0
    total = body_len + mac_len
    if total >= wire.DATA_FLAG:
        raise ValueError(f"data frame too large ({total} bytes)")
    prefix = struct.pack(">I", total | wire.DATA_FLAG)
    if codec is not None:
        mac = codec.seal_parts(parts)
        _send_parts(sock, [prefix, mac, *parts])
    else:
        _send_parts(sock, [prefix, *parts])
    return 4 + total


def recv_msg(sock: socket.socket, codec: Optional[FrameCodec]):
    """Next dataplane message as ``(msg, nbytes)``; ``(None, 0)`` at EOF.

    Legacy frames decode exactly like `recv_obj`. Binary columnar data
    frames — flag bit set in the length prefix — are read into ONE
    preallocated buffer with `recv_into`, MAC-verified over that buffer
    BEFORE any parsing, then decoded to ``("data", channel, seq, payload)``
    with the payload's raw columns as zero-copy `np.frombuffer` views
    (security/wire.py). `nbytes` is the frame's full wire size, feeding the
    receiver's numBytesIn accounting."""
    while True:
        hdr = _read_n(sock, 4)
        if hdr is None:
            return None, 0
        # chaos seam (recv side), consulted only once a frame provably
        # exists (after the length prefix) — at EOF a bounded drop rule
        # must not burn its max_fires budget on nothing. A drop swallows
        # the frame AFTER it is read (and MAC-verified) — exactly loss in
        # transit past the NIC; a dropped data frame then surfaces as a
        # sequence gap. The site carries the receiver's OWN port so one
        # exchange server is targetable without every other live socket
        # skewing nth-counts.
        hook = _chaos.HOOK
        dropping = False
        if hook is not None:
            try:
                own = sock.getsockname()[1]
            except OSError:
                own = 0
            dropping = hook("transport", f"recv_msg:{own}") == "drop"
        (n,) = struct.unpack(">I", hdr)
        if not (n & wire.DATA_FLAG):
            body = _read_n(sock, n)
            if body is None:
                return None, 0
            if codec is not None:
                obj_bytes = codec.open(body)   # MAC-verify (and advance the
                # replay counter) even for a dropped frame — the drop models
                # loss ABOVE the authenticated transport, not a desync
                if dropping:
                    continue
                return restricted_loads(obj_bytes), 4 + n
            if dropping:
                continue
            import pickle

            return pickle.loads(body), 4 + n
        n &= wire.DATA_FLAG - 1
        total = 4 + n
        if codec is not None:
            if n < MAC_LEN:
                raise FrameAuthError("binary frame shorter than its MAC")
            # one allocation, one recv_into stream for MAC + body together,
            # with the body (byte MAC_LEN) placed on the alignment grid
            buf = wire.alloc_body(n, lead=MAC_LEN)
            if not _recv_into_exact(sock, buf):
                return None, 0
            body = memoryview(buf)[MAC_LEN:]
            codec.open_parts(bytes(buf[:MAC_LEN]), (body,))
            if dropping:   # after MAC verify: the replay counter advanced
                continue
            channel, seq, payload = wire.decode_frame(body)
        else:
            buf = wire.alloc_body(n)
            if not _recv_into_exact(sock, buf):
                return None, 0
            if dropping:
                continue
            channel, seq, payload = wire.decode_frame(buf, trusted_pickle=True)
        return ("data", channel, seq, payload), total
