"""Zero-copy binary columnar wire format for the dataplane exchange.

The legacy exchange wire pickles every record batch into a fresh buffer,
MACs it in a second full pass, sends it as one monolithic frame, and
restricted-unpickles it on the receiver — three or more full copies per hop
of data that is already columnar numpy. This module is the DCN analogue of
the reference's zero-copy Netty buffer transfer (NetworkBuffer /
BufferResponse carrying the buffer by reference): a batch payload travels
as

    [compact little-endian header] [restricted-pickle sidecar] [raw buffers]

where the header describes each raw column (name, dtype string, shape,
absolute byte offset, byte count) and the buffers are transmitted as
`memoryview`s over the producer's existing numpy arrays — NO copy for
contiguous numeric columns. Object-dtype columns (and any element that is
not a raw-encodable ndarray: scalars, strings, tags) ride the sidecar, a
restricted-pickle of the residual payload skeleton. The receiver reads the
whole frame body into ONE preallocated buffer with `recv_into` and maps
each column back as an `np.frombuffer` view — again no copy.

Authentication composes with security/framing.py unchanged: the per-frame
HMAC is computed incrementally over header, sidecar, and each buffer
(`FrameCodec.seal_parts`), and the receiver MAC-verifies the single
received body BEFORE the header is parsed or the sidecar is deserialized —
the same MAC-verify-before-deserialize guarantee as the legacy wire,
without the concatenated copy.

Frame discipline (security/transport.py `send_data_frame`/`recv_msg`): a
binary data frame is distinguished from legacy restricted-pickle frames by
the top bit of the 4-byte length prefix (`DATA_FLAG`), so both kinds can
interleave on one connection — control frames (`open`/`credit`/`eos`) and
non-batch payloads stay on the legacy codec, and a peer that never learned
the flag bit never sees it (the sender only emits binary frames after the
receiver advertises support on the `open` reply; see runtime/dataplane.py).

Wire layout of a frame body (everything little-endian; the outer length
prefix stays big-endian to match the legacy framing):

    offset  size  field
    0       2     magic "FB"
    2       1     wire version (1)
    3       1     flags (reserved, 0)
    4       4     header_len u32 — total header size in bytes
    8       8     seq u64 — per-channel batch sequence number
    16      2     channel_len u16, then channel id (utf-8)
    ..      2     ncols u16 — number of raw columns
    ..      4     sidecar_len u32
    then per column:
            2     name_len u16, then column name (utf-8)
            1     dtype_len u8, then numpy dtype string (ascii, e.g. "<f8")
            1     ndim u8
            8*nd  shape u64 × ndim
            8     offset u64 — ABSOLUTE offset of the column's bytes in the
                  frame body (64-byte aligned)
            8     nbytes u64

    [sidecar bytes]  at [header_len, header_len + sidecar_len)
    [column buffers] at their declared offsets, zero-padded to alignment
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.security.framing import dumps, restricted_loads

__all__ = [
    "BUFFER_ALIGN",
    "DATA_FLAG",
    "WIRE_MAGIC",
    "WIRE_VERSION",
    "WireFormatError",
    "decode_frame",
    "encode_frame",
    "extract_columns",
]

WIRE_MAGIC = b"FB"
WIRE_VERSION = 1

# top bit of the outer big-endian length prefix marks a binary data frame;
# legacy frames keep bit 31 clear (their payloads are capped well below 2 GiB)
DATA_FLAG = 0x8000_0000

# column buffers land 64-byte aligned inside the receiver's single recv_into
# allocation, so np.frombuffer views are vector-load friendly
BUFFER_ALIGN = 64

# a payload tuple longer than this is not a record batch; refuse rather than
# let a crafted sidecar allocate an absurd skeleton (relevant with auth off)
_MAX_PAYLOAD_ITEMS = 4096


class WireFormatError(ConnectionError):
    """Binary data frame failed structural validation (truncated header,
    out-of-bounds buffer, dtype/shape mismatch, or malformed sidecar)."""


def alloc_body(n: int, lead: int = 0):
    """Receive buffer for one frame of n bytes: numpy uint8 (UNINITIALIZED
    — a bytearray would zero-fill n bytes before recv_into overwrites
    them), placed so that byte `lead` sits on a BUFFER_ALIGN memory
    address. The frame body starts at `lead` (MAC_LEN when auth is on, 0
    otherwise) and its column offsets are BUFFER_ALIGN-multiples, so the
    decoded frombuffer views land on truly aligned addresses — np.empty
    alone guarantees only malloc alignment, and the 32-byte MAC prefix
    would shift every column off the grid. Supports the buffer protocol
    for recv_into/hmac and serves as the base of the decoded views."""
    raw = np.empty(n + BUFFER_ALIGN, dtype=np.uint8)
    shift = (-(raw.ctypes.data + lead)) % BUFFER_ALIGN
    return raw[shift:shift + n]


def _raw_encodable(el: Any) -> bool:
    """True for arrays whose bytes can travel as a raw wire buffer: plain
    C-reconstructible ndarrays (subclasses like np.matrix keep their pickle
    path) of at least one dimension, with a fixed-size, object-free dtype —
    numeric, bool, datetime/timedelta, and fixed-width string/bytes all
    qualify; object and structured/void dtypes ride the sidecar."""
    return (
        type(el) is np.ndarray
        and el.ndim >= 1
        and not el.dtype.hasobject
        and el.dtype.kind != "V"
    )


def extract_columns(payload: Any) -> Optional[Tuple[List[Tuple[str, np.ndarray]], bytes]]:
    """Split a data payload into raw wire columns + a restricted-pickle
    sidecar.

    Payloads are positional: tuple element i becomes raw column "i" when it
    is a raw-encodable ndarray; everything else (tags, scalars, object /
    structured arrays) goes into the sidecar skeleton ``(n_items, {i:
    value})``. Returns None when the payload is not binary-eligible — not a
    tuple, too long, or holding no raw column — in which case it rides a
    legacy restricted-pickle frame unchanged (control messages like
    ``("w", wm)`` stay on the old codec by construction)."""
    if type(payload) is not tuple or len(payload) > _MAX_PAYLOAD_ITEMS:
        return None
    cols: List[Tuple[str, np.ndarray]] = []
    rest: Dict[int, Any] = {}
    for i, el in enumerate(payload):
        if _raw_encodable(el):
            cols.append((str(i), np.ascontiguousarray(el)))
        else:
            rest[i] = el
    if not cols:
        return None
    return cols, dumps((len(payload), rest))


def encode_frame(
    channel: str,
    seq: int,
    cols: List[Tuple[str, np.ndarray]],
    sidecar: bytes,
) -> Tuple[List[Any], int]:
    """Build the scatter-gather part list for one binary data frame body:
    ``[header, sidecar, pad?, buffer, pad?, buffer, ...]``. Returns
    ``(parts, body_len)``. Array bytes are NOT copied — each buffer part is
    a memoryview over the caller's array, ready for `socket.sendmsg` and
    for incremental MACing (`FrameCodec.seal_parts`)."""
    ch = channel.encode()
    entries = []
    fixed = 16 + 2 + len(ch) + 2 + 4
    hlen = fixed
    for name, arr in cols:
        nb = name.encode()
        dt = arr.dtype.str.encode("ascii")
        entries.append((nb, dt, arr))
        hlen += 2 + len(nb) + 1 + len(dt) + 1 + 8 * arr.ndim + 16

    head = bytearray()
    head += WIRE_MAGIC
    head.append(WIRE_VERSION)
    head.append(0)
    head += struct.pack("<IQ", hlen, seq)
    head += struct.pack("<H", len(ch)) + ch
    head += struct.pack("<HI", len(entries), len(sidecar))

    parts: List[Any] = [None, sidecar]  # header backpatched once complete
    off = hlen + len(sidecar)
    for nb, dt, arr in entries:
        pad = (-off) % BUFFER_ALIGN
        if pad:
            parts.append(b"\x00" * pad)
            off += pad
        head += struct.pack("<H", len(nb)) + nb
        head += struct.pack("<B", len(dt)) + dt
        head += struct.pack("<B", arr.ndim)
        head += struct.pack(f"<{arr.ndim}Q", *arr.shape)
        head += struct.pack("<QQ", off, arr.nbytes)
        try:
            parts.append(memoryview(arr).cast("B"))
        except (ValueError, TypeError):
            # datetime64/timedelta64 refuse the buffer protocol directly;
            # a u1 view of the same (contiguous) memory is still zero-copy
            parts.append(memoryview(arr.view("u1")).cast("B"))
        off += arr.nbytes
    if len(head) != hlen:
        raise WireFormatError(
            f"internal header-size mismatch ({len(head)} != {hlen})")
    parts[0] = bytes(head)
    return parts, off


def decode_frame(body, *, trusted_pickle: bool = False) -> Tuple[str, int, tuple]:
    """Parse one binary data frame body — AFTER MAC verification when auth
    is on — into ``(channel, seq, payload)``. Raw columns come back as
    `np.frombuffer` views into `body` (zero copy; the arrays keep `body`
    alive through their base reference). `trusted_pickle` selects plain
    pickle for the sidecar, matching the legacy wire's semantics when
    `security.transport.enabled: false`; with auth on the sidecar goes
    through the restricted allowlist like every other frame."""
    try:
        return _decode_frame(body, trusted_pickle)
    except WireFormatError:
        raise
    except (struct.error, UnicodeDecodeError, TypeError, ValueError,
            OverflowError, IndexError) as e:
        raise WireFormatError(f"malformed binary data frame: {e}") from e


def _decode_frame(body, trusted_pickle: bool) -> Tuple[str, int, tuple]:
    n = len(body)
    if n < 24 or bytes(body[0:2]) != WIRE_MAGIC:
        raise WireFormatError("bad binary frame magic")
    if body[2] != WIRE_VERSION:
        raise WireFormatError(f"unsupported wire version {body[2]}")
    hlen, seq = struct.unpack_from("<IQ", body, 4)
    pos = 16
    (clen,) = struct.unpack_from("<H", body, pos)
    pos += 2
    if pos + clen > n:
        raise WireFormatError("channel id overruns frame")
    channel = bytes(body[pos:pos + clen]).decode()
    pos += clen
    ncols, slen = struct.unpack_from("<HI", body, pos)
    pos += 6
    if hlen > n or hlen + slen > n:
        raise WireFormatError("header/sidecar overrun frame")

    cols = []
    for _ in range(ncols):
        if pos >= hlen:
            raise WireFormatError("column table overruns declared header")
        (nl,) = struct.unpack_from("<H", body, pos)
        pos += 2
        name = bytes(body[pos:pos + nl]).decode()
        pos += nl
        dl = body[pos]
        pos += 1
        dtype_s = bytes(body[pos:pos + dl]).decode("ascii")
        pos += dl
        ndim = body[pos]
        pos += 1
        shape = struct.unpack_from(f"<{ndim}Q", body, pos)
        pos += 8 * ndim
        off, nbytes = struct.unpack_from("<QQ", body, pos)
        pos += 16
        cols.append((name, dtype_s, shape, off, nbytes))
    if pos != hlen:
        raise WireFormatError("header length mismatch")

    sidecar = bytes(body[hlen:hlen + slen])
    if trusted_pickle:
        import pickle

        nitems, rest = pickle.loads(sidecar)
    else:
        nitems, rest = restricted_loads(sidecar)
    if not isinstance(nitems, int) or not isinstance(rest, dict) \
            or not 0 <= nitems <= _MAX_PAYLOAD_ITEMS:
        raise WireFormatError("malformed sidecar skeleton")

    missing = object()
    items: List[Any] = [missing] * nitems
    data_start = hlen + slen
    for name, dtype_s, shape, off, nbytes in cols:
        dt = np.dtype(dtype_s)
        if dt.hasobject:
            raise WireFormatError("raw column with object dtype")
        count = 1
        for d in shape:
            count *= d
        if dt.itemsize * count != nbytes:
            raise WireFormatError(
                f"column {name!r}: {nbytes} bytes != shape {shape} of {dtype_s}")
        if off < data_start or off + nbytes > n:
            raise WireFormatError(f"column {name!r} buffer out of bounds")
        idx = int(name)
        if not 0 <= idx < nitems or items[idx] is not missing:
            raise WireFormatError(f"column {name!r}: bad payload position")
        items[idx] = np.frombuffer(
            body, dtype=dt, count=count, offset=off).reshape(shape)
    for k, v in rest.items():
        idx = int(k)
        if not 0 <= idx < nitems or items[idx] is not missing:
            raise WireFormatError(f"sidecar item {k!r}: bad payload position")
        items[idx] = v
    if any(it is missing for it in items):
        raise WireFormatError("payload positions incomplete")
    return channel, seq, tuple(items)
