"""Keyed state: descriptors, heap backend (oracle/CPU), columnar device backend."""
