"""Keyed state: descriptors, heap backend (oracle/CPU), columnar device
backend, and the key-group remap used for rescaling (key_groups.py)."""
