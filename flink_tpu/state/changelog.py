"""Changelog state backend: journal every mutation, materialize periodically.

Analogue of the reference's changelog backend + DSTL (S5:
flink-statebackend-changelog/.../ChangelogKeyedStateBackend.java:114,
flink-dstl/.../FsStateChangelogStorage.java:57): wraps any keyed backend,
appends each state mutation to a durable segment log so a checkpoint is
just (last materialized snapshot handle, log offset) — near-instant —
while a background-ish `materialize()` folds the log into a fresh full
snapshot and truncates.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.state.heap import HeapKeyedStateBackend, StateDescriptor


class FsStateChangelog:
    """Append-only mutation log in segment files (DSTL-dfs analogue)."""

    def __init__(self, directory: Optional[str] = None, segment_bytes: int = 1 << 20):
        self.dir = directory or tempfile.mkdtemp(prefix="flink_tpu_dstl_")
        os.makedirs(self.dir, exist_ok=True)
        self.segment_bytes = segment_bytes
        self._seg_id = 0
        self._seg_file = None
        self._offset = 0  # global sequence number of appended entries
        self.bytes_written = 0  # lifetime appended bytes (delta-size gauge)
        # opening an existing log directory resumes numbering after the last
        # entry (a fresh writer must never collide with surviving segments)
        for seg in sorted(os.listdir(self.dir)):
            if not seg.startswith("seg-"):
                continue
            self._seg_id = max(self._seg_id, int(seg[4:12]) + 1)
            with open(os.path.join(self.dir, seg), "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    body = f.read(int.from_bytes(hdr, "big"))
                    if len(body) < int.from_bytes(hdr, "big"):
                        break  # torn tail (crash mid-append): not a record
                    try:
                        seq, _ = pickle.loads(body)
                    except Exception:  # noqa: BLE001 — torn tail record
                        break
                    self._offset = max(self._offset, seq)

    def _segment_path(self, seg_id: int) -> str:
        return os.path.join(self.dir, f"seg-{seg_id:08d}.log")

    def append(self, entry: tuple) -> int:
        if self._seg_file is None:
            self._seg_file = open(self._segment_path(self._seg_id), "ab")
        self._offset += 1
        # entries carry their absolute sequence number so truncated logs
        # keep a stable numbering
        data = pickle.dumps((self._offset, entry), protocol=pickle.HIGHEST_PROTOCOL)
        self._seg_file.write(len(data).to_bytes(4, "big") + data)
        self.bytes_written += len(data) + 4
        self._seg_file.flush()
        if self._seg_file.tell() >= self.segment_bytes:
            self._seg_file.close()
            self._seg_file = None
            self._seg_id += 1
        return self._offset

    def read_entries(self, from_offset: int,
                     upto_offset: Optional[int] = None) -> List[Tuple[int, tuple]]:
        """(seq, entry) pairs with from_offset < seq <= upto_offset, in
        sequence order. A torn TAIL entry (a crash mid-append) is skipped:
        appends flush per entry and checkpoints only reference offsets of
        completed appends, so a truncated record can only ever sit beyond
        every offset a restore will ask for."""
        out: List[Tuple[int, tuple]] = []
        for seg in sorted(os.listdir(self.dir)):
            if not seg.startswith("seg-"):
                continue
            with open(os.path.join(self.dir, seg), "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    body = f.read(int.from_bytes(hdr, "big"))
                    if len(body) < int.from_bytes(hdr, "big"):
                        break  # torn tail: the entry was never committed
                    try:
                        seq, entry = pickle.loads(body)
                    except Exception:  # noqa: BLE001 — torn tail record
                        break
                    if seq > from_offset and (upto_offset is None
                                              or seq <= upto_offset):
                        out.append((seq, entry))
        out.sort(key=lambda p: p[0])
        return out

    def read_from(self, from_offset: int) -> List[tuple]:
        """All entries with sequence > from_offset (1-based)."""
        return [e for _, e in self.read_entries(from_offset)]

    def trim_above(self, offset: int) -> int:
        """Drop every entry with seq > `offset` — the DEAD TIMELINE cut a
        restore must make before resuming writes. Without it, orphan
        entries from the failed attempt (appended after the restored
        checkpoint) survive in the segments; a later checkpoint's offset
        lies above them (the writer resumes past the max seq seen), so a
        subsequent replay would fold the dead timeline's mutations into
        live state. Segments fully above the cut unlink; a straddling
        segment is rewritten in place. Returns entries dropped."""
        if self._seg_file is not None:
            self._seg_file.close()
            self._seg_file = None
        dropped = 0
        max_live = 0
        for seg in sorted(os.listdir(self.dir)):
            if not seg.startswith("seg-"):
                continue
            path = os.path.join(self.dir, seg)
            keep: List[bytes] = []
            any_dropped = False
            with open(path, "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    body = f.read(int.from_bytes(hdr, "big"))
                    if len(body) < int.from_bytes(hdr, "big"):
                        any_dropped = True  # torn tail goes with the trim
                        break
                    try:
                        seq, _entry = pickle.loads(body)
                    except Exception:  # noqa: BLE001 — torn tail record
                        any_dropped = True
                        break
                    if seq <= offset:
                        keep.append(hdr + body)
                        max_live = max(max_live, seq)
                    else:
                        any_dropped = True
                        dropped += 1
            if not any_dropped:
                continue
            if keep:
                tmp = path + ".trim"
                with open(tmp, "wb") as f:
                    for rec in keep:
                        f.write(rec)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            else:
                os.unlink(path)
        # resume numbering from the cut, not from the dead timeline's max
        self._offset = max(max_live, min(self._offset, offset))
        return dropped

    def truncate(self, upto_offset: int) -> None:
        """Drop whole segments fully covered by `upto_offset` (best-effort,
        like DSTL truncation after materialization)."""
        for seg in sorted(os.listdir(self.dir)):
            if not seg.startswith("seg-"):
                continue
            path = os.path.join(self.dir, seg)
            max_seq = 0
            with open(path, "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    seq, _ = pickle.loads(f.read(int.from_bytes(hdr, "big")))
                    max_seq = max(max_seq, seq)
            if max_seq <= upto_offset and self._segment_path(self._seg_id) != path:
                os.unlink(path)

    @property
    def offset(self) -> int:
        return self._offset


class ChangelogKeyedStateBackend:
    """Heap backend wrapper journaling mutations; checkpoints are O(1)."""

    def __init__(self, inner: HeapKeyedStateBackend,
                 changelog: Optional[FsStateChangelog] = None):
        self.inner = inner
        self.log = changelog or FsStateChangelog()
        self._materialized: Optional[dict] = None
        self._materialized_offset = 0
        # dead-timeline cut owed from a restore, applied LAZILY on the
        # first journaled mutation: an eager cut would invalidate newer
        # retained checkpoints that are still restorable while this
        # instance has not actually diverged from the restored point
        self._pending_trim: Optional[int] = None

    # -- delegated reads ----------------------------------------------------
    def set_current_key(self, key) -> None:
        self.inner.set_current_key(key)

    @property
    def current_key(self):
        return self.inner.current_key

    def register(self, descriptor: StateDescriptor) -> None:
        self.inner.register(descriptor)

    def get(self, name: str, namespace=None):
        return self.inner.get(name, namespace)

    def keys(self, name: str):
        return self.inner.keys(name)

    # -- journaled writes ---------------------------------------------------
    def _journal(self, entry: tuple) -> None:
        if self._pending_trim is not None:
            # first mutation after a restore: the timeline diverges HERE,
            # so the failed attempt's orphan entries (seq above the
            # restored offset) must go before this append — otherwise a
            # later checkpoint's (materialized_offset, log_offset] range
            # would cover and replay them
            self.log.trim_above(self._pending_trim)
            self._pending_trim = None
        self.log.append(entry)

    def put(self, name: str, value, namespace=None) -> None:
        self.inner.put(name, value, namespace)
        self._journal(("put", self.inner.current_key, name, namespace, value))

    def add(self, name: str, value, namespace=None) -> None:
        self.inner.add(name, value, namespace)
        self._journal(("add", self.inner.current_key, name, namespace, value))

    def clear(self, name: str, namespace=None) -> None:
        self.inner.clear(name, namespace)
        self._journal(("clear", self.inner.current_key, name, namespace, None))

    # -- checkpointing ------------------------------------------------------
    def checkpoint(self) -> dict:
        """O(1): a reference to the last materialization + the log offset."""
        return {
            "materialized": self._materialized,
            "materialized_offset": self._materialized_offset,
            "log_offset": self.log.offset,
            "log_dir": self.log.dir,
        }

    def materialize(self, truncate_upto: Optional[int] = None) -> None:
        """Fold the journal into a full snapshot. Truncation is driven by
        checkpoint subsumption (the reference truncates DSTL only once no
        retained checkpoint references the range): pass the log offset of
        the oldest checkpoint still retained; entries below min(it, the new
        materialization) are dropped. Default: keep everything."""
        self._materialized = self.inner.snapshot()
        self._materialized_offset = self.log.offset
        if truncate_upto is not None:
            self.log.truncate(min(truncate_upto, self._materialized_offset))

    def restore(self, checkpoint: dict,
                descriptors: Optional[Dict[str, StateDescriptor]] = None) -> None:
        if checkpoint["materialized"] is not None:
            self.inner.restore(checkpoint["materialized"], descriptors)
        replay = FsStateChangelog(checkpoint["log_dir"]) if checkpoint["log_dir"] != self.log.dir else self.log
        # only entries within (materialized_offset, log_offset] belong
        # here — selected BY SEQUENCE, not by position: entries appended
        # after the restored checkpoint (a failed attempt's orphans) sit
        # interleaved in the segments, and a positional slice would replay
        # the wrong set. The dead timeline is then trimmed so a FUTURE
        # checkpoint's offset range can never cover orphan sequences.
        entries = [e for _s, e in replay.read_entries(
            checkpoint["materialized_offset"], checkpoint["log_offset"])]
        if replay is self.log:
            self._pending_trim = checkpoint["log_offset"]
        for op, key, name, namespace, value in entries:
            self.inner.set_current_key(key)
            if op == "put":
                self.inner.put(name, value, namespace)
            elif op == "add":
                self.inner.add(name, value, namespace)
            else:
                self.inner.clear(name, namespace)
        # adopt the restored state as this backend's baseline so the next
        # checkpoint()/restore cycle describes it (not an empty log). With
        # a pending trim the baseline offset is the RESTORED offset — the
        # log's current max seq still counts the dead timeline's orphans
        # until the first mutation cuts them
        self._materialized = self.inner.snapshot()
        self._materialized_offset = (checkpoint["log_offset"]
                                     if self._pending_trim is not None
                                     else self.log.offset)
