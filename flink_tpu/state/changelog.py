"""Changelog state backend: journal every mutation, materialize periodically.

Analogue of the reference's changelog backend + DSTL (S5:
flink-statebackend-changelog/.../ChangelogKeyedStateBackend.java:114,
flink-dstl/.../FsStateChangelogStorage.java:57): wraps any keyed backend,
appends each state mutation to a durable segment log so a checkpoint is
just (last materialized snapshot handle, log offset) — near-instant —
while a background-ish `materialize()` folds the log into a fresh full
snapshot and truncates.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.state.heap import HeapKeyedStateBackend, StateDescriptor


class FsStateChangelog:
    """Append-only mutation log in segment files (DSTL-dfs analogue)."""

    def __init__(self, directory: Optional[str] = None, segment_bytes: int = 1 << 20):
        self.dir = directory or tempfile.mkdtemp(prefix="flink_tpu_dstl_")
        os.makedirs(self.dir, exist_ok=True)
        self.segment_bytes = segment_bytes
        self._seg_id = 0
        self._seg_file = None
        self._offset = 0  # global sequence number of appended entries
        # opening an existing log directory resumes numbering after the last
        # entry (a fresh writer must never collide with surviving segments)
        for seg in sorted(os.listdir(self.dir)):
            if not seg.startswith("seg-"):
                continue
            self._seg_id = max(self._seg_id, int(seg[4:12]) + 1)
            with open(os.path.join(self.dir, seg), "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    seq, _ = pickle.loads(f.read(int.from_bytes(hdr, "big")))
                    self._offset = max(self._offset, seq)

    def _segment_path(self, seg_id: int) -> str:
        return os.path.join(self.dir, f"seg-{seg_id:08d}.log")

    def append(self, entry: tuple) -> int:
        if self._seg_file is None:
            self._seg_file = open(self._segment_path(self._seg_id), "ab")
        self._offset += 1
        # entries carry their absolute sequence number so truncated logs
        # keep a stable numbering
        data = pickle.dumps((self._offset, entry), protocol=pickle.HIGHEST_PROTOCOL)
        self._seg_file.write(len(data).to_bytes(4, "big") + data)
        self._seg_file.flush()
        if self._seg_file.tell() >= self.segment_bytes:
            self._seg_file.close()
            self._seg_file = None
            self._seg_id += 1
        return self._offset

    def read_from(self, from_offset: int) -> List[tuple]:
        """All entries with sequence > from_offset (1-based)."""
        out: List[Tuple[int, tuple]] = []
        for seg in sorted(os.listdir(self.dir)):
            if not seg.startswith("seg-"):
                continue
            with open(os.path.join(self.dir, seg), "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    seq, entry = pickle.loads(f.read(int.from_bytes(hdr, "big")))
                    if seq > from_offset:
                        out.append((seq, entry))
        out.sort(key=lambda p: p[0])
        return [e for _, e in out]

    def truncate(self, upto_offset: int) -> None:
        """Drop whole segments fully covered by `upto_offset` (best-effort,
        like DSTL truncation after materialization)."""
        for seg in sorted(os.listdir(self.dir)):
            if not seg.startswith("seg-"):
                continue
            path = os.path.join(self.dir, seg)
            max_seq = 0
            with open(path, "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    seq, _ = pickle.loads(f.read(int.from_bytes(hdr, "big")))
                    max_seq = max(max_seq, seq)
            if max_seq <= upto_offset and self._segment_path(self._seg_id) != path:
                os.unlink(path)

    @property
    def offset(self) -> int:
        return self._offset


class ChangelogKeyedStateBackend:
    """Heap backend wrapper journaling mutations; checkpoints are O(1)."""

    def __init__(self, inner: HeapKeyedStateBackend,
                 changelog: Optional[FsStateChangelog] = None):
        self.inner = inner
        self.log = changelog or FsStateChangelog()
        self._materialized: Optional[dict] = None
        self._materialized_offset = 0

    # -- delegated reads ----------------------------------------------------
    def set_current_key(self, key) -> None:
        self.inner.set_current_key(key)

    @property
    def current_key(self):
        return self.inner.current_key

    def register(self, descriptor: StateDescriptor) -> None:
        self.inner.register(descriptor)

    def get(self, name: str, namespace=None):
        return self.inner.get(name, namespace)

    def keys(self, name: str):
        return self.inner.keys(name)

    # -- journaled writes ---------------------------------------------------
    def put(self, name: str, value, namespace=None) -> None:
        self.inner.put(name, value, namespace)
        self.log.append(("put", self.inner.current_key, name, namespace, value))

    def add(self, name: str, value, namespace=None) -> None:
        self.inner.add(name, value, namespace)
        self.log.append(("add", self.inner.current_key, name, namespace, value))

    def clear(self, name: str, namespace=None) -> None:
        self.inner.clear(name, namespace)
        self.log.append(("clear", self.inner.current_key, name, namespace, None))

    # -- checkpointing ------------------------------------------------------
    def checkpoint(self) -> dict:
        """O(1): a reference to the last materialization + the log offset."""
        return {
            "materialized": self._materialized,
            "materialized_offset": self._materialized_offset,
            "log_offset": self.log.offset,
            "log_dir": self.log.dir,
        }

    def materialize(self, truncate_upto: Optional[int] = None) -> None:
        """Fold the journal into a full snapshot. Truncation is driven by
        checkpoint subsumption (the reference truncates DSTL only once no
        retained checkpoint references the range): pass the log offset of
        the oldest checkpoint still retained; entries below min(it, the new
        materialization) are dropped. Default: keep everything."""
        self._materialized = self.inner.snapshot()
        self._materialized_offset = self.log.offset
        if truncate_upto is not None:
            self.log.truncate(min(truncate_upto, self._materialized_offset))

    def restore(self, checkpoint: dict,
                descriptors: Optional[Dict[str, StateDescriptor]] = None) -> None:
        if checkpoint["materialized"] is not None:
            self.inner.restore(checkpoint["materialized"], descriptors)
        replay = FsStateChangelog(checkpoint["log_dir"]) if checkpoint["log_dir"] != self.log.dir else self.log
        # only entries within (materialized_offset, log_offset] belong here
        entries = replay.read_from(checkpoint["materialized_offset"])
        upto = checkpoint["log_offset"] - checkpoint["materialized_offset"]
        for op, key, name, namespace, value in entries[:upto]:
            self.inner.set_current_key(key)
            if op == "put":
                self.inner.put(name, value, namespace)
            elif op == "add":
                self.inner.add(name, value, namespace)
            else:
                self.inner.clear(name, namespace)
        # adopt the restored state as this backend's baseline so the next
        # checkpoint()/restore cycle describes it (not an empty log)
        self._materialized = self.inner.snapshot()
        self._materialized_offset = self.log.offset
