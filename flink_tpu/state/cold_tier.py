"""Cold-key state tier: per-(key, slice) accumulator rows in the native
spill store, for key cardinalities beyond the device's HBM columns.

The role RocksDB/ForSt play in the reference (state larger than memory,
S3/S4: RocksDBKeyedStateBackend / ForStKeyedStateBackend): the hot
`key_capacity` dense ids stay as device columns (state/columnar.py); ids
past it aggregate host-side into the batched C++ LSM
(native/spill_store.cpp via utils/native_bridge.NativeSpillStore).

Layout: store key = s_abs * (1<<32) + cold_kid (absolute slice, so ring
reuse never aliases history); value = one f64 per accumregator field plus
the count. All accesses are batched multi-get/multi-put — the ForSt
batching pattern (ForStGeneralMultiGetOperation.java).

A pure-python dict fallback keeps capability without a compiler.
"""

from __future__ import annotations

import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.ops.aggregators import DeviceAggregator, ONE

_COMBINE = {
    "add": lambda a, b: a + b,
    "min": np.minimum,
    "max": np.maximum,
}

_SLICE_SHIFT = np.uint64(32)


class _PyStoreFallback:
    """dict-backed stand-in with the NativeSpillStore surface."""

    def __init__(self, width: int):
        self.width = width
        self._d: Dict[int, bytes] = {}

    def put_batch(self, keys, values):
        values = np.ascontiguousarray(values).reshape(len(keys), self.width)
        for k, v in zip(keys.tolist(), values):
            self._d[int(k)] = v.tobytes()

    def get_batch(self, keys):
        out = np.zeros((len(keys), self.width), dtype=np.uint8)
        found = np.zeros(len(keys), dtype=bool)
        for i, k in enumerate(keys.tolist()):
            b = self._d.get(int(k))
            if b is not None:
                out[i] = np.frombuffer(b, dtype=np.uint8)
                found[i] = True
        return out, found

    def flush(self):
        return 0

    def compact(self):
        return 0

    def purge_below(self, threshold: int) -> int:
        dead = [k for k in self._d if k < threshold]
        for k in dead:
            del self._d[k]
        return len(dead)

    @property
    def mem_entries(self) -> int:
        return len(self._d)

    def checkpoint(self) -> str:
        import base64
        import pickle

        return "py:" + base64.b64encode(pickle.dumps(self._d)).decode()

    def restore(self, manifest: str) -> None:
        import base64
        import pickle

        self._d = pickle.loads(base64.b64decode(manifest[3:]))  # full replace


class ColdKeyTier:
    """Host/LSM accumulator rows for cold dense key ids."""

    def __init__(self, agg: DeviceAggregator, ring_slices: int,
                 directory: Optional[str] = None,
                 flush_threshold: int = 1 << 18,
                 purge_granularity: Optional[int] = None,
                 gc_retained: int = 4):
        self.agg = agg
        self.S = ring_slices
        self.fields = list(agg.fields)
        self.width = (len(self.fields) + 1) * 8  # f64 per field + count
        self.dir = directory or tempfile.mkdtemp(prefix="flink_tpu_cold_")
        try:
            from flink_tpu.utils.native_bridge import NativeSpillStore

            self.store = NativeSpillStore(self.width, self.dir)
            self.native = True
        except (RuntimeError, OSError):
            self.store = _PyStoreFallback(self.width)
            self.native = False
        self.num_cold_rows_written = 0
        self.num_cold_rows_purged = 0
        # Disk GC window: keep run files reachable from this many recent
        # manifests INCLUDING the in-flight one. Must be >= the checkpoint
        # coordinator's max_retained + 1, or a failed in-flight checkpoint
        # could orphan the oldest retained checkpoint's files.
        self.gc_retained = gc_retained
        self._retained_manifests: list = []
        self._gc_holdoff = 0
        # memtable spills to a sorted run past this size (bounds host RSS)
        self.flush_threshold = flush_threshold
        # retention cuts batch up: purge once the slice frontier has moved
        # this many slices past the last cut (a per-watermark purge would
        # rewrite run files constantly)
        self.purge_granularity = purge_granularity or max(ring_slices // 4, 16)
        self._purged_to_slice: Optional[int] = None

    # ------------------------------------------------------------------
    def _store_keys(self, cold_kid: np.ndarray, s_abs: np.ndarray) -> np.ndarray:
        return (s_abs.astype(np.uint64) << _SLICE_SHIFT) | cold_kid.astype(np.uint64)

    def ingest(self, cold_kid: np.ndarray, s_abs: np.ndarray, vals: np.ndarray) -> None:
        """Aggregate a batch of cold-key records into the store (read-combine
        -write on the unique (key, slice) cells)."""
        if len(cold_kid) == 0:
            return
        skeys = self._store_keys(cold_kid, s_abs)
        uniq, inverse = np.unique(skeys, return_inverse=True)
        rows = np.zeros((len(uniq), len(self.fields) + 1), dtype=np.float64)
        for fi, f in enumerate(self.fields):
            src = np.ones(len(vals)) if f.source == ONE else vals.astype(np.float64)
            if f.scatter == "add":
                np.add.at(rows[:, fi], inverse, src)
            elif f.scatter == "min":
                rows[:, fi] = np.asarray(f.identity, dtype=np.float64)
                np.minimum.at(rows[:, fi], inverse, src)
            else:
                rows[:, fi] = np.asarray(f.identity, dtype=np.float64)
                np.maximum.at(rows[:, fi], inverse, src)
        np.add.at(rows[:, -1], inverse, 1.0)

        old, found = self.store.get_batch(uniq)
        old_rows = old.view(np.float64).reshape(len(uniq), len(self.fields) + 1)
        for fi, f in enumerate(self.fields):
            rows[found, fi] = _COMBINE[f.scatter](rows[found, fi], old_rows[found, fi])
        rows[found, -1] += old_rows[found, -1]
        self.store.put_batch(uniq, rows.view(np.uint8))
        self.num_cold_rows_written += len(uniq)
        if self.store.mem_entries >= self.flush_threshold:
            self.store.flush()

    def fire(self, num_cold: int, slice_range) -> Tuple[np.ndarray, np.ndarray]:
        """Combine a window's slices for every cold key.
        Returns (result[num_cold] of agg.result_dtype, counts[num_cold])."""
        nf = len(self.fields)
        acc = np.tile(
            np.asarray([f.identity for f in self.fields], dtype=np.float64),
            (max(num_cold, 1), 1),
        )
        counts = np.zeros(max(num_cold, 1), dtype=np.float64)
        if num_cold == 0:
            return acc[:0, 0], counts[:0]
        ckids = np.arange(num_cold, dtype=np.uint64)
        for s in slice_range:
            skeys = self._store_keys(ckids, np.full(num_cold, s, dtype=np.int64))
            vals, found = self.store.get_batch(skeys)
            rows = vals.view(np.float64).reshape(num_cold, nf + 1)
            for fi, f in enumerate(self.fields):
                acc[found, fi] = _COMBINE[f.scatter](acc[found, fi], rows[found, fi])
            counts[found] += rows[found, -1]
        fields = {f.name: acc[:, fi].astype(f.dtype) for fi, f in enumerate(self.fields)}
        result = np.asarray(self.agg.extract(fields), dtype=self.agg.result_dtype)
        return result, counts

    def purge_below_slice(self, frontier_slice: int) -> None:
        """Retention cut: rows for slices below `frontier_slice` can never
        fire again (every window containing them has fired and purged) —
        delete them so the store tracks the live window span instead of the
        whole stream history. Cuts are batched by `purge_granularity`."""
        if (self._purged_to_slice is not None
                and frontier_slice - self._purged_to_slice < self.purge_granularity):
            return
        self._purged_to_slice = frontier_slice
        if frontier_slice <= 0:
            return
        threshold = int(np.uint64(frontier_slice) << _SLICE_SHIFT)
        self.num_cold_rows_purged += self.store.purge_below(threshold)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        manifest = self.store.checkpoint()
        if self.native:
            # disk GC: files outside the retained-manifest window and the
            # live run list are unreachable (every run a future restore can
            # name lives in one of these manifests)
            self._retained_manifests.append(manifest)
            if len(self._retained_manifests) > self.gc_retained:
                self._retained_manifests = \
                    self._retained_manifests[-self.gc_retained:]
            if self._gc_holdoff > 0:
                self._gc_holdoff -= 1
            else:
                try:
                    self.store.gc(self._retained_manifests)
                except OSError:
                    pass  # GC is best-effort; correctness is unaffected
        return {"manifest": manifest, "dir": self.dir,
                "native": self.native, "purged_to_slice": self._purged_to_slice}

    def restore(self, snap: dict) -> None:
        self.store.restore(snap["manifest"])
        # A fresh instance must not GC away files that checkpoints from
        # BEFORE the restore still reference (the coordinator may retain
        # several older than the one restored, and their manifests are not
        # knowable here): hold GC off until gc_retained NEW checkpoints have
        # completed, by which time the retention window has provably rolled
        # past every pre-restore checkpoint.
        self._retained_manifests = [snap["manifest"]]
        self._gc_holdoff = self.gc_retained
        self._purged_to_slice = snap.get("purged_to_slice")

    def compact(self) -> None:
        self.store.compact()
