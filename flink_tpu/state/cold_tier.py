"""Cold-key state tier: per-(key, slice) accumulator rows in the native
spill store, for key cardinalities beyond the device's HBM columns.

The role RocksDB/ForSt play in the reference (state larger than memory,
S3/S4: RocksDBKeyedStateBackend / ForStKeyedStateBackend): the hot
`key_capacity` dense ids stay as device columns (state/columnar.py); ids
past it aggregate host-side into the batched C++ LSM
(native/spill_store.cpp via utils/native_bridge.NativeSpillStore).

Layout: store key = s_abs * (1<<32) + cold_kid (absolute slice, so ring
reuse never aliases history); value = one f64 per accumregator field plus
the count. All accesses are batched multi-get/multi-put — the ForSt
batching pattern (ForStGeneralMultiGetOperation.java).

A pure-python dict fallback keeps capability without a compiler.
"""

from __future__ import annotations

import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.chaos import plan as _chaos
from flink_tpu.ops.aggregators import DeviceAggregator, ONE

_COMBINE = {
    "add": lambda a, b: a + b,
    "min": np.minimum,
    "max": np.maximum,
}

_SLICE_SHIFT = np.uint64(32)


class ColdTierError(RuntimeError):
    """A cold-tier artifact is missing or unreadable (corrupt manifest,
    store-kind mismatch, decode failure). Typed so restore paths can treat
    a broken cold artifact like a corrupt checkpoint — skip back to an
    older complete one — instead of dying on a raw pickle/base64 error."""


class _PyStoreFallback:
    """dict-backed stand-in with the NativeSpillStore surface."""

    def __init__(self, width: int):
        self.width = width
        self._d: Dict[int, bytes] = {}

    def put_batch(self, keys, values):
        values = np.ascontiguousarray(values).reshape(len(keys), self.width)
        for k, v in zip(keys.tolist(), values):
            self._d[int(k)] = v.tobytes()

    def get_batch(self, keys):
        out = np.zeros((len(keys), self.width), dtype=np.uint8)
        found = np.zeros(len(keys), dtype=bool)
        for i, k in enumerate(keys.tolist()):
            b = self._d.get(int(k))
            if b is not None:
                out[i] = np.frombuffer(b, dtype=np.uint8)
                found[i] = True
        return out, found

    def flush(self):
        return 0

    def compact(self):
        return 0

    def purge_below(self, threshold: int) -> int:
        dead = [k for k in self._d if k < threshold]
        for k in dead:
            del self._d[k]
        return len(dead)

    @property
    def mem_entries(self) -> int:
        return len(self._d)

    def checkpoint(self) -> str:
        import base64
        import pickle

        return "py:" + base64.b64encode(pickle.dumps(self._d)).decode()

    def restore(self, manifest: str) -> None:
        import base64
        import pickle

        if not isinstance(manifest, str) or not manifest.startswith("py:"):
            raise ColdTierError(
                "cold-tier manifest is not a python-store artifact (was "
                "this snapshot taken by the native LSM store?)")
        try:
            self._d = pickle.loads(base64.b64decode(manifest[3:]))
        except Exception as e:  # noqa: BLE001 — surface as a typed artifact
            raise ColdTierError(
                f"corrupt cold-tier manifest: {e!r}") from e


class ColdKeyTier:
    """Host/LSM accumulator rows for cold dense key ids."""

    def __init__(self, agg: DeviceAggregator, ring_slices: int,
                 directory: Optional[str] = None,
                 flush_threshold: int = 1 << 18,
                 purge_granularity: Optional[int] = None,
                 gc_retained: int = 4):
        self.agg = agg
        self.S = ring_slices
        self.fields = list(agg.fields)
        self.width = (len(self.fields) + 1) * 8  # f64 per field + count
        self.dir = directory or tempfile.mkdtemp(prefix="flink_tpu_cold_")
        try:
            from flink_tpu.utils.native_bridge import NativeSpillStore

            self.store = NativeSpillStore(self.width, self.dir)
            self.native = True
        except (RuntimeError, OSError):
            self.store = _PyStoreFallback(self.width)
            self.native = False
        self.num_cold_rows_written = 0
        self.num_cold_rows_purged = 0
        # Disk GC window: keep run files reachable from this many recent
        # manifests INCLUDING the in-flight one. Must be >= the checkpoint
        # coordinator's max_retained + 1, or a failed in-flight checkpoint
        # could orphan the oldest retained checkpoint's files.
        self.gc_retained = gc_retained
        self._retained_manifests: list = []
        self._gc_holdoff = 0
        # memtable spills to a sorted run past this size (bounds host RSS)
        self.flush_threshold = flush_threshold
        # retention cuts batch up: purge once the slice frontier has moved
        # this many slices past the last cut (a per-watermark purge would
        # rewrite run files constantly)
        self.purge_granularity = purge_granularity or max(ring_slices // 4, 16)
        self._purged_to_slice: Optional[int] = None

    # ------------------------------------------------------------------
    def _store_keys(self, cold_kid: np.ndarray, s_abs: np.ndarray) -> np.ndarray:
        return (s_abs.astype(np.uint64) << _SLICE_SHIFT) | cold_kid.astype(np.uint64)

    def ingest(self, cold_kid: np.ndarray, s_abs: np.ndarray, vals: np.ndarray) -> None:
        """Aggregate a batch of cold-key records into the store (read-combine
        -write on the unique (key, slice) cells)."""
        if len(cold_kid) == 0:
            return
        skeys = self._store_keys(cold_kid, s_abs)
        uniq, inverse = np.unique(skeys, return_inverse=True)
        rows = np.zeros((len(uniq), len(self.fields) + 1), dtype=np.float64)
        for fi, f in enumerate(self.fields):
            src = np.ones(len(vals)) if f.source == ONE else vals.astype(np.float64)
            if f.scatter == "add":
                np.add.at(rows[:, fi], inverse, src)
            elif f.scatter == "min":
                rows[:, fi] = np.asarray(f.identity, dtype=np.float64)
                np.minimum.at(rows[:, fi], inverse, src)
            else:
                rows[:, fi] = np.asarray(f.identity, dtype=np.float64)
                np.maximum.at(rows[:, fi], inverse, src)
        np.add.at(rows[:, -1], inverse, 1.0)

        old, found = self.store.get_batch(uniq)
        old_rows = old.view(np.float64).reshape(len(uniq), len(self.fields) + 1)
        for fi, f in enumerate(self.fields):
            rows[found, fi] = _COMBINE[f.scatter](rows[found, fi], old_rows[found, fi])
        rows[found, -1] += old_rows[found, -1]
        self.store.put_batch(uniq, rows.view(np.uint8))
        self.num_cold_rows_written += len(uniq)
        if self.store.mem_entries >= self.flush_threshold:
            self.store.flush()

    def fire(self, num_cold: int, slice_range) -> Tuple[np.ndarray, np.ndarray]:
        """Combine a window's slices for every cold key.
        Returns (result[num_cold] of agg.result_dtype, counts[num_cold])."""
        nf = len(self.fields)
        acc = np.tile(
            np.asarray([f.identity for f in self.fields], dtype=np.float64),
            (max(num_cold, 1), 1),
        )
        counts = np.zeros(max(num_cold, 1), dtype=np.float64)
        if num_cold == 0:
            return acc[:0, 0], counts[:0]
        ckids = np.arange(num_cold, dtype=np.uint64)
        for s in slice_range:
            skeys = self._store_keys(ckids, np.full(num_cold, s, dtype=np.int64))
            vals, found = self.store.get_batch(skeys)
            rows = vals.view(np.float64).reshape(num_cold, nf + 1)
            for fi, f in enumerate(self.fields):
                acc[found, fi] = _COMBINE[f.scatter](acc[found, fi], rows[found, fi])
            counts[found] += rows[found, -1]
        fields = {f.name: acc[:, fi].astype(f.dtype) for fi, f in enumerate(self.fields)}
        result = np.asarray(self.agg.extract(fields), dtype=self.agg.result_dtype)
        return result, counts

    # -- tier-manager surface (state/tier_manager.py) -------------------
    def _guarded_get(self, skeys: np.ndarray, site: str):
        """All promotion/fire reads go through here: the chaos plane's
        storage seam covers the cold tier (site ``cold-tier:<what>``), so
        a scenario can model a lost/corrupt cold artifact mid-promotion;
        one is-None check when chaos is off."""
        hook = _chaos.HOOK
        if hook is not None:
            hook("storage", f"cold-tier:{site}")
        try:
            return self.store.get_batch(skeys)
        except ColdTierError:
            raise
        except Exception as e:  # noqa: BLE001 — typed artifact error
            raise ColdTierError(f"cold-tier read failed: {e!r}") from e

    def absorb_rows(self, cold_kid: np.ndarray, s_abs: np.ndarray,
                    rows: np.ndarray, counts: np.ndarray) -> None:
        """Merge PRE-AGGREGATED per-(key, slice) rows into the store —
        the demotion path: a hot HBM row's live cells move here when its
        key is evicted. `rows` is [m, num_fields] in field order, `counts`
        [m]; cells already present combine by each field's scatter op
        (so demote-then-reingest and plain ingest are order-independent)."""
        if len(cold_kid) == 0:
            return
        skeys = self._store_keys(np.asarray(cold_kid, np.uint64),
                                 np.asarray(s_abs))
        uniq, inverse = np.unique(skeys, return_inverse=True)
        nf = len(self.fields)
        merged = np.zeros((len(uniq), nf + 1), dtype=np.float64)
        for fi, f in enumerate(self.fields):
            if f.scatter != "add":
                merged[:, fi] = np.asarray(f.identity, dtype=np.float64)
            getattr(np, {"add": "add", "min": "minimum", "max": "maximum"}
                    [f.scatter]).at(merged[:, fi], inverse,
                                    np.asarray(rows[:, fi], np.float64))
        np.add.at(merged[:, -1], inverse, np.asarray(counts, np.float64))
        old, found = self.store.get_batch(uniq)
        old_rows = old.view(np.float64).reshape(len(uniq), nf + 1)
        for fi, f in enumerate(self.fields):
            merged[found, fi] = _COMBINE[f.scatter](
                merged[found, fi], old_rows[found, fi])
        merged[found, -1] += old_rows[found, -1]
        self.store.put_batch(uniq, merged.view(np.uint8))
        self.num_cold_rows_written += len(uniq)
        if self.store.mem_entries >= self.flush_threshold:
            self.store.flush()

    def read_rows(self, cold_kid: int, slices: np.ndarray):
        """Promotion read: one cold key's rows at `slices` (absolute).
        Returns (rows [m, num_fields] f64, counts [m] f64, found [m]
        bool). Raises :class:`ColdTierError` on an unreadable artifact."""
        slices = np.asarray(slices, dtype=np.int64)
        nf = len(self.fields)
        if slices.size == 0:
            return (np.zeros((0, nf), np.float64), np.zeros(0, np.float64),
                    np.zeros(0, bool))
        skeys = self._store_keys(
            np.full(slices.size, cold_kid, np.uint64), slices)
        vals, found = self._guarded_get(skeys, "get")
        rows = vals.view(np.float64).reshape(slices.size, nf + 1)
        return rows[:, :nf].copy(), rows[:, -1].copy(), found

    def clear_rows(self, cold_kid: int, slices: np.ndarray) -> None:
        """Promotion cut: overwrite one cold key's rows at `slices` with
        identity/zero-count rows (the store has no point delete; a
        zero-count row reads as absent everywhere)."""
        slices = np.asarray(slices, dtype=np.int64)
        if slices.size == 0:
            return
        skeys = self._store_keys(
            np.full(slices.size, cold_kid, np.uint64), slices)
        nf = len(self.fields)
        rows = np.tile(
            np.asarray([f.identity for f in self.fields] + [0.0],
                       dtype=np.float64),
            (slices.size, 1))
        self.store.put_batch(skeys, rows.view(np.uint8))

    def fire_ids(self, cold_kids: np.ndarray, slice_range):
        """Window fire over an EXPLICIT cold-id set (the tier manager's
        touched-slice index bounds it): combine the window's slices for
        those ids only — O(touched x slices-per-window), never O(all cold
        keys). Returns (fields {name: [m]}, counts [m] f64)."""
        cold_kids = np.asarray(cold_kids, dtype=np.uint64)
        m = len(cold_kids)
        nf = len(self.fields)
        acc = np.tile(
            np.asarray([f.identity for f in self.fields], dtype=np.float64),
            (max(m, 1), 1))
        counts = np.zeros(max(m, 1), dtype=np.float64)
        if m == 0:
            return {f.name: acc[:0, fi] for fi, f in enumerate(self.fields)}, \
                counts[:0]
        for s in slice_range:
            skeys = self._store_keys(
                cold_kids, np.full(m, s, dtype=np.int64))
            vals, found = self._guarded_get(skeys, "fire")
            rows = vals.view(np.float64).reshape(m, nf + 1)
            for fi, f in enumerate(self.fields):
                acc[found, fi] = _COMBINE[f.scatter](
                    acc[found, fi], rows[found, fi])
            counts[found] += rows[found, -1]
        fields = {f.name: acc[:, fi].astype(f.dtype)
                  for fi, f in enumerate(self.fields)}
        return fields, counts

    def approx_bytes(self) -> int:
        """Resident cold-store footprint estimate (memtable rows x row
        width) — the spilledBytes gauge's source. Spilled run files on
        disk are deliberately not walked per gauge read."""
        return int(self.store.mem_entries) * self.width

    def purge_below_slice(self, frontier_slice: int) -> None:
        """Retention cut: rows for slices below `frontier_slice` can never
        fire again (every window containing them has fired and purged) —
        delete them so the store tracks the live window span instead of the
        whole stream history. Cuts are batched by `purge_granularity`."""
        if (self._purged_to_slice is not None
                and frontier_slice - self._purged_to_slice < self.purge_granularity):
            return
        self._purged_to_slice = frontier_slice
        if frontier_slice <= 0:
            return
        threshold = int(np.uint64(frontier_slice) << _SLICE_SHIFT)
        self.num_cold_rows_purged += self.store.purge_below(threshold)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        manifest = self.store.checkpoint()
        if self.native:
            # disk GC: files outside the retained-manifest window and the
            # live run list are unreachable (every run a future restore can
            # name lives in one of these manifests)
            self._retained_manifests.append(manifest)
            if len(self._retained_manifests) > self.gc_retained:
                self._retained_manifests = \
                    self._retained_manifests[-self.gc_retained:]
            if self._gc_holdoff > 0:
                self._gc_holdoff -= 1
            else:
                try:
                    self.store.gc(self._retained_manifests)
                except OSError:
                    pass  # GC is best-effort; correctness is unaffected
        return {"manifest": manifest, "dir": self.dir,
                "native": self.native, "purged_to_slice": self._purged_to_slice}

    def restore(self, snap: dict) -> None:
        # adopt the snapshot's store kind + directory: a rebuilt operator
        # (restart with an unconfigured cold-dir) gets a fresh temp dir,
        # but a NATIVE manifest names run files relative to the dir the
        # snapshot was taken over — restoring it anywhere else reads
        # nothing. The py manifest is self-contained; a py snapshot into a
        # native instance downgrades to the py store (correct either way).
        if snap.get("native"):
            if not self.native:
                raise ColdTierError(
                    "cold-tier snapshot was taken by the native LSM store "
                    "but this build has no native bridge")
            if snap.get("dir") and snap["dir"] != self.dir:
                from flink_tpu.utils.native_bridge import NativeSpillStore

                self.dir = snap["dir"]
                self.store = NativeSpillStore(self.width, self.dir)
        elif self.native:
            self.store = _PyStoreFallback(self.width)
            self.native = False
        try:
            self.store.restore(snap["manifest"])
        except ColdTierError:
            raise
        except Exception as e:  # noqa: BLE001 — typed artifact error
            raise ColdTierError(
                f"cold-tier restore failed: {e!r}") from e
        # A fresh instance must not GC away files that checkpoints from
        # BEFORE the restore still reference (the coordinator may retain
        # several older than the one restored, and their manifests are not
        # knowable here): hold GC off until gc_retained NEW checkpoints have
        # completed, by which time the retention window has provably rolled
        # past every pre-restore checkpoint.
        self._retained_manifests = [snap["manifest"]]
        self._gc_holdoff = self.gc_retained
        self._purged_to_slice = snap.get("purged_to_slice")

    def compact(self) -> None:
        self.store.compact()
