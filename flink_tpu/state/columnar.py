"""Columnar device state: HBM-resident per-(key, slice) accumulator columns.

The `JaxColumnarStateBackend` sibling of the reference's HeapKeyedStateBackend
(HeapKeyedStateBackend.java:85): instead of a hash map of (key, window) →
accumulator objects mutated per record (CopyOnWriteStateMap.java:108), state
is a dict of dense [K, S] device arrays — K = distinct-key capacity, S =
slice-ring capacity — plus a host-side key dictionary mapping raw keys to
dense row ids and a slice ring that reuses columns as windows expire.

Snapshots pull the arrays to host (device→host is the step-aligned barrier,
SURVEY.md §7 stage 5) together with the key dictionary and ring frontiers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from flink_tpu.ops.aggregators import DeviceAggregator
from flink_tpu.ops import segment_ops


class KeyDictionary:
    """Raw key -> dense row id. `dense_int` mode skips the dict entirely for
    pre-densified integer keys (sources that emit key ids, e.g. benchmark
    generators and the keyBy shuffle's re-densified output).

    Batches of int64 or string keys take the native C++ open-addressing path
    (native/flink_tpu_native.cpp KeyDict via utils/native_bridge) — the host
    equivalent of the reference's native state-store key handling; arbitrary
    Python keys fall back to a dict loop."""

    def __init__(self, dense_int: bool = False):
        self.dense_int = dense_int
        self._map: Dict[Any, int] = {}
        self._keys: List[Any] = []
        self._native = None
        self._native_mode: str = ""  # '', 'i64', 'bytes', 'off' (fallback)

    def __len__(self) -> int:
        return len(self._keys)

    @property
    def num_ids(self) -> int:
        return len(self._keys)

    # -- native fast paths -------------------------------------------------
    def _try_native(self, keys: np.ndarray):
        """Returns (ids, size) via the C++ dict, or None to fall back."""
        if self._native_mode == "off":
            return None
        from flink_tpu.utils import native_bridge

        as_i64 = as_bytes = None
        if keys.dtype != object and np.issubdtype(keys.dtype, np.integer):
            mode, as_i64 = "i64", np.ascontiguousarray(keys, dtype=np.int64)
        else:
            try:
                as_bytes = np.asarray(keys, dtype=np.bytes_)
                mode = "bytes"
            except (TypeError, UnicodeEncodeError, ValueError):
                return None
        if self._native_mode and self._native_mode != mode:
            return None  # mixed key types: stay on the generic path
        if self._native is None:
            if native_bridge.get_lib() is None:
                self._native_mode = "off"
                return None
            self._native = native_bridge.NativeKeyDict(string_mode=(mode == "bytes"))
            self._native_mode = mode
            if self._keys:  # restore path: re-seed the table in id order
                if mode == "i64":
                    self._native.lookup_or_insert_i64(
                        np.asarray(self._keys, dtype=np.int64)
                    )
                else:
                    seed = np.asarray(self._keys, dtype=np.bytes_)
                    self._bytes_width = max(((seed.dtype.itemsize + 7) // 8) * 8, 24)
                    self._native.lookup_or_insert_bytes(
                        seed.astype(f"S{self._bytes_width}")
                    )
        if mode == "i64":
            ids, new, size = self._native.lookup_or_insert_i64(as_i64)
        else:
            # fixed-width byte keys: one width for the dictionary's lifetime
            # (padding with NULs is consistent; a longer key than the chosen
            # width cannot be represented -> permanent fallback)
            if not hasattr(self, "_bytes_width"):
                self._bytes_width = max(((as_bytes.dtype.itemsize + 7) // 8) * 8, 24)
            if as_bytes.dtype.itemsize > self._bytes_width:
                self._native_mode = "off"
                self._native = None
                return None
            as_bytes = as_bytes.astype(f"S{self._bytes_width}")
            ids, new, size = self._native.lookup_or_insert_bytes(as_bytes)
        if new.any():
            # tolist(): plain Python scalars, not np.int64/np.str_ (user-facing)
            self._keys.extend(keys[new].tolist())
        return ids, size

    def lookup_or_insert(self, keys: np.ndarray) -> Tuple[np.ndarray, int]:
        """Map a batch of raw keys to dense ids, inserting unseen keys.
        Returns (ids int32[B], required_capacity)."""
        if self.dense_int:
            ids = keys.astype(np.int32)
            hi = int(ids.max()) + 1 if ids.size else 0
            if hi > len(self._keys):
                self._keys.extend(range(len(self._keys), hi))
            return ids, len(self._keys)
        native = self._try_native(keys)
        if native is not None:
            return native[0], native[1]
        m = self._map
        if not m and self._keys:  # fell back after native use: rebuild map
            m = self._map = {k: i for i, k in enumerate(self._keys)}
        out = np.empty(len(keys), dtype=np.int32)
        for i, k in enumerate(keys):
            kid = m.get(k)
            if kid is None:
                kid = len(self._keys)
                m[k] = kid
                self._keys.append(k)
            out[i] = kid
        return out, len(self._keys)

    def key_at(self, kid: int):
        return self._keys[kid]

    def lookup(self, key) -> Optional[int]:
        """Dense id for a key, or None if never seen (read-only)."""
        if self.dense_int:
            k = int(key)
            return k if 0 <= k < len(self._keys) else None
        kid = self._map.get(key)
        if kid is None and self._keys and not self._map:
            # native-dict mode keeps _map empty; fall back to a scan
            try:
                kid = self._keys.index(key)
            except ValueError:
                kid = None
        return kid

    def keys_for(self, kids: np.ndarray) -> List:
        ks = self._keys
        return [ks[int(i)] for i in kids]

    def snapshot(self) -> dict:
        return {"dense_int": self.dense_int, "keys": list(self._keys)}

    @staticmethod
    def restore(snap: dict) -> "KeyDictionary":
        d = KeyDictionary(snap["dense_int"])
        d._keys = list(snap["keys"])
        d._map = {} if d.dense_int else {k: i for i, k in enumerate(d._keys)}
        return d


@dataclasses.dataclass
class RingFrontiers:
    """Host-tracked slice-ring accounting (all in absolute slice indices)."""

    purged_to: int = None      # slices < purged_to are recycled  # type: ignore[assignment]
    min_used: int = None       # smallest slice ever written       # type: ignore[assignment]
    max_used: int = None       # largest slice ever written        # type: ignore[assignment]


class ColumnarWindowState:
    """Device arrays + key dictionary + ring accounting for one shard."""

    PURGE_CHUNK = 8

    def __init__(
        self,
        agg: DeviceAggregator,
        *,
        key_capacity: int = 1 << 12,
        num_slices: int = 64,
        dense_int_keys: bool = False,
        device=None,
        ingest_kernel: str = "scatter",
    ):
        self.agg = agg
        self.K = key_capacity
        self.S = num_slices
        self.device = device
        self.keydict = KeyDictionary(dense_int_keys)
        self.frontiers = RingFrontiers()
        self.acc, self.count = segment_ops.init_state_arrays(agg, self.K, self.S)
        if ingest_kernel == "sort":
            from flink_tpu.ops.sorted_ingest import make_sorted_ingest_fn

            self._ingest = make_sorted_ingest_fn(agg, track_touch=True)
        else:
            self._ingest = segment_ops.make_ingest_fn(agg, track_touch=True)
        self._fire = segment_ops.make_fire_fn(agg, masked=False)
        self._fire_masked = segment_ops.make_fire_fn(agg, masked=True)
        self._purge = segment_ops.make_purge_fn(agg, self.PURGE_CHUNK)
        self.last_touch = None  # bool[K,S] from the most recent ingest

    # ------------------------------------------------------------------
    def ensure_key_capacity(self, required: int) -> None:
        if required <= self.K:
            return
        new_k = self.K
        while new_k < required:
            new_k *= 2
        self.acc, self.count = segment_ops.grow_keys(self.acc, self.count, self.agg, new_k)
        if self.last_touch is not None:
            import jax.numpy as jnp
            pad = jnp.zeros((new_k - self.K, self.S), dtype=self.last_touch.dtype)
            self.last_touch = jnp.concatenate([self.last_touch, pad], axis=0)
        self.K = new_k

    def ring_pos(self, slices: np.ndarray) -> np.ndarray:
        return (slices % self.S).astype(np.int32)

    def ingest(self, kid: np.ndarray, slices_abs: np.ndarray, vals: np.ndarray) -> None:
        """Scatter a prepared batch into the columns.
        kid == INVALID_INDEX lanes are dropped."""
        f = self.frontiers
        valid = kid != segment_ops.INVALID_INDEX
        live = slices_abs[valid]
        if live.size:
            lo, hi = int(live.min()), int(live.max())
            f.min_used = lo if f.min_used is None else min(f.min_used, lo)
            f.max_used = hi if f.max_used is None else max(f.max_used, hi)
        spos = np.where(valid, slices_abs % self.S, segment_ops.INVALID_INDEX).astype(np.int32)
        self.acc, self.count, self.last_touch = self._ingest(
            self.acc, self.count, kid.astype(np.int32), spos, vals
        )

    def fire(self, slice_range: range, *, touch_mask: bool = False):
        """Combine the window's slices; returns (result, counts, mask) device arrays."""
        positions = np.asarray([s % self.S for s in slice_range], dtype=np.int32)
        if touch_mask:
            return self._fire_masked(self.acc, self.count, positions, self.last_touch)
        return self._fire(self.acc, self.count, positions)

    def purge_slices(self, slices_abs: List[int]) -> None:
        """Reset columns of expired absolute slices (chunked)."""
        for i in range(0, len(slices_abs), self.PURGE_CHUNK):
            chunk = slices_abs[i : i + self.PURGE_CHUNK]
            positions = np.full(self.PURGE_CHUNK, segment_ops.INVALID_INDEX, dtype=np.int32)
            positions[: len(chunk)] = [s % self.S for s in chunk]
            self.acc, self.count = self._purge(self.acc, self.count, positions)

    def reset_all(self) -> None:
        self.acc, self.count = segment_ops.init_state_arrays(self.agg, self.K, self.S)
        self.last_touch = None

    def state_bytes(self) -> int:
        """HBM footprint of the resident device arrays (observability
        gauge; key-dictionary host memory not included)."""
        n = sum(int(getattr(a, "nbytes", 0)) for a in self.acc.values())
        n += int(getattr(self.count, "nbytes", 0))
        if self.last_touch is not None:
            n += int(getattr(self.last_touch, "nbytes", 0))
        return n

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "acc": {k: np.asarray(v) for k, v in self.acc.items()},
            "count": np.asarray(self.count),
            "keydict": self.keydict.snapshot(),
            "frontiers": dataclasses.asdict(self.frontiers),
            "K": self.K,
            "S": self.S,
        }

    def restore(self, snap: dict) -> None:
        import jax.numpy as jnp

        self.K = snap["K"]
        self.S = snap["S"]
        self.acc = {k: jnp.asarray(v) for k, v in snap["acc"].items()}
        self.count = jnp.asarray(snap["count"])
        self.keydict = KeyDictionary.restore(snap["keydict"])
        self.frontiers = RingFrontiers(**snap["frontiers"])
        self.last_touch = None
