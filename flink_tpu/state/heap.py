"""Heap keyed-state backend: the CPU/oracle state store.

Capability parity with HeapKeyedStateBackend
(flink-runtime .../state/heap/HeapKeyedStateBackend.java:85): named states of
kind Value/List/Map/Reducing/Aggregating, scoped by (current key, current
namespace), organized per key group for snapshot/rescale. Namespaces are the
window objects, exactly as in the reference (state per key×window).

Snapshots are deep-ish copies of the per-key-group tables (the reference uses
copy-on-write state maps for async snapshots, CopyOnWriteStateMap.java:108;
here snapshot cost is dominated by the device state anyway, and the heap
backend is the small/oracle path, so a plain copy keeps it simple and
correct).
"""

from __future__ import annotations

import copy
import dataclasses
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from flink_tpu.core.functions import AggregateFunction, ReduceFunction, as_reduce_function
from flink_tpu.core.keygroups import KeyGroupRange, assign_to_key_group


@dataclasses.dataclass(frozen=True)
class StateTtlConfig:
    """State time-to-live (TtlStateFactory.java:54 analogue): processing-time
    TTL with NeverReturnExpired visibility. `update_on_read=True` matches
    UpdateType.OnReadAndWrite; default is OnCreateAndWrite. Expired entries
    are invisible immediately, dropped on access, and filtered from
    snapshots (the 'cleanup in full snapshot' strategy)."""

    ttl_ms: int
    update_on_read: bool = False


@dataclasses.dataclass(frozen=True)
class StateDescriptor:
    name: str
    kind: str  # 'value' | 'list' | 'map' | 'reducing' | 'aggregating'
    default: Any = None
    reduce_fn: Optional[ReduceFunction] = None
    agg_fn: Optional[AggregateFunction] = None
    ttl: Optional[StateTtlConfig] = None


def value_state(name: str, default=None, ttl: Optional[StateTtlConfig] = None) -> StateDescriptor:
    return StateDescriptor(name, "value", default, ttl=ttl)


def list_state(name: str, ttl: Optional[StateTtlConfig] = None) -> StateDescriptor:
    return StateDescriptor(name, "list", ttl=ttl)


def map_state(name: str, ttl: Optional[StateTtlConfig] = None) -> StateDescriptor:
    return StateDescriptor(name, "map", ttl=ttl)


def reducing_state(name: str, reduce_fn, ttl: Optional[StateTtlConfig] = None) -> StateDescriptor:
    return StateDescriptor(name, "reducing", reduce_fn=as_reduce_function(reduce_fn), ttl=ttl)


def aggregating_state(name: str, agg_fn: AggregateFunction,
                      ttl: Optional[StateTtlConfig] = None) -> StateDescriptor:
    return StateDescriptor(name, "aggregating", agg_fn=agg_fn, ttl=ttl)


class HeapKeyedStateBackend:
    """State tables: {state_name: {key_group: {(key, namespace): value}}}.

    The (current_key, current_namespace) context is set by the operator
    before state access, mirroring AbstractKeyedStateBackend.setCurrentKey /
    InternalKvState.setCurrentNamespace.
    """

    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int,
                 auto_register: bool = False,
                 clock: Optional[Callable[[], int]] = None):
        self.key_group_range = key_group_range
        self.max_parallelism = max_parallelism
        self.auto_register = auto_register
        # processing-time source for state TTL (injectable for tests)
        self.clock = clock or (lambda: int(time.time() * 1000))
        self._tables: Dict[str, Dict[int, Dict[Tuple, Any]]] = {}
        self._auto_names: set = set()   # auto-registered placeholder states
        self._ttl_ts: Dict[str, Dict[int, Dict[Tuple, int]]] = {}
        self._descriptors: Dict[str, StateDescriptor] = {}
        self._current_key: Any = None
        self._current_key_group: int = -1

    # -- context ----------------------------------------------------------
    def set_current_key(self, key) -> None:
        self._current_key = key
        self._current_key_group = assign_to_key_group(key, self.max_parallelism)

    @property
    def current_key(self):
        return self._current_key

    def register(self, descriptor: StateDescriptor) -> None:
        existing = self._descriptors.get(descriptor.name)
        if existing is not None and descriptor.name in self._auto_names:
            # an explicit descriptor supersedes an auto-registered
            # placeholder (get()-before-register with auto_register=True),
            # so late TTL/kind declarations are honored, not discarded
            self._descriptors[descriptor.name] = descriptor
            self._auto_names.discard(descriptor.name)
        else:
            self._descriptors.setdefault(descriptor.name, descriptor)
        self._tables.setdefault(descriptor.name, {})
        if descriptor.ttl is not None:
            # a TTL descriptor registered over already-restored entries (the
            # auto_register/late-registration path) must stamp them now, or
            # they would never expire
            now = self.clock()
            for kg, entries in self._tables[descriptor.name].items():
                stamps = self._ttl_ts.setdefault(descriptor.name, {}).setdefault(kg, {})
                for k in entries:
                    stamps.setdefault(k, now)

    # -- access (key from context, namespace explicit) --------------------
    def _slot(self, name: str) -> Dict[Tuple, Any]:
        table = self._tables.get(name)
        if table is None:
            if not self.auto_register:
                raise KeyError(
                    f"state {name!r} not registered (register a descriptor "
                    "first, or construct the backend with auto_register=True)"
                )
            # dynamic registration: ProcessFunctions may declare state at
            # first use (getState(descriptor) mid-stream in the reference);
            # mark it auto so an explicit register() can supersede it
            self.register(value_state(name))
            self._auto_names.add(name)
            table = self._tables[name]
        return table.setdefault(self._current_key_group, {})

    def _ttl_slot(self, name: str) -> Dict[Tuple, int]:
        return self._ttl_ts.setdefault(name, {}).setdefault(
            self._current_key_group, {})

    def _ttl_live(self, name: str, desc: StateDescriptor, k: Tuple) -> bool:
        """NeverReturnExpired: drop + report dead when past the TTL."""
        ts = self._ttl_slot(name).get(k)
        if ts is not None and self.clock() - ts > desc.ttl.ttl_ms:
            self._slot(name).pop(k, None)
            self._ttl_slot(name).pop(k, None)
            return False
        return True

    def get(self, name: str, namespace=None):
        slot = self._slot(name)  # may dynamically register (auto_register)
        desc = self._descriptors[name]
        k = (self._current_key, namespace)
        val = slot.get(k, _MISSING)
        if val is not _MISSING and desc.ttl is not None:
            if not self._ttl_live(name, desc, k):
                val = _MISSING
            elif desc.ttl.update_on_read:
                self._ttl_slot(name)[k] = self.clock()
        if val is _MISSING:
            return copy.copy(desc.default) if desc.kind == "value" else None
        return val

    def put(self, name: str, value, namespace=None) -> None:
        k = (self._current_key, namespace)
        self._slot(name)[k] = value
        if self._descriptors[name].ttl is not None:
            self._ttl_slot(name)[k] = self.clock()

    def add(self, name: str, value, namespace=None) -> None:
        """Reducing/Aggregating/List add (HeapAggregatingState.add:94)."""
        if name not in self._descriptors and self.auto_register:
            # dynamic first-use via add() implies append semantics
            self.register(list_state(name))
            self._auto_names.add(name)
        desc = self._descriptors[name]
        slot = self._slot(name)
        k = (self._current_key, namespace)
        if desc.ttl is not None:
            self._ttl_live(name, desc, k)  # evicts an expired accumulator
        cur = slot.get(k, _MISSING)
        if desc.ttl is not None:
            self._ttl_slot(name)[k] = self.clock()
        if desc.kind == "list":
            if cur is _MISSING:
                slot[k] = [value]
            else:
                cur.append(value)
        elif desc.kind == "reducing":
            slot[k] = value if cur is _MISSING else desc.reduce_fn.reduce(cur, value)
        elif desc.kind == "aggregating":
            acc = desc.agg_fn.create_accumulator() if cur is _MISSING else cur
            slot[k] = desc.agg_fn.add(value, acc)
        else:
            raise TypeError(f"add() not supported for state kind {desc.kind}")

    def clear(self, name: str, namespace=None) -> None:
        self._slot(name).pop((self._current_key, namespace), None)
        if self._descriptors.get(name) is not None and \
                self._descriptors[name].ttl is not None:
            self._ttl_slot(name).pop((self._current_key, namespace), None)

    def merge_namespaces(self, name: str, target, sources: Iterable) -> None:
        """Merge state of `sources` namespaces into `target` for the current
        key (used by session-window merge; InternalMergingState)."""
        desc = self._descriptors[name]
        slot = self._slot(name)
        ttl_slot = self._ttl_slot(name) if desc.ttl is not None else None
        merged = slot.pop((self._current_key, target), _MISSING)
        for ns in sources:
            v = slot.pop((self._current_key, ns), _MISSING)
            if ttl_slot is not None:
                ttl_slot.pop((self._current_key, ns), None)
            if v is _MISSING:
                continue
            if merged is _MISSING:
                merged = v
            elif desc.kind == "list":
                merged = merged + v
            elif desc.kind == "reducing":
                merged = desc.reduce_fn.reduce(merged, v)
            elif desc.kind == "aggregating":
                merged = desc.agg_fn.merge(merged, v)
            else:
                raise TypeError(f"merge not supported for kind {desc.kind}")
        if merged is not _MISSING:
            slot[(self._current_key, target)] = merged
            if ttl_slot is not None:
                ttl_slot[(self._current_key, target)] = self.clock()

    # -- introspection / snapshot ----------------------------------------
    def namespaces_for_key(self, name: str, key) -> List:
        kg = assign_to_key_group(key, self.max_parallelism)
        table = self._tables.get(name, {}).get(kg, {})
        return [ns for (k, ns) in table.keys() if k == key]

    def keys(self, name: str) -> List:
        out = set()
        for kg_table in self._tables.get(name, {}).values():
            out.update(k for (k, _ns) in kg_table.keys())
        return list(out)

    def is_empty(self) -> bool:
        return all(not kg for t in self._tables.values() for kg in t.values())

    def snapshot(self) -> Dict:
        """Per-key-group snapshot: {state_name: {kg: {(key, ns): value}}}.
        Expired TTL entries are filtered out (the reference's
        'cleanup in full snapshot' strategy); surviving entries restore with
        a fresh TTL stamp."""
        now = self.clock()
        out = {}
        for name, table in self._tables.items():
            desc = self._descriptors.get(name)
            if desc is None or desc.ttl is None:
                out[name] = copy.deepcopy(table)
                continue
            ttl = desc.ttl.ttl_ms
            out[name] = {
                kg: {
                    k: copy.deepcopy(v)
                    for k, v in entries.items()
                    if now - self._ttl_ts.get(name, {}).get(kg, {}).get(k, now) <= ttl
                }
                for kg, entries in table.items()
            }
        return out

    def restore(self, snap: Dict, descriptors: Optional[Dict[str, StateDescriptor]] = None) -> None:
        if descriptors:
            for d in descriptors.values():
                self.register(d)
        # keep only key groups in our range (rescale-aware restore, S1)
        self._tables = {
            name: {
                kg: dict(entries)
                for kg, entries in table.items()
                if self.key_group_range.contains(kg)
            }
            for name, table in copy.deepcopy(snap).items()
        }
        for name in self._descriptors:
            self._tables.setdefault(name, {})
        # restored TTL entries restart their clock at restore time
        now = self.clock()
        self._ttl_ts = {}
        for name, desc in self._descriptors.items():
            if desc.ttl is None:
                continue
            for kg, entries in self._tables.get(name, {}).items():
                self._ttl_ts.setdefault(name, {})[kg] = {
                    k: now for k in entries
                }

    @property
    def descriptors(self) -> Dict[str, StateDescriptor]:
        return dict(self._descriptors)


_MISSING = object()
