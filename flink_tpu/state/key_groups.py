"""Key-group remapping for rescaling: merge, split and ownership checks.

Key groups are the unit of state redistribution when a keyed job changes
parallelism (reference: StateAssignmentOperation.java:64 — state handles
are re-grouped by KeyGroupRange when the new execution graph deploys).
This module holds the runtime-independent half of that operation so both
the failover rescale-down path and the autoscaler's deliberate rescale
share ONE implementation:

- `ranges_for_parallelism` — the ownership map old/new attempts slice by;
- `merge_keyed_state` / `merge_timers` — fold per-shard heap-table
  snapshots into one logical-state view (disjoint by key group by
  construction, so a plain union is exact);
- `filter_timers_for_range` — the restore-side split: keep only timers
  whose key falls in the restoring subtask's range (heap state tables
  filter themselves by range in state/heap.py restore);
- `reshardable` — device-operator snapshots re-shard inside the sharded
  device state, not via heap-table merge; callers must probe before
  committing to a rescale.

The invariant the property tests pin: for ANY (max_parallelism, old_p,
new_p), every key group is owned by exactly one subtask before and after
the remap — no state lost, none duplicated.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.core.keygroups import (
    KeyGroupRange,
    assign_to_key_group,
    key_group_range_for_operator,
    operator_index_for_key_group,
)


def ranges_for_parallelism(max_parallelism: int, parallelism: int) -> List[KeyGroupRange]:
    """Per-subtask key-group ownership at the given parallelism."""
    return [
        key_group_range_for_operator(max_parallelism, parallelism, i)
        for i in range(parallelism)
    ]


def owner_of_key_group(max_parallelism: int, parallelism: int, key_group: int) -> int:
    """Subtask index owning `key_group` (range-membership form of
    operator_index_for_key_group — the two must always agree, which the
    property tests assert)."""
    return operator_index_for_key_group(max_parallelism, parallelism, key_group)


def verify_partition(max_parallelism: int, parallelism: int) -> None:
    """Assert the ownership ranges partition [0, max_parallelism): every
    key group in exactly one range, and range membership agrees with
    operator_index_for_key_group. Raises AssertionError otherwise."""
    ranges = ranges_for_parallelism(max_parallelism, parallelism)
    owners: Dict[int, int] = {}
    for idx, rng in enumerate(ranges):
        for kg in rng:
            assert kg not in owners, (
                f"key group {kg} owned by both subtask {owners[kg]} and "
                f"{idx} (max={max_parallelism}, p={parallelism})")
            owners[kg] = idx
            assert owner_of_key_group(max_parallelism, parallelism, kg) == idx
    assert len(owners) == max_parallelism, (
        f"{max_parallelism - len(owners)} key groups unowned "
        f"(max={max_parallelism}, p={parallelism})")


def reshardable(handles: Dict[int, dict]) -> Tuple[bool, str]:
    """Whether a per-shard snapshot set can be re-sharded by heap-table
    merge. Device-operator snapshots (columnar state / fused-count rings)
    re-shard inside the sharded device state instead."""
    for shard in sorted(handles):
        op = handles[shard].get("operator", {})
        if "columnar" in op or "cnt" in op or "pipe" in op \
                or "tier" in op or "tier_changelog" in op:
            # "pipe" = fused-superscan ring state; "tier"/"tier_changelog"
            # = the million-key state plane's full/incremental snapshots —
            # all device-resident (or device-referencing) forms that the
            # heap-table merge cannot re-shard
            return False, (
                "device-operator snapshots re-shard by key group inside "
                "the sharded device state, not via heap-table merge; "
                "rescaling device jobs is not supported yet")
    return True, ""


def merge_keyed_state(per_shard_state: List[Dict[str, Dict[int, dict]]]) -> Dict[str, Dict[int, dict]]:
    """Union per-shard heap state tables ({name: {key_group: {key: val}}}).
    Shards own disjoint key-group ranges, so the union is exact (the
    StateAssignmentOperation merge half)."""
    merged: Dict[str, Dict[int, dict]] = {}
    for tables in per_shard_state:
        for name, table in tables.items():
            dst = merged.setdefault(name, {})
            for kg, entries in table.items():
                dst.setdefault(kg, {}).update(entries)
    return merged


def merge_timers(per_shard_timers: List[Optional[dict]]) -> dict:
    """Concatenate per-shard timer snapshots ({event, proc, watermark});
    the combined watermark is the MIN over shards (what every shard has
    reached)."""
    merged: dict = {"event": [], "proc": [], "watermark": None}
    for t in per_shard_timers:
        if t is None:
            continue
        merged["event"].extend(t.get("event", []))
        merged["proc"].extend(t.get("proc", []))
        wm = t.get("watermark")
        cur = merged["watermark"]
        if wm is not None:
            merged["watermark"] = wm if cur is None else min(cur, wm)
    return merged


def split_merged_snapshot(merged: dict, max_parallelism: int,
                          parallelism: int) -> Dict[int, dict]:
    """JM-side split of a merged logical snapshot: each new subtask ships
    only its own KeyGroupRange slice of state and timers (the collect-sink
    results ride with shard 0). Shipping the full merged state to every
    shard and letting restore-side filtering discard the rest would
    serialize ~parallelism copies of the whole job state over the deploy
    RPCs — exactly the rescale cost the autoscaler treats as the price of
    acting. The restore-side filters still run; on a pre-split slice they
    are no-ops."""
    op = merged.get("operator", {})
    state = op.get("state", {})
    timers = op.get("timers") or {"event": [], "proc": [], "watermark": None}
    out: Dict[int, dict] = {}
    ranges = ranges_for_parallelism(max_parallelism, parallelism)
    for shard, rng in enumerate(ranges):
        shard_state = {
            name: {kg: entries for kg, entries in table.items()
                   if rng.contains(kg)}
            for name, table in state.items()
        }
        out[shard] = {
            **merged,
            "operator": {
                "state": shard_state,
                "timers": filter_timers_for_range(timers, rng,
                                                  max_parallelism),
            },
            "results": list(merged.get("results", [])) if shard == 0 else [],
        }
    return out


def filter_timers_for_range(timers: dict, kg_range: KeyGroupRange,
                            max_parallelism: int) -> dict:
    """Restore-side split of a merged timer snapshot: keep only timers
    whose key's key group falls in this subtask's range. Timer entries are
    (time, key, ...) tuples — key at index 1."""

    def mine(entries: List[Any]) -> List[Any]:
        return [e for e in entries
                if kg_range.contains(assign_to_key_group(e[1], max_parallelism))]

    return {
        "event": mine(timers.get("event", [])),
        "proc": mine(timers.get("proc", [])),
        "watermark": timers.get("watermark"),
    }
