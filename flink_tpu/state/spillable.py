"""Spillable heap backend: cold key-groups move to disk under pressure.

Analogue of flink-statebackend-heap-spillable (S6): wraps the heap backend
(whose tables are already key-group organized,
state/heap.py `_tables[name][key_group][(key, ns)]` — mirroring the
reference's per-key-group StateTables), tracks key-group heat, and when the
in-memory entry count exceeds the budget, pickles the coldest key-groups to
disk; touching a spilled key-group faults it back in transparently.
Key-group granularity matches the rescaling unit (KeyGroupRange, S1), so
spilled units remain valid snapshot/restore units.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional

from flink_tpu.state.heap import HeapKeyedStateBackend, StateDescriptor


class SpillReadError(RuntimeError):
    """A spilled key-group artifact is missing or unreadable. Typed (vs a
    raw FileNotFoundError/UnpicklingError) so callers can distinguish
    "the spill tier lost data" from an ordinary state-access bug and take
    the restore-from-checkpoint path."""


class SpillableKeyedStateBackend:
    """Heap backend + key-group spill tier."""

    def __init__(
        self,
        inner: HeapKeyedStateBackend,
        *,
        max_entries_in_memory: int = 100_000,
        spill_dir: Optional[str] = None,
    ):
        self.inner = inner
        self.max_entries = max_entries_in_memory
        self.dir = spill_dir or tempfile.mkdtemp(prefix="flink_tpu_spill_kg_")
        os.makedirs(self.dir, exist_ok=True)
        self._heat: Dict[int, float] = {}           # key_group -> last access
        self._spilled: Dict[int, str] = {}          # key_group -> file
        self.num_spills = 0
        self.num_faults = 0
        # writes since the last exact count: the exact scan is O(state), so
        # it only runs after enough writes could have crossed the budget
        self._writes_since_check = 0
        self._entries_at_check = 0

    # -- context ------------------------------------------------------------
    def set_current_key(self, key) -> None:
        self.inner.set_current_key(key)
        kg = self.inner._current_key_group
        self._fault_in(kg)
        self._heat[kg] = time.monotonic()
        self._maybe_spill()

    @property
    def current_key(self):
        return self.inner.current_key

    # -- delegation ----------------------------------------------------------
    def register(self, descriptor: StateDescriptor) -> None:
        self.inner.register(descriptor)

    def get(self, name: str, namespace=None):
        return self.inner.get(name, namespace)

    def put(self, name: str, value, namespace=None) -> None:
        self.inner.put(name, value, namespace)
        self._writes_since_check += 1

    def add(self, name: str, value, namespace=None) -> None:
        self.inner.add(name, value, namespace)
        self._writes_since_check += 1

    def clear(self, name: str, namespace=None) -> None:
        self.inner.clear(name, namespace)

    def merge_namespaces(self, name: str, target, sources) -> None:
        self.inner.merge_namespaces(name, target, sources)

    def keys(self, name: str) -> List:
        self._fault_all()
        return self.inner.keys(name)

    def __getattr__(self, item):
        return getattr(self.inner, item)

    # -- spilling -------------------------------------------------------------
    def _mem_entries(self) -> int:
        return sum(
            len(slot)
            for table in self.inner._tables.values()
            for slot in table.values()
        )

    def _maybe_spill(self) -> None:
        if self._entries_at_check + self._writes_since_check <= self.max_entries:
            return  # cannot have crossed the budget yet: skip the exact scan
        self._entries_at_check = self._mem_entries()
        self._writes_since_check = 0
        if self._entries_at_check <= self.max_entries:
            return
        current_kg = self.inner._current_key_group
        live_kgs = {
            kg
            for table in self.inner._tables.values()
            for kg, slot in table.items()
            if slot
        }
        for kg in sorted(live_kgs, key=lambda g: self._heat.get(g, 0.0)):  # coldest first
            if kg == current_kg:
                continue
            if self._mem_entries() <= self.max_entries:
                break
            payload = {
                name: table.pop(kg)
                for name, table in self.inner._tables.items()
                if kg in table
            }
            path = os.path.join(self.dir, f"kg-{kg}.spill")
            with open(path, "wb") as f:
                pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
            self._spilled[kg] = path
            self.num_spills += 1
        self._entries_at_check = self._mem_entries()

    def _fault_in(self, kg: int) -> None:
        path = self._spilled.pop(kg, None)
        if path is None:
            return
        try:
            with open(path, "rb") as f:
                payload = pickle.load(f)
        except Exception as e:  # noqa: BLE001 — unpickling garbage raises
            # anything (UnpicklingError, EOFError, ValueError, ...);
            # re-raised typed below, never swallowed
            # the artifact stays registered so a retry/restore sees the
            # same state it faulted on (popping it would silently turn a
            # lost key-group into an empty one)
            self._spilled[kg] = path
            raise SpillReadError(
                f"spilled key-group {kg} unreadable at {path}: {e!r}"
            ) from e
        for name, slot in payload.items():
            self.inner._tables.setdefault(name, {})[kg] = slot
            self._entries_at_check += len(slot)
        os.unlink(path)
        self.num_faults += 1

    def _fault_all(self) -> None:
        for kg in list(self._spilled):
            self._fault_in(kg)

    # -- snapshot -------------------------------------------------------------
    def snapshot(self) -> Dict:
        self._fault_all()
        return self.inner.snapshot()

    def restore(self, snap: Dict,
                descriptors: Optional[Dict[str, StateDescriptor]] = None) -> None:
        self._spilled.clear()
        self._heat.clear()
        self.inner.restore(snap, descriptors)

    def is_empty(self) -> bool:
        return not self._spilled and self.inner.is_empty()
