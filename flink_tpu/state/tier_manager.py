"""Tiered state manager: hot HBM rows, host/disk cold rows, changelog
checkpoints.

The state plane of ROADMAP item 2 ("million-key state"): the fused window
operator keeps the HOT working set as dense [K, S] HBM ring columns (the
existing layout) and this manager owns everything beyond it —

- the **dynamic key vocabulary** (state/vocab.py) decides which keys are
  resident; this manager executes its decisions: a demotion gathers the
  victim's live device row into the cold tier (state/cold_tier.py) and
  clears it; a promotion moves a re-admitted key's cold rows back into its
  fresh device row.
- **cold ingest/fire**: records routed cold aggregate straight into the
  cold store under (cold_id, absolute slice); a per-slice TOUCHED index
  bounds window fires to the cold ids that actually hold data in the fired
  range (never O(all cold keys)).
- **incremental checkpoints**: every structural mutation (cold absorbs,
  promotion clears, vocabulary ops) journals into a
  :class:`~flink_tpu.state.changelog.FsStateChangelog`; at checkpoint time
  ONE ``cells`` entry captures the interval-touched device cells plus the
  pipeline/normalizer meta, so a checkpoint handle is (base file, log
  offset) and its cost scales with the per-interval delta, not the full
  [K, S] state. A periodic materialization folds the log into a fresh
  base. Restore reconstructs the CANONICAL full snapshot host-side (pure
  numpy replay over the base arrays), so a checkpoint taken on one mesh
  size restores on any other exactly like a full snapshot would (the
  PR-10 canonical-form contract).

Layering: state sits below the runtime (ARCH001). The manager never
imports the runtime — the operator hands in its device accessors
(gather/clear/write row callables) via :meth:`attach_device`, the same
outward-callback pattern the checkpoint layer uses.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import tempfile
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.ops.aggregators import DeviceAggregator, ONE
from flink_tpu.state.changelog import FsStateChangelog
from flink_tpu.state.cold_tier import ColdKeyTier, ColdTierError
from flink_tpu.state.vocab import DynamicKeyVocabulary, RoutedBatch

#: snapshot-dict marker of an incremental (changelog) checkpoint handle
CHANGELOG_HANDLE_KIND = "flink-tpu-changelog-v1"


@dataclasses.dataclass
class TierConfig:
    """Construction-time knobs (state.tier.* / state.changelog.*)."""

    hot_key_capacity: int = 1 << 13
    eviction_policy: str = "lru"
    admission_min_count: int = 1
    cold_dir: Optional[str] = None
    changelog_enabled: bool = False
    changelog_dir: Optional[str] = None
    materialize_interval: int = 8
    retained_bases: int = 4


class TieredStateManager:
    """One keyed window operator's hot/cold placement + changelog."""

    def __init__(self, agg: DeviceAggregator, ring_slices: int,
                 config: Optional[TierConfig] = None):
        self.cfg = config or TierConfig()
        self.agg = agg
        self.S = int(ring_slices)
        self.vocab = DynamicKeyVocabulary(
            self.cfg.hot_key_capacity,
            policy=self.cfg.eviction_policy,
            admission_min_count=self.cfg.admission_min_count)
        self.cold = ColdKeyTier(agg, ring_slices,
                                directory=self.cfg.cold_dir)
        self._fields = list(agg.fields)   # store column order == agg order
        # per-absolute-slice touched cold ids (bounds cold fires; sets —
        # promotion membership checks run per (slice, cold id) on the
        # batch path, so scans over per-batch array chunks would go
        # quadratic under churn)
        self._touched: Dict[int, set] = {}
        # device accessors, wired by the operator (attach_device)
        self._gather_rows: Optional[Callable] = None
        self._clear_rows: Optional[Callable] = None
        self._write_cells: Optional[Callable] = None
        self.num_demoted_rows = 0
        self.num_promoted_rows = 0
        self.num_cold_records = 0
        # incremental checkpointing
        self.log: Optional[FsStateChangelog] = None
        self._dirty: List[Tuple[np.ndarray, np.ndarray]] = []  # (kid, s_abs)
        self._bases: List[Tuple[int, str]] = []   # (offset, base file)
        self._cp_since_base = 0
        self.changelog_bytes_last_interval = 0
        #: per-checkpoint-interval appended bytes (bounded ring): the
        #: bench's incremental-vs-full measurement reads the median
        self.interval_bytes_history: List[int] = []
        self._bytes_mark = 0
        # post-restore log-truncation holdoff (the ColdKeyTier gc_holdoff
        # pattern): checkpoints retained from BEFORE the restore may
        # reference older bases/offsets this instance cannot know
        self._truncate_holdoff = 0
        if self.cfg.changelog_enabled:
            d = self.cfg.changelog_dir or tempfile.mkdtemp(
                prefix="flink_tpu_changelog_")
            os.makedirs(d, exist_ok=True)
            self.log = FsStateChangelog(d)

    # ------------------------------------------------------------------
    # device accessors (runtime hands them in; state never imports runtime)
    # ------------------------------------------------------------------
    def attach_device(self, gather_rows: Callable, clear_rows: Callable,
                      write_cells: Callable) -> None:
        """gather_rows(kids)->(counts[m,S], {field:[m,S]}) numpy;
        clear_rows(kids) resets rows to identity; write_cells(kids, spos,
        counts, {field: vals}) sets individual ring cells."""
        self._gather_rows = gather_rows
        self._clear_rows = clear_rows
        self._write_cells = write_cells

    # ------------------------------------------------------------------
    # routing + movement
    # ------------------------------------------------------------------
    def route(self, keys: np.ndarray, s_abs: np.ndarray,
              vals: Optional[np.ndarray], late: np.ndarray) -> RoutedBatch:
        """Vocabulary routing + cold ingest of the batch's cold records.
        `late` rows route with id -1 and are NOT cold-ingested (the fused
        pipeline drops and counts them — tiering must not resurrect
        them)."""
        routed = self.vocab.observe_batch(keys)
        ids = routed.ids
        cold_rows = (ids < 0) & ~late
        if late.any():
            ids = np.where(late, np.int32(-1), ids)
            routed.ids = ids
        if cold_rows.any():
            ckids = routed.cold_ids[cold_rows]
            cs = np.asarray(s_abs)[cold_rows]
            cvals = (np.asarray(vals, np.float32)[cold_rows]
                     if vals is not None
                     else np.zeros(int(cold_rows.sum()), np.float32))
            self.cold.ingest(ckids.astype(np.int64), cs, cvals)
            self.num_cold_records += len(ckids)
            self._note_touched(ckids, cs)
            # narrow the journaled id column when it fits (the common
            # case by ~2^32): replay casts back up
            ck = (ckids.astype(np.int32)
                  if ckids.max(initial=0) < 2 ** 31 else ckids)
            self._journal(("cold_ingest", ck, cs, cvals))
        return routed

    def _note_touched(self, ckids: np.ndarray, s_abs: np.ndarray) -> None:
        for s in np.unique(s_abs):
            self._touched.setdefault(int(s), set()).update(
                int(c) for c in ckids[s_abs == s])

    def note_hot_cells(self, kids: np.ndarray, s_abs: np.ndarray) -> None:
        """Record device cells written this checkpoint interval (the
        changelog delta gathers exactly these at checkpoint time)."""
        if self.log is None or len(kids) == 0:
            return
        self._dirty.append((np.asarray(kids, np.int64).copy(),
                            np.asarray(s_abs, np.int64).copy()))

    def apply_demotions(self, demotions: List[Tuple[Any, int, int]],
                        live_lo: Optional[int],
                        live_hi: Optional[int]) -> None:
        """Move each victim's live device row into the cold tier and clear
        it. Caller guarantees no buffered/in-flight step still references
        the victim ids (the operator flushes first)."""
        if not demotions or live_lo is None or live_hi is None \
                or self._gather_rows is None:
            if demotions and self._clear_rows is not None:
                # no live span yet: rows are identity — just recycle
                self._clear_rows(np.asarray([d[1] for d in demotions],
                                            np.int64))
            return
        kids = np.asarray([d[1] for d in demotions], np.int64)
        counts, fields = self._gather_rows(kids)
        span = np.arange(live_lo, live_hi + 1, dtype=np.int64)
        spos = (span % self.S).astype(np.int64)
        nf = len(self._fields)
        all_ckids, all_slices = [], []
        all_rows, all_counts = [], []
        for i, (_key, _hid, cid) in enumerate(demotions):
            c = np.asarray(counts[i])[spos]
            live = np.flatnonzero(c > 0)
            if live.size == 0:
                continue
            rows = np.zeros((live.size, nf), np.float64)
            for fi, f in enumerate(self._fields):
                if f.source == ONE:
                    rows[:, fi] = c[live]
                else:
                    rows[:, fi] = np.asarray(fields[f.name][i])[spos][live]
            all_ckids.append(np.full(live.size, cid, np.int64))
            all_slices.append(span[live])
            all_rows.append(rows)
            all_counts.append(c[live].astype(np.float64))
        if all_ckids:
            ckids = np.concatenate(all_ckids)
            slices = np.concatenate(all_slices)
            rows = np.concatenate(all_rows)
            cc = np.concatenate(all_counts)
            self.cold.absorb_rows(ckids, slices, rows, cc)
            self._note_touched(ckids, slices)
            self.num_demoted_rows += len(ckids)
            self._journal(("cold_absorb", ckids, slices, rows, cc))
        self._clear_rows(kids)
        # the cleared LIVE cells' checkpoint-time values are identity —
        # they must land in the delta or a restore resurrects the demoted
        # rows on the device AND in the cold store (cells that never held
        # data need no note: identity before, identity after)
        if self.log is not None and all_ckids:
            for i, (_key, hid, _cid) in enumerate(demotions):
                c = np.asarray(counts[i])[spos]
                live = np.flatnonzero(c > 0)
                if live.size:
                    self.note_hot_cells(
                        np.full(live.size, hid, np.int64), span[live])

    def apply_promotions(self, promotions: List[Tuple[Any, int, int]],
                         live_lo: Optional[int], live_hi: Optional[int],
                         ring_limit: Optional[int]
                         ) -> Optional[Tuple[int, int]]:
        """Move each re-admitted key's cold rows (within the live,
        ring-safe span) into its fresh device row. Cold rows beyond
        `ring_limit` stay cold (the emission merge covers the split).
        Returns the (smin, smax) span written to the device, or None.
        Raises :class:`ColdTierError` when the cold artifact is
        unreadable — promotion must fail loudly, never admit a key with
        silently missing history."""
        if not promotions or live_lo is None or live_hi is None \
                or self._write_cells is None:
            return None
        hi = live_hi if ring_limit is None else min(live_hi, ring_limit - 1)
        if hi < live_lo:
            return None
        span = np.arange(live_lo, hi + 1, dtype=np.int64)
        w_kids, w_spos, w_counts = [], [], []
        w_fields: Dict[str, list] = {f.name: [] for f in self._fields}
        cleared: List[Tuple[int, np.ndarray]] = []
        smin = smax = None
        for _key, hid, cid in promotions:
            # only slices this cold id actually touched are read
            touched = [s for s in span if self._has_touched(int(s), cid)]
            if not touched:
                continue
            t = np.asarray(touched, np.int64)
            rows, cc, found = self.cold.read_rows(int(cid), t)
            live = np.flatnonzero(found & (cc > 0))
            if live.size == 0:
                continue
            w_kids.append(np.full(live.size, hid, np.int64))
            w_spos.append((t[live] % self.S).astype(np.int64))
            w_counts.append(cc[live])
            for fi, f in enumerate(self._fields):
                w_fields[f.name].append(rows[live, fi])
            cleared.append((int(cid), t[live]))
            lo_i, hi_i = int(t[live].min()), int(t[live].max())
            smin = lo_i if smin is None else min(smin, lo_i)
            smax = hi_i if smax is None else max(smax, hi_i)
        if not w_kids:
            return None
        kids = np.concatenate(w_kids)
        spos = np.concatenate(w_spos)
        counts = np.concatenate(w_counts).astype(np.int64)
        fields = {name: np.concatenate(chunks)
                  for name, chunks in w_fields.items() if chunks}
        self._write_cells(kids, spos, counts, fields)
        self.num_promoted_rows += len(kids)
        abs_slices = np.concatenate([t for _cid, t in cleared])
        for cid, t in cleared:
            self.cold.clear_rows(cid, t)
        self._journal(("cold_clear", [(cid, t) for cid, t in cleared]))
        # promoted device cells belong to this interval's delta, and the
        # absolute slice is recoverable from (spos, span) only here
        if self.log is not None:
            self.note_hot_cells(kids, abs_slices)
        return smin, smax

    def _has_touched(self, s: int, cid: int) -> bool:
        ids = self._touched.get(s)
        return ids is not None and cid in ids

    # ------------------------------------------------------------------
    # fires + retention
    # ------------------------------------------------------------------
    def cold_fire(self, slice_range) -> Optional[
            Tuple[np.ndarray, Dict[str, np.ndarray], np.ndarray]]:
        """Cold contributions to one fired window: (cold_ids, fields,
        counts) over the touched ids in the range, or None."""
        union: set = set()
        for s in slice_range:
            union.update(self._touched.get(int(s), ()))
        if not union:
            return None
        ids = np.asarray(sorted(union), dtype=np.int64)
        # ids promoted/cleared since touch still resolve: their rows read
        # back as zero-count and fall out below
        fields, counts = self.cold.fire_ids(ids, slice_range)
        live = np.flatnonzero(counts > 0)
        if live.size == 0:
            return None
        return (ids[live],
                {n: v[live] for n, v in fields.items()},
                counts[live])

    def purge_below(self, frontier_slice: Optional[int]) -> None:
        if frontier_slice is None:
            return
        self.cold.purge_below_slice(int(frontier_slice))
        for s in [s for s in self._touched if s < frontier_slice]:
            del self._touched[s]

    # ------------------------------------------------------------------
    # gauges
    # ------------------------------------------------------------------
    def gauges(self) -> Dict[str, Any]:
        v = self.vocab
        return {
            "vocabSize": v.vocab_size,
            "residentKeys": v.resident_count,
            "evictions": v.num_evictions,
            "promotions": v.num_promotions,
            "spilledBytes": self.cold.approx_bytes(),
            "changelogBytes": self.changelog_bytes_last_interval,
            "tierHotFillRatio": round(
                v.resident_count / max(v.capacity, 1), 4),
        }

    def payload(self) -> Dict[str, Any]:
        """/jobs/:id/device tier block (JSON-safe)."""
        p = dict(self.gauges())
        p.update({
            "hotKeyCapacity": self.vocab.capacity,
            "evictionPolicy": self.vocab.policy,
            "admissionMinCount": self.vocab.admission_min_count,
            "coldRecords": self.num_cold_records,
            "demotedRows": self.num_demoted_rows,
            "promotedRows": self.num_promoted_rows,
            "changelogEnabled": self.log is not None,
        })
        return p

    # ------------------------------------------------------------------
    # checkpointing
    # ------------------------------------------------------------------
    def _journal(self, entry: tuple) -> None:
        if self.log is not None:
            self.log.append(entry)

    def _dirty_cells(self) -> Tuple[np.ndarray, np.ndarray]:
        if not self._dirty:
            return np.zeros(0, np.int64), np.zeros(0, np.int64)
        kid = np.concatenate([k for k, _ in self._dirty])
        s = np.concatenate([s for _, s in self._dirty])
        packed = np.unique(np.stack([kid, s], axis=1), axis=0)
        return packed[:, 0], packed[:, 1]

    def full_snapshot(self) -> dict:
        """The tier's own full (materialization-grade) state."""
        return {
            "vocab": self.vocab.snapshot(),
            "cold": self.cold.snapshot(),
            "touched": {int(s): sorted(ids)
                        for s, ids in self._touched.items() if ids},
            "counters": [self.num_demoted_rows, self.num_promoted_rows,
                         self.num_cold_records],
        }

    def restore_full(self, snap: dict) -> None:
        self.vocab = DynamicKeyVocabulary.restore(snap["vocab"])
        self.cold.restore(snap["cold"])
        self._touched = {int(s): set(int(i) for i in ids)
                         for s, ids in snap["touched"].items()}
        (self.num_demoted_rows, self.num_promoted_rows,
         self.num_cold_records) = snap["counters"]
        self._dirty = []

    def checkpoint(self, pipe_meta: dict, gather_cells: Callable,
                   full_pipe_snapshot: Callable[[], dict]) -> dict:
        """Incremental checkpoint: append ONE `cells` entry (the
        interval-touched device cells at their current values + the
        pipeline/operator meta) and return a (base, offset) handle.
        Every `materialize_interval` checkpoints the full state folds
        into a fresh base file and the log truncates below the oldest
        retained base."""
        assert self.log is not None, "checkpoint() needs changelog_enabled"
        if not self._bases or self._cp_since_base >= \
                max(self.cfg.materialize_interval, 1):
            self._materialize(full_pipe_snapshot)
        kids, s_abs = self._dirty_cells()
        self._dirty = []
        counts, fields = (gather_cells(kids, (s_abs % self.S))
                          if kids.size else
                          (np.zeros(0, np.int64),
                           {f.name: np.zeros(0) for f in self._fields
                            if f.source != ONE}))
        self.log.append(("cells", {
            # hot ids are < capacity and counts are the int32 ring's —
            # narrow dtypes keep the per-interval delta lean (s_abs stays
            # int64: absolute slice indices are unbounded)
            "kids": kids.astype(np.int32), "s_abs": s_abs,
            "counts": np.asarray(counts).astype(np.int32),
            "fields": {k: np.asarray(v) for k, v in fields.items()},
            "meta": pipe_meta,
        }))
        self._cp_since_base += 1
        self.changelog_bytes_last_interval = \
            self.log.bytes_written - self._bytes_mark
        self._bytes_mark = self.log.bytes_written
        self.interval_bytes_history.append(
            self.changelog_bytes_last_interval)
        if len(self.interval_bytes_history) > 256:
            del self.interval_bytes_history[:-256]
        base_offset, base_file = self._bases[-1]
        return {
            "kind": CHANGELOG_HANDLE_KIND,
            "dir": self.log.dir,
            "base_offset": base_offset,
            "base_file": base_file,
            "log_offset": self.log.offset,
        }

    def _materialize(self, full_pipe_snapshot: Callable[[], dict]) -> None:
        assert self.log is not None
        base = {
            "pipe": full_pipe_snapshot(),
            "tier": self.full_snapshot(),
        }
        offset = self.log.offset
        path = os.path.join(self.log.dir, f"base-{offset:012d}.mat")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(base, f, protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._bases.append((offset, path))
        self._cp_since_base = 0
        retained = max(self.cfg.retained_bases, 1)
        if len(self._bases) > retained:
            for _off, old in self._bases[:-retained]:
                try:
                    os.unlink(old)
                except OSError:
                    pass
            self._bases = self._bases[-retained:]
        # log entries below the OLDEST retained base can never be replayed
        # (every restorable handle references a retained base's offset) —
        # EXCEPT right after a restore, when the coordinator may still
        # retain checkpoints referencing older bases: hold the cut until
        # the retention window has provably rolled past them
        if self._truncate_holdoff > 0:
            self._truncate_holdoff -= 1
        else:
            self.log.truncate(self._bases[0][0])

    def last_base_bytes(self) -> int:
        """On-disk size of the newest materialized base — the FULL-state
        snapshot the per-interval deltas are measured against."""
        if not self._bases:
            return 0
        try:
            return os.path.getsize(self._bases[-1][1])
        except OSError:
            return 0

    def restore_changelog(self, handle: dict) -> dict:
        """Rebuild the canonical full snapshot from (base, log range):
        pure numpy replay — mesh-size independent by construction. The
        manager adopts the handle's log dir (trimmed of any dead
        timeline) and restores its own vocab/cold/touched state; the
        returned dict carries the reconstructed `pipe` snapshot + `meta`
        for the operator to apply."""
        if handle.get("kind") != CHANGELOG_HANDLE_KIND:
            raise ValueError("not a changelog checkpoint handle")
        log = (self.log if self.log is not None
               and self.log.dir == handle["dir"]
               else FsStateChangelog(handle["dir"]))
        try:
            with open(handle["base_file"], "rb") as f:
                base = pickle.load(f)
        except Exception as e:  # noqa: BLE001 — typed artifact error
            raise ColdTierError(
                f"changelog base unreadable: {e!r}") from e
        pipe_snap = base["pipe"]
        state = {k: np.array(v) for k, v in pipe_snap["state"].items()}
        count = np.array(pipe_snap["count"])
        self.restore_full(base["tier"])
        meta: Optional[dict] = None
        purged_to = pipe_snap.get("purged_to")
        entries = log.read_entries(handle["base_offset"],
                                   handle["log_offset"])
        expected = handle["log_offset"] - handle["base_offset"]
        if len(entries) != expected:
            raise ColdTierError(
                f"changelog range ({handle['base_offset']}, "
                f"{handle['log_offset']}] incomplete: "
                f"{len(entries)}/{expected} entries readable")
        identities = {f.name: f.identity for f in self._fields
                      if f.source != ONE}
        S = count.shape[1]
        for _seq, entry in entries:
            kind = entry[0]
            if kind == "cold_ingest":
                _k, ckids, cs, cvals = entry
                self.cold.ingest(ckids, cs, cvals)
                self.num_cold_records += len(ckids)
                self._note_touched(ckids, cs)
            elif kind == "cold_absorb":
                _k, ckids, slices, rows, cc = entry
                self.cold.absorb_rows(ckids, slices, rows, cc)
                self._note_touched(ckids, slices)
                self.num_demoted_rows += len(ckids)
            elif kind == "cold_clear":
                for cid, t in entry[1]:
                    self.cold.clear_rows(cid, t)
            elif kind == "vocab":
                self.vocab.apply_ops(entry[1])
            elif kind == "vocab_cold_batch":
                self.vocab.apply_ops(
                    [("cold", int(k), int(c))
                     for k, c in zip(entry[1], entry[2])])
            elif kind == "cells":
                d = entry[1]
                meta = d["meta"]
                new_p = meta.get("purged_to")
                if new_p is not None and (purged_to is None
                                          or new_p > purged_to):
                    lo = purged_to if purged_to is not None \
                        else new_p - S
                    if new_p - lo >= S:
                        count[:, :] = 0
                        for name, arr in state.items():
                            arr[:, :] = identities[name]
                    else:
                        cols = (np.arange(max(lo, new_p - S), new_p)
                                % S).astype(np.int64)
                        count[:, cols] = 0
                        for name, arr in state.items():
                            arr[:, cols] = identities[name]
                    purged_to = new_p
                kids = np.asarray(d["kids"], np.int64)
                if kids.size:
                    spos = (np.asarray(d["s_abs"], np.int64) % S)
                    count[kids, spos] = np.asarray(d["counts"])
                    for name, vals in d["fields"].items():
                        state[name][kids, spos] = np.asarray(vals)
            else:
                raise ColdTierError(
                    f"unknown changelog entry kind {kind!r}")
        if meta is None:
            raise ColdTierError(
                "changelog range holds no `cells` checkpoint entry")
        # dead-timeline cut, then adopt this log for the new attempt
        log.trim_above(handle["log_offset"])
        self.log = log
        self._bytes_mark = log.bytes_written
        self._bases = [(handle["base_offset"], handle["base_file"])]
        self._cp_since_base = max(self.cfg.materialize_interval, 1)
        self._truncate_holdoff = max(self.cfg.retained_bases, 1)
        self._dirty = []
        full_pipe = dict(pipe_snap)
        full_pipe["state"] = state
        full_pipe["count"] = count
        for k in ("watermark", "fire_cursor", "purged_to",
                  "min_used_slice", "max_seen_slice", "num_late_dropped"):
            if k in meta:
                full_pipe[k] = meta[k]
        return {"pipe": full_pipe, "meta": meta}

    def journal_vocab_ops(self) -> None:
        """Flush the vocabulary's structural-op journal into the log
        (called once per routed batch by the operator, so replay applies
        ops in stream order relative to the cold absorbs). The dominant
        op under high cardinality — ("cold", key, cid), one per NEW key —
        packs columnar when keys are ints (~3x smaller pickled than a
        tuple list); packing them FIRST preserves per-key order, since
        within one batch a key's cold op always precedes its admit/evict/
        promote ops."""
        if self.log is None:
            self.vocab.drain_ops()
            return
        ops = self.vocab.drain_ops()
        if not ops:
            return
        cold_keys: List[int] = []
        cold_cids: List[int] = []
        rest: List[tuple] = []
        for op in ops:
            if op[0] == "cold" and type(op[1]) is int:
                cold_keys.append(op[1])
                cold_cids.append(op[2])
            else:
                rest.append(op)
        if cold_keys:
            self.log.append(("vocab_cold_batch",
                             np.asarray(cold_keys, np.int64),
                             np.asarray(cold_cids, np.int64)))
        if rest:
            self.log.append(("vocab", rest))
