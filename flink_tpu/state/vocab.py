"""Dynamic key vocabulary: unbounded raw keys over a bounded dense-id space.

The grow-only :class:`~flink_tpu.state.columnar.KeyDictionary` couples key
cardinality to HBM key capacity ``K`` — every distinct key ever seen owns a
device row forever, so "millions of users" would need millions of resident
rows. This vocabulary decouples them (ROADMAP item 2, SURVEY §7 hard part 3
"skewed keys / dynamic key vocab"): at most ``capacity`` keys are RESIDENT
(own a dense hot id, i.e. an HBM ring row) at any instant; every other
known key is COLD (owns a cold id addressing rows in the host/disk tier,
state/cold_tier.py). Admission, eviction and id recycling are host-side
policy:

- **admission**: a key's first ``admission_min_count - 1`` sightings while
  the hot tier is full stay cold (tiny-LFU-style doorkeeper — one-touch
  keys in a heavy tail must not churn hot rows); with the default of 1
  every new key is admitted, evicting the coldest resident.
- **eviction**: the victim is the least-recently-used resident
  (``policy="lru"``, frequency as the tiebreak) or the least-frequently
  -used (``policy="lfu"``, recency tiebreak). Keys touched by the batch
  being routed are pinned — the operator is about to write their rows.
- **recycling**: an evicted key's hot id is reused by the admitted key
  (its device row is demoted to the cold tier first, by the caller); a
  promoted key's cold id returns to the cold free list one batch later
  (the demote/promote data movement of the SAME batch must never alias a
  just-freed cold id).

The vocabulary is pure host bookkeeping — it decides WHAT moves between
tiers; the tier manager (state/tier_manager.py) moves the bytes. Every
structural mutation is journaled (``drain_ops``/``apply_ops``) so the
incremental changelog checkpoint replays vocabulary state from per-interval
deltas instead of re-pickling the whole directory.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class RoutedBatch:
    """The routing decision for one batch of raw keys.

    ``ids[i]`` is the record's dense hot id, or -1 when the record stays
    cold; ``cold_ids[i]`` is its cold-tier id (-1 for resident records).
    ``demotions``/``promotions`` are the data movements the caller must
    perform BEFORE dispatching the batch: demote = read the hot row of
    ``hot_id`` into the cold tier under ``cold_id`` and clear it; promote
    = move ``cold_id``'s live cold rows into the (freshly identity) hot
    row ``hot_id``."""

    ids: np.ndarray                                 # int32[n]
    cold_ids: np.ndarray                            # int64[n]
    demotions: List[Tuple[Any, int, int]]           # (key, hot_id, cold_id)
    promotions: List[Tuple[Any, int, int]]          # (key, hot_id, cold_id)


def _plain(k):
    """numpy scalars hash/compare fine but leak into user-facing emissions;
    store plain Python keys like KeyDictionary does."""
    return k.item() if isinstance(k, np.generic) else k


class DynamicKeyVocabulary:
    """Bounded raw-key -> dense-hot-id map with cold-id overflow."""

    def __init__(self, capacity: int, *, policy: str = "lru",
                 admission_min_count: int = 1):
        if capacity < 1:
            raise ValueError("vocabulary capacity must be >= 1")
        if policy not in ("lru", "lfu"):
            raise ValueError(f"unknown eviction policy {policy!r} "
                             "(valid: lru, lfu)")
        self.capacity = int(capacity)
        self.policy = policy
        self.admission_min_count = max(int(admission_min_count), 1)
        self._resident: Dict[Any, int] = {}         # key -> hot id
        self._ids: List[Any] = []                   # hot id -> key (grow-once)
        self._free: List[int] = []                  # recycled hot ids
        self._cold: Dict[Any, int] = {}             # key -> cold id
        self._cold_keys: List[Any] = []             # cold id -> key
        self._cold_free: List[int] = []             # recycled cold ids
        self._pending_cold_free: List[int] = []     # freed NEXT batch
        # doorkeeper sightings of currently-cold keys (admission policy)
        self._cold_freq: Dict[Any, int] = {}
        # heat, indexed by hot id
        self._tick = 0
        self._last = np.zeros(self.capacity, dtype=np.int64)
        self._freq = np.zeros(self.capacity, dtype=np.int64)
        self.num_admissions = 0
        self.num_evictions = 0
        self.num_promotions = 0
        # structural-mutation journal for the changelog checkpoint
        self._ops: List[tuple] = []

    # -- introspection ------------------------------------------------------
    def __len__(self) -> int:
        return len(self._resident)

    @property
    def resident_count(self) -> int:
        return len(self._resident)

    @property
    def vocab_size(self) -> int:
        """Distinct keys currently tracked (resident + cold directory)."""
        return len(self._resident) + len(self._cold)

    def key_of_id(self, hot_id: int):
        return self._ids[hot_id]

    def key_of_cold_id(self, cold_id: int):
        return self._cold_keys[cold_id]

    def resident_id(self, key) -> Optional[int]:
        return self._resident.get(_plain(key))

    def cold_id(self, key) -> Optional[int]:
        return self._cold.get(_plain(key))

    # -- routing ------------------------------------------------------------
    def would_evict(self, keys: np.ndarray) -> bool:
        """Cheap pre-check: could routing this batch evict a resident key?
        The caller uses it to flush in-flight device work BEFORE ids are
        reassigned (a resolved emission must map old ids to old keys).
        Over-approximates (admission may still deny every candidate)."""
        slack = len(self._free) + (self.capacity - len(self._ids))
        if slack >= len(keys):
            return False
        min_count = self.admission_min_count
        # per-key occurrence counts: with a doorkeeper, a key's LAST
        # occurrence in this batch may cross the admission threshold even
        # though its first cannot — project the full batch's sightings
        occ: Dict[Any, int] = {}
        for k in keys:
            k = _plain(k)
            if k not in self._resident:
                occ[k] = occ.get(k, 0) + 1
        new = 0
        for k, c in occ.items():
            if min_count > 1 and \
                    self._cold_freq.get(k, 0) + c < min_count:
                continue    # the doorkeeper keeps it cold — cannot evict
            new += 1
            if new > slack:
                return True
        return False

    def observe_batch(self, keys: np.ndarray) -> RoutedBatch:
        """Route one batch: assign hot ids (admitting/evicting per policy)
        or cold ids, update heat, and return the data movements due."""
        self._tick += 1
        # cold ids freed by the PREVIOUS batch's promotions become reusable
        if self._pending_cold_free:
            self._cold_free.extend(self._pending_cold_free)
            self._pending_cold_free = []
        n = len(keys)
        ids = np.full(n, -1, dtype=np.int32)
        cold_out = np.full(n, -1, dtype=np.int64)
        demotions: List[Tuple[Any, int, int]] = []
        promotions: List[Tuple[Any, int, int]] = []
        pinned: set = set()
        self._victim_order: Optional[np.ndarray] = None
        self._victim_pos = 0
        for i in range(n):
            k = _plain(keys[i])
            hid = self._resident.get(k)
            if hid is None:
                hid = self._admit(k, pinned, demotions, promotions)
            if hid is None:
                cold_out[i] = self._cold_id_for(k)
                self._cold_freq[k] = self._cold_freq.get(k, 0) + 1
            else:
                ids[i] = hid
                self._freq[hid] += 1
                self._last[hid] = self._tick
                pinned.add(hid)
        return RoutedBatch(ids, cold_out, demotions, promotions)

    # -- internals ----------------------------------------------------------
    def _cold_id_for(self, k) -> int:
        cid = self._cold.get(k)
        if cid is None:
            if self._cold_free:
                cid = self._cold_free.pop()
                self._cold_keys[cid] = k
            else:
                cid = len(self._cold_keys)
                self._cold_keys.append(k)
            self._cold[k] = cid
            self._ops.append(("cold", k, cid))
        return cid

    def _admit(self, k, pinned: set, demotions: list,
               promotions: list) -> Optional[int]:
        if self._free:
            hid = self._free.pop()
        elif len(self._ids) < self.capacity:
            hid = len(self._ids)
            self._ids.append(None)
        else:
            # full: the doorkeeper gates admission, then a victim pays
            sightings = self._cold_freq.get(k, 0) + 1
            if sightings < self.admission_min_count:
                return None
            victim = self._pick_victim(pinned)
            if victim is None:
                return None           # every resident is pinned this batch
            vk = self._ids[victim]
            cid = self._cold_id_for(vk)
            del self._resident[vk]
            # an evicted key re-enters the doorkeeper with its hot
            # frequency as credit (a genuinely hot key evicted by a burst
            # must not be locked out by the admission gate)
            self._cold_freq[vk] = int(self._freq[victim])
            demotions.append((vk, victim, cid))
            self.num_evictions += 1
            self._ops.append(("evict", vk, victim, cid))
            hid = victim
        self._resident[k] = hid
        self._ids[hid] = k
        self._freq[hid] = 0
        self._last[hid] = self._tick
        self.num_admissions += 1
        self._ops.append(("admit", k, hid))
        prior_cid = self._cold.pop(k, None)
        self._cold_freq.pop(k, None)
        if prior_cid is not None:
            # re-admission: the caller promotes the cold rows; the cold id
            # frees one batch later (see _pending_cold_free)
            promotions.append((k, hid, prior_cid))
            self._cold_keys[prior_cid] = None
            self._pending_cold_free.append(prior_cid)
            self.num_promotions += 1
            self._ops.append(("promote", k, hid, prior_cid))
        return hid

    def _pick_victim(self, pinned: set) -> Optional[int]:
        """Coldest unpinned resident. The ordering is computed once per
        batch (heat changes within the batch only make victims warmer,
        never colder, so a stale order still evicts a valid cold key)."""
        if self._victim_order is None:
            if self.policy == "lru":
                self._victim_order = np.lexsort((self._freq, self._last))
            else:
                self._victim_order = np.lexsort((self._last, self._freq))
            self._victim_pos = 0
        order = self._victim_order
        while self._victim_pos < len(order):
            cand = int(order[self._victim_pos])
            self._victim_pos += 1
            if cand in pinned or cand >= len(self._ids):
                continue
            if self._ids[cand] is None or self._ids[cand] not in self._resident:
                continue
            if self._resident.get(self._ids[cand]) != cand:
                continue
            return cand
        return None

    # -- changelog journal ---------------------------------------------------
    def drain_ops(self) -> List[tuple]:
        ops, self._ops = self._ops, []
        return ops

    def apply_ops(self, ops: List[tuple]) -> None:
        """Replay a drained journal onto this vocabulary (restore path).
        Heat is not journaled — replayed entries restore structure exactly
        and heat approximately (eviction decisions after restore may
        differ; tier placement is semantically transparent, so results
        cannot)."""
        for op in ops:
            kind = op[0]
            if kind == "cold":
                _, k, cid = op
                while len(self._cold_keys) <= cid:
                    self._cold_keys.append(None)
                self._cold_keys[cid] = k
                self._cold[k] = cid
                if cid in self._cold_free:
                    self._cold_free.remove(cid)
            elif kind == "admit":
                _, k, hid = op
                while len(self._ids) <= hid:
                    self._ids.append(None)
                self._ids[hid] = k
                self._resident[k] = hid
                self._cold_freq.pop(k, None)
                if hid in self._free:
                    self._free.remove(hid)
                self._last[hid] = self._tick
                self._freq[hid] = 0
                self.num_admissions += 1
            elif kind == "evict":
                _, k, hid, cid = op
                if self._resident.get(k) == hid:
                    del self._resident[k]
                while len(self._cold_keys) <= cid:
                    self._cold_keys.append(None)
                self._cold_keys[cid] = k
                self._cold[k] = cid
                if cid in self._cold_free:
                    self._cold_free.remove(cid)
                self.num_evictions += 1
            elif kind == "promote":
                _, k, hid, cid = op
                if self._cold.get(k) == cid:
                    del self._cold[k]
                if cid < len(self._cold_keys):
                    self._cold_keys[cid] = None
                if cid not in self._cold_free:
                    self._cold_free.append(cid)
                self.num_promotions += 1
            else:
                raise ValueError(f"unknown vocabulary op {kind!r}")

    # -- snapshot ------------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "capacity": self.capacity,
            "policy": self.policy,
            "admission_min_count": self.admission_min_count,
            "ids": list(self._ids),
            "free": list(self._free),
            "cold_keys": list(self._cold_keys),
            "cold_free": list(self._cold_free) + list(self._pending_cold_free),
            "cold_freq": dict(self._cold_freq),
            "tick": self._tick,
            "last": self._last.tolist(),
            "freq": self._freq.tolist(),
            "counters": [self.num_admissions, self.num_evictions,
                         self.num_promotions],
        }

    @staticmethod
    def restore(snap: dict) -> "DynamicKeyVocabulary":
        v = DynamicKeyVocabulary(
            snap["capacity"], policy=snap["policy"],
            admission_min_count=snap["admission_min_count"])
        v._ids = list(snap["ids"])
        v._free = list(snap["free"])
        v._resident = {k: i for i, k in enumerate(v._ids)
                       if k is not None and i not in set(snap["free"])}
        v._cold_keys = list(snap["cold_keys"])
        v._cold_free = list(snap["cold_free"])
        v._cold = {k: i for i, k in enumerate(v._cold_keys)
                   if k is not None and i not in set(snap["cold_free"])}
        v._cold_freq = dict(snap["cold_freq"])
        v._tick = snap["tick"]
        v._last = np.asarray(snap["last"], dtype=np.int64)
        v._freq = np.asarray(snap["freq"], dtype=np.int64)
        (v.num_admissions, v.num_evictions, v.num_promotions) = \
            snap["counters"]
        return v
