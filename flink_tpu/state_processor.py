"""State Processor API: offline read / transform / bootstrap of savepoints.

Capability parity with flink-state-processing-api (SavepointReader.java:59,
SavepointWriter.java:62): load a savepoint, enumerate operators, read keyed
state (both heap-operator snapshots and device columnar-window snapshots),
transform or bootstrap state, and write a new savepoint that jobs can
restore from (operator-uid remapping contract, SURVEY §2.6 S10).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.checkpoint.storage import FsCheckpointStorage


class SavepointReader:
    def __init__(self, data: dict):
        self.data = data

    @staticmethod
    def load(path: str) -> "SavepointReader":
        storage = FsCheckpointStorage(path)
        latest = storage.latest()
        if latest is None:
            raise FileNotFoundError(f"no savepoint/checkpoint under {path}")
        return SavepointReader(storage.load(latest[1]))

    # -- introspection ----------------------------------------------------
    def operator_uids(self) -> List[str]:
        return list(self.data.get("runners", {}).keys())

    def records_in(self) -> int:
        return self.data.get("records_in", 0)

    def source_state(self) -> dict:
        """State of the (single) source; for multi-source jobs returns the
        first source's state — use source_states() for all of them."""
        if "sources" in self.data:
            srcs = self.data["sources"]
            return next(iter(srcs.values())) if srcs else {}
        return self.data.get("source", {})

    def source_states(self) -> Dict[str, dict]:
        """Per-source-uid state ({uid: state}); single-source snapshots from
        the pre-DAG layout appear under uid 'source'."""
        if "sources" in self.data:
            return dict(self.data["sources"])
        legacy = self.data.get("source")
        return {"source": legacy} if legacy else {}

    def _runner(self, uid: str) -> dict:
        runners = self.data.get("runners", {})
        if uid not in runners:
            raise KeyError(f"no operator {uid!r}; have {list(runners)}")
        return runners[uid]

    # -- keyed state ------------------------------------------------------
    def keyed_state(self, uid: str) -> Iterator[Tuple]:
        """Yields state entries for the operator:

        - heap-operator snapshots: (state_name, key, namespace, value)
        - device columnar-window snapshots: (key, slice_index,
          {field: value, 'count': n}) for every non-empty (key, slice) cell
        """
        snap = self._runner(uid)
        op = snap.get("operator", snap)
        if "columnar" in op or "sharded" in op:
            yield from self._columnar_entries(op.get("columnar") or op.get("sharded"))
        elif "pipe" in op:
            yield from self._fused_entries(op)
        elif "state" in op:
            for state_name, kg_tables in op["state"].items():
                for _kg, entries in kg_tables.items():
                    for (key, ns), value in entries.items():
                        yield (state_name, key, ns, value)
        else:
            raise TypeError(f"operator {uid!r} snapshot has no readable keyed state")

    def _columnar_entries(self, col: dict) -> Iterator[Tuple]:
        count = np.asarray(col["count"])
        acc = {k: np.asarray(v) for k, v in col["acc"].items()}
        S = col["S"]
        f = col["frontiers"]
        if f["min_used"] is None:
            return
        lo = f["min_used"] if f["purged_to"] is None else max(f["purged_to"], f["min_used"])
        hi = f["max_used"]
        sharded = count.ndim == 3
        keydicts = col.get("keydicts") or [col["keydict"]]
        for shard, kd in enumerate(keydicts):
            keys = kd["keys"]
            cnt = count[shard] if sharded else count
            for kid, key in enumerate(keys):
                for s in range(lo, hi + 1):
                    pos = s % S
                    c = int(cnt[kid, pos])
                    if c == 0:
                        continue
                    fields = {
                        name: (arr[shard] if sharded else arr)[kid, pos].item()
                        for name, arr in acc.items()
                    }
                    fields["count"] = c
                    yield (key, s, fields)

    def pending_output(self, uid: str) -> List[Tuple]:
        """Emissions resolved but not yet drained downstream at snapshot
        time (fused operators fire due windows when the checkpoint flushes
        their buffered steps; those rows ride the checkpoint and re-emit on
        restore). Rows are (key, window, value, timestamp)."""
        snap = self._runner(uid)
        op = snap.get("operator", snap)
        return list(op.get("output", []))

    def _fused_entries(self, op: dict) -> Iterator[Tuple]:
        """Fused window operator snapshots (runtime/fused_window_operator.py):
        same (key, slice, fields) rows as columnar snapshots, reconstructed
        from the superscan's ring state + the normalizer's frontiers."""
        pipe = op["pipe"]
        count = np.asarray(pipe["count"])
        acc = {k: np.asarray(v).copy() for k, v in pipe["state"].items()}
        count = count.copy()
        S = count.shape[1]
        keys = op["keydict"]["keys"]
        g = op["geometry"]["g"]
        offset = op["geometry"]["offset"]
        specs = op["fields"]  # (name, scatter, identity, source, dtype)
        combine = {"add": lambda a, b: a + b, "min": min, "max": max}

        # fold held-back future records (beyond the device ring span) into
        # host-side cells — they are part of the keyed state. Kept separate
        # from the ring loop: their slices can exceed lo+S, and folding them
        # through `pos = s % S` would alias live device cells.
        extra: Dict[Tuple[int, int], Dict[str, Any]] = {}
        buffered = [
            (np.asarray(k), None if v is None else np.asarray(v), np.asarray(t))
            for k, v, t in op.get("normalizer", {}).get("future", [])
        ]
        for kid_arr, val_arr, ts_arr in buffered:
            for i in range(len(ts_arr)):
                s = (int(ts_arr[i]) - offset) // g
                cell = extra.setdefault((int(kid_arr[i]), s), {"count": 0})
                cell["count"] += 1
                for name, scatter, ident, source, _dt in specs:
                    if source != "value":
                        continue
                    v = float(val_arr[i]) if val_arr is not None else 1.0
                    cell[name] = combine[scatter](cell.get(name, ident), v)

        # device ring cells: bounded by the ring frontiers (span < S)
        lo = pipe.get("purged_to")
        hi = pipe.get("max_seen_slice")
        if lo is None:
            lo = pipe.get("min_used_slice")
        if lo is not None and hi is not None:
            for kid, key in enumerate(keys):
                for s in range(lo, hi + 1):
                    pos = s % S
                    c = int(count[kid, pos])
                    cell = extra.pop((kid, s), None)
                    if cell is not None:
                        c += cell["count"]
                    if c == 0:
                        continue
                    fields = {name: arr[kid, pos].item() for name, arr in acc.items()}
                    if cell is not None:
                        for name, scatter, ident, _src, _dt in specs:
                            if name in fields:
                                fields[name] = combine[scatter](
                                    fields[name], cell.get(name, ident)
                                )
                    fields["count"] = c
                    yield (key, s, fields)

        # remaining host-only cells (slices outside the device span)
        for (kid, s), cell in sorted(extra.items(), key=lambda kv: (kv[0][1], kv[0][0])):
            fields = {
                name: cell.get(name, ident)
                for name, _scatter, ident, source, _dt in specs
                if source == "value"
            }
            fields["count"] = cell["count"]
            yield (keys[kid], s, fields)


class SavepointWriter:
    """Transforms an existing savepoint (or builds one from scratch) and
    writes it in restorable form."""

    def __init__(self, data: Optional[dict] = None):
        self.data = copy.deepcopy(data) if data else {
            "source": {
                "pending_splits": [],
                "current_split": None,
                "reader_position": {},
                "done": False,
            },
            "generator": None,
            "runners": {},
            "records_in": 0,
            "savepoint": True,
        }

    @staticmethod
    def from_reader(reader: SavepointReader) -> "SavepointWriter":
        return SavepointWriter(reader.data)

    def remove_operator(self, uid: str) -> "SavepointWriter":
        self.data.get("runners", {}).pop(uid, None)
        return self

    def rename_operator(self, old_uid: str, new_uid: str) -> "SavepointWriter":
        runners = self.data.get("runners", {})
        if old_uid in runners:
            runners[new_uid] = runners.pop(old_uid)
        return self

    def transform_heap_state(
        self, uid: str, fn: Callable[[str, Any, Any, Any], Optional[Any]]
    ) -> "SavepointWriter":
        """fn(state_name, key, namespace, value) -> new value or None to
        drop the entry."""
        snap = self.data["runners"][uid]
        op = snap.get("operator", snap)
        for state_name, kg_tables in op["state"].items():
            for kg, entries in list(kg_tables.items()):
                new_entries = {}
                for (key, ns), value in entries.items():
                    out = fn(state_name, key, ns, value)
                    if out is not None:
                        new_entries[(key, ns)] = out
                kg_tables[kg] = new_entries
        return self

    def transform_columnar_state(
        self, uid: str, fn: Callable[[str, np.ndarray], np.ndarray]
    ) -> "SavepointWriter":
        """fn(field_name, array) -> new array (applied to each accumulator
        field, including 'count'); shape must be preserved."""
        snap = self.data["runners"][uid]
        op = snap.get("operator", snap)
        col = op.get("columnar") or op.get("sharded")
        if col is None and "pipe" in op:
            # fused-operator snapshot: fields live as pipe["state"] arrays.
            # Held-back future records (raw values, not yet cells) get the
            # transform applied to their value column when the aggregate has
            # exactly one value-sourced field — elementwise fns distribute
            # over add/min/max combining, which is the API's contract.
            pipe = op["pipe"]
            for name, arr in pipe["state"].items():
                out = np.asarray(fn(name, np.asarray(arr)))
                if out.shape != np.asarray(arr).shape:
                    raise ValueError("columnar transform must preserve shape")
                pipe["state"][name] = out
            cnt = np.asarray(pipe["count"])
            new_cnt = np.asarray(fn("count", cnt))
            if new_cnt.shape != cnt.shape:
                raise ValueError("columnar transform must preserve shape")
            pipe["count"] = new_cnt
            vfields = [s for s in op.get("fields", []) if s[3] == "value"]
            fut = op.get("normalizer", {}).get("future", [])
            if fut and len(vfields) > 1:
                raise ValueError(
                    "savepoint holds raw future records for a multi-value-field "
                    "aggregate; per-field transforms cannot be applied to shared "
                    "raw values — flush the job past them or drop them explicitly"
                )
            if fut and len(vfields) == 1:
                fname = vfields[0][0]
                op["normalizer"]["future"] = [
                    (k, None if v is None else np.asarray(fn(fname, np.asarray(v))).tolist(), t)
                    for k, v, t in fut
                ]
            return self
        for name, arr in col["acc"].items():
            out = np.asarray(fn(name, np.asarray(arr)))
            if out.shape != np.asarray(arr).shape:
                raise ValueError("columnar transform must preserve shape")
            col["acc"][name] = out
        col["count"] = np.asarray(fn("count", np.asarray(col["count"])))
        return self

    def write(self, path: str) -> str:
        self.data["savepoint"] = True
        return FsCheckpointStorage(path).save(0, self.data)
