"""State Processor API: offline read / transform / bootstrap of savepoints.

Capability parity with flink-state-processing-api (SavepointReader.java:59,
SavepointWriter.java:62): load a savepoint, enumerate operators, read keyed
state (both heap-operator snapshots and device columnar-window snapshots),
transform or bootstrap state, and write a new savepoint that jobs can
restore from (operator-uid remapping contract, SURVEY §2.6 S10).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.checkpoint.storage import FsCheckpointStorage


class SavepointReader:
    def __init__(self, data: dict):
        self.data = data

    @staticmethod
    def load(path: str) -> "SavepointReader":
        storage = FsCheckpointStorage(path)
        latest = storage.latest()
        if latest is None:
            raise FileNotFoundError(f"no savepoint/checkpoint under {path}")
        return SavepointReader(storage.load(latest[1]))

    # -- introspection ----------------------------------------------------
    def operator_uids(self) -> List[str]:
        return list(self.data.get("runners", {}).keys())

    def records_in(self) -> int:
        return self.data.get("records_in", 0)

    def source_state(self) -> dict:
        return self.data.get("source", {})

    def _runner(self, uid: str) -> dict:
        runners = self.data.get("runners", {})
        if uid not in runners:
            raise KeyError(f"no operator {uid!r}; have {list(runners)}")
        return runners[uid]

    # -- keyed state ------------------------------------------------------
    def keyed_state(self, uid: str) -> Iterator[Tuple]:
        """Yields state entries for the operator:

        - heap-operator snapshots: (state_name, key, namespace, value)
        - device columnar-window snapshots: (key, slice_index,
          {field: value, 'count': n}) for every non-empty (key, slice) cell
        """
        snap = self._runner(uid)
        op = snap.get("operator", snap)
        if "columnar" in op or "sharded" in op:
            yield from self._columnar_entries(op.get("columnar") or op.get("sharded"))
        elif "state" in op:
            for state_name, kg_tables in op["state"].items():
                for _kg, entries in kg_tables.items():
                    for (key, ns), value in entries.items():
                        yield (state_name, key, ns, value)
        else:
            raise TypeError(f"operator {uid!r} snapshot has no readable keyed state")

    def _columnar_entries(self, col: dict) -> Iterator[Tuple]:
        count = np.asarray(col["count"])
        acc = {k: np.asarray(v) for k, v in col["acc"].items()}
        S = col["S"]
        f = col["frontiers"]
        if f["min_used"] is None:
            return
        lo = f["min_used"] if f["purged_to"] is None else max(f["purged_to"], f["min_used"])
        hi = f["max_used"]
        sharded = count.ndim == 3
        keydicts = col.get("keydicts") or [col["keydict"]]
        for shard, kd in enumerate(keydicts):
            keys = kd["keys"]
            cnt = count[shard] if sharded else count
            for kid, key in enumerate(keys):
                for s in range(lo, hi + 1):
                    pos = s % S
                    c = int(cnt[kid, pos])
                    if c == 0:
                        continue
                    fields = {
                        name: (arr[shard] if sharded else arr)[kid, pos].item()
                        for name, arr in acc.items()
                    }
                    fields["count"] = c
                    yield (key, s, fields)


class SavepointWriter:
    """Transforms an existing savepoint (or builds one from scratch) and
    writes it in restorable form."""

    def __init__(self, data: Optional[dict] = None):
        self.data = copy.deepcopy(data) if data else {
            "source": {
                "pending_splits": [],
                "current_split": None,
                "reader_position": {},
                "done": False,
            },
            "generator": None,
            "runners": {},
            "records_in": 0,
            "savepoint": True,
        }

    @staticmethod
    def from_reader(reader: SavepointReader) -> "SavepointWriter":
        return SavepointWriter(reader.data)

    def remove_operator(self, uid: str) -> "SavepointWriter":
        self.data.get("runners", {}).pop(uid, None)
        return self

    def rename_operator(self, old_uid: str, new_uid: str) -> "SavepointWriter":
        runners = self.data.get("runners", {})
        if old_uid in runners:
            runners[new_uid] = runners.pop(old_uid)
        return self

    def transform_heap_state(
        self, uid: str, fn: Callable[[str, Any, Any, Any], Optional[Any]]
    ) -> "SavepointWriter":
        """fn(state_name, key, namespace, value) -> new value or None to
        drop the entry."""
        snap = self.data["runners"][uid]
        op = snap.get("operator", snap)
        for state_name, kg_tables in op["state"].items():
            for kg, entries in list(kg_tables.items()):
                new_entries = {}
                for (key, ns), value in entries.items():
                    out = fn(state_name, key, ns, value)
                    if out is not None:
                        new_entries[(key, ns)] = out
                kg_tables[kg] = new_entries
        return self

    def transform_columnar_state(
        self, uid: str, fn: Callable[[str, np.ndarray], np.ndarray]
    ) -> "SavepointWriter":
        """fn(field_name, array) -> new array (applied to each accumulator
        field, including 'count'); shape must be preserved."""
        snap = self.data["runners"][uid]
        op = snap.get("operator", snap)
        col = op.get("columnar") or op.get("sharded")
        for name, arr in col["acc"].items():
            out = np.asarray(fn(name, np.asarray(arr)))
            if out.shape != np.asarray(arr).shape:
                raise ValueError("columnar transform must preserve shape")
            col["acc"][name] = out
        col["count"] = np.asarray(fn("count", np.asarray(col["count"])))
        return self

    def write(self, path: str) -> str:
        self.data["savepoint"] = True
        return FsCheckpointStorage(path).save(0, self.data)
