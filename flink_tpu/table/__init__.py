"""Table/SQL layer (reference: flink-table — Calcite parser T1, planner T2,
runtime T3). A compact dialect covering the streaming-aggregation core:
windowed GROUP BY over TUMBLE/HOP/SESSION, WHERE filters, and the standard
aggregate functions, translated onto the DataStream plan (and therefore onto
the device window operator — the same sliced-window execution the reference
SQL runtime uses via tvf/slicing)."""

from flink_tpu.table.table_env import TableEnvironment, TableSchema
from flink_tpu.table.sql import SqlParseError, parse_query
from flink_tpu.table.changelog import (
    DELETE,
    INSERT,
    ROW_KIND_FIELD,
    UPDATE_AFTER,
    UPDATE_BEFORE,
    materialize,
    row_kind,
    with_kind,
)
