"""Fluent Table API over registered streaming tables.

The reference's Table API (flink-table-api-java: Table.select/filter/
groupBy/window with Tumble/Slide/Session group windows) is the programmatic
sibling of SQL; both lower onto the same planner. Here a `Table` is a thin
builder over the dict-row DataStream machinery `TableEnvironment` already
uses for SQL — windowed aggregations take the device window operator
exactly like their SQL equivalents.

    t = tenv.table("clicks")
    result = (
        t.where(lambda r: r["price"] > 10)
         .window(Tumble.of_ms(10_000))
         .group_by("campaign")
         .aggregate(n=("count", "*"), total=("sum", "price"))
    )
    rows = result.to_list()
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple, Union

from flink_tpu.table.sql import SelectItem, WindowSpec

_AGGS = {"count", "sum", "min", "max", "avg"}


@dataclasses.dataclass(frozen=True)
class _GroupWindow:
    spec: WindowSpec


class Tumble:
    @staticmethod
    def of_ms(size_ms: int) -> _GroupWindow:
        return _GroupWindow(WindowSpec("tumble", "", size_ms=size_ms))


class Slide:
    @staticmethod
    def of_ms(size_ms: int, slide_ms: int) -> _GroupWindow:
        return _GroupWindow(WindowSpec("hop", "", size_ms=size_ms,
                                       slide_ms=slide_ms))


class Session:
    @staticmethod
    def with_gap_ms(gap_ms: int) -> _GroupWindow:
        return _GroupWindow(WindowSpec("session", "", size_ms=gap_ms))


class Table:
    """Immutable fluent builder; terminal ops hand off to the planner."""

    def __init__(self, tenv, name: str):
        self._tenv = tenv
        self._name = name
        self._filters: List[Tuple[Callable[[dict], bool], str]] = []
        self._projection: Optional[List[str]] = None
        self._window: Optional[_GroupWindow] = None
        self._keys: List[str] = []
        self._having: Optional[Tuple[Callable[[dict], bool], str]] = None
        self._order: List[Tuple[str, bool]] = []
        self._limit: Optional[int] = None

    def _copy(self) -> "Table":
        t = Table(self._tenv, self._name)
        t._filters = list(self._filters)
        t._projection = list(self._projection) if self._projection else None
        t._window = self._window
        t._keys = list(self._keys)
        t._having = self._having
        t._order = list(self._order)
        t._limit = self._limit
        return t

    # -- relational ops ---------------------------------------------------
    def where(self, pred: Callable[[dict], bool],
              label: str = "<callable>") -> "Table":
        t = self._copy()
        t._filters.append((pred, label))
        return t

    filter = where

    def select(self, *columns: str) -> "Table":
        t = self._copy()
        t._projection = list(columns)
        return t

    def window(self, w: _GroupWindow) -> "Table":
        t = self._copy()
        t._window = w
        return t

    def group_by(self, *keys: str) -> "Table":
        t = self._copy()
        t._keys = list(keys)
        return t

    def having(self, pred: Callable[[dict], bool],
               label: str = "<callable>") -> "Table":
        """Post-aggregation filter over OUTPUT rows (SQL HAVING)."""
        t = self._copy()
        t._having = (pred, label)
        return t

    def order_by(self, *cols: str) -> "Table":
        """Per-window ordering of the aggregate output (streaming top-N
        when combined with limit); prefix a column with '-' for DESC."""
        t = self._copy()
        t._order = [(c.lstrip("-"), c.startswith("-")) for c in cols]
        return t

    def limit(self, n: int) -> "Table":
        t = self._copy()
        t._limit = n
        return t

    # -- terminals --------------------------------------------------------
    def _base_stream(self):
        tab = self._tenv._tables.get(self._name)
        if tab is None:
            raise KeyError(
                f"unknown table {self._name!r}; registered: "
                f"{list(self._tenv._tables)}")
        # columnar tables read through the dict-row view here: the fluent
        # API's predicates/projections are row closures (the SQL planner's
        # fused lowering is the columnar consumer)
        stream = self._tenv._row_stream(tab)
        for pred, label in self._filters:
            stream = stream.filter(pred, name=f"where[{label}]")
        return stream

    def aggregate(self, **aggs: Tuple[str, ...]):
        """Windowed grouped aggregation: kwargs name the outputs,
        values are ('count',) / ('count', '*') / ('sum'|'min'|'max'|'avg',
        column). Returns a dict-row DataStream (device operator for a
        single device-resolvable aggregate, same as SQL)."""
        if self._window is None:
            raise ValueError("aggregate() requires .window(Tumble/Slide/Session)")
        if not self._keys:
            raise ValueError("aggregate() requires .group_by(keys)")
        items: List[SelectItem] = [
            SelectItem("column", k) for k in self._keys
        ]
        if self._projection is not None:
            raise ValueError(
                "select() composes with projection terminals; name aggregate "
                "outputs via the aggregate(...) kwargs instead")
        for out_name, spec in aggs.items():
            func = spec[0].lower()
            if func not in _AGGS:
                raise ValueError(f"unknown aggregate {spec[0]!r}")
            if func != "count" and (len(spec) < 2 or spec[1] == "*"):
                raise ValueError(
                    f"{func}() needs a column, e.g. ('{func}', 'price')")
            arg = spec[1] if len(spec) > 1 else "*"
            items.append(SelectItem("agg", arg, func=func.upper(),
                                    alias=out_name))
        from flink_tpu.table.sql import Query

        having = self._having[0] if self._having else None
        q = Query(items, self._name, None, None, list(self._keys),
                  self._window.spec, having=having,
                  having_text=self._having[1] if self._having else None,
                  order_by=list(self._order), limit=self._limit)
        stream = self._base_stream()
        return TableResult(self._tenv,
                           self._tenv._grouped_window_query(q, stream))

    def to_stream(self):
        """Projection terminal: the filtered (+selected) dict-row stream."""
        if self._window is not None or self._keys:
            raise ValueError(
                "window()/group_by() require the aggregate(...) terminal; "
                "to_stream()/to_list() are projection terminals")
        if self._having is not None or self._order or self._limit is not None:
            raise ValueError(
                "having()/order_by()/limit() apply to windowed aggregates; "
                "use them with the aggregate(...) terminal")
        stream = self._base_stream()
        if self._projection:
            cols = list(self._projection)
            stream = stream.map(
                lambda row, _c=tuple(cols): {k: row[k] for k in _c},
                name=f"select[{','.join(cols)}]")
        return stream

    def to_list(self) -> List[dict]:
        return TableResult(self._tenv, self.to_stream()).to_list()


class TableResult:
    """Materialization handle for a terminal table operation."""

    def __init__(self, tenv, stream):
        self._tenv = tenv
        self._stream = stream

    def to_stream(self):
        return self._stream

    def to_list(self) -> List[dict]:
        sink = self._stream.collect()
        self._tenv.env.execute("table-query")
        return sink.results
