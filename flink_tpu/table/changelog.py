"""Changelog (retract) stream model.

Reference: `RowKind` (flink-core .../apache/flink/types/RowKind.java:28) and
the planner's changelog-mode inference (ChangelogMode, retract vs upsert).
The reference attaches a RowKind byte to every row; continuous (non-windowed)
aggregates and regular joins consume and produce such changelogs.

TPU-first shape: rows stay plain dicts so the columnar batch machinery is
unchanged; the kind rides in a reserved field (`ROW_KIND_FIELD`). Insert-only
streams simply omit the field — `row_kind` defaults to INSERT — so every
existing source/operator is a valid changelog producer, and changelog-aware
operators (GroupAggRunner, StreamingJoinRunner) compose: a continuous
aggregate's output can be registered as a table and aggregated again, the
reference's cascading-retraction topology.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, List, Tuple

# RowKind.java:28 — shortString() byte codes
INSERT = "+I"
UPDATE_BEFORE = "-U"
UPDATE_AFTER = "+U"
DELETE = "-D"

ROW_KIND_FIELD = "__rowkind__"

# kinds that ADD a row to downstream state vs RETRACT one
_ADDITIVE = frozenset((INSERT, UPDATE_AFTER))
_RETRACTIVE = frozenset((UPDATE_BEFORE, DELETE))


def row_kind(row: dict) -> str:
    """The row's changelog kind; absent field means INSERT (insert-only
    streams are changelog streams with only +I, ChangelogMode.insertOnly)."""
    return row.get(ROW_KIND_FIELD, INSERT)


def is_additive(kind: str) -> bool:
    return kind in _ADDITIVE


def is_retractive(kind: str) -> bool:
    return kind in _RETRACTIVE


def with_kind(row: dict, kind: str) -> dict:
    out = dict(row)
    out[ROW_KIND_FIELD] = kind
    return out


def carry_kind(out: dict, src: dict) -> dict:
    """Copy `src`'s changelog kind onto `out` (in place) when present — THE
    way for projections/maps to forward a changelog row's kind; dropping it
    silently turns retractions into inserts downstream."""
    if ROW_KIND_FIELD in src:
        out[ROW_KIND_FIELD] = src[ROW_KIND_FIELD]
    return out


def strip_kind(row: dict) -> dict:
    if ROW_KIND_FIELD not in row:
        return row
    out = dict(row)
    out.pop(ROW_KIND_FIELD)
    return out


def _freeze(row: dict) -> Tuple:
    return tuple(sorted((k, v) for k, v in row.items() if k != ROW_KIND_FIELD))


def materialize(rows: Iterable[dict]) -> List[dict]:
    """Apply a changelog to an empty multiset and return the surviving rows
    (the reference's retract-sink materialization: +I/+U add a row, -U/-D
    remove an equal one). Works without upsert-key knowledge because -U/-D
    carry the FULL retracted row, which is the retract-changelog contract.
    Output order is first-insertion order; surviving duplicates are
    returned with their multiplicity (SQL multiset semantics)."""
    counts: Counter = Counter()
    sample: Dict[Tuple, dict] = {}
    order: List[Tuple] = []
    for row in rows:
        f = _freeze(row)
        kind = row_kind(row)
        if kind in _ADDITIVE:
            if counts[f] == 0 and f not in sample:
                order.append(f)
            counts[f] += 1
            sample[f] = strip_kind(row)
        elif kind in _RETRACTIVE:
            if counts[f] <= 0:
                raise ValueError(
                    f"changelog retracts a row that is not present: {row!r}")
            counts[f] -= 1
        else:
            raise ValueError(f"unknown row kind {kind!r} on {row!r}")
    out: List[dict] = []
    for f in order:
        out.extend(dict(sample[f]) for _ in range(counts[f]))
    return out
