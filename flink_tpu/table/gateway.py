"""SQL gateway: REST sessions executing SQL statements (T4 analogue).

Mirrors the reference's SQL gateway REST surface (flink-sql-gateway:
SqlGateway.java:47, SqlGatewayServiceImpl.java:65; the JDBC driver speaks
this protocol):

  POST   /v1/sessions                                  → {sessionHandle}
  DELETE /v1/sessions/<sh>                             → close
  POST   /v1/sessions/<sh>/tables                      → register a table
         {"name", "columns": [..], "rows": [...], "time_col",
          "watermark_delay_ms", "types": ["int"|"float"|"str", ..]}
  POST   /v1/sessions/<sh>/statements                  → {"statement": sql}
                                   → {operationHandle, executionPath,
                                      fallbackReason}
  GET    /v1/sessions/<sh>/operations/<oh>/status      → {status,
                                      executionPath, fallbackReason}
  GET    /v1/sessions/<sh>/operations/<oh>/result/<tk> → {columns, data, resultType}

Each session owns a TableEnvironment; statements run the SQL planner
(flink_tpu/planner → table_env.py) on the session's tables/models, and
every submitted statement reports which execution path it selected:
`executionPath` is "fused" when the planner lowered it onto the compiled
device superscan (requires declared numeric column `types`), else
"interpreted" with the catalogued `fallbackReason` attributed.

With `auth_token` set, every request must carry `Authorization: Bearer
<token>` (the REST server's bearer scheme — PR 2/4 pattern); a missing or
wrong token is a 401 before any route logic runs.
"""

from __future__ import annotations

import json
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from flink_tpu.security import bearer_header_equal
from flink_tpu.table.table_env import TableEnvironment, TableSchema


class _Session:
    def __init__(self):
        self.tenv = TableEnvironment()
        self.operations: Dict[str, dict] = {}
        # statements on ONE session run sequentially (the reference
        # gateway's per-session operation ordering): the session's
        # TableEnvironment is shared mutable state — its sink list and
        # last_plan_report would cross-stamp under the ThreadingHTTPServer
        # if two statements interleaved
        self.lock = threading.Lock()


# JSON-safe cell values (numpy scalars from the fused path carry .item());
# single-sourced with runtime/rest.py's payload coercion
from flink_tpu.utils.arrays import jsonable as _json_value  # noqa: E402


class SqlGateway:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 auth_token: Optional[str] = None):
        self._sessions: Dict[str, _Session] = {}
        gw = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self) -> bool:
                if auth_token is None:
                    return True
                # single-sourced with runtime/rest.py (security layer):
                # one constant-time, non-ASCII-safe comparison for every
                # HTTP plane
                if bearer_header_equal(
                        self.headers.get("Authorization") or "",
                        auth_token):
                    return True
                self._json(401, {"error": "missing or invalid bearer token"})
                return False

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            def do_POST(self):
                if not self._authorized():
                    return
                parts = self.path.strip("/").split("/")
                try:
                    if parts == ["v1", "sessions"]:
                        sh = uuid.uuid4().hex[:16]
                        gw._sessions[sh] = _Session()
                        return self._json(200, {"sessionHandle": sh})
                    if len(parts) == 4 and parts[:2] == ["v1", "sessions"]:
                        sess = gw._sessions.get(parts[2])
                        if sess is None:
                            return self._json(404, {"error": "unknown session"})
                        if parts[3] == "tables":
                            b = self._body()
                            with sess.lock:
                                sess.tenv.from_rows(
                                    b["name"], b["rows"],
                                    TableSchema(
                                        b["columns"], b.get("time_col"),
                                        b.get("watermark_delay_ms", 0),
                                        field_types=b.get("types"),
                                    ),
                                )
                            return self._json(200, {"registered": b["name"]})
                        if parts[3] == "statements":
                            b = self._body()
                            oh = uuid.uuid4().hex[:16]
                            op = {"status": "RUNNING", "rows": None,
                                  "error": None, "executionPath": None,
                                  "fallbackReason": None}
                            sess.operations[oh] = op
                            with sess.lock:
                                try:
                                    op["rows"] = sess.tenv.execute_sql_to_list(
                                        b["statement"])
                                    op["status"] = "FINISHED"
                                except Exception as e:  # noqa: BLE001 — surfaced via REST
                                    op["status"] = "ERROR"
                                    op["error"] = f"{type(e).__name__}: {e}"
                                # path selection (flink_tpu/planner):
                                # which execution path the statement took,
                                # with the catalogued reason when it fell
                                # back — read under the SAME lock so a
                                # concurrent statement cannot cross-stamp
                                report = sess.tenv.last_plan_report
                            if report is not None:
                                op["executionPath"] = report.path
                                op["fallbackReason"] = report.reason
                            return self._json(200, {
                                "operationHandle": oh,
                                "executionPath": op["executionPath"],
                                "fallbackReason": op["fallbackReason"],
                            })
                    return self._json(404, {"error": f"no route {self.path}"})
                except Exception as e:  # noqa: BLE001
                    return self._json(500, {"error": f"{type(e).__name__}: {e}"})

            def do_GET(self):
                if not self._authorized():
                    return
                parts = self.path.strip("/").split("/")
                if (len(parts) >= 6 and parts[:2] == ["v1", "sessions"]
                        and parts[3] == "operations"):
                    sess = gw._sessions.get(parts[2])
                    op = sess.operations.get(parts[4]) if sess else None
                    if op is None:
                        return self._json(404, {"error": "unknown operation"})
                    if parts[5] == "status":
                        return self._json(200, {
                            "status": op["status"], "error": op["error"],
                            "executionPath": op["executionPath"],
                            "fallbackReason": op["fallbackReason"],
                        })
                    if parts[5] == "result":
                        if op["status"] == "ERROR":
                            return self._json(400, {"error": op["error"]})
                        rows = op["rows"] or []
                        columns = sorted({k for r in rows for k in r}) if rows else []
                        return self._json(200, {
                            "resultType": "EOS",
                            "columns": columns,
                            "data": [[_json_value(r.get(c)) for c in columns]
                                     for r in rows],
                        })
                return self._json(404, {"error": f"no route {self.path}"})

            def do_DELETE(self):
                if not self._authorized():
                    return
                parts = self.path.strip("/").split("/")
                if len(parts) == 3 and parts[:2] == ["v1", "sessions"]:
                    gw._sessions.pop(parts[2], None)
                    return self._json(200, {"closed": True})
                return self._json(404, {"error": f"no route {self.path}"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name=f"sql-gateway-{self.port}", daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def session_env(self, session_handle: str) -> TableEnvironment:
        """Server-side access to a session's environment (e.g. to register
        models, which carry non-JSON callables)."""
        return self._sessions[session_handle].tenv

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class SqlGatewayClient:
    """Minimal client speaking the gateway protocol (JDBC-driver analogue)."""

    def __init__(self, address: str, auth_token: Optional[str] = None):
        self.address = address.rstrip("/")
        self.auth_token = auth_token

    def _request(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.address + path, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.auth_token is not None:
            req.add_header("Authorization", f"Bearer {self.auth_token}")
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", str(e))
            except Exception:
                detail = str(e)
            raise RuntimeError(f"gateway {method} {path}: {detail}") from None

    def open_session(self) -> str:
        return self._request("POST", "/v1/sessions")["sessionHandle"]

    def register_table(self, sh: str, name: str, columns: List[str], rows: List[dict],
                       time_col: Optional[str] = None,
                       watermark_delay_ms: int = 0,
                       types: Optional[List[str]] = None) -> None:
        """`types` (one of 'int'/'float'/'str' per column) declares the
        schema the SQL planner needs to lower statements onto the fused
        device path; untyped tables always interpret."""
        self._request("POST", f"/v1/sessions/{sh}/tables", {
            "name": name, "columns": columns, "rows": rows,
            "time_col": time_col, "watermark_delay_ms": watermark_delay_ms,
            "types": types,
        })

    def execute(self, sh: str, statement: str) -> List[dict]:
        oh = self._request("POST", f"/v1/sessions/{sh}/statements",
                           {"statement": statement})["operationHandle"]
        status = self._request("GET", f"/v1/sessions/{sh}/operations/{oh}/status")
        if status["status"] == "ERROR":
            raise RuntimeError(status["error"])
        res = self._request("GET", f"/v1/sessions/{sh}/operations/{oh}/result/0")
        return [dict(zip(res["columns"], row)) for row in res["data"]]

    def statement_status(self, sh: str, oh: str) -> dict:
        """Raw status payload incl. executionPath/fallbackReason."""
        return self._request("GET", f"/v1/sessions/{sh}/operations/{oh}/status")

    def close_session(self, sh: str) -> None:
        self._request("DELETE", f"/v1/sessions/{sh}")
