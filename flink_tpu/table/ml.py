"""Model inference in SQL / streams: the `flink-models` analogue (T5).

The reference exposes SQL `ML_PREDICT` against model endpoints through a
provider SPI (flink-table-common/.../ml/PredictRuntimeProvider.java:26,
AsyncPredictRuntimeProvider.java:26; OpenAI/Triton providers call external
services, runtime operator flink-table-runtime/.../ml/MLPredictRunner.java).

TPU-native twist: the natural provider here is not a remote endpoint but a
**JAX model running on the same chips as the pipeline** — features come out
of the stream as columns, inference is one jitted batched call, outputs
rejoin the row. Remote-endpoint providers fit the same SPI (implement
`predict_batch` with an HTTP call); an async wrapper pairs with the
AsyncWaitOperator analogue (runtime/async_io.py) the way
AsyncPredictRuntimeProvider pairs with AsyncWaitOperator in the reference.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


class PredictRuntimeProvider:
    """SPI: batched model inference over feature columns.

    **Determinism contract (changelog inputs).** ML_PREDICT over a retract
    stream re-scores each row twice: once for the +I and once for the -D
    that retracts it. The runtime matches a retraction to the row it
    retracts BY VALUE (StreamingJoinRunner / group-agg multisets), so
    `predict_batch` MUST be a pure function of its features — the -D's
    re-scored row must reproduce the +I's scored row bit-for-bit, or the
    retraction will not cancel its insert downstream (phantom rows in
    joins/aggregates). Providers with dropout left on, sampling
    temperature, non-deterministic kernels, or remote models that drift
    between calls violate this; freeze the model (eval mode, fixed
    weights, greedy decoding) or materialize scores before the changelog
    fans into stateful operators."""

    feature_cols: List[str]
    output_names: List[str]

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        """[B, n_features] -> [B, n_outputs]."""
        raise NotImplementedError

    def predict_row(self, row: dict) -> Dict[str, float]:
        feats = np.asarray([[float(row[c]) for c in self.feature_cols]], dtype=np.float32)
        out = np.asarray(self.predict_batch(feats))
        return {name: out[0, i].item() for i, name in enumerate(self.output_names)}


class JaxModelProvider(PredictRuntimeProvider):
    """A jitted JAX model as the inference runtime.

    `apply(params, features[B, F]) -> [B, O]`; batches are padded to powers
    of two so jit compiles a handful of shapes, and the compiled executables
    are shared across calls (the MLPredictRunner role, on-device).
    """

    def __init__(
        self,
        apply: Callable,
        params,
        feature_cols: Sequence[str],
        output_names: Sequence[str],
        *,
        min_pad: int = 16,
    ):
        import jax

        self.feature_cols = list(feature_cols)
        self.output_names = list(output_names)
        self.params = params
        self.min_pad = min_pad
        self._fn = jax.jit(apply)

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        n = len(features)
        padded = self.min_pad
        while padded < n:
            padded *= 2
        if padded != n:
            features = np.concatenate(
                [features, np.zeros((padded - n, features.shape[1]), features.dtype)]
            )
        out = np.asarray(self._fn(self.params, features))
        return out[:n]


class FnModelProvider(PredictRuntimeProvider):
    """Plain-python/numpy provider (external-endpoint stand-in)."""

    def __init__(self, fn: Callable[[np.ndarray], np.ndarray],
                 feature_cols: Sequence[str], output_names: Sequence[str]):
        self.fn = fn
        self.feature_cols = list(feature_cols)
        self.output_names = list(output_names)

    def predict_batch(self, features: np.ndarray) -> np.ndarray:
        return np.asarray(self.fn(features))


class BatchingPredictor:
    """Per-stream adapter: buffers rows to amortize device dispatches while
    preserving 1:1 row order (the micro-batching the device wants; flushed
    by the runner at watermark/step boundaries)."""

    def __init__(self, provider: PredictRuntimeProvider, max_batch: int = 1024):
        self.provider = provider
        self.max_batch = max_batch
        self._rows: List[dict] = []
        self._out: List[dict] = []

    def offer(self, row: dict) -> None:
        self._rows.append(row)
        if len(self._rows) >= self.max_batch:
            self.flush()

    def flush(self) -> None:
        if not self._rows:
            return
        rows, self._rows = self._rows, []
        feats = np.asarray(
            [[float(r[c]) for c in self.provider.feature_cols] for r in rows],
            dtype=np.float32,
        )
        preds = np.asarray(self.provider.predict_batch(feats))
        for i, r in enumerate(rows):
            out = dict(r)
            for j, name in enumerate(self.provider.output_names):
                out[name] = preds[i, j].item()
            self._out.append(out)

    def drain(self) -> List[dict]:
        self.flush()
        out, self._out = self._out, []
        return out
