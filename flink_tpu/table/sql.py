"""SQL parser for the streaming-aggregation dialect.

Reference: the Calcite-based parser (flink-sql-parser) + planner rewrite of
group windows. Supported grammar (case-insensitive keywords):

  SELECT <item> [, <item>]*
  FROM <table>
  [[LEFT|RIGHT|FULL [OUTER]|INNER] JOIN <table> ON a.col = b.col [WINDOW <window>]]
                                      -- with WINDOW: windowed join;
                                      -- without: regular streaming join
                                      -- emitting a retract changelog
  [WHERE <expr>]
  [GROUP BY <col> [, <col>]* [, <window>]]
                                      -- without a window: CONTINUOUS
                                      -- aggregation (retract changelog)
  [HAVING <expr>]                      -- over output rows (aliases visible)
  [ORDER BY <col> [ASC|DESC] [, ...]] -- per window (streaming top-N)
  [LIMIT <n>]
  [UNION ALL <query>]                 -- concatenate result streams

  <item>   := <col> | <agg>( <col> | * ) [AS <alias>]
            | WINDOW_START [AS alias] | WINDOW_END [AS alias]
  <agg>    := COUNT | SUM | MIN | MAX | AVG
  <window> := TUMBLE(<time_col>, INTERVAL '<n>' <unit>)
            | HOP(<time_col>, INTERVAL '<n>' <unit>, INTERVAL '<n>' <unit>)
            | SESSION(<time_col>, INTERVAL '<n>' <unit>)
  <expr>   := comparisons of columns and literals combined with AND / OR,
              operators = != <> < <= > >=

Hand-rolled recursive descent (no codegen: the reference compiles generated
Java at runtime, we compile to closures over columnar plans — XLA is the
codegen tier).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, List, Optional, Tuple

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<num>-?\d+\.\d+|-?\d+)|(?P<str>'[^']*')|(?P<op><=|>=|!=|<>|=|<|>|\(|\)|,|\*)"
    r"|(?P<word>[A-Za-z_][A-Za-z_0-9.]*))"
)

AGG_FUNCS = {"COUNT", "SUM", "MIN", "MAX", "AVG"}

#: SQL aggregate function -> builtin DeviceAggregator name. THE single
#: source for both front doors: the interpreted translation (table_env)
#: and the planner's agg-call mapping (planner/rules) read this one dict,
#: so they can never disagree about which aggregates have a device form.
DEVICE_AGG_OF = {
    "COUNT": "count", "SUM": "sum", "MIN": "min", "MAX": "max",
    "AVG": "mean",
}
_UNIT_MS = {
    "MILLISECOND": 1, "SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
    "DAY": 86_400_000,
}


class SqlParseError(ValueError):
    """A parse failure with position + snippet context. Subclasses
    ValueError so callers catching the parser's historical error type keep
    working; the gain is a *diagnostic* (where in the statement, with a
    caret) instead of a bare crash message — the reference throws
    SqlParseException with line/column for the same reason."""

    def __init__(self, message: str, sql: str, pos: int):
        self.reason = message
        self.sql = sql
        self.pos = max(0, min(pos, len(sql)))
        super().__init__(f"{message}\n{self.snippet()}")

    def snippet(self, width: int = 40) -> str:
        """The statement text around the failure with a caret under it."""
        start = max(0, self.pos - width)
        end = min(len(self.sql), self.pos + width)
        prefix = "..." if start > 0 else ""
        suffix = "..." if end < len(self.sql) else ""
        line = prefix + self.sql[start:end] + suffix
        caret = " " * (len(prefix) + self.pos - start) + "^"
        return f"  {line}\n  {caret} (at position {self.pos})"


def _tokenize(sql: str) -> Tuple[List[str], List[int]]:
    """Tokens plus their start offsets (for SqlParseError diagnostics)."""
    tokens: List[str] = []
    positions: List[int] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            rest = sql[pos:]
            if rest.strip():
                bad = pos + (len(rest) - len(rest.lstrip()))
                raise SqlParseError(
                    f"cannot tokenize at: {sql[bad:bad + 20]!r}", sql, bad)
            break
        tokens.append(m.group(0).strip())
        positions.append(m.end() - len(tokens[-1]))
        pos = m.end()
    return tokens, positions


@dataclasses.dataclass
class SelectItem:
    kind: str                 # 'column' | 'agg' | 'window_start' | 'window_end' | 'ml_predict'
    name: str                 # column name or agg arg ('*' for COUNT(*)); model name for ml_predict
    func: Optional[str] = None
    alias: Optional[str] = None
    args: Optional[List[str]] = None   # ml_predict feature columns

    @property
    def output_name(self) -> str:
        if self.alias:
            return self.alias
        if self.kind == "agg":
            return f"{self.func.lower()}_{self.name if self.name != '*' else 'all'}"
        if self.kind == "column":
            return self.name.split(".")[-1] if "." in self.name else self.name
        return self.kind


@dataclasses.dataclass
class WindowSpec:
    kind: str                 # 'tumble' | 'hop' | 'session'
    time_col: str
    size_ms: int
    slide_ms: Optional[int] = None  # hop only; for hop arg order: slide, size


@dataclasses.dataclass(frozen=True)
class Operand:
    """One side of a comparison: a column reference or a literal."""

    kind: str                 # 'column' | 'number' | 'string'
    value: Any


@dataclasses.dataclass(frozen=True)
class Comparison:
    """`lhs op rhs` with op in = != <> < <= > >=."""

    left: Operand
    op: str
    right: Operand


@dataclasses.dataclass(frozen=True)
class BoolExpr:
    """AND/OR combination of comparisons (parenthesization is structural)."""

    op: str                   # 'and' | 'or'
    left: Any                 # Comparison | BoolExpr
    right: Any


#: comparison op -> callable. Pure operator closures that work both
#: per-row (scalars; compile_predicate) and columnar/traced (elementwise
#: on numpy/jax arrays; planner/lowering's mask builder) — one table for
#: every consumer of the dialect's operators.
CMP_OPS = {
    "=": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<>": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}
_CMP_OPS = CMP_OPS   # historical internal alias


def compile_predicate(node) -> Callable[[dict], bool]:
    """Row-closure view of a predicate AST (the interpreted path's form).
    Semantics: NULL comparisons are not-TRUE (SQL three-valued logic),
    AND/OR short-circuit like Python's."""
    if isinstance(node, BoolExpr):
        l, r = compile_predicate(node.left), compile_predicate(node.right)
        if node.op == "and":
            return lambda row: l(row) and r(row)
        return lambda row: l(row) or r(row)
    fn = _CMP_OPS[node.op]
    lhs, rhs = _compile_operand(node.left), _compile_operand(node.right)

    def compare(row):
        a, b = lhs(row), rhs(row)
        if a is None or b is None:
            return False        # SQL three-valued logic: NULL cmp -> not TRUE
        return fn(a, b)

    return compare


def _compile_operand(op: Operand):
    if op.kind == "column":
        name = op.value
        return lambda row: row[name]
    lit = op.value
    return lambda row: lit


def predicate_columns(node) -> List[str]:
    """Column names a predicate AST references, in first-use order."""
    out: List[str] = []

    def walk(n):
        if isinstance(n, BoolExpr):
            walk(n.left)
            walk(n.right)
            return
        for side in (n.left, n.right):
            if side.kind == "column" and side.value not in out:
                out.append(side.value)

    walk(node)
    return out


@dataclasses.dataclass
class JoinSpec:
    """Equi-join. With a trailing WINDOW clause: windowed join (the
    reference implements stream joins as coGroup over a shared window;
    JoinedStreams.java:101). Without one: a REGULAR streaming join with
    retraction semantics (StreamingJoinOperator.java:40) — both sides'
    rows buffer indefinitely and the output is a changelog."""

    table2: str
    alias1: str
    alias2: str
    left_col: str           # qualified 'alias.col'
    right_col: str
    window: Optional[WindowSpec] = None
    join_type: str = "inner"   # 'inner' | 'left' | 'right' (regular only)
                               # | 'full' (parses; refused with the typed
                               # catalogued reason 'join-full-outer')


@dataclasses.dataclass
class Query:
    select: List[SelectItem]
    table: str
    where: Optional[Callable[[dict], bool]]
    where_text: Optional[str]
    group_by: List[str]
    window: Optional[WindowSpec]
    join: Optional[JoinSpec] = None
    having: Optional[Callable[[dict], bool]] = None   # over OUTPUT rows
    having_text: Optional[str] = None
    order_by: List[Tuple[str, bool]] = dataclasses.field(
        default_factory=list)                          # (col, descending)
    limit: Optional[int] = None
    union_all: Optional["Query"] = None               # concatenated branch
    # structural predicate ASTs (Comparison/BoolExpr): what the planner
    # (flink_tpu/planner/) reads — the closures above are the interpreted
    # path's compiled view of the same trees
    where_ast: Any = None
    having_ast: Any = None


class _Parser:
    def __init__(self, tokens: List[str], positions: List[int], sql: str):
        self.tokens = tokens
        self.positions = positions
        self.sql = sql
        self.i = 0

    def pos(self) -> int:
        """Character offset of the current token (end of input when past)."""
        if self.i < len(self.positions):
            return self.positions[self.i]
        return len(self.sql)

    def error(self, message: str, at: Optional[int] = None) -> SqlParseError:
        return SqlParseError(message, self.sql,
                             self.pos() if at is None else at)

    def peek(self) -> Optional[str]:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def peek_upper(self) -> Optional[str]:
        t = self.peek()
        return t.upper() if t is not None else None

    def next(self) -> str:
        t = self.peek()
        if t is None:
            raise self.error("unexpected end of query")
        self.i += 1
        return t

    def expect(self, word: str) -> None:
        at = self.pos()
        t = self.next()
        if t.upper() != word.upper():
            raise self.error(f"expected {word}, got {t!r}", at=at)

    # -- grammar ----------------------------------------------------------
    def query(self) -> Query:
        self.expect("SELECT")
        select = [self.select_item()]
        while self.peek() == ",":
            self.next()
            select.append(self.select_item())
        self.expect("FROM")
        table = self.next()
        join = None
        alias1 = table
        if self.peek_upper() == "AS":
            self.next()
            alias1 = self.next()
        join_type = "inner"
        has_join = self.peek_upper() == "JOIN"
        if self.peek_upper() in ("LEFT", "RIGHT", "FULL", "INNER"):
            join_type = self.next().lower()
            if join_type != "inner" and self.peek_upper() == "OUTER":
                self.next()
            self.expect("JOIN")
            has_join = True
        elif has_join:
            self.next()
        if has_join:
            table2 = self.next()
            alias2 = table2
            if self.peek_upper() == "AS":
                self.next()
                alias2 = self.next()
            if alias2 == alias1:
                raise ValueError(
                    f"join sides must have distinct aliases, both are "
                    f"{alias1!r} (use FROM t AS a JOIN t AS b ...)"
                )
            self.expect("ON")
            left = self.next()
            self.expect("=")
            right = self.next()
            # normalize side order to (alias1 col, alias2 col)
            if right.split(".")[0] == alias1 and left.split(".")[0] == alias2:
                left, right = right, left
            if left.split(".")[0] != alias1 or right.split(".")[0] != alias2:
                raise ValueError(
                    f"join condition must equate {alias1}.<col> with "
                    f"{alias2}.<col>, got {left} = {right}"
                )
            join = (table2, alias1, alias2, left, right)
        where = where_text = where_ast = None
        if self.peek_upper() == "WHERE":
            self.next()
            where, where_text, where_ast = self.where_expr()
        group_by: List[str] = []
        window = None
        if self.peek_upper() == "GROUP":
            self.next()
            self.expect("BY")
            while True:
                if self.peek_upper() in ("TUMBLE", "HOP", "SESSION"):
                    window = self.window_spec()
                else:
                    group_by.append(self.next())
                if self.peek() == ",":
                    self.next()
                    continue
                break
        having = having_text = having_ast = None
        if self.peek_upper() == "HAVING":
            if not group_by and window is None:
                raise self.error("HAVING requires GROUP BY")
            self.next()
            having, having_text, having_ast = self.where_expr()
        order_by: List[Tuple[str, bool]] = []
        if self.peek_upper() == "ORDER":
            self.next()
            self.expect("BY")
            while True:
                col = self.next()
                desc = False
                if self.peek_upper() in ("ASC", "DESC"):
                    desc = self.next().upper() == "DESC"
                order_by.append((col, desc))
                if self.peek() == ",":
                    self.next()
                    continue
                break
        limit = None
        if self.peek_upper() == "LIMIT":
            self.next()
            at = self.pos()
            lit = self.next()
            try:
                limit = int(lit)
            except ValueError:
                raise self.error(
                    f"LIMIT expects an integer literal, got {lit!r}", at=at
                ) from None
            if limit < 0:
                raise self.error(
                    f"LIMIT must be non-negative, got {limit}", at=at)
        if join is None and alias1 != table:
            raise ValueError(
                "table aliases are only meaningful on join queries; "
                f"drop 'AS {alias1}' or add a JOIN"
            )
        if join is not None:
            # an optional trailing WINDOW <spec> clause bounds the join
            # (windowed join); without it the join is a REGULAR streaming
            # join over unbounded state, emitting a changelog
            jwindow = None
            if self.peek_upper() == "WINDOW":
                self.next()
                jwindow = self.window_spec(time_col_optional=True)
            if jwindow is not None and join_type in ("left", "right"):
                # FULL parses through here on purpose: it gets the typed
                # catalogued refusal ('join-full-outer') downstream, not a
                # parse error
                raise ValueError(
                    "LEFT/RIGHT OUTER are only supported on regular "
                    "(non-windowed) joins")
            if self.peek_upper() == "UNION":
                raise ValueError(
                    "UNION ALL with a join as the LEFT branch is not "
                    "supported; put the join on the right branch"
                )
            if self.peek() is not None:
                raise ValueError(f"trailing tokens: {self.tokens[self.i:]}")
            if having is not None or order_by or limit is not None:
                raise ValueError(
                    "HAVING/ORDER BY/LIMIT are not supported on join queries"
                )
            return Query(select, table, where, where_text, group_by, None,
                         JoinSpec(join[0], join[1], join[2], join[3],
                                  join[4], jwindow, join_type),
                         where_ast=where_ast)
        union_all = None
        if self.peek_upper() == "UNION":
            self.next()
            self.expect("ALL")
            union_all = self.query()       # right-recursive: a UNION chain
        elif self.peek() is not None:
            raise ValueError(f"trailing tokens: {self.tokens[self.i:]}")
        return Query(select, table, where, where_text, group_by, window,
                     having=having, having_text=having_text,
                     order_by=order_by, limit=limit, union_all=union_all,
                     where_ast=where_ast, having_ast=having_ast)

    def select_item(self) -> SelectItem:
        t = self.next()
        up = t.upper()
        if up in AGG_FUNCS:
            self.expect("(")
            arg = self.next()
            self.expect(")")
            item = SelectItem("agg", arg, func=up)
        elif up == "ML_PREDICT":
            # ML_PREDICT(model, feature_col [, feature_col...]) — the SQL
            # model-inference function (T5; reference: ML_PREDICT TVF via
            # PredictRuntimeProvider.java:26)
            self.expect("(")
            model = self.next()
            feats: List[str] = []
            while self.peek() == ",":
                self.next()
                feats.append(self.next())
            self.expect(")")
            item = SelectItem("ml_predict", model, args=feats)
        elif up in ("WINDOW_START", "WINDOW_END"):
            item = SelectItem(up.lower(), up.lower())
        else:
            item = SelectItem("column", t)
        if self.peek_upper() == "AS":
            self.next()
            item.alias = self.next()
        return item

    def window_spec(self, time_col_optional: bool = False) -> WindowSpec:
        kind = self.next().upper()
        self.expect("(")
        time_col = ""
        if not (time_col_optional and self.peek_upper() == "INTERVAL"):
            time_col = self.next()
            self.expect(",")
        first = self.interval()
        if kind == "HOP":
            self.expect(",")
            second = self.interval()
            self.expect(")")
            # HOP(time, slide, size) — reference TVF argument order
            return WindowSpec("hop", time_col, size_ms=second, slide_ms=first)
        self.expect(")")
        return WindowSpec(kind.lower(), time_col, size_ms=first)

    def interval(self) -> int:
        self.expect("INTERVAL")
        at = self.pos()
        lit = self.next()
        if not (lit.startswith("'") and lit.endswith("'")):
            raise self.error(f"INTERVAL literal expected, got {lit!r}", at=at)
        try:
            n = float(lit[1:-1])
        except ValueError:
            raise self.error(
                f"INTERVAL literal must be numeric, got {lit!r}", at=at
            ) from None
        at = self.pos()
        unit = self.next().upper()
        key = unit[:-1] if unit.endswith("S") and unit[:-1] in _UNIT_MS else unit
        if key not in _UNIT_MS:
            raise self.error(f"unknown interval unit {unit!r}", at=at)
        return int(n * _UNIT_MS[key])

    # -- WHERE ------------------------------------------------------------
    def where_expr(self) -> Tuple[Callable[[dict], bool], str, Any]:
        """(compiled closure, source text, predicate AST)."""
        start = self.i
        node = self.or_expr()
        text = " ".join(self.tokens[start:self.i])
        return compile_predicate(node), text, node

    def or_expr(self):
        left = self.and_expr()
        while self.peek_upper() == "OR":
            self.next()
            left = BoolExpr("or", left, self.and_expr())
        return left

    def and_expr(self):
        left = self.comparison()
        while self.peek_upper() == "AND":
            self.next()
            left = BoolExpr("and", left, self.comparison())
        return left

    def comparison(self):
        if self.peek() == "(":
            self.next()
            inner = self.or_expr()
            self.expect(")")
            return inner
        lhs = self.operand()
        at = self.pos()
        op = self.next()
        rhs = self.operand()
        if op not in _CMP_OPS:
            raise self.error(f"unknown comparison operator {op!r}", at=at)
        return Comparison(lhs, op, rhs)

    def operand(self) -> Operand:
        at = self.pos()
        t = self.next()
        if t.startswith("'") and t.endswith("'"):
            return Operand("string", t[1:-1])
        try:
            return Operand("number", float(t) if "." in t else int(t))
        except ValueError:
            pass
        if not re.match(r"[A-Za-z_]", t):
            raise self.error(
                f"expected a column or literal operand, got {t!r}", at=at)
        return Operand("column", t)


def parse_query(sql: str) -> Query:
    """Parse one statement. Every parse failure surfaces as SqlParseError
    (a ValueError subclass) with position + snippet context — a raw
    IndexError/ValueError escaping the recursive descent is a crash, not a
    diagnostic, so any stray one is wrapped at the current token here."""
    tokens, positions = _tokenize(sql)
    parser = _Parser(tokens, positions, sql)
    try:
        return parser.query()
    except SqlParseError:
        raise
    except (ValueError, IndexError) as e:
        raise SqlParseError(
            str(e) or type(e).__name__, sql, parser.pos()) from e
