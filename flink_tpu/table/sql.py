"""SQL parser for the streaming-aggregation dialect.

Reference: the Calcite-based parser (flink-sql-parser) + planner rewrite of
group windows. Supported grammar (case-insensitive keywords):

  SELECT <item> [, <item>]*
  FROM <table>
  [[LEFT|RIGHT [OUTER]|INNER] JOIN <table> ON a.col = b.col [WINDOW <window>]]
                                      -- with WINDOW: windowed join;
                                      -- without: regular streaming join
                                      -- emitting a retract changelog
  [WHERE <expr>]
  [GROUP BY <col> [, <col>]* [, <window>]]
                                      -- without a window: CONTINUOUS
                                      -- aggregation (retract changelog)
  [HAVING <expr>]                      -- over output rows (aliases visible)
  [ORDER BY <col> [ASC|DESC] [, ...]] -- per window (streaming top-N)
  [LIMIT <n>]
  [UNION ALL <query>]                 -- concatenate result streams

  <item>   := <col> | <agg>( <col> | * ) [AS <alias>]
            | WINDOW_START [AS alias] | WINDOW_END [AS alias]
  <agg>    := COUNT | SUM | MIN | MAX | AVG
  <window> := TUMBLE(<time_col>, INTERVAL '<n>' <unit>)
            | HOP(<time_col>, INTERVAL '<n>' <unit>, INTERVAL '<n>' <unit>)
            | SESSION(<time_col>, INTERVAL '<n>' <unit>)
  <expr>   := comparisons of columns and literals combined with AND / OR,
              operators = != <> < <= > >=

Hand-rolled recursive descent (no codegen: the reference compiles generated
Java at runtime, we compile to closures over columnar plans — XLA is the
codegen tier).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, List, Optional, Tuple

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<num>\d+\.\d+|\d+)|(?P<str>'[^']*')|(?P<op><=|>=|!=|<>|=|<|>|\(|\)|,|\*)"
    r"|(?P<word>[A-Za-z_][A-Za-z_0-9.]*))"
)

AGG_FUNCS = {"COUNT", "SUM", "MIN", "MAX", "AVG"}
_UNIT_MS = {
    "MILLISECOND": 1, "SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
    "DAY": 86_400_000,
}


def _tokenize(sql: str) -> List[str]:
    tokens, pos = [], 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            if sql[pos:].strip():
                raise ValueError(f"cannot tokenize at: {sql[pos:pos+20]!r}")
            break
        tokens.append(m.group(0).strip())
        pos = m.end()
    return tokens


@dataclasses.dataclass
class SelectItem:
    kind: str                 # 'column' | 'agg' | 'window_start' | 'window_end' | 'ml_predict'
    name: str                 # column name or agg arg ('*' for COUNT(*)); model name for ml_predict
    func: Optional[str] = None
    alias: Optional[str] = None
    args: Optional[List[str]] = None   # ml_predict feature columns

    @property
    def output_name(self) -> str:
        if self.alias:
            return self.alias
        if self.kind == "agg":
            return f"{self.func.lower()}_{self.name if self.name != '*' else 'all'}"
        if self.kind == "column":
            return self.name.split(".")[-1] if "." in self.name else self.name
        return self.kind


@dataclasses.dataclass
class WindowSpec:
    kind: str                 # 'tumble' | 'hop' | 'session'
    time_col: str
    size_ms: int
    slide_ms: Optional[int] = None  # hop only; for hop arg order: slide, size


@dataclasses.dataclass
class JoinSpec:
    """Equi-join. With a trailing WINDOW clause: windowed join (the
    reference implements stream joins as coGroup over a shared window;
    JoinedStreams.java:101). Without one: a REGULAR streaming join with
    retraction semantics (StreamingJoinOperator.java:40) — both sides'
    rows buffer indefinitely and the output is a changelog."""

    table2: str
    alias1: str
    alias2: str
    left_col: str           # qualified 'alias.col'
    right_col: str
    window: Optional[WindowSpec] = None
    join_type: str = "inner"   # 'inner' | 'left' | 'right' (regular only)


@dataclasses.dataclass
class Query:
    select: List[SelectItem]
    table: str
    where: Optional[Callable[[dict], bool]]
    where_text: Optional[str]
    group_by: List[str]
    window: Optional[WindowSpec]
    join: Optional[JoinSpec] = None
    having: Optional[Callable[[dict], bool]] = None   # over OUTPUT rows
    having_text: Optional[str] = None
    order_by: List[Tuple[str, bool]] = dataclasses.field(
        default_factory=list)                          # (col, descending)
    limit: Optional[int] = None
    union_all: Optional["Query"] = None               # concatenated branch


class _Parser:
    def __init__(self, tokens: List[str]):
        self.tokens = tokens
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def peek_upper(self) -> Optional[str]:
        t = self.peek()
        return t.upper() if t is not None else None

    def next(self) -> str:
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of query")
        self.i += 1
        return t

    def expect(self, word: str) -> None:
        t = self.next()
        if t.upper() != word.upper():
            raise ValueError(f"expected {word}, got {t!r}")

    # -- grammar ----------------------------------------------------------
    def query(self) -> Query:
        self.expect("SELECT")
        select = [self.select_item()]
        while self.peek() == ",":
            self.next()
            select.append(self.select_item())
        self.expect("FROM")
        table = self.next()
        join = None
        alias1 = table
        if self.peek_upper() == "AS":
            self.next()
            alias1 = self.next()
        join_type = "inner"
        has_join = self.peek_upper() == "JOIN"
        if self.peek_upper() in ("LEFT", "RIGHT", "INNER"):
            join_type = self.next().lower()
            if join_type != "inner" and self.peek_upper() == "OUTER":
                self.next()
            self.expect("JOIN")
            has_join = True
        elif has_join:
            self.next()
        if has_join:
            table2 = self.next()
            alias2 = table2
            if self.peek_upper() == "AS":
                self.next()
                alias2 = self.next()
            if alias2 == alias1:
                raise ValueError(
                    f"join sides must have distinct aliases, both are "
                    f"{alias1!r} (use FROM t AS a JOIN t AS b ...)"
                )
            self.expect("ON")
            left = self.next()
            self.expect("=")
            right = self.next()
            # normalize side order to (alias1 col, alias2 col)
            if right.split(".")[0] == alias1 and left.split(".")[0] == alias2:
                left, right = right, left
            if left.split(".")[0] != alias1 or right.split(".")[0] != alias2:
                raise ValueError(
                    f"join condition must equate {alias1}.<col> with "
                    f"{alias2}.<col>, got {left} = {right}"
                )
            join = (table2, alias1, alias2, left, right)
        where = where_text = None
        if self.peek_upper() == "WHERE":
            self.next()
            where, where_text = self.where_expr()
        group_by: List[str] = []
        window = None
        if self.peek_upper() == "GROUP":
            self.next()
            self.expect("BY")
            while True:
                if self.peek_upper() in ("TUMBLE", "HOP", "SESSION"):
                    window = self.window_spec()
                else:
                    group_by.append(self.next())
                if self.peek() == ",":
                    self.next()
                    continue
                break
        having = having_text = None
        if self.peek_upper() == "HAVING":
            if not group_by and window is None:
                raise ValueError("HAVING requires GROUP BY")
            self.next()
            having, having_text = self.where_expr()
        order_by: List[Tuple[str, bool]] = []
        if self.peek_upper() == "ORDER":
            self.next()
            self.expect("BY")
            while True:
                col = self.next()
                desc = False
                if self.peek_upper() in ("ASC", "DESC"):
                    desc = self.next().upper() == "DESC"
                order_by.append((col, desc))
                if self.peek() == ",":
                    self.next()
                    continue
                break
        limit = None
        if self.peek_upper() == "LIMIT":
            self.next()
            limit = int(self.next())
        if join is None and alias1 != table:
            raise ValueError(
                "table aliases are only meaningful on join queries; "
                f"drop 'AS {alias1}' or add a JOIN"
            )
        if join is not None:
            # an optional trailing WINDOW <spec> clause bounds the join
            # (windowed join); without it the join is a REGULAR streaming
            # join over unbounded state, emitting a changelog
            jwindow = None
            if self.peek_upper() == "WINDOW":
                self.next()
                jwindow = self.window_spec(time_col_optional=True)
            if jwindow is not None and join_type != "inner":
                raise ValueError(
                    "LEFT/RIGHT OUTER are only supported on regular "
                    "(non-windowed) joins")
            if self.peek_upper() == "UNION":
                raise ValueError(
                    "UNION ALL with a join as the LEFT branch is not "
                    "supported; put the join on the right branch"
                )
            if self.peek() is not None:
                raise ValueError(f"trailing tokens: {self.tokens[self.i:]}")
            if having is not None or order_by or limit is not None:
                raise ValueError(
                    "HAVING/ORDER BY/LIMIT are not supported on join queries"
                )
            return Query(select, table, where, where_text, group_by, None,
                         JoinSpec(join[0], join[1], join[2], join[3],
                                  join[4], jwindow, join_type))
        union_all = None
        if self.peek_upper() == "UNION":
            self.next()
            self.expect("ALL")
            union_all = self.query()       # right-recursive: a UNION chain
        elif self.peek() is not None:
            raise ValueError(f"trailing tokens: {self.tokens[self.i:]}")
        return Query(select, table, where, where_text, group_by, window,
                     having=having, having_text=having_text,
                     order_by=order_by, limit=limit, union_all=union_all)

    def select_item(self) -> SelectItem:
        t = self.next()
        up = t.upper()
        if up in AGG_FUNCS:
            self.expect("(")
            arg = self.next()
            self.expect(")")
            item = SelectItem("agg", arg, func=up)
        elif up == "ML_PREDICT":
            # ML_PREDICT(model, feature_col [, feature_col...]) — the SQL
            # model-inference function (T5; reference: ML_PREDICT TVF via
            # PredictRuntimeProvider.java:26)
            self.expect("(")
            model = self.next()
            feats: List[str] = []
            while self.peek() == ",":
                self.next()
                feats.append(self.next())
            self.expect(")")
            item = SelectItem("ml_predict", model, args=feats)
        elif up in ("WINDOW_START", "WINDOW_END"):
            item = SelectItem(up.lower(), up.lower())
        else:
            item = SelectItem("column", t)
        if self.peek_upper() == "AS":
            self.next()
            item.alias = self.next()
        return item

    def window_spec(self, time_col_optional: bool = False) -> WindowSpec:
        kind = self.next().upper()
        self.expect("(")
        time_col = ""
        if not (time_col_optional and self.peek_upper() == "INTERVAL"):
            time_col = self.next()
            self.expect(",")
        first = self.interval()
        if kind == "HOP":
            self.expect(",")
            second = self.interval()
            self.expect(")")
            # HOP(time, slide, size) — reference TVF argument order
            return WindowSpec("hop", time_col, size_ms=second, slide_ms=first)
        self.expect(")")
        return WindowSpec(kind.lower(), time_col, size_ms=first)

    def interval(self) -> int:
        self.expect("INTERVAL")
        lit = self.next()
        if not (lit.startswith("'") and lit.endswith("'")):
            raise ValueError(f"INTERVAL literal expected, got {lit!r}")
        n = float(lit[1:-1])
        unit = self.next().upper()
        key = unit[:-1] if unit.endswith("S") and unit[:-1] in _UNIT_MS else unit
        if key not in _UNIT_MS:
            raise ValueError(f"unknown interval unit {unit!r}")
        return int(n * _UNIT_MS[key])

    # -- WHERE ------------------------------------------------------------
    def where_expr(self) -> Tuple[Callable[[dict], bool], str]:
        start = self.i
        node = self.or_expr()
        text = " ".join(self.tokens[start:self.i])
        return node, text

    def or_expr(self):
        left = self.and_expr()
        while self.peek_upper() == "OR":
            self.next()
            right = self.and_expr()
            left = (lambda l, r: lambda row: l(row) or r(row))(left, right)
        return left

    def and_expr(self):
        left = self.comparison()
        while self.peek_upper() == "AND":
            self.next()
            right = self.comparison()
            left = (lambda l, r: lambda row: l(row) and r(row))(left, right)
        return left

    def comparison(self):
        if self.peek() == "(":
            self.next()
            inner = self.or_expr()
            self.expect(")")
            return inner
        lhs = self.operand()
        op = self.next()
        rhs = self.operand()
        ops = {
            "=": lambda a, b: a == b,
            "!=": lambda a, b: a != b,
            "<>": lambda a, b: a != b,
            "<": lambda a, b: a < b,
            "<=": lambda a, b: a <= b,
            ">": lambda a, b: a > b,
            ">=": lambda a, b: a >= b,
        }
        if op not in ops:
            raise ValueError(f"unknown comparison operator {op!r}")
        fn = ops[op]

        def compare(row):
            a, b = lhs(row), rhs(row)
            if a is None or b is None:
                return False        # SQL three-valued logic: NULL cmp -> not TRUE
            return fn(a, b)

        return compare

    def operand(self):
        t = self.next()
        if t.startswith("'") and t.endswith("'"):
            lit = t[1:-1]
            return lambda row: lit
        try:
            num = float(t) if "." in t else int(t)
            return lambda row: num
        except ValueError:
            pass
        name = t
        return lambda row: row[name]


def parse_query(sql: str) -> Query:
    return _Parser(_tokenize(sql)).query()
