"""TableEnvironment: SQL over registered streaming tables.

Reference: TableEnvironment + the planner's translation of windowed GROUP BY
queries into DataStream transformations (flink-table-planner; group windows
lower onto the same WindowOperator machinery — here onto our device window
operator via the DataStream API, giving SQL the sliced-window device path
the reference SQL runtime gets from tvf/slicing).

Rows are dicts keyed by schema field names. Single-aggregate queries with a
device-resolvable function run on the TPU operator; multi-aggregate queries
use a composite oracle AggregateFunction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from flink_tpu.api.datastream import DataStream, StreamExecutionEnvironment
from flink_tpu.api.functions import AggregateFunction
from flink_tpu.api.windowing.assigners import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.connectors.sink import CollectSink, Sink
from flink_tpu.core.watermarks import WatermarkStrategy
from flink_tpu.table.sql import (
    AGG_FUNCS,
    DEVICE_AGG_OF as _DEVICE_AGG,   # single-sourced with planner/rules
    Query,
    SelectItem,
    parse_query,
)


@dataclasses.dataclass
class TableSchema:
    fields: List[str]
    rowtime: Optional[str] = None          # event-time column (ms)
    watermark_delay_ms: int = 0            # bounded out-of-orderness
    # optional declared field types ('int' | 'float' | 'str', one per
    # field): what lets the SQL planner (flink_tpu/planner) prove numeric
    # columns at plan time and lower the statement onto the fused device
    # path. Untyped row-mode tables always take the interpreted path.
    field_types: Optional[List[str]] = None

    def __post_init__(self):
        if self.field_types is not None and \
                len(self.field_types) != len(self.fields):
            raise ValueError(
                f"field_types ({len(self.field_types)}) must match fields "
                f"({len(self.fields)})")

    def py_cast(self, field: str):
        """Python-type cast for a field's values (identity when untyped)."""
        if self.field_types is None:
            return lambda v: v
        t = self.field_types[self.fields.index(field)]
        return {"int": int, "float": float, "str": str}.get(t, lambda v: v)


@dataclasses.dataclass
class _Table:
    stream: DataStream
    schema: TableSchema
    # columnar: the stream carries numeric [n, F] batches where column i is
    # the i-th non-rowtime schema field and the rowtime rides the batch
    # timestamps — the device-ready registration the fused SQL path stages
    # straight into the superscan. Row-mode (dict) tables interpret, or
    # fuse window-only when field_types declare numeric columns.
    columnar: bool = False


class _MultiAgg(AggregateFunction):
    """Composite accumulator for multi-aggregate SELECTs (oracle path)."""

    def __init__(self, items: List[SelectItem]):
        self.items = items

    def create_accumulator(self):
        return [
            {"count": 0, "sum": 0.0, "min": float("inf"), "max": float("-inf")}
            for _ in self.items
        ]

    def add(self, row, accs):
        out = []
        for item, acc in zip(self.items, accs):
            v = 1.0 if item.name == "*" else float(row[item.name])
            out.append({
                "count": acc["count"] + 1,
                "sum": acc["sum"] + (0 if item.name == "*" else v),
                "min": min(acc["min"], v),
                "max": max(acc["max"], v),
            })
        return out

    def get_result(self, accs):
        vals = []
        for item, acc in zip(self.items, accs):
            if item.func == "COUNT":
                vals.append(acc["count"])
            elif item.func == "SUM":
                vals.append(acc["sum"])
            elif item.func == "MIN":
                vals.append(acc["min"])
            elif item.func == "MAX":
                vals.append(acc["max"])
            elif item.func == "AVG":
                vals.append(acc["sum"] / max(acc["count"], 1))
        return tuple(vals)

    def merge(self, a, b):
        return [
            {
                "count": x["count"] + y["count"],
                "sum": x["sum"] + y["sum"],
                "min": min(x["min"], y["min"]),
                "max": max(x["max"], y["max"]),
            }
            for x, y in zip(a, b)
        ]


class TableEnvironment:
    def __init__(self, env: Optional[StreamExecutionEnvironment] = None):
        self.env = env or StreamExecutionEnvironment.get_execution_environment()
        self._tables: Dict[str, _Table] = {}
        self._models: Dict[str, Any] = {}
        # planning outcome of the last sql_query()/execute_sql* call — the
        # gateway reports it per statement (executionPath + fallbackReason)
        self.last_plan_report = None

    # -- registration -----------------------------------------------------
    def register_model(self, name: str, provider) -> None:
        """Register a PredictRuntimeProvider for SQL ML_PREDICT (T5)."""
        self._models[name] = provider

    def register_table(self, name: str, stream: DataStream,
                       schema: TableSchema, columnar: bool = False) -> None:
        """Register a stream as a table. `columnar=True` declares the
        device-ready contract: the stream's batches are numeric [n, F]
        columns, column i = the i-th non-rowtime schema field, event time
        rides the batch timestamps. Columnar tables are what the SQL
        planner fuses whole (docs/sql.md); the interpreted path reads them
        through a per-record row view."""
        self._tables[name] = _Table(stream, schema, columnar=columnar)

    def from_rows(self, name: str, rows: Sequence[dict], schema: TableSchema) -> None:
        """Register an in-memory table (fromValues analogue)."""
        strategy = None
        ts_fn = None
        if schema.rowtime:
            rt = schema.rowtime
            ts_fn = lambda row: int(row[rt])  # noqa: E731
            strategy = WatermarkStrategy.for_bounded_out_of_orderness(
                schema.watermark_delay_ms
            )
        stream = self.env.from_collection(list(rows), timestamp_fn=ts_fn,
                                          watermark_strategy=strategy)
        self.register_table(name, stream, schema)

    def table(self, name: str):
        """Fluent Table API handle (the reference's Table surface;
        flink_tpu/table/api.py)."""
        from flink_tpu.table.api import Table

        return Table(self, name)

    # -- queries ----------------------------------------------------------
    def sql_query(self, sql: str) -> DataStream:
        # cleared BEFORE parsing: a statement that fails to parse or
        # translate must not inherit the previous statement's report (the
        # gateway stamps this onto the operation as executionPath)
        self.last_plan_report = None
        q = parse_query(sql)
        return self._plan_and_translate(q)

    def explain_sql(self, sql: str):
        """Plan-only view of a statement: the SqlPlanReport the planner
        produces (fused logical tree, or the attributed fallback reason)."""
        from flink_tpu.config import TableOptions
        from flink_tpu.planner import SqlPlanReport, plan_query

        q = parse_query(sql)
        if not self.env.config.get(TableOptions.DEVICE_FUSION):
            return SqlPlanReport(path="interpreted", reason="disabled",
                                 detail="table.device-fusion is false")
        return plan_query(q, self._catalog())

    def _catalog(self):
        from flink_tpu.planner import TableInfo

        return {
            name: TableInfo(
                name=name,
                fields=tuple(t.schema.fields),
                rowtime=t.schema.rowtime,
                field_types=(tuple(t.schema.field_types)
                             if t.schema.field_types is not None else None),
                columnar=t.columnar,
            )
            for name, t in self._tables.items()
        }

    def _plan_and_translate(self, q: Query) -> DataStream:
        """Route through the SQL planner (flink_tpu/planner) behind
        table.device-fusion: fused-lowerable statements compile onto the
        whole-graph-fusion device path; everything else keeps the
        interpreted translation below, with the fallback reason recorded
        in `last_plan_report` (and surfaced by the gateway)."""
        from flink_tpu.config import TableOptions
        from flink_tpu.planner import SqlPlanReport, plan_query

        if not self.env.config.get(TableOptions.DEVICE_FUSION):
            self.last_plan_report = SqlPlanReport(
                path="interpreted", reason="disabled",
                detail="table.device-fusion is false")
            return self._translate(q)
        report = plan_query(
            q, self._catalog(),
            sources={n: t.stream.transform
                     for n, t in self._tables.items()},
        )
        self.last_plan_report = report
        if report.fused and q.join is not None:
            # fused windowed join: the planner validated the shape
            # (JoinLogicalPlan + rules.rewrite_join_window); construction
            # still happens here because row streams are an api-layer
            # concern, but the window_join transformation is stamped
            # sql_origin so the runtime's DeviceJoinRunner counts toward
            # sqlFusedSelected — the SQL front door selected the device
            # join, it didn't fall back
            return self._join_query(q, sql_fused=True)
        if report.lowered is None:
            return self._translate(q)
        low = report.lowered
        result = DataStream(self.env, low.terminal)
        return self._windowed_output_stage(
            result, q, [low.group_col], extract=None)

    def _translate(self, q: Query) -> DataStream:
        if q.union_all is not None:
            # UNION ALL: each branch plans independently, the result
            # streams concatenate (DataStream.union; watermarks
            # min-combine across branches as usual). Branch schemas must
            # agree, as in standard SQL.
            left_cols = [i.output_name for i in q.select]
            right_cols = [i.output_name for i in q.union_all.select]
            if left_cols != right_cols:
                raise ValueError(
                    f"UNION ALL branches must produce the same columns: "
                    f"{left_cols} vs {right_cols} (use AS aliases)"
                )
            left = dataclasses.replace(q, union_all=None)
            return self._translate(left).union(self._translate(q.union_all))
        if q.table not in self._tables:
            raise KeyError(f"unknown table {q.table!r}; registered: {list(self._tables)}")
        table = self._tables[q.table]
        stream = self._row_stream(table)

        if q.join is not None:
            return self._join_query(q)

        if q.where is not None:
            pred = q.where
            stream = stream.filter(pred, name=f"where[{q.where_text}]")

        aggs = [i for i in q.select if i.kind == "agg"]
        preds = [i for i in q.select if i.kind == "ml_predict"]
        if not aggs and (q.order_by or q.limit is not None):
            raise NotImplementedError(
                "ORDER BY / LIMIT are defined per window (streaming top-N); "
                "use them on a windowed GROUP BY aggregate query"
            )
        if not aggs and (q.having is not None or q.group_by):
            raise NotImplementedError(
                "GROUP BY / HAVING require aggregate select items with a "
                "TUMBLE/HOP/SESSION window"
            )
        if not aggs:
            # projection (+ optional model inference) query
            from flink_tpu.table.changelog import carry_kind

            cols = [i for i in q.select if i.kind == "column"]
            if preds:
                providers = []
                for item in preds:
                    if item.name not in self._models:
                        raise KeyError(
                            f"unknown model {item.name!r}; registered: {list(self._models)}"
                        )
                    providers.append((item, self._models[item.name]))

                for item, provider in providers:
                    # SQL args map POSITIONALLY onto the provider's features
                    args = item.args or provider.feature_cols
                    if len(args) != len(provider.feature_cols):
                        raise ValueError(
                            f"ML_PREDICT({item.name}, ...) got {len(args)} "
                            f"features, model wants {len(provider.feature_cols)}"
                        )

                def infer_batch(rows, _cols=cols, _providers=providers):
                    # whole-batch inference: ONE device dispatch per provider
                    # per step batch (MLPredictRunner batching, on-device)
                    import numpy as _np

                    # a changelog input's row kinds ride through inference.
                    # NOTE: a -D row is re-scored independently of the +I it
                    # retracts, and downstream multiset state matches the
                    # pair BY VALUE — the provider must therefore be
                    # deterministic over its features (see the
                    # PredictRuntimeProvider determinism contract in
                    # table/ml.py) or retractions will not cancel
                    outs = [
                        carry_kind({c.output_name: r[c.name] for c in _cols}, r)
                        for r in rows
                    ]
                    for item, provider in _providers:
                        args = item.args or provider.feature_cols
                        feats = _np.asarray(
                            [[float(r[a]) for a in args] for r in rows],
                            dtype=_np.float32,
                        )
                        preds = _np.asarray(provider.predict_batch(feats))
                        single = len(provider.output_names) == 1
                        for i, o in enumerate(outs):
                            if single:
                                o[item.alias or item.output_name] = preds[i, 0].item()
                            else:
                                for j, nm in enumerate(provider.output_names):
                                    o[nm] = preds[i, j].item()
                    return outs

                return stream.map_batch(infer_batch, name="ml_predict")
            def project(row, _cols=cols):
                return carry_kind(
                    {c.output_name: row[c.name] for c in _cols}, row)

            return stream.map(project, name="project")
        if preds:
            raise NotImplementedError(
                "ML_PREDICT inside windowed aggregate queries is not supported; "
                "apply it in a follow-up projection query"
            )
        if q.window is None:
            # continuous (non-windowed) aggregation: emits a retract
            # changelog (GroupAggFunction analogue; table/changelog.py)
            return self._continuous_agg_query(q, stream)
        if not q.group_by:
            raise NotImplementedError(
                "windowed aggregate queries require GROUP BY columns "
                "alongside the TUMBLE/HOP/SESSION window"
            )
        # unknown columns fail at translation with a diagnostic, not as a
        # per-record KeyError from inside the key/value selectors
        known = set(table.schema.fields)
        missing = [c for c in q.group_by if c not in known] + [
            i.name for i in aggs if i.name != "*" and i.name not in known
        ]
        if missing:
            raise ValueError(
                f"unknown column(s) {sorted(set(missing))} in GROUP "
                f"BY/aggregates; table {q.table!r} declares "
                f"{table.schema.fields}")
        return self._grouped_window_query(q, stream)

    def _row_stream(self, table: _Table) -> DataStream:
        """Dict-row view of a table for the interpreted path. Row-mode
        tables pass through; columnar tables get a per-record adapter
        (vector row + batch timestamp -> schema dict, cast through the
        declared field types so both paths emit the same Python values)."""
        if not table.columnar:
            return table.stream
        schema = table.schema
        rowtime = schema.rowtime
        value_fields = [f for f in schema.fields if f != rowtime]
        casts = [schema.py_cast(f) for f in value_fields]

        def to_row(v, ts, _fs=tuple(value_fields), _casts=tuple(casts),
                   _rt=rowtime):
            row = {f: c(v[i]) for i, (f, c) in enumerate(zip(_fs, _casts))}
            if _rt is not None:
                row[_rt] = int(ts)
            return row

        return table.stream.map_with_timestamp(to_row, name="sql_row_view")

    def _continuous_agg_query(self, q: Query, stream: DataStream) -> DataStream:
        """Non-windowed GROUP BY: continuous aggregation over the unbounded
        stream, emitting updates/retractions as the groups evolve — the
        reference's bread-and-butter streaming SQL
        (StreamExecGroupAggregate -> GroupAggFunction.java:33). The result
        is a changelog stream; registering it as a table and aggregating
        again composes (cascading retraction)."""
        aggs = [i for i in q.select if i.kind == "agg"]
        if q.having is not None or q.order_by or q.limit is not None:
            raise NotImplementedError(
                "HAVING/ORDER BY/LIMIT on continuous (non-windowed) "
                "aggregates are not supported; window the query or apply "
                "them downstream"
            )
        if any(i.kind in ("window_start", "window_end") for i in q.select):
            raise ValueError(
                "WINDOW_START/WINDOW_END require a TUMBLE/HOP/SESSION window")
        for i in q.select:
            if i.kind == "column" and i.name not in q.group_by:
                raise ValueError(
                    f"SELECT column {i.name!r} must appear in GROUP BY "
                    "(non-grouped columns are not defined for aggregates)")
        group_cols = list(q.group_by)
        if group_cols:
            key_fn = (
                (lambda row, c=group_cols[0]: row[c])
                if len(group_cols) == 1
                else (lambda row, cs=tuple(group_cols): tuple(row[c] for c in cs))
            )
        else:
            key_fn = lambda row: 0    # noqa: E731 — global aggregate
        specs = [(i.func, None if i.name == "*" else i.name) for i in aggs]
        key_fields = []
        for c in group_cols:
            item = next((i for i in q.select
                         if i.kind == "column" and i.name == c), None)
            key_fields.append(item.output_name if item is not None else c)
        out_names = [i.output_name for i in aggs]
        keyed = stream.key_by(
            key_fn, name=f"group_by[{','.join(group_cols) or 'GLOBAL'}]")
        result = keyed.continuous_aggregate(
            specs, key_fields, out_names, name="sql_group_agg")
        # SQL projection: GROUP BY columns not in the SELECT list must not
        # appear in output rows (the operator needs the full key to name
        # its fields; trim here, keeping the changelog kind — retraction
        # stays sound because -U/-D carry the full PROJECTED row and
        # materialization is multiset-based)
        selected = [i.output_name for i in q.select]
        if any(kf not in selected for kf in key_fields):
            from flink_tpu.table.changelog import carry_kind

            def trim(row, _sel=tuple(selected)):
                return carry_kind({c: row[c] for c in _sel}, row)

            result = result.map(trim, name="sql_group_agg_project")
        return result

    def _grouped_window_query(self, q: Query, stream: DataStream) -> DataStream:
        """Windowed GROUP BY translation shared by SQL and the fluent Table
        API (both lower onto the same DataStream window machinery, like the
        reference's two APIs lowering onto one planner)."""
        aggs = [i for i in q.select if i.kind == "agg"]
        group_cols = list(q.group_by)
        key_fn = (
            (lambda row, c=group_cols[0]: row[c])
            if len(group_cols) == 1
            else (lambda row, cs=tuple(group_cols): tuple(row[c] for c in cs))
        )
        assigner = self._assigner(q)
        keyed = stream.key_by(key_fn, name=f"group_by[{','.join(group_cols)}]")
        windowed = keyed.window(assigner)

        if len(aggs) == 1 and aggs[0].func in _DEVICE_AGG:
            item = aggs[0]
            value_fn = None if item.name == "*" else (
                lambda row, c=item.name: float(row[c])
            )
            result = windowed.aggregate(
                _DEVICE_AGG[item.func], value_fn, name=f"sql_{item.func.lower()}"
            )
            extract = None                # single device agg: result IS rec[1]
        else:
            result = windowed.aggregate(_MultiAgg(aggs), name="sql_multi_agg")
            extract = tuple               # composite accumulator result
        # mark the SQL origin on the interpreted path's window terminal
        # too: the job gauge sqlFusedSelected then reports 0 (SQL ran, but
        # not on the fused runner) instead of being absent
        result.transform.config["sql_origin"] = True
        return self._windowed_output_stage(result, q, group_cols, extract)

    def _windowed_output_stage(self, result: DataStream, q: Query,
                               group_cols: List[str], extract) -> DataStream:
        """Post-window host stage shared VERBATIM by the interpreted path
        and the planner's fused lowering: output-row assembly + HAVING +
        per-window top-N. One implementation is what makes the two paths'
        rows identical by construction (the three-way parity bar)."""
        # assemble output rows: group cols + aggregates + window bounds
        # (emission timestamp = window.maxTimestamp ⇒ end = ts+1,
        # start = end - size; session windows get end-only fidelity).
        # The assembler is CODE-GENERATED as one dict-literal lambda over
        # (rec, ts) — the reference compiles generated Java for exactly
        # this stage; here the closure tier is the codegen target. It runs
        # once per emitted window on the hot fused path, where a generic
        # kind-dispatch loop costs more than the compiled superscan saves
        # (the sql_path bench's ratio_vs_datastream_fused is the gate).
        size_ms = q.window.size_ms
        topn = bool(q.order_by) or q.limit is not None
        single = extract is None          # single device aggregate: rec[1]
        parts = []
        ai = 0
        for item in q.select:
            if item.kind == "column":
                if item.name not in group_cols:
                    # non-grouped columns are undefined for aggregates; a
                    # silent key-value stand-in would be plausibly-shaped
                    # wrong data (the continuous-agg path already refuses)
                    raise ValueError(
                        f"SELECT column {item.name!r} must appear in "
                        "GROUP BY (non-grouped columns are not defined "
                        "for aggregates)")
                expr = ("rec[0]" if len(group_cols) == 1
                        else f"rec[0][{group_cols.index(item.name)}]")
            elif item.kind == "agg":
                expr = "rec[1]" if single else f"_ex(rec[1])[{ai}]"
                ai += 1
            elif item.kind == "window_end":
                expr = "ts + 1"
            elif item.kind == "window_start":
                expr = f"ts + 1 - {size_ms}"
            else:
                continue
            parts.append(f"{item.output_name!r}: {expr}")
        if topn:
            parts.append("'__wend': ts + 1")  # per-window key (internal)
        src = f"lambda rec, ts: {{{', '.join(parts)}}}"
        to_row = eval(src, {"__builtins__": {}, "_ex": extract})  # noqa: S307
        to_row.__sql_codegen__ = src       # introspection/debugging handle

        out = result.map_with_timestamp(to_row, name="sql_output")
        if q.having is not None:
            out = out.filter(q.having, name=f"having[{q.having_text}]")
        if topn:
            # streaming top-N (the reference expresses this as ROW_NUMBER()
            # OVER per window; here ORDER BY/LIMIT rank WITHIN each window).
            # A window's rows all emit in the step its trigger fires, so
            # ranking groups by window inside the step batch — no
            # cross-batch state needed. Vectorized flat_map: N rows in,
            # ranked/cut rows out, timestamps follow the source index.
            known = {i.output_name for i in q.select}
            for col, _desc in q.order_by:
                if col not in known:
                    raise ValueError(
                        f"ORDER BY column {col!r} is not produced by the "
                        f"SELECT list (available: {sorted(known)})"
                    )
            order_by, limit = list(q.order_by), q.limit

            def rank_vec(vals):
                from itertools import groupby

                import numpy as _np

                from flink_tpu.utils.arrays import obj_array

                rows = list(vals)
                by_w = sorted(range(len(rows)),
                              key=lambda i: rows[i]["__wend"])
                out_vals, out_idx = [], []
                for _w, grp in groupby(by_w, key=lambda i: rows[i]["__wend"]):
                    grp = list(grp)
                    for col, desc in reversed(order_by):
                        grp.sort(key=lambda i, c=col: rows[i][c],
                                 reverse=desc)
                    if limit is not None:
                        grp = grp[:limit]
                    for i in grp:
                        r = dict(rows[i])
                        r.pop("__wend", None)
                        out_vals.append(r)
                        out_idx.append(i)
                return obj_array(out_vals), _np.asarray(out_idx,
                                                        dtype=_np.int64)

            # ranking is global per window: pin the rank step to ONE
            # parallel instance (GlobalPartitioner hint) so a sharded plan
            # cannot emit per-shard top-Ns
            out = out.global_().flat_map(rank_vec, name="sql_topn",
                                         vectorized=True)
        return out

    def _join_query(self, q: Query, sql_fused: bool = False) -> DataStream:
        """Windowed equi-join: translated onto DataStream.join (the fused
        DeviceJoinRunner when the planner selected it — `sql_fused` —
        else the host windowed join / coGroup path). Joined rows carry
        both alias-qualified and (side-unique) plain column names; the
        SELECT projects them."""
        j = q.join
        if j.join_type == "full":
            # typed + attributed at translate time, single-sourced with
            # the planner catalog and the runner's own refusal — a FULL
            # OUTER statement must never build a job that dies at runner
            # construction with a bare error
            from flink_tpu.joins.spec import JoinUnsupported

            raise JoinUnsupported(
                "join-full-outer",
                "FULL OUTER JOIN is not supported: neither the host "
                "StreamingJoinRunner nor the device join ring implements "
                "two-sided padding retraction")
        if j.table2 not in self._tables:
            raise KeyError(
                f"unknown table {j.table2!r}; registered: {list(self._tables)}")
        if q.group_by:
            raise ValueError("join queries aggregate via a follow-up query; "
                             "GROUP BY on a join is not supported yet")
        if any(i.kind == "agg" for i in q.select):
            raise ValueError("aggregates over a join are not supported yet")
        if any(i.kind == "ml_predict" for i in q.select):
            raise ValueError("ML_PREDICT over a join is not supported yet")
        if j.window is not None and j.window.kind == "session":
            raise ValueError("session windows are not supported for joins")

        s1 = self._row_stream(self._tables[q.table])
        s2 = self._row_stream(self._tables[j.table2])
        lcol = j.left_col.split(".", 1)[1]
        rcol = j.right_col.split(".", 1)[1]
        cols1 = set(self._tables[q.table].schema.fields)
        cols2 = set(self._tables[j.table2].schema.fields)
        a1, a2 = j.alias1, j.alias2

        def merge(l, r):
            row = {f"{a1}.{k}": v for k, v in l.items()}
            row.update({f"{a2}.{k}": v for k, v in r.items()})
            for k, v in l.items():        # side-unique plain names
                if k not in cols2:
                    row[k] = v
            for k, v in r.items():
                if k not in cols1:
                    row[k] = v
            return row

        if j.window is None:
            # REGULAR streaming join (no window bound): unbounded two-sided
            # state with retraction output (StreamingJoinOperator.java:40)
            from flink_tpu.graph.transformation import Transformation

            t = Transformation(
                "regular_join",
                f"sql_regular_join[{j.left_col}={j.right_col}]",
                [s1.transform, s2.transform],
                {
                    "key_selector1": lambda row, c=lcol: row[c],
                    "key_selector2": lambda row, c=rcol: row[c],
                    "merge_fn": merge,
                    "join_type": j.join_type,
                    # schema-shaped NULL rows so outer-join paddings carry
                    # every field (as SQL NULL) for downstream predicates
                    "null_rows": (dict.fromkeys(cols1), dict.fromkeys(cols2)),
                },
            )
            joined = DataStream(self.env, t)
        else:
            assigner = self._assigner_for(j.window)
            # SQL equi-join: NULL never matches (not even NULL = NULL).
            # The windowed path is inner-only, so NULL-keyed rows can
            # never contribute — filter them before the join buckets
            # them under a shared None key
            s1 = s1.filter(lambda row, c=lcol: row[c] is not None,
                           name="null_key_filter_l")
            s2 = s2.filter(lambda row, c=rcol: row[c] is not None,
                           name="null_key_filter_r")
            joined = (
                s1.join(s2)
                .where(lambda row, c=lcol: row[c])
                .equal_to(lambda row, c=rcol: row[c])
                .window(assigner)
                .apply(merge, name=f"sql_join[{j.left_col}={j.right_col}]")
            )
            if sql_fused:
                joined.transform.config["sql_origin"] = True
        if q.where is not None:
            joined = joined.filter(q.where, name=f"where[{q.where_text}]")
        cols = [i for i in q.select if i.kind == "column"]
        if any(i.kind in ("window_start", "window_end") for i in q.select):
            raise ValueError("WINDOW_START/WINDOW_END are not supported on "
                             "join projections yet")
        from flink_tpu.table.changelog import carry_kind

        def project(row, _cols=cols):
            # .get: an outer join's NULL-padded side reads as None (SQL
            # NULL); the changelog kind rides through the projection
            return carry_kind(
                {i.output_name: row.get(i.name) for i in _cols}, row)

        return joined.map(project, name="sql_join_output")

    def execute_sql_to_list(self, sql: str) -> List[dict]:
        """Convenience: run the query to completion, return rows. A
        changelog result (continuous aggregate / regular join) is
        MATERIALIZED: the retractions are applied and the surviving rows
        returned (the reference's retract-sink view of the stream)."""
        from flink_tpu.table.changelog import ROW_KIND_FIELD, materialize

        rows = self.execute_sql_to_changelog(sql)
        if any(isinstance(r, dict) and ROW_KIND_FIELD in r for r in rows):
            return materialize(rows)
        return rows

    def execute_sql_to_changelog(self, sql: str) -> List[dict]:
        """Run the query to completion and return the RAW emitted rows —
        for changelog queries these carry their row kinds
        (table/changelog.py) in emission order."""
        sink = self.sql_query(sql).collect()
        self.env.execute("sql-query")
        return sink.results

    def _assigner(self, q: Query):
        return self._assigner_for(q.window)

    def _assigner_for(self, w):
        if w.kind == "tumble":
            return TumblingEventTimeWindows.of(w.size_ms)
        if w.kind == "hop":
            return SlidingEventTimeWindows.of(w.size_ms, w.slide_ms)
        if w.kind == "session":
            return EventTimeSessionWindows.with_gap(w.size_ms)
        raise ValueError(w.kind)
