"""Test utilities: operator harnesses (reference:
flink-runtime test util KeyedOneInputStreamOperatorTestHarness.java)."""

from flink_tpu.testing.harness import KeyedWindowOperatorHarness
