"""Operator test harness: drive an operator without any cluster.

Mirrors the reference's workhorse testing pattern
(AbstractStreamOperatorTestHarness.java /
KeyedOneInputStreamOperatorTestHarness.java): push records and watermarks,
inspect emitted output and snapshots. Works for both the oracle operator and
the device-backed operator (duck-typed: process_record / process_watermark /
drain_output / snapshot / restore)."""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple


class KeyedWindowOperatorHarness:
    def __init__(self, operator, key_selector: Callable[[Any], Any] = None,
                 value_selector: Callable[[Any], Any] = None):
        self.op = operator
        self.key_selector = key_selector or (lambda v: v[0])
        self.value_selector = value_selector or (lambda v: v[1])
        self.watermark = None

    def process_element(self, value, timestamp: int) -> None:
        self.op.process_record(self.key_selector(value), self.value_selector(value), timestamp)

    def process_elements(self, *records: Tuple[Any, int]) -> None:
        for value, ts in records:
            self.process_element(value, ts)

    def process_watermark(self, watermark: int) -> None:
        self.watermark = watermark
        self.op.process_watermark(watermark)

    def set_processing_time(self, time: int) -> None:
        self.op.advance_processing_time(time)

    def extract_output(self) -> List[Tuple[Any, Any, Any, int]]:
        """Returns (key, window, result, timestamp) tuples emitted so far."""
        return self.op.drain_output()

    def extract_results(self) -> List[Tuple[Any, Any]]:
        """(key, result) pairs, window/ts dropped."""
        return [(k, r) for k, _w, r, _t in self.extract_output()]

    def side_output(self, tag_id: str) -> List:
        return list(self.op.side_output.get(tag_id, []))

    def snapshot(self) -> dict:
        return self.op.snapshot()

    def restore(self, snap: dict) -> None:
        self.op.restore(snap)
