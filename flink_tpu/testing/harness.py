"""Operator test harness: drive an operator without any cluster.

Mirrors the reference's workhorse testing pattern
(AbstractStreamOperatorTestHarness.java /
KeyedOneInputStreamOperatorTestHarness.java): push records and watermarks,
inspect emitted output and snapshots. Works for both the oracle operator and
the device-backed operator (duck-typed: process_record / process_watermark /
drain_output / snapshot / restore)."""

from __future__ import annotations

import contextlib
import secrets as _secrets
from typing import Any, Callable, List, Optional, Tuple


def ephemeral_transport_security(cluster_id: str = "flink-tpu-test"):
    """A fresh random-secret SecurityConfig for one test cluster, isolated
    from the per-user default secret (two clusters built from separate
    calls cannot authenticate to each other)."""
    from flink_tpu.security.transport import SecurityConfig

    return SecurityConfig.with_secret(_secrets.token_hex(16), cluster_id)


@contextlib.contextmanager
def fault_injection(plan=None, *, rules=None, seed: int = 0):
    """Install a chaos FaultPlan for the duration of the block (the chaos
    scenarios' and tests' entry point — docs/robustness.md). Pass a built
    :class:`flink_tpu.chaos.FaultPlan`, or `rules` (a list of FaultRule
    field dicts) + `seed` to build one. Yields the plan so the body can
    assert `plan.total_fired` / `plan.report()` afterwards; always
    uninstalls, even when the body raises."""
    from flink_tpu.chaos import FaultPlan, install_plan, uninstall_plan

    if plan is None:
        plan = FaultPlan.from_rules(list(rules or []), seed=seed)
    install_plan(plan)
    try:
        yield plan
    finally:
        uninstall_plan()


@contextlib.contextmanager
def transport_security(sec=None):
    """Context manager pinning the PROCESS-DEFAULT SecurityConfig — every
    RpcService/ExchangeServer/OutputChannel/RpcGateway constructed inside
    (without an explicit `security=`) uses `sec`. Tests run with auth on by
    default; this is how a test opts into a known secret or into
    SecurityConfig.disabled() for the legacy wire."""
    from flink_tpu.security.transport import _set_process_default

    sec = ephemeral_transport_security() if sec is None else sec
    prev = _set_process_default(sec)
    try:
        yield sec
    finally:
        _set_process_default(prev)


class KeyedWindowOperatorHarness:
    def __init__(self, operator, key_selector: Callable[[Any], Any] = None,
                 value_selector: Callable[[Any], Any] = None):
        self.op = operator
        self.key_selector = key_selector or (lambda v: v[0])
        self.value_selector = value_selector or (lambda v: v[1])
        self.watermark = None

    def process_element(self, value, timestamp: int) -> None:
        self.op.process_record(self.key_selector(value), self.value_selector(value), timestamp)

    def process_elements(self, *records: Tuple[Any, int]) -> None:
        for value, ts in records:
            self.process_element(value, ts)

    def process_watermark(self, watermark: int) -> None:
        self.watermark = watermark
        self.op.process_watermark(watermark)

    def set_processing_time(self, time: int) -> None:
        self.op.advance_processing_time(time)

    def extract_output(self) -> List[Tuple[Any, Any, Any, int]]:
        """Returns (key, window, result, timestamp) tuples emitted so far."""
        return self.op.drain_output()

    def extract_results(self) -> List[Tuple[Any, Any]]:
        """(key, result) pairs, window/ts dropped."""
        return [(k, r) for k, _w, r, _t in self.extract_output()]

    def side_output(self, tag_id: str) -> List:
        return list(self.op.side_output.get(tag_id, []))

    def snapshot(self) -> dict:
        return self.op.snapshot()

    def restore(self, snap: dict) -> None:
        self.op.restore(snap)


def keyed_window_stream(seed: int, steps: int, batch: int, num_keys: int,
                        with_vals: bool = False, ms_per_batch: float = 400.0,
                        jitter_ms: int = 120, wm_lag_ms: int = 150):
    """Deterministic keyed test stream shared by the sharded-superscan tests
    and the driver dryrun: sorted random timestamps per batch with backward
    jitter strictly below the watermark lag, so late-drop behavior is
    deterministic across operators. Returns (batches, watermarks) where
    batches[t] = (keys, vals|None, ts)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    batches, wms = [], []
    t_cursor = 0.0
    for _ in range(steps):
        keys = rng.integers(0, num_keys, size=batch).astype(np.int32)
        base = t_cursor + np.sort(rng.random(batch)) * ms_per_batch
        ts = np.maximum(
            base.astype(np.int64) - rng.integers(0, jitter_ms, batch), 0)
        vals = (rng.integers(0, 9, size=batch).astype(np.float32)
                if with_vals else None)
        batches.append((keys, vals, ts))
        wms.append(int(base[-1]) - wm_lag_ms)
        t_cursor += ms_per_batch
    return batches, wms
