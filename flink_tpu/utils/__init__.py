"""Shared utilities."""

from flink_tpu.utils.arrays import obj_array
