"""Array helpers."""

from __future__ import annotations

from typing import Sequence

import numpy as np


def obj_array(items: Sequence) -> np.ndarray:
    """1-D object ndarray of arbitrary Python values. (np.asarray(...,
    dtype=object) would build a 2-D array from a list of equal-length
    tuples — records must stay scalar elements.)"""
    arr = np.empty(len(items), dtype=object)
    if len(items):
        arr[:] = list(items)
    return arr
