"""Array helpers."""

from __future__ import annotations

from typing import Sequence

import numpy as np


def jsonable(obj):
    """Best-effort JSON coercion (int dict keys -> str, numpy scalars via
    .item(), unknowns repr'd) — THE coercion both HTTP surfaces
    (runtime/rest.py payloads, the SQL gateway's result cells) apply, so
    a fix to one edge case can never silently miss the other."""
    if isinstance(obj, dict):
        return {str(k): jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonable(v) for v in obj]
    if hasattr(obj, "item"):
        return obj.item()
    if isinstance(obj, (int, float, str, bool)) or obj is None:
        return obj
    return repr(obj)


def obj_array(items: Sequence) -> np.ndarray:
    """1-D object ndarray of arbitrary Python values. (np.asarray(...,
    dtype=object) would build a 2-D array from a list of equal-length
    tuples — records must stay scalar elements.)"""
    arr = np.empty(len(items), dtype=object)
    if len(items):
        arr[:] = list(items)
    return arr


def canonical_column(arr, where: str = "column"):
    """Cast a numeric column to jax's canonical dtype (x64-off: int64→int32,
    float64→float32), refusing LOUDLY when the values don't survive the
    narrowing. Traceable UDFs compute in canonical dtype on EVERY path —
    the fused device program stages canonical buffers, and the host
    fallback casts through here too — so fused-vs-fallback results stay
    identical, and a value that would silently wrap (ints) or overflow to
    inf (floats) is an error, never a silent semantics divergence."""
    from jax import dtypes as _jdt

    arr = np.asarray(arr)
    dt = np.dtype(_jdt.canonicalize_dtype(arr.dtype))
    if dt == arr.dtype:
        return arr
    if arr.size and np.issubdtype(arr.dtype, np.integer):
        info = np.iinfo(dt)
        lo, hi = int(arr.min()), int(arr.max())
        if lo < info.min or hi > info.max:
            raise TypeError(
                f"{where}: values in [{lo}, {hi}] do not fit the backend's "
                f"canonical {dt} (jax x64 is disabled) and would silently "
                "wrap; re-encode the column, enable jax x64, or drop "
                "traceable=True to keep the host chain"
            )
        return arr.astype(dt)
    with np.errstate(over="ignore", invalid="ignore"):
        out = arr.astype(dt)  # overflow checked explicitly below
    if arr.size and np.issubdtype(arr.dtype, np.floating):
        bad = ~np.isfinite(out) & np.isfinite(arr)
        if bad.any():
            raise TypeError(
                f"{where}: {int(bad.sum())} value(s) overflow the backend's "
                f"canonical {dt} (jax x64 is disabled); re-scale the column, "
                "enable jax x64, or drop traceable=True to keep the host "
                "chain"
            )
    return out


def as_device_column(arr):
    """Normalize a numeric column for device staging without copying when
    avoidable: a contiguous numeric ndarray (including the read-only
    `np.frombuffer` views the binary columnar wire decodes into) passes
    through untouched — `jax.device_put` accepts read-only buffers — and
    only a non-contiguous view pays one compaction copy. Object arrays are
    returned unchanged (record-mode data; columnarized downstream)."""
    if not isinstance(arr, np.ndarray) or arr.dtype == object:
        return arr
    if arr.flags.c_contiguous:
        return arr
    return np.ascontiguousarray(arr)
