"""Compatibility shims over moving jax APIs.

`shard_map` has lived in three places across the jax versions this repo
meets in the wild: `jax.experimental.shard_map.shard_map` (<= 0.4.x),
`jax.shard_map` (>= 0.8), with the replication-check kwarg renamed
`check_rep` -> `check_vma` along the way. Importing through this module
gives every caller one spelling (`shard_map`, new-style `check_vma`
kwarg) and a single flag (`HAS_SHARD_MAP`) to gate tests and optional
fan-out paths on builds where neither form exists.
"""

from __future__ import annotations

import inspect

HAS_SHARD_MAP = True

try:  # jax >= 0.8: top-level export, `check_vma` kwarg
    from jax import shard_map as _shard_map
except ImportError:
    try:  # older jax: experimental module, `check_rep` kwarg
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # pragma: no cover - no shard_map at all
        _shard_map = None
        HAS_SHARD_MAP = False


if _shard_map is None:  # pragma: no cover - exercised only on crippled builds
    def shard_map(*_args, **_kwargs):
        raise NotImplementedError(
            "this jax build provides neither jax.shard_map nor "
            "jax.experimental.shard_map; multi-shard execution is unavailable"
        )
else:
    _params = inspect.signature(_shard_map).parameters
    if "check_vma" in _params:
        shard_map = _shard_map
    else:
        def shard_map(*args, **kwargs):
            # translate the new-style kwarg for pre-rename jax
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(*args, **kwargs)
