"""ctypes binding for the native host-runtime library (native/*.cpp).

Builds native/libflink_tpu_native.so on demand with g++ (cached by source
mtime) and exposes typed wrappers. Every caller has a pure-Python/numpy
fallback, so a missing compiler degrades performance, not capability —
the same posture as the reference shipping prebuilt JNI jars.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRCS = [
    os.path.join(_REPO_ROOT, "native", "flink_tpu_native.cpp"),
    os.path.join(_REPO_ROOT, "native", "spill_store.cpp"),
]
_SRC = _SRCS[0]
_LIB = os.path.join(_REPO_ROOT, "native", "libflink_tpu_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", _LIB, *_SRCS],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Loads (building if stale/missing) the native library; None if
    unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if not all(os.path.exists(src) for src in _SRCS):
                _load_failed = True
                return None
            if (
                not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < max(os.path.getmtime(s) for s in _SRCS)
            ):
                if not _build():
                    _load_failed = True
                    return None
            lib = ctypes.CDLL(_LIB)
            _declare(lib)
            _lib = lib
        except OSError:
            _load_failed = True
    return _lib


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.kd_new.restype = c.c_void_p
    lib.kd_new.argtypes = [c.c_int64, c.c_int]
    lib.kd_free.argtypes = [c.c_void_p]
    lib.kd_size.restype = c.c_int64
    lib.kd_size.argtypes = [c.c_void_p]
    lib.kd_lookup_or_insert_i64.restype = c.c_int64
    lib.kd_lookup_or_insert_i64.argtypes = [
        c.c_void_p, c.c_void_p, c.c_int64, c.c_void_p, c.c_void_p,
    ]
    lib.kd_lookup_or_insert_fixed.restype = c.c_int64
    lib.kd_lookup_or_insert_fixed.argtypes = [
        c.c_void_p, c.c_void_p, c.c_int64, c.c_int64, c.c_void_p, c.c_void_p,
    ]
    lib.codec_parse_csv.restype = c.c_int64
    lib.codec_parse_csv.argtypes = [
        c.c_char_p, c.c_int64, c.c_int64, c.c_void_p, c.c_int64, c.c_void_p, c.c_void_p,
    ]
    lib.ring_new.restype = c.c_void_p
    lib.ring_new.argtypes = [c.c_int64, c.c_int64]
    lib.ring_free.argtypes = [c.c_void_p]
    lib.ring_offer.restype = c.c_int
    lib.ring_offer.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.ring_poll.restype = c.c_int64
    lib.ring_poll.argtypes = [c.c_void_p, c.c_void_p, c.c_int64]
    lib.ring_available.restype = c.c_int64
    lib.ring_available.argtypes = [c.c_void_p]
    lib.ring_free_segments.restype = c.c_int64
    lib.ring_free_segments.argtypes = [c.c_void_p]
    lib.ss_create.restype = c.c_void_p
    lib.ss_create.argtypes = [c.c_int64, c.c_char_p]
    lib.ss_free.argtypes = [c.c_void_p]
    lib.ss_mem_entries.restype = c.c_int64
    lib.ss_mem_entries.argtypes = [c.c_void_p]
    lib.ss_num_runs.restype = c.c_int64
    lib.ss_num_runs.argtypes = [c.c_void_p]
    lib.ss_put_batch.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64]
    lib.ss_get_batch.restype = c.c_int64
    lib.ss_get_batch.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64]
    lib.ss_flush.restype = c.c_int64
    lib.ss_flush.argtypes = [c.c_void_p]
    lib.ss_compact.restype = c.c_int64
    lib.ss_compact.argtypes = [c.c_void_p]
    lib.ss_manifest.restype = c.c_int64
    lib.ss_manifest.argtypes = [c.c_void_p, c.c_void_p, c.c_int64]
    lib.ss_gc.restype = c.c_int64
    lib.ss_gc.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.ss_restore.restype = c.c_int64
    lib.ss_restore.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.ss_clear.argtypes = [c.c_void_p]
    lib.ss_set_next_run_id.argtypes = [c.c_void_p, c.c_int64]
    lib.ss_purge_below.restype = c.c_int64
    lib.ss_purge_below.argtypes = [c.c_void_p, c.c_uint64]


class NativeKeyDict:
    """Batch key dictionary over the C++ open-addressing table."""

    def __init__(self, initial_capacity: int = 1 << 12, string_mode: bool = False):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.kd_new(initial_capacity, 1 if string_mode else 0)
        self.string_mode = string_mode

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.kd_free(self._handle)
            self._handle = None

    def __len__(self) -> int:
        return self._lib.kd_size(self._handle)

    def lookup_or_insert_i64(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray, int]:
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        n = len(keys)
        out_ids = np.empty(n, dtype=np.int32)
        out_new = np.empty(n, dtype=np.uint8)
        size = self._lib.kd_lookup_or_insert_i64(
            self._handle,
            keys.ctypes.data_as(ctypes.c_void_p),
            n,
            out_ids.ctypes.data_as(ctypes.c_void_p),
            out_new.ctypes.data_as(ctypes.c_void_p),
        )
        return out_ids, out_new.astype(bool), int(size)

    def lookup_or_insert_bytes(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray, int]:
        """keys: numpy fixed-width bytes array (dtype 'S<w>')."""
        assert keys.dtype.kind == "S", keys.dtype
        keys = np.ascontiguousarray(keys)
        width = keys.dtype.itemsize
        n = len(keys)
        out_ids = np.empty(n, dtype=np.int32)
        out_new = np.empty(n, dtype=np.uint8)
        size = self._lib.kd_lookup_or_insert_fixed(
            self._handle,
            keys.ctypes.data_as(ctypes.c_void_p),
            width,
            n,
            out_ids.ctypes.data_as(ctypes.c_void_p),
            out_new.ctypes.data_as(ctypes.c_void_p),
        )
        return out_ids, out_new.astype(bool), int(size)


def parse_csv(
    data: bytes, max_rows: int, key_width: int = 32
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """C++ CSV fast path: b"key,value,ts\\n"* -> (keys S<w>, values f64,
    timestamps i64, rows)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    out_keys = np.zeros(max_rows, dtype=f"S{key_width}")
    out_vals = np.empty(max_rows, dtype=np.float64)
    out_ts = np.empty(max_rows, dtype=np.int64)
    rows = lib.codec_parse_csv(
        data,
        len(data),
        max_rows,
        out_keys.ctypes.data_as(ctypes.c_void_p),
        key_width,
        out_vals.ctypes.data_as(ctypes.c_void_p),
        out_ts.ctypes.data_as(ctypes.c_void_p),
    )
    return out_keys[:rows], out_vals[:rows], out_ts[:rows], int(rows)


class SegmentRing:
    """Bounded SPSC ring of fixed-size segments (backpressure when full)."""

    def __init__(self, segment_size: int = 32 * 1024, num_segments: int = 64):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.ring_new(segment_size, num_segments)
        self.segment_size = segment_size

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.ring_free(self._handle)
            self._handle = None

    def offer(self, data: bytes) -> bool:
        return bool(self._lib.ring_offer(self._handle, data, len(data)))

    def poll(self) -> Optional[bytes]:
        buf = ctypes.create_string_buffer(self.segment_size)
        n = self._lib.ring_poll(self._handle, buf, self.segment_size)
        if n < 0:
            return None
        return buf.raw[:n]

    def __len__(self) -> int:
        return self._lib.ring_available(self._handle)

    def free_segments(self) -> int:
        return self._lib.ring_free_segments(self._handle)


class NativeSpillStore:
    """Batched u64 -> fixed-width-bytes store over the C++ LSM
    (native/spill_store.cpp); the host spill tier for state beyond HBM."""

    def __init__(self, value_width: int, directory: str):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        os.makedirs(directory, exist_ok=True)
        self._lib = lib
        self.width = value_width
        self.dir = directory
        self._handle = lib.ss_create(value_width, directory.encode())
        # never reuse run ids of files already on disk (old manifests may
        # still reference them)
        max_id = 0
        for name in os.listdir(directory):
            if name.startswith("run-") and name.endswith(".spill"):
                try:
                    max_id = max(max_id, int(name[4:-6]))
                except ValueError:
                    pass
        if max_id:
            lib.ss_set_next_run_id(self._handle, max_id + 1)

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.ss_free(self._handle)
            self._handle = None

    def put_batch(self, keys: np.ndarray, values: np.ndarray) -> None:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        values = np.ascontiguousarray(values)
        assert values.nbytes == len(keys) * self.width
        self._lib.ss_put_batch(
            self._handle, keys.ctypes.data, values.ctypes.data, len(keys)
        )

    def get_batch(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (values uint8[n, width], found bool[n])."""
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        out = np.zeros((len(keys), self.width), dtype=np.uint8)
        found = np.zeros(len(keys), dtype=np.uint8)
        self._lib.ss_get_batch(
            self._handle, keys.ctypes.data, out.ctypes.data, found.ctypes.data, len(keys)
        )
        return out, found.astype(bool)

    def flush(self) -> int:
        rid = self._lib.ss_flush(self._handle)
        if rid < 0:
            raise OSError(f"spill flush failed in {self.dir}")
        return rid

    def compact(self) -> int:
        rid = self._lib.ss_compact(self._handle)
        if rid < 0:
            raise OSError(f"spill compact failed in {self.dir}")
        return rid

    @property
    def mem_entries(self) -> int:
        return self._lib.ss_mem_entries(self._handle)

    @property
    def num_runs(self) -> int:
        return self._lib.ss_num_runs(self._handle)

    def checkpoint(self) -> str:
        """Flush and return the manifest (newline-joined immutable run file
        names) — successive checkpoints share unchanged runs."""
        self.flush()
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.ss_manifest(self._handle, buf, cap)
            if n >= 0:
                return buf.raw[:n].decode()
            cap = -n + 1

    def clear(self) -> None:
        self._lib.ss_clear(self._handle)

    def purge_below(self, threshold: int) -> int:
        """Drop every entry with key < threshold (retention cut). Returns
        entries dropped; raises on I/O error while rewriting a run."""
        n = self._lib.ss_purge_below(self._handle, ctypes.c_uint64(threshold))
        if n < 0:
            raise OSError(f"spill purge failed in {self.dir}")
        return int(n)

    def gc(self, retained_manifests) -> int:
        """Unlink run files referenced by neither the live run list nor any
        retained checkpoint manifest (the shared-state registry's
        unregisterUnusedState analogue). Pass the manifests the checkpoint
        retention window still holds."""
        blob = "\n".join(m for m in retained_manifests if m).encode()
        n = self._lib.ss_gc(self._handle, blob, len(blob))
        if n < 0:
            raise OSError(f"spill gc failed in {self.dir}")
        return int(n)

    def restore(self, manifest: str) -> None:
        """Replace the store's contents with the manifest's runs (rollback)."""
        m = manifest.encode()
        n = self._lib.ss_restore(self._handle, m, len(m))
        if n < 0:
            raise OSError(f"spill restore failed from manifest in {self.dir}")
