// Native host-runtime components for flink_tpu.
//
// The reference keeps its hot host-side structures native: off-heap
// MemorySegments (flink-core .../core/memory/MemorySegment.java:70), the
// Netty buffer pool (NetworkBufferPool.java:63), JNI LSM state stores
// (frocksdbjni / forstjni), and Cython record coders
// (flink-python fn_execution/coder_impl_fast.pyx). This module provides the
// TPU-native equivalents for the host half of the pipeline — everything
// between the wire/file format and the device arrays:
//
//   1. KeyDict     — batch open-addressing key dictionary: raw keys -> dense
//                    device row ids (the host half of keyBy; the device half
//                    is the all-to-all in ops/exchange.py).
//   2. csv codec   — delimited text -> columnar (int64 key-ish column,
//                    double value column, int64 timestamp column).
//   3. SegmentRing — fixed-size-segment SPSC ring: bounded ingest queue
//                    between a producer (network/file thread) and the step
//                    loop; "no free segment" is the backpressure signal
//                    (LocalBufferPool exhaustion analogue).
//
// C ABI only (ctypes binding in flink_tpu/utils/native_bridge.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {

// ===========================================================================
// 1. KeyDict
// ===========================================================================

struct KeyDict {
  // open addressing, power-of-two capacity, linear probing
  std::vector<int64_t> hashes;     // slot -> key hash (or EMPTY)
  std::vector<int32_t> ids;        // slot -> dense id
  std::vector<int64_t> int_keys;   // id -> int key (int mode)
  std::vector<int64_t> str_off;    // id -> offset into arena (str mode)
  std::vector<int32_t> str_len;    // id -> length
  std::vector<char> arena;         // string storage
  int64_t mask = 0;
  int64_t size = 0;
  bool string_mode = false;
};

static const int64_t EMPTY = INT64_MIN;

static inline uint64_t mix64(uint64_t x) {
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33;
  x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33;
  return x;
}

static inline uint64_t hash_bytes(const char* data, int64_t len) {
  // FNV-1a 64 then mixed; sufficient dispersion for a dictionary
  uint64_t h = 1469598103934665603ULL;
  for (int64_t i = 0; i < len; i++) {
    h ^= (unsigned char)data[i];
    h *= 1099511628211ULL;
  }
  return mix64(h);
}

static void kd_rehash(KeyDict* kd, int64_t new_cap) {
  std::vector<int64_t> old_hashes = std::move(kd->hashes);
  std::vector<int32_t> old_ids = std::move(kd->ids);
  kd->hashes.assign(new_cap, EMPTY);
  kd->ids.assign(new_cap, -1);
  kd->mask = new_cap - 1;
  for (size_t i = 0; i < old_hashes.size(); i++) {
    if (old_hashes[i] == EMPTY) continue;
    uint64_t slot = (uint64_t)old_hashes[i] & kd->mask;
    while (kd->hashes[slot] != EMPTY) slot = (slot + 1) & kd->mask;
    kd->hashes[slot] = old_hashes[i];
    kd->ids[slot] = old_ids[i];
  }
}

KeyDict* kd_new(int64_t initial_capacity, int string_mode) {
  KeyDict* kd = new KeyDict();
  int64_t cap = 64;
  while (cap < initial_capacity * 2) cap <<= 1;
  kd->hashes.assign(cap, EMPTY);
  kd->ids.assign(cap, -1);
  kd->mask = cap - 1;
  kd->string_mode = string_mode != 0;
  return kd;
}

void kd_free(KeyDict* kd) { delete kd; }

int64_t kd_size(KeyDict* kd) { return kd->size; }

static inline void kd_maybe_grow(KeyDict* kd) {
  if (kd->size * 10 >= (kd->mask + 1) * 7) kd_rehash(kd, (kd->mask + 1) * 2);
}

// int64 keys -> dense ids; out_new[i]=1 when the key was first seen in this
// call (caller appends those keys to its id->key list in lane order).
int64_t kd_lookup_or_insert_i64(KeyDict* kd, const int64_t* keys, int64_t n,
                                int32_t* out_ids, uint8_t* out_new) {
  for (int64_t i = 0; i < n; i++) {
    kd_maybe_grow(kd);
    int64_t h = (int64_t)mix64((uint64_t)keys[i]);
    if (h == EMPTY) h = 0;
    uint64_t slot = (uint64_t)h & kd->mask;
    for (;;) {
      if (kd->hashes[slot] == EMPTY) {
        kd->hashes[slot] = h;
        int32_t id = (int32_t)kd->size++;
        kd->ids[slot] = id;
        kd->int_keys.push_back(keys[i]);
        out_ids[i] = id;
        out_new[i] = 1;
        break;
      }
      if (kd->hashes[slot] == h && kd->int_keys[kd->ids[slot]] == keys[i]) {
        out_ids[i] = kd->ids[slot];
        out_new[i] = 0;
        break;
      }
      slot = (slot + 1) & kd->mask;
    }
  }
  return kd->size;
}

// fixed-width (numpy 'S<w>') byte keys; trailing NULs are part of the key
// (numpy pads consistently, so equality is well-defined).
int64_t kd_lookup_or_insert_fixed(KeyDict* kd, const char* data, int64_t width,
                                  int64_t n, int32_t* out_ids, uint8_t* out_new) {
  for (int64_t i = 0; i < n; i++) {
    kd_maybe_grow(kd);
    const char* key = data + i * width;
    int64_t h = (int64_t)hash_bytes(key, width);
    if (h == EMPTY) h = 0;
    uint64_t slot = (uint64_t)h & kd->mask;
    for (;;) {
      if (kd->hashes[slot] == EMPTY) {
        kd->hashes[slot] = h;
        int32_t id = (int32_t)kd->size++;
        kd->ids[slot] = id;
        kd->str_off.push_back((int64_t)kd->arena.size());
        kd->str_len.push_back((int32_t)width);
        kd->arena.insert(kd->arena.end(), key, key + width);
        out_ids[i] = id;
        out_new[i] = 1;
        break;
      }
      int32_t id = kd->ids[slot];
      if (kd->hashes[slot] == h && kd->str_len[id] == (int32_t)width &&
          memcmp(kd->arena.data() + kd->str_off[id], key, width) == 0) {
        out_ids[i] = id;
        out_new[i] = 0;
        break;
      }
      slot = (slot + 1) & kd->mask;
    }
  }
  return kd->size;
}

// ===========================================================================
// 2. CSV codec: "key,value,timestamp\n" lines -> columns
// ===========================================================================

// Parses up to max_rows lines; returns rows parsed. key column is written as
// fixed-width bytes (width = key_width, truncated/padded).
int64_t codec_parse_csv(const char* data, int64_t len, int64_t max_rows,
                        char* out_keys, int64_t key_width, double* out_values,
                        int64_t* out_timestamps) {
  int64_t row = 0;
  int64_t pos = 0;
  while (pos < len && row < max_rows) {
    // field 1: key
    int64_t start = pos;
    while (pos < len && data[pos] != ',' && data[pos] != '\n') pos++;
    if (pos >= len || data[pos] != ',') {  // malformed: skip line
      while (pos < len && data[pos] != '\n') pos++;
      pos++;
      continue;
    }
    int64_t klen = pos - start;
    if (klen > key_width) klen = key_width;
    memcpy(out_keys + row * key_width, data + start, klen);
    if (klen < key_width) memset(out_keys + row * key_width + klen, 0, key_width - klen);
    pos++;  // skip comma

    // field 2: value (double)
    char* endp = nullptr;
    out_values[row] = strtod(data + pos, &endp);
    pos = endp - data;
    if (pos < len && data[pos] == ',') {
      pos++;
      out_timestamps[row] = strtoll(data + pos, &endp, 10);
      pos = endp - data;
    } else {
      out_timestamps[row] = 0;
    }
    while (pos < len && data[pos] != '\n') pos++;
    pos++;  // skip newline
    row++;
  }
  return row;
}

// ===========================================================================
// 3. SegmentRing: bounded SPSC queue of fixed-size segments
// ===========================================================================

struct SegmentRing {
  char* memory;
  int64_t segment_size;
  int64_t num_segments;
  std::vector<int64_t> lengths;  // payload length per segment
  volatile int64_t head = 0;     // consumer cursor
  volatile int64_t tail = 0;     // producer cursor
};

SegmentRing* ring_new(int64_t segment_size, int64_t num_segments) {
  SegmentRing* r = new SegmentRing();
  r->memory = (char*)malloc(segment_size * num_segments);
  r->segment_size = segment_size;
  r->num_segments = num_segments;
  r->lengths.assign(num_segments, 0);
  return r;
}

void ring_free(SegmentRing* r) {
  free(r->memory);
  delete r;
}

// returns 1 on success, 0 when full (backpressure: producer must wait)
int ring_offer(SegmentRing* r, const char* data, int64_t len) {
  if (len > r->segment_size) return 0;
  if (r->tail - r->head >= r->num_segments) return 0;  // full
  int64_t slot = r->tail % r->num_segments;
  memcpy(r->memory + slot * r->segment_size, data, len);
  r->lengths[slot] = len;
  __sync_synchronize();
  r->tail++;
  return 1;
}

// returns payload length (>0), or -1 when empty
int64_t ring_poll(SegmentRing* r, char* out, int64_t out_cap) {
  if (r->head >= r->tail) return -1;
  int64_t slot = r->head % r->num_segments;
  int64_t len = r->lengths[slot];
  if (len > out_cap) return -2;
  memcpy(out, r->memory + slot * r->segment_size, len);
  __sync_synchronize();
  r->head++;
  return len;
}

int64_t ring_available(SegmentRing* r) { return r->tail - r->head; }
int64_t ring_free_segments(SegmentRing* r) { return r->num_segments - (r->tail - r->head); }

}  // extern "C"
