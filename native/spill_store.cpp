// Host-side spill store: log-structured u64 -> fixed-width-value state,
// batched API, immutable sorted runs with incremental checkpoints.
//
// The TPU-native counterpart of the reference's native state backends
// (frocksdbjni: flink-statebackend-rocksdb, loaded via JNI; ForSt:
// flink-statebackend-forst/.../ForStStateBackend.java:936): keyed state
// that exceeds HBM spills here. Mirrors their architecture at the scale
// this framework needs:
//  - memtable (open addressing) in front of immutable sorted runs on disk
//    (the LSM shape; runs ~ SST files),
//  - batched multi-get/multi-put (ForStGeneralMultiGetOperation.java's
//    batching is the access pattern the device pipeline wants),
//  - per-run min/max + bloom filters to skip runs on lookup,
//  - checkpoints = flush + manifest of immutable run files, so successive
//    checkpoints share unchanged runs (RocksIncrementalSnapshotStrategy.java:71
//    shared-file dedup),
//  - full compaction folding all runs into one (newest wins).
//
// Values are fixed width per store (columnar accumulator rows); keys are
// u64 (callers densify via KeyDict and fold namespaces in).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <set>
#include <string>
#include <vector>

#include <dirent.h>
#include <unistd.h>

extern "C" {

static inline uint64_t mix64(uint64_t x) {
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33;
  x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33;
  return x;
}

struct Run {
  std::string path;
  std::vector<uint64_t> keys;   // sorted
  std::vector<char> values;     // keys.size() * width
  std::vector<uint64_t> bloom;  // bitset
  uint64_t min_key = 0, max_key = 0;
};

struct SpillStore {
  int64_t width;
  std::string dir;
  uint64_t next_run_id = 1;
  // memtable: open addressing keys -> index into mem_vals
  std::vector<uint64_t> slots;      // key+1, 0 = empty
  std::vector<int64_t> slot_idx;
  std::vector<uint64_t> mem_keys;
  std::vector<char> mem_vals;
  std::vector<Run*> runs;           // oldest .. newest
};

static void st_rehash(SpillStore* st, size_t cap) {
  std::vector<uint64_t> slots(cap, 0);
  std::vector<int64_t> idx(cap, -1);
  size_t mask = cap - 1;
  for (size_t i = 0; i < st->mem_keys.size(); i++) {
    uint64_t k = st->mem_keys[i];
    size_t p = mix64(k) & mask;
    while (slots[p] != 0) p = (p + 1) & mask;
    slots[p] = k + 1;
    idx[p] = (int64_t)i;
  }
  st->slots.swap(slots);
  st->slot_idx.swap(idx);
}

SpillStore* ss_create(int64_t width, const char* dir) {
  auto* st = new SpillStore();
  st->width = width;
  st->dir = dir;
  st->slots.assign(1024, 0);
  st->slot_idx.assign(1024, -1);
  return st;
}

void ss_free(SpillStore* st) {
  for (auto* r : st->runs) delete r;
  delete st;
}

// Callers opening a pre-existing directory set this past the largest run id
// on disk so new flushes never clobber files referenced by old manifests.
void ss_set_next_run_id(SpillStore* st, int64_t id) {
  if ((uint64_t)id > st->next_run_id) st->next_run_id = (uint64_t)id;
}

int64_t ss_mem_entries(SpillStore* st) { return (int64_t)st->mem_keys.size(); }
int64_t ss_num_runs(SpillStore* st) { return (int64_t)st->runs.size(); }

void ss_put_batch(SpillStore* st, const uint64_t* keys, const char* vals, int64_t n) {
  size_t need = st->mem_keys.size() + (size_t)n;
  if (need * 2 >= st->slots.size()) {
    size_t cap = st->slots.size();
    while (need * 2 >= cap) cap *= 2;
    st_rehash(st, cap);
  }
  size_t mask = st->slots.size() - 1;
  for (int64_t i = 0; i < n; i++) {
    uint64_t k = keys[i];
    size_t p = mix64(k) & mask;
    while (true) {
      if (st->slots[p] == 0) {
        st->slots[p] = k + 1;
        st->slot_idx[p] = (int64_t)st->mem_keys.size();
        st->mem_keys.push_back(k);
        st->mem_vals.insert(st->mem_vals.end(), vals + i * st->width,
                            vals + (i + 1) * st->width);
        break;
      }
      if (st->slots[p] == k + 1) {  // overwrite in place
        std::memcpy(&st->mem_vals[st->slot_idx[p] * st->width],
                    vals + i * st->width, st->width);
        break;
      }
      p = (p + 1) & mask;
    }
  }
}

static bool run_get(const Run* r, uint64_t k, int64_t width, char* out) {
  if (r->keys.empty() || k < r->min_key || k > r->max_key) return false;
  uint64_t h = mix64(k);
  size_t nbits = r->bloom.size() * 64;
  if (nbits) {
    if (!(r->bloom[(h % nbits) >> 6] & (1ULL << ((h % nbits) & 63)))) return false;
    uint64_t h2 = mix64(h);
    if (!(r->bloom[(h2 % nbits) >> 6] & (1ULL << ((h2 % nbits) & 63)))) return false;
  }
  auto it = std::lower_bound(r->keys.begin(), r->keys.end(), k);
  if (it == r->keys.end() || *it != k) return false;
  size_t i = (size_t)(it - r->keys.begin());
  std::memcpy(out, &r->values[i * width], width);
  return true;
}

int64_t ss_get_batch(SpillStore* st, const uint64_t* keys, char* out,
                     uint8_t* found, int64_t n) {
  size_t mask = st->slots.size() - 1;
  int64_t hits = 0;
  for (int64_t i = 0; i < n; i++) {
    uint64_t k = keys[i];
    found[i] = 0;
    size_t p = mix64(k) & mask;
    while (st->slots[p] != 0) {
      if (st->slots[p] == k + 1) {
        std::memcpy(out + i * st->width, &st->mem_vals[st->slot_idx[p] * st->width],
                    st->width);
        found[i] = 1;
        hits++;
        break;
      }
      p = (p + 1) & mask;
    }
    if (found[i]) continue;
    for (auto it = st->runs.rbegin(); it != st->runs.rend(); ++it) {  // newest first
      if (run_get(*it, k, st->width, out + i * st->width)) {
        found[i] = 1;
        hits++;
        break;
      }
    }
  }
  return hits;
}

static void build_bloom(Run* r) {
  size_t nbits = std::max<size_t>(64, r->keys.size() * 10);
  r->bloom.assign((nbits + 63) / 64, 0);
  nbits = r->bloom.size() * 64;
  for (uint64_t k : r->keys) {
    uint64_t h = mix64(k), h2 = mix64(h);
    r->bloom[(h % nbits) >> 6] |= 1ULL << ((h % nbits) & 63);
    r->bloom[(h2 % nbits) >> 6] |= 1ULL << ((h2 % nbits) & 63);
  }
}

static bool write_run(SpillStore* st, Run* r) {
  FILE* f = std::fopen(r->path.c_str(), "wb");
  if (!f) return false;
  uint64_t n = r->keys.size(), w = (uint64_t)st->width;
  bool ok = std::fwrite(&n, 8, 1, f) == 1 && std::fwrite(&w, 8, 1, f) == 1;
  if (ok && n) {
    ok = std::fwrite(r->keys.data(), 8, n, f) == n &&
         std::fwrite(r->values.data(), 1, n * w, f) == n * w;
  }
  std::fclose(f);
  return ok;
}

// Flush the memtable into a new immutable sorted run. Returns run id, 0 if
// empty, -1 on I/O error.
int64_t ss_flush(SpillStore* st) {
  if (st->mem_keys.empty()) return 0;
  auto* r = new Run();
  uint64_t id = st->next_run_id++;
  r->path = st->dir + "/run-" + std::to_string(id) + ".spill";
  std::vector<size_t> order(st->mem_keys.size());
  for (size_t i = 0; i < order.size(); i++) order[i] = i;
  std::sort(order.begin(), order.end(), [&](size_t a, size_t b) {
    return st->mem_keys[a] < st->mem_keys[b];
  });
  r->keys.reserve(order.size());
  r->values.reserve(order.size() * st->width);
  for (size_t i : order) {
    r->keys.push_back(st->mem_keys[i]);
    r->values.insert(r->values.end(), &st->mem_vals[i * st->width],
                     &st->mem_vals[(i + 1) * st->width]);
  }
  r->min_key = r->keys.front();
  r->max_key = r->keys.back();
  build_bloom(r);
  if (!write_run(st, r)) {
    delete r;
    st->next_run_id--;
    return -1;
  }
  st->runs.push_back(r);
  st->mem_keys.clear();
  st->mem_vals.clear();
  st_rehash(st, 1024);
  return (int64_t)id;
}

// Fold all runs + memtable into one new run (newest value wins).
int64_t ss_compact(SpillStore* st) {
  ss_flush(st);
  if (st->runs.size() <= 1) return 0;
  auto* merged = new Run();
  uint64_t id = st->next_run_id++;
  merged->path = st->dir + "/run-" + std::to_string(id) + ".spill";
  // collect newest-first, keep first occurrence of each key
  std::vector<std::pair<uint64_t, const char*>> entries;
  for (auto it = st->runs.rbegin(); it != st->runs.rend(); ++it) {
    Run* r = *it;
    for (size_t i = 0; i < r->keys.size(); i++) {
      entries.emplace_back(r->keys[i], &r->values[i * st->width]);
    }
  }
  std::stable_sort(entries.begin(), entries.end(),
                   [](const auto& a, const auto& b) { return a.first < b.first; });
  for (size_t i = 0; i < entries.size(); i++) {
    if (i > 0 && entries[i].first == merged->keys.back()) continue;  // newer kept
    merged->keys.push_back(entries[i].first);
    merged->values.insert(merged->values.end(), entries[i].second,
                          entries[i].second + st->width);
  }
  if (merged->keys.empty()) {
    delete merged;
    st->next_run_id--;
    return 0;
  }
  merged->min_key = merged->keys.front();
  merged->max_key = merged->keys.back();
  build_bloom(merged);
  if (!write_run(st, merged)) {
    delete merged;
    st->next_run_id--;
    return -1;
  }
  for (auto* r : st->runs) delete r;   // files stay on disk for old manifests
  st->runs.clear();
  st->runs.push_back(merged);
  return (int64_t)id;
}

// Delete every entry with key < threshold (the retention cut: callers fold
// an absolute slice into the key's high bits, so an advancing watermark
// frontier maps to a monotone key threshold). Whole runs strictly below the
// threshold drop from the index; partially-below runs are rewritten as new
// filtered run files. Old files stay on disk for manifests that still
// reference them. Returns entries dropped, or -1 on I/O error.
int64_t ss_purge_below(SpillStore* st, uint64_t threshold) {
  int64_t dropped = 0;
  if (!st->mem_keys.empty()) {
    std::vector<uint64_t> keys;
    std::vector<char> vals;
    keys.reserve(st->mem_keys.size());
    vals.reserve(st->mem_vals.size());
    for (size_t i = 0; i < st->mem_keys.size(); i++) {
      if (st->mem_keys[i] >= threshold) {
        keys.push_back(st->mem_keys[i]);
        vals.insert(vals.end(), &st->mem_vals[i * st->width],
                    &st->mem_vals[(i + 1) * st->width]);
      } else {
        dropped++;
      }
    }
    if (keys.size() != st->mem_keys.size()) {
      st->mem_keys.swap(keys);
      st->mem_vals.swap(vals);
      size_t cap = 1024;
      while (st->mem_keys.size() * 2 >= cap) cap *= 2;
      st_rehash(st, cap);
    }
  }
  std::vector<Run*> kept;
  bool io_error = false;
  for (auto* r : st->runs) {
    if (r->keys.empty() || r->max_key < threshold) {
      dropped += (int64_t)r->keys.size();
      delete r;  // file stays on disk for old manifests
      continue;
    }
    if (r->min_key >= threshold) {
      kept.push_back(r);
      continue;
    }
    auto it = std::lower_bound(r->keys.begin(), r->keys.end(), threshold);
    size_t cut = (size_t)(it - r->keys.begin());
    auto* nr = new Run();
    uint64_t id = st->next_run_id++;
    nr->path = st->dir + "/run-" + std::to_string(id) + ".spill";
    nr->keys.assign(r->keys.begin() + cut, r->keys.end());
    nr->values.assign(r->values.begin() + (long)(cut * st->width), r->values.end());
    nr->min_key = nr->keys.front();
    nr->max_key = nr->keys.back();
    build_bloom(nr);
    if (!write_run(st, nr)) {
      delete nr;
      st->next_run_id--;
      kept.push_back(r);  // keep unfiltered data rather than lose it
      io_error = true;
      continue;
    }
    dropped += (int64_t)cut;
    delete r;
    kept.push_back(nr);
  }
  st->runs.swap(kept);
  return io_error ? -1 : dropped;
}

// Write the current run list into `out` as \n-joined ids (after a flush this
// fully describes the store — the checkpoint manifest).
int64_t ss_manifest(SpillStore* st, char* out, int64_t cap) {
  std::string m;
  for (auto* r : st->runs) {
    size_t slash = r->path.rfind('/');
    m += r->path.substr(slash + 1);
    m += "\n";
  }
  if ((int64_t)m.size() > cap) return -(int64_t)m.size();
  std::memcpy(out, m.data(), m.size());
  return (int64_t)m.size();
}

// Drop all in-memory state (memtable + run index); run files stay on disk
// for manifests that still reference them.
void ss_clear(SpillStore* st) {
  st->mem_keys.clear();
  st->mem_vals.clear();
  st_rehash(st, 1024);
  for (auto* r : st->runs) delete r;
  st->runs.clear();
}

// Load runs (oldest..newest order of the manifest) from disk, REPLACING the
// store's current contents (restore is a rollback, not a merge).
int64_t ss_restore(SpillStore* st, const char* manifest, int64_t len) {
  ss_clear(st);
  std::string m(manifest, (size_t)len);
  size_t pos = 0;
  while (pos < m.size()) {
    size_t nl = m.find('\n', pos);
    if (nl == std::string::npos) nl = m.size();
    std::string name = m.substr(pos, nl - pos);
    pos = nl + 1;
    if (name.empty()) continue;
    auto* r = new Run();
    r->path = st->dir + "/" + name;
    FILE* f = std::fopen(r->path.c_str(), "rb");
    if (!f) {
      delete r;
      return -1;
    }
    uint64_t n = 0, w = 0;
    if (std::fread(&n, 8, 1, f) != 1 || std::fread(&w, 8, 1, f) != 1 ||
        (int64_t)w != st->width) {
      std::fclose(f);
      delete r;
      return -1;
    }
    r->keys.resize(n);
    r->values.resize(n * w);
    if (n && (std::fread(r->keys.data(), 8, n, f) != n ||
              std::fread(r->values.data(), 1, n * w, f) != n * w)) {
      std::fclose(f);
      delete r;
      return -1;
    }
    std::fclose(f);
    if (n) {
      r->min_key = r->keys.front();
      r->max_key = r->keys.back();
    }
    build_bloom(r);
    // track max run id so new flushes don't collide with restored files
    size_t dash = name.find('-');
    size_t dot = name.find('.');
    if (dash != std::string::npos && dot != std::string::npos) {
      uint64_t id = std::stoull(name.substr(dash + 1, dot - dash - 1));
      if (id >= st->next_run_id) st->next_run_id = id + 1;
    }
    st->runs.push_back(r);
  }
  return (int64_t)st->runs.size();
}

// Garbage-collect superseded run files. Compaction/purge rewrite runs but
// must leave old files on disk while earlier checkpoint manifests still
// reference them; once the checkpoint coordinator's retention window moves
// past those manifests the files are unreachable garbage (the analogue of
// RocksDB's shared-state registry discarding unreferenced SSTs,
// SharedStateRegistryImpl.unregisterUnusedState). `retained` is the
// \n-joined union of run ids referenced by every RETAINED manifest; the
// live run list is always kept. Returns files unlinked, -1 on error.
int64_t ss_gc(SpillStore* st, const char* retained, int64_t len) {
  std::set<std::string> keep;
  std::string cur;
  for (int64_t i = 0; i < len; i++) {
    char c = retained[i];
    if (c == '\n') {
      if (!cur.empty()) keep.insert(cur);
      cur.clear();
    } else {
      cur.push_back(c);
    }
  }
  if (!cur.empty()) keep.insert(cur);
  for (auto* r : st->runs) {
    // live runs: manifests store file names, so keep the basename
    size_t slash = r->path.rfind('/');
    keep.insert(slash == std::string::npos ? r->path
                                           : r->path.substr(slash + 1));
  }
  DIR* d = opendir(st->dir.c_str());
  if (!d) return -1;
  int64_t deleted = 0;
  bool err = false;
  struct dirent* e;
  while ((e = readdir(d)) != nullptr) {
    std::string name(e->d_name);
    if (name.size() < 10 || name.compare(0, 4, "run-") != 0) continue;
    size_t dot = name.rfind(".spill");
    if (dot == std::string::npos || dot + 6 != name.size()) continue;
    if (keep.count(name)) continue;
    std::string full = st->dir + "/" + name;
    if (::unlink(full.c_str()) == 0) {
      deleted++;
    } else {
      err = true;
    }
  }
  closedir(d);
  return err ? -1 : deleted;
}

}  // extern "C"
