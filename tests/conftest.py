"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding logic is validated on
a forced 8-device CPU platform (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Must run before any `import jax` in test modules.
"""

import os

# Force-override: the environment presets JAX_PLATFORMS=axon (real TPU via a
# single-client relay); tests must not claim the chip.
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_COMPILATION_CACHE", "false")

# The axon PJRT plugin is registered by a sitecustomize hook that imports jax
# before pytest starts, so the env vars above are too late for jax's config:
# update the live config and drop the axon factory before any backend init
# (its single-client relay hangs when probed from a second process).
import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

jax.config.update("jax_platforms", "cpu")
_xb._backend_factories.pop("axon", None)
_xb._topology_factories.pop("axon", None)

