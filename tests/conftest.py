"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding logic is validated on
a forced 8-device CPU platform (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Must run before any `import jax` in test modules.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_COMPILATION_CACHE", "false")
