"""Deliberately-violating fixture: bare pickle on a network plane (WIRE001)."""

import pickle


def decode(payload: bytes):
    return pickle.loads(payload)
