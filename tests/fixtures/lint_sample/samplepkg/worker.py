"""Deliberately-violating fixture: unnamed thread (CONC004)."""

import threading


def spawn(fn):
    threading.Thread(target=fn).start()
