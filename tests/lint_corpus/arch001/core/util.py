# SEEDED: core layer imports the runtime at module level
from arch001.runtime import executor


def run(job):
    return executor.run_local(job)
