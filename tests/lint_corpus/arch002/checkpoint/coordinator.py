def restore_and_run(path):
    # SEEDED: even a lazy runtime import inverts the dependency
    from arch002.runtime.executor import run_local

    return run_local(path)
