import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def add(self, n):
        with self._lock:
            self._count += n

    def reset(self):
        # SEEDED: bare write races add()'s locked write
        self._count = 0
