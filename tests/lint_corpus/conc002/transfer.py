import threading


class Transfer:
    def __init__(self):
        self._accounts = threading.Lock()
        self._journal = threading.Lock()

    def debit(self):
        with self._accounts:
            with self._journal:
                return 1

    def audit(self):
        # SEEDED: opposite acquisition order -> static lock-order cycle
        with self._journal:
            with self._accounts:
                return 2
