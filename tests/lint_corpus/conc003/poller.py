import threading
import time


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._last = 0.0

    def poll(self):
        with self._lock:
            # SEEDED: every contender waits out the full sleep
            time.sleep(0.1)
            self._last = time.time()
