import threading


def spawn(fn):
    # SEEDED: unnamed, implicitly non-daemon thread
    t = threading.Thread(target=fn)
    t.start()
    return t
