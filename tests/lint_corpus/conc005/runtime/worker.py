def best_effort_ping(sock):
    try:
        sock.send(b"ping")
    except Exception:
        # SEEDED: silent blanket swallow on the runtime plane
        pass
