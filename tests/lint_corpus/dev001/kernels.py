import jax
import jax.numpy as jnp


@jax.jit
def bad_mean(x):
    total = jnp.sum(x)
    # SEEDED: .item() forces a device->host readback per call
    return total.item()
