import jax


def run_epoch(batches, fn):
    outs = []
    for batch in batches:
        # SEEDED: a fresh compiled callable per iteration
        step = jax.jit(fn)
        outs.append(step(batch))
    return outs
