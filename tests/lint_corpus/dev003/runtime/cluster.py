# SEEDED: control-plane module imports jax at module level
import jax


def plan_slots(n):
    return jax.device_count() + n
