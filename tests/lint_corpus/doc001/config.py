class ConfigOptions:
    @staticmethod
    def key(name):
        return name


# SEEDED: no docs/configuration.md exists next to this corpus package
UNDOCUMENTED = ConfigOptions.key("corpus.undocumented.option")
