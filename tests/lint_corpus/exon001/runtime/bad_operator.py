"""Corpus twin of FusedWindowOperator with the drain call removed."""
from collections import deque

from flink_tpu.lint.contracts import inflight_ring


@inflight_ring("_inflight", drained_by="_resolve_inflight")
class BadFusedOperator:
    def __init__(self):
        self._inflight = deque()
        self._state = {}

    def dispatch(self, batch):
        self._inflight.append(batch)

    def _resolve_inflight(self):
        while self._inflight:
            self._state.update(self._inflight.popleft())

    def flush_all(self):
        # SEEDED MUTATION: the real operator calls self._resolve_inflight()
        # here; without it the snapshot captures a cut that silently drops
        # everything still in the dispatch ring
        return dict(self._state)

    def snapshot(self):
        self.flush_all()
        return dict(self._state)


class UndeclaredOperator:
    """Captures checkpoint state while owning an UNDECLARED in-flight
    container — the analyzer cannot verify what was never declared."""

    def __init__(self):
        self._pending = []
        self._state = {}

    def enqueue(self, item):
        self._pending.append(item)

    def snapshot(self):
        return dict(self._state)
