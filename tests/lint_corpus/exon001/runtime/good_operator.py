"""Control twins: the same operators with the contract intact."""
from collections import deque

from flink_tpu.lint.contracts import inflight_ring


@inflight_ring("_inflight", drained_by="_resolve_inflight")
class GoodFusedOperator:
    """snapshot -> flush_all -> _resolve_inflight: the drain is reached
    through the self-call chain, not directly — proves the
    interprocedural composition, not just lexical matching."""

    def __init__(self):
        self._inflight = deque()
        self._state = {}
        self._future_batches = []      # held records: legally RIDE the cut

    def dispatch(self, batch):
        self._inflight.append(batch)

    def _resolve_inflight(self):
        while self._inflight:
            self._state.update(self._inflight.popleft())

    def flush_all(self):
        self._resolve_inflight()
        return dict(self._state)

    def snapshot(self):
        self.flush_all()
        return dict(self._state)


@inflight_ring("_pending", drained_by="_resolve_pending")
class GoodGuardedOperator:
    """`if self._pending: drain()` — a guard that tests only the ring
    itself still dominates the capture."""

    def __init__(self):
        self._pending = []
        self._state = {}

    def enqueue(self, item):
        self._pending.append(item)

    def _resolve_pending(self):
        for item in self._pending:
            self._state.update(item)
        self._pending.clear()

    def snapshot(self):
        if self._pending:
            self._resolve_pending()
        return dict(self._state)
