"""Corpus twins of the PR-17 donation bug: a jit option input missing
from the executable cache key."""
import functools

import jax

_BACKEND = "cpu"


def _kernel(x):
    return x


@functools.lru_cache(maxsize=None)
def build_step(shape, dtype):
    # SEEDED MUTATION: _BACKEND flows into jit options but is not a
    # parameter — the functools cache key cannot see a backend flip
    return jax.jit(_kernel, backend=_BACKEND, static_argnums=(0,))


class BadStepCache:
    def __init__(self, donate):
        self._donate = donate
        self._cache = {}

    def get(self, fn, shape, dtype):
        # SEEDED MUTATION: key omits self._donate, which selects the
        # donation calling convention — a flip serves an executable that
        # frees (or fails to free) the wrong buffers
        key = (shape, dtype)
        if key in self._cache:
            return self._cache[key]
        step = jax.jit(fn, donate_argnums=(0,) if self._donate else ())
        self._cache[key] = step
        return step
