"""Control twins: every jit option input appears in the cache key."""
import functools

import jax


def _kernel(x):
    return x


@functools.lru_cache(maxsize=None)
def build_step_good(shape, dtype, backend):
    return jax.jit(_kernel, backend=backend, static_argnums=(0,))


class GoodStepCache:
    def __init__(self, donate):
        self._donate = donate
        self._cache = {}

    def get(self, fn, shape, dtype):
        key = (shape, dtype, self._donate)
        if key in self._cache:
            return self._cache[key]
        step = jax.jit(fn, donate_argnums=(0,) if self._donate else ())
        self._cache[key] = step
        return step
