"""Corpus twin of a dataplane seam whose retry loop eats the fault."""
from exon003.chaos import plan as _chaos


def send_batch(sock, payload):
    hook = _chaos.HOOK
    if hook is not None:
        hook("dataplane", "send")      # the fault seam
    sock.sendall(payload)


def retry_once(sock, payload):
    try:
        send_batch(sock, payload)
    except OSError:
        # SEEDED MUTATION: OSError catches InjectedCrash (a
        # ConnectionError subclass) — injected process death becomes a
        # soft retry and the chaos test passes vacuously
        return False
    return True
