"""Control twins: the fault stays transparent through the handler."""
from exon003.chaos import plan as _chaos
from flink_tpu.lint.contracts import absorbs_faults


def send_batch(sock, payload):
    hook = _chaos.HOOK
    if hook is not None:
        hook("dataplane", "send")      # the fault seam
    sock.sendall(payload)


def retry_once(sock, payload):
    try:
        send_batch(sock, payload)
    except _chaos.InjectedCrash:
        raise                           # chaos stays loud
    except OSError:
        return False
    return True


def rethrow(sock, payload):
    try:
        send_batch(sock, payload)
    except OSError as e:
        raise RuntimeError("send failed") from e


@absorbs_faults("corpus control: absorption IS this helper's contract — "
                "the caller treats any failure as peer death")
def allowlisted(sock, payload):
    try:
        send_batch(sock, payload)
    except OSError:
        return False
    return True
