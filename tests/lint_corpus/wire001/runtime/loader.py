import pickle


def decode_frame(payload):
    # SEEDED: raw deserialization of socket-originated bytes
    return pickle.loads(payload)
