# SEEDED: the dataplane module serializes payloads itself
import pickle


def ship_batch(sock, batch):
    sock.sendall(pickle.dumps(batch))
