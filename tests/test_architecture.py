"""Architecture tests — thin wrapper over the flink_tpu.lint registry.

The rules that used to live here as ad-hoc AST functions (layering DAG,
jax-free control plane, checkpoint layering, pickle bans, dataplane
serialization freedom, config-docs completeness) are now registry rules
in ``flink_tpu/lint/`` — the ArchUnit-style analyzer framework with a
frozen-violation baseline (ISSUE-5). This module generates **one test per
registered rule**, so the rules live in exactly one place and a new rule
is gated here automatically; `tests/test_lint.py` covers the engine
itself (CLI, formats, baseline lifecycle).

A failure here prints the same actionable ``file:line [RULE] message``
output as ``python -m flink_tpu.lint`` (or ``bin/lint``); fix the
violation or baseline it WITH a written justification in
``lint_baseline.json``.
"""

import pathlib

import pytest

import flink_tpu
from flink_tpu.lint import Baseline, ModuleIndex, all_rules

PKG = pathlib.Path(flink_tpu.__file__).parent
BASELINE_PATH = PKG.parent / "lint_baseline.json"

_cache = {}


def _shared_index() -> ModuleIndex:
    """One parse of the package for all per-rule tests."""
    if "index" not in _cache:
        _cache["index"] = ModuleIndex(PKG)
    return _cache["index"]


def _baseline() -> Baseline:
    # a fresh Baseline per rule-test: `match` marks entries live, and tests
    # must not share that state across rules
    return Baseline.load(BASELINE_PATH) if BASELINE_PATH.exists() else \
        Baseline()


@pytest.mark.parametrize("rule", all_rules(), ids=lambda r: r.id)
def test_rule(rule):
    index = _shared_index()
    baseline = _baseline()
    active = []
    for violation in rule.check(index):
        entry = baseline.match(violation)
        if entry is None or not entry.justified:
            active.append(violation)
    assert not active, (
        f"[{rule.id} {rule.name}] {rule.rationale}\n\n"
        + "\n".join(v.render() for v in active)
    )


def test_no_parse_failures():
    assert not _shared_index().parse_failures, [
        f"{f.rel}:{f.line}: {f.error}"
        for f in _shared_index().parse_failures
    ]
