"""Architecture tests: layering rules over module imports.

Reference capability: flink-architecture-tests (ArchUnit rules freezing
layering and API discipline, e.g. ApiAnnotationRules.java / ConnectorRules
with checked-in violation stores). The analogue here parses each module's
AST and asserts the layer DAG:

    core, utils          — foundation: import nothing above themselves
    ops                  — device kernels: no runtime/api/table/cep deps
    state, graph         — no api/table/cep deps
    api                  — builds plans; may reach runtime only lazily
                           (inside functions), never at module import time

Lazy (function-scoped) imports are the sanctioned escape hatch — the same
role ArchUnit's violation store plays, but enforced structurally: execution
entry points import the executor when called, so importing the API layer
can never drag in the whole runtime.
"""

import ast
import pathlib

import flink_tpu

PKG = pathlib.Path(flink_tpu.__file__).parent

# layer -> module prefixes it must NOT import at module level
FORBIDDEN = {
    "core": ["flink_tpu.runtime", "flink_tpu.api", "flink_tpu.table",
             "flink_tpu.cep", "flink_tpu.ops", "flink_tpu.state"],
    "utils": ["flink_tpu.runtime", "flink_tpu.api", "flink_tpu.table",
              "flink_tpu.cep"],
    "ops": ["flink_tpu.runtime", "flink_tpu.api", "flink_tpu.table",
            "flink_tpu.cep"],
    "state": ["flink_tpu.api", "flink_tpu.table", "flink_tpu.cep"],
    "graph": ["flink_tpu.table", "flink_tpu.cep", "flink_tpu.runtime"],
    "api": ["flink_tpu.table", "flink_tpu.runtime"],
}


def _module_level_imports(path: pathlib.Path):
    """Imports executed at import time: module body + class bodies, but NOT
    function bodies (lazy imports are the sanctioned layering escape)."""
    tree = ast.parse(path.read_text())
    found = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Import):
                found.extend(a.name for a in child.names)
            elif isinstance(child, ast.ImportFrom) and child.module:
                found.append(child.module)
            else:
                walk(child)

    walk(tree)
    return found


def test_layering_rules():
    violations = []
    for layer, banned in FORBIDDEN.items():
        layer_dir = PKG / layer
        files = list(layer_dir.rglob("*.py")) if layer_dir.is_dir() else []
        assert files, f"layer {layer!r} has no modules?"
        for f in files:
            for imp in _module_level_imports(f):
                for b in banned:
                    if imp == b or imp.startswith(b + "."):
                        violations.append(
                            f"{f.relative_to(PKG.parent)} imports {imp} "
                            f"(layer {layer!r} must not depend on {b})"
                        )
    assert not violations, "\n".join(violations)


def test_jax_stays_out_of_the_control_plane():
    """The cluster control plane (JM/TM endpoints, RPC, blob, heartbeats,
    HA) must not import jax at module level: an oracle-path worker process
    must never initialize a TPU backend just by starting up (backend init
    claims the chip; see _make_operator's device-path-only import)."""
    control = ["runtime/cluster.py", "runtime/rpc.py", "runtime/blob.py",
               "runtime/heartbeat.py", "runtime/ha.py",
               "runtime/ha_kubernetes.py", "runtime/rest.py",
               "runtime/dataplane.py",
               "security/framing.py", "security/transport.py"]
    bad = []
    for rel in control:
        for imp in _module_level_imports(PKG / rel):
            if imp == "jax" or imp.startswith("jax."):
                bad.append(f"{rel} imports {imp} at module level")
    assert not bad, "\n".join(bad)


def _all_imports(path: pathlib.Path):
    """EVERY import in the file, function bodies included — for rules where
    even a lazy import is a layering violation."""
    found = []
    for node in ast.walk(ast.parse(path.read_text())):
        if isinstance(node, ast.Import):
            found.extend((a.name, node.lineno) for a in node.names)
        elif isinstance(node, ast.ImportFrom) and node.module:
            found.append((node.module, node.lineno))
    return found


def test_checkpoint_layer_never_imports_the_runtime():
    """flink_tpu/checkpoint/ must not import flink_tpu.runtime — anywhere,
    lazy imports included. Checkpoint/failure/recovery statistics flow
    OUTWARD: the coordinator reports into trackers the runtime hands it
    (metrics/checkpoint_stats.py stats + state_bytes_fn callbacks), it
    never reaches into the scheduler or executor. A runtime import here
    would invert the dependency and let coordinator changes drag in the
    whole cluster stack (and, on TPU hosts, risk backend init from a
    checkpoint utility)."""
    bad = []
    for f in sorted((PKG / "checkpoint").rglob("*.py")):
        for imp, line in _all_imports(f):
            if imp == "flink_tpu.runtime" or imp.startswith("flink_tpu.runtime."):
                bad.append(
                    f"{f.relative_to(PKG.parent)}:{line} imports {imp} "
                    "(checkpoint layer must stay below the runtime; pass "
                    "data outward via callbacks/trackers instead)"
                )
    assert not bad, "\n".join(bad)


def _pickle_load_sites(path: pathlib.Path):
    """Every way raw deserialization can be spelled, anywhere in the file
    (function bodies included — unlike _module_level_imports this must see
    lazy code paths too): `pickle.loads/load(...)`, `pickle.Unpickler`
    references, and `from pickle import loads/load/Unpickler` (which would
    make later bare-name calls invisible to attribute matching — the
    import itself is the violation)."""
    tree = ast.parse(path.read_text())
    found = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
                "pickle", "cloudpickle"):
            for a in node.names:
                if a.name in ("loads", "load", "Unpickler", "*"):
                    found.append(
                        (node.module, f"import {a.name}", node.lineno))
        elif isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
                and node.value.id in ("pickle", "cloudpickle"):
            if node.attr in ("loads", "load", "Unpickler"):
                found.append((node.value.id, node.attr, node.lineno))
    return found


def test_every_config_option_is_documented():
    """Every ConfigOption declared in flink_tpu/config.py must appear in
    docs/configuration.md (regenerate with `python -m
    flink_tpu.docs.generate`). The reference gates its docs the same way
    (ConfigOptionsDocsCompletenessITCase): an undocumented option fails CI
    before it ships, so the generated reference can be trusted to be the
    full surface."""
    from flink_tpu.docs.generate import collect_options

    doc = (PKG.parent / "docs" / "configuration.md").read_text()
    missing = [
        opt.key
        for _cls, _attr, opt in collect_options()
        if f"`{opt.key}`" not in doc
    ]
    assert not missing, (
        "config options missing from docs/configuration.md (run `python -m "
        f"flink_tpu.docs.generate`): {missing}"
    )


def test_dataplane_data_path_is_serialization_free():
    """runtime/dataplane.py may not serialize batch payloads itself — no
    pickle/cloudpickle import, no `dumps(`/`loads(` call anywhere in the
    module. Batch bytes cross the process boundary only through
    flink_tpu.security: the zero-copy binary columnar wire
    (security/wire.py via transport.send_data_frame/recv_msg) or the
    legacy restricted-pickle codec (transport.send_obj/recv_obj). This
    pins the ISSUE-3 zero-copy property: a convenience `dumps(batch)`
    creeping back into the data path reintroduces the full-copy
    serialization tax (and, on the receive side, a deserialize-before-MAC
    hazard) that the binary wire exists to remove."""
    path = PKG / "runtime" / "dataplane.py"
    tree = ast.parse(path.read_text())
    bad = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("pickle", "cloudpickle"):
                    bad.append(f"line {node.lineno}: import {a.name}")
        elif isinstance(node, ast.ImportFrom):
            if node.module in ("pickle", "cloudpickle"):
                bad.append(f"line {node.lineno}: from {node.module} import ...")
            elif node.module and any(
                    a.name in ("dumps", "loads", "dump", "load")
                    for a in node.names):
                bad.append(
                    f"line {node.lineno}: from {node.module} imports a "
                    "serializer name"
                )
        elif isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if name in ("dumps", "loads", "dump", "load"):
                bad.append(f"line {node.lineno}: call to {name}(...)")
    assert not bad, (
        "runtime/dataplane.py serializes on the data path — route batches "
        "through security.transport/security.wire instead:\n" + "\n".join(bad)
    )


def test_no_bare_pickle_loads_on_network_planes():
    """Everything under flink_tpu/runtime/ and flink_tpu/fs/ handles bytes
    that can originate from a socket (RPC frames, exchange batches, blob
    payloads, object-store reads), so NO module there may deserialize with
    pickle directly — loads/load calls, Unpickler subclassing, and
    `from pickle import loads` are all banned; deserialization goes through
    flink_tpu/security (restricted_loads after MAC verification;
    trusted_loads for post-auth job specs). This lint keeps the ISSUE-1
    fix from regressing: a new raw-pickle path on a network plane fails CI
    before it fails an incident review."""
    bad = []
    for layer in ("runtime", "fs"):
        for f in sorted((PKG / layer).rglob("*.py")):
            for mod, what, line in _pickle_load_sites(f):
                bad.append(
                    f"{f.relative_to(PKG.parent)}:{line} uses {mod}.{what} "
                    "— route it through flink_tpu.security.framing "
                    "(restricted_loads/trusted_loads)"
                )
    assert not bad, "\n".join(bad)
