"""Architecture tests: layering rules over module imports.

Reference capability: flink-architecture-tests (ArchUnit rules freezing
layering and API discipline, e.g. ApiAnnotationRules.java / ConnectorRules
with checked-in violation stores). The analogue here parses each module's
AST and asserts the layer DAG:

    core, utils          — foundation: import nothing above themselves
    ops                  — device kernels: no runtime/api/table/cep deps
    state, graph         — no api/table/cep deps
    api                  — builds plans; may reach runtime only lazily
                           (inside functions), never at module import time

Lazy (function-scoped) imports are the sanctioned escape hatch — the same
role ArchUnit's violation store plays, but enforced structurally: execution
entry points import the executor when called, so importing the API layer
can never drag in the whole runtime.
"""

import ast
import pathlib

import flink_tpu

PKG = pathlib.Path(flink_tpu.__file__).parent

# layer -> module prefixes it must NOT import at module level
FORBIDDEN = {
    "core": ["flink_tpu.runtime", "flink_tpu.api", "flink_tpu.table",
             "flink_tpu.cep", "flink_tpu.ops", "flink_tpu.state"],
    "utils": ["flink_tpu.runtime", "flink_tpu.api", "flink_tpu.table",
              "flink_tpu.cep"],
    "ops": ["flink_tpu.runtime", "flink_tpu.api", "flink_tpu.table",
            "flink_tpu.cep"],
    "state": ["flink_tpu.api", "flink_tpu.table", "flink_tpu.cep"],
    "graph": ["flink_tpu.table", "flink_tpu.cep", "flink_tpu.runtime"],
    "api": ["flink_tpu.table", "flink_tpu.runtime"],
}


def _module_level_imports(path: pathlib.Path):
    """Imports executed at import time: module body + class bodies, but NOT
    function bodies (lazy imports are the sanctioned layering escape)."""
    tree = ast.parse(path.read_text())
    found = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(child, ast.Import):
                found.extend(a.name for a in child.names)
            elif isinstance(child, ast.ImportFrom) and child.module:
                found.append(child.module)
            else:
                walk(child)

    walk(tree)
    return found


def test_layering_rules():
    violations = []
    for layer, banned in FORBIDDEN.items():
        layer_dir = PKG / layer
        files = list(layer_dir.rglob("*.py")) if layer_dir.is_dir() else []
        assert files, f"layer {layer!r} has no modules?"
        for f in files:
            for imp in _module_level_imports(f):
                for b in banned:
                    if imp == b or imp.startswith(b + "."):
                        violations.append(
                            f"{f.relative_to(PKG.parent)} imports {imp} "
                            f"(layer {layer!r} must not depend on {b})"
                        )
    assert not violations, "\n".join(violations)


def test_jax_stays_out_of_the_control_plane():
    """The cluster control plane (JM/TM endpoints, RPC, blob, heartbeats,
    HA) must not import jax at module level: an oracle-path worker process
    must never initialize a TPU backend just by starting up (backend init
    claims the chip; see _make_operator's device-path-only import)."""
    control = ["runtime/cluster.py", "runtime/rpc.py", "runtime/blob.py",
               "runtime/heartbeat.py", "runtime/ha.py",
               "runtime/ha_kubernetes.py", "runtime/rest.py",
               "runtime/dataplane.py"]
    bad = []
    for rel in control:
        for imp in _module_level_imports(PKG / rel):
            if imp == "jax" or imp.startswith("jax."):
                bad.append(f"{rel} imports {imp} at module level")
    assert not bad, "\n".join(bad)
