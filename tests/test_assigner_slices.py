"""Exact slice decomposition of the sliceable assigners (ISSUE-14
satellite): the sharing optimizer derives every member window from shared
gcd-granule partials, so the decomposition must be EXACT in the shapes a
naive `size // slide` computation gets wrong — a slide that does not
divide the size, the size == slide tumbling collapse, and slide > size
(sampling windows with dead slices). These tests pin the `slices_on`
contract directly against the reference assignment semantics
(assign_sliding/assign_tumbling), for the assigner's own gcd granule AND
for coarser-group granules.
"""

import math

import numpy as np
import pytest

from flink_tpu.api.windowing.assigners import (
    EventTimeSessionWindows,
    GlobalWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.core.time import assign_sliding, assign_tumbling


def _windows_via_slices(assigner, ts: int, granule: int):
    """Windows containing `ts` per the slice model on `granule`: slice
    s = (ts - offset) // granule; window j covers [j*sl, j*sl + spw)."""
    spw, sl = assigner.slices_on(granule)
    s = (ts - assigner.offset_ms) // granule
    out = []
    j_hi = s // sl
    j_lo = -((spw - s - 1) // sl) if s < spw else (s - spw) // sl + 1
    for j in range(j_lo - 2, j_hi + 1):          # margin; filtered below
        if j * sl <= s < j * sl + spw:
            start = assigner.offset_ms + j * sl * granule
            out.append((start, start + spw * granule))
    return sorted(out)


@pytest.mark.parametrize("size,slide", [
    (10, 4),      # slide does not divide size: gcd granule 2
    (9, 6),       # gcd 3
    (7, 3),       # gcd 1
    (10, 10),     # size == slide tumbling collapse: granule = size, NOT slide
    (2, 5),       # slide > size: dead slices between windows
    (3, 7),
])
def test_sliding_decomposition_matches_reference_assignment(size, slide):
    a = SlidingEventTimeWindows.of(size, slide)
    g = a.slice_ms
    assert g == math.gcd(size, slide)
    assert a.slices_per_window * g == size
    assert a.slide_slices * g == slide
    for ts in range(0, 4 * size + 4 * slide):
        expect = sorted((w.start, w.end) for w in
                        assign_sliding(ts, size, slide, 0))
        got = _windows_via_slices(a, ts, g)
        assert got == expect, (size, slide, ts)


@pytest.mark.parametrize("size,slide,offset", [
    (10, 4, 2), (10, 4, -2), (7, 3, 1), (9, 6, -5),
])
def test_sliding_decomposition_with_offset(size, slide, offset):
    a = SlidingEventTimeWindows.of(size, slide, offset)
    for ts in range(0, 3 * size + 3 * slide):
        expect = sorted((w.start, w.end) for w in
                        assign_sliding(ts, size, slide, offset))
        assert _windows_via_slices(a, ts, a.slice_ms) == expect


def test_tumbling_decomposition():
    a = TumblingEventTimeWindows.of(6)
    assert (a.slices_per_window, a.slide_slices) == (1, 1)
    for ts in range(0, 40):
        expect = sorted((w.start, w.end) for w in assign_tumbling(ts, 6, 0))
        assert _windows_via_slices(a, ts, a.slice_ms) == expect


# ---------------------------------------------------------------------------
# slices_on: decomposition onto an ARBITRARY (group) granule
# ---------------------------------------------------------------------------

def test_slices_on_exact_for_group_gcd():
    """The 1m/5m/1h group decomposes exactly on the 1m gcd granule."""
    members = [TumblingEventTimeWindows.of(60_000),
               TumblingEventTimeWindows.of(300_000),
               TumblingEventTimeWindows.of(3_600_000)]
    g = 0
    for a in members:
        g = math.gcd(g, a.slice_ms)
    assert g == 60_000
    assert [a.slices_on(g) for a in members] == [(1, 1), (5, 5), (60, 60)]


def test_slices_on_degenerate_member_exact():
    """A sliding member whose slide does not divide its size still
    decomposes exactly on a shared granule dividing gcd(size, slide)."""
    a = SlidingEventTimeWindows.of(90_000, 36_000)   # gcd 18s
    assert a.slices_on(18_000) == (5, 2)
    assert a.slices_on(6_000) == (15, 6)             # finer group granule
    assert a.slices_on(2_000) == (45, 18)
    # reference cross-check on the finer granule
    for ts in range(0, 300_000, 1711):
        expect = sorted((w.start, w.end) for w in
                        assign_sliding(ts, 90_000, 36_000, 0))
        assert _windows_via_slices(a, ts, 6_000) == expect


def test_slices_on_refuses_inexact_granules():
    a = SlidingEventTimeWindows.of(10_000, 4_000)    # gcd 2s
    with pytest.raises(ValueError, match="does not divide"):
        a.slices_on(3_000)       # divides neither
    with pytest.raises(ValueError, match="does not divide"):
        a.slices_on(4_000)       # divides slide but not size
    with pytest.raises(ValueError, match="does not divide"):
        a.slices_on(0)
    # size == slide collapse: slide itself IS valid (== size == gcd), but
    # anything that does not divide it is not
    t = SlidingEventTimeWindows.of(5_000, 5_000)
    assert t.slices_on(5_000) == (1, 1)
    with pytest.raises(ValueError):
        t.slices_on(2_000)


def test_slices_on_not_sliceable():
    with pytest.raises(ValueError, match="not sliceable"):
        EventTimeSessionWindows.with_gap(1000).slices_on(1000)
    with pytest.raises(ValueError, match="not sliceable"):
        GlobalWindows().slices_on(1000)
