"""Async I/O operator tests (AsyncWaitOperator semantics: ordered/unordered,
capacity, timeout, retries) + processing-time window path."""

import threading
import time

import pytest

from flink_tpu.api.datastream import StreamExecutionEnvironment
from flink_tpu.api.windowing.assigners import TumblingProcessingTimeWindows
from flink_tpu.runtime.async_io import AsyncExecutor, AsyncFunction, RetryStrategy


def test_ordered_results_despite_varied_latency():
    def slow_lookup(x):
        time.sleep(0.02 if x % 2 == 0 else 0.001)
        return x * 10

    ex = AsyncExecutor(slow_lookup, capacity=8, ordered=True)
    out = ex.process(range(20))
    assert [r for _, r in out] == [x * 10 for x in range(20)]
    ex.close()


def test_unordered_returns_all():
    def lookup(x):
        time.sleep(0.001 * (x % 5))
        return x + 100

    ex = AsyncExecutor(lookup, capacity=4, ordered=False)
    out = ex.process(range(30))
    assert sorted(r for _, r in out) == [x + 100 for x in range(30)]
    ex.close()


def test_capacity_bounds_concurrency():
    active = [0]
    peak = [0]
    lock = threading.Lock()

    def tracked(x):
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        time.sleep(0.005)
        with lock:
            active[0] -= 1
        return x

    ex = AsyncExecutor(tracked, capacity=3, ordered=True)
    ex.process(range(30))
    assert peak[0] <= 3
    ex.close()


def test_timeout_uses_fallback_value():
    class Fn(AsyncFunction):
        def async_invoke(self, value):
            if value == 2:
                time.sleep(1.0)
            return value

        def timeout_value(self, value):
            return -value

    ex = AsyncExecutor(Fn(), capacity=4, timeout_ms=50, ordered=True)
    out = ex.process([1, 2, 3])
    assert [r for _, r in out] == [1, -2, 3]
    ex.close()


def test_retry_recovers_transient_failures():
    attempts = {}

    def flaky(x):
        attempts[x] = attempts.get(x, 0) + 1
        if attempts[x] < 3:
            raise IOError("transient")
        return x

    ex = AsyncExecutor(flaky, capacity=2, retry=RetryStrategy(max_attempts=3, delay_ms=1))
    out = ex.process([7, 8])
    assert sorted(r for _, r in out) == [7, 8]
    assert attempts == {7: 3, 8: 3}
    ex.close()


def test_retry_exhaustion_raises():
    def always_fails(x):
        raise IOError("down")

    ex = AsyncExecutor(always_fails, capacity=1, retry=RetryStrategy(max_attempts=2, delay_ms=1))
    with pytest.raises(IOError):
        ex.process([1])
    ex.close()


def test_async_map_end_to_end():
    env = StreamExecutionEnvironment.get_execution_environment()
    stream = env.from_collection(list(range(50)), timestamp_fn=lambda x: x)
    sink = (
        stream.async_map(lambda x: x * 2, capacity=8, ordered=True)
        .filter(lambda x: x % 4 == 0)
        .collect()
    )
    env.execute()
    assert sink.results == [x * 2 for x in range(50) if (x * 2) % 4 == 0]


def test_processing_time_windows_end_to_end():
    env = StreamExecutionEnvironment.get_execution_environment()
    data = [("k", 1.0)] * 10
    stream = env.from_collection(data)
    sink = (
        stream.key_by(lambda x: x[0])
        .window(TumblingProcessingTimeWindows.of(1))  # 1ms PT windows
        .count()
        .collect()
    )
    env.execute()
    # all records arrive in one step batch at one wall-clock instant; the PT
    # timer fires when processing time advances past the tiny window
    assert sum(n for _, n in sink.results) == 10
