"""Elastic autoscaler (flink_tpu/scheduler/): signals, policies,
coordinator, the JM rescale executor, and the load-spike acceptance e2e.

The e2e models the ROADMAP item-2 scenario: an arrival-paced keyed job
whose per-record service cost releases the GIL (bulk sleeps), so adding
task threads genuinely raises capacity even in one test process. A 2x
traffic step saturates parallelism 1 (capacity ~1.4x the pre-step rate,
below the required 1.5x), the autoscaler scales up by checkpoint rewind +
key-group remap, throughput recovers to 2x, and a later load drop scales
back down — with exactly-once results against a fixed-parallelism oracle.
"""

import time

import numpy as np
import pytest

from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
from flink_tpu.config import AutoscalerOptions, Configuration
from flink_tpu.scheduler import (
    AutoscalerCoordinator,
    LearningPolicy,
    SignalWindow,
    ThresholdPolicy,
    build_policy,
    extract_signals,
)
from flink_tpu.scheduler.signals import SignalSample


# ---------------------------------------------------------------------------
# 1. signals
# ---------------------------------------------------------------------------

def _sample(t, busy=0.5, bp=0.0, records=0.0):
    return SignalSample(timestamp=t, busy=busy, backpressured=bp,
                        records_in=records)


def test_signal_window_means_and_throughput():
    win = SignalWindow(size=4)
    for i in range(4):
        est = win.observe(_sample(float(i), busy=0.2 * (i + 1),
                                  records=1000.0 * i))
    assert est.samples == 4
    assert est.utilization == pytest.approx((0.2 + 0.4 + 0.6 + 0.8) / 4)
    # 3000 records over 3 seconds
    assert est.throughput_per_s == pytest.approx(1000.0)
    # bounded: a 5th sample evicts the 1st
    est = win.observe(_sample(4.0, busy=1.0, records=4000.0))
    assert est.samples == 4
    assert est.utilization == pytest.approx((0.4 + 0.6 + 0.8 + 1.0) / 4)


def test_signal_window_clears_on_counter_reset():
    """A records_in counter going backwards means a fresh attempt deployed
    (rescale/failover): the window must not mix attempts or report
    negative throughput."""
    win = SignalWindow(size=4)
    for i in range(3):
        win.observe(_sample(float(i), records=5000.0 + 1000 * i))
    est = win.observe(_sample(3.0, records=100.0))   # reset
    assert est.samples == 1
    assert est.throughput_per_s == 0.0


def test_extract_signals_prefers_windowed_rates():
    est = extract_signals({
        "job.busyTimeMsPerSecond": 700.0,
        "job.busyTimeRatio": 0.1,          # lifetime: stale, must lose
        "job.backPressuredTimeMsPerSecond": 100.0,
        "job.numRecordsIn": 42.0,
        "job.exchange.inPoolUsage.0": 0.5,
        "job.exchange.inPoolUsage.1": 1.0,
        "job.watermarkSkewMs": 123.0,
    }, now=0.0)
    assert est.busy == pytest.approx(0.7)
    assert est.backpressured == pytest.approx(0.1)
    assert est.utilization == pytest.approx(0.8)
    assert est.in_pool_usage == pytest.approx(0.75)
    assert est.watermark_skew_ms == 123.0
    # falls back to the lifetime ratio when the rate gauge is absent
    est = extract_signals({"job.busyTimeRatio": 0.33}, now=0.0)
    assert est.busy == pytest.approx(0.33)
    # a PRESENT windowed gauge is authoritative even at zero: a fully
    # idle vertex must not inherit its stale lifetime ratio (that would
    # read ~0.9 busy on an idle job and block every scale-down)
    est = extract_signals({"job.busyTimeMsPerSecond": 0.0,
                           "job.busyTimeRatio": 0.9}, now=0.0)
    assert est.busy == 0.0


def test_device_signals_absent_read_none_not_zero():
    """Satellite (f): a build/job without device stats simply LACKS the
    skew/roofline gauges — reading the absence as 0.0 would feed "zero
    skew, zero utilization" into the learning policy and bias it toward
    jobs that merely lack the gauge. None means not measured."""
    s = extract_signals({"job.busyTimeMsPerSecond": 500.0}, now=0.0)
    assert s.key_skew is None
    assert s.device_utilization is None
    win = SignalWindow(size=4)
    win.observe(s)
    est = win.estimate()
    assert est.key_skew is None
    assert est.device_utilization is None
    d = est.as_dict()
    assert d["key_skew"] is None and d["device_utilization"] is None


def test_device_signals_present_fold_over_measured_samples_only():
    s_with = extract_signals({
        "job.keySkew": 3.0,
        "job.device.hbmUtilizationPct": 40.0,
        "job.device.flopsUtilizationPct": 10.0,
    }, now=0.0)
    # device_utilization is the BINDING resource: worst roofline fraction
    assert s_with.key_skew == 3.0
    assert s_with.device_utilization == pytest.approx(0.40)
    s_without = extract_signals({"job.busyTimeMsPerSecond": 100.0}, now=1.0)
    win = SignalWindow(size=4)
    win.observe(s_with)
    win.observe(s_without)
    win.observe(extract_signals({"job.keySkew": 5.0}, now=2.0))
    est = win.estimate()
    # mean over the samples that MEASURED the signal (3.0, 5.0), the
    # unmeasured middle sample excluded — not (3+0+5)/3
    assert est.key_skew == pytest.approx(4.0)
    assert est.device_utilization == pytest.approx(0.40)


def test_device_signal_zero_is_still_a_measurement():
    """A PRESENT 0.0 gauge is a real reading (an idle device), distinct
    from an absent gauge — it must participate in the mean."""
    win = SignalWindow(size=4)
    win.observe(extract_signals({"job.keySkew": 4.0}, now=0.0))
    win.observe(extract_signals({"job.keySkew": 0.0}, now=1.0))
    assert win.estimate().key_skew == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# 2. policies
# ---------------------------------------------------------------------------

def _estimate(util, samples=5, tput=1000.0):
    win = SignalWindow(size=samples)
    for i in range(samples):
        win.observe(_sample(float(i), busy=util, records=tput * i))
    return win.estimate()


def test_threshold_policy_scales_up_down_and_clamps():
    p = ThresholdPolicy(scale_up_threshold=0.8, scale_down_threshold=0.3)
    up = p.decide(_estimate(0.9), 2, 1, 8)
    assert (up.action, up.target) == ("scale-up", 4)
    down = p.decide(_estimate(0.1), 4, 1, 8)
    assert (down.action, down.target) == ("scale-down", 2)
    assert p.decide(_estimate(0.5), 2, 1, 8).action == "none"
    # clamping: at max, at min
    assert p.decide(_estimate(0.9), 8, 1, 8).action == "none"
    assert p.decide(_estimate(0.1), 1, 1, 8).action == "none"
    # max below doubling still makes progress
    assert p.decide(_estimate(0.9), 2, 1, 3).target == 3
    # cold window: no decision before min_samples
    assert p.decide(_estimate(0.9, samples=1), 2, 1, 8).action == "none"


def test_threshold_policy_scale_down_vetoed_by_window_peak():
    """A transient stall must not halve a busy job: scale-down requires
    the WHOLE window idle — a mean dragged below the threshold by a few
    stalled ticks around a genuinely busy one is load jitter, and acting
    on it churns rescales (each one a checkpoint rewind + replay)."""
    pol = ThresholdPolicy(scale_down_threshold=0.3, min_samples=1)
    win = SignalWindow(size=4)
    for i, u in enumerate([0.9, 0.0, 0.0, 0.0]):     # mean 0.225, peak 0.9
        est = win.observe(_sample(float(i), busy=u, records=100.0 * i))
    d = pol.decide(est, 4, 1, 8)
    assert d.action == "none" and "peak" in d.reason
    # a genuinely idle window still scales down
    win = SignalWindow(size=4)
    for i in range(4):
        est = win.observe(_sample(float(i), busy=0.1, records=100.0 * i))
    assert pol.decide(est, 4, 1, 8).action == "scale-down"


def test_learning_policy_damps_unhelpful_rescales_with_patience():
    """The Adaptive Parallelism Tuning blueprint: a scale-up that bought
    nothing is suppressed for `patience` triggers, then retried; a good
    outcome clears the damping immediately."""
    p = LearningPolicy(ThresholdPolicy(scale_up_threshold=0.8), patience=3,
                       min_gain=1.1)
    hot = _estimate(0.95)
    assert p.decide(hot, 2, 1, 8).action == "scale-up"
    # past 2->4 rescale observed no gain
    p.record_outcome("scale-up", 2, 4, 1000.0, 1005.0)
    for i in range(3):
        d = p.decide(hot, 2, 1, 8)
        assert d.action == "none" and "damped" in d.reason, (i, d)
    # patience exhausted: retried
    retry = p.decide(hot, 2, 1, 8)
    assert retry.action == "scale-up" and "retry" in retry.reason
    # a GOOD outcome clears the grudge for subsequent decisions
    p.record_outcome("scale-up", 2, 4, 1000.0, 1800.0)
    assert p.decide(hot, 2, 1, 8).action == "scale-up"
    # a different from-parallelism is never damped by the 2->4 history
    p.record_outcome("scale-up", 2, 4, 1000.0, 1001.0)
    assert p.decide(hot, 4, 1, 16).action == "scale-up"


def test_learning_policy_damps_scale_down_that_lost_throughput():
    p = LearningPolicy(ThresholdPolicy(scale_down_threshold=0.3),
                       patience=2, min_gain=1.25)
    cold = _estimate(0.05)
    assert p.decide(cold, 4, 1, 8).action == "scale-down"
    # past 4->2 halved throughput (below the 1/1.25 = 0.8 retention bar)
    p.record_outcome("scale-down", 4, 2, 1000.0, 500.0)
    assert p.decide(cold, 4, 1, 8).action == "none"
    # a scale-down that RETAINED throughput is not damped
    p2 = LearningPolicy(ThresholdPolicy(scale_down_threshold=0.3),
                        patience=2, min_gain=1.25)
    p2.record_outcome("scale-down", 4, 2, 1000.0, 990.0)
    assert p2.decide(cold, 4, 1, 8).action == "scale-down"


def test_build_policy_factory():
    assert build_policy("threshold").name == "threshold"
    assert build_policy("learning").name == "learning"
    with pytest.raises(ValueError):
        build_policy("oracle")


# ---------------------------------------------------------------------------
# 3. coordinator
# ---------------------------------------------------------------------------

def _coordinator(executor=None, **kw):
    clock = [0.0]
    kw.setdefault("stabilization_interval_ms", 0)
    coord = AutoscalerCoordinator(
        ThresholdPolicy(scale_up_threshold=0.8, scale_down_threshold=0.2,
                        min_samples=1),
        rescale_executor=executor, clock=lambda: clock[0], **kw)
    return coord, clock


def _busy_metrics(ratio, records):
    return {"job.busyTimeMsPerSecond": ratio * 1000.0,
            "job.numRecordsIn": records}


def test_coordinator_executes_and_logs_decisions():
    calls = []

    def executor(job_id, target, reason):
        calls.append((job_id, target))
        return True, "ok"

    coord, clock = _coordinator(executor, stabilization_interval_ms=1500)
    d = None
    for i in range(3):
        clock[0] = float(i)
        d = coord.observe("j1", 2, _busy_metrics(0.95, 1000.0 * i),
                          max_slots=8) or d
    # first ticks sit inside the initial stabilization window; the t=2
    # tick executes once, then post-rescale stabilization holds
    assert calls == [("j1", 4)]
    assert d is not None and d.action == "scale-up"
    payload = coord.payload("j1", num_rescales=len(calls))
    assert payload["enabled"] and payload["policy"] == "threshold"
    assert payload["num_rescales"] == 1    # caller-supplied: the executor
    entry = payload["decisions"][0]        # (JM) owns the counters
    assert entry["action"] == "scale-up" and entry["outcome"] == "executed"
    assert entry["signals"]["utilization"] > 0.9
    executed = [d for d in payload["decisions"] if d["outcome"] == "executed"]
    assert len(executed) == 1


def test_coordinator_stabilization_window_blocks_decisions():
    calls = []
    coord, clock = _coordinator(
        lambda j, t, r: (calls.append(t) or True, "ok"),
        stabilization_interval_ms=10_000)
    # window fills during stabilization but nothing executes
    for i in range(5):
        clock[0] = float(i)
        assert coord.observe("j1", 2, _busy_metrics(0.95, 100.0 * i)) is None
    assert calls == []
    clock[0] = 11.0
    coord.observe("j1", 2, _busy_metrics(0.95, 600.0))
    assert calls == [4]
    # after the executed rescale: quiet again until completion + interval
    clock[0] = 12.0
    assert coord.observe("j1", 4, _busy_metrics(0.95, 50.0)) is None
    coord.rescale_completed("j1", 250.0)
    assert coord.payload("j1")["decisions"][0]["duration_ms"] == 250.0


def test_coordinator_rejected_execution_is_logged():
    coord, clock = _coordinator(lambda j, t, r: (False, "no checkpoint"))
    clock[0] = 1.0
    d = coord.observe("j1", 1, _busy_metrics(0.95, 10.0), max_slots=4)
    assert d is not None and d.action == "scale-up"
    entry = coord.payload("j1")["decisions"][0]
    assert entry["outcome"] == "rejected: no checkpoint"
    assert coord.payload("j1")["num_rescales"] == 0


def test_coordinator_decision_log_is_bounded():
    # window of 1: each tick's estimate is that sample, so alternating
    # utilization yields alternating (non-coalescable) up/down proposals
    coord, clock = _coordinator(None, decision_log_size=2, signal_window=1)
    for i in range(6):
        clock[0] = float(i)
        coord.observe("j1", 2, _busy_metrics(0.95 if i % 2 else 0.05,
                                             10.0 * i), max_slots=8)
    decisions = coord.payload("j1")["decisions"]
    assert len(decisions) == 2
    assert all(d["outcome"] == "observe-only" for d in decisions)
    assert {d["action"] for d in decisions} == {"scale-up", "scale-down"}


def test_coordinator_coalesces_repeated_identical_decisions():
    """A decision the executor keeps refusing (or observe-only mode)
    refires every tick by design; identical repeats must coalesce in
    place — not churn real rescale history out of the bounded log."""
    coord, clock = _coordinator(lambda j, t, r: (False, "no checkpoint"),
                                decision_log_size=4)
    for i in range(5):
        clock[0] = float(i)
        coord.observe("j1", 1, _busy_metrics(0.95, 10.0 * i), max_slots=4)
    decisions = coord.payload("j1")["decisions"]
    assert len(decisions) == 1
    assert decisions[0]["repeats"] == 5
    assert decisions[0]["outcome"] == "rejected: no checkpoint"
    assert decisions[0]["timestamp_ms"] >= 0


def test_coordinator_discards_pending_outcome_on_foreign_parallelism():
    """A failover that lands the job somewhere OTHER than the rescale's
    target mid-stabilization must not record that deployment's throughput
    as the rescale's outcome — it would poison the learning history."""
    policy = LearningPolicy(
        ThresholdPolicy(scale_up_threshold=0.8, min_samples=1),
        min_gain=1.2)
    clock = [0.0]
    coord = AutoscalerCoordinator(
        policy, stabilization_interval_ms=1000,
        rescale_executor=lambda j, t, r: (True, "ok"),
        clock=lambda: clock[0])
    coord.observe("j1", 2, _busy_metrics(0.95, 0.0), max_slots=8)
    clock[0] = 2.0
    assert coord.observe("j1", 2, _busy_metrics(0.95, 200.0),
                         max_slots=8).action == "scale-up"   # 2 -> 4
    coord.rescale_completed("j1", 50.0)
    # a TM loss drops the job to p=1 (not the rescale's target 4)
    for i in range(4):
        clock[0] = 4.0 + i
        coord.observe("j1", 1, _busy_metrics(0.5, 100.0 * i))
    assert len(policy.history) == 0
    entry = coord.payload("j1")["decisions"][0]
    assert entry["throughput_after"] is None


def test_coordinator_discards_pending_outcome_when_rescale_never_lands():
    """A deploy failure can restart the job at its ORIGINAL parallelism —
    no shape change, so the window-reset guard never fires. The rescale's
    pending outcome must still be discarded: recording old-parallelism
    throughput as the scale-up's gain (~1.0) would damp a genuinely
    needed rescale."""
    policy = LearningPolicy(
        ThresholdPolicy(scale_up_threshold=0.8, min_samples=1),
        min_gain=1.2)
    clock = [0.0]
    coord = AutoscalerCoordinator(
        policy, stabilization_interval_ms=1000,
        rescale_executor=lambda j, t, r: (True, "ok"),
        clock=lambda: clock[0])
    coord.observe("j1", 1, _busy_metrics(0.95, 0.0), max_slots=8)
    clock[0] = 2.0
    assert coord.observe("j1", 1, _busy_metrics(0.95, 200.0),
                         max_slots=8).action == "scale-up"    # 1 -> 2
    # the deploy failed and the adaptive restart landed back at p=1
    for i in range(4):
        clock[0] = 4.0 + i
        coord.observe("j1", 1, _busy_metrics(0.95, 100.0 * i), max_slots=8)
    assert len(policy.history) == 0
    executed = [d for d in coord.payload("j1")["decisions"]
                if d["outcome"] == "executed"]
    assert executed and all(d["throughput_after"] is None for d in executed)


def test_coordinator_outcome_feeds_learning_policy():
    policy = LearningPolicy(
        ThresholdPolicy(scale_up_threshold=0.8, min_samples=1),
        min_gain=1.2, patience=10)
    clock = [0.0]
    coord = AutoscalerCoordinator(
        policy, stabilization_interval_ms=1000,
        rescale_executor=lambda j, t, r: (True, "ok"),
        clock=lambda: clock[0])
    coord.observe("j1", 2, _busy_metrics(0.95, 0.0), max_slots=8)
    clock[0] = 2.0        # decision-time window: 100 records/s
    assert coord.observe("j1", 2, _busy_metrics(0.95, 200.0),
                         max_slots=8).action == "scale-up"
    coord.rescale_completed("j1", 100.0)
    # post-stabilization samples at the SAME 100 rec/s: gain ~1.0
    for i in range(4):
        clock[0] = 4.0 + i
        coord.observe("j1", 4, _busy_metrics(0.5, 100.0 * i))
    assert len(policy.history) == 1
    assert policy.history[0].gain == pytest.approx(1.0)
    assert policy.history[0].from_parallelism == 2
    entry = coord.payload("j1")["decisions"][0]
    assert entry["throughput_after"] is not None
    # the unhelpful outcome now damps the next 2->N scale-up
    clock[0] = 20.0
    coord2_decision = policy.decide(_estimate(0.95), 2, 1, 8)
    assert coord2_decision.action == "none" and "damped" in coord2_decision.reason


def test_maybe_observe_throttles_by_interval():
    seen = []
    coord, clock = _coordinator(None, interval_ms=1000)
    for t in (0.0, 0.1, 0.5, 1.1, 1.2, 2.2):
        clock[0] = t
        coord.maybe_observe("j1", 1,
                            lambda: seen.append(1) or _busy_metrics(0.5, 0.0))
    assert len(seen) == 3          # t=0.0, 1.1, 2.2


def test_from_config_builds_policy_and_bounds():
    cfg = (Configuration()
           .set(AutoscalerOptions.POLICY, "learning")
           .set(AutoscalerOptions.MIN_PARALLELISM, 2)
           .set(AutoscalerOptions.MAX_PARALLELISM, 6)
           .set(AutoscalerOptions.STABILIZATION_INTERVAL_MS, 5000))
    coord = AutoscalerCoordinator.from_config(cfg)
    assert isinstance(coord.policy, LearningPolicy)
    assert (coord.min_parallelism, coord.max_parallelism) == (2, 6)
    assert coord.stabilization_s == 5.0
    p = coord.payload("nope")
    assert p["policy"] == "learning" and p["decisions"] == []


def test_from_config_small_signal_window_still_decides_and_settles():
    """autoscaler.signal-window below the default 3-sample warm-up must
    clamp the warm-up (and the outcome-settling bar) to the window, not
    leave the policy 'warming up (2/3 samples)' forever — a silently
    inert autoscaler."""
    cfg = (Configuration()
           .set(AutoscalerOptions.SIGNAL_WINDOW, 2)
           .set(AutoscalerOptions.STABILIZATION_INTERVAL_MS, 0)
           .set(AutoscalerOptions.SCALE_UP_THRESHOLD, 0.8))
    calls = []
    clock = [0.0]
    coord = AutoscalerCoordinator.from_config(
        cfg, rescale_executor=lambda j, t, r: (calls.append(t) or True, "ok"),
        clock=lambda: clock[0])
    for i in range(2):
        clock[0] = float(i)
        coord.observe("j1", 1, _busy_metrics(0.95, 100.0 * i), max_slots=4)
    assert calls == [2], "window of 2 never cleared the hardcoded warm-up"
    # the settling bar clamps too: the outcome lands once the 2-sample
    # window refills after the post-rescale arm
    for i in range(4):
        clock[0] = 3.0 + i
        coord.observe("j1", 2, _busy_metrics(0.5, 100.0 * i))
    entry = [d for d in coord.payload("j1")["decisions"]
             if d["outcome"] == "executed"][0]
    assert entry["throughput_after"] == pytest.approx(100.0)


def test_back_to_back_rescales_both_settle_outcomes():
    """A job that stays saturated after a scale-up must not execute the
    next rescale until the first one's outcome has settled — otherwise
    every pending measurement in the chain is overwritten and the
    learning history stays empty under sustained load."""
    policy = LearningPolicy(
        ThresholdPolicy(scale_up_threshold=0.8, min_samples=1),
        min_gain=1.2, patience=10)
    clock = [0.0]
    calls = []
    coord = AutoscalerCoordinator(
        policy, stabilization_interval_ms=1000,
        rescale_executor=lambda j, t, r: (calls.append(t) or True, "ok"),
        clock=lambda: clock[0])
    coord.observe("j1", 1, _busy_metrics(0.95, 0.0), max_slots=8)
    clock[0] = 2.0
    assert coord.observe("j1", 1, _busy_metrics(0.95, 200.0),
                         max_slots=8).action == "scale-up"      # 1 -> 2
    assert calls == [2]
    # still saturated at p=2: the 2->4 rescale waits for the 1->2
    # outcome to settle, then fires in the same tick
    for i in range(4):
        clock[0] = 4.0 + i
        coord.observe("j1", 2, _busy_metrics(0.95, 300.0 * i), max_slots=8)
    assert calls == [2, 4]
    assert len(policy.history) == 1
    assert policy.history[0].from_parallelism == 1
    assert policy.history[0].gain == pytest.approx(3.0)   # 100 -> 300 rec/s
    first_up = [d for d in coord.payload("j1")["decisions"]
                if d["outcome"] == "executed" and d["parallelism"] == 1][0]
    assert first_up["throughput_after"] == pytest.approx(300.0)


# ---------------------------------------------------------------------------
# 4. distributed rescale executor (manual path)
# ---------------------------------------------------------------------------

class _MeteredTumblingWindows(TumblingEventTimeWindows):
    """Tumbling assigner with an amortized per-record service cost that
    releases the GIL (one bulk sleep per `bulk` records — assign_windows
    runs exactly once per record, unlike the reduce fn which skips each
    window's first record). More task threads therefore mean genuinely
    more capacity even inside one test process. cost_s=0 is a plain
    assigner (oracle/expected runs). The bulk granule is coarse (~30 ms)
    so sandbox sleep overshoot stays a small RELATIVE error — measured
    utilization must track the nominal service cost, not timer jitter."""

    def __init__(self, size_ms, cost_s=0.0, bulk=150):
        super().__init__(size_ms)
        self.cost_s = cost_s
        self.bulk = bulk
        self._n = 0

    def assign_windows(self, element, timestamp):
        if self.cost_s:
            self._n += 1
            if self._n % self.bulk == 0:
                time.sleep(self.cost_s * self.bulk)
        return super().assign_windows(element, timestamp)


class _PacedLoadBatches:
    """Partition-invariant load profile, arrival-paced (picklable).

    profile[s] = records in step s ACROSS shards; each shard takes its
    keys[shard::num_shards] slice of the deterministically generated step
    batch, so the per-step union is identical at any parallelism. With
    `interval_s` set, step s blocks until its scheduled arrival time
    (anchored at this attempt's first access, so replay after a rescale
    resumes paced rather than bursting). 64 keys split the 16 key groups
    evenly, so traffic balances 50/50 across two shards."""

    def __init__(self, profile, interval_s, shard, num_shards, n_keys=64):
        self.profile = list(profile)
        self.interval_s = interval_s
        self.shard = shard
        self.num_shards = num_shards
        self.n_keys = n_keys
        self._anchor = None

    def __len__(self):
        return len(self.profile)

    def __getitem__(self, s):
        if self.interval_s:
            now = time.monotonic()
            if self._anchor is None:
                self._anchor = (now, s)
            due = self._anchor[0] + (s - self._anchor[1]) * self.interval_s
            if due > now:
                time.sleep(due - now)
        rng = np.random.default_rng(9000 + s)      # per-STEP determinism
        n = self.profile[s]
        keys = np.asarray([f"k{v}" for v in rng.integers(0, self.n_keys, n)],
                          dtype=object)
        vals = np.ones(n, dtype=np.float64)
        ts = (s * 1000 + rng.integers(0, 1000, n)).astype(np.int64)
        sl = slice(self.shard, None, self.num_shards)
        return keys[sl], vals[sl], ts[sl], s * 1000 + 500


class _LoadFactory:
    def __init__(self, profile, interval_s):
        self.profile = list(profile)
        self.interval_s = interval_s

    def __call__(self, shard, num_shards):
        return _PacedLoadBatches(self.profile, self.interval_s, shard,
                                 num_shards)


def _load_spec(profile, interval_s, cost_s=0.0):
    from flink_tpu.runtime.cluster import DistributedJobSpec

    return DistributedJobSpec(
        name="load-step",
        source_factory=_LoadFactory(profile, interval_s),
        assigner=_MeteredTumblingWindows(2000, cost_s=cost_s),
        aggregate="sum",
        max_parallelism=16,
    )


def _expected_results(profile):
    """Fixed-parallelism oracle run of the same profile (unpaced, costless)."""
    from flink_tpu.ops.aggregators import resolve
    from flink_tpu.runtime.oracle_window_operator import OracleWindowOperator

    op = OracleWindowOperator(TumblingEventTimeWindows.of(2000),
                              resolve("sum").python_equivalent(),
                              max_parallelism=16)
    batches = _PacedLoadBatches(profile, 0.0, 0, 1)
    for s in range(len(batches)):
        keys, vals, ts, wm = batches[s]
        for i in range(len(keys)):
            op.process_record(keys[i], float(vals[i]), int(ts[i]))
        op.process_watermark(wm)
    op.process_watermark((1 << 63) - 1)
    return {(k, w.start): r for k, w, r, _ in op.drain_output()}


def _collect(result):
    return {(k, w[0]): r for k, w, r, _ in result}


def _wait(predicate, timeout, interval=0.2, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def test_manual_rescale_up_preserves_results(tmp_path):
    """The rescale executor alone (no policy): deliberate 2->3 then 3->2
    rescales of a RUNNING keyed job rewind to the latest checkpoint,
    remap key-groups onto the new slot set, and results stay exact. Each
    rescale shows up in num_rescales, lastRescaleDurationMs, and the
    recovery timeline as kind='rescale' with a nonzero restore duration —
    without consuming the restart-attempts budget. Also pins the rescale
    hygiene sweeps: stale-attempt heartbeats are dropped, in-flight
    checkpoints fail instead of pending forever."""
    from flink_tpu.runtime.cluster import (
        JobManagerEndpoint,
        TaskExecutorEndpoint,
    )
    from flink_tpu.runtime.rpc import RpcService

    profile = [120] * 50
    spec = _load_spec(profile, interval_s=0.08)
    svc_jm, svc_tm = RpcService(), RpcService()
    jm = JobManagerEndpoint(
        svc_jm, checkpoint_dir=str(tmp_path / "chk"),
        heartbeat_interval=0.2, heartbeat_timeout=10.0,
    )
    te = TaskExecutorEndpoint(svc_tm, slots=3, shipping_interval_ms=200)
    te.connect(svc_jm.address)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    job_id = client.submit_job(spec.to_bytes(), 2)
    try:
        # rejected pre-checkpoint: nothing to rewind to
        r = client.rescale_job(job_id, 3)
        assert not r["accepted"] and "checkpoint" in r["detail"]

        _wait(lambda: client.trigger_checkpoint(job_id)
              and client.job_status(job_id)["checkpoints"],
              30, desc="first completed checkpoint")
        r = client.rescale_job(job_id, 3, "test scale-up")
        assert r["accepted"], r
        # same-parallelism and over-capacity targets are refused
        _wait(lambda: client.job_status(job_id)["status"] in
              ("RUNNING", "FINISHED"), 30, desc="rescale redeploy")
        assert not client.rescale_job(job_id, 3)["accepted"]
        assert not client.rescale_job(job_id, 9)["accepted"]
        # beyond the spec's key-group count: refused regardless of slots
        r = client.rescale_job(job_id, 17)
        assert not r["accepted"] and "max-parallelism" in r["detail"]

        # a late heartbeat carrying the CANCELLED attempt's snapshots is
        # dropped by the attempt guard (it would otherwise re-land after
        # the redeploy's clear and pollute the new attempt's aggregates —
        # and the autoscaler's signal windows — forever); 2-tuple legacy
        # keys from older TMs stay accepted
        job = jm._jobs[job_id]
        stale = job.attempt - 1
        jm.heartbeat_tm(te.tm_id, steps={(job_id, 7, stale): 999},
                        metrics={(job_id, 7, stale):
                                 {"job.numRecordsIn": 1e9}})
        assert 7 not in job.steps and 7 not in job.metric_snapshots
        jm.heartbeat_tm(te.tm_id, metrics={(job_id, 7): {"probe": 1.0}})
        assert 7 in job.metric_snapshots
        del job.metric_snapshots[7]

        # a checkpoint in flight when a rescale lands can never complete
        # (the attempt guard rejects the dead attempt's acks, checkpoint
        # ids are not reused): its stats record is swept to FAILED, like
        # the _fail_job path, instead of sitting IN_PROGRESS forever
        cp2 = _wait(lambda: client.trigger_checkpoint(job_id), 30,
                    desc="checkpoint trigger on the rescaled attempt")
        r = client.rescale_job(job_id, 2, "down while checkpoint pending")
        assert r["accepted"], r
        rec = client.job_checkpoint(job_id, cp2)
        assert rec["status"] == "FAILED", rec
        assert "superseded by rescale 3->2" in rec["failure_cause"]
        assert client.job_checkpoints(job_id)["counts"]["in_progress"] == 0

        st = _wait(lambda: (lambda s: s if s["status"] == "FINISHED" else None)(
            client.job_status(job_id)), 90, desc="job finish")
        assert st["restarts"] == 0          # budget untouched
        assert st["rescales"] == 2
        assert st["parallelism"] == 2

        auto = client.job_autoscaler(job_id)
        assert auto["num_rescales"] == 2
        assert auto["last_rescale_duration_ms"] > 0
        assert auto["enabled"] is False      # no policy attached
        recs = client.job_exceptions(job_id)["recoveries"]
        rescales = [r for r in recs if r.get("kind") == "rescale"]
        assert len(rescales) == 2
        assert all(r["restore_duration_ms"] > 0 for r in rescales)
        assert "rescale 3->2" in rescales[0]["cause"]   # newest first
        assert "rescale 2->3" in rescales[1]["cause"]
        metrics = client.job_metrics(job_id)
        assert metrics["jm"]["job.numRescales"] == 2
        assert metrics["jm"]["job.lastRescaleDurationMs"] > 0

        assert _collect(client.job_result(job_id)) == _expected_results(profile)
    finally:
        te.stop()
        jm.heartbeats.stop()
        svc_jm.stop()
        svc_tm.stop()


# ---------------------------------------------------------------------------
# 5. load-spike acceptance e2e (ROADMAP item 2)
# ---------------------------------------------------------------------------

# arrival interval and amortized per-record service cost: at cost 0.2 ms a
# single shard saturates near interval/cost ~ 280 records/step (with
# per-record overhead), so the 2x step (324) saturates p=1 while p=2 keeps
# ~0.55 nominal utilization per shard — enough headroom that sandbox timer
# overshoot cannot saturate p=2 and pile an arrival backlog into the low
# phase (a drained backlog reads as busy and masks the scale-down signal)
_INTERVAL_S = 0.062
_COST_S = 0.0002
_PRE, _HIGH, _LOW = 162, 324, 40
_PROFILE = [_PRE] * 40 + [_HIGH] * 120 + [_LOW] * 90


def test_autoscaler_load_spike_scales_up_then_down(tmp_path):
    """Acceptance: under autoscaler.enabled, a 2x traffic step saturates
    the single shard and triggers a scale-up; measured throughput after
    adaptation recovers to >= 1.5x the pre-step rate; the later load drop
    triggers a scale-down; every rescale appears in the decision log AND
    the recovery timeline with nonzero restore duration; final results are
    exactly-once against a fixed-parallelism oracle."""
    from flink_tpu.runtime.cluster import (
        JobManagerEndpoint,
        TaskExecutorEndpoint,
    )
    from flink_tpu.runtime.rpc import RpcService

    pre_rate = _PRE / _INTERVAL_S                     # records/s offered
    cfg = (Configuration()
           .set(AutoscalerOptions.ENABLED, True)
           .set(AutoscalerOptions.POLICY, "threshold")
           .set(AutoscalerOptions.MIN_PARALLELISM, 1)
           .set(AutoscalerOptions.MAX_PARALLELISM, 4)
           .set(AutoscalerOptions.INTERVAL_MS, 200)
           .set(AutoscalerOptions.SIGNAL_WINDOW, 6)
           .set(AutoscalerOptions.STABILIZATION_INTERVAL_MS, 1500)
           # thresholds sit far from every steady-state reading: the pre
           # phase reads ~0.6 busy, p=1 saturation ~0.87+ (the self-channel
           # fast path keeps the shuffle transit out of idle; checkpoint
           # cost is excluded from busy), p=2 high ~0.55-0.7, the low
           # phase ~0.1-0.3 — sandbox timer jitter inflates sleeps, so
           # each phase needs real margin to its deciding threshold
           .set(AutoscalerOptions.SCALE_UP_THRESHOLD, 0.85)
           .set(AutoscalerOptions.SCALE_DOWN_THRESHOLD, 0.45))
    spec = _load_spec(_PROFILE, _INTERVAL_S, cost_s=_COST_S)
    svc_jm, svc_tm = RpcService(), RpcService()
    jm = JobManagerEndpoint(
        svc_jm, checkpoint_dir=str(tmp_path / "chk"),
        checkpoint_interval=0.3, heartbeat_interval=0.2,
        heartbeat_timeout=15.0, autoscaler_config=cfg,
    )
    te = TaskExecutorEndpoint(svc_tm, slots=4, shipping_interval_ms=200)
    te.connect(svc_jm.address)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    job_id = client.submit_job(spec.to_bytes(), 1)
    try:
        # measure the DELIVERED pre-step rate on the same wall clock the
        # coordinator uses: under sandbox contention every paced sleep
        # stretches, so the nominal offered rate can overstate what this
        # run could ever deliver — the 1.5x recovery bar below is honest
        # only against min(nominal, delivered). Count-bounded endpoints
        # keep the window inside the pre phase at any slowdown.
        def _records_in():
            agg = client.job_metrics(job_id).get("job") or {}
            return float(agg.get("job.numRecordsIn", 0.0))

        c0 = _wait(lambda: (lambda c: c >= 2 * _PRE and c)(_records_in()), 30,
                   interval=0.05, desc="pre-phase traffic")
        t0 = time.monotonic()
        c1 = _wait(lambda: (lambda c: c >= c0 + 25 * _PRE and c)(_records_in()),
                   60, interval=0.05, desc="pre-phase measurement window")
        measured_pre = (c1 - c0) / (time.monotonic() - t0)

        # the 2x step must trigger a policy scale-up within a bounded window
        _wait(lambda: client.job_status(job_id)["rescales"] >= 1, 30,
              interval=0.1, desc="policy-driven scale-up")
        st = client.job_status(job_id)
        assert st["parallelism"] >= 2, st
        auto = client.job_autoscaler(job_id)
        ups = [d for d in auto["decisions"]
               if d["action"] == "scale-up" and d["outcome"] == "executed"]
        assert ups, auto["decisions"]
        # the decision was driven by genuine saturation: the windowed
        # utilization the policy saw cleared its configured threshold
        assert ups[-1]["signals"]["utilization"] >= 0.85
        # the tick's metric view carries the JM-side checkpoint gauges: an
        # executed rescale required a completed checkpoint, so the signals
        # it decided on must show its duration (0 would mean the signal
        # extractor never saw job.lastCheckpointDuration on this path)
        assert ups[-1]["signals"]["checkpoint_duration_ms"] > 0

        # measure the delivered post-rescale rate over a ~20-step window
        # on the same clock as the pre measurement, while the high phase
        # is still offering. The redeploy reset the records counter (and
        # any further rescale resets it again), so the anchor re-arms on
        # a backwards step and the window is wholly within one attempt.
        anchor = [None]

        def _high_rate():
            c = _records_in()
            if anchor[0] is None or c < anchor[0][1]:
                anchor[0] = (time.monotonic(), c)
                return None
            if c >= anchor[0][1] + 20 * _HIGH:
                return (c - anchor[0][1]) / (time.monotonic() - anchor[0][0])
            return None

        measured_high = _wait(_high_rate, 60, interval=0.05,
                              desc="post-rescale measurement window")

        # the load drop scales back down
        _wait(lambda: client.job_status(job_id)["rescales"] >= 2
              or client.job_status(job_id)["status"] == "FINISHED", 45,
              interval=0.1, desc="scale-down after load drop")
        st = _wait(lambda: (lambda s: s if s["status"] == "FINISHED" else None)(
            client.job_status(job_id)), 90, desc="job finish")
        assert st["rescales"] >= 2, st
        assert st["parallelism"] == 1, st
        assert st["restarts"] == 0, st      # no failures, only rescales

        auto = client.job_autoscaler(job_id)
        downs = [d for d in auto["decisions"]
                 if d["action"] == "scale-down" and d["outcome"] == "executed"]
        assert downs, auto["decisions"]
        assert downs[-1]["signals"]["utilization"] <= 0.45
        assert auto["num_rescales"] == st["rescales"]
        assert auto["last_rescale_duration_ms"] > 0
        executed = [d for d in auto["decisions"] if d["outcome"] == "executed"]
        assert all(d["duration_ms"] > 0 for d in executed)

        # throughput after adaptation recovered to >= 1.5x the pre-step
        # rate — p=1 capacity (~1.4x) cannot reach this, only the rescale
        # can. The bar is the slower of the nominal offered rate and the
        # rate this run actually delivered pre-step (both capacity and
        # offered rate stretch by the same factor under contention, so
        # the saturation story and the 1.5x margin are preserved); the
        # measurement is the in-test 20-step window above — wide enough
        # that one shipping stall cannot under-read a healthy rescale.
        assert measured_pre > 0
        baseline = min(pre_rate, measured_pre)
        assert measured_high >= 1.5 * baseline, (
            measured_high, pre_rate, measured_pre,
            client.job_status(job_id), client.job_autoscaler(job_id))
        # the coordinator's own (shorter) post-stabilization measurement
        # also settled and closed the loop into the learning policy.
        # Payload entries are copies, so re-read the settled log.
        ups = [d for d in auto["decisions"]
               if d["action"] == "scale-up" and d["outcome"] == "executed"]
        settled = [d for d in ups if d["throughput_after"] is not None]
        assert settled, f"scale-up outcome never settled: {auto['decisions']}"
        assert settled[-1]["throughput_after"] > 0

        # every rescale in the recovery timeline, restore durations nonzero
        recs = client.job_exceptions(job_id)["recoveries"]
        rescales = [r for r in recs if r.get("kind") == "rescale"]
        assert len(rescales) == st["rescales"]
        assert all(r["restore_duration_ms"] > 0 for r in rescales)
        assert all(r["restored_checkpoint_id"] is not None for r in rescales)

        # exactly-once: no dropped or duplicated outputs vs the oracle
        assert _collect(client.job_result(job_id)) == _expected_results(_PROFILE)
    finally:
        te.stop()
        jm.heartbeats.stop()
        svc_jm.stop()
        svc_tm.stop()


# ---------------------------------------------------------------------------
# 6. MiniCluster: observe-only autoscaler
# ---------------------------------------------------------------------------

def test_minicluster_autoscaler_observe_only():
    """An in-process job runs as one task, so the coordinator attaches in
    observe-only mode: decisions are logged (outcome 'observe-only'),
    gauges register, nothing rescales."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.config import ExecutionOptions
    from flink_tpu.connectors.sink import CollectSink
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.runtime.minicluster import MiniCluster
    from flink_tpu.utils.arrays import obj_array

    def gen(idx):
        return Batch(obj_array([int(i) & 7 for i in idx]),
                     (idx * 10).astype("int64"))

    cfg = (Configuration()
           .set(ExecutionOptions.BATCH_SIZE, 1024)
           .set(AutoscalerOptions.ENABLED, True)
           .set(AutoscalerOptions.INTERVAL_MS, 10)
           .set(AutoscalerOptions.STABILIZATION_INTERVAL_MS, 0)
           # threshold 0 => every warm window proposes a scale-up, so the
           # observe-only log fills deterministically
           .set(AutoscalerOptions.SCALE_UP_THRESHOLD, 0.0))
    env = StreamExecutionEnvironment(cfg)
    env.from_source(
        DataGeneratorSource(gen, count=100_000),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    ).map(lambda x: x).sink_to(CollectSink())
    client = env.execute_async("observe-only")
    MiniCluster.get_shared().jobs.setdefault(client.job_id, client)
    client.wait(60)

    auto = getattr(client, "autoscaler", None)
    assert auto is not None
    payload = auto.payload(client.job_id)
    assert payload["enabled"] and payload["num_rescales"] == 0
    assert payload["decisions"], "no decisions logged"
    assert all(d["outcome"] == "observe-only" for d in payload["decisions"])
    assert all(d["action"] == "scale-up" for d in payload["decisions"])
    # gauges registered on the job registry
    metrics = {k: m.value() for k, m in client.metrics.all_metrics().items()}
    assert metrics.get("job.numRescales") == 0
