"""Bench smoke gate for the API-path scenario (whole-graph fusion).

Runs the real `bench.api_path_microbench` at smoke scale and asserts the
result JSON carries the keys every BENCH_*.json must now track — so a
regression that silently reroutes the DataStream API back to the slow
ChainRunner path (fused_selected False) or breaks result parity fails
tier-1, not just a human eyeballing the next bench run. Throughput
NUMBERS are deliberately not asserted (sandbox scheduler noise); the
structural keys and the parity/fused-selection booleans are the gate.
"""

import importlib.util
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_smoke", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    # smoke scale: small batch + few events keeps compile+run well under a
    # minute on the CPU backend while exercising warmup, parity (all three
    # paths), and the timed sweeps exactly as the real bench does
    return bench.api_path_microbench(events=8192, batch=2048)


def test_result_carries_the_tracked_keys(result):
    for key in (
        "api_path_tuples_per_sec",
        "chain_runner_tuples_per_sec",
        "scalar_api_tuples_per_sec",
        "speedup_vs_chain_runner",
        "speedup_vs_scalar_api",
        "parity",
        "fused_selected",
    ):
        assert key in result, f"bench result JSON lost {key!r}"
    assert result["api_path_tuples_per_sec"] > 0


def test_api_path_parity_is_exact(result):
    assert result["parity"] is True, (
        "fused vs chain vs per-record scalar results diverged — the API "
        "path is emitting different windows than the oracle"
    )


def test_fused_runner_is_actually_selected(result):
    assert result["fused_selected"] is True, (
        "graph translation no longer routes the benchmark program to "
        "DeviceChainRunner — parity would still hold on the slow path, "
        "so this flag is the reroute gate"
    )


def test_windows_were_emitted(result):
    assert result["windows_emitted"] > 0
