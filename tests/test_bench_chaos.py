"""Bench smoke gate for the chaos scenario matrix (ISSUE-10).

Runs the real `bench.chaos_microbench` (the full 7-scenario matrix at its
normal smoke scale — MiniCluster + distributed paths, short paced jobs)
and asserts the result JSON carries the `chaos.*` keys every BENCH_*.json
must now track — so a regression that silently breaks a hardening layer
(rpc retry dropped, reconnect window wired to 0, tolerance off, restore
no longer skipping torn checkpoints, the injected attribution lost) fails
tier-1, not just the next human bench read. Per the acceptance criteria
the gate fails on ANY scenario with parity=false, any failed scenario,
and any scenario whose injected fault lost its `injected: true`
ExceptionHistory attribution.
"""

import importlib.util
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"

#: scenarios whose injected fault causes an ExceptionHistory-visible
#: failure and must therefore carry injected-attribution (the others'
#: faults are absorbed by hardening, or surface as storage/TM-loss causes
#: asserted inside the scenario itself)
ATTRIBUTED_SCENARIOS = {"device-dispatch-error", "storage-brownout",
                        "latency-mode-restore"}


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_chaos_smoke", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    return bench.chaos_microbench()


def test_result_carries_the_tracked_chaos_keys(result):
    for key in ("scenarios", "scenarios_total", "scenarios_passed",
                "parity", "recovery_time_ms_p50"):
        assert key in result, f"bench chaos block lost {key!r}"
    assert result["scenarios_total"] >= 7, (
        "the scenario matrix shrank below the 7 named scenarios")


def test_every_scenario_passes_with_parity(result):
    failed = [(s["name"], s["detail"]) for s in result["scenarios"]
              if not s["passed"]]
    assert not failed, f"chaos scenarios failed: {failed}"
    assert result["parity"], "a scenario lost exactly-once parity"
    assert result["scenarios_passed"] == result["scenarios_total"]


def test_matrix_covers_both_execution_paths(result):
    paths = {s["path"] for s in result["scenarios"]}
    assert {"mini", "distributed"} <= paths, (
        f"scenario matrix no longer covers both execution paths: {paths}")


def test_injected_faults_actually_fired(result):
    # skipped scenarios (a precondition the backend cannot meet, e.g. no
    # mesh on a single-device host) legitimately inject nothing — the
    # explicit flag is what distinguishes them from a seam losing its hook
    dry = [s["name"] for s in result["scenarios"]
           if not s["injected_fired"] and not s.get("skipped")]
    assert not dry, (
        f"scenarios ran with ZERO injected faults — the seams lost their "
        f"hooks: {dry}")


def test_chip_loss_scenario_not_skipped_on_the_virtual_mesh(result):
    by_name = {s["name"]: s for s in result["scenarios"]}
    assert "chip-loss-sharded" in by_name
    assert not by_name["chip-loss-sharded"].get("skipped"), (
        "chip-loss-sharded skipped on the forced 8-device virtual mesh — "
        "the reduced-mesh recovery path went unexercised")


def test_failure_causing_injections_are_attributed(result):
    by_name = {s["name"]: s for s in result["scenarios"]}
    for name in ATTRIBUTED_SCENARIOS:
        assert name in by_name, f"scenario {name!r} vanished from the matrix"
        assert by_name[name].get("attributed") is True, (
            f"{name}: injected fault lost its injected:true "
            "ExceptionHistory attribution")


def test_recovery_time_is_measured(result):
    # at least the restart-driven scenarios must contribute a recovery
    # downtime sample, or resilience stops being tracked per PR
    assert result["recovery_time_ms_p50"] is not None
    assert result["recovery_time_ms_p50"] > 0
