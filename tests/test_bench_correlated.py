"""Bench smoke gate for the shared-partials (correlated windows) scenario
(ISSUE-14, Factor Windows).

Runs the real `bench.correlated_windows_microbench` at smoke scale (the
virtual 8-device CPU mesh from tests/conftest.py gives the mesh leg
devices) and asserts the result JSON carries the `correlated_windows.*`
keys every BENCH_*.json must now track — so the sharing speedup can't
silently regress: a change that reroutes the 1m/5m/1h job back to three
independent programs (`shared_selected` false), breaks shared-vs-
independent parity on either leg, stops planning the group, or craters
the speedup fails tier-1, not just a human eyeballing the next bench run.

Absolute throughput is deliberately not asserted, and the CPU speedup
floor is a catastrophic-regression guard only: at smoke scale on a
shared 2-vCPU host the per-dispatch fixed costs dominate and the
~sharing-factor acceptance bar (shared beats N independent fused runs by
roughly N) is judged at full scale, where the saved (N-1) ingest scans
are the dominant cost.
"""

import importlib.util
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"

#: catastrophic-regression floor: the shared program doing the work of
#: three must never cost more than ~3x the three separate programs — that
#: would mean the shared scan stopped sharing anything (or recompiles per
#: dispatch) and the optimizer actively hurts
CPU_SHARING_SPEEDUP_FLOOR = 0.3


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_correlated_smoke",
                                                  _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    # smoke scale, one sweep: the gate must stay well under two minutes
    # on the CPU backend; distinctive batch so compiled shapes are ours
    return bench.correlated_windows_microbench(events=1 << 19, batch=24576,
                                               sweeps=1)


def test_result_carries_the_tracked_correlated_keys(result):
    assert "error" not in result, result.get("error")
    for key in (
        "shared_tuples_per_sec",
        "independent_tuples_per_sec",
        "speedup_vs_independent",
        "parity",
        "shared_selected",
        "groups_planned",
        "sharing_factor_estimate",
        "granule_ms",
        "mesh",
    ):
        assert key in result, f"bench correlated block lost {key!r}"


def test_sharing_optimizer_actually_selected(result):
    """The reroute gate: translation must build ONE SharedWindowRunner for
    the 1m/5m/1h group — parity alone would still pass if the optimizer
    silently stopped firing and both legs ran three independent programs."""
    assert result["groups_planned"] >= 1, "the sharing optimizer planned 0 groups"
    assert result["shared_selected"], (
        "build_runners no longer selects SharedWindowRunner for the "
        "correlated group — the scenario would compare identical paths"
    )
    assert result["granule_ms"] == 60_000, (
        "the 1m/5m/1h group's shared granule must be the gcd (1m); a "
        "different granule means the slice decomposition changed"
    )


def test_shared_vs_independent_parity(result):
    assert result["parity"], (
        "shared-partial emissions diverged from the independent fused "
        "programs — sharing must be a perf switch, never a semantics switch"
    )
    assert all(n > 0 for n in result["windows_emitted"]), (
        f"some member window emitted nothing: {result['windows_emitted']}"
    )


def test_mesh_leg_runs_and_holds_parity(result):
    mesh = result["mesh"]
    assert "skipped" not in mesh, f"mesh leg skipped: {mesh}"
    assert mesh["devices"] >= 2
    assert mesh["parity"], (
        "shared-partials diverged from independent programs ON THE MESH — "
        "the sharded shared ring lost exactness"
    )


def test_sharing_speedup_above_catastrophic_floor(result):
    assert result["speedup_vs_independent"] >= CPU_SHARING_SPEEDUP_FLOOR, (
        f"shared-partial program runs {result['speedup_vs_independent']}x "
        "the independent plans at smoke scale — the shared scan stopped "
        "sharing (the ~sharing-factor bar itself is judged at full scale)"
    )
