"""Bench smoke gate for the device-plane scenario (ISSUE-8).

Runs the real `bench.device_plane_microbench` at smoke scale and asserts
the result JSON carries the `device.*` keys every BENCH_*.json must now
track — so a regression that silently stops counting compiles (the jit
entry points losing their CompileTracker wrap), drops the recompile cause
attribution, or zeroes the phase/key telemetry fails tier-1, not just a
human eyeballing the next bench run. Throughput and overhead NUMBERS are
deliberately not asserted (sandbox scheduler noise; the <= 2% overhead
acceptance is judged on the real bench at full scale) — the structural
keys, the nonzero compile count, and the attributed recompile causes are
the gate (the PR-7 reroute-gate pattern).
"""

import importlib.util
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_device_smoke", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    # smoke scale: distinctive key capacity + batch so the jitted
    # executables are this run's own (a geometry another test already
    # compiled would hide the compile events); one sweep keeps the gate
    # well under a minute on the CPU backend
    return bench.device_plane_microbench(events=49152, batch=2048,
                                         num_keys=384, sweeps=1)


def test_result_carries_the_tracked_device_keys(result):
    for key in (
        "tuples_per_sec_on",
        "tuples_per_sec_off",
        "overhead_pct",
        "numCompiles",
        "numRecompiles",
        "compileTimeMsTotal",
        "recompileStorm",
        "recompile_causes",
        "hbmUtilizationPct",
        "flopsUtilizationPct",
        "phases",
        "keySkew",
        "activeKeys",
    ):
        assert key in result, f"bench device block lost {key!r}"


def test_compile_count_is_nonzero(result):
    assert result["numCompiles"] > 0, (
        "zero compiles observed — the superscan dispatch sites lost their "
        "CompileTracker wrap, so the bench can no longer detect "
        "recompile-thrashing regressions"
    )
    assert result["compileTimeMsTotal"] > 0


def test_induced_recompile_is_cause_attributed(result):
    assert "ring-doubling" in result["recompile_causes"], (
        "the induced key-dictionary growth no longer surfaces as a "
        "ring-doubling recompile in the event ring"
    )


def test_phase_counters_and_key_telemetry_populate(result):
    phases = result["phases"]
    # the traced filter keeps 1/3 of the stream; every survivor ingests
    assert phases["ingestRecords"] > 0
    assert phases["fireSteps"] > 0
    assert result["keySkew"] is not None and result["keySkew"] >= 1.0
    assert result["activeKeys"] > 0
    assert result["hotKeys"]


def test_throughput_measured_on_both_sides(result):
    assert result["tuples_per_sec_on"] > 0
    assert result["tuples_per_sec_off"] > 0
