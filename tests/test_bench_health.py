"""Bench smoke gate for the history/doctor plane (ISSUE-19).

Runs the real `bench.health_microbench` at smoke scale and asserts the
result carries the `health.*` keys every BENCH_*.json must now track: a
regression that silently empties the history rings (the sampler stopped
ticking), loses the counter-rate derivation, returns verdict "unknown"
on an undisturbed flagship-shaped leg, or lets the sampler's measured
overhead blow past the catastrophic floor fails tier-1, not just a human
eyeballing the next bench run.

The <=2% sampler-overhead budget is judged on real TPU hardware over the
full flagship run — at smoke scale on a shared CPU the self-timed ratio
is dominated by the short wall clock, so this gate pins only the
CATASTROPHIC floor (a per-record sampling bug costs integer multiples,
not fractions of a percent).
"""

import importlib.util
import os
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_health_smoke",
                                                  _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    # smoke scale: one short MiniCluster job — the gate checks structure
    # and ring liveness, never absolute rates
    os.environ["BENCH_HEALTH_EVENTS"] = str(1 << 18)
    try:
        res = bench.health_microbench()
        # counter rates need two sampler ticks; a scheduler stall on a
        # shared 1-CPU runner can compress the whole run into one tick
        # even though the structural margin is several-fold. A real
        # regression (the tick gate broken, the rate derivation lost)
        # fails EVERY run, so one retry bounds the false-failure rate
        # without masking real breakage.
        if res["sample_count"] < 2 or res["rate_series"] == 0:
            res = bench.health_microbench()
        return res
    finally:
        os.environ.pop("BENCH_HEALTH_EVENTS", None)


def test_result_carries_the_tracked_health_keys(result):
    for key in (
        "verdict",
        "verdict_score",
        "diagnoses",
        "watchdog_events",
        "sampler_overhead_pct",
        "sample_count",
        "sample_time_ms",
        "history_series",
        "history_points",
        "rate_series",
        "interval_ms",
        "tuples_per_sec",
        "workload",
    ):
        assert key in result, f"health block lost {key!r}"


def test_doctor_reached_a_verdict(result):
    """"unknown" means the sampler never ticked — the history plane went
    dark and the doctor had nothing to diagnose. Any real verdict
    (healthy, or compile-stall on a short CPU leg where XlaCompile
    genuinely dominates) passes; the absence of observation fails."""
    assert result["verdict"] != "unknown", (
        "doctor verdict 'unknown' on an undisturbed flagship-shaped leg "
        "— did the history sampler stop ticking?")
    assert result["sample_count"] >= 1


def test_history_rings_are_live(result):
    """Empty rings mean the processing-time tick lost the sampling hook
    (or the snapshot went dunder-only). Counter families must appear as
    derived counter-rate series — that is the contract the doctor's
    throughput-collapse detector reads."""
    assert result["history_series"] > 0, "history rings are empty"
    assert result["history_points"] > 0, "history rings hold no points"
    assert result["rate_series"] > 0, (
        "no counter-rate series — counters are no longer recorded as "
        "windowed rates")


def test_sampler_overhead_below_catastrophic_floor(result):
    """A per-record (or per-batch-synchronous) sampling regression costs
    integer multiples of wall time; the measured steady-state cost is
    fractions of a percent even at a 20x-default tick rate. The tier-1
    floor sits between."""
    assert result["sampler_overhead_pct"] < 10.0, (
        "history sampler costs a structural fraction of wall time — is "
        "it sampling per record instead of per interval tick?")
