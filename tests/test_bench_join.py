"""Bench smoke gate for the streaming-join scenarios (ISSUE-16).

Runs the real `bench.join_microbench` at smoke scale on the virtual
8-device CPU mesh (tests/conftest.py forces it) and asserts the result
carries the `join.*` keys every BENCH_*.json must now track: a
regression that silently drops a NEXMark scenario, breaks device-vs-host
join parity (uniform or zipf), stops selecting the fused runner, loses
the sharded leg, or lets SQL fall back UNATTRIBUTED fails tier-1, not
just a human eyeballing the next bench run.

The >= 20x device-vs-host bar is judged on real TPU hardware (the host
oracle's dict probes are exactly what the CPU "device" leg also pays at
smoke scale) — this gate pins selection and parity, never the ratio.
"""

import importlib.util
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_join_smoke",
                                                  _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    # smoke scale, distinctive geometry (the bench-gate pattern)
    return bench.join_microbench(events=3072, batch=512, num_keys=384)


def test_result_carries_the_tracked_join_keys(result):
    assert "error" not in result, result.get("error")
    for key in (
        "devices",
        "scenarios",
        "parity",
        "fused_selected",
        "join_tuples_per_sec",
        "host_join_tuples_per_sec",
        "speedup_vs_host_join",
        "sharded",
        "sql",
        "workload",
    ):
        assert key in result, f"bench join block lost {key!r}"
    assert "error" not in result["sharded"], result["sharded"]
    assert "error" not in result["sql"], result["sql"]


def test_both_nexmark_scenarios_present_with_throughput(result):
    for name in ("nexmark_q3", "nexmark_q8"):
        blk = result["scenarios"].get(name)
        assert blk is not None, f"bench lost the {name} scenario"
        assert blk["matches"] > 0, f"{name} emitted no join rows"
        assert blk["join_tuples_per_sec"] > 0
        assert blk["host_join_tuples_per_sec"] > 0
        assert blk["speedup_vs_host_join"] > 0


def test_exact_parity_on_every_leg(result):
    """Device ring vs host oracle, uniform AND zipf, both scenarios —
    the zipf leg is the one that exercises adaptive bucket growth."""
    assert result["parity"]
    for name, blk in result["scenarios"].items():
        assert blk["parity_uniform"], f"{name} uniform parity broken"
        assert blk["parity_zipf"], f"{name} zipf parity broken"


def test_fused_runner_actually_selected(result):
    assert result["fused_selected"], (
        "the factory no longer picks DeviceJoinRunner for a windowed "
        "event-time inner equi-join")


def test_sharded_leg_selected_and_at_parity(result):
    assert result["sharded"]["sharded_selected"], (
        "mesh available but the join rode the unsharded pipeline")
    assert result["sharded"]["parity"], (
        "sharded mesh join diverged from the single-chip rows")


def test_sql_join_lowering_fused_and_attributed(result):
    sql = result["sql"]
    assert sql["sql_fused_selected"], (
        "SQL windowed JOIN no longer selects the fused device runner")
    assert "device=join-ring" in sql["explain"]
    assert sql["parity"], "SQL fused vs interpreted rows diverged"
    assert sql["fallback_attributed"], (
        "FULL OUTER JOIN fell back without the catalogued "
        "join-full-outer reason")
