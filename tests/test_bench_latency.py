"""Bench smoke gate for the latency x throughput frontier (ISSUE-17).

Runs the real `bench.latency_frontier_microbench` at smoke scale and
asserts the result carries the `latency_frontier.*` keys every
BENCH_*.json must now track: a regression that silently drops a load
point, breaks pacing-vs-oracle parity, stops recording emission samples
(the plane went dark), or lets the plane's overhead blow past the
catastrophic floor fails tier-1, not just a human eyeballing the next
bench run.

The <2% overhead budget is judged on real TPU hardware over the full
flagship run — at smoke scale on a shared CPU the on/off delta is mostly
scheduler noise, so this gate pins only the CATASTROPHIC floor (a
serializing bug like stamping at dispatch costs integer multiples, not
percents).
"""

import importlib.util
import os
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_latency_smoke",
                                                  _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    # smoke scale: short legs, one sweep — the gate checks structure and
    # parity, never absolute rates
    os.environ["BENCH_LATENCY_EVENTS"] = str(1 << 15)
    os.environ["BENCH_LATENCY_LEG_S"] = "0.6"
    os.environ["BENCH_LATENCY_SWEEPS"] = "1"
    try:
        res = bench.latency_frontier_microbench(batch=4096)
        # the two timing gates compare a p99 drawn from the handful of
        # window fires a smoke leg produces, so one scheduler stall on a
        # shared 1-CPU runner can flip them even though the structural
        # margin is several-fold. A real regression (the rung never
        # shrinking, the controller pinned small at saturation) costs
        # integer multiples and fails EVERY run, so one retry bounds the
        # false-failure rate without masking real breakage.
        tp = res["latency_frontier"]["load_points"]["25"]["p99_emission_ms"]
        if (res["latency_mode_p99_ms"] >= tp
                or res["latency_mode_peak_fraction"] <= 0.4):
            res = bench.latency_frontier_microbench(batch=4096)
        return res
    finally:
        for k in ("BENCH_LATENCY_EVENTS", "BENCH_LATENCY_LEG_S",
                  "BENCH_LATENCY_SWEEPS"):
            os.environ.pop(k, None)


def test_result_carries_the_tracked_frontier_keys(result):
    assert "latency_frontier" in result
    fr = result["latency_frontier"]
    for key in (
        "peak_tuples_per_sec",
        "plane_on_tuples_per_sec",
        "plane_off_tuples_per_sec",
        "plane_overhead_pct",
        "load_points",
        "parity",
        "samples",
        "pacing",
        "workload",
    ):
        assert key in fr, f"latency_frontier block lost {key!r}"
    assert fr["pacing"] == "open-loop-arrival"
    # the headline the flagship row carries
    assert result.get("p99_emission_latency_ms") is not None


def test_every_load_point_present_with_the_tracked_keys(result):
    points = result["latency_frontier"]["load_points"]
    for lp in ("25", "50", "100"):
        blk = points.get(lp)
        assert blk is not None, f"frontier lost the {lp}% load point"
        for key in (
            "target_rate_tuples_per_sec",
            "achieved_rate_tuples_per_sec",
            "p50_emission_ms",
            "p99_emission_ms",
            "p999_emission_ms",
            "samples",
            "watermark_lag_ms",
            "parity",
            "stall_outliers",
            "stall_attributed",
            "stall_unattributed",
        ):
            assert key in blk, f"load point {lp} lost {key!r}"


def test_pacing_never_changes_results(result):
    """Open-loop arrival pacing must only move WHEN windows fire, never
    WHAT they contain: every paced leg at exact oracle parity."""
    assert result["latency_frontier"]["parity"]
    for lp, blk in result["latency_frontier"]["load_points"].items():
        assert blk["parity"], f"load point {lp} diverged from the oracle"


def test_plane_actually_recorded_samples(result):
    """Zero emission samples means the plane went dark — the stamping at
    the deferred-resolve points was lost or the default got flipped."""
    assert result["latency_frontier"]["samples"] > 0
    for lp, blk in result["latency_frontier"]["load_points"].items():
        assert blk["samples"] > 0, f"load point {lp} recorded no fires"
        assert blk["p99_emission_ms"] >= blk["p50_emission_ms"] >= 0


def test_latency_mode_block_carries_the_tracked_keys(result):
    """ISSUE-18: the latency-mode leg is part of the tracked frontier —
    a regression that silently drops it (flag lost, leg skipped) fails
    tier-1, not just a human eyeballing the next bench run."""
    lm = result["latency_frontier"].get("latency_mode")
    assert lm is not None, "latency_frontier lost the latency_mode block"
    for key in ("target_ms", "max_inflight", "peak_tuples_per_sec",
                "peak_fraction", "load_points", "parity"):
        assert key in lm, f"latency_mode block lost {key!r}"
    for lp in ("25", "50", "100"):
        blk = lm["load_points"].get(lp)
        assert blk is not None, f"latency_mode lost the {lp}% load point"
        for key in ("target_rate_tuples_per_sec",
                    "achieved_rate_tuples_per_sec",
                    "p50_emission_ms", "p99_emission_ms", "p999_emission_ms",
                    "samples", "parity", "controller"):
            assert key in blk, f"latency_mode point {lp} lost {key!r}"
        ctl = blk["controller"]
        assert ctl.get("active"), \
            f"latency_mode point {lp}: controller gauges absent — the " \
            "flag did not reach the operator"
        # adaptation must stay on the pow2 rung ladder: the count of
        # distinct dispatched geometries is bounded by the ladder size,
        # never one-per-decision (a recompile storm)
        assert 1 <= int(ctl["ladderRecompiles"]) <= 8, \
            f"unbounded geometry churn: {ctl['ladderRecompiles']}"
    assert result.get("latency_mode_p99_ms") is not None
    assert result.get("latency_mode_peak_fraction") is not None


def test_latency_mode_never_changes_results(result):
    """Adaptive superbatch sizing, the in-flight ring, and streaming
    readback must only move WHEN results become host-visible, never WHAT
    they contain: every latency-mode leg at exact oracle parity."""
    lm = result["latency_frontier"]["latency_mode"]
    assert lm["parity"]
    for lp, blk in lm["load_points"].items():
        assert blk["parity"], \
            f"latency-mode point {lp} diverged from the oracle"


def test_latency_mode_beats_throughput_mode_tail_at_light_load(result):
    """The mode's reason to exist: at 25% load the adaptive rung must
    dispatch long before the full span fills, so the emission p99 sits
    STRICTLY below throughput mode's at the same load point."""
    tp = result["latency_frontier"]["load_points"]["25"]["p99_emission_ms"]
    lat = result["latency_mode_p99_ms"]
    assert lat < tp, (
        f"latency mode p99@25% ({lat} ms) not below throughput mode's "
        f"({tp} ms) — rung adaptation is not engaging at light load")


def test_latency_mode_peak_above_catastrophic_floor(result):
    """At saturation the controller must escalate to the full span; the
    real >=80% peak budget is judged on the full flagship run, so (like
    the plane-overhead gate) smoke pins only the catastrophic floor — a
    controller stuck on a small rung at peak costs integer multiples."""
    assert result["latency_mode_peak_fraction"] > 0.4, (
        "latency-mode peak collapsed — is the controller escalating to "
        "the full span under sustained load?")


def test_plane_overhead_below_catastrophic_floor(result):
    """A serializing regression (stamping at dispatch, forced readback
    per fire) costs integer multiples of throughput; scheduler noise at
    smoke scale costs tens of percent. The tier-1 floor sits between."""
    assert result["latency_frontier"]["plane_overhead_pct"] < 60.0, (
        "emission-latency plane costs a multiple of throughput — is a "
        "fire path forcing device sync or eager resolution?")
