"""Bench smoke gate for the million-key state plane (ISSUE-12).

Runs the real `bench.millikey_microbench` at smoke scale (key cardinality
~50x the resident HBM capacity, uniform + zipf(1.0) variants, the sharded
variant on the virtual 8-device CPU mesh) and asserts the result JSON
carries the `state_tier.*` keys every BENCH_*.json must now track — so a
regression that silently breaks tier parity, lets the resident key set
grow unbounded (the vocabulary stopped evicting), stops exercising the
cold tier, or inflates incremental checkpoints back to full-state cost
fails tier-1, not just a human reading the next bench artifact.

Absolute throughput is deliberately not asserted (2-vCPU CI); the
structural keys and the parity/boundedness/ratio gates are the contract.
"""

import importlib.util
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"

#: acceptance bar: median per-checkpoint-interval changelog bytes must
#: stay below a quarter of the materialized full-state base size
INCREMENTAL_RATIO_BAR = 0.25


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_millikey_smoke",
                                                  _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    # smoke scale: 100k-key vocabulary over a 2048-row hot tier — far
    # past capacity so admission/eviction/cold routing all engage, small
    # enough for the tier-1 budget
    return bench.millikey_microbench(events=49152, batch=2048,
                                     num_keys=100_000, hot_capacity=2048,
                                     parity_keys=4096)


def test_result_carries_the_tracked_state_tier_keys(result):
    assert "error" not in result, result.get("error")
    for key in ("events", "num_keys", "hot_key_capacity", "parity",
                "tuples_per_sec", "incremental_ratio", "uniform", "zipf",
                "sharded"):
        assert key in result, f"bench state_tier block lost {key!r}"
    for variant in ("uniform", "zipf"):
        blk = result[variant]
        for key in ("parity", "parity_vs_untired", "tuples_per_sec",
                    "vocab_size", "resident_keys", "evictions",
                    "promotions", "cold_records", "resident_bounded",
                    "changelog_interval_bytes_p50", "full_snapshot_bytes",
                    "incremental_ratio"):
            assert key in blk, f"state_tier.{variant} lost {key!r}"


def test_parity_both_variants_and_both_oracles(result):
    for variant in ("uniform", "zipf"):
        blk = result[variant]
        assert blk["parity_vs_untired"], (
            f"{variant}: tiered run diverged from the untired fused run")
        assert blk["parity"], (
            f"{variant}: full-cardinality tiered run diverged from the "
            "host oracle")
    assert result["parity"]


def test_resident_keys_bounded_with_eviction_under_skew(result):
    for variant in ("uniform", "zipf"):
        blk = result[variant]
        assert blk["resident_bounded"], (
            f"{variant}: resident keys {blk['resident_keys']} exceed the "
            f"hot capacity {result['hot_key_capacity']} — the vocabulary "
            "stopped bounding HBM")
        assert blk["vocab_size"] > result["hot_key_capacity"], (
            f"{variant}: the workload never exceeded the hot capacity — "
            "the scenario stopped testing tiering at all")
    # the zipf head churns the hot boundary: zero evictions means the
    # vocabulary stopped evicting (unbounded-HBM regression incoming)
    assert result["zipf"]["evictions"] > 0, (
        "zero evictions under zipf — eviction is dead")
    assert result["zipf"]["promotions"] > 0, (
        "zero promotions under zipf — re-admission never moves cold rows "
        "back")
    # the cold tier must actually hold data in both variants
    for variant in ("uniform", "zipf"):
        assert result[variant]["cold_records"] > 0, (
            f"{variant}: no record ever routed cold")


def test_incremental_checkpoints_beat_full_snapshots(result):
    for variant in ("uniform", "zipf"):
        blk = result[variant]
        assert blk.get("checkpoints", 0) > 3, (
            f"{variant}: too few checkpoints completed to judge the "
            "incremental ratio")
        assert blk["incremental_ratio"] < INCREMENTAL_RATIO_BAR, (
            f"{variant}: per-interval changelog bytes "
            f"({blk['changelog_interval_bytes_p50']}) are "
            f"{blk['incremental_ratio']:.2f}x the full snapshot "
            f"({blk['full_snapshot_bytes']}) — incremental checkpoints "
            f"no longer scale with the delta (bar {INCREMENTAL_RATIO_BAR})")


def test_sharded_variant_runs_at_parity(result):
    sh = result["sharded"]
    if sh.get("skipped"):
        pytest.skip(f"no usable mesh ({sh.get('devices')} device(s))")
    assert sh["mesh_selected"], (
        "the tiered job fell back to single-chip — parallel.mesh.enabled "
        "no longer promotes tiered jobs to the mesh")
    assert sh["parity"], "tiered mesh run diverged from the untired run"
    assert sh["evictions"] and sh["evictions"] > 0
