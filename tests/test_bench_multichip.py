"""Bench smoke gate for the multichip SPMD scenario (ISSUE-11).

Runs the real `bench.multichip_microbench` at smoke scale on the virtual
8-device CPU mesh (tests/conftest.py forces it) and asserts the result
JSON carries the `multichip.*` keys every BENCH_*.json must now track —
so the MULTICHIP_r*.json dryrun stops being an unasserted side artifact:
a regression that silently reroutes fused jobs back to single-chip
(`sharded_selected` false), breaks mesh-vs-single-chip parity, stops
measuring the zipf skewed variant, or craters scaling efficiency fails
tier-1, not just a human eyeballing the next bench run.

Absolute throughput is deliberately not asserted, and the CPU-mesh
efficiency floor is a catastrophic-regression guard only: the 8 virtual
"chips" timeshare one host CPU, so linear scaling is structurally
impossible here — the >= 0.8x-linear acceptance bar is judged on real
multi-chip hardware, where the same compiled program rides ICI.
"""

import importlib.util
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"

#: catastrophic-regression floor for the virtual CPU mesh: 8 shards on one
#: core plus per-step all-to-alls legitimately cost ~100x vs single-chip at
#: smoke scale; a three-orders-of-magnitude collapse means the sharded
#: path stopped amortizing dispatches entirely
CPU_MESH_EFFICIENCY_FLOOR = 1e-3


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_multichip_smoke",
                                                  _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    # smoke scale: distinctive key capacity + batch so the jitted
    # executables are this run's own; one sweep keeps the gate well under
    # two minutes on the CPU backend
    return bench.multichip_microbench(events=49152, batch=2048,
                                      num_keys=384, sweeps=1)


def test_result_carries_the_tracked_multichip_keys(result):
    assert "error" not in result, result.get("error")
    for key in (
        "devices",
        "tuples_per_sec",
        "single_chip_tuples_per_sec",
        "scaling_efficiency",
        "skewed_tuples_per_sec",
        "skewed_scaling_efficiency",
        "parity",
        "skewed_parity",
        "fused_selected",
        "sharded_selected",
        "mesh_load_skew",
        "per_device_records",
    ):
        assert key in result, f"bench multichip block lost {key!r}"


def test_mesh_actually_selected_for_the_user_facing_path(result):
    assert result["devices"] >= 2, "no mesh was built"
    assert result["fused_selected"], (
        "graph translation no longer selects the DeviceChainRunner — the "
        "scenario would measure a host path, not the fused program"
    )
    assert result["sharded_selected"], (
        "the fused runner fell back to the single-chip pipeline: "
        "parallel.mesh.enabled no longer promotes user jobs to the mesh"
    )


def test_parity_uniform_and_skewed(result):
    assert result["parity"], "mesh vs single-chip parity broken"
    assert result["skewed_parity"], (
        "mesh vs single-chip parity broken under zipf keys"
    )


def test_scaling_efficiency_above_cpu_floor(result):
    assert result["scaling_efficiency"] > CPU_MESH_EFFICIENCY_FLOOR, (
        f"scaling efficiency {result['scaling_efficiency']} collapsed "
        f"below the CPU-mesh floor {CPU_MESH_EFFICIENCY_FLOOR} — the "
        "sharded dispatch stopped amortizing"
    )
    assert result["skewed_scaling_efficiency"] > 0


def test_per_device_telemetry_exercised_under_imbalance(result):
    # zipf(1.0) keys: the hottest device must be visibly hotter than the
    # mean — the per-device fold reading device 0's view (or nothing)
    # regresses exactly what this block exists to measure
    assert result["mesh_load_skew"] is not None
    assert result["mesh_load_skew"] > 1.0
    recs = result["per_device_records"]
    assert len(recs) == result["devices"]
    assert all(isinstance(r, int) for r in recs), recs
    assert max(recs) > 0


def test_throughput_measured_on_both_sides(result):
    assert result["tuples_per_sec"] > 0
    assert result["single_chip_tuples_per_sec"] > 0
