"""Bench smoke gate for the skew scenario matrix (ISSUE-15, ROADMAP 4c).

Runs the real `bench.skew_matrix_microbench` at smoke scale on the
virtual 8-device CPU mesh (tests/conftest.py forces it) and asserts the
result carries the `skew_matrix.*` keys every BENCH_*.json must now
track: a regression that silently drops a matrix cell, breaks any
parity (mesh-vs-single-chip, combine-on-vs-off, or the rebalanced
adaptive leg), stops rebalancing under forced zipf(1.0), or craters the
skewed/uniform ratio fails tier-1, not just a human eyeballing the next
bench run.

Also pins the single-sourced zipf sampler's distribution shape, so
"zipf(1.0)" stays the same distribution in every scenario.

The >= 0.8 skewed/uniform acceptance bar is judged on real TPU hardware
(ICI traffic is what local-combine saves; the 8 virtual "chips" here
timeshare one CPU and the rebalance rebuild dominates at smoke scale) —
the CPU floor gates catastrophic regressions only.
"""

import importlib.util
import pathlib

import numpy as np
import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"

#: catastrophic-regression floor for the CPU-mesh skewed/uniform ratio:
#: the adaptive zipf leg pays a stop-the-world rebalance rebuild that the
#: uniform leg does not, which at smoke scale legitimately costs ~half
#: the run; a collapse below this means the skewed path stopped working
CPU_SKEW_RATIO_FLOOR = 0.15


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_skew_smoke",
                                                  _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    # smoke scale, distinctive geometry (the bench-gate pattern): one
    # sweep keeps the gate well under the tier-1 budget
    return bench.skew_matrix_microbench(events=49152, batch=2048,
                                        num_keys=384, sweeps=1)


def test_result_carries_the_tracked_skew_matrix_keys(result):
    assert "error" not in result, result.get("error")
    for key in (
        "devices",
        "workloads",
        "cells",
        "cell_parity",
        "parity",
        "combine_parity",
        "fused_selected",
        "sharded_selected",
        "local_combine_active",
        "static_mesh_load_skew",
        "post_rebalance_mesh_load_skew",
        "rebalances",
        "adaptive",
        "skewed_uniform_ratio",
    ):
        assert key in result, f"bench skew_matrix block lost {key!r}"
    assert "error" not in result["adaptive"], result["adaptive"]


def test_matrix_covers_parallelism_workload_skew(result):
    """The PDSP-Bench grid: every (workload, parallelism) combination
    must report BOTH a uniform and a zipf cell with throughput."""
    cells = {(c["workload"], c["parallelism"], c["skew"]): c
             for c in result["cells"]}
    for workload in result["workloads"]:
        for par in (1, result["devices"]):
            for skew in ("uniform", "zipf"):
                cell = cells.get((workload, par, skew))
                assert cell is not None, (
                    f"matrix lost the ({workload}, {par}, {skew}) cell")
                assert cell["tuples_per_sec"] > 0


def test_every_cell_at_exact_parity(result):
    assert result["parity"], result["cell_parity"]
    for name, ok in result["cell_parity"].items():
        assert ok, f"mesh vs single-chip parity broken for {name}"


def test_local_combine_is_a_pure_perf_switch(result):
    assert result["fused_selected"] and result["sharded_selected"]
    assert result["local_combine_active"], (
        "parallel.mesh.local-combine no longer engages the map-side "
        "combiner for a decomposable aggregate")
    assert result["combine_parity"], (
        "combine-on vs combine-off results diverged — the combiner "
        "became a semantics switch"
    )


def test_rebalance_fires_and_reduces_mesh_skew(result):
    assert result["rebalances"] >= 1, (
        "zero rebalances under forced zipf(1.0) hot-clustered traffic — "
        "the skew-rebalance loop is dead")
    static = result["static_mesh_load_skew"]
    post = result["post_rebalance_mesh_load_skew"]
    assert isinstance(static, (int, float)) and static > 1.2, (
        f"static-routing skew {static!r} no longer shows the imbalance "
        "the scenario constructs")
    assert isinstance(post, (int, float)) and post < static, (
        f"post-rebalance meshLoadSkew {post!r} not below the static "
        f"value {static!r}")
    assert result["adaptive"]["parity"], (
        "rebalanced adaptive leg broke exactly-once parity")


def test_skewed_uniform_ratio_above_cpu_floor(result):
    ratio = result["skewed_uniform_ratio"]
    assert ratio is not None and ratio > CPU_SKEW_RATIO_FLOOR, (
        f"skewed/uniform ratio {ratio} collapsed below the CPU floor "
        f"{CPU_SKEW_RATIO_FLOOR} (the 0.8 bar is judged on TPU)")


# ---------------------------------------------------------------------------
# the single-sourced zipf sampler's distribution shape
# ---------------------------------------------------------------------------

def test_zipf_sampler_shape(bench):
    n_keys, s, n = 1024, 1.0, 200_000
    keys = bench.zipf_keys(np.arange(n), n_keys, s)
    assert keys.min() >= 0 and keys.max() < n_keys
    freq = np.bincount(keys, minlength=n_keys) / n
    h = np.sum(1.0 / np.arange(1, n_keys + 1, dtype=np.float64) ** s)
    # top rank ~ 1/H(n_keys); the first ranks dominate per the power law
    assert freq[0] == pytest.approx(1.0 / h, rel=0.05)
    assert freq[1] == pytest.approx(0.5 / h, rel=0.1)
    top_mass = freq[np.argsort(-freq)[:16]].sum()
    assert top_mass == pytest.approx(
        np.sum(1.0 / np.arange(1, 17.0) ** s) / h, rel=0.05)
    # ranks are (statistically) monotone decreasing
    assert np.all(freq[:8] > freq[64:72])


def test_zipf_sampler_is_stateless_and_permutation_preserves_shape(bench):
    idx = np.arange(50_000)
    whole = bench.zipf_keys(idx, 512, 1.0)
    chunked = np.concatenate([
        bench.zipf_keys(idx[lo:lo + 7_919], 512, 1.0)
        for lo in range(0, len(idx), 7_919)])
    np.testing.assert_array_equal(whole, chunked)
    perm = np.random.default_rng(3).permutation(512)
    permuted = bench.zipf_keys(idx, 512, 1.0, hot_perm=perm)
    np.testing.assert_array_equal(permuted, perm[whole])
    # same multiset of frequencies, relocated support
    np.testing.assert_array_equal(
        np.sort(np.bincount(whole, minlength=512)),
        np.sort(np.bincount(permuted, minlength=512)))
