"""Bench smoke gate for the SQL-path scenario (the SQL front door).

Runs the real `bench.sql_path_microbench` at smoke scale and asserts the
result JSON carries the keys every BENCH_*.json must now track — so a
regression that silently reroutes SQL back to the interpreted path
(fused_selected False), breaks three-way result parity, or turns the
fallback contract into a failure fails tier-1, not just a human eyeballing
the next bench run. Throughput NUMBERS are deliberately not asserted
(sandbox scheduler noise); the structural keys and the parity/selection/
attribution booleans are the gate. The ~1.2x ratio_vs_datastream_fused
acceptance bar is judged on the full-scale bench artifact, where the
fixed window-output volume amortizes.
"""

import importlib.util
import pathlib

import pytest

_BENCH = pathlib.Path(__file__).resolve().parents[1] / "bench.py"


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_smoke", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def result(bench):
    # smoke scale: small batch + few events keeps compile+run well under a
    # minute on the CPU backend while exercising the planner, all three
    # paths' parity, the fallback demo, and the timed sweeps exactly as
    # the real bench does
    return bench.sql_path_microbench(events=8192, batch=2048)


def test_result_carries_the_tracked_keys(result):
    for key in (
        "sql_tuples_per_sec",
        "interpreted_tuples_per_sec",
        "datastream_fused_tuples_per_sec",
        "speedup_vs_interpreted",
        "ratio_vs_datastream_fused",
        "parity",
        "fused_selected",
        "fallback_attributed",
    ):
        assert key in result, f"bench result JSON lost {key!r}"
    assert result["sql_tuples_per_sec"] > 0


def test_sql_path_parity_is_exact(result):
    assert result["parity"] is True, (
        "SQL-fused vs interpreted-table vs hand-built DataStream results "
        "diverged — the SQL front door is emitting different windows than "
        "its oracles"
    )


def test_fused_runner_is_actually_selected(result):
    assert result["fused_selected"] is True, (
        "the planner (or graph translation) no longer routes the SQL YSB "
        "statement to DeviceChainRunner — parity would still hold on the "
        "slow path, so this flag is the reroute gate"
    )


def test_fallbacks_are_attributed_not_failures(result):
    assert result["fallback_attributed"] is True, (
        "the session-window statement must EXECUTE on the interpreted "
        "path with reason 'session-window' attributed"
    )
    assert result["fallback_reason_demo"] == "session-window"


def test_windows_were_emitted(result):
    assert result["windows_emitted"] > 0
