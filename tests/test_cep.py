"""CEP tests: pattern semantics against the NFA and the keyed operator
(reference: flink-cep NFA/CepOperator behavior)."""

import pytest

from flink_tpu.cep import CepOperator, Pattern, pattern_stream
from flink_tpu.cep.nfa import NFA


def run_nfa(pattern, events):
    """events: (value, ts) fed in order; returns completed matches."""
    nfa = NFA(pattern)
    runs = []
    matches = []
    for value, ts in events:
        runs, m = nfa.advance(runs, value, ts)
        matches.extend(m)
    return matches


def test_strict_next():
    p = (
        Pattern.begin("a").where(lambda e: e == "a")
        .next("b").where(lambda e: e == "b")
    )
    assert len(run_nfa(p, [("a", 1), ("b", 2)])) == 1
    assert len(run_nfa(p, [("a", 1), ("x", 2), ("b", 3)])) == 0  # strict broken
    assert len(run_nfa(p, [("a", 1), ("a", 2), ("b", 3)])) == 1  # second 'a' matches


def test_relaxed_followed_by():
    p = (
        Pattern.begin("a").where(lambda e: e == "a")
        .followed_by("b").where(lambda e: e == "b")
    )
    assert len(run_nfa(p, [("a", 1), ("x", 2), ("b", 3)])) == 1
    # two a's then b: both runs complete (a1->b, a2->b)
    assert len(run_nfa(p, [("a", 1), ("a", 2), ("b", 3)])) == 2


def test_three_stage_and_binding():
    p = (
        Pattern.begin("start").where(lambda e: e[0] == "s")
        .followed_by("mid").where(lambda e: e[0] == "m")
        .followed_by("end").where(lambda e: e[0] == "e")
    )
    matches = run_nfa(p, [(("s", 1), 1), (("m", 2), 2), (("e", 3), 3)])
    assert len(matches) == 1
    assert matches[0]["start"] == [("s", 1)]
    assert matches[0]["mid"] == [("m", 2)]
    assert matches[0]["end"] == [("e", 3)]


def test_times_quantifier():
    p = (
        Pattern.begin("a").where(lambda e: e == "a").times(3)
        .followed_by("b").where(lambda e: e == "b")
    )
    matches = run_nfa(p, [("a", 1), ("a", 2), ("a", 3), ("b", 4)])
    assert any(len(m["a"]) == 3 for m in matches)
    assert len(run_nfa(p, [("a", 1), ("a", 2), ("b", 3)])) == 0


def test_one_or_more_greedy_growth():
    p = (
        Pattern.begin("nums").where(lambda e: isinstance(e, int)).one_or_more()
        .followed_by("stop").where(lambda e: e == "stop")
    )
    matches = run_nfa(p, [(1, 1), (2, 2), ("stop", 3)])
    # runs: [1], [2], [1,2] each followed by stop
    collected = sorted(tuple(m["nums"]) for m in matches)
    assert (1,) in collected and (2,) in collected and (1, 2) in collected


def test_within_prunes_old_runs():
    p = (
        Pattern.begin("a").where(lambda e: e == "a")
        .followed_by("b").where(lambda e: e == "b")
        .within(10)
    )
    assert len(run_nfa(p, [("a", 0), ("b", 5)])) == 1
    assert len(run_nfa(p, [("a", 0), ("b", 50)])) == 0  # timed out


def test_optional_stage():
    p = (
        Pattern.begin("a").where(lambda e: e == "a")
        .followed_by("opt").where(lambda e: e == "o").optional()
        .followed_by("b").where(lambda e: e == "b")
    )
    with_opt = run_nfa(p, [("a", 1), ("o", 2), ("b", 3)])
    without = run_nfa(p, [("a", 1), ("b", 2)])
    assert any(m["opt"] == ["o"] for m in with_opt)
    assert any(m["opt"] == [] for m in without)


def test_cep_operator_event_time_ordering():
    """Out-of-order events are buffered and NFA-fed in timestamp order on
    watermark (CepOperator event-time contract)."""
    p = (
        Pattern.begin("a").where(lambda e: e[1] == "a")
        .next("b").where(lambda e: e[1] == "b")
    )
    op = CepOperator(p)
    # arrive out of order: b(ts2) before a(ts1)
    op.process_record("k", ("k", "b"), 20)
    op.process_record("k", ("k", "a"), 10)
    op.process_watermark(100)
    out = op.drain_output()
    assert len(out) == 1
    assert out[0][2]["a"] == [("k", "a")]


def test_cep_operator_keys_isolated():
    p = (
        Pattern.begin("a").where(lambda e: e[1] == "a")
        .next("b").where(lambda e: e[1] == "b")
    )
    op = CepOperator(p)
    op.process_record("k1", ("k1", "a"), 1)
    op.process_record("k2", ("k2", "b"), 2)  # b without a: no match
    op.process_record("k1", ("k1", "b"), 3)
    op.process_watermark(100)
    out = op.drain_output()
    assert len(out) == 1 and out[0][0] == "k1"


def test_cep_snapshot_restore():
    p = (
        Pattern.begin("a").where(lambda e: e == "a")
        .followed_by("b").where(lambda e: e == "b")
    )
    op = CepOperator(p)
    op.process_record("k", "a", 1)
    op.process_watermark(5)
    snap = op.snapshot()

    op2 = CepOperator(p)
    op2.restore(snap)
    op2.process_record("k", "b", 10)
    op2.process_watermark(20)
    assert len(op2.drain_output()) == 1


def test_cep_end_to_end():
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.core.watermarks import WatermarkStrategy

    env = StreamExecutionEnvironment.get_execution_environment()
    # (user, action, ts): detect login -> purchase per user
    events = [
        ("u1", "login", 100), ("u2", "login", 200), ("u1", "browse", 300),
        ("u1", "purchase", 400), ("u2", "logout", 500),
    ]
    stream = env.from_collection(
        events,
        timestamp_fn=lambda e: e[2],
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    p = (
        Pattern.begin("login").where(lambda e: e[1] == "login")
        .followed_by("purchase").where(lambda e: e[1] == "purchase")
    )
    keyed = stream.key_by(lambda e: e[0])
    result = pattern_stream(keyed, p, select_fn=lambda m: m["login"][0][0])
    sink = result.collect()
    env.execute()
    assert sink.results == ["u1"]
