"""Whole-graph fusion (graph/fusion.py + DeviceChainRunner): parity of the
fused device chain with the host ChainRunner path and the per-record oracle.

The fused path compiles a traceable map/filter/map_ts prologue, key/value
extraction, and the windowed aggregation into ONE jitted multi-step device
program (`lax.scan` over T batches). These tests pin that the compiled
program produces results identical to (a) today's ChainRunner + fused
window operator path and (b) the per-record OracleWindowOperator, including
the mixed-chain fallback boundary, empty/watermark-only steps, and the
out-of-range-key hard error. Values are integer-valued floats with window
sums far below 2**24, so float32 accumulation is exact in any order and
the comparisons are exact, not approximate.
"""

import warnings

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from flink_tpu.api.datastream import StreamExecutionEnvironment
from flink_tpu.api.functions import AggregateFunction
from flink_tpu.api.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.config import Configuration, ExecutionOptions
from flink_tpu.connectors.source import Batch, DataGeneratorSource
from flink_tpu.core.time import MAX_WATERMARK
from flink_tpu.core.watermarks import WatermarkStrategy
from flink_tpu.graph.fusion import (
    DeviceChainPlan,
    plan_device_chains,
    window_is_device_fusable,
)
from flink_tpu.graph.transformation import plan
from flink_tpu.runtime.executor import (
    ChainRunner,
    DeviceChainRunner,
    WindowStepRunner,
    build_runners,
)
from flink_tpu.utils.arrays import as_device_column

NUM_KEYS = 7


def _source(n=4000, seed=3, disorder=40):
    """Deterministic 2-column (key, value) records: value columns are small
    integers so float32 sums are exact; timestamps mildly out of order
    within `disorder` ms."""
    rng = np.random.default_rng(seed)
    jitter = rng.integers(0, disorder, size=n)

    def gen(idx):
        keys = (idx * 7919) % NUM_KEYS
        vals = ((idx * 31) % 19 + 1).astype(np.float64)
        ts = 10_000 + idx * 13 - jitter[idx]
        return Batch(
            np.stack([keys, vals], axis=1).astype(np.float64),
            ts.astype(np.int64),
        )

    return DataGeneratorSource(gen, n)


class _SumAgg(AggregateFunction):
    """Python sum: forces the per-record OracleWindowOperator."""

    def create_accumulator(self):
        return 0.0

    def add(self, value, acc):
        return acc + float(value)

    def get_result(self, acc):
        return acc

    def merge(self, a, b):
        return a + b


_ORACLE_AGGS = {"sum": _SumAgg}


def _program(path, aggregate="sum", assigner=None, batch_size=512, n=4000,
             superbatch_steps=4, extra_conf=None):
    """Build + run one program through `path` in {'oracle','chain','fused'}:
    filter -> map (projection) -> keyBy -> window -> aggregate -> collect.
    Identical logical semantics in all three.

    Returns (sorted results, runner type names)."""
    assigner = assigner or SlidingEventTimeWindows.of(2_000, 1_000)
    cfg = Configuration()
    cfg.set(ExecutionOptions.BATCH_SIZE, batch_size)
    cfg.set(ExecutionOptions.SUPERBATCH_STEPS, superbatch_steps)
    cfg.set(ExecutionOptions.CHAIN_FUSION, path == "fused")
    for opt, v in (extra_conf or {}).items():
        cfg.set(opt, v)
    env = StreamExecutionEnvironment.get_execution_environment(cfg)
    ds = env.from_source(
        _source(n=n),
        watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(50),
    )
    if path == "oracle":
        ds = ds.filter(lambda r: r[1] > 3)
        ds = ds.map(lambda r: (r[0], r[1] * 2.0))
        win = (
            ds.key_by(lambda r: int(r[0]))
            .window(assigner)
            .aggregate(_ORACLE_AGGS[aggregate](), value_fn=lambda r: r[1])
        )
    else:
        ds = ds.filter(lambda col: col[:, 1] > 3, traceable=True)
        ds = ds.map(lambda col: col * jnp.asarray([1.0, 2.0]), traceable=True)
        win = (
            ds.key_by(lambda col: col[:, 0].astype(jnp.int32), traceable=True)
            .window(assigner)
            .aggregate(aggregate, value_fn=lambda col: col[:, 1],
                       value_traceable=True)
        )
    sink = win.collect()
    runners, _ = build_runners(plan(env._sinks), cfg)
    kinds = [type(r).__name__ for r in runners]
    env.execute()
    out = sorted((int(k), float(v)) for k, v in sink.results)
    return out, kinds


# ---------------------------------------------------------------------------
# three-way parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("assigner_fn", [
    lambda: TumblingEventTimeWindows.of(1_000),
    lambda: SlidingEventTimeWindows.of(2_000, 1_000),
], ids=["tumbling", "sliding"])
def test_three_way_parity_sum(assigner_fn):
    fused, kf = _program("fused", assigner=assigner_fn())
    chain, kc = _program("chain", assigner=assigner_fn())
    oracle, ko = _program("oracle", assigner=assigner_fn())
    assert "DeviceChainRunner" in kf
    assert "DeviceChainRunner" not in kc and "ChainRunner" in kc
    assert "DeviceChainRunner" not in ko
    assert len(fused) > 0
    assert fused == chain          # exact: integer-valued float32 sums
    assert fused == oracle


@pytest.mark.parametrize("aggregate", ["count", "min", "max"])
def test_parity_fused_vs_chain_all_scatter_kinds(aggregate):
    """min/max exercise the scatter-combine fields, count the ONE-source
    field; parity against today's ChainRunner + fused window path."""
    fused, kf = _program("fused", aggregate=aggregate)
    chain, kc = _program("chain", aggregate=aggregate)
    assert "DeviceChainRunner" in kf and "DeviceChainRunner" not in kc
    assert len(fused) > 0
    assert fused == chain


@pytest.mark.parametrize("batch_size,superbatch_steps", [(64, 2), (251, 7)])
def test_parity_across_batch_geometries(batch_size, superbatch_steps):
    """Ragged last batches, odd superbatch sizes: the staged [T, B] geometry
    must not leak into results."""
    fused, _ = _program("fused", batch_size=batch_size, n=1777,
                        superbatch_steps=superbatch_steps)
    chain, _ = _program("chain", batch_size=batch_size, n=1777,
                        superbatch_steps=superbatch_steps)
    assert len(fused) > 0
    assert fused == chain


# ---------------------------------------------------------------------------
# fallback boundaries
# ---------------------------------------------------------------------------

def _build_env(traceable_chain=True, traceable_key=True, flat_map=False,
               aggregate="sum", second_consumer=False):
    cfg = Configuration()
    cfg.set(ExecutionOptions.BATCH_SIZE, 512)
    env = StreamExecutionEnvironment.get_execution_environment(cfg)
    ds = env.from_source(
        _source(n=600),
        watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(50),
    )
    ds = ds.filter(lambda col: col[:, 1] > 3, traceable=True)
    if not traceable_chain:
        # vectorized but NOT declared traceable: pins the chain on host
        ds = ds.map(lambda col: np.asarray(col) * np.asarray([1.0, 2.0]),
                    vectorized=True)
    else:
        ds = ds.map(lambda col: col * jnp.asarray([1.0, 2.0]), traceable=True)
    if flat_map:
        def dup(col):
            col = np.asarray(col)
            return np.repeat(col, 2, axis=0), np.repeat(np.arange(len(col)), 2)
        ds = ds.flat_map(dup, vectorized=True)
    if second_consumer:
        ds.map(lambda col: col[:, 1], traceable=True).collect()
    key_kw = {"traceable": True} if traceable_key else {"vectorized": True}
    win = (
        ds.key_by(lambda col: jnp.asarray(col)[:, 0].astype(jnp.int32),
                  **key_kw)
        .window(SlidingEventTimeWindows.of(2_000, 1_000))
        .aggregate(aggregate, value_fn=lambda col: jnp.asarray(col)[:, 1],
                   value_vectorized=True,
                   value_traceable=traceable_key)
    )
    sink = win.collect()
    return env, cfg, sink


def _kinds(env, cfg):
    runners, _ = build_runners(plan(env._sinks), cfg)
    return [type(r).__name__ for r in runners]


def test_fully_traceable_chain_is_absorbed():
    env, cfg, _ = _build_env()
    kinds = _kinds(env, cfg)
    # chain + window collapse into ONE DeviceChainRunner: no ChainRunner
    assert "DeviceChainRunner" in kinds
    assert "ChainRunner" not in kinds


def test_untraceable_transform_keeps_chain_on_host():
    """Mixed chain: one vectorized-but-not-traceable transform pins the
    chain on host, but key/value extraction + window still fuse; results
    match the fully-host path exactly."""
    env, cfg, sink = _build_env(traceable_chain=False)
    kinds = _kinds(env, cfg)
    assert "ChainRunner" in kinds and "DeviceChainRunner" in kinds
    env.execute()
    got = sorted((int(k), float(v)) for k, v in sink.results)

    env2, cfg2, sink2 = _build_env(traceable_chain=False)
    cfg2.set(ExecutionOptions.CHAIN_FUSION, False)
    env2.execute()
    want = sorted((int(k), float(v)) for k, v in sink2.results)
    assert len(got) > 0 and got == want


def test_flat_map_always_falls_back():
    """flat_map changes cardinality dynamically: no static-shape trace
    exists, so the chain keeps the host path (results still correct)."""
    env, cfg, sink = _build_env(flat_map=True)
    kinds = _kinds(env, cfg)
    assert "ChainRunner" in kinds
    env.execute()
    got = sorted((int(k), float(v)) for k, v in sink.results)

    env2, cfg2, sink2 = _build_env(flat_map=True)
    cfg2.set(ExecutionOptions.CHAIN_FUSION, False)
    env2.execute()
    want = sorted((int(k), float(v)) for k, v in sink2.results)
    assert len(got) > 0 and got == want


def test_undeclared_key_selector_keeps_window_path():
    """key_by without traceable=True: the window step keeps today's
    WindowStepRunner (the key dictionary path)."""
    env, cfg, _ = _build_env(traceable_key=False)
    kinds = _kinds(env, cfg)
    assert "DeviceChainRunner" not in kinds
    assert "WindowStepRunner" in kinds


def test_second_consumer_pins_chain_on_host():
    """A chain whose output feeds a second consumer cannot be absorbed
    (fusing would corrupt the other consumer's input); the window still
    fuses key/value extraction alone."""
    env, cfg, _ = _build_env(second_consumer=True)
    kinds = _kinds(env, cfg)
    assert "ChainRunner" in kinds and "DeviceChainRunner" in kinds


def test_fusion_config_off_disables_the_path():
    env, cfg, _ = _build_env()
    cfg.set(ExecutionOptions.CHAIN_FUSION, False)
    kinds = _kinds(env, cfg)
    assert "DeviceChainRunner" not in kinds


def test_oracle_aggregate_function_not_fusable():
    """An AggregateFunction has no device form: the planner must refuse."""
    env = StreamExecutionEnvironment.get_execution_environment()
    win = (
        env.from_source(_source(n=100))
        .key_by(lambda col: col[:, 0].astype(np.int64), traceable=True)
        .window(SlidingEventTimeWindows.of(2_000, 1_000))
        .aggregate(_SumAgg(), value_fn=lambda col: col[:, 1],
                   value_traceable=True)
    )
    win.collect()
    g = plan(env._sinks)
    plans, absorbed = plan_device_chains(g)
    assert plans == {} and absorbed == set()
    for s in g.steps:
        if s.terminal is not None and s.terminal.kind == "window_aggregate":
            assert not window_is_device_fusable(s.terminal)


# ---------------------------------------------------------------------------
# empty batches / watermark-only steps
# ---------------------------------------------------------------------------

def test_empty_and_watermark_only_steps():
    """Drive a DeviceChainRunner directly with empty object-dtype batches
    (the stage reader's idle poll shape) and watermark-only advances: no
    warning, no error, and results match the host path fed identically."""
    from flink_tpu.utils.arrays import obj_array

    def build(fused):
        cfg = Configuration()
        cfg.set(ExecutionOptions.CHAIN_FUSION, fused)
        cfg.set(ExecutionOptions.SUPERBATCH_STEPS, 2)
        env = StreamExecutionEnvironment.get_execution_environment(cfg)
        win = (
            env.from_source(_source(n=10))
            .filter(lambda col: col[:, 1] > 0, traceable=True)
            .key_by(lambda col: col[:, 0].astype(jnp.int32), traceable=True)
            .window(TumblingEventTimeWindows.of(1_000))
            .aggregate("sum", value_fn=lambda col: col[:, 1],
                       value_traceable=True)
        )
        win.collect()
        runners, feeds = build_runners(plan(env._sinks), cfg)
        entry = runners[0]
        results = []
        runners[-1].downstream = None
        sink = runners[-1]
        orig = sink.on_batch

        def capture(vals, ts):
            results.extend(
                (int(k), float(v)) for k, v in vals)
            orig(vals, ts)
        sink.on_batch = capture
        return entry, results

    def drive(entry):
        empty = obj_array([])
        ets = np.asarray([], dtype=np.int64)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            entry.on_batch(empty, ets)                      # idle poll
            entry.on_watermark(9_000)                       # watermark-only
            vals = np.asarray([[1.0, 5.0], [2.0, 7.0]])
            entry.on_batch(vals, np.asarray([10_100, 10_150], dtype=np.int64))
            entry.on_batch(empty, ets)
            entry.on_watermark(11_500)
            entry.on_batch(np.asarray([[1.0, 3.0]]),
                           np.asarray([11_700], dtype=np.int64))
            entry.on_watermark(13_000)
            # the run loop's finish() ends every stream with MAX watermark;
            # the fused operator flushes there (superbatch granularity)
            entry.on_watermark(MAX_WATERMARK - 1)
            entry.on_end()

    e_fused, r_fused = build(True)
    assert isinstance(e_fused, DeviceChainRunner)
    drive(e_fused)
    e_host, r_host = build(False)
    drive(e_host)
    assert len(r_fused) > 0
    assert sorted(r_fused) == sorted(r_host)


# ---------------------------------------------------------------------------
# hard errors: map_batch 1:N, out-of-range traced keys
# ---------------------------------------------------------------------------

def test_map_batch_non_1_to_1_raises_loudly():
    """The bare assert became an attributed ValueError: a 1:N map_batch
    must fail loudly (asserts vanish under python -O) instead of silently
    corrupting timestamp alignment."""
    env = StreamExecutionEnvironment.get_execution_environment()
    ds = env.from_collection(
        [(1, 10_000), (2, 10_001), (3, 10_002)], timestamp_fn=lambda r: r[1]
    )
    ds.map_batch(lambda vs: vs[:-1], name="bad_batch").collect()
    with pytest.raises(ValueError, match="bad_batch.*must be.*1:1"):
        env.execute()


@pytest.mark.parametrize("bad_key", [200, -3], ids=["over", "negative"])
def test_traced_key_out_of_range_is_a_hard_error(bad_key):
    """Dense device keying cannot grow mid-dispatch: a traced selector
    emitting a key outside [0, capacity) must raise at resolve — never
    silently alias another key's row or drop the record."""
    cfg = Configuration()
    cfg.set(ExecutionOptions.KEY_CAPACITY, 64)
    cfg.set(ExecutionOptions.SUPERBATCH_STEPS, 1)
    env = StreamExecutionEnvironment.get_execution_environment(cfg)
    win = (
        env.from_source(_source(n=50))
        .key_by(lambda col: col[:, 0].astype(jnp.int32) + bad_key,
                traceable=True)
        .window(TumblingEventTimeWindows.of(1_000))
        .aggregate("sum", value_fn=lambda col: col[:, 1],
                   value_traceable=True)
    )
    win.collect()
    with pytest.raises(ValueError, match="key-capacity|non-negative"):
        env.execute()


def test_record_mode_source_columnarizes_with_warning():
    """A record-mode (object column) source feeding a fused chain pays a
    per-batch columnarization pass and warns once; results stay correct."""
    rows = [(float(i % 3), float(i % 5 + 1), 10_000 + i * 13)
            for i in range(400)]

    def build(fused):
        cfg = Configuration()
        cfg.set(ExecutionOptions.CHAIN_FUSION, fused)
        env = StreamExecutionEnvironment.get_execution_environment(cfg)
        win = (
            env.from_collection(
                rows, timestamp_fn=lambda r: int(r[2]),
                watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(0),
            )
            .key_by(lambda col: col[:, 0].astype(jnp.int32), traceable=True)
            .window(TumblingEventTimeWindows.of(1_000))
            .aggregate("sum", value_fn=lambda col: col[:, 1],
                       value_traceable=True)
        )
        sink = win.collect()
        return env, sink

    env, sink = build(True)
    with pytest.warns(RuntimeWarning, match="record-mode"):
        env.execute()
    env2, sink2 = build(False)
    env2.execute()
    got = sorted((int(k), float(v)) for k, v in sink.results)
    want = sorted((int(k), float(v)) for k, v in sink2.results)
    assert len(got) > 0 and got == want


# ---------------------------------------------------------------------------
# snapshot / restore through the fused runner
# ---------------------------------------------------------------------------

def test_fused_runner_snapshot_restore_parity():
    """Snapshot mid-stream, restore into a fresh runner, continue: the
    union of outputs matches an uninterrupted run (checkpointed jobs take
    the fused path by default now)."""
    def build():
        cfg = Configuration()
        cfg.set(ExecutionOptions.SUPERBATCH_STEPS, 2)
        env = StreamExecutionEnvironment.get_execution_environment(cfg)
        win = (
            env.from_source(_source(n=10))
            .key_by(lambda col: col[:, 0].astype(jnp.int32), traceable=True)
            .window(TumblingEventTimeWindows.of(1_000))
            .aggregate("sum", value_fn=lambda col: col[:, 1],
                       value_traceable=True)
        )
        win.collect()
        runners, _ = build_runners(plan(env._sinks), cfg)
        entry = runners[0]
        assert isinstance(entry, DeviceChainRunner)
        out = []
        entry.downstream = _Collect(out)
        return entry, out

    class _Collect:
        def __init__(self, out):
            self.out = out

        def on_batch(self, vals, ts):
            self.out.extend((int(k), float(v)) for k, v in vals)

        def on_watermark(self, wm):
            pass

        def on_end(self):
            pass

    def batches():
        for t0 in range(0, 8):
            base = 10_000 + t0 * 400
            vals = np.asarray(
                [[float(t0 % 3), 2.0], [float((t0 + 1) % 3), 3.0]])
            ts = np.asarray([base, base + 100], dtype=np.int64)
            yield vals, ts, base

    # uninterrupted
    r1, out1 = build()
    for vals, ts, base in batches():
        r1.on_batch(vals, ts)
        r1.on_watermark(base)
    r1.on_end()

    # snapshot after 4 batches, restore, continue
    r2, out2 = build()
    it = list(batches())
    for vals, ts, base in it[:4]:
        r2.on_batch(vals, ts)
        r2.on_watermark(base)
    snap = r2.snapshot()
    r3, out3 = build()
    r3.restore(snap)
    for vals, ts, base in it[4:]:
        r3.on_batch(vals, ts)
        r3.on_watermark(base)
    r3.on_end()
    assert sorted(out1) == sorted(out2 + out3)


def test_restore_preserves_held_future_column_dtype():
    """Held-back far-future raw columns survive a snapshot/restore round
    trip at their ORIGINAL dtype: the raw-payload cast is dtype-free, and a
    tolist() round-trip would promote float32 to float64 — making the first
    post-restore dispatch trip the fused pipeline's fixed-geometry check."""
    from flink_tpu.runtime.fused_window_operator import StepNormalizer
    from flink_tpu.runtime.fused_window_pipeline import (
        FusedWindowPipeline,
        TracedPrologue,
    )

    pro = TracedPrologue(transforms=(),
                         key_fn=lambda col: col[:, 0].astype(jnp.int32))
    pipe = FusedWindowPipeline(
        SlidingEventTimeWindows.of(10_000, 1_000), "count",
        key_capacity=8, prologue=pro,
    )
    norm = StepNormalizer(pipe, raw_payload=True)
    col = np.asarray([[1.0, 2.0]], dtype=np.float32)
    norm.push(col, None, np.asarray([10_000], np.int64))
    # far enough past the ring frontier to be held back, not staged
    far = 10_000 + (pipe.S + pipe.NSB + 1) * pipe.sl * pipe.slice_ms \
        if hasattr(pipe, "slice_ms") else 10_000 + 10_000_000
    norm.push(col, None, np.asarray([far], np.int64))
    assert norm.num_future_held == 1, "harness: record was not held back"

    restored = StepNormalizer(pipe, raw_payload=True)
    restored.restore(norm.snapshot())
    held_col = restored._future[0][0]
    assert held_col.dtype == np.float32, (
        f"held column came back as {held_col.dtype}: restore must preserve "
        "the raw payload dtype or the geometry check kills the job"
    )


def test_all_empty_superbatch_does_not_pin_geometry():
    """A watermark-only dispatch before any data (the restore-then-watermark
    ordering) must not pin the scalar placeholder column shape on the
    pipeline — the first real batch afterwards is NOT a mid-stream geometry
    change."""
    from flink_tpu.runtime.fused_window_pipeline import (
        FusedWindowPipeline,
        TracedPrologue,
    )

    pro = TracedPrologue(transforms=(),
                         key_fn=lambda col: col[:, 0].astype(jnp.int32))
    pipe = FusedWindowPipeline(
        TumblingEventTimeWindows.of(1_000), "count",
        key_capacity=8, prologue=pro,
    )
    empty = (np.empty((0, 2), np.float32), np.empty(0, np.int64))
    pipe.process_superbatch_raw([empty, empty], [11_000, 12_000])
    assert pipe._raw_shape is None
    data = (np.asarray([[1.0, 0.0]], np.float32),
            np.asarray([13_000], np.int64))
    pipe.process_superbatch_raw([data, empty], [13_000, 13_500])  # no raise
    assert pipe._raw_shape == (2,)


def test_wide_integer_columns_raise_instead_of_wrapping():
    """An int64 record column whose values exceed int32 must raise loudly
    on BOTH paths (fused staging and the host fallback cast) — narrowing
    silently would re-key records differently than 64-bit host math."""
    from flink_tpu.runtime.fused_window_pipeline import (
        FusedWindowPipeline,
        TracedPrologue,
    )
    from flink_tpu.utils.arrays import canonical_column

    big = np.asarray([[5_000_000_000]], dtype=np.int64)  # > 2**31
    with pytest.raises(TypeError, match="would silently wrap"):
        canonical_column(big, "key_by selector input")

    pro = TracedPrologue(transforms=(),
                         key_fn=lambda col: (col[:, 0] // 1_000_000_000))
    pipe = FusedWindowPipeline(
        TumblingEventTimeWindows.of(1_000), "count",
        key_capacity=8, prologue=pro,
    )
    with pytest.raises(TypeError, match="would silently wrap"):
        pipe.stage_superbatch_raw(
            [(big, np.asarray([10_000], np.int64))], [10_000])

    # in-range wide columns narrow cleanly (and floats guard overflow)
    ok = canonical_column(np.asarray([[7]], np.int64), "x")
    assert ok.dtype == np.int32
    with pytest.raises(TypeError, match="overflow"):
        canonical_column(np.asarray([1e300]), "x")


def test_epoch_scale_timestamps_with_traced_map_ts_raise_loudly():
    """With jax x64 disabled the staged timestamp column is int32; epoch-ms
    timestamps do not fit and would silently wrap inside a traceable map_ts
    UDF — that must be a loud error, never silent divergence from the host
    path."""
    from flink_tpu.runtime.fused_window_pipeline import (
        FusedWindowPipeline,
        TracedPrologue,
    )

    pro = TracedPrologue(
        transforms=(("map_ts", lambda col, ts: col),),
        key_fn=lambda col: col[:, 0].astype(jnp.int32),
    )
    pipe = FusedWindowPipeline(
        TumblingEventTimeWindows.of(1_000), "count",
        key_capacity=8, prologue=pro,
    )
    epoch_ms = 1_760_000_000_000  # far beyond int32
    step = (np.asarray([[1.0, 0.0]], np.float32),
            np.asarray([epoch_ms], np.int64))
    with pytest.raises(TypeError, match="do not fit"):
        pipe.stage_superbatch_raw([step], [epoch_ms])


# ---------------------------------------------------------------------------
# ingest edge: as_device_column
# ---------------------------------------------------------------------------

def test_as_device_column_zero_copy_and_compaction():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    assert as_device_column(a) is a                      # contiguous: untouched
    ro = np.frombuffer(a.tobytes(), dtype=np.float32).reshape(3, 4)
    ro.flags.writeable = False
    assert as_device_column(ro) is ro                    # wire view: untouched
    nc = a[:, ::2]
    out = as_device_column(nc)
    assert out is not nc and out.flags.c_contiguous
    np.testing.assert_array_equal(out, nc)
    objs = np.empty(2, dtype=object)
    assert as_device_column(objs) is objs                # record mode: pass
    assert as_device_column([1, 2]) == [1, 2]            # non-ndarray: pass


def test_plan_describe_names_the_chain():
    env, cfg, _ = _build_env()
    plans, absorbed = plan_device_chains(plan(env._sinks))
    assert len(plans) == 1 and len(absorbed) == 1
    (p,) = plans.values()
    assert isinstance(p, DeviceChainPlan)
    assert "=>" in p.name
