"""Chaos plane (ISSUE-10): FaultPlan determinism, the five seam hooks, and
every piece of transient-fault hardening the scenario matrix leans on —
rpc retry/timeout, tolerable-failed-checkpoints, fsync + typed corrupt-
checkpoint restore skip, dataplane reconnect with seq continuity, the
stuck-task watchdog, and prompt observable heartbeat shutdown.

The `*_without_*` tests double as the PR's load-bearing proof: they run
the same injected faults with one hardening layer disabled and show the
scenario assertions (zero restarts / no tolerance / no reconnect) fail —
i.e. the pre-hardening runtime demonstrably fails the chaos matrix.
"""

import os
import threading
import time

import numpy as np
import pytest

from flink_tpu.chaos import (
    INJECTED_MARKER,
    FaultPlan,
    FaultRule,
    InjectedCrash,
    InjectedFault,
    active_plan,
)
from flink_tpu.chaos import plan as chaos_plan_module
from flink_tpu.chaos.scenarios import (
    PacedKeyedSource,
    _cluster,
    _collect_dist,
    _dist_expected,
    _dist_spec,
    _await,
    _await_job,
    _run_mini_count_job,
)
from flink_tpu.testing.harness import fault_injection


# ---------------------------------------------------------------------------
# FaultPlan / hook mechanics
# ---------------------------------------------------------------------------

def test_hook_is_none_by_default_and_install_uninstall_cycle():
    assert chaos_plan_module.HOOK is None      # zero hot-path cost when off
    assert active_plan() is None
    with fault_injection(rules=[]) as plan:
        assert chaos_plan_module.HOOK is not None
        assert chaos_plan_module.HOOK.__self__ is plan   # the plan's act
        assert active_plan() is plan
        with pytest.raises(RuntimeError, match="already installed"):
            chaos_plan_module.install_plan(plan)
    assert chaos_plan_module.HOOK is None
    assert active_plan() is None


def test_nth_and_max_fires_are_call_deterministic():
    p = FaultPlan([FaultRule("rpc", "error", match="a.b", nth=3,
                             max_fires=2)])
    assert p.act("rpc", "a.b") is None          # call 1
    assert p.act("rpc", "other") is None        # non-matching site
    assert p.act("rpc", "a.b") is None          # call 2
    for _ in range(2):                          # calls 3, 4 fire
        with pytest.raises(InjectedFault):
            p.act("rpc", "a.b")
    assert p.act("rpc", "a.b") is None          # budget exhausted
    assert p.total_fired == 2


def test_probability_sequence_is_seed_deterministic():
    def fire_pattern(seed):
        p = FaultPlan([FaultRule("device", "drop", probability=0.5,
                                 max_fires=None)], seed=seed)
        return [p.act("device", "op") == "drop" for _ in range(64)]

    a, b = fire_pattern(11), fire_pattern(11)
    assert a == b and any(a) and not all(a)
    assert fire_pattern(12) != a


def test_window_trigger_bounds_the_outage():
    clock_box = [0.0]
    p = FaultPlan([FaultRule("heartbeat", "partition", window_s=(1.0, 2.0),
                             max_fires=None)], clock=lambda: clock_box[0])
    assert p.act("heartbeat", "tm-x") is None        # before the window
    clock_box[0] = 1.5
    assert p.act("heartbeat", "tm-x") == "drop"      # inside
    clock_box[0] = 2.5
    assert p.act("heartbeat", "tm-x") is None        # healed


def test_injected_fault_is_a_labeled_connection_error():
    e = InjectedFault("rpc:error:x")
    assert isinstance(e, ConnectionError) and isinstance(e, OSError)
    assert INJECTED_MARKER in str(e) and INJECTED_MARKER in repr(e)
    assert isinstance(InjectedCrash("x"), InjectedFault)


def test_plan_from_config_json_rules_and_disabled():
    from flink_tpu.config import ChaosOptions, Configuration

    assert FaultPlan.from_config(Configuration()) is None   # default off
    cfg = (Configuration()
           .set(ChaosOptions.ENABLED, True)
           .set(ChaosOptions.SEED, 9)
           .set(ChaosOptions.RULES,
                '[{"scope": "storage", "fault": "error", "match": "save",'
                ' "nth": 2}]'))
    plan = FaultPlan.from_config(cfg)
    assert plan.seed == 9 and len(plan.rules) == 1
    assert plan.rules[0].scope == "storage" and plan.rules[0].nth == 2


def test_unknown_scope_or_fault_is_rejected():
    with pytest.raises(ValueError, match="scope"):
        FaultRule("warp-drive", "error")
    with pytest.raises(ValueError, match="fault"):
        FaultRule("rpc", "gremlins")


def test_partition_default_widens_but_explicit_max_fires_wins():
    # omitted: partition models an outage -> unlimited fires
    assert FaultRule("heartbeat", "partition").max_fires is None
    # explicit: an operator asking for exactly one dropped beat gets one
    assert FaultRule("heartbeat", "partition", max_fires=1).max_fires == 1
    assert FaultRule("rpc", "error").max_fires == 1


# ---------------------------------------------------------------------------
# checkpoint storage: typed corruption + fsync durability + restore skip
# ---------------------------------------------------------------------------

def test_fs_load_wraps_torn_metadata(tmp_path):
    from flink_tpu.checkpoint.storage import (
        CorruptCheckpointError,
        FsCheckpointStorage,
    )

    st = FsCheckpointStorage(str(tmp_path))
    handle = st.save(1, {"x": np.arange(16)})
    assert st.load(handle)["x"].shape == (16,)
    size = os.path.getsize(handle)
    with open(handle, "r+b") as f:
        f.truncate(size // 3)
    with pytest.raises(CorruptCheckpointError, match="chk-1"):
        st.load(handle)


def test_fs_load_wraps_missing_chk_dir(tmp_path):
    import shutil

    from flink_tpu.checkpoint.storage import (
        CorruptCheckpointError,
        FsCheckpointStorage,
    )

    st = FsCheckpointStorage(str(tmp_path))
    handle = st.save(1, {"x": 1})
    shutil.rmtree(tmp_path / "chk-1")
    with pytest.raises(CorruptCheckpointError):
        st.load(handle)


def test_memory_load_wraps_missing_handle():
    from flink_tpu.checkpoint.storage import (
        CorruptCheckpointError,
        MemoryCheckpointStorage,
    )

    st = MemoryCheckpointStorage()
    st.save(1, {"x": 1})
    assert st.load("mem:1") == {"x": 1}
    with pytest.raises(CorruptCheckpointError):
        st.load("mem:7")


def test_fs_save_fsyncs_file_and_parent_dir(tmp_path, monkeypatch):
    from flink_tpu.checkpoint import storage as storage_mod

    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(storage_mod.os, "fsync",
                        lambda fd: (synced.append(fd), real_fsync(fd))[1])
    st = storage_mod.FsCheckpointStorage(str(tmp_path))
    st.save(1, {"x": np.arange(4)})
    # one fsync for the temp file (before the rename) + one for the parent
    # directory (after it): the torn-metadata-behind-the-marker window the
    # torn-checkpoint scenario models is closed
    assert len(synced) >= 2


def test_latest_snapshot_skips_torn_checkpoints(tmp_path):
    from flink_tpu.checkpoint.coordinator import CheckpointCoordinator
    from flink_tpu.checkpoint.storage import FsCheckpointStorage

    st = FsCheckpointStorage(str(tmp_path))
    for cid in (1, 2, 3):
        st.save(cid, {"cid": cid})
    for cid in (3,):          # the newest is torn
        handle = os.path.join(str(tmp_path), f"chk-{cid}", "_metadata")
        with open(handle, "r+b") as f:
            f.truncate(4)
    coord = CheckpointCoordinator(st, interval_ms=1000)
    assert coord.latest_snapshot()["cid"] == 2     # skipped the torn one
    for cid in (1, 2):        # tear everything
        handle = os.path.join(str(tmp_path), f"chk-{cid}", "_metadata")
        with open(handle, "r+b") as f:
            f.truncate(4)
    assert coord.latest_snapshot() is None


# ---------------------------------------------------------------------------
# tolerable-failed-checkpoints (coordinator / MiniCluster path)
# ---------------------------------------------------------------------------

class _FlakyStorage:
    def __init__(self, inner):
        self.inner = inner
        self.exploding = False
        self.last_save_bytes = 0

    def save(self, cid, data):
        if self.exploding:
            raise OSError("disk on fire")
        out = self.inner.save(cid, data)
        self.last_save_bytes = self.inner.last_save_bytes
        return out

    def __getattr__(self, name):
        return getattr(self.inner, name)


def test_coordinator_tolerates_then_escalates():
    from flink_tpu.checkpoint.coordinator import (
        CheckpointCoordinator,
        CheckpointFailuresExhaustedError,
    )
    from flink_tpu.checkpoint.storage import MemoryCheckpointStorage
    from flink_tpu.metrics.checkpoint_stats import CheckpointStatsTracker

    st = _FlakyStorage(MemoryCheckpointStorage())
    stats = CheckpointStatsTracker()
    clock_box = [0.0]
    coord = CheckpointCoordinator(st, interval_ms=1, stats=stats,
                                  clock=lambda: clock_box[0],
                                  tolerable_failures=2)

    def tick():
        clock_box[0] += 1.0

    st.exploding = True
    for expected_gauge in (1, 2):              # two tolerated failures
        tick()
        assert coord.trigger(lambda: {"s": 1}) is None
        assert stats.gauge_values()["consecutiveFailedCheckpoints"] \
            == expected_gauge
    st.exploding = False
    tick()
    cid = coord.trigger(lambda: {"s": 1})      # heals: completes + resets
    assert cid is not None
    assert stats.gauge_values()["consecutiveFailedCheckpoints"] == 0
    assert stats.num_failed == 2 and stats.num_completed == 1
    st.exploding = True
    tick()
    assert coord.trigger(lambda: {"s": 1}) is None
    tick()
    assert coord.trigger(lambda: {"s": 1}) is None
    tick()
    with pytest.raises(CheckpointFailuresExhaustedError,
                       match="tolerable-failed-checkpoints 2"):
        coord.trigger(lambda: {"s": 1})        # 3rd consecutive: escalate
    # the escalation restarts the job; the NEW attempt must get its full
    # tolerance back (the coordinator outlives restarts) — without the
    # reset, one isolated failure would re-escalate and hot-loop restarts
    coord.reset_failure_streak()
    tick()
    assert coord.trigger(lambda: {"s": 1}) is None   # tolerated again


def test_coordinator_never_tolerates_crash_or_base_exceptions():
    """Tolerance is for storage faults: an InjectedCrash (chaos process-
    death model) and interpreter-level BaseExceptions (KeyboardInterrupt)
    must propagate even with budget left — a swallowed Ctrl-C, or an
    absorbed crash fault, would violate plan.py's escalation contract."""
    from flink_tpu.checkpoint.coordinator import CheckpointCoordinator
    from flink_tpu.checkpoint.storage import MemoryCheckpointStorage

    clock_box = [10.0]
    coord = CheckpointCoordinator(MemoryCheckpointStorage(), interval_ms=1,
                                  clock=lambda: clock_box[0],
                                  tolerable_failures=5)

    def crash_capture():
        raise InjectedCrash("storage:crash:*")

    with pytest.raises(InjectedCrash):
        coord.trigger(crash_capture)

    def interrupt_capture():
        raise KeyboardInterrupt()

    clock_box[0] += 1
    with pytest.raises(KeyboardInterrupt):
        coord.trigger(interrupt_capture)


def test_jm_persist_crash_fault_is_never_tolerated(tmp_path):
    import numpy as np

    from flink_tpu.runtime.cluster import JobManagerEndpoint, _JobState

    svc = RpcService()
    jm = JobManagerEndpoint(svc, checkpoint_dir=str(tmp_path / "chk"),
                            tolerable_failed_checkpoints=5)
    try:
        job = _JobState("j1", "bk", 1, "spec")
        job.attempt = 1
        job.status = "RUNNING"
        jm._jobs["j1"] = job
        job.pending[1] = {}
        job.pending_target[1] = 10
        job.stats.report_pending(1)

        def crash_save(cid, data):
            raise InjectedCrash("storage:crash:save")

        jm._storage.save = crash_save
        with pytest.raises(InjectedCrash):
            jm.ack_checkpoint("j1", 1, 0, 1, {"x": np.arange(4)})
        # the record still flipped FAILED (no PENDING-forever leak)
        assert job.stats.checkpoint(1)["status"] == "FAILED"
    finally:
        jm.stop()
        svc.stop()


def test_minicluster_config_chaos_plan_uninstalls_after_the_job():
    from flink_tpu.config import ChaosOptions

    assert active_plan() is None
    _client, results = _run_mini_count_job(
        "drill", records=650,
        extra_config={ChaosOptions.ENABLED: True, ChaosOptions.SEED: 3})
    # the drill's plan must not leak into later jobs in this process
    assert _await(lambda: active_plan() is None, 10.0), \
        "config-installed FaultPlan leaked past its job"
    assert results       # empty-rule drill is result-neutral


def test_coordinator_default_zero_tolerance_raises_original():
    from flink_tpu.checkpoint.coordinator import CheckpointCoordinator
    from flink_tpu.checkpoint.storage import MemoryCheckpointStorage

    st = _FlakyStorage(MemoryCheckpointStorage())
    st.exploding = True
    clock_box = [10.0]
    coord = CheckpointCoordinator(st, interval_ms=1,
                                  clock=lambda: clock_box[0])
    with pytest.raises(OSError, match="disk on fire"):
        coord.trigger(lambda: {"s": 1})


# ---------------------------------------------------------------------------
# heartbeat: prompt shutdown + counted swallows
# ---------------------------------------------------------------------------

def test_heartbeat_stop_joins_promptly():
    from flink_tpu.runtime.heartbeat import HeartbeatManager

    hb = HeartbeatManager(interval=30.0, timeout=60.0)   # long sleep cycle
    t0 = time.monotonic()
    hb.stop()
    assert time.monotonic() - t0 < 5.0, "stop() blocked on the interval"
    assert not hb._thread.is_alive(), "stop() did not join the loop thread"


def test_heartbeat_counts_missed_pings_and_on_dead_errors():
    from flink_tpu.runtime.heartbeat import HeartbeatManager

    def bad_ping():
        raise ConnectionError("flap")

    def bad_on_dead(tid):
        raise RuntimeError("callback bug")

    hb = HeartbeatManager(interval=0.05, timeout=0.2, on_dead=bad_on_dead)
    hb.monitor("tm-1", ping=bad_ping)
    assert _await(lambda: hb.missed_pings >= 2 and not hb.is_alive("tm-1"),
                  5.0), (hb.missed_pings, hb.is_alive("tm-1"))
    assert _await(lambda: hb.on_dead_errors == 1, 5.0)
    hb.stop()


# ---------------------------------------------------------------------------
# rpc: per-call timeout, idempotent retry, crash escalation
# ---------------------------------------------------------------------------

from flink_tpu.runtime.rpc import (  # noqa: E402
    RetryPolicy,
    RpcEndpoint,
    RpcGateway,
    RpcService,
)


class _FlakyTarget(RpcEndpoint):
    def __init__(self):
        super().__init__(name="flaky")
        self.pings = 0
        self.mutations = 0

    def ping(self):                 # in IDEMPOTENT_METHODS
        self.pings += 1
        return "pong"

    def mutate(self):               # job-mutating shape: single-attempt
        self.mutations += 1
        return "mutated"


def test_idempotent_call_retries_through_injected_flap():
    svc = RpcService()
    ep = _FlakyTarget()
    svc.register(ep)
    gw = svc.gateway(svc.address, "flaky")
    try:
        with fault_injection(rules=[
            {"scope": "rpc", "fault": "error", "match": "flaky.ping",
             "nth": 1, "max_fires": 2},
        ]) as plan:
            assert gw.ping() == "pong"        # absorbed by backoff retry
        assert plan.total_fired == 2
        assert ep.pings == 1                  # server saw exactly one call
    finally:
        gw.close()
        svc.stop()


def test_non_idempotent_call_is_single_attempt():
    svc = RpcService()
    ep = _FlakyTarget()
    svc.register(ep)
    gw = svc.gateway(svc.address, "flaky")
    try:
        with fault_injection(rules=[
            {"scope": "rpc", "fault": "error", "match": "flaky.mutate",
             "nth": 1},
        ]):
            with pytest.raises(InjectedFault):
                gw.mutate()
        assert ep.mutations == 0              # never re-sent
    finally:
        gw.close()
        svc.stop()


def test_injected_crash_is_never_retried():
    svc = RpcService()
    svc.register(_FlakyTarget())
    gw = svc.gateway(svc.address, "flaky")
    try:
        with fault_injection(rules=[
            {"scope": "rpc", "fault": "crash", "match": "flaky.ping",
             "nth": 1},
        ]) as plan:
            with pytest.raises(InjectedCrash):
                gw.ping()
        assert plan.total_fired == 1          # one attempt, no retry
    finally:
        gw.close()
        svc.stop()


def test_retry_gives_up_at_max_attempts():
    svc = RpcService()
    svc.register(_FlakyTarget())
    gw = RpcGateway(svc.address, "flaky",
                    retry=RetryPolicy(max_attempts=3,
                                      initial_backoff_s=0.005))
    try:
        with fault_injection(rules=[
            {"scope": "rpc", "fault": "error", "match": "flaky.ping",
             "max_fires": None},
        ]) as plan:
            with pytest.raises(InjectedFault):
                gw.ping()
        assert plan.total_fired == 3
    finally:
        gw.close()
        svc.stop()


def test_server_side_crash_severs_the_connection_without_a_reply():
    """A crash rule at the server: rpc seam models the server dying
    mid-request: the connection drops with NO reply (shipping it back as
    a RemoteRpcError would absorb the process-death model into an
    ordinary handler error); the service itself survives the drill."""
    svc = RpcService()
    ep = _FlakyTarget()
    svc.register(ep)
    gw = svc.gateway(svc.address, "flaky")
    try:
        with fault_injection(rules=[
            {"scope": "rpc", "fault": "crash", "match": "server:flaky.mutate",
             "nth": 1},
        ]) as plan:
            with pytest.raises(ConnectionError):
                gw.mutate()           # non-idempotent: no retry, loud fail
        assert plan.total_fired == 1
        assert ep.mutations == 0      # the "crashed" dispatch never ran
        assert gw.ping() == "pong"    # fresh connection: service is alive
    finally:
        gw.close()
        svc.stop()


def test_benign_declines_do_not_move_the_consecutive_gauge():
    from flink_tpu.metrics.checkpoint_stats import CheckpointStatsTracker

    t = CheckpointStatsTracker()
    t.report_pending(1)
    t.report_failed(1, "at step 9 > target 7", benign=True)   # outrun decline
    assert t.num_failed == 1
    assert t.gauge_values()["consecutiveFailedCheckpoints"] == 0
    t.report_pending(2)
    t.report_failed(2, "persist failed: disk on fire")        # real fault
    assert t.gauge_values()["consecutiveFailedCheckpoints"] == 1


class _WedgedTarget(RpcEndpoint):
    def __init__(self):
        super().__init__(name="wedged")
        self.release = threading.Event()

    def block(self):
        # holds the endpoint main thread (and the server's connection
        # thread blocked in _invoke(...).result()) until released — the
        # stuck-endpoint model from the satellite task
        self.release.wait()
        return "unblocked"


def test_wedged_endpoint_times_out_attributed_and_service_recovers():
    from flink_tpu.metrics.checkpoint_stats import ExceptionHistory

    svc = RpcService()
    wedged = _WedgedTarget()
    healthy = _FlakyTarget()
    svc.register(wedged)
    svc.register(healthy)
    gw = svc.gateway(svc.address, "wedged", timeout=0.75)
    try:
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="wedged.block .* timed out"):
            gw.block()
        assert time.monotonic() - t0 < 8.0, "the gateway timeout never fired"
        # the error is attributable in the exception history (the shape a
        # supervising job records for a wedged control-plane dependency)
        history = ExceptionHistory()
        try:
            gw.block()
        except TimeoutError as e:
            history.record_failure(repr(e), task="wedged-endpoint",
                                   exception=e)
        entry = history.payload()["entries"][0]
        assert entry["task"] == "wedged-endpoint"
        assert entry["injected"] is False       # real wedge, not chaos
        assert "timed out" in entry["exception"]
        # a subsequent call on a FRESH connection to a healthy endpoint of
        # the same service succeeds — the wedge holds one connection
        # thread, not the server
        gw2 = svc.gateway(svc.address, "flaky")
        assert gw2.ping() == "pong"
        gw2.close()
    finally:
        wedged.release.set()
        gw.close()
        svc.stop()


# ---------------------------------------------------------------------------
# dataplane: reconnect with sequence continuity
# ---------------------------------------------------------------------------

def _exchange_pair(tmp_channel="chaos-ch"):
    from flink_tpu.runtime.dataplane import ExchangeServer, OutputChannel

    es = ExchangeServer(capacity=8)
    ch = es.channel(tmp_channel)
    out = OutputChannel(es.address, tmp_channel)
    return es, ch, out


def test_reconnect_resumes_on_seq_continuity():
    es, ch, out = _exchange_pair()
    try:
        out.send(("p", 0))
        out.send(("p", 1))
        assert ch.poll(5.0) == ("p", 0) and ch.poll(5.0) == ("p", 1)
        out.reconnect()                 # re-runs open/credit negotiation
        out.send(("p", 2))
        assert ch.poll(5.0) == ("p", 2)
        assert out.num_reconnects == 1
    finally:
        out.close()
        es.stop()


def test_injected_send_error_is_resendable_after_reconnect():
    es, ch, out = _exchange_pair()
    try:
        with fault_injection(rules=[
            {"scope": "dataplane", "fault": "error", "nth": 2},
        ]):
            out.send(("p", 0))
            with pytest.raises(InjectedFault):
                out.send(("p", 1))      # raised BEFORE any seq/credit burn
            out.reconnect()
            out.send(("p", 1))          # exact resume: nothing was lost
        assert ch.poll(5.0) == ("p", 0) and ch.poll(5.0) == ("p", 1)
    finally:
        out.close()
        es.stop()


def test_dropped_frame_poisons_receiver_and_refuses_resume():
    from flink_tpu.runtime.dataplane import SequenceLostError

    es, ch, out = _exchange_pair()
    try:
        with fault_injection(rules=[
            {"scope": "dataplane", "fault": "drop", "nth": 2},
        ]):
            out.send(("p", 0))
            out.send(("p", 1))          # consumed seq 1, never hit the wire
            out.send(("p", 2))          # receiver sees seq 2: gap
        assert ch.poll(5.0) == ("p", 0)
        with pytest.raises(ConnectionError, match="sequence gap"):
            ch.poll(5.0)
        # a frame is GONE: the reconnect negotiation must refuse to resume
        # with the TYPED loss error send_part escalates on immediately
        # (re-dialing can never heal a lost frame)
        with pytest.raises(SequenceLostError, match="lost in transit"):
            out.reconnect()
    finally:
        out.close()
        es.stop()


def test_transport_recv_drop_swallows_a_verified_frame():
    """recv-side transport drop: the frame is read AND MAC-verified (the
    replay counter advances — drop models loss above the authenticated
    transport, never a codec desync), then discarded; a dropped data
    frame therefore surfaces as a clean sequence gap downstream."""
    from flink_tpu.runtime.dataplane import ExchangeServer, OutputChannel

    es = ExchangeServer(capacity=8)
    ch = es.channel("rx-drop")
    out = None
    try:
        with fault_injection(rules=[
            # port-qualified match: only THIS server's frames count toward
            # nth (other live sockets' recv_msg calls must not skew it).
            # The sender connects INSIDE the block, so the handler's
            # per-frame recv_msg entries count 1=open, 2=p0, 3=p1.
            {"scope": "transport", "fault": "drop",
             "match": f"recv_msg:{es.port}", "nth": 3},
        ]) as plan:
            out = OutputChannel(es.address, "rx-drop")
            out.send(("p", 0))
            out.send(("p", 1))          # swallowed at the receiver
            out.send(("p", 2))
            assert ch.poll(5.0) == ("p", 0)
            with pytest.raises(ConnectionError, match="sequence gap"):
                ch.poll(5.0)
            assert plan.total_fired == 1
    finally:
        if out is not None:
            out.close()
        es.stop()


# ---------------------------------------------------------------------------
# load-bearing proofs: the same faults with one hardening layer disabled
# fail the scenario assertions (what the pre-hardening runtime did)
# ---------------------------------------------------------------------------

def test_dataplane_blip_without_reconnect_window_restarts_the_job():
    from flink_tpu.config import Configuration, ExchangeOptions

    source = PacedKeyedSource(steps=30, batch=40, n_keys=9, interval_s=0.02)
    expected = _dist_expected(source)
    spec = _dist_spec(source, "blip-no-reconnect")
    spec.config = Configuration().set(ExchangeOptions.RECONNECT_WINDOW_MS, 0)
    with _cluster(num_tms=2) as (client, _jm, _tes):
        with fault_injection(rules=[
            {"scope": "dataplane", "fault": "error", "match": "0->1",
             "nth": 5, "max_fires": 1},
        ]):
            job_id = client.submit_job(spec.to_bytes(), 2)
            st = _await_job(client, job_id)
        assert st["status"] == "FINISHED", st
        # recovery machinery still saves the job — but only through a full
        # restart: exactly what the reconnect hardening exists to avoid
        # (the dataplane-blip scenario asserts restarts == 0)
        assert st["restarts"] >= 1
        assert _collect_dist(client.job_result(job_id)) == expected


def test_ack_flap_without_idempotent_retry_restarts_the_job(monkeypatch):
    import flink_tpu.runtime.rpc as rpc_mod

    monkeypatch.setattr(rpc_mod, "IDEMPOTENT_METHODS", frozenset())
    source = PacedKeyedSource(steps=30, batch=40, n_keys=9, interval_s=0.08)
    expected = _dist_expected(source)
    with _cluster(num_tms=2) as (client, _jm, _tes):
        with fault_injection(rules=[
            {"scope": "rpc", "fault": "error",
             "match": "jobmanager.ack_checkpoint", "nth": 1, "max_fires": 1},
        ]) as plan:
            job_id = client.submit_job(
                _dist_spec(source, "flap-no-retry").to_bytes(), 2)
            st = _await_job(client, job_id)
        assert st["status"] == "FINISHED", st
        assert plan.total_fired == 1
        # without retry, one transient ack failure costs a whole restart
        # (the rpc-flap scenario asserts restarts == 0 with retry in place)
        assert st["restarts"] >= 1
        assert _collect_dist(client.job_result(job_id)) == expected


def test_brownout_without_tolerance_restarts_the_job(tmp_path):
    with fault_injection(rules=[
        {"scope": "storage", "fault": "error", "match": "save",
         "nth": 2, "max_fires": 1},
    ]):
        client, _results = _run_mini_count_job(
            "brownout-no-tolerance", chk_dir=str(tmp_path / "chk"),
            tolerable=0)
    assert client.status().value == "FINISHED"
    # zero tolerance: one failed save = one restart (the storage-brownout
    # scenario asserts restarts == 0 with tolerable=5)
    assert client.num_restarts >= 1
    entry = client.exceptions.payload()["entries"][0]
    assert entry["injected"] is True     # and it is chaos-attributed


# ---------------------------------------------------------------------------
# stuck-task watchdog (distributed)
# ---------------------------------------------------------------------------

class WedgingSource:
    """Wraps a PacedKeyedSource; step `wedge_at` blocks until `flag_path`
    exists (cross-attempt: the wedge persists through restarts until the
    test releases it)."""

    def __init__(self, base: PacedKeyedSource, flag_path: str, wedge_at: int):
        self.base = base
        self.flag_path = flag_path
        self.wedge_at = wedge_at

    def __call__(self, shard, num_shards):
        inner = self.base(shard, num_shards)
        outer = self

        class _W(list):
            def __init__(self):
                super().__init__(range(outer.base.steps))

            def __getitem__(self, s):
                if s == outer.wedge_at:
                    deadline = time.monotonic() + 60
                    while not os.path.exists(outer.flag_path) \
                            and time.monotonic() < deadline:
                        time.sleep(0.05)
                return inner[s]

        return _W()


def test_stuck_task_watchdog_fails_over_a_wedged_task(tmp_path):
    flag = str(tmp_path / "resume")
    base = PacedKeyedSource(steps=30, batch=40, n_keys=9, interval_s=0.01)
    expected = _dist_expected(base)
    spec = _dist_spec(base, "stuck-task")
    spec.source_factory = WedgingSource(base, flag, wedge_at=5)
    with _cluster(num_tms=1, stuck_task_timeout_ms=900,
                  heartbeat_interval=0.1) as (client, _jm, _tes):
        job_id = client.submit_job(spec.to_bytes(), 1)
        # the wedged task is INSIDE a live, heartbeating TM — only the
        # watchdog can see it; wait for the attributed failover
        assert _await(
            lambda: client.job_status(job_id)["restarts"] >= 1, 30.0), \
            client.job_status(job_id)
        exc = client.job_exceptions(job_id)
        entry = exc["entries"][0]
        assert "stuck-task watchdog" in entry["exception"]
        assert entry["task"] == "shard-0"
        assert entry["task_manager"]           # TM attribution: it is ALIVE
        open(flag, "w").close()                # release the wedge
        st = _await_job(client, job_id)
        assert st["status"] == "FINISHED", st
        assert _collect_dist(client.job_result(job_id)) == expected


# ---------------------------------------------------------------------------
# chaos-off/empty-plan parity: the plane never perturbs results
# ---------------------------------------------------------------------------

def test_installed_empty_plan_is_result_neutral():
    _c1, baseline = _run_mini_count_job("parity-off", records=1300)
    with fault_injection(rules=[]):        # hooks live, zero rules
        _c2, hooked = _run_mini_count_job("parity-on", records=1300)
    assert hooked == baseline
