"""Checkpoint, failure & recovery observability (control-plane half of the
observability plane): CheckpointStatsTracker records/counters/gauges,
exception history + recovery timeline, the coordinator's phase spans and
failed-persist handling, the JM watermark-skew aggregate, and the e2e
MiniCluster acceptance path over REST + Prometheus."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from flink_tpu.api.datastream import StreamExecutionEnvironment
from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
from flink_tpu.checkpoint.coordinator import CheckpointCoordinator
from flink_tpu.checkpoint.storage import MemoryCheckpointStorage
from flink_tpu.config import (
    CheckpointingOptions,
    Configuration,
    ExecutionOptions,
    ObservabilityOptions,
    RestartOptions,
)
from flink_tpu.connectors.sink import CollectSink
from flink_tpu.connectors.source import Batch, DataGeneratorSource
from flink_tpu.core.watermarks import WatermarkStrategy
from flink_tpu.metrics.checkpoint_stats import (
    CheckpointStatsTracker,
    ExceptionHistory,
    operator_bytes_from_snapshot,
    root_cause_chain,
    snapshot_bytes_estimate,
)
from flink_tpu.metrics.registry import MetricRegistry
from flink_tpu.metrics.traces import InMemoryTraceReporter, TraceRegistry
from flink_tpu.runtime.minicluster import JobStatus, MiniCluster
from flink_tpu.runtime.rest import RestServer
from flink_tpu.utils.arrays import obj_array


class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# tracker: records, ring, counters, gauges
# ---------------------------------------------------------------------------

def test_tracker_ring_is_bounded_and_newest_first():
    t = CheckpointStatsTracker(history_size=3)
    for cid in range(1, 6):
        t.report_pending(cid)
        t.report_completed(cid, state_size_bytes=cid * 10)
    p = t.payload()
    assert p["counts"]["completed"] == 5           # lifetime, not ring-bounded
    assert [c["id"] for c in p["history"]] == [5, 4, 3]
    assert t.checkpoint(1) is None                 # evicted
    assert t.checkpoint(5)["state_size_bytes"] == 50
    g = t.gauge_values()
    assert g["numberOfCompletedCheckpoints"] == 5
    assert g["lastCheckpointSize"] == 50
    assert g["numberOfInProgressCheckpoints"] == 0


def test_tracker_ack_latency_failure_and_restore():
    clock = _FakeClock(100.0)
    t = CheckpointStatsTracker(history_size=8, clock=clock)
    t.report_pending(1)
    clock.t = 100.5
    t.report_ack(1, "shard-0", state_size_bytes=400)
    clock.t = 101.0
    t.report_ack(1, "shard-1", state_size_bytes=600)
    clock.t = 101.5
    t.report_completed(1, async_duration_ms=3.0)
    rec = t.checkpoint(1)
    assert rec["status"] == "COMPLETED"
    assert rec["tasks"]["shard-0"]["ack_latency_ms"] == pytest.approx(500.0)
    assert rec["tasks"]["shard-1"]["ack_latency_ms"] == pytest.approx(1000.0)
    # no explicit size: completed record sums the per-task snapshots
    assert rec["state_size_bytes"] == 1000
    assert rec["end_to_end_duration_ms"] == pytest.approx(1500.0)

    t.report_pending(2)
    t.report_failed(2, "persist exploded")
    assert t.checkpoint(2)["status"] == "FAILED"
    assert t.checkpoint(2)["failure_cause"] == "persist exploded"
    # a late decline must never un-complete a completed checkpoint
    t.report_failed(1, "stale decline")
    assert t.checkpoint(1)["status"] == "COMPLETED"
    assert t.gauge_values()["numberOfFailedCheckpoints"] == 1

    clock.t = 110.0
    t.report_restore(1, 42.0)
    g = t.gauge_values()
    assert g["lastCheckpointRestoreTimestamp"] == pytest.approx(110_000.0)
    assert t.payload()["latest"]["restored"]["checkpoint_id"] == 1
    assert t.payload()["latest"]["restored"]["restore_duration_ms"] == 42.0


def test_tracker_straggler_completion_cannot_resurrect_failed_record():
    """A job failure flips pending records to FAILED; a delayed ack that
    then completes the set must not flip the record back to COMPLETED and
    double-count it in both tallies."""
    t = CheckpointStatsTracker()
    t.report_pending(1)
    t.report_failed(1, "job failure: tm lost")
    t.report_ack(1, "shard-1", 100)
    t.report_completed(1, state_size_bytes=999)
    assert t.checkpoint(1)["status"] == "FAILED"
    g = t.gauge_values()
    assert g["numberOfFailedCheckpoints"] == 1
    assert g["numberOfCompletedCheckpoints"] == 0
    assert t.payload()["counts"]["total"] == 1


def test_tracker_retriggered_id_does_not_duplicate_ring_slot():
    t = CheckpointStatsTracker(history_size=4)
    t.report_pending(1)
    t.report_failed(1, "first try died")
    t.report_pending(1)                     # coordinator re-uses the id
    t.report_completed(1)
    assert [c["id"] for c in t.payload()["history"]] == [1]
    assert t.checkpoint(1)["status"] == "COMPLETED"


def test_tracker_gauges_register_on_metric_group():
    reg = MetricRegistry()
    t = CheckpointStatsTracker()
    t.register_metrics(reg.group("job"))
    t.report_pending(1)
    t.report_completed(1, state_size_bytes=123)
    metrics = reg.all_metrics()
    assert metrics["job.numberOfCompletedCheckpoints"].value() == 1
    assert metrics["job.lastCheckpointSize"].value() == 123


# ---------------------------------------------------------------------------
# exception history + recovery timeline
# ---------------------------------------------------------------------------

def test_exception_history_chain_attribution_and_bounds():
    h = ExceptionHistory(size=2)
    try:
        try:
            raise ValueError("root cause")
        except ValueError as inner:
            raise RuntimeError("wrapper") from inner
    except RuntimeError as e:
        h.record_failure(repr(e), task="window-1", task_manager="tm-a",
                         restart_number=0, exception=e)
    entry = h.latest()
    assert entry["task"] == "window-1" and entry["task_manager"] == "tm-a"
    assert entry["root_cause_chain"] == [
        "RuntimeError: wrapper", "ValueError: root cause"]
    for n in range(1, 4):
        h.record_failure(f"f{n}", restart_number=n)
    p = h.payload()
    assert len(p["entries"]) == 2                  # bounded
    assert p["root_exception"] == "f3"             # newest first
    assert p["entries"][0]["restart_number"] == 3


def test_recovery_timeline_downtime_and_replay_depth():
    clock = _FakeClock(50.0)
    h = ExceptionHistory(size=4, clock=clock)
    h.begin_recovery(1, cause="boom", steps_at_failure=17,
                     events_at_failure=1700)
    # open recovery is visible (downtime still unknown)
    assert h.payload()["recoveries"][0]["downtime_ms"] is None
    clock.t = 52.0
    h.complete_recovery(restored_checkpoint_id=3, restore_duration_ms=80.0,
                        restored_step=12)
    rec = h.payload()["recoveries"][0]
    assert rec["downtime_ms"] == pytest.approx(2000.0)
    assert rec["restored_checkpoint_id"] == 3
    assert rec["steps_replayed"] == 5              # 17 at failure, rewound to 12
    g = h.gauge_values()
    assert g["numRestarts"] == 1
    assert g["lastRestartDowntimeMs"] == pytest.approx(2000.0)
    assert g["lastCheckpointRestoreDurationMs"] == 80.0
    # completing again without an open record is a no-op
    h.complete_recovery(restored_checkpoint_id=9)
    assert len(h.payload()["recoveries"]) == 1


def test_root_cause_chain_handles_cycles():
    a = ValueError("a")
    b = RuntimeError("b")
    a.__cause__ = b
    b.__cause__ = a          # pathological cycle must not loop forever
    assert root_cause_chain(a) == ["ValueError: a", "RuntimeError: b"]


# ---------------------------------------------------------------------------
# sizing helpers
# ---------------------------------------------------------------------------

def test_snapshot_bytes_estimate_counts_array_buffers():
    snap = {
        "operator": {"acc": np.zeros((8, 16), dtype=np.float32),
                     "count": np.zeros(64, dtype=np.int32)},
        "step": 3,
        "blob": b"\x00" * 100,
    }
    est = snapshot_bytes_estimate(snap)
    assert est >= 8 * 16 * 4 + 64 * 4 + 100


def test_operator_bytes_from_snapshot_sums_shards():
    per_op: dict = {}
    operator_bytes_from_snapshot(
        {"job.operator.win.stateBytes": 100, "job.busyTimeRatio": 0.5,
         "job.operator.win.stateKeyCount": 7}, into=per_op)
    operator_bytes_from_snapshot(
        {"job.operator.win.stateBytes": 50,
         "job.operator.agg.stateBytes": 25}, into=per_op)
    assert per_op == {"win": 150, "agg": 25}


# ---------------------------------------------------------------------------
# coordinator: phase spans + failed persist/capture
# ---------------------------------------------------------------------------

class _ExplodingStorage(MemoryCheckpointStorage):
    def save(self, checkpoint_id, data):
        raise OSError("disk full")


def _coordinator(storage, stats):
    traces = TraceRegistry(trace_id="ab" * 16)
    rep = InMemoryTraceReporter()
    traces.add_reporter(rep)
    coord = CheckpointCoordinator(storage, interval_ms=1, traces=traces,
                                  stats=stats)
    return coord, rep


def test_coordinator_reports_phases_and_sizes_on_success():
    stats = CheckpointStatsTracker()
    coord, rep = _coordinator(MemoryCheckpointStorage(), stats)
    coord.state_bytes_fn = lambda: {"win": 2048}
    cid = coord.trigger(lambda: {"state": np.arange(100)})
    rec = stats.checkpoint(cid)
    assert rec["status"] == "COMPLETED"
    assert rec["sync_duration_ms"] is not None
    assert rec["async_duration_ms"] is not None
    assert rec["state_size_bytes"] > 0             # pickled artifact size
    assert rec["operators"] == {"win": 2048}
    names = [s.name for s in rep.spans]
    assert names == ["CheckpointCapture", "CheckpointPersist", "Checkpoint"]
    assert all(s.trace_id == "ab" * 16 for s in rep.spans)
    assert rep.spans[-1].attributes["status"] == "COMPLETED"


def test_coordinator_failed_persist_ends_spans_records_failed_and_reraises():
    stats = CheckpointStatsTracker()
    coord, rep = _coordinator(_ExplodingStorage(), stats)
    with pytest.raises(OSError, match="disk full"):
        coord.trigger(lambda: {"state": 1})
    # tracker: FAILED with the cause; no completion counted
    assert stats.gauge_values()["numberOfFailedCheckpoints"] == 1
    assert stats.gauge_values()["numberOfCompletedCheckpoints"] == 0
    rec = stats.checkpoint(1)
    assert rec["status"] == "FAILED" and "disk full" in rec["failure_cause"]
    # no span leaked open: capture closed clean, persist + root closed FAILED
    by_name = {s.name: s for s in rep.spans}
    assert set(by_name) == {"CheckpointCapture", "CheckpointPersist",
                            "Checkpoint"}
    assert by_name["CheckpointPersist"].attributes["status"] == "FAILED"
    assert by_name["Checkpoint"].attributes["status"] == "FAILED"
    assert "disk full" in by_name["Checkpoint"].attributes["failureCause"]


def test_coordinator_failed_capture_records_failed_and_reraises():
    stats = CheckpointStatsTracker()
    coord, rep = _coordinator(MemoryCheckpointStorage(), stats)

    def bad_capture():
        raise RuntimeError("capture raced a teardown")

    with pytest.raises(RuntimeError, match="teardown"):
        coord.trigger(bad_capture)
    assert stats.checkpoint(1)["status"] == "FAILED"
    by_name = {s.name: s for s in rep.spans}
    assert set(by_name) == {"CheckpointCapture", "Checkpoint"}
    assert by_name["CheckpointCapture"].attributes["status"] == "FAILED"


# ---------------------------------------------------------------------------
# JM aggregate: watermark skew
# ---------------------------------------------------------------------------

def test_aggregate_shard_metrics_watermark_skew():
    from flink_tpu.runtime.cluster import aggregate_shard_metrics

    agg = aggregate_shard_metrics({
        0: {"job.operator.win.currentWatermark": 1000,
            "job.numRecordsInPerSecond": 10.0},
        1: {"job.operator.win.currentWatermark": 400,
            "job.numRecordsInPerSecond": 20.0},
    })
    assert agg["job.operator.win.currentWatermark"] == 400   # MIN rule intact
    assert agg["job.watermarkSkewMs"] == 600
    assert agg["job.numRecordsInPerSecond"] == 30.0
    # single shard: skew is zero, not absent (the gauge stays scrapeable)
    agg1 = aggregate_shard_metrics(
        {0: {"job.operator.win.currentWatermark": 1000}})
    assert agg1["job.watermarkSkewMs"] == 0


def test_aggregate_watermark_skew_ignores_min_watermark_sentinel():
    """A subtask that has not seen a watermark yet sits at the
    MIN_WATERMARK sentinel (-(1<<63)); differencing against it would
    export ~9.2e18 ms of garbage skew. Sentinels are excluded — skew is
    over subtasks that HAVE a watermark, 0 when fewer than two do."""
    from flink_tpu.core.time import MIN_WATERMARK
    from flink_tpu.runtime.cluster import aggregate_shard_metrics

    agg = aggregate_shard_metrics({
        0: {"job.operator.win.currentWatermark": 1000},
        1: {"job.operator.win.currentWatermark": MIN_WATERMARK},
        2: {"job.operator.win.currentWatermark": 400},
    })
    assert agg["job.watermarkSkewMs"] == 600
    # only one real watermark: no pair to difference -> 0, never the sentinel
    agg2 = aggregate_shard_metrics({
        0: {"job.operator.win.currentWatermark": 1000},
        1: {"job.operator.win.currentWatermark": MIN_WATERMARK},
    })
    assert agg2["job.watermarkSkewMs"] == 0


def test_jm_persist_failure_marks_checkpoint_failed(tmp_path):
    """Distributed parity with the coordinator's _abort: a persist that
    raises after the pending entry was popped must flip the stats record
    to FAILED itself — _fail_job's pending sweep can no longer reach it.
    The JM owns the persist, so (beyond tolerable-failed-checkpoints,
    default 0) it fails the job through the normal attributed restart
    path instead of raising into the innocent acking task's RPC."""
    from flink_tpu.runtime.cluster import JobManagerEndpoint, _JobState
    from flink_tpu.runtime.rpc import RpcService

    svc = RpcService()
    jm = JobManagerEndpoint(svc, checkpoint_dir=str(tmp_path / "chk"))
    try:
        job = _JobState("j1", "bk", 1, "spec")
        job.attempt = 1
        job.status = "RUNNING"
        jm._jobs["j1"] = job
        job.pending[5] = {}
        job.pending_target[5] = 10
        job.stats.report_pending(5)

        def boom(cid, data):
            raise OSError("disk full")

        jm._storage.save = boom
        jm.ack_checkpoint("j1", 1, 0, 5, {"x": np.arange(4)})
        rec = job.stats.checkpoint(5)
        assert rec["status"] == "FAILED" and "disk full" in rec["failure_cause"]
        assert job.stats.gauge_values()["numberOfInProgressCheckpoints"] == 0
        # beyond tolerance (0): the job took the restart path, attributed
        assert job.status == "RESTARTING"
        assert "disk full" in job.failure and "persist" in job.failure
    finally:
        jm.stop()
        svc.stop()


def test_jm_tolerable_failed_checkpoints_absorbs_brownout(tmp_path):
    """execution.checkpointing.tolerable-failed-checkpoints on the
    distributed path: consecutive persist failures within the budget are
    FAILED stats records (consecutiveFailedCheckpoints gauge climbing),
    the job stays RUNNING; a completion resets the streak; exceeding the
    budget restarts the job."""
    from flink_tpu.runtime.cluster import JobManagerEndpoint, _JobState
    from flink_tpu.runtime.rpc import RpcService

    svc = RpcService()
    jm = JobManagerEndpoint(svc, checkpoint_dir=str(tmp_path / "chk"),
                            tolerable_failed_checkpoints=2)
    try:
        job = _JobState("j1", "bk", 1, "spec")
        job.attempt = 1
        job.status = "RUNNING"
        jm._jobs["j1"] = job
        real_save = jm._storage.save
        boom_box = {"on": True}

        def flaky_save(cid, data):
            if boom_box["on"]:
                raise OSError("storage brownout")
            return real_save(cid, data)

        jm._storage.save = flaky_save
        for cid in (1, 2):
            job.pending[cid] = {}
            job.pending_target[cid] = 10
            job.stats.report_pending(cid)
            jm.ack_checkpoint("j1", 1, 0, cid, {"x": np.arange(4)})
            assert job.status == "RUNNING", cid       # tolerated
        assert job.consecutive_cp_failures == 2
        assert job.stats.gauge_values()["consecutiveFailedCheckpoints"] == 2
        # storage heals: a completion resets the streak
        boom_box["on"] = False
        job.pending[3] = {}
        job.pending_target[3] = 10
        job.stats.report_pending(3)
        jm.ack_checkpoint("j1", 1, 0, 3, {"x": np.arange(4)})
        assert job.consecutive_cp_failures == 0
        assert job.status == "RUNNING"
        # a fresh 3-failure streak exceeds tolerable=2 on the third
        boom_box["on"] = True
        for cid in (4, 5, 6):
            job.pending[cid] = {}
            job.pending_target[cid] = 10
            job.stats.report_pending(cid)
            jm.ack_checkpoint("j1", 1, 0, cid, {"x": np.arange(4)})
        assert job.status == "RESTARTING"
        assert "tolerable" in job.failure
    finally:
        jm.stop()
        svc.stop()


# ---------------------------------------------------------------------------
# e2e acceptance: induced failure + restart, served over REST + Prometheus
# ---------------------------------------------------------------------------

def test_e2e_minicluster_failure_recovery_observability(tmp_path):
    cluster = MiniCluster()
    server = RestServer(cluster).start()

    config = Configuration()
    config.set(CheckpointingOptions.INTERVAL_MS, 1)      # checkpoint per step
    config.set(CheckpointingOptions.DIRECTORY, str(tmp_path / "chk"))
    config.set(ExecutionOptions.BATCH_SIZE, 100)
    config.set(RestartOptions.INITIAL_BACKOFF_MS, 1)
    config.set(ObservabilityOptions.CHECKPOINT_HISTORY_SIZE, 6)

    state = {"failed": False}

    def maybe_fail(x):
        if not state["failed"] and x[2] >= 12_000:
            state["failed"] = True
            raise RuntimeError("injected failure")
        return x

    def gen(idx: np.ndarray) -> Batch:
        values = [(int(i % 7), 1.0, int(i * 10)) for i in idx]
        return Batch(obj_array(values), (idx * 10).astype(np.int64))

    env = StreamExecutionEnvironment(config)
    stream = env.from_source(
        DataGeneratorSource(gen, count=2000, num_splits=8),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    (stream.map(maybe_fail)
           .key_by(lambda x: x[0])
           .window(TumblingEventTimeWindows.of(1000)).count()
           .sink_to(CollectSink()))
    client = env.execute_async("cp-observability")
    cluster.jobs.setdefault(client.job_id, client)

    def get(path):
        with urllib.request.urlopen(f"{server.url}{path}", timeout=10) as r:
            return json.loads(r.read())

    try:
        assert client.wait(60) == JobStatus.FINISHED
        assert client.num_restarts == 1

        # -- /checkpoints: >=1 COMPLETED with nonzero duration and size ----
        cps = get(f"/jobs/{client.job_id}/checkpoints")
        assert cps["counts"]["completed"] >= 1
        completed = [c for c in cps["history"] if c["status"] == "COMPLETED"]
        assert completed
        c0 = completed[0]
        assert c0["end_to_end_duration_ms"] > 0
        assert c0["sync_duration_ms"] > 0 and c0["async_duration_ms"] > 0
        assert c0["state_size_bytes"] > 0
        assert cps["summary"]["state_size_bytes"]["max"] > 0
        assert len(cps["history"]) <= 6            # configured ring size
        # per-checkpoint drill-down serves the same record
        one = get(f"/jobs/{client.job_id}/checkpoints/{c0['id']}")
        assert one["id"] == c0["id"] and one["status"] == "COMPLETED"

        # -- /exceptions: the induced failure, attributed, with the chain --
        exc = get(f"/jobs/{client.job_id}/exceptions")
        assert exc["root_exception"] and "injected failure" in exc["root_exception"]
        entry = exc["entries"][0]
        assert entry["task"]                        # operator/job attribution
        assert any("injected failure" in c for c in entry["root_cause_chain"])
        assert entry["restart_number"] == 0

        # -- recovery timeline: rewound checkpoint + nonzero durations -----
        rec = exc["recoveries"][0]
        assert rec["restored_checkpoint_id"] is not None
        assert rec["restore_duration_ms"] > 0
        assert rec["downtime_ms"] > 0
        assert rec["events_replayed"] is not None

        # latest.restored mirrors the rewind for the checkpoints view
        assert cps["latest"]["restored"]["checkpoint_id"] == \
            rec["restored_checkpoint_id"]

        # -- Prometheus: the standard checkpoint gauges are scrapeable -----
        with urllib.request.urlopen(f"{server.url}/metrics", timeout=10) as r:
            prom = r.read().decode()
        for family in ("job_numberOfCompletedCheckpoints",
                       "job_numberOfFailedCheckpoints",
                       "job_lastCheckpointDuration",
                       "job_lastCheckpointSize",
                       "job_lastCheckpointRestoreTimestamp",
                       "job_numRestarts",
                       "job_lastRestartDowntimeMs"):
            assert f"# TYPE {family} gauge" in prom, family
        # values, not just families: at least one completed checkpoint and
        # the restore timestamp is a real wall clock
        line = next(l for l in prom.splitlines()
                    if l.startswith("job_numberOfCompletedCheckpoints{"))
        assert float(line.rsplit(" ", 1)[1]) >= 1
    finally:
        server.stop()


def test_checkpoints_route_without_checkpointing_is_empty_not_404():
    cluster = MiniCluster()
    server = RestServer(cluster).start()

    def gen(idx: np.ndarray) -> Batch:
        return Batch(obj_array([int(i) for i in idx]),
                     (idx * 10).astype(np.int64))

    env = StreamExecutionEnvironment(Configuration())
    env.from_source(
        DataGeneratorSource(gen, count=64),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    ).map(lambda x: x).sink_to(CollectSink())
    client = env.execute_async("no-cp")
    cluster.jobs.setdefault(client.job_id, client)
    try:
        assert client.wait(30) == JobStatus.FINISHED
        with urllib.request.urlopen(
                f"{server.url}/jobs/{client.job_id}/checkpoints",
                timeout=10) as r:
            body = json.loads(r.read())
        assert body["counts"]["completed"] == 0 and body["history"] == []
        with urllib.request.urlopen(
                f"{server.url}/jobs/{client.job_id}/exceptions",
                timeout=10) as r:
            body = json.loads(r.read())
        assert body["entries"] == [] and body["root_exception"] is None
        # unknown checkpoint id: 404 with a JSON error, not a crash
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"{server.url}/jobs/{client.job_id}/checkpoints/7", timeout=10)
        assert err.value.code == 404
    finally:
        server.stop()
