"""Fault-tolerance tests (reference test strategy §4.4/§4.5: checkpointing
ITCases + in-JVM fault injection): crash/recover exactly-once, savepoints,
cancellation, storage, restart strategies."""

import os
import threading
import time

import numpy as np
import pytest

from flink_tpu.api.datastream import StreamExecutionEnvironment
from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
from flink_tpu.checkpoint.restart import (
    ExponentialDelayRestartStrategy,
    FailureRateRestartStrategy,
    FixedDelayRestartStrategy,
    NoRestartStrategy,
)
from flink_tpu.checkpoint.storage import FsCheckpointStorage, MemoryCheckpointStorage
from flink_tpu.config import CheckpointingOptions, Configuration, ExecutionOptions, RestartOptions
from flink_tpu.connectors.sink import FileSink
from flink_tpu.connectors.source import Batch, DataGeneratorSource
from flink_tpu.core.watermarks import WatermarkStrategy
from flink_tpu.runtime.minicluster import JobStatus, MiniCluster
from flink_tpu.utils.arrays import obj_array


def _gen_source(count=2000, keys=7):
    def gen(idx: np.ndarray) -> Batch:
        values = [(int(i % keys), 1.0, int(i * 10)) for i in idx]
        return Batch(obj_array(values), (idx * 10).astype(np.int64))

    return DataGeneratorSource(gen, count=count, num_splits=8)


def _pipeline(env, fail_once_at=None, sink_dir=None):
    """keyed tumbling count over the datagen source; optional one-shot
    failure injected in a map function."""
    state = {"failed": False}

    def maybe_fail(x):
        if fail_once_at is not None and not state["failed"] and x[2] >= fail_once_at:
            state["failed"] = True
            raise RuntimeError("injected failure")
        return x

    stream = env.from_source(
        _gen_source(), watermark_strategy=WatermarkStrategy.for_monotonous_timestamps()
    )
    result = (
        stream.map(maybe_fail)
        .key_by(lambda x: x[0])
        .window(TumblingEventTimeWindows.of(1000))
        .count()
    )
    result.sink_to(FileSink(sink_dir, prefix="out"))
    return env


def _read_results(sink_dir):
    lines = []
    for name in sorted(os.listdir(sink_dir)):
        if name.startswith("."):
            continue
        with open(os.path.join(sink_dir, name)) as f:
            lines.extend(l for l in f.read().splitlines() if l)
    return sorted(lines)


def _expected_results():
    """2000 records, keys i%7, ts=i*10 → tumbling 1s windows of 100 records."""
    from collections import Counter

    c = Counter()
    for i in range(2000):
        c[(i % 7, (i * 10) // 1000)] += 1
    return sorted(f"({k}, {v})" for (k, _w), v in c.items())


def test_exactly_once_crash_recovery(tmp_path):
    sink_dir = str(tmp_path / "out")
    chk_dir = str(tmp_path / "chk")
    config = Configuration()
    config.set(CheckpointingOptions.INTERVAL_MS, 1)       # checkpoint every step
    config.set(CheckpointingOptions.DIRECTORY, chk_dir)
    config.set(ExecutionOptions.BATCH_SIZE, 100)
    config.set(RestartOptions.INITIAL_BACKOFF_MS, 1)

    env = StreamExecutionEnvironment(config)
    _pipeline(env, fail_once_at=12_000, sink_dir=sink_dir)
    client = env.execute_async("exactly-once")
    assert client.wait(60) == JobStatus.FINISHED
    assert client.num_restarts == 1
    assert _read_results(sink_dir) == _expected_results()
    # checkpoints were retained
    assert FsCheckpointStorage(chk_dir).list_checkpoints()


def test_no_restart_fails_job(tmp_path):
    config = Configuration()
    config.set(RestartOptions.STRATEGY, "none")
    env = StreamExecutionEnvironment(config)
    _pipeline(env, fail_once_at=0, sink_dir=str(tmp_path / "out"))
    client = env.execute_async()
    with pytest.raises(RuntimeError, match="failed"):
        client.wait(30)
    assert client.status() == JobStatus.FAILED


def test_cancellation(tmp_path):
    config = Configuration()
    config.set(ExecutionOptions.BATCH_SIZE, 10)

    # slow source so we can cancel mid-flight
    def slow_gen(idx: np.ndarray) -> Batch:
        time.sleep(0.01)
        values = [(int(i % 3), 1.0, int(i)) for i in idx]
        return Batch(obj_array(values), idx.astype(np.int64))

    env = StreamExecutionEnvironment(config)
    stream = env.from_source(
        DataGeneratorSource(slow_gen, count=100_000),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    stream.key_by(lambda x: x[0]).window(TumblingEventTimeWindows.of(1000)).count().sink_to(
        FileSink(str(tmp_path / "out"))
    )
    client = env.execute_async()
    time.sleep(0.2)
    client.cancel()
    assert client.wait(30) == JobStatus.CANCELED


def test_savepoint_and_resume(tmp_path):
    sp_path = str(tmp_path / "savepoint")
    sink1 = str(tmp_path / "out1")
    config = Configuration()
    config.set(ExecutionOptions.BATCH_SIZE, 50)

    def slow_gen(idx: np.ndarray) -> Batch:
        time.sleep(0.005)
        values = [(int(i % 7), 1.0, int(i * 10)) for i in idx]
        return Batch(obj_array(values), (idx * 10).astype(np.int64))

    source = DataGeneratorSource(slow_gen, count=4000, num_splits=8)

    def build(env, sink_dir):
        stream = env.from_source(
            source, watermark_strategy=WatermarkStrategy.for_monotonous_timestamps()
        )
        stream.key_by(lambda x: x[0]).window(TumblingEventTimeWindows.of(1000)).count().sink_to(
            FileSink(sink_dir, prefix="out")
        )

    env = StreamExecutionEnvironment(config)
    build(env, sink1)
    client = env.execute_async("sp-source-job")
    deadline = time.time() + 30
    while client.records_in < 1500 and time.time() < deadline:
        time.sleep(0.01)
    assert client.records_in >= 1500, "source never progressed"
    client.trigger_savepoint(sp_path)
    client.cancel()
    client.wait(30)

    # resume a NEW job from the savepoint: combined output of job1 (committed
    # epochs only... job1 canceled: nothing committed) + job2 = full results
    from flink_tpu.graph.transformation import plan

    sink2 = str(tmp_path / "out2")
    env2 = StreamExecutionEnvironment(config)
    build(env2, sink2)
    graph = plan(env2._sinks[0])
    client2 = MiniCluster.get_shared().submit(
        graph, config, "resumed", savepoint_restore_path=sp_path
    )
    assert client2.wait(60) == JobStatus.FINISHED
    # cumulative records_in restores from the savepoint and ends at the total
    assert client2.records_in == 4000
    sp_data = FsCheckpointStorage(sp_path).load(FsCheckpointStorage(sp_path).latest()[1])
    assert sp_data["savepoint"] is True
    assert 1500 <= sp_data["records_in"] < 4000
    results2 = _read_results(sink2)
    # exactly-once across the savepoint boundary: job1 committed nothing
    # (cancelled), so job2's output plus job1's (empty) committed output is
    # at most one emission per window — no duplicates, nothing lost after
    # the savepoint. (The fused window operator buffers steps per
    # superbatch, so whether any window fired *before* the savepoint —
    # making job2 emit strictly fewer than all 7*40 — depends on dispatch
    # phase; both outcomes are correct.)
    assert 0 < len(results2) <= 7 * 40


def test_storage_roundtrip(tmp_path):
    for storage in (MemoryCheckpointStorage(), FsCheckpointStorage(str(tmp_path / "c"))):
        data = {"x": np.arange(5), "y": {"nested": [1, 2, 3]}}
        storage.save(1, dict(data))
        storage.save(2, {"x": np.arange(3), "y": None})
        assert [cid for cid, _ in storage.list_checkpoints()] == [1, 2]
        cid, handle = storage.latest()
        assert cid == 2
        loaded = storage.load(handle)
        assert list(loaded["x"]) == [0, 1, 2]
        storage.discard(1)
        assert [cid for cid, _ in storage.list_checkpoints()] == [2]


def test_restart_strategies():
    assert NoRestartStrategy().next_delay_ms(1) is None

    fixed = FixedDelayRestartStrategy(3, 10)
    assert [fixed.next_delay_ms(i) for i in (1, 2, 3, 4)] == [10, 10, 10, None]

    expo = ExponentialDelayRestartStrategy(10, 100, 1000, 2.0)
    assert expo.next_delay_ms(1) == 100
    assert expo.next_delay_ms(2) == 200
    assert expo.next_delay_ms(5) == 1000  # capped

    clock = [0.0]
    rate = FailureRateRestartStrategy(2, 1000, 5, clock=lambda: clock[0])
    assert rate.next_delay_ms(1) == 5
    assert rate.next_delay_ms(2) == 5
    assert rate.next_delay_ms(3) is None  # 3 failures within the window
    clock[0] = 10.0  # window slides
    assert rate.next_delay_ms(4) == 5
