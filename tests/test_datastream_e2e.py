"""End-to-end DataStream API tests (MiniCluster-ITCase analogue, SURVEY §4.4):
source → chain → keyBy → window → aggregate → sink, on the local stepped
executor. WordCount tumbling-1s sum is BASELINE.json config 1."""

import numpy as np
import pytest

from flink_tpu.api.datastream import StreamExecutionEnvironment
from flink_tpu.api.windowing.assigners import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.connectors.sink import FileSink
from flink_tpu.connectors.source import Batch, DataGeneratorSource, FileSource
from flink_tpu.core.watermarks import WatermarkStrategy


def test_wordcount_tumbling_window():
    """BASELINE config 1: WordCount keyBy().sum() over tumbling 1s windows."""
    env = StreamExecutionEnvironment.get_execution_environment()
    sentences = [
        ("to be or not to be", 100),
        ("that is the question", 600),
        ("to be to be", 1500),
    ]
    stream = env.from_collection(
        sentences,
        timestamp_fn=lambda x: x[1],
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    counts = (
        stream.flat_map(lambda x: ((w, 1) for w in x[0].split()))
        .key_by(lambda wc: wc[0])
        .window(TumblingEventTimeWindows.of(1000))
        .sum(lambda wc: wc[1])
    )
    sink = counts.collect()
    result = env.execute("wordcount")
    assert result.records_in == 3

    # window [0,1000): to=2 be=2 or=1 not=1 that=1 is=1 the=1 question=1
    # window [1000,2000): to=2 be=2
    flat = sorted(sink.results)
    assert flat.count(("to", 2.0)) == 2
    assert flat.count(("be", 2.0)) == 2
    assert ("or", 1.0) in flat and ("question", 1.0) in flat
    assert len(flat) == 10


def test_sliding_window_device_path_used():
    env = StreamExecutionEnvironment.get_execution_environment()
    data = [(f"k{i % 3}", 1.0, i * 100) for i in range(100)]
    stream = env.from_collection(
        data,
        timestamp_fn=lambda x: x[2],
        watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(200),
    )
    sink = (
        stream.key_by(lambda x: x[0])
        .window(SlidingEventTimeWindows.of(2000, 1000))
        .aggregate("count")
        .collect()
    )
    env.execute()
    # count over all (key, window) pairs must equal 2x records (each record
    # is in 2 sliding windows)
    assert sum(n for _, n in sink.results) == 200


def test_session_window_falls_back_to_oracle():
    env = StreamExecutionEnvironment.get_execution_environment()
    data = [("u", 1.0, 0), ("u", 2.0, 400), ("u", 4.0, 2000)]
    stream = env.from_collection(
        data,
        timestamp_fn=lambda x: x[2],
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    sink = (
        stream.key_by(lambda x: x[0])
        .window(EventTimeSessionWindows.with_gap(1000))
        .sum(lambda x: x[1])
        .collect()
    )
    env.execute()
    assert sorted(sink.results) == [("u", 3.0), ("u", 4.0)]


def test_map_filter_chain():
    env = StreamExecutionEnvironment.get_execution_environment()
    stream = env.from_collection(list(range(20)), timestamp_fn=lambda x: x)
    sink = (
        stream.map(lambda x: x * 2)
        .filter(lambda x: x % 4 == 0)
        .collect()
    )
    env.execute()
    assert sink.results == [x * 2 for x in range(20) if (x * 2) % 4 == 0]


def test_rolling_keyed_reduce():
    env = StreamExecutionEnvironment.get_execution_environment()
    data = [("a", 1), ("a", 2), ("b", 10), ("a", 4), ("b", 5)]
    stream = env.from_collection(data, timestamp_fn=lambda x: 0)
    sink = (
        stream.key_by(lambda x: x[0])
        .reduce(lambda a, b: (a[0], a[1] + b[1]))
        .collect()
    )
    env.execute()
    assert sink.results == [("a", 1), ("a", 3), ("b", 10), ("a", 7), ("b", 15)]


def test_datagen_source_columnar():
    env = StreamExecutionEnvironment.get_execution_environment()

    from flink_tpu.utils.arrays import obj_array

    def gen(idx: np.ndarray) -> Batch:
        values = [(int(i % 5), 1.0) for i in idx]
        return Batch(obj_array(values), (idx * 10).astype(np.int64))

    stream = env.from_source(
        DataGeneratorSource(gen, count=1000, num_splits=4),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    sink = (
        stream.key_by(lambda x: x[0])
        .window(TumblingEventTimeWindows.of(10_000))
        .count()
        .collect()
    )
    env.execute()
    assert sum(n for _, n in sink.results) == 1000


def test_file_source_and_2pc_file_sink(tmp_path):
    src = tmp_path / "in.txt"
    src.write_text("\n".join(f"k{i % 2},{i}" for i in range(10)))
    out_dir = tmp_path / "out"

    env = StreamExecutionEnvironment.get_execution_environment()
    stream = env.from_source(
        FileSource(
            [str(src)],
            parse_fn=lambda line: (line.split(",")[0], int(line.split(",")[1])),
            timestamp_fn=lambda v: v[1] * 100,
        ),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    stream.key_by(lambda x: x[0]).window(TumblingEventTimeWindows.of(500)).count().sink_to(
        FileSink(str(out_dir), prefix="counts")
    )
    env.execute()
    parts = sorted(p.name for p in out_dir.iterdir() if not p.name.startswith("."))
    assert parts  # committed (renamed) part files exist
    content = "".join((out_dir / p).read_text() for p in parts)
    assert content.count("\n") == sum(1 for _ in content.splitlines())
    # 10 records over 500ms tumbling windows at ts = i*100: windows of 5 slots
    total = sum(
        1 for line in content.splitlines() if line
    )
    assert total == 4  # 2 keys x 2 windows


def test_late_data_dropped_end_to_end():
    env = StreamExecutionEnvironment.get_execution_environment()
    # monotonous watermarks, then a very late record
    data = [("a", 1.0, 100), ("a", 1.0, 5000), ("a", 1.0, 200)]
    stream = env.from_collection(
        data,
        timestamp_fn=lambda x: x[2],
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    sink = (
        stream.key_by(lambda x: x[0])
        .window(TumblingEventTimeWindows.of(1000))
        .sum(lambda x: x[1])
        .collect()
    )
    env.execute()
    # batching note: all three records arrive in one step batch, so the
    # watermark only advances after the full batch -> the "late" record is
    # NOT late here; end-to-end lateness is covered in operator tests.
    assert sorted(sink.results) == [("a", 1.0), ("a", 2.0)]


def test_partitioning_hints_compose_through_pipelines():
    """rebalance/broadcast/forward/shuffle/rescale/global_ are explicit
    repartitioning points (DataStream.java partitioners); locally they are
    correctness-neutral views and pipelines through them stay exact."""
    env = StreamExecutionEnvironment.get_execution_environment()
    data = [(f"k{i % 3}", 1.0, i * 100) for i in range(60)]
    s = env.from_collection(
        data, timestamp_fn=lambda x: x[2],
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    sink = (
        s.rebalance()
        .map(lambda x: x)
        .shuffle()
        .broadcast()
        .forward()
        .rescale()
        .global_()
        .key_by(lambda x: x[0])
        .window(TumblingEventTimeWindows.of(2000))
        .count()
        .collect()
    )
    env.execute()
    assert sum(n for _, n in sink.results) == 60


def test_keyed_process_processing_time_timers():
    """ProcessingTimeService tick: wall-clock timers registered by a
    KeyedProcessFunction fire from the run loop (the reference's
    registerProcessingTimeTimer path)."""
    import time as _time

    class Deferred:
        def process_element(self, v, ctx):
            ctx.timer_service.register_processing_time_timer(
                int(_time.time() * 1000) + 80)
            return []

        def on_timer(self, ts, ctx):
            return [("fired", ts)]

    # a slow source keeps the loop alive past the timer deadline
    import numpy as np
    from flink_tpu.connectors.source import Batch, DataGeneratorSource

    def gen(idx):
        _time.sleep(0.05)
        return Batch(
            np.asarray([f"k{int(i) % 2}" for i in idx], dtype=object),
            (idx * 10).astype(np.int64),
        )

    from flink_tpu.config import Configuration, ExecutionOptions

    conf = Configuration()
    conf.set(ExecutionOptions.BATCH_SIZE, 2)
    env2 = StreamExecutionEnvironment(conf)
    sink = (
        env2.from_source(
            DataGeneratorSource(gen, count=20),
            watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
        )
        .key_by(lambda v: v)
        .process(Deferred())
        .collect()
    )
    env2.execute()
    assert any(tag == "fired" for tag, _ in sink.results)


def test_operator_coordinator_event_bus():
    """OperatorCoordinator SPI (D15): the operator sends events up, the
    coordinator reacts and pushes configuration back down; coordinator state
    rides checkpoints."""
    from flink_tpu.config import Configuration, ExecutionOptions
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.coordination import OperatorCoordinator
    from flink_tpu.runtime.executor import JobRuntime

    class ThresholdCoordinator(OperatorCoordinator):
        def __init__(self):
            self.seen = 0

        def start(self, context):
            self.ctx = context

        def handle_event(self, event):
            self.seen += event
            if self.seen >= 10:
                self.ctx.send_to_operator(("raise_threshold", 100))

        def checkpoint(self):
            return {"seen": self.seen}

        def restore(self, snap):
            self.seen = snap["seen"]

    class Gated:
        def __init__(self):
            self.threshold = 0

        def create_coordinator(self):
            return ThresholdCoordinator()

        def handle_coordinator_event(self, event):
            kind, value = event
            if kind == "raise_threshold":
                self.threshold = value

        def process_element(self, v, ctx):
            self.coordinator_gateway.send_event(1)
            return [v] if v >= self.threshold else []

    conf = Configuration()
    conf.set(ExecutionOptions.BATCH_SIZE, 5)
    env = StreamExecutionEnvironment(conf)
    sink = (
        env.from_collection(list(range(30)))
        .key_by(lambda v: v % 2)
        .process(Gated())
        .collect()
    )
    rt = JobRuntime(plan(env._sinks), conf)
    assert len(rt.coordinators) == 1
    rt.run()
    # elements 0..8 pass (threshold 0); the 10th event raises the threshold
    # to 100 while element 9 is in flight, so 9 and everything after gate
    assert sink.results == list(range(9))
    snap = rt.capture()
    uid = next(iter(rt.coordinators))
    assert snap["coordinators"][uid]["seen"] == 30   # every event counted

    rt2 = JobRuntime(plan(env._sinks), conf)
    rt2.restore(snap)
    assert next(iter(rt2.coordinators.values())).seen == 30
