"""Device join subsystem end to end (ISSUE-16).

The two-input keyed join on the bucket ring (flink_tpu/joins +
runtime/device_join_operator.py) promises EXACT parity with the host
`WindowJoinRunner` oracle under every shape it claims: tumbling and
sliding windows, out-of-order input with late drops, adaptive ring
growth, degrade-to-host mid-stream (key capacity, slot overflow, ring
wrap) with the reason attributed, snapshot/restore at both modes, the
sharded mesh pipeline, and the SQL front door selecting the fused
runner. Each test diffs the device leg against a host leg of the SAME
job with `execution.join.device-enabled` off.
"""

import numpy as np
import pytest

from flink_tpu.api.datastream import StreamExecutionEnvironment
from flink_tpu.api.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.config import Configuration, ExecutionOptions, ParallelOptions
from flink_tpu.core.watermarks import WatermarkStrategy
from flink_tpu.graph.transformation import plan
from flink_tpu.joins.spec import JOIN_FALLBACK_CODES, fallback_code
from flink_tpu.runtime.device_join_operator import DeviceJoinRunner
from flink_tpu.runtime.executor import JobRuntime, WindowJoinRunner, build_runners


def _env(batch=16, device=True, **extra):
    conf = Configuration()
    conf.set(ExecutionOptions.BATCH_SIZE, batch)
    conf.set(ExecutionOptions.DEVICE_JOINS, device)
    for opt, val in extra.items():
        conf.set(getattr(ExecutionOptions, opt), val)
    return StreamExecutionEnvironment.get_execution_environment(conf)


def _stream(env, pairs, out_of_order=0):
    values = [p[0] for p in pairs]
    ts_map = {i: p[1] for i, p in enumerate(pairs)}
    wrapped = list(enumerate(values))
    strategy = (
        WatermarkStrategy.for_bounded_out_of_orderness(out_of_order)
        if out_of_order else
        WatermarkStrategy.for_monotonous_timestamps())
    s = env.from_collection(
        wrapped, timestamp_fn=lambda iv: ts_map[iv[0]],
        watermark_strategy=strategy)
    return s.map(lambda iv: iv[1], name="unwrap")


def _join_job(env, left, right, assigner, out_of_order=0):
    a = _stream(env, left, out_of_order)
    b = _stream(env, right, out_of_order)
    return (a.join(b)
            .where(lambda v: v[0]).equal_to(lambda v: v[0])
            .window(assigner)
            .apply(lambda l, r: (l[0], l[1], r[1]))
            .collect())


def _run(env, sink):
    """Execute through JobRuntime so the actual runners stay inspectable."""
    rt = JobRuntime(plan(env._sinks + env._roots), env.config)
    rt.run()
    return sorted(sink.results), rt


def _device_runner(rt):
    (r,) = [r for r in rt.runners if isinstance(r, DeviceJoinRunner)]
    return r


def _parity(left, right, assigner, out_of_order=0, **extra):
    """Same join on both legs; returns (rows, device runner)."""
    envd = _env(**extra)
    got, rtd = _run(envd, _join_job(envd, left, right, assigner, out_of_order))
    envh = _env(device=False, **extra)
    exp, rth = _run(envh, _join_job(envh, left, right, assigner, out_of_order))
    (host,) = [r for r in rth.runners if isinstance(r, WindowJoinRunner)]
    dev = _device_runner(rtd)
    assert got == exp, (len(got), len(exp))
    return got, dev, host


# ---------------------------------------------------------------------------
# parity: tumbling / sliding / out-of-order with late drops
# ---------------------------------------------------------------------------

def test_tumbling_parity_multi_key_multi_window():
    left = [((f"k{i % 5}", i), i * 137 % 4000) for i in range(200)]
    right = [((f"k{i % 5}", -i), i * 211 % 4000) for i in range(150)]
    rows, dev, _ = _parity(left, right, TumblingEventTimeWindows.of(1000))
    assert rows and dev._host is None and dev.fallback_reason is None
    assert dev.matches_emitted == len(rows)


def test_sliding_parity_overlapping_windows():
    left = [((f"k{i % 3}", i), i * 100) for i in range(60)]
    right = [((f"k{i % 3}", i + 1000), i * 100 + 7) for i in range(60)]
    rows, dev, _ = _parity(left, right, SlidingEventTimeWindows.of(2000, 500))
    assert rows and dev._host is None


def test_out_of_order_parity_counts_late_drops_like_the_host():
    """Shuffled timestamps under a bounded-out-of-orderness strategy: the
    device leg must emit the same pairs AND count the same per-(record,
    window) late drops as the host oracle."""
    rng = np.random.RandomState(7)
    ts_l = rng.permutation(80) * 100
    ts_r = rng.permutation(80) * 100 + 3
    left = [((f"k{i % 4}", i), int(ts_l[i])) for i in range(80)]
    right = [((f"k{i % 4}", -i), int(ts_r[i])) for i in range(80)]
    rows, dev, host = _parity(
        left, right, SlidingEventTimeWindows.of(1000, 500), out_of_order=300)
    assert dev.num_late_dropped == host.num_late_dropped


# ---------------------------------------------------------------------------
# adaptive geometry: grow in place, never over-allocate up front
# ---------------------------------------------------------------------------

def test_bucket_capacity_grows_past_initial_without_degrade():
    """>16 same-(key, bucket) records: the ring starts at capacity 16 and
    must DOUBLE toward execution.join.bucket-capacity, not degrade."""
    left = [(("hot", i), 100 + i % 7) for i in range(50)]
    right = [(("hot", -i), 200 + i % 5) for i in range(40)]
    rows, dev, _ = _parity(left, right, TumblingEventTimeWindows.of(1000))
    assert len(rows) == 50 * 40
    assert dev._host is None, dev.fallback_reason
    assert dev.geom.bucket_capacity > 16


def test_key_capacity_grows_past_initial_without_degrade():
    """>1024 distinct keys under a large configured cap: the key lanes
    double instead of degrading."""
    n = 1500
    left = [((i, "l"), (i % 8) * 100) for i in range(n)]
    right = [((i, "r"), (i % 8) * 100 + 1) for i in range(n)]
    rows, dev, _ = _parity(left, right, TumblingEventTimeWindows.of(1000),
                           batch=256)
    assert len(rows) == n
    assert dev._host is None
    assert dev.geom.key_capacity == 2048


# ---------------------------------------------------------------------------
# degrade-to-host: exactly-once replay + attributed reason
# ---------------------------------------------------------------------------

def test_key_capacity_degrade_keeps_parity_and_attributes():
    got, dev, _ = _parity(
        [((i, "l"), 100) for i in range(40)],
        [((i, "r"), 200) for i in range(40)],
        TumblingEventTimeWindows.of(1000),
        KEY_CAPACITY=16)
    assert len(got) == 40
    assert dev._host is not None
    assert dev.fallback_reason == "join-key-capacity"
    assert fallback_code(dev.fallback_reason) \
        == JOIN_FALLBACK_CODES["join-key-capacity"] > 0


def test_slot_overflow_at_cap_degrades_with_parity():
    """More same-(key, bucket) records than the configured cap allows:
    all-or-nothing ingest refuses the batch, the live ring replays into
    the host runner, and the failed batch replays whole — no pair lost,
    none duplicated."""
    left = [(("hot", i), 100) for i in range(30)]
    right = [(("hot", -i), 150) for i in range(10)]
    got, dev, _ = _parity(left, right, TumblingEventTimeWindows.of(1000),
                          JOIN_BUCKET_CAPACITY=8)
    assert len(got) == 300
    assert dev._host is not None
    assert dev.fallback_reason == "join-ring-overflow"


def test_ring_wrap_degrades_with_parity():
    """Event time running further ahead of the purge horizon than the ring
    holds: the wrap is detected BEFORE any mutation and the stream
    degrades, preserving every pair of both the old and the far bucket."""
    left = [(("a", 1), 100), (("a", 2), 100 + 2 * 1000)]
    right = [(("a", 10), 150), (("a", 20), 150 + 2 * 1000)]
    # slack 1 => ring of 2 buckets for a 1000ms tumble: ts 2100 wraps
    # onto the still-live bucket 0 (watermarks lag the whole collection)
    got, dev, _ = _parity(left, right, TumblingEventTimeWindows.of(1000),
                          out_of_order=4000, JOIN_RING_SLACK=1, batch=1)
    assert len(got) == 2
    assert dev._host is not None
    assert dev.fallback_reason == "join-ring-overflow"


def test_mid_stream_degrade_replays_ring_exactly_once():
    """Records resident BEFORE the degrade replay into the host runner
    with the device watermark set first: fired windows stay fired (drop
    as late on replay), unfired windows emit exactly once."""
    conf_pairs = dict(KEY_CAPACITY=8, batch=4)
    # 8 early keyed pairs in window [0, 1000) fire first; then key
    # cardinality blows past the cap in window [2000, 3000)
    left = ([((f"e{i}", i), 100 + i) for i in range(6)]
            + [((f"x{i}", i), 2100 + i) for i in range(20)])
    right = ([((f"e{i}", -i), 500 + i) for i in range(6)]
             + [((f"x{i}", -i), 2500 + i) for i in range(20)])
    got, dev, _ = _parity(left, right, TumblingEventTimeWindows.of(1000),
                          **conf_pairs)
    assert len(got) == 26
    assert dev.fallback_reason == "join-key-capacity"


# ---------------------------------------------------------------------------
# snapshot / restore
# ---------------------------------------------------------------------------

def _fresh_runner(**extra):
    env = _env(**extra)
    sink = _join_job(env, [(("k", 1), 100)], [(("k", 2), 200)],
                     TumblingEventTimeWindows.of(1000))
    graph = plan(env._sinks + env._roots)
    runners, _ = build_runners(graph, env.config)
    return _device_runner(type("rt", (), {"runners": runners})())


def test_snapshot_restore_roundtrip_device_mode():
    from flink_tpu.utils.arrays import obj_array

    r = _fresh_runner()
    r.downstream = None
    r.on_batch_n(0, obj_array([("a", i) for i in range(20)]),
                 np.arange(20, dtype=np.int64) * 50)
    r.on_batch_n(1, obj_array([("a", -i) for i in range(10)]),
                 np.arange(10, dtype=np.int64) * 50 + 5)
    snap = r.snapshot()
    assert snap["mode"] == "device"

    r2 = _fresh_runner()
    r2.downstream = None
    r2.restore(snap)
    assert r2.pipeline.occupancy() == r.pipeline.occupancy()
    assert r2._keys == r._keys
    # both fire the same pairs from the restored state
    outs = []
    for runner in (r, r2):
        captured = []
        runner.downstream = type(
            "D", (), {"on_batch": lambda self, v, t: captured.extend(v),
                      "on_watermark": lambda self, wm: None})()
        runner.on_watermark(10_000)
        outs.append(sorted(captured))
    assert outs[0] == outs[1] and len(outs[0]) == 200


def test_snapshot_restore_preserves_grown_geometry():
    from flink_tpu.utils.arrays import obj_array

    r = _fresh_runner()
    r.downstream = None
    # force bucket-capacity growth (30 same-key-bucket records > 16)
    r.on_batch_n(0, obj_array([("hot", i) for i in range(30)]),
                 np.full(30, 100, dtype=np.int64))
    assert r.geom.bucket_capacity > 16
    snap = r.snapshot()
    r2 = _fresh_runner()
    r2.restore(snap)
    assert r2.geom.bucket_capacity == r.geom.bucket_capacity
    assert r2.pipeline.occupancy() == 30


def test_snapshot_restore_host_mode_carries_fallback():
    from flink_tpu.utils.arrays import obj_array

    r = _fresh_runner(KEY_CAPACITY=4)
    r.downstream = None
    r.on_batch_n(0, obj_array([(f"k{i}", i) for i in range(10)]),
                 np.full(10, 100, dtype=np.int64))
    assert r._host is not None
    snap = r.snapshot()
    assert snap["mode"] == "host"
    r2 = _fresh_runner(KEY_CAPACITY=4)
    r2.downstream = None
    r2.restore(snap)
    assert r2._host is not None
    assert r2.fallback_reason == "join-key-capacity"
    assert r2.pipeline is None


def _flatten_snaps(obj):
    if isinstance(obj, dict):
        yield obj
        for v in obj.values():
            yield from _flatten_snaps(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            yield from _flatten_snaps(v)


def test_checkpointed_join_restores_through_job_runtime():
    """Capture mid-stream while the join is still in DEVICE mode, crash,
    restore into a fresh runtime, finish: output equals an undisturbed
    run. This is the exactly-once contract for the device ring — the
    restored pipeline must rebuild the (possibly grown) geometry before
    replaying state, and fired windows must not re-fire."""

    def build(env):
        left = [((f"k{i % 3}", i), i * 100) for i in range(60)]
        right = [((f"k{i % 3}", -i), i * 100 + 3) for i in range(60)]
        return _join_job(env, left, right,
                         SlidingEventTimeWindows.of(1000, 500))

    env1 = _env(batch=8)
    ref_sink = build(env1)
    rt1 = JobRuntime(plan(env1._sinks), env1.config)
    rt1.run()
    assert _device_runner(rt1)._host is None
    expected = sorted(ref_sink.results)
    assert expected

    env2 = _env(batch=8)
    build(env2)
    rt = JobRuntime(plan(env2._sinks), env2.config)
    captured = {}

    class _OneShotCoordinator:
        def register_on_complete(self, fn):
            pass

        def maybe_trigger(self, capture):
            if not captured and rt.records_in >= 40:
                captured["snap"] = capture()
                raise KeyboardInterrupt  # crash right after the capture

    try:
        rt.run(coordinator=_OneShotCoordinator())
    except KeyboardInterrupt:
        pass
    assert "snap" in captured
    # the capture happened while the join was still on-device
    device_snaps = [s for s in _flatten_snaps(captured["snap"])
                    if isinstance(s, dict) and s.get("mode") == "device"]
    assert device_snaps, "checkpoint did not capture a device-mode join"

    env3 = _env(batch=8)
    sink3 = build(env3)
    rt2 = JobRuntime(plan(env3._sinks), env3.config)
    rt2.restore(captured["snap"])
    rt2.run()
    assert _device_runner(rt2)._host is None
    assert sorted(sink3.results) == expected


# ---------------------------------------------------------------------------
# sharded pipeline on the virtual mesh
# ---------------------------------------------------------------------------

def test_sharded_join_parity_on_forced_mesh():
    """MESH_ENABLED on the 8-device CPU mesh: the sharded pipeline (key
    lanes sharded over devices, GSPMD exchange) must match the host leg
    exactly."""
    left = [((i % 64, i), (i // 64) * 500) for i in range(400)]
    right = [((i % 64, -i), (i // 64) * 500 + 7) for i in range(300)]

    envd = _env(batch=64)
    envd.config.set(ParallelOptions.MESH_ENABLED, True)
    got, rtd = _run(envd, _join_job(
        envd, left, right, TumblingEventTimeWindows.of(1000)))
    dev = _device_runner(rtd)
    assert dev.sharded, "mesh available but the join pipeline is unsharded"
    assert dev._host is None

    # the host leg must see the SAME watermark cadence (batch size drives
    # watermark emission, which drives late drops)
    envh = _env(batch=64, device=False)
    exp, _ = _run(envh, _join_job(
        envh, left, right, TumblingEventTimeWindows.of(1000)))
    assert got == exp and got


# ---------------------------------------------------------------------------
# SQL front door
# ---------------------------------------------------------------------------

def _sql_env(device=True):
    from flink_tpu.table.table_env import TableEnvironment, TableSchema

    conf = Configuration()
    conf.set(ExecutionOptions.BATCH_SIZE, 16)
    conf.set(ExecutionOptions.DEVICE_JOINS, device)
    env = StreamExecutionEnvironment.get_execution_environment(conf)
    tenv = TableEnvironment(env)
    orders = [{"user": f"u{i % 3}", "amount": float(i), "rowtime": i * 100}
              for i in range(10)]
    users = [{"user": f"u{i}", "city": f"city{i}", "ts": i * 100}
             for i in range(3)]
    tenv.from_rows("orders", orders, TableSchema(
        ["user", "amount", "rowtime"], rowtime="rowtime"))
    tenv.from_rows("users", users, TableSchema(
        ["user", "city", "ts"], rowtime="ts"))
    return env, tenv


_SQL_JOIN = ("SELECT a.user, b.city, a.amount FROM orders AS a "
             "JOIN users AS b ON a.user = b.user "
             "WHERE a.amount > 1 WINDOW TUMBLE(INTERVAL '10' SECOND)")


def test_sql_window_join_selects_fused_runner_with_fused_explain():
    env, tenv = _sql_env()
    report = tenv.explain_sql(_SQL_JOIN)
    assert report.fused
    assert "device=join-ring" in report.describe()
    tenv.sql_query(_SQL_JOIN).collect()
    runners, _ = build_runners(plan(env._sinks), env.config)
    djr = [r for r in runners if isinstance(r, DeviceJoinRunner)]
    assert djr and djr[0].sql_origin


def test_sql_fused_selected_gauge_counts_the_join():
    env, tenv = _sql_env()
    tenv.sql_query(_SQL_JOIN).collect()
    rt = JobRuntime(plan(env._sinks), env.config)
    gauge = rt.registry.all_metrics().get("job.sqlFusedSelected")
    assert gauge is not None and gauge.value() == 1


def test_sql_window_join_parity_device_vs_interpreted():
    env, tenv = _sql_env()
    sink = tenv.sql_query(_SQL_JOIN).collect()
    env.execute("fused")
    envh, tenvh = _sql_env(device=False)
    sinkh = tenvh.sql_query(_SQL_JOIN).collect()
    envh.execute("host")

    def norm(rows):
        return sorted(tuple(sorted(r.items())) for r in rows)

    assert norm(sink.results) == norm(sinkh.results) and sink.results


def test_sql_full_outer_join_attributed_not_crashed():
    from flink_tpu.joins.spec import JoinUnsupported

    _env_, tenv = _sql_env()
    sql = ("SELECT a.user, b.city FROM orders AS a FULL OUTER JOIN users "
           "AS b ON a.user = b.user")
    report = tenv.explain_sql(sql)
    assert report.path == "interpreted"
    assert report.reason == "join-full-outer"
    with pytest.raises(JoinUnsupported) as ei:
        tenv.sql_query(sql)
    assert ei.value.reason == "join-full-outer"


def test_sql_regular_join_attributed_unwindowed():
    _env_, tenv = _sql_env()
    sql = ("SELECT a.user, b.city FROM orders AS a JOIN users AS b "
           "ON a.user = b.user")
    report = tenv.explain_sql(sql)
    assert report.path == "interpreted"
    assert report.reason == "join-unwindowed"
    # and it still executes (fallback is attributed, never a failure)
    rows = tenv.execute_sql_to_list(sql)
    assert len(rows) == 10


# ---------------------------------------------------------------------------
# cluster fold + device payload filters (the _TIER_GAUGES lesson)
# ---------------------------------------------------------------------------

def test_join_gauges_fold_and_survive_the_device_payload_filters():
    from flink_tpu.runtime.cluster import (
        _JOIN_GAUGES,
        _shard_combine,
        aggregate_shard_metrics,
    )

    assert _shard_combine("job.join.joinFallbackReason") == "max"
    assert _shard_combine("job.join.joinRingOccupancy") == "sum"
    assert _shard_combine("job.join.joinMatchesEmitted") == "sum"
    agg = aggregate_shard_metrics({
        0: {"job.join.joinRingOccupancy": 10,
            "job.join.joinMatchesEmitted": 100,
            "job.join.joinFallbackReason": 0},
        1: {"job.join.joinRingOccupancy": 6,
            "job.join.joinMatchesEmitted": 40,
            "job.join.joinFallbackReason": 7},
    })
    assert agg["job.join.joinRingOccupancy"] == 16
    assert agg["job.join.joinMatchesEmitted"] == 140
    assert agg["job.join.joinFallbackReason"] == 7   # worst shard, not 7+0

    # regression for the _TIER_GAUGES omission: the family must pass BOTH
    # device payload filters, or the job-level view silently drops it.
    # Both filters now consult ONE derived leaf set behind the shared
    # _is_device_payload_key predicate, so the invariant is structural:
    # the family sits in the set, and both JobManagerEndpoint payload
    # sites go through the predicate.
    import inspect

    from flink_tpu.runtime import cluster as cluster_mod
    from flink_tpu.runtime.cluster import (_DEVICE_PAYLOAD_LEAVES,
                                           _is_device_payload_key)

    for name in ("joinRingOccupancy", "joinMatchesEmitted",
                 "joinFallbackReason"):
        assert name in _JOIN_GAUGES
        assert name in _DEVICE_PAYLOAD_LEAVES, (
            "join gauges missing from the shared device payload leaf set")
        assert _is_device_payload_key(f"job.join.{name}")
    src = inspect.getsource(cluster_mod.JobManagerEndpoint)
    assert src.count("_is_device_payload_key") >= 2, (
        "a /jobs/:id/device payload filter stopped consulting the shared "
        "device-payload predicate")


def test_runner_registers_the_join_gauge_family():
    env = _env()
    sink = _join_job(env, [(("k", 1), 100)], [(("k", 2), 200)],
                     TumblingEventTimeWindows.of(1000))
    rt = JobRuntime(plan(env._sinks + env._roots), env.config)
    rt.run()
    metrics = rt.registry.all_metrics()
    keys = [k for k in metrics
            if k.rsplit(".", 1)[-1] in ("joinRingOccupancy",
                                        "joinMatchesEmitted",
                                        "joinFallbackReason")]
    assert len(keys) == 3, sorted(metrics)
    by_leaf = {k.rsplit(".", 1)[-1]: metrics[k].value() for k in keys}
    assert by_leaf["joinMatchesEmitted"] == len(sink.results) == 1
    assert by_leaf["joinFallbackReason"] == 0
