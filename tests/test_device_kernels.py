"""Alternate device kernels: sort-based ingest equivalence (skew-robust
kernel) + device global-window count triggers vs oracle parity."""

import numpy as np
import pytest

from flink_tpu.api.windowing.assigners import GlobalWindows, TumblingEventTimeWindows, SlidingEventTimeWindows
from flink_tpu.api.windowing.triggers import CountTrigger, PurgingTrigger
from flink_tpu.ops.aggregators import BUILTINS
from flink_tpu.runtime.oracle_window_operator import OracleWindowOperator
from flink_tpu.runtime.tpu_global_window_operator import (
    TpuGlobalWindowOperator,
    supported_trigger,
)
from flink_tpu.runtime.tpu_window_operator import TpuWindowOperator
from flink_tpu.testing.harness import KeyedWindowOperatorHarness
from flink_tpu.utils.arrays import obj_array


@pytest.mark.parametrize("agg", ["sum", "count", "min", "max", "mean"])
def test_sorted_ingest_matches_scatter(agg):
    rng = np.random.default_rng(5)
    # heavy skew: most records on one key (the scatter worst case)
    keys = np.where(rng.random(600) < 0.7, 0, rng.integers(0, 20, 600)).astype(np.int64)
    vals = rng.integers(1, 100, 600).astype(np.float32)
    ts = rng.integers(0, 10_000, 600).astype(np.int64)

    def run(kernel):
        op = TpuWindowOperator(
            SlidingEventTimeWindows.of(3000, 1000),
            agg,
            num_slices=64,
            dense_int_keys=True,
            ingest_kernel=kernel,
        )
        op.process_batch(keys, vals, ts)
        op.process_watermark(50_000)
        return sorted(
            (k, w, round(float(r), 4), t) for k, w, r, t in op.drain_output()
        )

    assert run("scatter") == run("sort")


def test_sorted_ingest_multi_batch_with_lateness():
    rng = np.random.default_rng(9)

    def run(kernel):
        op = TpuWindowOperator(
            TumblingEventTimeWindows.of(1000),
            "sum",
            num_slices=64,
            dense_int_keys=True,
            allowed_lateness=500,
            ingest_kernel=kernel,
        )
        wm = 0
        for b in range(6):
            keys = rng.integers(0, 8, 100).astype(np.int64)
            ts = rng.integers(max(0, b * 800 - 400), (b + 1) * 800, 100).astype(np.int64)
            vals = np.ones(100, dtype=np.float32)
            op.process_batch(keys, vals, ts)
            wm = b * 800
            op.process_watermark(wm)
        op.process_watermark(10**6)
        return sorted((k, w, float(r)) for k, w, r, _ in op.drain_output())

    rng = np.random.default_rng(9)
    a = run("scatter")
    rng = np.random.default_rng(9)
    b = run("sort")
    assert a == b


def test_supported_trigger_detection():
    assert supported_trigger(CountTrigger.of(5)) == (5, False)
    assert supported_trigger(PurgingTrigger.of(CountTrigger.of(3))) == (3, True)
    assert supported_trigger(None) is None


def test_global_count_purging_parity_per_record():
    """Per-record batches: device global-window operator matches the oracle
    exactly (fires every N with purge)."""
    device = TpuGlobalWindowOperator("sum", count_n=3, purging=True, key_capacity=16)
    oracle = OracleWindowOperator(
        GlobalWindows.create(),
        BUILTINS["sum"]().python_equivalent(),
        trigger=PurgingTrigger.of(CountTrigger.of(3)),
    )
    rng = np.random.default_rng(2)
    for i in range(60):
        key = f"k{rng.integers(0, 4)}"
        val = float(rng.integers(1, 10))
        device.process_record(key, val, i)
        device.flush()
        oracle.process_record(key, val, i)
    d = [(k, round(float(r), 3)) for k, _w, r, _t in device.drain_output()]
    o = [(k, round(float(r), 3)) for k, _w, r, _t in oracle.drain_output()]
    assert d == o


def test_global_count_nonpurging_accumulates():
    device = TpuGlobalWindowOperator("max", count_n=2, purging=False, key_capacity=8)
    for i, v in enumerate([5.0, 1.0, 9.0, 2.0]):
        device.process_record("k", v, i)
        device.flush()
    out = [r for _, _, r, _ in device.drain_output()]
    # fires at counts 2 and 4 with the running max (no purge)
    assert out == [5.0, 9.0]


def test_global_count_snapshot_restore():
    op = TpuGlobalWindowOperator("sum", count_n=4, purging=True, key_capacity=8)
    op.process_record("a", 1.0, 0)
    op.process_record("a", 2.0, 1)
    op.flush()
    snap = op.snapshot()
    op2 = TpuGlobalWindowOperator("sum", count_n=4, purging=True, key_capacity=8)
    op2.restore(snap)
    op2.process_record("a", 3.0, 2)
    op2.process_record("a", 4.0, 3)
    op2.flush()
    out = op2.drain_output()
    assert len(out) == 1 and out[0][2] == 10.0


def test_global_count_end_to_end_device():
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.config import Configuration, ExecutionOptions
    from flink_tpu.core.watermarks import WatermarkStrategy

    config = Configuration()
    # batch boundaries aligned with count-trigger crossings: exact parity
    # (intra-batch crossings coalesce by design — see operator docstring)
    config.set(ExecutionOptions.BATCH_SIZE, 5)
    env = StreamExecutionEnvironment(config)
    data = [(f"k{i % 2}", 1.0, i) for i in range(20)]
    stream = env.from_collection(
        data,
        timestamp_fn=lambda x: x[2],
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    ws = stream.key_by(lambda x: x[0]).window(GlobalWindows.create())
    ws = ws.trigger(PurgingTrigger.of(CountTrigger.of(5)))
    sink = ws.count().collect()
    env.execute()
    # 10 records/key -> two fires of 5 per key
    assert sorted(sink.results) == [("k0", 5), ("k0", 5), ("k1", 5), ("k1", 5)]
