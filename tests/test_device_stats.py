"""Device-plane observability (ISSUE-8): compile/recompile tracking with
cause attribution, per-kernel cost & roofline capture, superscan phase
counters, key-skew telemetry, and the /jobs/:id/device exposure.

Layers:

1. **CompileTracker units** — compile detection off the jit executable
   cache, recompile cause attribution by signature diff (ring doubling /
   batch geometry / dtype change), ring bounding, storm gauge, cost
   capture, payload/merge shapes.
2. **Key-stats units** — the device fold against a numpy oracle: per-group
   histogram, top-K hot keys, skew coefficient, readiness gating.
3. **Operator integration** — phase counters match the stream's ground
   truth; device stats change NO results (parity on vs off); ring
   doubling recompiles with the right cause.
4. **End-to-end** — a MiniCluster job serves /jobs/:id/device with a
   nonzero compile count, an induced geometry-churn recompile in the
   event ring, roofline/phase/key blocks, compile spans on the trace
   registry, and the profiler capture surface (satellite: the per-attempt
   jax.profiler capture used to be write-only).
"""

import json
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from flink_tpu.core.time import MAX_WATERMARK
from flink_tpu.metrics.device_stats import (
    CompileTracker,
    attribute_cause,
    compile_event_span,
    empty_device_payload,
    merge_compile_payloads,
    platform_peaks,
    roofline_pct,
)
from flink_tpu.metrics.key_stats import KeyStatsCollector


# ---------------------------------------------------------------------------
# 1. CompileTracker units
# ---------------------------------------------------------------------------

def _jit_add(scale):
    import jax

    return jax.jit(lambda x: x * scale + 1)


def test_tracker_counts_compiles_and_dispatches():
    t = CompileTracker()
    fn = _jit_add(2)
    x = jnp.ones((8,))
    t.call("p", fn, (x,), {"T": 1, "B": 8})
    t.call("p", fn, (x,), {"T": 1, "B": 8})
    t.call("p", fn, (x,), {"T": 1, "B": 8})
    assert t.num_compiles == 1
    assert t.num_recompiles == 0
    assert t.dispatches_total() == 3
    assert t.compile_ms_total > 0
    p = t.payload()
    assert p["programs"]["p"]["compiles"] == 1
    assert p["programs"]["p"]["dispatches"] == 3
    assert p["events"][0]["cause"] == "initial"


def test_tracker_detects_recompile_with_batch_geometry_cause():
    t = CompileTracker()
    fn = _jit_add(3)
    t.call("p", fn, (jnp.ones((8,)),), {"T": 1, "B": 8})
    t.call("p", fn, (jnp.ones((16,)),), {"T": 1, "B": 16})
    assert t.num_compiles == 2
    assert t.num_recompiles == 1
    ev = t.events()[-1]
    assert ev["recompile"] is True
    assert ev["cause"] == "batch-geometry"
    assert "B=16" in ev["signature"]


def test_cause_attribution_priorities():
    assert attribute_cause(None, {"K": 1}) == "initial"
    assert attribute_cause({"K": 1, "T": 2}, {"K": 2, "T": 2}) == "ring-doubling"
    assert attribute_cause({"K": 1, "T": 2}, {"K": 1, "T": 4}) == "batch-geometry"
    assert attribute_cause({"K": 1, "B": 2}, {"K": 1, "B": 4}) == "batch-geometry"
    # dtype outranks everything: it is a program-semantics change
    assert attribute_cause(
        {"K": 1, "dtype": "f32"}, {"K": 2, "dtype": "f64"}) == "dtype-change"
    assert attribute_cause({"K": 1}, {"K": 1}) == "cache-eviction"
    assert attribute_cause({"S": 8, "K": 1}, {"S": 16, "K": 1}) == "other:S"


def test_tracker_cost_capture_feeds_roofline():
    t = CompileTracker()
    fn = _jit_add(5)
    x = jnp.ones((128,))
    t.call("p", fn, (x,), {"B": 128})
    assert t.bytes_accessed_total() > 0
    assert t.flops_total() > 0
    ev = t.events()[0]
    assert ev["cost"]["bytes_accessed"] > 0
    # non-compiling dispatches keep accumulating the cached per-signature
    # cost — the roofline numerator grows with every dispatch
    before = t.bytes_accessed_total()
    t.call("p", fn, (x,), {"B": 128})
    assert t.bytes_accessed_total() == pytest.approx(2 * before)


def test_tracker_event_ring_is_bounded_and_storm_gauge_trips():
    clock = [0.0]
    t = CompileTracker(history_size=4, storm_threshold=3,
                       storm_window_ms=10_000, cost_analysis=False,
                       clock=lambda: clock[0])
    for i in range(8):
        t.call("p", _jit_add(100 + i), (jnp.ones((4,)),), {"B": 4, "v": i})
    assert len(t.events()) == 4
    assert t.num_compiles == 8
    assert t.num_recompiles == 7
    assert t.recompile_storm() == 1
    clock[0] += 100.0   # storm window slides past
    assert t.recompile_storm() == 0


def test_warm_cache_job_still_captures_cost_for_roofline():
    """A second job whose program geometry is already warm in the
    process-wide jit caches observes NO compile (truthful — it paid
    none), but the roofline must still get the per-dispatch cost: a
    warm-cache job reading 0% utilization forever would be a lie."""
    fn = _jit_add(42)
    x = jnp.ones((32,))
    warm = CompileTracker()
    warm.call("p", fn, (x,), {"B": 32})     # pays the compile
    fresh = CompileTracker()                 # same fn, already compiled
    fresh.call("p", fn, (x,), {"B": 32})
    fresh.call("p", fn, (x,), {"B": 32})
    assert fresh.num_compiles == 0           # no compile event: none happened
    assert fresh.events() == []
    assert fresh.bytes_accessed_total() > 0  # ...but the cost is captured
    assert fresh.bytes_accessed_total() == pytest.approx(
        warm.bytes_accessed_total() * 2)     # accumulated per dispatch


def test_tracker_falls_back_to_signature_tracking():
    calls = []

    def plain_fn(x):   # no _cache_size, no lower: a non-jit callable
        calls.append(1)
        return x

    t = CompileTracker()
    t.call("p", plain_fn, (1,), {"B": 1})
    t.call("p", plain_fn, (1,), {"B": 1})
    t.call("p", plain_fn, (2,), {"B": 2})
    assert len(calls) == 3
    assert t.num_compiles == 2          # one per distinct signature
    assert t.num_recompiles == 1


def test_memory_analysis_capture_when_enabled():
    t = CompileTracker(memory_analysis=True)
    fn = _jit_add(7)
    t.call("p", fn, (jnp.ones((64,)),), {"B": 64})
    cost = t.events()[0]["cost"]
    assert "output_bytes" in cost and cost["output_bytes"] > 0


def test_merge_compile_payloads_and_empty_payload_shape():
    t1, t2 = CompileTracker(), CompileTracker()
    t1.call("a", _jit_add(11), (jnp.ones((4,)),), {"B": 4})
    t2.call("b", _jit_add(12), (jnp.ones((4,)),), {"B": 4})
    merged = merge_compile_payloads([t1.payload(), t2.payload()])
    assert merged["numCompiles"] == 2
    assert set(merged["programs"]) == {"a", "b"}
    assert len(merged["events"]) == 2
    empty = empty_device_payload()
    assert empty["enabled"] is False
    assert empty["compile"]["numCompiles"] == 0
    assert empty["profiler"] == {"enabled": False, "captures": 0,
                                 "last_capture_dir": None}


def test_roofline_pct_math_and_platform_peaks():
    r = roofline_pct(bytes_accessed=50e9, flops=137.5e12,
                     device_time_s=1.0, hbm_gbps=100.0, peak_tflops=275.0)
    assert r["hbmUtilizationPct"] == pytest.approx(50.0)
    assert r["flopsUtilizationPct"] == pytest.approx(50.0)
    assert roofline_pct(1e9, 1e9, 0.0, 100.0, 1.0) == {
        "hbmUtilizationPct": 0.0, "flopsUtilizationPct": 0.0}
    # configured values win; zeros fall back to the platform table
    assert platform_peaks(123.0, 4.5) == (123.0, 4.5)
    hbm, tf = platform_peaks(0.0, 0.0)
    assert hbm > 0 and tf > 0


def test_compile_event_span_attribute_mapping():
    t = CompileTracker()
    t.call("prog", _jit_add(13), (jnp.ones((8,)),), {"T": 2, "B": 8})
    span = compile_event_span(t.events()[0])
    assert span.scope == "device" and span.name == "XlaCompile"
    assert span.attributes["program"] == "prog"
    assert span.attributes["cause"] == "initial"
    assert span.attributes["recompile"] is False
    assert span.attributes["compileCount"] == 1
    assert span.attributes["costBytesAccessed"] > 0
    # end - (end - dur) at epoch-ms magnitude loses ~1e-4 ms to float
    # cancellation; the span is for humans, not for timing arithmetic
    assert span.duration_ms == pytest.approx(
        t.events()[0]["duration_ms"], abs=1e-2)


# ---------------------------------------------------------------------------
# 2. key-stats units
# ---------------------------------------------------------------------------

def test_key_stats_fold_matches_numpy_oracle():
    K, G = 64, 8
    loads_np = np.zeros(K, np.int32)
    loads_np[3] = 100      # hot key in group 0
    loads_np[17] = 40      # group 2
    loads_np[40] = 20      # group 5
    loads = jnp.asarray(loads_np)
    c = KeyStatsCollector(lambda: loads, num_key_groups=G, top_k=3,
                          row_bytes_fn=lambda: 128, interval_ms=0)
    assert c.collect()
    p = c.payload()
    gids = (np.arange(K, dtype=np.int64) * G) // K
    per_group = np.bincount(gids, weights=loads_np, minlength=G)
    assert p["totalRecordsResident"] == 160
    assert p["maxKeyLoad"] == 100
    assert p["activeKeys"] == 3
    assert p["hotKeys"] == [[3, 100], [17, 40], [40, 20]]
    # skew = max group load / mean group load
    assert p["keySkew"] == pytest.approx(
        per_group.max() / per_group.mean(), rel=1e-4)
    assert p["keyGroupLoad"]["max"] == per_group.max()
    assert p["keyGroupLoad"]["count"] == G
    # state bytes histogram: active keys per group x row bytes
    assert p["keyGroupStateBytes"]["max"] == 128.0


def test_key_stats_skew_even_vs_hot():
    K, G = 128, 16
    even = KeyStatsCollector(lambda: jnp.ones((K,), jnp.int32),
                             num_key_groups=G, interval_ms=0)
    even.collect()
    assert even.payload()["keySkew"] == pytest.approx(1.0)
    hot_np = np.zeros(K, np.int32)
    hot_np[0] = 1000
    hot = KeyStatsCollector(lambda: jnp.asarray(hot_np),
                            num_key_groups=G, interval_ms=0)
    hot.collect()
    assert hot.payload()["keySkew"] == pytest.approx(G)   # one group owns all


def test_key_stats_empty_state_reads_none_not_zero():
    c = KeyStatsCollector(lambda: jnp.zeros((16,), jnp.int32),
                          num_key_groups=4, interval_ms=0)
    c.collect()
    assert c.skew() is None          # absent measurement, never "0 skew"
    assert c.payload()["keySkew"] is None


def test_key_stats_ready_gate_defers_interval():
    ready = [False]
    folds = []

    def loads():
        folds.append(1)
        return jnp.ones((8,), jnp.int32)

    clock = [0.0]
    c = KeyStatsCollector(loads, num_key_groups=4, interval_ms=1000,
                          ready_fn=lambda: ready[0],
                          clock=lambda: clock[0])
    assert not c.maybe_collect()     # not ready: no fold, interval intact
    assert not folds
    ready[0] = True
    assert c.maybe_collect()         # first ready tick folds immediately
    assert len(folds) == 1
    clock[0] += 0.5
    assert not c.maybe_collect()     # throttled
    clock[0] += 0.6
    assert c.maybe_collect()
    assert len(folds) == 2


# ---------------------------------------------------------------------------
# 3. operator integration
# ---------------------------------------------------------------------------

def _fused_count_op(key_capacity=64, superbatch_steps=4, prologue=None):
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.runtime.fused_window_operator import FusedWindowOperator

    return FusedWindowOperator(
        TumblingEventTimeWindows.of(1000), "count",
        key_capacity=key_capacity, superbatch_steps=superbatch_steps,
        chunk=256, prologue=prologue,
    )


def _drive(op, steps=8, n=256, keys_hi=16):
    rng = np.random.default_rng(3)
    for s in range(steps):
        keys = rng.integers(0, keys_hi, n)
        op.process_batch(keys, np.ones(n, np.float32),
                         np.full(n, s * 300, np.int64))
        op.process_watermark(s * 300)
    op.process_watermark(MAX_WATERMARK)
    return op.drain_output()


def test_phase_counters_match_stream_ground_truth():
    op = _fused_count_op()
    op.attach_device_stats(CompileTracker())
    _drive(op, steps=8, n=256)
    phases = op.phase_totals()
    # every on-time record ingests exactly once
    assert phases["ingestRecords"] == 8 * 256
    # tumbling 1000ms over 8 steps of 300ms: windows 0..2400 fire
    assert phases["fireSteps"] >= 2
    assert phases["purgeSteps"] >= 1


def test_device_stats_do_not_change_results():
    plain = _fused_count_op()
    out_plain = _drive(plain)
    tracked = _fused_count_op()
    tracked.attach_device_stats(CompileTracker())
    out_tracked = _drive(tracked)
    assert sorted((k, w.start, r) for k, w, r, _t in out_plain) == \
        sorted((k, w.start, r) for k, w, r, _t in out_tracked)


def test_ring_doubling_recompile_cause_on_key_growth():
    op = _fused_count_op(key_capacity=32, superbatch_steps=2)
    t = CompileTracker()
    op.attach_device_stats(t)
    rng = np.random.default_rng(5)
    for s in range(10):
        hi = 24 if s < 5 else 120   # dispatch small first, then outgrow K
        keys = rng.integers(0, hi, 128)
        op.process_batch(keys, np.ones(128, np.float32),
                         np.full(128, s * 300, np.int64))
        op.process_watermark(s * 300)
    op.process_watermark(MAX_WATERMARK)
    causes = {e["cause"] for e in t.events() if e["recompile"]}
    assert "ring-doubling" in causes
    assert t.num_recompiles >= 1


def test_fused_operator_key_loads_and_ready_probe():
    op = _fused_count_op(superbatch_steps=2)
    assert op.key_stats_ready() is False
    _drive(op, steps=4, n=64, keys_hi=8)
    # after MAX watermark everything purged; drive again mid-stream
    op2 = _fused_count_op(superbatch_steps=2)
    rng = np.random.default_rng(1)
    for s in range(4):
        op2.process_batch(rng.integers(0, 8, 64),
                          np.ones(64, np.float32),
                          np.full(64, s * 300, np.int64))
        op2.process_watermark(s * 300)
    assert op2.key_stats_ready() is True
    loads = np.asarray(op2.key_loads())
    assert loads.shape[0] == op2.pipe.K
    assert loads.sum() > 0
    assert op2.state_row_bytes() > 0


def test_tpu_window_operator_key_loads():
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.runtime.tpu_window_operator import TpuWindowOperator

    op = TpuWindowOperator(TumblingEventTimeWindows.of(1000), "sum",
                           key_capacity=32)
    assert op.key_stats_ready() is False
    op.process_batch(np.arange(8), np.ones(8, np.float32),
                     np.full(8, 100, np.int64))
    assert op.key_stats_ready() is True
    assert int(np.asarray(op.key_loads()).sum()) == 8
    assert op.state_row_bytes() > 0


# ---------------------------------------------------------------------------
# 4. end to end: MiniCluster -> REST /jobs/:id/device
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def device_job():
    """Two jobs on one cluster: a fused-chain job sized to dispatch
    mid-stream (key stats see resident state, phases count the traced
    filter's survivors) and a classic fused job whose key dictionary
    outgrows the initial ring capacity mid-stream — a REAL induced
    geometry-churn recompile for the event ring to attribute."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.config import (
        Configuration,
        ExecutionOptions,
        ObservabilityOptions,
    )
    from flink_tpu.connectors.sink import CollectSink
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.runtime.minicluster import MiniCluster
    from flink_tpu.runtime.rest import RestServer
    from flink_tpu.utils.arrays import obj_array

    # compile observability is detected through the jitted callables'
    # cache growth, and superscan executables are cached MODULE-LEVEL by
    # geometry — any earlier test whose wall-clock-dependent dispatch
    # pattern (checkpoint flushes truncate superbatches at arbitrary T)
    # happens to hit this fixture's geometry would pre-compile it and
    # silently hide the compile/recompile events asserted below. Start
    # from a clean executable cache so the events are THIS fixture's own.
    from flink_tpu.runtime import fused_window_pipeline as _fwp

    _fwp._build_superscan.cache_clear()
    _fwp._CHAINED_CACHE.clear()

    def gen(idx):
        col = np.stack([(idx * 31) % 23, idx % 3], axis=1).astype(np.float32)
        return Batch(col, (idx * 5).astype(np.int64))

    cfg = Configuration()
    cfg.set(ExecutionOptions.BATCH_SIZE, 128)
    cfg.set(ExecutionOptions.KEY_CAPACITY, 23)
    cfg.set(ExecutionOptions.SUPERBATCH_STEPS, 4)
    cfg.set(ObservabilityOptions.DEVICE_KEY_STATS_INTERVAL_MS, 0)
    env = StreamExecutionEnvironment(cfg)
    ds = env.from_source(
        DataGeneratorSource(gen, count=3264),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    (ds.filter(lambda c: c[:, 1] < 1.5, traceable=True)
       .key_by(lambda c: c[:, 0].astype(jnp.int32), traceable=True)
       .window(TumblingEventTimeWindows.of(1000)).count()
       .sink_to(CollectSink()))
    client = env.execute_async("device-plane-e2e")
    assert client.wait(180).value == "FINISHED"

    # classic fused path with a growing key dictionary: dense ring starts
    # at min(1024, capacity) and doubles when the dictionary outgrows it,
    # recompiling the superscan with cause 'ring-doubling'
    def gen_grow(idx):
        # first ~10 batches stay under 1024 distinct keys (dispatches run
        # at the initial capacity), later ones outgrow it
        lo = idx % np.where(idx < 1280, 700, 1500)
        return Batch(obj_array([(int(k), 1.0) for k in lo]),
                     (idx * 5).astype(np.int64))

    cfg2 = Configuration()
    cfg2.set(ExecutionOptions.BATCH_SIZE, 128)
    cfg2.set(ExecutionOptions.KEY_CAPACITY, 2048)
    cfg2.set(ExecutionOptions.SUPERBATCH_STEPS, 4)
    env2 = StreamExecutionEnvironment(cfg2)
    (env2.from_source(
        DataGeneratorSource(gen_grow, count=3000),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps())
        .key_by(lambda r: r[0])
        .window(TumblingEventTimeWindows.of(1000)).count()
        .sink_to(CollectSink()))
    grow_client = env2.execute_async("device-plane-ring-doubling")
    assert grow_client.wait(180).value == "FINISHED"

    cluster = MiniCluster.get_shared()
    cluster.jobs.setdefault(client.job_id, client)
    cluster.jobs.setdefault(grow_client.job_id, grow_client)
    server = RestServer(cluster).start()
    yield server, client, grow_client
    server.stop()


def _get(server, path):
    with urllib.request.urlopen(f"{server.url}{path}", timeout=10) as r:
        return json.loads(r.read())


def test_rest_device_payload_has_compile_and_recompile_ring(device_job):
    server, client, grow = device_job
    body = _get(server, f"/jobs/{client.job_id}/device")
    assert body["enabled"] is True
    comp = body["compile"]
    assert comp["numCompiles"] >= 1
    assert all(e["duration_ms"] > 0 for e in comp["events"])
    # induced geometry churn: the classic job's key dictionary outgrew the
    # initial dense ring mid-stream — the recompile appears in the event
    # ring with its cause attributed
    grow_body = _get(server, f"/jobs/{grow.job_id}/device")
    gcomp = grow_body["compile"]
    assert gcomp["numRecompiles"] >= 1
    recompiles = [e for e in gcomp["events"] if e["recompile"]]
    assert recompiles
    assert any(e["cause"] == "ring-doubling" for e in recompiles)


def test_rest_device_payload_operator_blocks(device_job):
    server, client, _grow = device_job
    body = _get(server, f"/jobs/{client.job_id}/device")
    ops = body["operators"]
    assert ops, "no operator entries in the device payload"
    (entry,) = [e for e in ops.values() if "compile" in e]
    assert entry["deviceDispatches"] > 0
    assert "hbmUtilizationPct" in entry and "flopsUtilizationPct" in entry
    phases = entry["phases"]
    # the chained program masks the filter INSIDE the scan: the ingest
    # counter sees exactly the records that survive it (etype < 1.5 keeps
    # 2/3 of 3264)
    assert phases["ingestRecords"] == 2176
    assert phases["fireSteps"] >= 1
    keys = entry["keys"]
    assert keys["keySkew"] is not None and keys["keySkew"] >= 1.0
    assert keys["activeKeys"] > 0
    assert keys["hotKeys"]


def test_rest_device_payload_profiler_block_default_off(device_job):
    server, client, _grow = device_job
    body = _get(server, f"/jobs/{client.job_id}/device")
    assert body["profiler"] == {"enabled": False, "captures": 0,
                                "last_capture_dir": None}


def test_compile_events_ride_the_trace_registry(device_job):
    _server, client, _grow = device_job
    spans = client.otel.payload()["resourceSpans"][0]["scopeSpans"][0]["spans"]
    device_spans = [s for s in spans if s["name"] == "device.XlaCompile"]
    assert device_spans
    attrs = {a["key"]: list(a["value"].values())[0]
             for a in device_spans[0]["attributes"]}
    assert attrs["program"].startswith("fused")
    assert "cause" in attrs and "signature" in attrs
    # the job's deterministic trace id correlates compile spans with the
    # rest of the job's trace
    assert device_spans[0]["traceId"] == client.trace_id


def test_job_level_gauges_ship_in_metric_snapshot(device_job):
    _server, client, grow = device_job
    from flink_tpu.metrics.registry import metrics_snapshot

    snap = metrics_snapshot(client.metrics.all_metrics())
    assert snap["job.device.numCompiles"] >= 1
    assert "job.device.hbmUtilizationPct" in snap
    assert snap["job.keySkew"] >= 1.0
    # operator-scope families for Prometheus
    op_keys = [k for k in snap if ".numCompiles" in k and "operator" in k]
    assert op_keys
    # the induced-recompile job's counter rides the same key space
    grow_snap = metrics_snapshot(grow.metrics.all_metrics())
    assert grow_snap["job.device.numRecompiles"] >= 1


def test_signals_pick_up_device_gauges(device_job):
    _server, client, _grow = device_job
    from flink_tpu.metrics.registry import metrics_snapshot
    from flink_tpu.scheduler.signals import extract_signals

    s = extract_signals(metrics_snapshot(client.metrics.all_metrics()))
    assert s.key_skew is not None and s.key_skew >= 1.0
    assert s.device_utilization is not None


def test_device_payload_empty_for_unknown_runtime(device_job):
    server, _client, _grow = device_job
    from flink_tpu.runtime.minicluster import JobClient, MiniCluster

    stub = JobClient("stubjob", "stub")   # no _runtime attribute
    MiniCluster.get_shared().jobs["stubjob"] = stub
    try:
        body = _get(server, "/jobs/stubjob/device")
        assert body["enabled"] is False
        assert body["compile"]["numCompiles"] == 0
    finally:
        MiniCluster.get_shared().jobs.pop("stubjob", None)


# ---------------------------------------------------------------------------
# 5. OTLP/JSON export of compile-event spans (satellite c: OtlpJsonTrace-
#    Reporter coverage — attribute mapping + payload golden)
# ---------------------------------------------------------------------------

def _compile_event(**over):
    ev = {
        "program": "fused_superscan",
        "signature": "B=8192,K=8192,S=32,T=32,dtype=float32",
        "cause": "ring-doubling",
        "recompile": True,
        "compile_count": 2,
        "duration_ms": 1500.0,
        "wall_ts_ms": 1_700_000_001_500.0,
        "cost": {"flops": 2.5e9, "bytes_accessed": 4.0e9},
    }
    ev.update(over)
    return ev


def test_otlp_reporter_encodes_compile_span_attributes():
    from flink_tpu.metrics.otel import OtlpJsonTraceReporter

    rep = OtlpJsonTraceReporter(service_name="flink-tpu-test")
    rep.report_span(compile_event_span(_compile_event()))
    payload = rep.payload()
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert len(spans) == 1
    s = spans[0]
    assert s["name"] == "device.XlaCompile"
    # nanosecond timestamps bracket the compile wall time
    dur_ns = int(s["endTimeUnixNano"]) - int(s["startTimeUnixNano"])
    assert dur_ns == pytest.approx(1500.0 * 1e6, rel=1e-3)
    attrs = {a["key"]: a["value"] for a in s["attributes"]}
    # OTLP typed-value mapping: strings stay strings, ints encode as
    # STRING intValue (the OTLP/JSON int64 rule), bools as boolValue,
    # floats as doubleValue
    assert attrs["program"] == {"stringValue": "fused_superscan"}
    assert attrs["signature"]["stringValue"].startswith("B=8192,K=8192")
    assert attrs["cause"] == {"stringValue": "ring-doubling"}
    assert attrs["recompile"] == {"boolValue": True}
    assert attrs["compileCount"] == {"intValue": "2"}
    assert attrs["costFlops"] == {"doubleValue": 2.5e9}
    assert attrs["costBytesAccessed"] == {"doubleValue": 4.0e9}


def test_otlp_payload_golden_shape():
    from flink_tpu.metrics.otel import OtlpJsonTraceReporter

    rep = OtlpJsonTraceReporter(service_name="flink-tpu-test")
    span = compile_event_span(_compile_event(cost=None, recompile=False,
                                             compile_count=1,
                                             cause="initial"))
    span.trace_id = "ab" * 16
    rep.report_span(span)
    payload = rep.payload()
    # golden envelope: resourceSpans -> resource.attributes(service.name)
    # -> scopeSpans -> scope(name/version) -> spans
    assert list(payload) == ["resourceSpans"]
    rs = payload["resourceSpans"][0]
    assert rs["resource"]["attributes"] == [
        {"key": "service.name", "value": {"stringValue": "flink-tpu-test"}}]
    sc = rs["scopeSpans"][0]
    assert sc["scope"] == {"name": "flink_tpu", "version": "1"}
    s = sc["spans"][0]
    assert set(s) == {"traceId", "spanId", "name", "kind",
                      "startTimeUnixNano", "endTimeUnixNano", "attributes",
                      "status"}
    assert s["traceId"] == "ab" * 16          # correlation id propagates
    assert s["kind"] == 1                     # SPAN_KIND_INTERNAL
    assert json.dumps(payload)                # strictly JSON-serializable


def test_otlp_reporter_bounds_buffer_and_flushes_file(tmp_path):
    from flink_tpu.metrics.otel import OtlpJsonTraceReporter

    path = tmp_path / "spans.jsonl"
    rep = OtlpJsonTraceReporter(path=str(path), max_spans=4)
    for i in range(6):
        rep.report_span(compile_event_span(_compile_event(compile_count=i + 1)))
    spans = rep.payload()["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert len(spans) == 4                    # bounded buffer keeps newest
    counts = [
        {a["key"]: a["value"] for a in s["attributes"]}["compileCount"]
        for s in spans
    ]
    assert counts == [{"intValue": str(i)} for i in (3, 4, 5, 6)]
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 6                    # the file keeps every export
    for line in lines:
        doc = json.loads(line)
        assert doc["resourceSpans"][0]["scopeSpans"][0]["spans"]


# ---------------------------------------------------------------------------
# 6. distributed plane: shard folding + TM->JM shipping
# ---------------------------------------------------------------------------

def test_shard_combine_rules_for_device_gauges():
    """Skew/storm take the WORST shard, roofline percentages average per
    chip, compile counters sum — summing a skew ratio across shards would
    be meaningless and averaging would hide one hot shard."""
    from flink_tpu.runtime.cluster import aggregate_shard_metrics

    agg = aggregate_shard_metrics({
        0: {"job.keySkew": 1.2, "job.device.numCompiles": 2,
            "job.device.hbmUtilizationPct": 10.0,
            "job.device.recompileStorm": 0,
            "job.operator.keyed-window.hotKeyLoad": 5},
        1: {"job.keySkew": 4.0, "job.device.numCompiles": 3,
            "job.device.hbmUtilizationPct": 30.0,
            "job.device.recompileStorm": 1,
            "job.operator.keyed-window.hotKeyLoad": 9},
    })
    assert agg["job.keySkew"] == 4.0
    assert agg["job.device.numCompiles"] == 5
    assert agg["job.device.hbmUtilizationPct"] == pytest.approx(20.0)
    assert agg["job.device.recompileStorm"] == 1
    assert agg["job.operator.keyed-window.hotKeyLoad"] == 9


def test_distributed_keyed_job_ships_key_skew(tmp_path):
    """The keyed distributed path folds per-key loads on device, ships
    keySkew on the heartbeat snapshots, and the JM serves it through
    job_device — the signal the autoscaler's learning policy lacked."""
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.runtime.cluster import (
        DistributedJobSpec,
        JobManagerEndpoint,
        TaskExecutorEndpoint,
    )
    from flink_tpu.runtime.rpc import RpcService
    import time as _time

    def source_factory(shard, num_shards):
        rng = np.random.default_rng(23 + shard)
        steps = []
        for s in range(20):
            # heavy skew: ~3/4 of records on key 0
            keys = np.where(rng.random(64) < 0.75, 0,
                            rng.integers(1, 16, 64)).astype(np.int64)
            steps.append((keys, np.ones(64, np.float64),
                          (s * 1000 + rng.integers(0, 1000, 64)).astype(
                              np.int64),
                          s * 1000 + 500))
        return steps

    spec = DistributedJobSpec(
        name="keyed-device-skew", source_factory=source_factory,
        assigner=TumblingEventTimeWindows.of(60_000), aggregate="sum",
        max_parallelism=16, operator="device",
    )
    svc_jm, svc_tm = RpcService(), RpcService()
    jm = JobManagerEndpoint(svc_jm, checkpoint_dir=str(tmp_path / "chk"))
    te = TaskExecutorEndpoint(svc_tm, slots=1, shipping_interval_ms=100)
    te.connect(svc_jm.address)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    try:
        job_id = client.submit_job(spec.to_bytes(), 1)
        deadline = _time.time() + 60
        while _time.time() < deadline:
            if client.job_status(job_id)["status"] in ("FINISHED", "FAILED"):
                break
            _time.sleep(0.05)
        assert client.job_status(job_id)["status"] == "FINISHED"
        # the heartbeat keeps shipping snapshots for the finished task:
        # poll until the skew gauge lands
        body = None
        while _time.time() < deadline:
            body = client.job_device(job_id)
            if any("keySkew" in k for k in body["metrics"]):
                break
            _time.sleep(0.1)
        skews = [v for k, v in body["metrics"].items() if "keySkew" in k
                 and isinstance(v, (int, float))]
        assert skews, f"no keySkew shipped: {sorted(body['metrics'])}"
        # ~3/4 of the load on one key of 16 key-groups: strong skew
        assert max(skews) > 2.0
        assert body["enabled"] is True
    finally:
        te.stop()
        jm.heartbeats.stop()
        svc_jm.stop()
        svc_tm.stop()


def test_profiler_capture_surface(tmp_path):
    """Satellite (a): observability.profiler.enabled captures are no
    longer write-only — the device payload reports count + location."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.config import Configuration, ObservabilityOptions
    from flink_tpu.connectors.sink import CollectSink
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.utils.arrays import obj_array

    def gen(idx):
        return Batch(obj_array([int(i) for i in idx]),
                     (idx * 10).astype(np.int64))

    prof_dir = str(tmp_path / "prof")
    cfg = Configuration()
    cfg.set(ObservabilityOptions.PROFILER_ENABLED, True)
    cfg.set(ObservabilityOptions.PROFILER_DIR, prof_dir)
    env = StreamExecutionEnvironment(cfg)
    env.from_source(
        DataGeneratorSource(gen, count=64),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    ).map(lambda x: x).sink_to(CollectSink())
    client = env.execute_async("profiler-surface")
    assert client.wait(120).value == "FINISHED"
    snap = client._runtime.device_snapshot()
    assert snap["profiler"]["enabled"] is True
    assert snap["profiler"]["captures"] == 1
    assert snap["profiler"]["last_capture_dir"] == prof_dir
