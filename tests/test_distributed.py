"""Distributed runtime: RPC, heartbeats, blobs, HA, credit flow control, and
the JM+TM cluster running a keyed windowed job with checkpointed failover.

MiniCluster-ITCase style (SURVEY.md §4.4): multiple task executors with real
sockets/RPC in one process; fault injection kills a TM mid-job and recovery
must restore from the step-aligned checkpoint with exact results.
"""

import os
import threading
import time

import numpy as np
import pytest

from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
from flink_tpu.runtime.cluster import (
    DistributedJobSpec,
    JobManagerEndpoint,
    TaskExecutorEndpoint,
)
from flink_tpu.runtime.dataplane import BatchDebloater, ExchangeServer, OutputChannel
from flink_tpu.runtime.ha import FileLeaderElection, JobResultStore
from flink_tpu.runtime.heartbeat import HeartbeatManager
from flink_tpu.runtime.rpc import RemoteRpcError, RpcEndpoint, RpcService


# ---------------------------------------------------------------------------
# RPC
# ---------------------------------------------------------------------------

class _Echo(RpcEndpoint):
    def __init__(self):
        super().__init__(name="echo")
        self.count = 0

    def shout(self, text: str) -> str:
        self.validate_main_thread()
        self.count += 1
        return text.upper()

    def boom(self):
        raise ValueError("kapow")


def test_rpc_roundtrip_and_errors():
    svc = RpcService()
    ep = _Echo()
    svc.register(ep)
    gw = svc.gateway(svc.address, "echo")
    assert gw.shout("hi") == "HI"
    assert gw.shout("yo") == "YO"
    assert ep.count == 2
    with pytest.raises(RemoteRpcError, match="kapow"):
        gw.boom()
    with pytest.raises(RemoteRpcError, match="no endpoint"):
        svc.gateway(svc.address, "nope").anything()
    gw.close()
    svc.stop()


def test_rpc_concurrent_callers_single_main_thread():
    svc = RpcService()
    svc.register(_Echo())
    errs = []

    def worker():
        gw = svc.gateway(svc.address, "echo")
        try:
            for _ in range(20):
                assert gw.shout("x") == "X"
        except Exception as e:  # noqa: BLE001
            errs.append(e)
        finally:
            gw.close()

    threads = [threading.Thread(target=worker) for _ in range(4)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert not errs
    svc.stop()


# ---------------------------------------------------------------------------
# heartbeats / HA / blobs
# ---------------------------------------------------------------------------

def test_heartbeat_detects_death_once():
    # Root cause of the historical in-suite flake: this test used real
    # sleeps (interval=0.05, timeout=0.2), and in a full suite run sibling
    # jax compilations hold the GIL for long stretches — the feeder's
    # 50 ms sleeps routinely stretched past the 200 ms timeout, so the
    # target was declared dead while beats were still being fed (passed
    # in isolation, failed in-suite).  The manager now takes an injectable
    # clock; the test drives virtual time and calls check_now() itself,
    # so detection no longer depends on scheduler latency.  The huge
    # interval parks the background loop thread out of the way.
    dead = []
    t = [0.0]
    hb = HeartbeatManager(interval=3600.0, timeout=0.2,
                          on_dead=dead.append, clock=lambda: t[0])
    hb.monitor("tm-1")
    for _ in range(5):
        hb.receive_heartbeat("tm-1")
        t[0] += 0.05
        hb.check_now()
    assert hb.is_alive("tm-1") and dead == []
    t[0] += 0.5
    hb.check_now()
    hb.check_now()  # a second sweep past the timeout must NOT re-report
    assert dead == ["tm-1"] and not hb.is_alive("tm-1")
    hb.stop()


def test_leader_election_failover(tmp_path):
    lease = str(tmp_path / "leader.lease")
    a = FileLeaderElection(lease, "a", renew_interval=0.05, lease_timeout=0.4)
    deadline = time.time() + 2
    while not a.is_leader and time.time() < deadline:
        time.sleep(0.02)
    assert a.is_leader
    b = FileLeaderElection(lease, "b", renew_interval=0.05, lease_timeout=0.4)
    time.sleep(0.3)
    assert not b.is_leader  # lease held and renewed
    a.stop(release=True)

    # b flips is_leader before its lease record hits the file, so wait on
    # the published record rather than asserting it right after takeover.
    def b_published():
        rec = b.current_leader()
        return b.is_leader and rec is not None and rec["leader"] == "b"

    deadline = time.time() + 3
    while not b_published() and time.time() < deadline:
        time.sleep(0.05)
    assert b.is_leader
    assert b.current_leader()["leader"] == "b"
    b.stop()


def test_job_result_store(tmp_path):
    store = JobResultStore(str(tmp_path))
    store.create_dirty("job1", {"state": "FINISHED"})
    assert store.has_result("job1")
    assert store.dirty_results() == {"job1": {"state": "FINISHED"}}
    store.mark_clean("job1")
    assert store.dirty_results() == {}
    assert store.has_result("job1")


# ---------------------------------------------------------------------------
# data plane: credit-based flow control
# ---------------------------------------------------------------------------

def test_exchange_credit_backpressure():
    server = ExchangeServer(capacity=2)
    ch = server.channel("c1")
    out = OutputChannel(server.address, "c1")
    deadline = time.time() + 2
    while out.available_credits() == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert out.available_credits() == 2

    out.send({"n": 0})
    out.send({"n": 1})
    assert out.available_credits() == 0
    with pytest.raises(TimeoutError, match="backpressured"):
        out.send({"n": 2}, timeout=0.2)          # receiver full: sender blocks

    assert ch.poll(timeout=1)["n"] == 0           # consuming frees a credit
    out.send({"n": 2}, timeout=2)
    assert ch.poll(timeout=1)["n"] == 1
    assert ch.poll(timeout=1)["n"] == 2
    out.end()
    assert ch.poll(timeout=1) is None and ch.ended
    out.close()
    server.stop()


def test_batch_debloater_tracks_rate():
    d = BatchDebloater(target_latency_s=0.1, min_size=10, max_size=100_000)
    assert d.batch_size() == 10
    assert not d.observed
    for _ in range(10):
        d.observe(50_000, 0.1)  # 500k rec/s -> 50k per 100ms
    assert d.observed
    assert 40_000 <= d.batch_size() <= 50_000


# ---------------------------------------------------------------------------
# data plane: binary columnar wire
# ---------------------------------------------------------------------------

def _exchange_pair(capacity=4, credit_batch=0, server_fmt="binary",
                   sender_fmt="binary", security=None, channel="bw"):
    server = ExchangeServer(capacity=capacity, credit_batch=credit_batch,
                            wire_format=server_fmt, security=security)
    ch = server.channel(channel)
    out = OutputChannel(server.address, channel, wire_format=sender_fmt,
                        security=security)
    return server, ch, out


def test_binary_wire_end_to_end_with_auth():
    """Batches negotiate onto the binary columnar wire (auth on by default
    in tests), arrive bit-exact, and non-batch payloads interleave on the
    legacy codec over the same connection."""
    server, ch, out = _exchange_pair()
    vals = np.random.default_rng(3).random(512)
    ts = np.arange(512, dtype=np.int64)
    keys = np.asarray([f"k{i % 5}" for i in range(512)], dtype=object)
    out.send(("b", vals, ts))
    out.send(("w", 1234))                    # control payload: legacy frame
    out.send((keys, vals, ts, 777, 3))       # keyed 5-tuple
    b1 = ch.poll(timeout=2)
    assert b1[0] == "b"
    np.testing.assert_array_equal(b1[1], vals)
    np.testing.assert_array_equal(b1[2], ts)
    assert ch.poll(timeout=2) == ("w", 1234)
    b3 = ch.poll(timeout=2)
    np.testing.assert_array_equal(b3[0], keys)
    assert b3[3] == 777 and b3[4] == 3
    assert out._wire == "binary"
    # byte accounting on both ends (numBytesIn/Out feed the metrics plane)
    assert out.bytes_out > vals.nbytes and ch.bytes_in > vals.nbytes
    assert out.out_rate() > 0 and ch.in_rate() > 0
    out.end()
    assert ch.poll(timeout=2) is None
    out.close()
    server.stop()


@pytest.mark.parametrize("server_fmt,sender_fmt", [
    ("pickle", "binary"),   # old/forced-pickle receiver: sender downgrades
    ("binary", "pickle"),   # sender pinned to pickle: never offers binary
])
def test_wire_format_downgrade_interop(server_fmt, sender_fmt):
    """exchange.wire-format negotiation: when either side only speaks
    pickle the channel transparently falls back to the legacy frames with
    identical payload semantics — the old-wire x new-wire handshake path."""
    server, ch, out = _exchange_pair(server_fmt=server_fmt,
                                     sender_fmt=sender_fmt)
    vals = np.arange(64, dtype=np.float64)
    out.send(("b", vals, np.arange(64, dtype=np.int64)))
    got = ch.poll(timeout=2)
    assert got[0] == "b"
    np.testing.assert_array_equal(got[1], vals)
    if sender_fmt == "binary":
        assert out._wire == "pickle"   # receiver's reply forced the downgrade
    out.end()
    out.close()
    server.stop()


def test_legacy_open_tuple_still_served():
    """A sender that never learned the format offer (the old 2-tuple open)
    keeps working against a new server — the reply's extra element is
    ignored by old credit loops, asserted here by driving the old open
    shape through a new OutputChannel pinned to pickle."""
    server, ch, out = _exchange_pair(sender_fmt="pickle")
    out.send({"n": 1})
    assert ch.poll(timeout=2) == {"n": 1}
    out.close()
    server.stop()


def test_binary_wire_many_column_payload_exceeds_iov_max():
    """A payload with more scatter-gather parts than the kernel's IOV_MAX
    (1024 iovecs) must still send — the sendmsg loop caps each call's
    group instead of dying with EMSGSIZE."""
    server, ch, out = _exchange_pair()
    payload = tuple(np.full(2, float(i)) for i in range(600))  # ~1200 iovecs
    out.send(payload)
    got = ch.poll(timeout=5)
    assert len(got) == 600
    np.testing.assert_array_equal(got[599], np.full(2, 599.0))
    assert out._wire == "binary"
    out.end()
    out.close()
    server.stop()


def test_binary_wire_decoded_columns_are_64_byte_aligned():
    """The alignment promise holds END-TO-END with auth enabled: the
    32-byte MAC prefix shares the receive allocation, so the body must be
    placed on the grid or every column lands at addr % 64 == 32."""
    server, ch, out = _exchange_pair()
    out.send(("b", np.arange(256, dtype=np.float64),
              np.arange(256, dtype=np.int64)))
    got = ch.poll(timeout=5)
    assert out._wire == "binary"
    for col in (got[1], got[2]):
        assert col.ctypes.data % 64 == 0, hex(col.ctypes.data)
    out.end()
    out.close()
    server.stop()


def test_output_channel_seq_is_contiguous_under_concurrent_senders():
    """Two threads sharing one OutputChannel: sequence numbers are assigned
    under the send lock, so the receiver observes a gapless sequence (the
    pre-fix race interleaved seq against frame order, which the receiver
    now rejects as corruption)."""
    server, ch, out = _exchange_pair(capacity=64)
    n_per_thread = 40

    def sender():
        for _ in range(n_per_thread):
            out.send(("b", np.arange(4, dtype=np.float64),
                      np.arange(4, dtype=np.int64)))

    threads = [threading.Thread(target=sender) for _ in range(2)]
    [t.start() for t in threads]
    got = 0
    while got < 2 * n_per_thread:
        assert ch.poll(timeout=5) is not None
        got += 1
    [t.join() for t in threads]
    out.end()
    assert ch.poll(timeout=2) is None      # no seq-gap error raised
    out.close()
    server.stop()


def test_refused_frame_does_not_burn_a_sequence_number(monkeypatch):
    """A frame refused at the sender BEFORE any byte hits the wire (e.g.
    the >=2GiB size guard) must not consume a channel seq — the next good
    frame would otherwise be misdiagnosed as a sequence gap."""
    import flink_tpu.runtime.dataplane as dpmod

    server, ch, out = _exchange_pair()
    real = dpmod.send_data_frame
    state = {"failed": False}

    def flaky(*a, **kw):
        if not state["failed"]:
            state["failed"] = True
            raise ValueError("frame too large (simulated)")
        return real(*a, **kw)

    monkeypatch.setattr(dpmod, "send_data_frame", flaky)
    payload = ("b", np.arange(8, dtype=np.float64),
               np.arange(8, dtype=np.int64))
    with pytest.raises(ValueError, match="too large"):
        out.send(payload)
    out.send(payload)                      # retries as seq 0, not seq 1
    got = ch.poll(timeout=5)
    np.testing.assert_array_equal(got[1], np.arange(8, dtype=np.float64))
    out.end()
    assert ch.poll(timeout=2) is None      # clean eos, no gap error
    out.close()
    server.stop()


def test_input_channel_rejects_sequence_gap():
    """A dropped/reordered frame is a loud error: the valid ring prefix
    drains, then poll raises instead of silently skipping data."""
    from flink_tpu.runtime.dataplane import InputChannel

    ch = InputChannel("gap", capacity=8, grant=lambda n: None)
    assert ch._on_data(0, "first", 10)
    assert not ch._on_data(2, "third", 10)     # gap: handler drops the conn
    assert ch.poll(timeout=1) == "first"
    with pytest.raises(ConnectionError, match="sequence gap"):
        ch.poll(timeout=1)


def test_credit_coalescing_grants_in_batches_and_preserves_backpressure():
    """exchange.credit-batch: grants return in coalesced frames (none until
    credit_batch slots free) while the blocking discipline is unchanged —
    the sender still stalls exactly while the ring is full."""
    server, ch, out = _exchange_pair(capacity=4, credit_batch=2)
    deadline = time.time() + 2
    while out.available_credits() < 4 and time.time() < deadline:
        time.sleep(0.01)
    assert out.available_credits() == 4

    for i in range(4):
        out.send({"n": i})
    assert out.available_credits() == 0
    with pytest.raises(TimeoutError, match="backpressured"):
        out.send({"n": 99}, timeout=0.2)       # ring full: sender blocks

    assert ch.poll(timeout=1)["n"] == 0        # one slot freed -> banked,
    time.sleep(0.15)                           # NOT granted yet
    assert out.available_credits() == 0
    assert ch.poll(timeout=1)["n"] == 1        # second slot -> grant of 2
    deadline = time.time() + 2
    while out.available_credits() < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert out.available_credits() == 2
    out.send({"n": 4}, timeout=2)              # and the sender resumes
    assert ch.poll(timeout=1)["n"] == 2
    assert ch.poll(timeout=1)["n"] == 3
    assert ch.poll(timeout=1)["n"] == 4
    out.end()
    assert ch.poll(timeout=1) is None and ch.ended
    out.close()
    server.stop()


def test_stage_output_runner_debloats_oversized_batches():
    """BatchDebloater wired into StageOutputRunner: after observations
    establish a low send rate, oversized batches are split into
    target-sized slices; the gauge exposes the current batch size."""
    from flink_tpu.graph.transformation import Step, Transformation
    from flink_tpu.metrics.registry import MetricRegistry
    from flink_tpu.runtime.stages import StageOutputRunner

    class _Sink:
        def __init__(self):
            self.sent = []
            self.backpressured_s = 0.0

        def send(self, msg, timeout=None):
            self.sent.append(msg)

        def available_credits(self):
            return 1

        def end(self):
            self.sent.append(("eos",))

    sink = _Sink()
    debloater = BatchDebloater(target_latency_s=0.1, min_size=8,
                               max_size=1 << 20)
    t = Transformation("stage_output", "out", [], {
        "sender": sink, "cancelled": threading.Event(),
        "debloater": debloater,
    })
    t.uid = "out"
    runner = StageOutputRunner(Step(chain=[], terminal=t,
                                    partitioning="forward", inputs=[]))
    registry = MetricRegistry()
    runner.register_metrics(registry.group("op"))
    assert "op.debloatedBatchSize" in registry.all_metrics()

    vals = np.arange(100, dtype=np.float64)
    ts = np.arange(100, dtype=np.int64)
    # before any observation the batch passes through whole
    assert not debloater.observed
    runner.on_batch(vals, ts)
    assert len(sink.sent) == 1
    # the runner observed its own (instant) send above; drive the EMA back
    # down to a slow channel: 100 rec/s x 0.1s target -> 10 records/batch
    while debloater.batch_size() != 10:
        debloater.observe(10, 0.1)
    runner.on_batch(vals, ts)                  # 100 records -> 10 slices
    slices = sink.sent[1:]
    assert len(slices) == 10
    assert all(m[0] == "b" and len(m[2]) == 10 for m in slices)
    np.testing.assert_array_equal(np.concatenate([m[1] for m in slices]), vals)
    np.testing.assert_array_equal(np.concatenate([m[2] for m in slices]), ts)


# ---------------------------------------------------------------------------
# cluster end-to-end
# ---------------------------------------------------------------------------

def _make_spec(n_steps=6, batch=40, n_keys=7):
    def source_factory(shard, num_shards):
        rng = np.random.default_rng(100 + shard)
        batches = []
        for s in range(n_steps):
            keys = np.asarray([f"k{v}" for v in rng.integers(0, n_keys, batch)], dtype=object)
            vals = np.ones(batch, dtype=np.float64)
            ts = (s * 1000 + rng.integers(0, 1000, batch)).astype(np.int64)
            wm = s * 1000 + 500
            batches.append((keys, vals, ts, wm))
        return batches

    return DistributedJobSpec(
        name="dist-wordcount",
        source_factory=source_factory,
        assigner=TumblingEventTimeWindows.of(2000),
        aggregate="sum",
        max_parallelism=16,
    )


def _expected(spec, parallelism):
    """Run the same workload through a single oracle operator."""
    from flink_tpu.ops.aggregators import resolve
    from flink_tpu.runtime.oracle_window_operator import OracleWindowOperator

    op = OracleWindowOperator(spec.assigner, resolve(spec.aggregate).python_equivalent(),
                              max_parallelism=spec.max_parallelism)
    shard_batches = [spec.source_factory(i, parallelism) for i in range(parallelism)]
    n_steps = len(shard_batches[0])
    for s in range(n_steps):
        wms = []
        for b in shard_batches:
            keys, vals, ts, wm = b[s]
            for i in range(len(keys)):
                op.process_record(keys[i], float(vals[i]), int(ts[i]))
            wms.append(wm)
        op.process_watermark(min(wms))
    op.process_watermark((1 << 63) - 1)
    return {
        (k, w.start): r for k, w, r, _ in op.drain_output()
    }


def _collect(result):
    return {(k, w[0]): r for k, w, r, _ in result}


def test_cluster_device_session_operator_two_shards():
    """Sessions scale past one device the cluster way: each shard runs its
    own TpuSessionWindowOperator over its key-group range (sessions never
    cross keys, so sharding needs no cross-shard merge). Parity vs the
    oracle MergingWindowSet path on the same sharded stream."""
    from flink_tpu.api.windowing.assigners import EventTimeSessionWindows

    gap = 1000

    def source_factory(shard, num_shards):
        rng = np.random.default_rng(50 + shard)
        batches = []
        t = 0
        for s in range(6):
            keys = np.asarray(
                [f"k{v}" for v in rng.integers(0, 6, 30)], dtype=object)
            vals = np.ones(30, dtype=np.float64)
            ts = (t + rng.integers(0, 600, 30)).astype(np.int64)
            batches.append((keys, vals, ts, t + 300))
            # bursts separated by > gap so sessions really close
            t += 600 + (gap * 2 if s % 2 else 0)
        return batches

    def mk_spec(operator):
        return DistributedJobSpec(
            name=f"sessions-{operator}", source_factory=source_factory,
            assigner=EventTimeSessionWindows.with_gap(gap),
            aggregate="sum", max_parallelism=16, operator=operator,
        )

    def run(operator):
        svc_jm, svc1, svc2 = RpcService(), RpcService(), RpcService()
        jm = JobManagerEndpoint(svc_jm, heartbeat_interval=0.2,
                                heartbeat_timeout=10.0)
        tes = []
        for svc in (svc1, svc2):
            te = TaskExecutorEndpoint(svc, slots=1)
            te.connect(svc_jm.address)
            tes.append(te)
        client = svc_jm.gateway(svc_jm.address, "jobmanager")
        job_id = client.submit_job(mk_spec(operator).to_bytes(), 2)
        deadline = time.time() + 40
        while time.time() < deadline:
            st = client.job_status(job_id)
            if st["status"] in ("FINISHED", "FAILED"):
                break
            time.sleep(0.1)
        assert st["status"] == "FINISHED", st
        out = client.job_result(job_id)
        for te in tes:
            te.stop()
        jm.heartbeats.stop()
        for svc in (svc_jm, svc1, svc2):
            svc.stop()
        return sorted((k, tuple(w), round(float(r), 4)) for k, w, r, _ in out)

    got = run("device")
    ref = run("oracle")
    assert got == ref and len(ref) > 0


def test_cluster_savepoint_and_resubmit(tmp_path):
    """Distributed savepoints (S10 on the cluster runtime): a user-triggered
    savepoint rides the normal trigger/ack machinery and writes a durable
    snapshot set; a NEW job submitted with savepoint_path restores from it
    and finishes with the full exact results."""
    def slow_source(shard, num_shards):
        rng = np.random.default_rng(100 + shard)
        batches = []
        for s in range(600):      # long enough to savepoint mid-run
            keys = np.asarray(
                [f"k{v}" for v in rng.integers(0, 5, 30)], dtype=object)
            vals = np.ones(30, dtype=np.float64)
            ts = (s * 500 + rng.integers(0, 500, 30)).astype(np.int64)
            batches.append((keys, vals, ts, s * 500 + 250))
        return batches

    spec = DistributedJobSpec(
        name="savepointed", source_factory=slow_source,
        assigner=TumblingEventTimeWindows.of(2000), aggregate="sum",
        max_parallelism=16,
    )

    svc_jm, svc1 = RpcService(), RpcService()
    # NOTE: no checkpoint_dir — savepoints must work without configured
    # periodic-checkpoint storage (they carry their own target directory)
    jm = JobManagerEndpoint(svc_jm, heartbeat_interval=0.2,
                            heartbeat_timeout=10.0)
    te1 = TaskExecutorEndpoint(svc1, slots=2)
    te1.connect(svc_jm.address)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")

    job_id = client.submit_job(spec.to_bytes(), 2)
    sp_dir = str(tmp_path / "sp")
    # trigger once the job reports progress
    deadline = time.time() + 30
    cp = None
    while time.time() < deadline and cp is None:
        if client.job_status(job_id)["status"] != "RUNNING":
            break
        cp = client.trigger_savepoint(job_id, sp_dir)
        time.sleep(0.05)
    assert cp is not None, client.job_status(job_id)
    deadline = time.time() + 30
    while time.time() < deadline:
        st = client.job_status(job_id)
        if st["savepoints"] or st["status"] in ("FINISHED", "FAILED"):
            break
        time.sleep(0.05)
    assert st["savepoints"] == [sp_dir], st
    client.cancel_job(job_id)

    # new job FROM the savepoint: replays the remainder, full exact result
    job2 = client.submit_job(spec.to_bytes(), 2, sp_dir)
    deadline = time.time() + 40
    while time.time() < deadline:
        st = client.job_status(job2)
        if st["status"] in ("FINISHED", "FAILED"):
            break
        time.sleep(0.1)
    assert st["status"] == "FINISHED", st
    assert _collect(client.job_result(job2)) == _expected(spec, 2)

    te1.stop()
    jm.heartbeats.stop()
    svc_jm.stop()
    svc1.stop()


def test_savepoint_spec_mismatch_rejected(tmp_path):
    """submit_job(savepoint_path=...) validates the savepoint's snapshot
    set against the submitted spec up front: per-stage runtime snapshots
    cannot seed a keyed job (and a staged job needs a matching stage
    layout) — the failure is a descriptive error at submit time, not a
    KeyError deep inside scheduling."""
    from flink_tpu.checkpoint.storage import FsCheckpointStorage

    sp_dir = str(tmp_path / "staged-sp")
    FsCheckpointStorage(sp_dir).save(1, {
        "job": "old", "step": 7, "savepoint": True,
        "shards": {0: {"runtime": {}, "step": 7},
                   1: {"runtime": {}, "step": 7}},
    })

    svc_jm = RpcService()
    jm = JobManagerEndpoint(svc_jm, heartbeat_interval=0.2,
                            heartbeat_timeout=10.0)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    with pytest.raises(RemoteRpcError, match="per-stage runtime"):
        client.submit_job(_make_spec().to_bytes(), 2, sp_dir)

    # the reverse direction: a KEYED savepoint whose shard count happens to
    # match the stage count must not seed a staged job either
    keyed_dir = str(tmp_path / "keyed-sp")
    FsCheckpointStorage(keyed_dir).save(1, {
        "job": "old", "step": 7, "savepoint": True,
        "shards": {0: {"operator": {}, "step": 7, "results": []},
                   1: {"operator": {}, "step": 7, "results": []}},
    })
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.cluster import GraphJobSpec

    env = StreamExecutionEnvironment.get_execution_environment()
    (env.from_collection(
        [(f"k{i % 3}", i * 250) for i in range(40)],
        timestamp_fn=lambda v: v[1],
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    ).map(lambda v: v[0]).key_by(lambda v: v)
     .window(TumblingEventTimeWindows.of(2000)).count()
     .slot_sharing_group("agg").collect())
    from flink_tpu.config import Configuration

    spec2 = GraphJobSpec("staged", plan(env._sinks), Configuration())
    with pytest.raises(RemoteRpcError, match="keyed snapshots"):
        client.submit_job(spec2.to_bytes(), 2, keyed_dir)
    jm.heartbeats.stop()
    svc_jm.stop()


def test_auto_parallelism_from_source_volume(tmp_path):
    """AdaptiveBatchScheduler analogue: parallelism=0 derives the task
    count from the declared source volume (one task per
    auto_records_per_task records), clamped to max_parallelism; without a
    hint it sizes to the free slots."""
    svc_jm = RpcService()
    jm = JobManagerEndpoint(svc_jm, auto_records_per_task=1000,
                            heartbeat_interval=0.2, heartbeat_timeout=10.0)
    svcs, tms = [], []
    for _ in range(3):
        svc = RpcService()
        te = TaskExecutorEndpoint(svc, slots=1)
        te.connect(svc_jm.address)
        svcs.append(svc)
        tms.append(te)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")

    # 2500 records / 1000 per task -> 3 tasks
    spec = _make_spec()
    spec.source_records_hint = 2500
    job_id = client.submit_job(spec.to_bytes(), 0)
    assert client.job_status(job_id)["parallelism"] == 3

    deadline = time.time() + 30
    while time.time() < deadline:
        st = client.job_status(job_id)
        if st["status"] in ("FINISHED", "FAILED"):
            break
        time.sleep(0.1)
    assert st["status"] == "FINISHED", st
    assert _collect(client.job_result(job_id)) == _expected(spec, 3)

    # no hint: size to free slots (all 3 again free after the job finished)
    spec2 = _make_spec()
    job2 = client.submit_job(spec2.to_bytes(), 0)
    assert client.job_status(job2)["parallelism"] == 3

    # hint clamps at max_parallelism
    spec3 = _make_spec()
    spec3.source_records_hint = 10_000_000
    job3 = client.submit_job(spec3.to_bytes(), 0)
    assert client.job_status(job3)["parallelism"] == spec3.max_parallelism

    for te in tms:
        te.stop()
    jm.heartbeats.stop()
    svc_jm.stop()
    for svc in svcs:
        svc.stop()


def test_cluster_end_to_end_two_tms():
    svc_jm, svc_tm1, svc_tm2 = RpcService(), RpcService(), RpcService()
    jm = JobManagerEndpoint(svc_jm)
    tms = []
    for svc in (svc_tm1, svc_tm2):
        te = TaskExecutorEndpoint(svc, slots=1)
        te.connect(svc_jm.address)
        tms.append(te)

    spec = _make_spec()
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    job_id = client.submit_job(spec.to_bytes(), 2)

    deadline = time.time() + 30
    while time.time() < deadline:
        st = client.job_status(job_id)
        if st["status"] in ("FINISHED", "FAILED"):
            break
        time.sleep(0.2)
    assert st["status"] == "FINISHED", st
    got = _collect(client.job_result(job_id))
    assert got == _expected(spec, 2)

    for te in tms:
        te.stop()
    jm.heartbeats.stop()
    for svc in (svc_jm, svc_tm1, svc_tm2):
        svc.stop()


def test_cluster_checkpoint_failover_exactly_once(tmp_path):
    """Kill a TM mid-job; the job restarts from the step-aligned checkpoint
    on a replacement and the final results are exact (no loss, no dupes)."""
    svc_jm = RpcService()
    jm = JobManagerEndpoint(
        svc_jm, checkpoint_dir=str(tmp_path / "chk"),
        restart_attempts=3, restart_delay=0.2,
        heartbeat_interval=0.2, heartbeat_timeout=1.5,
        adaptive=False,  # this test pins parallelism: replacement TM joins
    )
    spec = _make_spec(n_steps=40, batch=30)

    svc1, svc2 = RpcService(), RpcService()
    te1 = TaskExecutorEndpoint(svc1, slots=1)
    te1.connect(svc_jm.address)
    te2 = TaskExecutorEndpoint(svc2, slots=1)
    te2.connect(svc_jm.address)

    # slow the job down so the kill lands mid-flight
    orig_factory = spec.source_factory

    def slow_factory(shard, num_shards):
        batches = orig_factory(shard, num_shards)
        return _SlowList(batches, delay=0.1)

    spec.source_factory = slow_factory
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    job_id = client.submit_job(spec.to_bytes(), 2)

    # wait until at least one checkpoint completed, then kill TM2
    deadline = time.time() + 30
    while time.time() < deadline:
        if client.trigger_checkpoint(job_id) and client.job_status(job_id)["checkpoints"]:
            break
        time.sleep(0.3)
    assert client.job_status(job_id)["checkpoints"], "no checkpoint completed"
    te2.stop()
    svc2.stop()

    # replacement worker joins; the job must redeploy onto te1 + te3
    svc3 = RpcService()
    te3 = TaskExecutorEndpoint(svc3, slots=1)
    te3.connect(svc_jm.address)

    deadline = time.time() + 60
    while time.time() < deadline:
        st = client.job_status(job_id)
        if st["status"] in ("FINISHED", "FAILED"):
            break
        time.sleep(0.3)
    assert st["status"] == "FINISHED", st
    assert st["restarts"] >= 1
    got = _collect(client.job_result(job_id))
    assert got == _expected(_make_spec(n_steps=40, batch=30), 2)

    # ---- control-plane observability of the same run (ISSUE 4) ----------
    # checkpoint stats: >=1 COMPLETED record with per-task acks + real sizes
    cps = client.job_checkpoints(job_id)
    assert cps["counts"]["completed"] >= 1
    completed = [c for c in cps["history"] if c["status"] == "COMPLETED"]
    assert completed, cps
    c0 = completed[0]
    assert c0["end_to_end_duration_ms"] > 0
    assert c0["state_size_bytes"] > 0              # persisted artifact size
    assert len(c0["tasks"]) == 2                   # both shards acked
    assert all(a["ack_latency_ms"] >= 0 and a["state_size_bytes"] > 0
               for a in c0["tasks"].values())
    assert client.job_checkpoint(job_id, c0["id"])["id"] == c0["id"]

    # exception history: the TM loss, attributed to the dead TaskManager
    exc = client.job_exceptions(job_id)
    assert exc["entries"], exc
    # the kill surfaces either as te2's heartbeat timeout (attributed to
    # te2) or as a channel failure reported by the surviving shard on te1 —
    # either way the entry must name a REAL TaskManager of the job
    assert any(e["task_manager"] in (te1.tm_id, te2.tm_id)
               for e in exc["entries"]), exc
    assert "lost" in exc["root_exception"] or "shard" in exc["root_exception"]

    # recovery timeline: rewound checkpoint id + nonzero restore/downtime
    assert exc["recoveries"], exc
    rec = exc["recoveries"][-1]                    # oldest = the TM-loss one
    assert rec["restored_checkpoint_id"] is not None
    assert rec["restore_duration_ms"] > 0
    assert rec["downtime_ms"] > 0
    assert rec["steps_replayed"] is not None and rec["steps_replayed"] >= 0

    # JM-side gauges ride job_metrics for /metrics exposition
    jm_metrics = client.job_metrics(job_id)
    assert jm_metrics["jm"]["job.numberOfCompletedCheckpoints"] >= 1
    assert jm_metrics["jm"]["job.numRestarts"] >= 1
    assert jm_metrics["job"]["job.numberOfCompletedCheckpoints"] >= 1

    te1.stop()
    te3.stop()
    jm.heartbeats.stop()
    svc_jm.stop()
    svc1.stop()
    svc3.stop()


class _SlowList(list):
    """Source batches that pace the step loop (picklable)."""

    def __init__(self, items, delay):
        super().__init__(items)
        self.delay = delay

    def __getitem__(self, i):
        time.sleep(self.delay)
        return super().__getitem__(i)


def _partition_invariant_spec(n_steps=30, batch=60, n_keys=9):
    """Source whose per-step UNION of shard batches is the same for any
    parallelism (the split-redistribution contract rescaling relies on)."""

    def source_factory(shard, num_shards):
        out = []
        for s in range(n_steps):
            rng = np.random.default_rng(1000 + s)     # per-STEP determinism
            keys = np.asarray([f"k{v}" for v in rng.integers(0, n_keys, batch)],
                              dtype=object)
            vals = np.ones(batch, dtype=np.float64)
            ts = (s * 1000 + rng.integers(0, 1000, batch)).astype(np.int64)
            sl = slice(shard, None, num_shards)
            out.append((keys[sl], vals[sl], ts[sl], s * 1000 + 500))
        return out

    return DistributedJobSpec(
        name="rescale-job",
        source_factory=source_factory,
        assigner=TumblingEventTimeWindows.of(2000),
        aggregate="sum",
        max_parallelism=16,
    )


class _GatedList(list):
    """Source batches lightly paced, BLOCKING at `gate_step` until the
    `resume` flag file appears — the test, not the clock, decides when
    the stream may end (picklable; the gate survives restarts because
    every attempt re-runs the factory)."""

    def __init__(self, items, gate_step, resume_flag, delay=0.08):
        super().__init__(items)
        self.gate_step = gate_step
        self.resume_flag = resume_flag
        self.delay = delay

    def __getitem__(self, i):
        time.sleep(self.delay)
        if i >= self.gate_step:
            deadline = time.time() + 120
            while not os.path.exists(self.resume_flag):
                if time.time() > deadline:
                    raise RuntimeError("resume flag never appeared")
                time.sleep(0.05)
        return super().__getitem__(i)


def test_cluster_rescales_down_after_tm_loss(tmp_path):
    """Lose a TM with no replacement: the adaptive scheduler restarts the
    job at parallelism 1 from the checkpoint, re-sharding state by
    key-group; results stay exact.

    Deterministic by construction (this was checkpoint-timing flaky under
    suite load): the source GATES at a step past the checkpoint window and
    only the test's resume flag lets the stream end, so the job cannot
    race to FINISHED before the heartbeat timeout notices the dead TM —
    every transition below is a condition wait, not a sleep budget."""
    resume = str(tmp_path / "resume")
    svc_jm = RpcService()
    jm = JobManagerEndpoint(
        svc_jm, checkpoint_dir=str(tmp_path / "chk"),
        restart_attempts=3, restart_delay=0.3,
        heartbeat_interval=0.2, heartbeat_timeout=1.2,
    )
    spec = _partition_invariant_spec()
    orig_factory = spec.source_factory

    def gated_factory(shard, num_shards, _orig=orig_factory, _resume=resume):
        return _GatedList(_orig(shard, num_shards), gate_step=20,
                          resume_flag=_resume)

    spec.source_factory = gated_factory

    svc1, svc2 = RpcService(), RpcService()
    # sub-500ms shipping tightens the heartbeat beat, so the JM's view of
    # step progress is fresh enough for the checkpoint target margin
    te1 = TaskExecutorEndpoint(svc1, slots=1, shipping_interval_ms=200)
    te1.connect(svc_jm.address)
    te2 = TaskExecutorEndpoint(svc2, slots=1, shipping_interval_ms=200)
    te2.connect(svc_jm.address)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    job_id = client.submit_job(spec.to_bytes(), 2)

    def wait_for(predicate, timeout, desc):
        deadline = time.time() + timeout
        while time.time() < deadline:
            got = predicate()
            if got:
                return got
            time.sleep(0.2)
        raise AssertionError(
            f"timed out waiting for {desc}: {client.job_status(job_id)}")

    wait_for(lambda: client.trigger_checkpoint(job_id)
             and client.job_status(job_id)["checkpoints"],
             60, "a completed checkpoint")
    te2.stop()
    svc2.stop()        # no replacement: must downscale to te1 alone

    # the gate holds the stream open, so the ONLY way forward is the
    # heartbeat timeout -> fail -> adaptive reschedule at parallelism 1
    wait_for(lambda: (lambda s: s["restarts"] >= 1
                      and s["status"] == "RUNNING"
                      and s["parallelism"] == 1)(client.job_status(job_id)),
             60, "adaptive rescale-down to the surviving TM")

    (tmp_path / "resume").touch()      # release the stream end
    st = wait_for(lambda: (lambda s: s if s["status"] in
                           ("FINISHED", "FAILED") else None)(
                               client.job_status(job_id)),
                  90, "job completion")
    assert st["status"] == "FINISHED", st
    assert st["restarts"] >= 1
    assert st["parallelism"] == 1
    got = _collect(client.job_result(job_id))
    want = _expected(_partition_invariant_spec(), 1)
    assert got == want

    te1.stop()
    jm.heartbeats.stop()
    svc_jm.stop()
    svc1.stop()


def test_local_recovery_restores_from_tm_local_copy(tmp_path):
    """S11 local recovery: a task that fails after a checkpoint and is
    redeployed onto the SAME TaskExecutor restores from the TM-local copy of
    its snapshot (no snapshot re-ships), and results stay exact."""
    flag = tmp_path / "failed-once"

    def source_factory(shard, num_shards, _flag=str(flag)):
        import os as _os

        rng = np.random.default_rng(500)
        batches = []
        for s in range(14):
            keys = np.asarray([f"k{v}" for v in rng.integers(0, 5, 40)],
                              dtype=object)
            vals = np.ones(40, dtype=np.float64)
            ts = (s * 1000 + rng.integers(0, 1000, 40)).astype(np.int64)
            batches.append((keys, vals, ts, s * 1000 + 500))

        class _FailOnce(list):
            def __getitem__(self, i):
                time.sleep(0.15)
                if i >= 10 and not _os.path.exists(_flag):
                    open(_flag, "w").write("x")
                    raise RuntimeError("injected task failure")
                return list.__getitem__(self, i)

        return _FailOnce(batches)

    spec = DistributedJobSpec(
        name="local-recovery",
        source_factory=source_factory,
        assigner=TumblingEventTimeWindows.of(2000),
        aggregate="sum",
        max_parallelism=16,
    )

    svc_jm = RpcService()
    jm = JobManagerEndpoint(
        svc_jm, checkpoint_dir=str(tmp_path / "chk"),
        restart_attempts=3, restart_delay=0.2,
        heartbeat_interval=0.2, heartbeat_timeout=5.0,
    )
    svc1 = RpcService()
    te1 = TaskExecutorEndpoint(svc1, slots=1)
    te1.connect(svc_jm.address)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    job_id = client.submit_job(spec.to_bytes(), 1)

    # land a checkpoint before the injected failure at step >= 6
    deadline = time.time() + 20
    while time.time() < deadline:
        if client.trigger_checkpoint(job_id) and \
                client.job_status(job_id)["checkpoints"]:
            break
        time.sleep(0.1)
    assert client.job_status(job_id)["checkpoints"]

    deadline = time.time() + 60
    while time.time() < deadline:
        st = client.job_status(job_id)
        if st["status"] in ("FINISHED", "FAILED"):
            break
        time.sleep(0.2)
    assert st["status"] == "FINISHED", st
    assert st["restarts"] >= 1
    assert te1.num_local_restores >= 1, "expected a TM-local restore"

    def clean_factory(shard, num_shards):
        rng = np.random.default_rng(500)
        batches = []
        for s in range(14):
            keys = np.asarray([f"k{v}" for v in rng.integers(0, 5, 40)],
                              dtype=object)
            vals = np.ones(40, dtype=np.float64)
            ts = (s * 1000 + rng.integers(0, 1000, 40)).astype(np.int64)
            batches.append((keys, vals, ts, s * 1000 + 500))
        return batches

    ref_spec = DistributedJobSpec(
        name="ref", source_factory=clean_factory,
        assigner=TumblingEventTimeWindows.of(2000), aggregate="sum",
        max_parallelism=16,
    )
    assert _collect(client.job_result(job_id)) == _expected(ref_spec, 1)

    te1.stop()
    jm.heartbeats.stop()
    svc_jm.stop()
    svc1.stop()


def test_cluster_runs_general_graph_job_with_failover(tmp_path):
    """GraphJobSpec: an arbitrary planned pipeline (two-source union into a
    keyed window) executes on the cluster as one supervised task — with a
    step-aligned checkpoint, an injected failure, restart from the TM-local
    snapshot, and exact results."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.config import Configuration, ExecutionOptions
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.cluster import GraphJobSpec

    flag = str(tmp_path / "boomed")

    def build(inject_failure):
        conf = Configuration()
        conf.set(ExecutionOptions.BATCH_SIZE, 4)
        env = StreamExecutionEnvironment.get_execution_environment(conf)

        def mk(pairs):
            vals = [p[0] for p in pairs]
            ts = {i: p[1] for i, p in enumerate(pairs)}
            return env.from_collection(
                list(enumerate(vals)), timestamp_fn=lambda iv: ts[iv[0]],
                watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
            ).map(lambda iv: iv[1])

        a = mk([((f"k{i % 3}"), i * 250) for i in range(40)])
        b = mk([((f"k{i % 3}"), i * 250 + 100) for i in range(40)])

        def maybe_boom(v, _flag=flag, _inject=inject_failure):
            import os as _os
            import time as _time

            _time.sleep(0.02)
            if _inject and not _os.path.exists(_flag):
                # fail mid-stream exactly once, after some batches
                maybe_boom.count = getattr(maybe_boom, "count", 0) + 1
                if maybe_boom.count > 60:
                    open(_flag, "w").write("x")
                    raise RuntimeError("injected graph task failure")
            return v

        u = a.union(b).map(maybe_boom)
        windowed = (
            u.key_by(lambda v: v)
            .window(TumblingEventTimeWindows.of(2000))
            .count()
        )
        windowed.collect()
        return GraphJobSpec("graph-job", plan(env._sinks), conf)

    svc_jm = RpcService()
    jm = JobManagerEndpoint(
        svc_jm, checkpoint_dir=str(tmp_path / "chk"),
        checkpoint_interval=0.2,
        restart_attempts=3, restart_delay=0.2,
        heartbeat_interval=0.2, heartbeat_timeout=5.0,
    )
    svc1 = RpcService()
    te1 = TaskExecutorEndpoint(svc1, slots=1)
    te1.connect(svc_jm.address)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    job_id = client.submit_job(build(True).to_bytes(), 1)

    deadline = time.time() + 60
    while time.time() < deadline:
        st = client.job_status(job_id)
        if st["status"] in ("FINISHED", "FAILED"):
            break
        time.sleep(0.2)
    assert st["status"] == "FINISHED", st
    assert st["restarts"] >= 1
    assert te1.num_local_restores >= 1     # restarted from the TM-local copy

    got = sorted(client.job_result(job_id))
    # reference: the same graph run locally without failure injection
    from flink_tpu.runtime.executor import JobRuntime, SinkRunner

    ref_spec = build(False)
    rt = JobRuntime(ref_spec.graph, ref_spec.config)
    rt.run()
    ref = sorted(
        x for r in rt.runners if isinstance(r, SinkRunner)
        and hasattr(r.writer, "store") for x in r.writer.store
    )
    assert got == ref and len(ref) > 0

    te1.stop()
    jm.heartbeats.stop()
    svc_jm.stop()
    svc1.stop()
