"""End-to-end tests against REAL processes.

Reference capability: flink-end-to-end-tests — start_cluster launches an
actual dist build (test-scripts/common.sh:308), jobs run against it, and
HA tests SIGKILL real processes (kill_single, common_ha.sh:121). Here:
`python -m flink_tpu.runtime.cluster jobmanager|taskmanager` subprocesses,
a job submitted over the real RPC socket, and a taskmanager killed with
SIGKILL mid-job to exercise checkpoint-restore failover across OS
processes (not threads).
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
from flink_tpu.runtime.cluster import DistributedJobSpec
from flink_tpu.runtime.rpc import RpcService


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"     # workers must not touch a TPU backend
    return subprocess.Popen(
        [sys.executable, "-m", "flink_tpu.runtime.cluster", *args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )


def _wait_line(proc, needle, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = proc.stdout.readline().decode(errors="replace")
        if needle in line:
            return line
        if proc.poll() is not None:
            raise RuntimeError(f"process exited: {line}")
    raise TimeoutError(f"no {needle!r} within {timeout}s")


def _spec(n_steps=8, batch=40):
    def source_factory(shard, num_shards, _n=n_steps, _b=batch):
        rng = np.random.default_rng(7 + shard)
        out = []
        for s in range(_n):
            keys = np.asarray(
                [f"k{v}" for v in rng.integers(0, 5, _b)], dtype=object)
            vals = np.ones(_b, dtype=np.float64)
            ts = (s * 1000 + rng.integers(0, 1000, _b)).astype(np.int64)
            out.append((keys, vals, ts, s * 1000 + 500))
        return out

    return DistributedJobSpec(
        name="e2e", source_factory=source_factory,
        assigner=TumblingEventTimeWindows.of(2000), aggregate="sum",
        max_parallelism=16,
    )


def _await_status(client, job_id, want, timeout=60):
    deadline = time.time() + timeout
    st = None
    while time.time() < deadline:
        st = client.job_status(job_id)
        if st["status"] in want:
            return st
        time.sleep(0.2)
    raise TimeoutError(f"job stuck in {st}")


@pytest.fixture
def jm_port():
    return _free_port()


def test_real_process_cluster_runs_job(jm_port):
    jm = _spawn(["jobmanager", "--port", str(jm_port)])
    tms = []
    try:
        _wait_line(jm, "jobmanager listening")
        tm = _spawn(["taskmanager", "--jobmanager", f"127.0.0.1:{jm_port}",
                     "--slots", "2"])
        tms.append(tm)
        _wait_line(tm, "registered with")

        svc = RpcService()
        client = svc.gateway(f"127.0.0.1:{jm_port}", "jobmanager")
        job_id = client.submit_job(_spec().to_bytes(), 2)
        st = _await_status(client, job_id, ("FINISHED", "FAILED"))
        assert st["status"] == "FINISHED", st
        result = client.job_result(job_id)
        # every record of every shard counted: 2 shards x 8 steps x 40 ones
        total = sum(r for (_k, _w, r, _t) in result)
        assert total == 2 * 8 * 40
        svc.stop()
    finally:
        for p in tms + [jm]:
            p.kill()
            p.wait(timeout=10)


def test_real_process_taskmanager_sigkill_failover(jm_port, tmp_path):
    """kill_single analogue: SIGKILL a real TM process mid-job; the job
    restarts from the latest checkpoint on a second TM and finishes with
    exact results."""
    jm = _spawn(["jobmanager", "--port", str(jm_port),
                 "--checkpoint-dir", str(tmp_path / "chk"),
                 "--checkpoint-interval", "0.2"])
    procs = [jm]
    try:
        _wait_line(jm, "jobmanager listening")
        tm1 = _spawn(["taskmanager", "--jobmanager", f"127.0.0.1:{jm_port}"])
        procs.append(tm1)
        _wait_line(tm1, "registered with")

        svc = RpcService()
        client = svc.gateway(f"127.0.0.1:{jm_port}", "jobmanager")
        spec = _spec(n_steps=4000, batch=150)     # long enough to kill mid-run
        job_id = client.submit_job(spec.to_bytes(), 1)
        _await_status(client, job_id, ("RUNNING",))
        # let at least one checkpoint land, then SIGKILL the worker
        time.sleep(0.8)
        assert client.job_status(job_id)["status"] == "RUNNING"
        os.kill(tm1.pid, signal.SIGKILL)
        tm1.wait(timeout=10)

        tm2 = _spawn(["taskmanager", "--jobmanager", f"127.0.0.1:{jm_port}"])
        procs.append(tm2)
        _wait_line(tm2, "registered with")
        st = _await_status(client, job_id, ("FINISHED", "FAILED"), timeout=90)
        assert st["status"] == "FINISHED", st
        assert st["restarts"] >= 1            # it really failed over
        result = client.job_result(job_id)
        total = sum(r for (_k, _w, r, _t) in result)
        assert total == 4000 * 150            # exactly-once despite the kill
        svc.stop()
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=10)
