"""Emission-latency plane tests: log-bucket histogram geometry, bucket-wise
merges, sentinel clamps, fire-to-resolve stamping, stall attribution, the
shard-fold + payload-filter registration regression, and the uniform
Prometheus summary export (observability.emission-latency.*)."""

import math

import numpy as np

from flink_tpu.metrics.emission_latency import (
    LATENCY_SPAN_NAME,
    LATENCY_SPAN_SCOPE,
    NUM_BUCKETS,
    SUBBUCKETS,
    EmissionHistogram,
    EmissionLatencyTracker,
    bucket_index,
    bucket_upper,
    build_latency_report,
    is_emission_snapshot,
    merge_snapshots,
    stall_attribution,
    watermark_lag_ms,
)

MAX_WATERMARK = (1 << 63) - 1
MIN_WATERMARK = -(1 << 63)


# -- histogram geometry ---------------------------------------------------

def test_bucket_boundaries():
    # <=1ms (and degenerate inputs) collapse into bucket 0
    for v in (0.0, 0.5, 1.0, -3.0, float("nan")):
        assert bucket_index(v) == 0
    assert bucket_upper(0) == 1.0
    # each octave splits into SUBBUCKETS; exact powers of two open a new
    # octave's first sub-bucket
    assert bucket_index(1.0001) == 1
    assert bucket_index(2.0) == 1 + SUBBUCKETS    # octave 1, first sub
    # bucket_upper is the inclusive upper bound: a value never lands in a
    # bucket whose upper bound is below it, and the relative error of
    # reporting the upper bound is <= 1/SUBBUCKETS
    rng = np.random.default_rng(7)
    for v in rng.uniform(1.001, 1e9, size=500):
        idx = bucket_index(v)
        up = bucket_upper(idx)
        assert up >= v * (1.0 - 1e-9)
        assert up <= v * (1.0 + 1.0 / SUBBUCKETS) * (1.0 + 1e-9)
    # monotone: larger values never map to smaller buckets
    vals = np.sort(rng.uniform(0.0, 1e12, size=1000))
    idxs = [bucket_index(v) for v in vals]
    assert idxs == sorted(idxs)
    # the top bucket absorbs everything beyond the covered range
    assert bucket_index(float(1 << 60)) == NUM_BUCKETS - 1


def test_histogram_percentiles_basic():
    h = EmissionHistogram()
    for v in range(1, 101):           # 1..100 ms
        h.record(float(v))
    assert h.count == 100
    s = h.snapshot()
    assert s["min"] == 1.0 and s["max"] == 100.0
    # log-bucket percentiles carry <=12.5% relative error upward, and are
    # clamped to the observed max
    assert 50.0 <= s["p50"] <= 50.0 * 1.125
    assert 99.0 <= s["p99"] <= 100.0
    assert s["p999"] <= s["max"]


def test_p999_adversarial_tail():
    # 990 fast fires + 10 catastrophic stalls: p999 (rank 999 of 1000)
    # must surface a stall — a reservoir histogram routinely misses the
    # tail; the log buckets cannot
    h = EmissionHistogram()
    h.record(1.0, n=990)
    h.record(10_000.0, n=10)
    assert h.value_at(99.9) >= 10_000.0 * (1.0 - 1.0 / SUBBUCKETS)
    assert h.value_at(99.9) <= h.max
    # all-identical distribution: every percentile is that value's bucket
    h2 = EmissionHistogram()
    h2.record(42.0, n=10_000)
    for pct in (50.0, 95.0, 99.0, 99.9):
        assert h2.value_at(pct) == 42.0  # clamped to observed max


def test_merge_associativity_and_commutativity():
    rng = np.random.default_rng(11)
    chunks = [rng.lognormal(3.0, 2.0, size=200) for _ in range(3)]
    hs = []
    for c in chunks:
        h = EmissionHistogram()
        for v in c:
            h.record(float(v))
        hs.append(h.snapshot())
    direct = EmissionHistogram()
    for c in chunks:
        for v in c:
            direct.record(float(v))
    # (a + b) + c == a + (b + c) == direct recording, bucket-exactly
    ab_c = merge_snapshots([merge_snapshots(hs[:2]), hs[2]])
    a_bc = merge_snapshots([hs[0], merge_snapshots(hs[1:])])
    cba = merge_snapshots(list(reversed(hs)))
    want = direct.snapshot()
    assert ab_c == a_bc == cba == want
    assert ab_c["count"] == 600


def test_snapshot_roundtrip_flat_numeric():
    h = EmissionHistogram()
    for v in (2.0, 30.0, 400.0):
        h.record(v)
    s = h.snapshot()
    # flat numeric dict: survives metrics_snapshot's numeric-only filter
    assert all(isinstance(v, (int, float)) for v in s.values())
    assert is_emission_snapshot(s)
    assert not is_emission_snapshot({"count": 3})     # no buckets
    back = EmissionHistogram.from_snapshot(s)
    assert back.snapshot() == s


def test_int64_sentinel_clamps():
    t = EmissionLatencyTracker("op", clock=lambda: 1000.0)
    # watermark sentinels carry no event-time close: counted, not recorded
    assert t.record_fire(MAX_WATERMARK - 1) is None
    assert t.record_fire(MIN_WATERMARK + 1) is None
    assert t.record_fire(0) is None
    assert t.record_fire("not-a-number") is None
    assert t.sentinel == 3            # the non-numeric fire isn't a fire
    assert t.histogram.count == 0
    assert t.snapshot()["sentinel"] == 3
    # a plausible event time records without overflow even at huge lag
    lat = t.record_fire(1.0)
    assert lat is not None and lat > 0
    assert math.isfinite(t.histogram.max)


def test_watermark_lag_sentinels():
    now = 1_700_000_000_000.0
    assert watermark_lag_ms(MIN_WATERMARK, now) == 0.0
    assert watermark_lag_ms(MAX_WATERMARK, now) == 0.0
    assert watermark_lag_ms(0, now) == 0.0
    assert watermark_lag_ms(None, now) == 0.0
    assert watermark_lag_ms(now - 250.0, now) == 250.0
    assert watermark_lag_ms(now + 10_000.0, now) == 0.0   # never negative


# -- fire-to-resolve stamping --------------------------------------------

def test_record_fire_measures_resolve_not_dispatch():
    clock = [100.0]                   # seconds
    t = EmissionLatencyTracker("w", clock=lambda: clock[0])
    # window closed at event-time 99_000ms with 500ms lateness; the host
    # resolves it at wall 100_000ms -> 500ms emission latency
    lat = t.record_fire(99_000, lateness_ms=500)
    assert lat == 500.0
    # resolving later (deferred readback drained on a later step) grows
    # the measured latency — the stamp is at RESOLVE time
    clock[0] = 101.0
    assert t.record_fire(99_000, lateness_ms=500) == 1500.0


def test_outlier_capture_ring_and_spans():
    clock = [0.0]
    spans = []

    def sink(scope, name, start, end, attrs):
        spans.append((scope, name, start, end, attrs))

    t = EmissionLatencyTracker(
        "w", outlier_pct=99.0, outlier_floor_ms=5.0, ring_size=4,
        min_samples=1, span_sink=sink, span_min_gap_ms=0.0,
        clock=lambda: clock[0])
    # sub-floor latencies never capture
    for i in range(20):
        clock[0] = i * 10.0 + 0.001
        t.record_fire(clock[0] * 1000.0 - 1.0)
    assert t.outliers == [] and spans == []
    # a 50ms stall beats the floor and the p99 threshold
    clock[0] = 300.0
    t.record_fire(clock[0] * 1000.0 - 50.0)
    assert len(t.outliers) == 1
    assert t.outliers[0]["latencyMs"] == 50.0
    [(scope, name, start, end, attrs)] = spans
    assert (scope, name) == (LATENCY_SPAN_SCOPE, LATENCY_SPAN_NAME)
    assert attrs["operator"] == "w" and attrs["latencyMs"] == 50.0
    assert end == 300_000.0
    # the ring stays bounded
    for i in range(10):
        clock[0] = 400.0 + i
        t.record_fire(clock[0] * 1000.0 - 60.0)
    assert len(t.outliers) == 4


def test_outlier_min_samples_gate():
    clock = [10.0]
    t = EmissionLatencyTracker("w", min_samples=16, outlier_floor_ms=5.0,
                               clock=lambda: clock[0])
    for _ in range(15):
        t.record_fire(clock[0] * 1000.0 - 100.0)
    assert t.outliers == []           # still warming up
    t.record_fire(clock[0] * 1000.0 - 100.0)
    assert len(t.outliers) == 1       # 16th fire may capture


def test_outlier_span_liveness_bound():
    # synthetic-epoch job (event time near 1970): the stall span must
    # start no earlier than the tracker's birth / previous resolve, never
    # at the 1970 window close — otherwise attribution degenerates to
    # "whichever control span is longest"
    clock = [500.0]
    spans = []
    t = EmissionLatencyTracker(
        "w", min_samples=1, span_min_gap_ms=0.0, clock=lambda: clock[0],
        span_sink=lambda *a: spans.append(a))
    clock[0] = 500.2
    t.record_fire(12_000)             # window end = 12s after 1970
    [(_s, _n, start, end, _a)] = spans
    assert start >= 500_000.0         # tracker birth wall, not 12_000
    assert end == 500_200.0


# -- stall attribution ----------------------------------------------------

def _span(scope, name, start, end, **attrs):
    return {"scope": scope, "name": name, "start_ts_ms": start,
            "end_ts_ms": end, "attributes": attrs}


def test_stall_attribution_largest_overlap_wins():
    spans = [
        _span("checkpointing", "Checkpoint", 1000.0, 1010.0),
        _span("recovery", "JobRestart", 1005.0, 1095.0),
        _span(LATENCY_SPAN_SCOPE, LATENCY_SPAN_NAME, 1000.0, 1100.0,
              latencyMs=100.0),
    ]
    rep = stall_attribution(spans, slack_ms=0.0)
    assert rep["outliers"] == 1 and rep["unattributed"] == 0
    assert set(rep["attributed"]) == {"recovery.JobRestart"}
    blk = rep["attributed"]["recovery.JobRestart"]
    assert blk["count"] == 1 and blk["maxLatencyMs"] == 100.0


def test_stall_attribution_unattributed_and_slack():
    stall = _span(LATENCY_SPAN_SCOPE, LATENCY_SPAN_NAME, 2000.0, 2100.0)
    far = _span("checkpointing", "Checkpoint", 2140.0, 2150.0)
    assert stall_attribution([stall, far],
                             slack_ms=0.0)["unattributed"] == 1
    # the same control span within the slack window attributes
    assert stall_attribution([stall, far], slack_ms=50.0)["attributed"]


def test_build_latency_report_shape():
    snap = EmissionHistogram()
    snap.record(10.0, n=98)
    snap.record(500.0, n=2)
    metrics = {
        "job.op.win-1.emissionLatencyMs": snap.snapshot(),
        "job.op.win-1.watermarkLagMs": 25.0,
        "job.op.src-0.watermarkLagMs": 75.0,
        "job.op.win-1.numRecordsIn": 100,
    }
    rep = build_latency_report(metrics, [])
    assert rep["samples"] == 100
    assert rep["p99_ms"] >= 500.0 * (1.0 - 1.0 / SUBBUCKETS)
    assert rep["watermarkLagMs"] == 75.0          # MAX across operators
    assert rep["operators"]["win-1"]["watermarkLagMs"] == 25.0
    assert "emissionLatencyMs" in rep["operators"]["win-1"]
    assert "attribution" in rep and rep["attribution"]["outliers"] == 0
    # the job-level emission block is bucket-free (payload hygiene)
    assert not any(k.startswith("b") for k in rep["emission"])


# -- shard-fold + payload-filter registration (the _TIER_GAUGES lesson) ---

def test_latency_gauges_registered_in_fold_and_filters():
    """Every emission-plane leaf the executors register must sit in the
    ONE shared tuple that feeds both the aggregate_shard_metrics fold rule
    and the /jobs/:id/device payload filters — a family missing from
    either silently reads 0/absent at the job level."""
    from flink_tpu.runtime.cluster import (
        _LATENCY_GAUGES,
        _LATENCY_HISTOGRAMS,
        _LATENCY_MAX_GAUGES,
        _shard_combine,
    )

    # the leaves register_metrics/JobRuntime actually register
    assert "emissionLatencyMs" in _LATENCY_HISTOGRAMS
    assert "watermarkLagMs" in _LATENCY_MAX_GAUGES
    assert "p99EmissionLatencyMs" in _LATENCY_MAX_GAUGES
    assert set(_LATENCY_GAUGES) == (
        set(_LATENCY_MAX_GAUGES) | set(_LATENCY_HISTOGRAMS))
    # lag/percentile scalars fold MAX (worst shard), never sum
    for leaf in _LATENCY_MAX_GAUGES:
        assert _shard_combine(f"op.win-1.{leaf}") == "max"
    # ISSUE-18: the latency-mode controller gauges ride the same tuple —
    # omitting any from the fold rule OR either payload filter silently
    # hides a shard's rung/ring state at the job level (the exact
    # _TIER_GAUGES failure class this test exists to pin)
    from flink_tpu.runtime.cluster import _LATENCY_CONTROLLER_GAUGES

    assert set(_LATENCY_CONTROLLER_GAUGES) == {
        "latencyModeActive", "currentBatchRung",
        "inflightDepth", "ladderRecompiles"}
    for leaf in _LATENCY_CONTROLLER_GAUGES:
        assert leaf in _LATENCY_MAX_GAUGES, \
            f"{leaf} missing from the MAX fold family"
        assert leaf in _LATENCY_GAUGES, \
            f"{leaf} missing from the payload-filter family"
        assert _shard_combine(f"op.win-1.{leaf}") == "max"


def test_aggregate_shard_metrics_folds_emission_bucketwise():
    from flink_tpu.runtime.cluster import aggregate_shard_metrics

    h1, h2 = EmissionHistogram(), EmissionHistogram()
    h1.record(4.0, n=50)
    h2.record(900.0, n=50)
    per_shard = {
        0: {"op.win-1.emissionLatencyMs": h1.snapshot(),
            "op.win-1.watermarkLagMs": 10.0,
            "job.p99EmissionLatencyMs": 4.5},
        1: {"op.win-1.emissionLatencyMs": h2.snapshot(),
            "op.win-1.watermarkLagMs": 90.0,
            "job.p99EmissionLatencyMs": 1012.0},
    }
    agg = aggregate_shard_metrics(per_shard)
    merged = agg["op.win-1.emissionLatencyMs"]
    direct = EmissionHistogram()
    direct.record(4.0, n=50)
    direct.record(900.0, n=50)
    # EXACT bucket-wise fold: identical to recording on one shard — the
    # generic dict envelope (sum counts, max percentiles) would report
    # p50 == 900 for this split
    assert merged == direct.snapshot()
    assert merged["count"] == 100
    assert merged["p50"] <= 4.5
    assert agg["op.win-1.watermarkLagMs"] == 90.0
    assert agg["job.p99EmissionLatencyMs"] == 1012.0


# -- end-to-end: deferred-path stamping + the /latency report -------------

def test_windowed_job_records_emission_latency_end_to_end():
    """A windowed job on the MiniCluster path stamps every fired window at
    its host-resolve point and serves the aggregate through
    client.latency_report() (the /jobs/:id/latency payload)."""
    import time as _time

    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.core.watermarks import WatermarkStrategy

    t0 = _time.time() * 1000.0 - 10_000.0     # wall-anchored event time
    env = StreamExecutionEnvironment.get_execution_environment()
    data = [(f"k{i % 4}", 1.0, int(t0 + i * 10)) for i in range(400)]
    stream = env.from_collection(
        data,
        timestamp_fn=lambda x: x[2],
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    sink = (stream.key_by(lambda x: x[0])
            .window(TumblingEventTimeWindows.of(1000))
            .aggregate("count")
            .collect())
    client = env.execute_async("emission-e2e")
    client.wait(60.0)
    assert sum(n for _, n in sink.results) == 400
    rep = client.latency_report()
    # every window that closed inside the run was stamped at resolve; the
    # terminal-watermark flush fires count as sentinel, not latency
    assert rep["samples"] > 0
    assert rep["p99_ms"] >= rep["p50_ms"] > 0
    ops = [op for op in rep["operators"].values()
           if "emissionLatencyMs" in op]
    assert ops and any(op["emissionLatencyMs"]["count"] > 0 for op in ops)


# -- uniform histogram export (Prometheus text) ---------------------------

def test_prometheus_text_renders_emission_snapshot_as_summary():
    from flink_tpu.metrics.registry import MetricRegistry, prometheus_text

    h = EmissionHistogram()
    h.record(10.0, n=999)
    h.record(5000.0)
    reg = MetricRegistry()
    g = reg.group("job", "op", "win-1")
    g.gauge("emissionLatencyMs", h.snapshot)
    g.gauge("watermarkLagMs", lambda: 12.5)
    text = prometheus_text(reg.all_metrics())
    # a dict-valued gauge with a `count` key exports as a summary family —
    # same quantile set as reservoir Histograms, p999 included
    assert "# TYPE job_op_win_1_emissionLatencyMs summary" in text
    assert 'job_op_win_1_emissionLatencyMs{quantile="0.999"}' in text
    assert "job_op_win_1_emissionLatencyMs_count 1000" in text
    assert "job_op_win_1_watermarkLagMs 12.5" in text
