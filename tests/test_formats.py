"""Formats (K2): json lines, csv, avro binary, parquet gating."""

import io

import pytest

from flink_tpu.formats import get_format
from flink_tpu.formats.registry import AvroFormat

ROWS = [
    {"user": "a", "n": 3, "score": 1.5, "ok": True},
    {"user": "b", "n": -7, "score": 0.25, "ok": False},
]


@pytest.mark.parametrize("name", ["json", "csv", "avro"])
def test_roundtrip(name, tmp_path):
    fmt = get_format(name)
    path = str(tmp_path / f"data.{name}")
    fmt.write_file(ROWS, path)
    got = fmt.read_file(path)
    if name == "csv":  # csv loses bool typing: compare loosely
        assert [r["user"] for r in got] == ["a", "b"]
        assert [r["n"] for r in got] == [3, -7]
        assert [r["score"] for r in got] == [1.5, 0.25]
    else:
        assert got == ROWS


def test_avro_nullable_union():
    fmt = AvroFormat(schema={
        "type": "record", "name": "Row",
        "fields": [{"name": "k", "type": "string"},
                   {"name": "v", "type": ["null", "long"]}],
    })
    buf = io.BytesIO()
    fmt.write([{"k": "x", "v": 5}, {"k": "y", "v": None}], buf)
    buf.seek(0)
    assert fmt.read(buf) == [{"k": "x", "v": 5}, {"k": "y", "v": None}]


def test_avro_zigzag_negative_longs():
    fmt = get_format("avro")
    buf = io.BytesIO()
    fmt.write([{"n": -(1 << 40)}], buf)
    buf.seek(0)
    assert fmt.read(buf) == [{"n": -(1 << 40)}]


def test_avro_rejects_foreign_bytes():
    with pytest.raises(ValueError, match="not an avro container"):
        get_format("avro").read(io.BytesIO(b"garbage data here"))


def test_parquet_gated_without_pyarrow():
    try:
        import pyarrow  # noqa: F401

        have = True
    except ImportError:
        have = False
    if have:
        fmt = get_format("parquet")
        buf = io.BytesIO()
        fmt.write(ROWS, buf)
        buf.seek(0)
        assert fmt.read(buf) == ROWS
    else:
        with pytest.raises(ImportError, match="pyarrow"):
            get_format("parquet")


def test_unknown_format_lists_available():
    with pytest.raises(ValueError, match="available"):
        get_format("orc-nope")


def test_formatted_file_source_to_sink_pipeline(tmp_path):
    """End-to-end: avro files -> DataStream -> windowed sum -> json sink."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.formats.file_io import FormattedFileSink, FormattedFileSource

    src_path = str(tmp_path / "in.avro")
    rows = [{"word": w, "n": 1, "ts": 100 * i}
            for i, w in enumerate(["a", "b", "a", "c", "a", "b"] * 5)]
    get_format("avro").write_file(rows, src_path)

    env = StreamExecutionEnvironment.get_execution_environment()
    out_dir = str(tmp_path / "out")
    stream = env.from_source(
        FormattedFileSource([src_path], format="avro", timestamp_fn=lambda r: r["ts"]),
        WatermarkStrategy.for_bounded_out_of_orderness(0),
        "avro-in",
    )
    (stream
     .key_by(lambda r: r["word"])
     .window(TumblingEventTimeWindows.of(1000))
     .aggregate("count")
     .map(lambda kv: {"word": kv[0], "count": kv[1]})
     .sink_to(FormattedFileSink(out_dir, format="json")))
    env.execute("fmt-pipeline")

    import os

    out_rows = []
    for f in sorted(os.listdir(out_dir)):
        out_rows.extend(get_format("json").read_file(os.path.join(out_dir, f)))
    totals = {}
    for r in out_rows:
        totals[r["word"]] = totals.get(r["word"], 0) + r["count"]
    assert totals == {"a": 15, "b": 10, "c": 5}
