"""FusedWindowOperator (the product-path fused driver) parity vs the oracle.

The adapter buffers executor steps into fixed-T superbatches and must keep
exactly the reference WindowOperator's fired (key, window, value) sets
(WindowOperator.java:293-447) under every stream shape that used to crash
the raw planner: wide out-of-order spans, watermark stalls followed by
catch-up jumps, records beyond the slice ring, key-capacity overflow.
"""

import numpy as np
import pytest

from flink_tpu.api.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.core.time import MAX_WATERMARK
from flink_tpu.ops.aggregators import resolve
from flink_tpu.runtime.fused_window_operator import FusedWindowOperator
from flink_tpu.runtime.oracle_window_operator import OracleWindowOperator


def _run_oracle(assigner, agg_name, steps):
    op = OracleWindowOperator(assigner, resolve(agg_name).python_equivalent())
    out = {}
    for keys, vals, ts, wm in steps:
        for i in range(len(ts)):
            v = 1.0 if vals is None else float(vals[i])
            op.process_record(int(keys[i]), v, int(ts[i]))
        if wm is not None:
            op.process_watermark(wm)
    op.process_watermark(MAX_WATERMARK - 1)
    for key, window, value, _ts in op.drain_output():
        out[(key, window.start)] = value
    return out, op.num_late_records_dropped


def _run_fused(assigner, agg_name, steps, **kw):
    op = FusedWindowOperator(assigner, agg_name, **kw)
    out = {}
    for keys, vals, ts, wm in steps:
        v = np.ones(len(ts), np.float32) if vals is None else vals
        op.process_batch(np.asarray(keys), np.asarray(v, np.float32),
                         np.asarray(ts, np.int64))
        if wm is not None:
            op.process_watermark(wm)
        for key, window, value, _ts in op.drain_output():
            out[(key, window.start)] = value
    op.process_watermark(MAX_WATERMARK - 1)
    for key, window, value, _ts in op.drain_output():
        out[(key, window.start)] = value
    return out, op.num_late_records_dropped


def _assert_same(got, gl, expect, el):
    assert gl == el
    assert set(got) == set(expect)
    for k in expect:
        assert got[k] == pytest.approx(expect[k]), k


def _workload(rng, nkeys, nbatches, batch, ooo_ms, rate_ms, start=10_000):
    steps = []
    t = start
    for _ in range(nbatches):
        kid = rng.integers(0, nkeys, batch).astype(np.int64) * 7 + 3  # sparse keys
        base = t + np.sort(rng.integers(0, rate_ms, batch))
        ts = np.maximum(base - rng.integers(0, ooo_ms, batch), 0).astype(np.int64)
        vals = rng.integers(1, 50, batch).astype(np.float32)
        steps.append((kid, vals, ts, int(base[-1]) - ooo_ms))
        t += rate_ms
    return steps


@pytest.mark.parametrize(
    "assigner",
    [
        SlidingEventTimeWindows.of(10_000, 2_000),
        TumblingEventTimeWindows.of(5_000),
    ],
)
@pytest.mark.parametrize("agg", ["count", "sum", "mean"])
def test_fused_operator_parity_random(assigner, agg):
    rng = np.random.default_rng(11)
    steps = _workload(rng, nkeys=17, nbatches=10, batch=64, ooo_ms=900, rate_ms=3_000)
    expect, el = _run_oracle(assigner, agg, steps)
    got, gl = _run_fused(
        assigner, agg, steps,
        key_capacity=8, superbatch_steps=4, nsb=4, chunk=32,
        fires_per_step=2, out_rows=32,
    )
    _assert_same(got, gl, expect, el)


@pytest.mark.parametrize("agg", ["min", "max"])
def test_fused_operator_minmax_parity(agg):
    assigner = SlidingEventTimeWindows.of(8_000, 2_000)
    rng = np.random.default_rng(5)
    steps = _workload(rng, nkeys=9, nbatches=8, batch=48, ooo_ms=700, rate_ms=2_500)
    expect, el = _run_oracle(assigner, agg, steps)
    got, gl = _run_fused(
        assigner, agg, steps,
        key_capacity=16, superbatch_steps=3, nsb=4, chunk=16,
    )
    _assert_same(got, gl, expect, el)


def test_fused_operator_stall_then_catchup():
    """Watermark stalls for many batches, then jumps: dozens of windows fire
    in one advance (>fires_per_step, >out_rows per naive dispatch) — the
    normalizer must stage the advance instead of raising."""
    assigner = SlidingEventTimeWindows.of(10_000, 1_000)
    rng = np.random.default_rng(23)
    steps = []
    t = 10_000
    for i in range(12):
        kid = rng.integers(0, 11, 48).astype(np.int64)
        base = t + np.sort(rng.integers(0, 3_000, 48))
        ts = (base - rng.integers(0, 400, 48)).astype(np.int64)
        # watermark frozen for the first 11 batches, then one huge jump
        wm = 9_000 if i < 11 else int(base[-1])
        steps.append((kid, None, ts, wm))
        t += 3_000
    expect, el = _run_oracle(assigner, "count", steps)
    got, gl = _run_fused(
        assigner, "count", steps,
        key_capacity=11, superbatch_steps=4, nsb=16, chunk=16,
        fires_per_step=2, out_rows=8, num_slices=128,
    )
    _assert_same(got, gl, expect, el)


def test_fused_operator_wide_span_batch_split():
    """One batch spanning far more slices than nsb must be split, not
    rejected."""
    assigner = TumblingEventTimeWindows.of(1_000)
    keys = np.arange(24, dtype=np.int64)
    ts = (np.arange(24, dtype=np.int64) * 900) + 100  # spans ~22 slices
    steps = [(keys, None, ts, int(ts.max()))]
    expect, el = _run_oracle(assigner, "count", steps)
    got, gl = _run_fused(
        assigner, "count", steps,
        key_capacity=32, superbatch_steps=4, nsb=2, chunk=8, num_slices=64,
        fires_per_step=4, out_rows=64,
    )
    _assert_same(got, gl, expect, el)


def test_fused_operator_ring_overflow_heldback():
    """Records too far in the future are held on host and re-injected when
    the purge frontier opens ring space."""
    assigner = TumblingEventTimeWindows.of(1_000)
    steps = [
        # ring S=16 slices; future record at slice 40 cannot fit yet
        (np.array([1, 2]), None, np.array([500, 40_500]), 900),
        (np.array([1]), None, np.array([1_500]), 2_000),
        # advance far enough that slice 40 becomes resident
        (np.array([3]), None, np.array([39_000]), 39_500),
        (np.array([3]), None, np.array([41_000]), 42_000),
    ]
    expect, el = _run_oracle(assigner, "count", steps)
    got, gl = _run_fused(
        assigner, "count", steps,
        key_capacity=8, superbatch_steps=2, nsb=4, chunk=8, num_slices=16,
    )
    _assert_same(got, gl, expect, el)


def test_fused_operator_key_capacity_growth():
    """More distinct keys than the initial capacity: state grows in place."""
    assigner = TumblingEventTimeWindows.of(2_000)
    rng = np.random.default_rng(2)
    steps = _workload(rng, nkeys=50, nbatches=6, batch=64, ooo_ms=300, rate_ms=1_500)
    expect, el = _run_oracle(assigner, "count", steps)
    got, gl = _run_fused(
        assigner, "count", steps,
        key_capacity=4, superbatch_steps=3, nsb=4, chunk=32,
    )
    _assert_same(got, gl, expect, el)


def test_fused_operator_snapshot_restore():
    assigner = SlidingEventTimeWindows.of(4_000, 2_000)
    rng = np.random.default_rng(3)
    steps = _workload(rng, nkeys=7, nbatches=8, batch=32, ooo_ms=500, rate_ms=1_500)

    kw = dict(key_capacity=8, superbatch_steps=3, nsb=4, chunk=16)
    op = FusedWindowOperator(assigner, "count", **kw)
    for keys, vals, ts, wm in steps[:4]:
        op.process_batch(keys, np.ones(len(ts), np.float32), ts)
        op.process_watermark(wm)
    out = {(k, w.start): v for k, w, v, _ in op.drain_output()}
    snap = op.snapshot()
    out.update({(k, w.start): v for k, w, v, _ in op.drain_output()})

    op2 = FusedWindowOperator(assigner, "count", **kw)
    op2.restore(snap)
    for keys, vals, ts, wm in steps[4:]:
        op2.process_batch(keys, np.ones(len(ts), np.float32), ts)
        op2.process_watermark(wm)
    op2.process_watermark(MAX_WATERMARK - 1)
    out.update({(k, w.start): v for k, w, v, _ in op2.drain_output()})

    expect, _ = _run_oracle(assigner, "count", steps)
    assert set(out) == set(expect)
    for k in expect:
        assert out[k] == expect[k]


def test_executor_selects_fused_operator():
    """A DataStream-API eligible sliding event-time aggregate must run on
    FusedWindowOperator (the WindowOperatorBuilder.java:79 swap, now
    pointing at the flagship path), and the whole job must match the
    output of the same job with fusion disabled."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.config import Configuration, ExecutionOptions
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.executor import JobRuntime, WindowStepRunner

    rng = np.random.default_rng(9)
    rows = []
    t = 10_000
    for _ in range(600):
        t += 17
        rows.append((int(rng.integers(0, 5)), float(rng.integers(1, 9)), t))

    def build(conf):
        env = StreamExecutionEnvironment.get_execution_environment(conf)
        sink = (
            env.from_collection(
                rows,
                timestamp_fn=lambda r: r[2],
                watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(100),
            )
            .key_by(lambda r: r[0])
            .window(SlidingEventTimeWindows.of(2_000, 1_000))
            .aggregate("sum", value_fn=lambda r: r[1])
            .collect()
        )
        graph = plan(env._sinks[0])
        runtime = JobRuntime(graph, env.config)
        win = [r for r in runtime.runners if isinstance(r, WindowStepRunner)]
        assert len(win) == 1
        runtime.run()
        return sink.results, win[0].op

    fused_results, fused_op = build(Configuration())
    assert isinstance(fused_op, FusedWindowOperator)

    conf_off = Configuration()
    conf_off.set(ExecutionOptions.FUSED_WINDOWS, False)
    base_results, base_op = build(conf_off)
    assert not isinstance(base_op, FusedWindowOperator)
    assert len(fused_results) > 0

    # compare as multisets of (key, value) pairs — emission order may differ
    assert sorted(map(repr, fused_results)) == sorted(map(repr, base_results))


# ---------------------------------------------------------------------------
# advisor-regression tests (round 2 findings)
# ---------------------------------------------------------------------------

def test_fused_operator_heldback_survive_watermark_jump():
    """A watermark jump past a held-back record's slice must re-inject the
    record while its windows can still fire — not reclassify it as late
    (the reference only drops records late ON ARRIVAL,
    WindowOperator.java:440-446). Regression: advisor r2 high finding."""
    assigner = TumblingEventTimeWindows.of(1_000)
    steps = [
        # ring S=8, nsb=2: slices 12/13 exceed limit 0+8-2=6 and are held
        (np.array([1, 2, 2, 3]), None,
         np.array([500, 12_400, 12_600, 13_100]), 900),
        # jump straight past the held records' windows, no data in between
        (np.array([], np.int64), None, np.array([], np.int64), 40_000),
    ]
    expect, el = _run_oracle(assigner, "count", steps)
    got, gl = _run_fused(
        assigner, "count", steps,
        key_capacity=8, superbatch_steps=2, nsb=2, chunk=8, num_slices=8,
    )
    _assert_same(got, gl, expect, el)


def test_fused_operator_end_of_input_flush_keeps_heldback():
    """Same failure mode via the end-of-input MAX_WATERMARK flush (the
    advance every _run_fused issues last)."""
    assigner = TumblingEventTimeWindows.of(1_000)
    steps = [
        (np.array([1, 2]), None, np.array([500, 20_500]), None),
    ]
    expect, el = _run_oracle(assigner, "count", steps)
    got, gl = _run_fused(
        assigner, "count", steps,
        key_capacity=8, superbatch_steps=2, nsb=2, chunk=8, num_slices=8,
    )
    _assert_same(got, gl, expect, el)


def test_fused_operator_late_plus_future_batch():
    """A batch containing ONLY late rows plus far-future (held-back) rows
    must not crash on the empty on-time remainder. Regression: advisor r2
    medium finding (_append_data empty-reduction)."""
    assigner = TumblingEventTimeWindows.of(1_000)
    steps = [
        (np.array([1]), None, np.array([500]), 2_500),
        # slice 0 is late (min_live=2); slice 30 is beyond the ring limit
        (np.array([2, 3]), None, np.array([100, 30_000]), 31_000),
    ]
    expect, el = _run_oracle(assigner, "count", steps)
    got, gl = _run_fused(
        assigner, "count", steps,
        key_capacity=8, superbatch_steps=2, nsb=2, chunk=8, num_slices=8,
    )
    _assert_same(got, gl, expect, el)


def test_fused_operator_pad_watermark_after_early_cut():
    """Pads appended after an out_rows early cut must carry the group's
    last real step watermark: a pad stamped with the normalizer's committed
    watermark performs the whole staged jump in one step and blows
    fires_per_step. Regression: advisor r2 medium finding."""
    assigner = TumblingEventTimeWindows.of(1_000)
    keys = np.arange(16, dtype=np.int64)
    ts = np.arange(16, dtype=np.int64) * 1_000 + 10
    steps = [
        (keys[:8], None, ts[:8], None),
        (keys[8:], None, ts[8:], None),
        (np.array([], np.int64), None, np.array([], np.int64), 20_000),
    ]
    expect, el = _run_oracle(assigner, "count", steps)
    got, gl = _run_fused(
        assigner, "count", steps,
        key_capacity=32, superbatch_steps=8, nsb=8, chunk=8, num_slices=32,
        fires_per_step=2, out_rows=4,
    )
    _assert_same(got, gl, expect, el)


def test_fused_pipeline_inverted_skew_clear_error():
    """Pre-watermark inverted skew (a batch >= num_slices BELOW resident
    data) is a configuration limit the hold-back cannot absorb; it must
    surface as an actionable error naming the config knob."""
    assigner = TumblingEventTimeWindows.of(1_000)
    op = FusedWindowOperator(
        assigner, "count",
        key_capacity=8, superbatch_steps=2, nsb=2, chunk=8, num_slices=8,
    )
    op.process_batch(np.array([1]), np.ones(1, np.float32),
                     np.array([100_000], np.int64))
    with pytest.raises(ValueError, match="num-slices"):
        op.process_batch(np.array([2]), np.ones(1, np.float32),
                         np.array([500], np.int64))
        op.flush_all()
