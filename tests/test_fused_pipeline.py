"""MXU matmul-histogram kernels + FusedWindowPipeline parity vs the oracle.

The oracle is OracleWindowOperator — a per-record Python port of the
reference's WindowOperator semantics (WindowOperator.java:293-575); the
fused pipeline must produce identical fired (key, window, value) sets.
"""

import numpy as np
import pytest

from flink_tpu.api.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.ops import matmul_hist
from flink_tpu.runtime.fused_window_pipeline import FusedWindowPipeline
from flink_tpu.runtime.oracle_window_operator import OracleWindowOperator


# ---------------------------------------------------------------------------
# kernel-level: histograms vs np.bincount
# ---------------------------------------------------------------------------

def test_count_hist_matches_bincount():
    rng = np.random.default_rng(0)
    nseg = 1000
    idx = rng.integers(0, nseg, 4096).astype(np.int32)
    got = np.asarray(matmul_hist.count_hist(idx, nseg, chunk=512))
    expect = np.bincount(idx, minlength=nseg)
    np.testing.assert_array_equal(got, expect)


def test_count_hist_drops_invalid_lanes():
    idx = np.array([-1, 3, 3, -1, 7, 2000000], dtype=np.int32)
    (idx_p,), _ = matmul_hist.pad_batch((idx,), len(idx), 8)
    got = np.asarray(matmul_hist.count_hist(idx_p, 10, chunk=8))
    expect = np.zeros(10, np.int64)
    expect[3] = 2
    expect[7] = 1
    np.testing.assert_array_equal(got, expect)


@pytest.mark.parametrize("exact", [True, False])
def test_weighted_hist(exact):
    rng = np.random.default_rng(1)
    nseg = 300
    idx = rng.integers(0, nseg, 2048).astype(np.int32)
    vals = rng.normal(size=2048).astype(np.float32)
    got = np.asarray(matmul_hist.weighted_hist(idx, vals, nseg, chunk=256, exact=exact))
    expect = np.bincount(idx, weights=vals.astype(np.float64), minlength=nseg)
    tol = 1e-4 if exact else 8e-2
    np.testing.assert_allclose(got, expect, rtol=tol, atol=tol)


def test_weighted_hist_exact_integer_values():
    # integer-valued f32 sums must be bit-exact through the split-float path
    rng = np.random.default_rng(2)
    idx = rng.integers(0, 64, 1024).astype(np.int32)
    vals = rng.integers(0, 1000, 1024).astype(np.float32)
    got = np.asarray(matmul_hist.weighted_hist(idx, vals, 64, chunk=256, exact=True))
    expect = np.bincount(idx, weights=vals, minlength=64).astype(np.float32)
    np.testing.assert_array_equal(got, expect)


# ---------------------------------------------------------------------------
# pipeline parity vs oracle
# ---------------------------------------------------------------------------

def _run_oracle(assigner, agg_name, batches, wms):
    from flink_tpu.ops.aggregators import resolve

    op = OracleWindowOperator(assigner, resolve(agg_name).python_equivalent())
    out = {}
    for (kid, vals, ts), wm in zip(batches, wms):
        for i in range(len(ts)):
            v = 1.0 if vals is None else float(vals[i])
            op.process_record(int(kid[i]), v, int(ts[i]))
        op.process_watermark(wm)
    for key, window, value, _ts in op.drain_output():
        out[(key, window.start)] = value
    return out, op.num_late_records_dropped


def _run_fused(assigner, agg_name, batches, wms, **kw):
    pipe = FusedWindowPipeline(assigner, agg_name, **kw)
    out = {}
    half = len(batches) // 2 or 1
    for lo in range(0, len(batches), half):
        for window, counts, fields in pipe.process_superbatch(
            batches[lo : lo + half], wms[lo : lo + half]
        ):
            live = np.flatnonzero(counts > 0)
            for k in live:
                if agg_name == "count":
                    out[(int(k), window.start)] = int(counts[k])
                else:
                    out[(int(k), window.start)] = float(fields["sum"][k])
    return out, pipe.num_late_records_dropped


def _workload(rng, nkeys, nbatches, batch, ooo_ms, rate_ms):
    batches, wms = [], []
    t = 10_000
    for _ in range(nbatches):
        kid = rng.integers(0, nkeys, batch).astype(np.int32)
        base = t + np.sort(rng.integers(0, rate_ms, batch))
        ts = np.maximum(base - rng.integers(0, ooo_ms, batch), 0).astype(np.int64)
        vals = rng.integers(1, 50, batch).astype(np.float32)
        batches.append((kid, vals, ts))
        wms.append(int(base[-1]) - ooo_ms)
        t += rate_ms
    return batches, wms


@pytest.mark.parametrize(
    "assigner",
    [
        SlidingEventTimeWindows.of(10_000, 2_000),
        TumblingEventTimeWindows.of(5_000),
    ],
)
@pytest.mark.parametrize("agg", ["count", "sum"])
def test_fused_parity_random(assigner, agg):
    rng = np.random.default_rng(7)
    batches, wms = _workload(rng, nkeys=13, nbatches=8, batch=96, ooo_ms=900, rate_ms=4_000)
    expect, late_o = _run_oracle(assigner, agg, batches, wms)
    got, late_f = _run_fused(
        assigner, agg, batches, wms,
        key_capacity=13, nsb=8, chunk=32, fires_per_step=8, out_rows=64,
    )
    assert late_f == late_o
    assert set(got) == set(expect)
    for k in expect:
        assert got[k] == pytest.approx(expect[k]), k


def test_fused_late_records_dropped():
    assigner = TumblingEventTimeWindows.of(1_000)
    pipe = FusedWindowPipeline(assigner, "count", key_capacity=4, nsb=4, chunk=8)
    kid = np.zeros(8, np.int32)
    ts = np.full(8, 5_500, np.int64)
    res = pipe.process_superbatch([(kid, None, ts)], [7_999])
    assert [r[0].start for r in res] == [5_000]
    # watermark is now 7999 >= window-end 7000 - 1: slice 5 is expired
    res2 = pipe.process_superbatch([(kid, None, np.full(8, 5_600, np.int64))], [8_000])
    assert res2 == []
    assert pipe.num_late_records_dropped == 8


def test_fused_snapshot_restore_roundtrip():
    assigner = SlidingEventTimeWindows.of(4_000, 2_000)
    rng = np.random.default_rng(3)
    batches, wms = _workload(rng, nkeys=5, nbatches=6, batch=32, ooo_ms=500, rate_ms=1_500)

    pipe = FusedWindowPipeline(assigner, "count", key_capacity=5, nsb=4, chunk=16,
                               fires_per_step=4)
    out_a = list(pipe.process_superbatch(batches[:3], wms[:3]))
    snap = pipe.snapshot()

    pipe2 = FusedWindowPipeline(assigner, "count", key_capacity=5, nsb=4, chunk=16,
                                fires_per_step=4)
    pipe2.restore(snap)
    out_b = pipe2.process_superbatch(batches[3:], wms[3:])
    out_b2 = pipe.process_superbatch(batches[3:], wms[3:])
    assert [(w.start, list(c)) for w, c, _ in out_b] == [
        (w.start, list(c)) for w, c, _ in out_b2
    ]
