"""Global-window superscan (ISSUE-14): keyed-partial → cross-segment fold.

The Q7-shaped laggard path: per-window GLOBAL aggregates hold a [S] slice
ring of partials instead of [K, S] keyed state; each batch folds to NSB
per-rel-slice partials (no scatter, no one-hot matrices) and a window
fire folds its slice run into ONE scalar. These tests pin:

- fold exactness vs numpy for every scatter kind, including UNBOUNDED
  max/min (which have no keyed matmul form and previously had no fused
  device path at all);
- parity of the XLA scan form and the pallas kernel (interpret mode)
  against the keyed pipeline folded over keys — the global result must
  equal max/min/sum-over-keys of the keyed result by construction;
- staged-input interchangeability: the global pipeline consumes the SAME
  `idx = kid * NSB + srel` streams the keyed superscan stages;
- snapshot/restore mid-stream.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
from flink_tpu.ops.segment_ops import bounded_segment_fold
from flink_tpu.runtime.fused_window_pipeline import (
    FusedGlobalWindowPipeline,
    FusedWindowPipeline,
)


def test_bounded_segment_fold_vs_numpy():
    rng = np.random.RandomState(0)
    vals = rng.randn(4096).astype(np.float32) * 100
    seg = rng.randint(-1, 4, size=4096).astype(np.int32)   # -1 drops
    for op, ident, np_red in (("add", 0.0, np.sum),
                              ("min", np.finfo(np.float32).max, np.min),
                              ("max", np.finfo(np.float32).min, np.max)):
        got = np.asarray(bounded_segment_fold(vals, jnp.asarray(seg), 4,
                                              op, ident))
        for s in range(4):
            sel = vals[seg == s]
            expect = np_red(sel) if len(sel) else ident
            assert abs(float(got[s]) - float(expect)) < 1e-3, (op, s)


def _batches(rng, T, B, t0, nkeys=64):
    out, wms = [], []
    for t in range(T):
        keys = rng.randint(0, nkeys, size=B).astype(np.int32)
        ts = (t0 + t) * 1000 + rng.randint(0, 1000, size=B).astype(np.int64)
        vals = rng.randint(0, 250, size=B).astype(np.float32)
        out.append((keys, vals, ts))
        wms.append((t0 + t + 1) * 1000 - 500)
    return out, wms


def _keyed_fold(pipe, c, f, scatter):
    c = np.asarray(c)
    live = c > 0
    folded = {}
    for name, col in f.items():
        col = np.asarray(col)
        if scatter == "add":
            folded[name] = float(col[live].sum())
        elif scatter == "min":
            folded[name] = float(col[live].min())
        else:
            folded[name] = float(col[live].max())
    return int(c.sum()), folded


@pytest.mark.parametrize("agg,scatter", [
    ("max", "max"), ("min", "min"), ("sum", "add"), ("count", "add"),
])
@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_global_matches_keyed_fold(agg, scatter, backend):
    """Global pipeline == keyed pipeline folded over keys, both backends.
    Unbounded max/min run on the global path without any domain_bits."""
    assigner = SlidingEventTimeWindows.of(10_000, 2_000)
    gp = FusedGlobalWindowPipeline(
        assigner, agg, num_slices=16, nsb=4, chunk=1024,
        backend=backend, pallas_interpret=(backend == "pallas"))
    kp = FusedWindowPipeline(assigner, agg, key_capacity=64, num_slices=16,
                             nsb=4, backend="xla", chunk=1024)
    rng = np.random.RandomState(7)
    state = rng.get_state()
    got, ref = {}, {}
    for blk in range(4):
        b, wms = _batches(rng, 6, 1024, blk * 6)
        for w, c, f in gp.process_superbatch(b, wms):
            got[w.start] = (int(c),
                            {k: float(v) for k, v in f.items()})
    rng.set_state(state)
    for blk in range(4):
        b, wms = _batches(rng, 6, 1024, blk * 6)
        for w, c, f in kp.process_superbatch(b, wms):
            cnt, folded = _keyed_fold(kp, c, f, scatter)
            ref[w.start] = (cnt, folded)
    assert len(got) > 3
    assert got.keys() == ref.keys()
    for k in got:
        assert got[k][0] == ref[k][0], f"count mismatch at {k}"
        for name in got[k][1]:
            assert abs(got[k][1][name] - ref[k][1][name]) < 1e-3, (k, name)


def test_global_snapshot_restore_mid_stream():
    assigner = SlidingEventTimeWindows.of(10_000, 2_000)

    def run(restore_at=None):
        gp = FusedGlobalWindowPipeline(assigner, "max", num_slices=16,
                                       nsb=4, chunk=1024, backend="xla")
        rng = np.random.RandomState(3)
        got = {}
        for blk in range(4):
            b, wms = _batches(rng, 6, 1024, blk * 6)
            for w, c, f in gp.process_superbatch(b, wms):
                got[w.start] = (int(c), float(f["max"]))
            if restore_at == blk:
                snap = gp.snapshot()
                gp2 = FusedGlobalWindowPipeline(
                    assigner, "max", num_slices=16, nsb=4, chunk=1024,
                    backend="xla")
                gp2.restore(snap)
                gp = gp2
        return got

    assert run(restore_at=1) == run()


def test_global_phase_counters_count_and_preserve_results():
    """attach_device_stats(phase_counters=True) threads the ingest/fire/
    purge counters through the global scan carry (the keyed pipeline's
    contract): totals come back nonzero on the planner's phase_totals,
    and turning the counters on never changes a result."""
    assigner = SlidingEventTimeWindows.of(10_000, 2_000)

    def run(phases):
        gp = FusedGlobalWindowPipeline(assigner, "max", num_slices=16,
                                       nsb=4, chunk=1024, backend="xla")
        if phases:
            gp.attach_device_stats(None, phase_counters=True)
        rng = np.random.RandomState(11)
        got = {}
        for blk in range(3):
            b, wms = _batches(rng, 6, 1024, blk * 6)
            for w, c, f in gp.process_superbatch(b, wms):
                got[w.start] = (int(c), float(f["max"]))
        return got, np.asarray(gp.phase_totals)

    got_on, totals_on = run(True)
    got_off, totals_off = run(False)
    assert got_on == got_off
    assert totals_on[0] == 3 * 6 * 1024          # every record ingested
    assert totals_on[1] > 0                      # fires executed
    assert np.all(totals_off == 0)               # off: counters never run


def test_global_scalar_readback_shape():
    """Fire rows are scalars, not [K] rows — the readback the Q7 rewrite
    exists to shrink."""
    assigner = SlidingEventTimeWindows.of(10_000, 10_000)
    gp = FusedGlobalWindowPipeline(assigner, "max", num_slices=8, nsb=4,
                                   chunk=1024, backend="xla")
    rng = np.random.RandomState(0)
    b, wms = _batches(rng, 12, 1024, 0)
    fires = gp.process_superbatch(b, wms)
    assert len(fires) > 0
    for _w, c, f in fires:
        assert np.ndim(c) == 0
        assert all(np.ndim(v) == 0 for v in f.values())
