"""Kubernetes Lease leader election against a fake apiserver implementing
coordination.k8s.io/v1 verbs with resourceVersion optimistic concurrency."""

import threading
import time

import pytest

from flink_tpu.runtime.ha_kubernetes import (
    KubernetesLeaderElection,
    LeaseApi,
    LeaseConflict,
)


class FakeLeaseApi(LeaseApi):
    """In-process apiserver: get/create/replace with 404/409 semantics."""

    def __init__(self):
        self._leases = {}
        self._lock = threading.Lock()

    def get_lease(self, namespace, name):
        with self._lock:
            key = (namespace, name)
            if key not in self._leases:
                raise KeyError(name)
            import copy

            return copy.deepcopy(self._leases[key])

    def create_lease(self, namespace, name, body):
        with self._lock:
            key = (namespace, name)
            if key in self._leases:
                raise LeaseConflict(name)
            body = dict(body)
            body.setdefault("metadata", {})["resourceVersion"] = "1"
            self._leases[key] = body
            return body

    def replace_lease(self, namespace, name, body):
        with self._lock:
            key = (namespace, name)
            if key not in self._leases:
                raise KeyError(name)
            cur_rv = self._leases[key]["metadata"]["resourceVersion"]
            if body.get("metadata", {}).get("resourceVersion") != cur_rv:
                raise LeaseConflict(name)
            body = dict(body)
            body["metadata"]["resourceVersion"] = str(int(cur_rv) + 1)
            self._leases[key] = body
            return body


def _wait(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_single_contender_acquires_and_renews():
    api = FakeLeaseApi()
    grants = []
    e = KubernetesLeaderElection(
        api, "flink", "jm-leader", "a", address="host-a:6123",
        renew_interval=0.05, lease_duration=1.0,
        on_grant=lambda: grants.append(1),
    )
    assert _wait(lambda: e.is_leader)
    assert grants == [1]
    assert e.current_leader() == {"leader_id": "a", "address": "host-a:6123"}
    rv0 = api.get_lease("flink", "jm-leader")["metadata"]["resourceVersion"]
    time.sleep(0.2)
    rv1 = api.get_lease("flink", "jm-leader")["metadata"]["resourceVersion"]
    assert int(rv1) > int(rv0)   # renewals bump resourceVersion
    e.stop()


def test_failover_to_second_contender():
    api = FakeLeaseApi()
    a = KubernetesLeaderElection(
        api, "flink", "jm-leader", "a", renew_interval=0.05,
        lease_duration=1.0)
    assert _wait(lambda: a.is_leader)
    revokes = []
    b = KubernetesLeaderElection(
        api, "flink", "jm-leader", "b", renew_interval=0.05,
        lease_duration=1.0, on_revoke=lambda: revokes.append(1))
    time.sleep(0.3)
    assert a.is_leader and not b.is_leader   # holder keeps the lease

    a.stop(release=False)                    # crash: no release, lease decays
    assert _wait(lambda: b.is_leader, timeout=5.0)
    assert api.get_lease("flink", "jm-leader")["spec"]["holderIdentity"] == "b"
    b.stop()


def test_clean_release_hands_over_fast():
    api = FakeLeaseApi()
    a = KubernetesLeaderElection(
        api, "flink", "jm-leader", "a", renew_interval=0.05,
        lease_duration=5.0)
    assert _wait(lambda: a.is_leader)
    a.stop(release=True)                     # zeroed renewTime = expired
    b = KubernetesLeaderElection(
        api, "flink", "jm-leader", "b", renew_interval=0.05,
        lease_duration=5.0)
    assert _wait(lambda: b.is_leader, timeout=2.0)
    b.stop()


def test_conflict_loser_does_not_become_leader():
    api = FakeLeaseApi()

    class RacingApi(FakeLeaseApi):
        """Every replace loses the race once: inject a conflicting bump."""

        def __init__(self):
            super().__init__()
            self.injected = 0

        def replace_lease(self, namespace, name, body):
            if self.injected < 3:
                self.injected += 1
                with self._lock:
                    cur = self._leases[(namespace, name)]
                    cur["metadata"]["resourceVersion"] = str(
                        int(cur["metadata"]["resourceVersion"]) + 1)
                raise LeaseConflict(name)
            return super().replace_lease(namespace, name, body)

    rapi = RacingApi()
    rapi.create_lease("flink", "jm-leader", {
        "spec": {"holderIdentity": "other",
                 "leaseDurationSeconds": 0,
                 "renewTime": "1970-01-01T00:00:00.000000Z"},
        "metadata": {},
    })
    e = KubernetesLeaderElection(
        rapi, "flink", "jm-leader", "x", renew_interval=0.05,
        lease_duration=1.0)
    time.sleep(0.12)
    # while conflicts are injected the contender must not claim leadership
    assert rapi.injected >= 1
    assert _wait(lambda: e.is_leader, timeout=3.0)  # wins once races stop
    e.stop()
