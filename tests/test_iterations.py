"""Stream iterations: feedback edges on the stepped executor.

Reference semantics under parity test: DataStream.iterate/closeWith
(flink-runtime .../streaming/api/datastream/IterativeStream.java,
runtime StreamIterationHead/Tail in .../streaming/runtime/tasks/):
records fed back re-enter the loop body; watermarks never cross the
feedback edge; bounded jobs terminate when feedback quiesces.
"""

import numpy as np
import pytest

from flink_tpu.api.datastream import StreamExecutionEnvironment
from flink_tpu.core.watermarks import WatermarkStrategy


def test_collatz_iteration_reaches_one():
    """Classic loop: every value iterates x -> x/2 | 3x+1 until 1; the exit
    stream must see exactly one 1 per input element."""
    env = StreamExecutionEnvironment.get_execution_environment()
    src = env.from_collection([7, 12, 27, 1, 6])

    it = src.iterate()
    body = it.map(lambda x: x // 2 if x % 2 == 0 else 3 * x + 1)
    it.close_with(body.filter(lambda x: x != 1))
    done = body.filter(lambda x: x == 1).collect()

    env.execute("collatz")
    assert done.results == [1] * 5


def test_iteration_decrement_counts_rounds():
    """x enters at n and is fed back n times -> the exit sees one 0 per
    input, and the head re-injected sum(values) feedback records total."""
    env = StreamExecutionEnvironment.get_execution_environment()
    src = env.from_collection([3, 1, 4])

    it = src.iterate()
    body = it.map(lambda x: x - 1)
    it.close_with(body.filter(lambda x: x > 0))
    out = body.filter(lambda x: x <= 0).collect()

    env.execute("decrement")
    assert sorted(out.results) == [0, 0, 0]


def test_iteration_head_passthrough_also_reaches_sink():
    """The head stream itself (initial + feedback records) is observable:
    sum of everything passing the head = initial values + all feedback."""
    env = StreamExecutionEnvironment.get_execution_environment()
    src = env.from_collection([2])

    it = src.iterate()
    seen = it.collect()                     # taps the head's output
    body = it.map(lambda x: x - 1)
    it.close_with(body.filter(lambda x: x > 0))

    env.execute("tap")
    # head passes 2, then feedback 1 -> [2, 1]
    assert sorted(seen.results) == [1, 2]


def test_iteration_max_rounds_guard():
    env = StreamExecutionEnvironment.get_execution_environment()
    src = env.from_collection([1])

    it = src.iterate(max_rounds=50)
    body = it.map(lambda x: x)              # never converges
    it.close_with(body)
    body.collect()

    with pytest.raises(RuntimeError, match="max_rounds"):
        env.execute("diverges")


def _run_iter_window(watermark_strategy):
    """Loop whose exits feed a tumbling window; returns the fired counts."""
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows

    env = StreamExecutionEnvironment.get_execution_environment()
    src = env.from_collection(
        [(1, 500), (2, 1500), (3, 2500)],
        timestamp_fn=lambda v: v[1],
        watermark_strategy=watermark_strategy,
    )
    it = src.iterate()
    body = it.map(lambda v: (v[0] - 1, v[1]))
    it.close_with(body.filter(lambda v: v[0] > 0))
    out = (
        body.filter(lambda v: v[0] <= 0)
        .key_by(lambda v: 0)
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .collect()
    )
    env.execute("iter-window")
    return sorted((k, int(c)) for (k, c) in out.results)


def test_iteration_window_fires_at_end_without_watermarks():
    """Without a source watermark strategy every exit record is on time and
    the end-of-input flush (held by the head until quiescence) fires one
    window per input's original 1s bucket."""
    assert _run_iter_window(None) == [(0, 1), (0, 1), (0, 1)]


def test_iteration_watermarks_do_not_cross_feedback():
    """With monotonic source watermarks the feedback edge still emits NO
    watermarks (reference contract), so a record that re-enters after the
    source watermark passed its bucket is dropped as late on arrival —
    exactly the reference's iteration/lateness interaction: (2,1500) exits
    on round 2 when the watermark is already 2499 (its [1000,2000) bucket
    closed), while (1,500) exits in-batch and (3,2500)'s bucket is still
    open at the final flush."""
    assert _run_iter_window(
        WatermarkStrategy.for_monotonous_timestamps()
    ) == [(0, 1), (0, 1)]


def test_iteration_close_with_foreign_head_rejected():
    env = StreamExecutionEnvironment.get_execution_environment()
    a = env.from_collection([1]).iterate()
    b = env.from_collection([2])
    a.close_with(b.map(lambda x: x))
    b.collect()          # job that plans the tail but NOT a's head
    env._sinks = [env._sinks[-1]]
    with pytest.raises(ValueError, match="iteration tail"):
        env.execute("foreign")


def test_iteration_feedback_rides_checkpoints():
    """Pending feedback is operator state: snapshot mid-flight, restore into
    a fresh runtime, finish the run — nothing lost, nothing duplicated."""
    from flink_tpu.config import Configuration
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.executor import JobRuntime, IterationHeadRunner

    env = StreamExecutionEnvironment.get_execution_environment()
    src = env.from_collection([5])
    it = src.iterate()
    body = it.map(lambda x: x - 1)
    it.close_with(body.filter(lambda x: x > 0))
    out = body.filter(lambda x: x <= 0).collect()

    graph = plan(env._sinks + env._roots)
    rt = JobRuntime(graph, Configuration())
    head = rt.iteration_heads[0]
    head.enqueue_feedback(np.array([9], dtype=object), np.array([0]))
    snap = rt.capture()

    rt2 = JobRuntime(graph, Configuration())
    rt2.restore(snap)
    h2 = rt2.iteration_heads[0]
    assert h2.has_feedback()
    (v, ts) = h2._feedback[0]
    assert list(v) == [9]
