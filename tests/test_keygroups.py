"""Key-group assignment parity tests.

Expected values in `test_murmur_reference_vectors` were computed by executing
the reference algorithm's semantics (MathUtils.murmurHash /
KeyGroupRangeAssignment) independently — they pin the exact bit-level contract.
"""

import numpy as np
import pytest

from flink_tpu.core.keygroups import (
    KeyGroupRange,
    assign_to_key_group,
    compute_key_group_for_key_hash,
    java_hash_int,
    java_hash_string,
    key_group_range_for_operator,
    key_groups_for_hashes,
    murmur_finalize,
    murmur_finalize_np,
    operator_index_for_key_group,
    shard_for_key_groups_np,
    key_hash,
)


def _java_murmur_oracle(code: int) -> int:
    """Straight-line reimplementation of MathUtils.murmurHash for cross-check,
    using explicit 32-bit two's-complement emulation."""
    def i32(x):
        x &= 0xFFFFFFFF
        return x - (1 << 32) if x >= (1 << 31) else x

    def rotl(x, n):
        x &= 0xFFFFFFFF
        return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF

    c = code & 0xFFFFFFFF
    c = (c * 0xCC9E2D51) & 0xFFFFFFFF
    c = rotl(c, 15)
    c = (c * 0x1B873593) & 0xFFFFFFFF
    c = rotl(c, 13)
    c = (c * 5 + 0xE6546B64) & 0xFFFFFFFF
    c ^= 4
    c ^= c >> 16
    c = (c * 0x85EBCA6B) & 0xFFFFFFFF
    c ^= c >> 13
    c = (c * 0xC2B2AE35) & 0xFFFFFFFF
    c ^= c >> 16
    s = i32(c)
    if s >= 0:
        return s
    return -s if s != -(1 << 31) else 0


@pytest.mark.parametrize("code", [0, 1, -1, 42, 12345, -987654, 2**31 - 1, -(2**31), 0xDEADBEEF - (1 << 32)])
def test_murmur_matches_oracle(code):
    assert murmur_finalize(code) == _java_murmur_oracle(code)


def test_murmur_vectorized_matches_scalar():
    rng = np.random.default_rng(0)
    codes = rng.integers(-(2**31), 2**31, size=4096, dtype=np.int64).astype(np.int32)
    vec = murmur_finalize_np(codes)
    for c, v in zip(codes.tolist()[:512], vec.tolist()[:512]):
        assert murmur_finalize(c) == v
    assert (vec >= 0).all()


def test_java_string_hash():
    # Values verifiable against java.lang.String#hashCode by construction:
    # h = h*31 + ord(c), int32 wraparound.
    assert java_hash_string("") == 0
    assert java_hash_string("a") == 97
    assert java_hash_string("ab") == 97 * 31 + 98
    # wraparound case
    s = "some-moderately-long-key-string-for-wraparound"
    h = 0
    for ch in s:
        h = (h * 31 + ord(ch)) & 0xFFFFFFFF
    if h >= 1 << 31:
        h -= 1 << 32
    assert java_hash_string(s) == h


def test_java_int_hash():
    assert java_hash_int(5) == 5
    assert java_hash_int(-5) == -5
    v = 123456789012345
    assert java_hash_int(v) == _fold64(v)


def _fold64(v):
    v64 = v & 0xFFFFFFFFFFFFFFFF
    x = (v64 ^ (v64 >> 32)) & 0xFFFFFFFF
    return x - (1 << 32) if x >= (1 << 31) else x


def test_key_group_in_range():
    for key in ["user-1", "user-2", 7, 123456789012345, ("a", 3)]:
        kg = assign_to_key_group(key, 128)
        assert 0 <= kg < 128


def test_key_group_batch_matches_scalar():
    keys = [f"key-{i}" for i in range(1000)]
    hashes = np.array([key_hash(k) for k in keys], dtype=np.int32)
    batch = key_groups_for_hashes(hashes, 128)
    for k, kg in zip(keys, batch.tolist()):
        assert assign_to_key_group(k, 128) == kg


def test_key_group_ranges_partition_exactly():
    """Ranges for all operator indices must partition [0, max) disjointly —
    the invariant behind rescaling (KeyGroupRangeAssignment.java:93-106)."""
    for max_par in (1, 2, 7, 128, 32768):
        for par in (1, 2, 3, 5, max_par):
            if par > max_par:
                continue
            seen = []
            for idx in range(par):
                r = key_group_range_for_operator(max_par, par, idx)
                seen.extend(list(r))
                # every key group in the range maps back to this operator
                for kg in (r.start, r.end):
                    assert operator_index_for_key_group(max_par, par, kg) == idx
            assert seen == list(range(max_par))


def test_shard_for_key_groups_vectorized():
    max_par, par = 128, 8
    kgs = np.arange(max_par, dtype=np.int32)
    shards = shard_for_key_groups_np(kgs, max_par, par)
    for kg in range(max_par):
        assert shards[kg] == operator_index_for_key_group(max_par, par, kg)


def test_range_len_and_contains():
    r = KeyGroupRange(3, 10)
    assert len(r) == 8
    assert r.contains(3) and r.contains(10) and not r.contains(11)


def test_parallelism_exceeds_max_raises():
    with pytest.raises(ValueError):
        key_group_range_for_operator(4, 8, 0)
