"""Latency-mode execution (ISSUE-18), policy in isolation and at the
operator: the pow2 rung ladder, the windowed-rate controller's
warm-up / hysteresis / min-dwell / spike-escalation discipline, byte
parity of every rung geometry against the full-span run, the bounded
in-flight dispatch ring draining at barriers, and the flag-off identity
guarantee (no controller, depth-1 ring, no donation, no readback split).

Controller tests drive an injected clock — policy decisions must be a
pure function of (samples, now), never of real scheduler timing.
"""

import numpy as np
import pytest

from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
from flink_tpu.core.time import MAX_WATERMARK
from flink_tpu.runtime.fused_window_operator import FusedWindowOperator
from flink_tpu.scheduler.latency_controller import (
    LatencySpec,
    SuperbatchController,
    build_rung_ladder,
)


# ---------------------------------------------------------------------------
# the rung ladder
# ---------------------------------------------------------------------------

def test_rung_ladder_pow2_monotone_full_span_top():
    # the full span is ALWAYS the top rung, even when it is not pow2 —
    # it is the one geometry the throughput path compiles anyway
    assert build_rung_ladder(2, 48) == (2, 4, 8, 16, 32, 48)
    assert build_rung_ladder(2, 32) == (2, 4, 8, 16, 32)
    assert build_rung_ladder(3, 32) == (4, 8, 16, 32)   # floor snaps up to pow2
    assert build_rung_ladder(2, 2) == (2,)
    assert build_rung_ladder(5, 4) == (4,)              # floor clamped to full
    for floor, full in ((1, 1), (2, 7), (2, 64), (4, 100)):
        ladder = build_rung_ladder(floor, full)
        assert ladder[-1] == full
        assert all(a < b for a, b in zip(ladder, ladder[1:])), \
            f"ladder not strictly increasing: {ladder}"
        assert all(r == full or (r & (r - 1)) == 0 for r in ladder), \
            f"non-pow2 intermediate rung: {ladder}"


# ---------------------------------------------------------------------------
# controller policy (injected clock — pure decisions)
# ---------------------------------------------------------------------------

def _ctrl(**kw):
    t = [0.0]
    defaults = dict(full_steps=32, target_ms=100, floor_steps=2,
                    min_dwell_ms=500, hysteresis_pct=25,
                    clock=lambda: t[0])
    defaults.update(kw)
    return SuperbatchController(**defaults), t


def _feed(c, t, n, *, per_obs, dt):
    for _ in range(n):
        c.observe(per_obs, now=t[0])
        t[0] += dt


def test_warm_up_holds_the_full_span():
    c, t = _ctrl()
    assert c.steps() == 32                  # cold start = throughput geometry
    _feed(c, t, 2, per_obs=1, dt=0.1)       # below min_samples
    assert c.step_rate() is None
    assert c.steps() == 32


def test_adapts_down_at_light_load():
    c, t = _ctrl()
    # 100 steps/s x 100 ms target = 10-step budget -> rung 8
    _feed(c, t, 6, per_obs=10, dt=0.1)
    assert c.step_rate() == pytest.approx(100.0, rel=0.01)
    assert c.steps() == 8


def test_hysteresis_never_flaps_across_a_rung_boundary():
    c, t = _ctrl()
    _feed(c, t, 6, per_obs=10, dt=0.1)
    assert c.steps() == 8
    t[0] += 1.0                             # clear of any dwell
    # budget oscillating across the rung-8 boundary (7 <-> 9 steps):
    # neither side clears the 25% margin, so the rung must never move
    for budget in (7, 9) * 10:
        _feed(c, t, 8, per_obs=budget, dt=0.1)
        assert c.steps() == 8, f"flapped at budget {budget}"


def test_min_dwell_gates_consecutive_moves():
    # window=3 so the windowed estimate turns over INSIDE the dwell —
    # isolating the dwell gate from the window's own inertia
    c, t = _ctrl(window=3)
    _feed(c, t, 6, per_obs=10, dt=0.1)
    assert c.steps() == 8                   # first move at t=0.6
    # rate drops to 40 steps/s (4-step budget, clears the down margin),
    # but only 0.2 s into the 0.5 s dwell: the rung must hold
    _feed(c, t, 2, per_obs=4, dt=0.1)       # t -> 0.8
    assert c.steps() == 8
    # past the dwell at the same low rate: now it moves
    _feed(c, t, 4, per_obs=4, dt=0.1)       # t -> 1.2
    assert c.steps() == 4


def test_rate_spike_escalates_to_full_span_bypassing_dwell():
    c, t = _ctrl()
    _feed(c, t, 6, per_obs=10, dt=0.1)
    assert c.steps() == 8
    # 30 ms later — deep inside the 500 ms dwell — the rate spikes: the
    # budget clears the top rung's boundary with margin, and falling
    # behind is strictly worse than a dwell violation, so escalation to
    # the full span applies NOW
    _feed(c, t, 3, per_obs=100, dt=0.01)
    assert c.steps() == 32


def test_reset_forgets_the_window_and_reholds_full_span():
    c, t = _ctrl()
    _feed(c, t, 6, per_obs=10, dt=0.1)
    assert c.steps() == 8
    c.reset()
    assert c.step_rate() is None
    assert c.current_steps() == 32
    assert c.steps() == 32


def test_gauge_read_never_advances_policy_state():
    c, t = _ctrl()
    _feed(c, t, 6, per_obs=10, dt=0.1)
    # current_steps() is the gauge read: it must report the HELD rung
    # without evaluating (and committing) a pending move
    assert c.current_steps() == 32
    assert c.steps() == 8
    assert c.current_steps() == 8


# ---------------------------------------------------------------------------
# operator: rung parity, the in-flight ring, flag-off identity
# ---------------------------------------------------------------------------

class _PinnedController:
    """Controller stub pinned to one rung: policy is exercised above in
    isolation; these tests pin geometry to prove DISPATCH correctness."""

    def __init__(self, steps):
        self._steps = steps

    def observe(self, n_steps, now=None):
        pass

    def steps(self, now=None):
        return self._steps

    def current_steps(self):
        return self._steps

    def reset(self):
        pass


def _run_stream(op, *, seed=11, steps=24, n_keys=96, batch=48):
    r = np.random.default_rng(seed)
    out = []
    for s in range(steps):
        keys = r.integers(0, n_keys, batch)
        vals = (keys % 5 + 1).astype(np.float32)
        ts = (s * 250 + r.integers(0, 250, batch)).astype(np.int64)
        op.process_batch(keys, vals, ts)
        op.process_watermark(s * 250 + 125)
        out.extend(op.drain_output())
    op.process_watermark(MAX_WATERMARK - 1)
    out.extend(op.drain_output())
    return sorted((int(k), int(w.start), float(v)) for k, w, v, _ in out)


def _mk_op(latency=None, agg="sum"):
    return FusedWindowOperator(
        TumblingEventTimeWindows.of(1000), agg, key_capacity=256,
        superbatch_steps=8, latency=latency)


@pytest.mark.parametrize("readback", [0, 2])
def test_every_rung_geometry_matches_the_full_span_run(readback):
    """Byte parity across the whole ladder, with and without streaming
    readback: rung choice and per-group readback move WHEN emissions
    become host-visible, never WHAT they contain."""
    ref = _run_stream(_mk_op())
    for rung in build_rung_ladder(2, 8):
        op = _mk_op(latency=LatencySpec(
            target_ms=50, max_inflight=2, readback_steps=readback))
        op._controller = _PinnedController(rung)
        got = _run_stream(op)
        assert got == ref, f"rung {rung} (readback={readback}) diverged"


def test_inflight_ring_bounded_and_drained_at_barriers():
    ref = _run_stream(_mk_op())
    op = _mk_op(latency=LatencySpec(target_ms=50, max_inflight=3))
    op._controller = _PinnedController(2)
    r = np.random.default_rng(11)
    out, max_depth = [], 0
    for s in range(24):
        keys = r.integers(0, 96, 48)
        vals = (keys % 5 + 1).astype(np.float32)
        ts = (s * 250 + r.integers(0, 250, 48)).astype(np.int64)
        op.process_batch(keys, vals, ts)
        op.process_watermark(s * 250 + 125)
        out.extend(op.drain_output())
        max_depth = max(max_depth, len(op._inflight))
    # rung 2 dispatches every other step: the ring must actually fill to
    # its configured depth (overlap is real) and never exceed it
    assert max_depth == 3
    snap = op.snapshot()                    # barrier: flush + capture
    assert len(op._inflight) == 0, "snapshot captured a non-empty ring"
    assert op.latency_gauges()["inflightDepth"] == 0
    op.process_watermark(MAX_WATERMARK - 1)
    out.extend(op.drain_output())
    assert sorted((int(k), int(w.start), float(v))
                  for k, w, v, _ in out) == ref
    # restore resets ring AND controller: pre-failure samples describe a
    # stream position that no longer exists
    op2 = _mk_op(latency=LatencySpec(target_ms=50, max_inflight=3))
    op2._controller.observe(4)
    op2._controller.observe(4)
    op2.restore(snap)
    assert len(op2._inflight) == 0
    assert op2._controller.step_rate() is None
    assert op2._controller.current_steps() == 8     # full span re-held


def test_adaptive_run_stays_on_ladder_shapes_and_keeps_parity():
    """The bounded-compile guarantee: an adaptive run may only ever
    dispatch ladder rungs (plus the pow2 flush tails the throughput path
    already compiles) — never one geometry per decision."""
    ref = _run_stream(_mk_op())
    op = _mk_op(latency=LatencySpec(target_ms=1, max_inflight=2,
                                    min_dwell_ms=0))
    got = _run_stream(op)
    assert got == ref
    ladder = set(build_rung_ladder(2, 8))
    pow2_tails = {1, 2, 4, 8}
    assert op._ladder_geoms <= ladder | pow2_tails, \
        f"off-ladder geometry dispatched: {op._ladder_geoms}"
    assert op.latency_gauges()["ladderRecompiles"] <= \
        len(ladder | pow2_tails)


def test_flag_off_is_identical_to_throughput_mode():
    """No LatencySpec => the operator is constructed exactly as before
    the mode existed: no controller, depth-1 ring, no donated carries, no
    readback split, no gauges — and every pre-flush dispatch cuts at the
    fixed full span."""
    op = _mk_op()
    assert op._controller is None
    assert op._max_inflight == 1
    assert op.pipe.donate_carry is False
    assert op.pipe.readback_steps == 0
    assert op.latency_gauges() is None

    calls = []
    orig = op.pipe.process_superbatch

    def spy(batches, wms, defer=False):
        calls.append(len(wms))
        return orig(batches, wms, defer=defer)

    op.pipe.process_superbatch = spy
    r = np.random.default_rng(11)
    for s in range(24):
        keys = r.integers(0, 96, 48)
        vals = (keys % 5 + 1).astype(np.float32)
        ts = (s * 250 + r.integers(0, 250, 48)).astype(np.int64)
        op.process_batch(keys, vals, ts)
        op.process_watermark(s * 250 + 125)
        op.drain_output()
    pre_flush = list(calls)
    op.process_watermark(MAX_WATERMARK - 1)
    op.drain_output()
    assert pre_flush and all(c == 8 for c in pre_flush), \
        f"flag-off dispatch trace changed: {pre_flush}"
    # flush tails stay pow2-padded (the historical bounded-shapes rule)
    assert all((c & (c - 1)) == 0 for c in calls[len(pre_flush):])


def test_executor_threads_the_flag_and_defaults_off():
    from flink_tpu.config import Configuration, LatencyOptions
    from flink_tpu.runtime.executor import _latency_kwargs

    # default: EMPTY kwargs — the operator call site is byte-identical
    assert _latency_kwargs(Configuration()) == {}
    cfg = Configuration()
    cfg.set(LatencyOptions.TARGET_MS, 25)
    cfg.set(LatencyOptions.MAX_INFLIGHT, 3)
    kw = _latency_kwargs(cfg)
    spec = kw["latency"]
    assert isinstance(spec, LatencySpec)
    assert spec.target_ms == 25 and spec.max_inflight == 3
    assert spec.floor_steps == 2 and spec.readback_steps == 8
