"""Tier-1 gate for the flink_tpu.lint analyzer (ISSUE-5).

Three layers:

1. **The gate** — the full engine over the real ``flink_tpu`` package
   against the checked-in ``lint_baseline.json`` must be clean (exit 0),
   every baseline entry justified and live. This is what keeps future
   PRs' invariants enforced by CI rather than reviewer memory.
2. **Engine mechanics** — CLI exit codes (0/1/2), output formats (text /
   JSON / SARIF-against-golden), ``--write-baseline`` seeding.
3. **Baseline lifecycle round-trip** — add → suppress → remove → fail
   (a stale entry is an error, so fixed debt must leave the ledger).
"""

import json
import pathlib
import shutil
import textwrap

import flink_tpu
from flink_tpu.lint import Baseline, all_rules, run_lint
from flink_tpu.lint.cli import main as lint_main
from flink_tpu.lint.engine import (
    EXIT_BASELINE_ERROR,
    EXIT_CLEAN,
    EXIT_VIOLATIONS,
)

PKG = pathlib.Path(flink_tpu.__file__).parent
REPO = PKG.parent
BASELINE = REPO / "lint_baseline.json"
FIXTURES = pathlib.Path(__file__).parent / "fixtures"
SAMPLE_PKG = FIXTURES / "lint_sample" / "samplepkg"


# ---------------------------------------------------------------------------
# 1. the gate
# ---------------------------------------------------------------------------

def test_flink_tpu_is_lint_clean_against_the_checked_in_baseline():
    baseline = Baseline.load(BASELINE)
    report = run_lint(PKG, baseline=baseline)
    rendered = "\n".join(v.render() for v in report.violations)
    assert not report.violations, (
        "new lint violations (fix them, or baseline WITH a written "
        f"justification in {BASELINE.name}):\n{rendered}"
    )
    assert not report.baseline_errors, "\n".join(report.baseline_errors)
    assert report.exit_code == EXIT_CLEAN
    assert report.modules_scanned > 100     # really scanned the package


def test_every_baseline_entry_is_justified():
    baseline = Baseline.load(BASELINE)
    unjustified = [e.fingerprint for e in baseline.entries if not e.justified]
    assert not unjustified, (
        f"baseline entries without a written justification: {unjustified}"
    )


def test_cli_gate_matches_engine():
    assert lint_main([str(PKG), "--baseline", str(BASELINE)]) == EXIT_CLEAN


def test_path_scoped_rules_are_not_vacuous():
    """Every path the rules are configured against must exist in the real
    package — otherwise a rename silently disables the rule and it passes
    forever without checking anything (the old test_layering_rules had
    this guard; the registry migration must not lose it)."""
    from flink_tpu.lint import ModuleIndex
    from flink_tpu.lint.rules_architecture import LAYER_FORBIDDEN
    from flink_tpu.lint.rules_device import CONTROL_PLANE
    from flink_tpu.lint.rules_wire import SerializationFreeDataplaneRule

    index = ModuleIndex(PKG)
    # the history/doctor plane must stay REGISTERED under DEV003's jax
    # ban: both modules consume plain-data snapshots/span dicts, and a
    # module-level jax import would drag backend init into every REST
    # reader and JM schedule tick. A rename (or a dropped tuple entry)
    # would disable the ban silently.
    for rel in ("metrics/history.py", "metrics/doctor.py"):
        assert rel in CONTROL_PLANE, (
            f"{rel} no longer registered in DEV003's CONTROL_PLANE — "
            "the history/doctor plane may not import jax")
        assert index.get(rel) is not None, (
            f"{rel} missing — the history/doctor plane moved and "
            "DEV003's control-plane ban no longer covers it")
    for layer in LAYER_FORBIDDEN:
        assert any(index.in_subtree(layer)), (
            f"layer {layer!r} has no modules — LAYER_FORBIDDEN is stale "
            f"and ARCH001 is vacuous for it")
    # the scheduler layer must stay REGISTERED, not merely existent: a
    # deleted dict entry would leave scheduler/ free to grow runtime
    # imports with every test still green
    assert "scheduler" in LAYER_FORBIDDEN, (
        "scheduler layer unregistered from ARCH001 — the autoscaler may "
        "not import the runtime (rescales flow through injected callables)")
    assert any("runtime" in b for b in LAYER_FORBIDDEN["scheduler"]), (
        "scheduler layer no longer forbids runtime imports")
    assert "metrics" in LAYER_FORBIDDEN and any(
        "scheduler" in b for b in LAYER_FORBIDDEN["metrics"]), (
        "metrics layer no longer forbids importing the scheduler")
    # the fusion planner must stay in graph/ under the graph layer's
    # runtime ban: the DeviceChainPlan is pure data about transformations,
    # and a planner that imports the runtime inverts the translation DAG
    assert "graph" in LAYER_FORBIDDEN and any(
        "runtime" in b for b in LAYER_FORBIDDEN["graph"]), (
        "graph layer no longer forbids runtime imports — the fusion "
        "planner (graph/fusion.py) must not reach into the executor")
    assert index.get("graph/fusion.py") is not None, (
        "graph/fusion.py missing — the whole-graph fusion planner moved "
        "and ARCH001's graph-layer ban no longer covers it")
    # the sharing optimizer must stay in graph/ under the same ban: a
    # SharedWindowPlan is pure data about correlated window siblings the
    # executor consumes — a runtime import here would invert the
    # translation DAG exactly like a fusion-planner one
    assert index.get("graph/window_sharing.py") is not None, (
        "graph/window_sharing.py missing — the Factor-Windows sharing "
        "optimizer moved and ARCH001's graph-layer ban no longer covers "
        "it")
    # the SQL planner must stay REGISTERED with its runtime AND api bans:
    # it emits transformations the executor consumes — an executor (or
    # fluent-api) import here inverts the translation DAG, and a deleted
    # dict entry would let planner/ grow those imports silently
    assert "planner" in LAYER_FORBIDDEN, (
        "planner layer unregistered from ARCH001 — the SQL planner may "
        "not import the runtime or the api")
    assert any("runtime" in b for b in LAYER_FORBIDDEN["planner"]), (
        "planner layer no longer forbids runtime imports")
    assert any(b.endswith(".api") for b in LAYER_FORBIDDEN["planner"]), (
        "planner layer no longer forbids api imports (assigner "
        "construction must stay a function-scoped lazy import)")
    for mod in ("planner/__init__.py", "planner/logical.py",
                "planner/rules.py", "planner/lowering.py"):
        assert index.get(mod) is not None, (
            f"{mod} missing — the SQL planner moved and ARCH001's "
            f"planner-layer bans no longer cover it")
    # the multichip library must stay in parallel/ under the parallel
    # layer's runtime/api ban: the sharded superscan is a kernel/state
    # library the runtime composes (FusedWindowOperator targets it), and
    # a module-level runtime import would invert that DAG
    assert "parallel" in LAYER_FORBIDDEN and any(
        "runtime" in b for b in LAYER_FORBIDDEN["parallel"]), (
        "parallel layer unregistered from ARCH001 (or no longer forbids "
        "runtime imports) — the mesh library may not reach into the "
        "executor")
    for rel in ("parallel/mesh.py", "parallel/sharded_superscan.py"):
        assert index.get(rel) is not None, (
            f"{rel} missing — the multichip SPMD core moved and the "
            "parallel layer's ARCH001 entry no longer covers it")
    # the join subsystem must stay REGISTERED with its runtime/api/table/
    # scheduler bans: the bucket rings and the fused match pipeline are a
    # kernel/state library the runtime's DeviceJoinRunner composes — a
    # module-level runtime (or table) import would invert that DAG, and a
    # deleted dict entry would let joins/ grow those imports silently
    assert "joins" in LAYER_FORBIDDEN, (
        "joins layer unregistered from ARCH001 — the join subsystem may "
        "not import the runtime, api, table, or scheduler")
    for banned in ("runtime", "api", "table", "scheduler"):
        assert any(b.endswith("." + banned)
                   for b in LAYER_FORBIDDEN["joins"]), (
            f"joins layer no longer forbids {banned} imports")
    for rel in ("joins/spec.py", "joins/ring.py", "joins/pipeline.py",
                "joins/sharded.py"):
        assert index.get(rel) is not None, (
            f"{rel} missing — the join subsystem moved and the joins "
            "layer's ARCH001 entry no longer covers it")
    # the skew-adaptive exchange splits across two layers and both must
    # stay under their bans: the routing-table LAYOUT algebra lives in
    # parallel/ (pure numpy, composed by the runtime), while the
    # rebalance POLICY lives in scheduler/ (decides from telemetry, the
    # runtime executes through the capture/restore machinery — the
    # autoscaler's injected-callable pattern)
    assert index.get("parallel/routing.py") is not None, (
        "parallel/routing.py missing — the key-group routing table moved "
        "and the parallel layer's ARCH001 entry no longer covers it")
    assert index.get("scheduler/rebalancer.py") is not None, (
        "scheduler/rebalancer.py missing — the skew rebalancer moved and "
        "the scheduler layer's runtime ban no longer covers it")
    # the million-key state plane must stay in state/ under the state
    # layer's runtime ban: the vocabulary decides placement and the tier
    # manager moves bytes through operator-injected callables — a module
    # that imported the runtime would invert that DAG
    assert any("runtime" in b for b in LAYER_FORBIDDEN["state"]), (
        "state layer no longer forbids runtime imports — vocab.py/"
        "tier_manager.py could silently grow executor dependencies")
    for rel in ("state/vocab.py", "state/tier_manager.py"):
        assert index.get(rel) is not None, (
            f"{rel} missing — the state plane moved and the state "
            "layer's ARCH001 entry no longer covers it")
    # the device-plane observability modules must stay in metrics/ under
    # the metrics layer's runtime ban: compile/key telemetry flows OUTWARD
    # (runtime callers hand in jitted fns and load columns), and a tracker
    # that imported the runtime would invert the metrics DAG
    for rel in ("metrics/device_stats.py", "metrics/key_stats.py"):
        assert index.get(rel) is not None, (
            f"{rel} missing — the device-plane observability core moved "
            "and the metrics layer's runtime-import ban no longer covers "
            "it")
    assert any("runtime" in b for b in LAYER_FORBIDDEN["metrics"]), (
        "metrics layer no longer forbids runtime imports — device_stats/"
        "key_stats could silently grow executor dependencies")
    for rel in CONTROL_PLANE:
        assert index.get(rel) is not None, (
            f"control-plane module {rel} missing — CONTROL_PLANE is stale "
            f"and DEV003 is vacuous for it")
    assert index.get(SerializationFreeDataplaneRule.DATAPLANE) is not None
    assert any(index.in_subtree("checkpoint")), (
        "checkpoint/ has no modules — ARCH002 is vacuous")
    assert index.get("config.py") is not None, "DOC001 is vacuous"
    # CONC005 no-silent-swallow is path-scoped: every configured subtree
    # must exist AND stay configured, or a rename/edit silently frees the
    # runtime/checkpoint planes to grow `except Exception: pass` again
    from flink_tpu.lint.rules_concurrency import SWALLOW_SCOPED_SUBTREES

    assert set(SWALLOW_SCOPED_SUBTREES) >= {"runtime", "checkpoint"}, (
        "CONC005 no longer scopes the runtime/checkpoint subtrees — "
        "silent swallows on the failure-detection planes would pass CI")
    for layer in SWALLOW_SCOPED_SUBTREES:
        assert any(index.in_subtree(layer)), (
            f"CONC005 subtree {layer!r} has no modules — the rule is "
            "vacuous for it")
    # the chaos plane's leaf module must stay where every seam imports it
    # from (security/transport, rpc, dataplane, storage, executor), and
    # the scenario matrix must stay runnable
    for rel in ("chaos/plan.py", "chaos/scenarios.py"):
        assert index.get(rel) is not None, (
            f"{rel} missing — the chaos plane moved and the seams' "
            "module-level hook (and the bench chaos gate) no longer "
            "cover it")


# ---------------------------------------------------------------------------
# 2. engine mechanics
# ---------------------------------------------------------------------------

def _write_violating_pkg(tmp_path) -> pathlib.Path:
    root = tmp_path / "vpkg"
    root.mkdir()
    (root / "__init__.py").touch()
    (root / "w.py").write_text(textwrap.dedent("""
        import threading

        def spawn(fn):
            threading.Thread(target=fn).start()
    """))
    return root


def test_cli_exit_1_on_violations(tmp_path, capsys):
    root = _write_violating_pkg(tmp_path)
    rc = lint_main([str(root), "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == EXIT_VIOLATIONS
    assert "CONC004" in out and "vpkg/w.py:5" in out


def test_cli_exit_0_on_clean_package(tmp_path):
    root = tmp_path / "cleanpkg"
    root.mkdir()
    (root / "__init__.py").touch()
    (root / "ok.py").write_text("X = 1\n")
    assert lint_main([str(root), "--no-baseline"]) == EXIT_CLEAN


def test_cli_json_format(tmp_path, capsys):
    root = _write_violating_pkg(tmp_path)
    rc = lint_main([str(root), "--no-baseline", "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == EXIT_VIOLATIONS
    assert doc["exit_code"] == EXIT_VIOLATIONS
    assert [v["rule"] for v in doc["violations"]] == ["CONC004"]
    assert doc["violations"][0]["fingerprint"].startswith("CONC004::")


def test_cli_rule_filter_and_list(tmp_path, capsys):
    root = _write_violating_pkg(tmp_path)
    # a filter that excludes the only violation reports clean
    assert lint_main([str(root), "--no-baseline",
                      "--rule", "WIRE001"]) == EXIT_CLEAN
    capsys.readouterr()
    assert lint_main(["--list-rules"]) == EXIT_CLEAN
    out = capsys.readouterr().out
    for rule in all_rules():
        assert rule.id in out


def test_sarif_output_matches_golden(capsys):
    rc = lint_main([str(SAMPLE_PKG), "--no-baseline",
                    "--rule", "CONC004", "--rule", "WIRE001",
                    "--format", "sarif"])
    out = capsys.readouterr().out
    assert rc == EXIT_VIOLATIONS
    golden = (FIXTURES / "lint_expected.sarif").read_text()
    assert json.loads(out) == json.loads(golden), (
        "SARIF output drifted from tests/fixtures/lint_expected.sarif — "
        "if the change is intentional, regenerate the golden with:\n"
        "  python -m flink_tpu.lint tests/fixtures/lint_sample/samplepkg "
        "--no-baseline --rule CONC004 --rule WIRE001 --format sarif "
        "> tests/fixtures/lint_expected.sarif"
    )
    doc = json.loads(out)
    results = doc["runs"][0]["results"]
    assert {r["ruleId"] for r in results} == {"CONC004", "WIRE001"}
    uris = {r["locations"][0]["physicalLocation"]["artifactLocation"]["uri"]
            for r in results}
    assert uris == {"samplepkg/worker.py", "samplepkg/runtime/blob.py"}


def test_sarif_output_validates_against_2_1_0_schema(capsys):
    """The emitted SARIF must satisfy the 2.1.0 schema (vendored subset:
    the official OASIS schema's required/enum/type constraints for every
    object we produce — CI has no network to fetch the full file)."""
    import jsonschema

    rc = lint_main([str(SAMPLE_PKG), "--no-baseline",
                    "--rule", "CONC004", "--rule", "WIRE001",
                    "--format", "sarif"])
    out = capsys.readouterr().out
    assert rc == EXIT_VIOLATIONS
    doc = json.loads(out)
    schema = json.loads(
        (FIXTURES / "sarif-schema-2.1.0.subset.json").read_text())
    jsonschema.validate(doc, schema)          # raises on violation

    # the schema subset must not be vacuous: each of these mutations is
    # illegal under the real 2.1.0 schema and must be rejected here too
    for mutate in (
        lambda d: d.update(version="3.0.0"),
        lambda d: d.pop("runs"),
        lambda d: d["runs"][0].pop("tool"),
        lambda d: d["runs"][0]["tool"]["driver"].pop("name"),
        lambda d: d["runs"][0]["results"][0].pop("message"),
        lambda d: d["runs"][0]["results"][0].update(level="fatal"),
        lambda d: d["runs"][0]["results"][0]["locations"][0]
        ["physicalLocation"]["region"].update(startLine=0),
    ):
        broken = json.loads(out)
        mutate(broken)
        try:
            jsonschema.validate(broken, schema)
        except jsonschema.ValidationError:
            pass
        else:
            raise AssertionError(
                f"schema subset accepted an illegal mutation: {mutate}")


# ---------------------------------------------------------------------------
# 3. baseline lifecycle: add -> suppress -> remove -> fail
# ---------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    root = _write_violating_pkg(tmp_path)
    bl_path = tmp_path / "lint_baseline.json"

    # (0) violation fails the run
    report = run_lint(root)
    assert report.exit_code == EXIT_VIOLATIONS
    violation = report.violations[0]

    # (1) add WITHOUT justification: suppressed but the run errors (exit 2)
    baseline = Baseline(path=bl_path)
    baseline.add(violation)                      # seeds a TODO justification
    baseline.save()
    report = run_lint(root, baseline=Baseline.load(bl_path))
    assert report.exit_code == EXIT_BASELINE_ERROR
    assert any("justification" in e for e in report.baseline_errors)

    # (2) write the justification: suppressed cleanly (exit 0)
    baseline = Baseline.load(bl_path)
    baseline.entries[0].justification = (
        "fixture thread is short-lived and joined by the test harness")
    baseline.save()
    report = run_lint(root, baseline=Baseline.load(bl_path))
    assert report.exit_code == EXIT_CLEAN
    assert len(report.suppressed) == 1

    # (3) fix the code: the entry goes stale and the run fails again
    (root / "w.py").write_text(textwrap.dedent("""
        import threading

        def spawn(fn):
            threading.Thread(target=fn, daemon=True, name="fix-w").start()
    """))
    report = run_lint(root, baseline=Baseline.load(bl_path))
    assert report.exit_code == EXIT_BASELINE_ERROR
    assert any("stale" in e for e in report.baseline_errors)

    # (4) remove the stale entry: clean again
    baseline = Baseline.load(bl_path)
    baseline.entries = []
    baseline.save()
    report = run_lint(root, baseline=Baseline.load(bl_path))
    assert report.exit_code == EXIT_CLEAN


def test_write_baseline_rejects_no_baseline_combo(tmp_path, capsys):
    """--no-baseline --write-baseline would rebuild the file from empty
    and destroy every human-written justification — refused outright."""
    root = _write_violating_pkg(tmp_path)
    assert lint_main([str(root), "--no-baseline",
                      "--write-baseline"]) == EXIT_BASELINE_ERROR
    assert "mutually exclusive" in capsys.readouterr().err


def test_write_baseline_merges_into_existing(tmp_path, capsys):
    """--write-baseline must preserve already-justified entries."""
    root = _write_violating_pkg(tmp_path)
    (root / "w2.py").write_text(textwrap.dedent("""
        import threading

        def spawn2(fn):
            threading.Thread(target=fn).start()
    """))
    bl_path = tmp_path / "lint_baseline.json"
    report = run_lint(root)
    assert len(report.violations) == 2
    baseline = Baseline(path=bl_path)
    first = next(v for v in report.violations if v.path.endswith("w.py"))
    baseline.add(first, justification="human-written reason")
    baseline.save()

    assert lint_main([str(root), "--baseline", str(bl_path),
                      "--write-baseline"]) == EXIT_CLEAN
    capsys.readouterr()
    doc = json.loads(bl_path.read_text())
    justs = sorted(e["justification"] for e in doc["entries"])
    assert len(doc["entries"]) == 2
    assert justs[0].startswith("TODO")           # the newly-frozen one
    assert justs[1] == "human-written reason"    # preserved, not clobbered


def test_write_baseline_cli_flow(tmp_path, capsys):
    root = _write_violating_pkg(tmp_path)
    bl_path = tmp_path / "lint_baseline.json"

    # seeding writes TODO entries and exits 0 (the freeze itself succeeds)
    assert lint_main([str(root), "--baseline", str(bl_path),
                      "--write-baseline"]) == EXIT_CLEAN
    capsys.readouterr()
    doc = json.loads(bl_path.read_text())
    assert len(doc["entries"]) == 1
    assert doc["entries"][0]["justification"].startswith("TODO")

    # ...but the engine refuses the TODO until a human justifies it
    assert lint_main([str(root), "--baseline",
                      str(bl_path)]) == EXIT_BASELINE_ERROR

    baseline = Baseline.load(bl_path)
    baseline.entries[0].justification = "documented fixture debt"
    baseline.save()
    assert lint_main([str(root), "--baseline", str(bl_path)]) == EXIT_CLEAN


def test_fingerprints_survive_line_churn(tmp_path):
    """Baseline matching is line-independent: prepending code must not
    orphan the entry."""
    root = _write_violating_pkg(tmp_path)
    report = run_lint(root)
    fp_before = report.violations[0].fingerprint
    line_before = report.violations[0].line
    src = (root / "w.py").read_text()
    (root / "w.py").write_text("# a comment\nY = 2\n" + src)
    report = run_lint(root)
    assert report.violations[0].fingerprint == fp_before
    assert report.violations[0].line == line_before + 2  # line moved; fp did not


def _baseline_with_stale_entries(tmp_path) -> pathlib.Path:
    """A baseline holding one live entry, one whose file is gone, and one
    whose rule id was retired."""
    root = _write_violating_pkg(tmp_path)
    bl_path = tmp_path / "lint_baseline.json"
    report = run_lint(root)
    baseline = Baseline(path=bl_path)
    baseline.add(report.violations[0], justification="documented debt")
    baseline.entries.append(type(baseline.entries[0])(
        rule="CONC004", path="vpkg/deleted_module.py", scope="gone",
        symbol="thread@gone", justification="file was deleted in PR 12"))
    baseline.entries.append(type(baseline.entries[0])(
        rule="ZZZZ999", path="vpkg/w.py", scope="spawn",
        symbol="whatever", justification="rule was retired"))
    baseline.save()
    return root


def test_prune_stale_drops_missing_file_and_unknown_rule(tmp_path):
    """Entries whose file no longer exists or whose rule id is unknown
    were previously carried forever (the stale check reports them as
    engine errors against a file nobody can re-lint); prune_stale drops
    exactly those and keeps the live entry."""
    root = _baseline_with_stale_entries(tmp_path)
    baseline = Baseline.load(tmp_path / "lint_baseline.json")
    assert len(baseline) == 3

    pruned = baseline.prune_stale(tmp_path, [r.id for r in all_rules()])
    reasons = sorted(reason for _, reason in pruned)
    assert len(pruned) == 2
    assert any("no longer exists" in r for r in reasons)
    assert any("unknown rule" in r for r in reasons)
    assert len(baseline) == 1
    assert baseline.entries[0].path.endswith("w.py")

    # the pruned baseline still suppresses the live violation
    report = run_lint(root, baseline=baseline)
    assert report.exit_code == EXIT_CLEAN
    assert len(report.suppressed) == 1


def test_cli_prune_baseline_rewrites_the_file(tmp_path, capsys):
    root = _baseline_with_stale_entries(tmp_path)
    bl_path = tmp_path / "lint_baseline.json"

    # without the flag: pruned in memory (run is clean, warning printed)
    # but the file keeps all three entries
    assert lint_main([str(root), "--baseline", str(bl_path)]) == EXIT_CLEAN
    err = capsys.readouterr().err
    assert err.count("pruned stale entry") == 2
    assert len(json.loads(bl_path.read_text())["entries"]) == 3

    # with the flag: the file is rewritten without the stale entries
    assert lint_main([str(root), "--baseline", str(bl_path),
                      "--prune-baseline"]) == EXIT_CLEAN
    err = capsys.readouterr().err
    assert "2 stale entries removed, 1 kept" in err
    doc = json.loads(bl_path.read_text())
    assert len(doc["entries"]) == 1
    assert doc["entries"][0]["path"].endswith("w.py")

    # idempotent: a second prune finds nothing
    assert lint_main([str(root), "--baseline", str(bl_path),
                      "--prune-baseline"]) == EXIT_CLEAN
    assert "0 stale entries removed" in capsys.readouterr().err


def test_prune_baseline_rejects_no_baseline_combo(tmp_path, capsys):
    root = _write_violating_pkg(tmp_path)
    assert lint_main([str(root), "--no-baseline",
                      "--prune-baseline"]) == EXIT_BASELINE_ERROR
    assert "mutually exclusive" in capsys.readouterr().err


def test_rule_filter_skips_stale_check_for_other_rules(tmp_path):
    """A --rule filtered run must not call every other rule's baseline
    entries stale."""
    root = _write_violating_pkg(tmp_path)
    bl_path = tmp_path / "lint_baseline.json"
    baseline = Baseline(path=bl_path)
    report = run_lint(root)
    baseline.add(report.violations[0], justification="documented debt")
    baseline.save()
    from flink_tpu.lint import get_rule

    report = run_lint(root, rules=[get_rule("WIRE001")],
                      baseline=Baseline.load(bl_path))
    assert report.exit_code == EXIT_CLEAN
