"""Seeded-violation corpus: every registered rule must actually fire.

``tests/lint_corpus/<rule id lowercased>/`` holds one tiny synthetic
package per rule, each seeding the exact violation class the rule exists
to catch (ISSUE-20 — ``test_path_scoped_rules_are_not_vacuous`` only
proves the *paths* exist; this proves the *detection* works). For each
package the FULL registry runs and must report:

- the target rule fires (the rule is not vacuous),
- no other rule fires (the corpus is a controlled experiment, not noise),
- violations anchor only in ``bad_*`` modules — the ``good_*`` twins are
  the mutation check's control group: the same code shape with the seeded
  defect repaired (drain call restored, cache-key component added, fault
  re-raised/allowlisted), which must pass.

Zero-on-the-real-tree is asserted per rule in tests/test_lint_full.py
(which times each rule individually anyway); here we only prove firing.
"""

import pathlib

import pytest

from flink_tpu.lint import all_rules, run_lint

CORPUS = pathlib.Path(__file__).parent / "lint_corpus"
RULE_IDS = [r.id for r in all_rules()]


def test_every_rule_has_a_corpus_package():
    """Registry <-> corpus coverage both ways: a new rule without a
    corpus package is vacuous-by-default; a corpus dir without a rule is
    dead weight (a retired rule whose fixtures were forgotten)."""
    dirs = {p.name for p in CORPUS.iterdir() if p.is_dir()}
    want = {rid.lower() for rid in RULE_IDS}
    assert want - dirs == set(), (
        f"rules without a corpus package: {sorted(want - dirs)}")
    assert dirs - want == set(), (
        f"corpus packages without a registered rule: {sorted(dirs - want)}")


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_fires_on_its_corpus(rule_id):
    report = run_lint(CORPUS / rule_id.lower())     # full registry
    fired = report.by_rule(rule_id)
    assert fired, (
        f"{rule_id} did not fire on its seeded corpus — the rule is "
        f"vacuous (or the corpus no longer seeds the violation)")
    strays = [v for v in report.violations if v.rule_id != rule_id]
    assert not strays, (
        f"corpus for {rule_id} trips other rules (not a controlled "
        f"experiment): {[(v.rule_id, v.path, v.line) for v in strays]}")
    polluted = [v for v in fired
                if pathlib.PurePosixPath(v.path).name.startswith("good_")]
    assert not polluted, (
        f"{rule_id} fired on its control twin — the repaired shape must "
        f"pass: {[(v.path, v.line, v.symbol) for v in polluted]}")


# ---------------------------------------------------------------------------
# mutation checks: the seeded defect flips exactly the expected finding
# ---------------------------------------------------------------------------

def test_exon001_catches_removed_drain_and_undeclared_ring():
    """The corpus twin of FusedWindowOperator with `_resolve_inflight`
    removed from flush_all is caught as an undrained ring (through the
    snapshot -> flush_all chain), and the undeclared `_pending` container
    on a capturing class is caught independently."""
    report = run_lint(CORPUS / "exon001")
    by_symbol = {v.symbol: v for v in report.by_rule("EXON001")}
    assert "undrained:_inflight" in by_symbol
    assert by_symbol["undrained:_inflight"].scope == \
        "BadFusedOperator.snapshot"
    assert "undeclared:_pending" in by_symbol
    assert by_symbol["undeclared:_pending"].scope == "UndeclaredOperator"
    assert {v.path for v in report.by_rule("EXON001")} == \
        {"exon001/runtime/bad_operator.py"}


def test_exon002_catches_missing_key_components():
    """Both memoization styles: the functools cache whose parameters
    omit a jit-option input, and the dict memo whose key tuple omits the
    donation flag (the PR-17 bug class)."""
    report = run_lint(CORPUS / "exon002")
    symbols = {v.symbol for v in report.by_rule("EXON002")}
    assert "lru-key-incomplete" in symbols
    assert any(s.startswith("key-incomplete:") for s in symbols)
    msgs = " ".join(v.message for v in report.by_rule("EXON002"))
    assert "self._donate" in msgs and "_BACKEND" in msgs


def test_exon003_catches_the_swallowed_fault():
    """`except OSError: return` around a seam-reaching call absorbs
    InjectedCrash; the three transparent shapes in the control twin
    (explicit clause, wrap-and-raise, @absorbs_faults) all pass."""
    report = run_lint(CORPUS / "exon003")
    fired = report.by_rule("EXON003")
    assert {v.path for v in fired} == {"exon003/runtime/bad_sender.py"}
    assert {v.symbol for v in fired} == {"except:OSError"}
    assert {v.scope for v in fired} == {"retry_once"}
