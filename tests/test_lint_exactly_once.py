"""Unit tests for the exactly-once contract analyzer (ISSUE-20):
the contracts vocabulary, the dataflow summary layer, and the three EXON
rules over synthesized fixture packages (same mechanism as
test_lint_rules.py — the rules are package-relative by design).
"""

import ast
import textwrap

import pytest

from flink_tpu.lint import ModuleIndex, get_rule
from flink_tpu.lint import contracts, dataflow


def make_index(tmp_path, files, package="fixpkg"):
    root = tmp_path / package
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    (root / "__init__.py").touch()
    return ModuleIndex(root)


def run_rule(rule_id, tmp_path, files, package="fixpkg"):
    return list(get_rule(rule_id).check(make_index(tmp_path, files, package)))


# ---------------------------------------------------------------------------
# contracts: runtime decorators + AST extraction
# ---------------------------------------------------------------------------

def test_contracts_runtime_decorators_attach_metadata():
    @contracts.inflight_ring("_a", drained_by="drain_a")
    @contracts.inflight_ring("_b", drained_by="drain_b")
    class C:
        @contracts.drains("_a", "_b")
        def flush(self):
            pass

    # innermost decorator applies first, so _b is declared first
    assert getattr(C, contracts.RING_ATTR) == \
        (("_b", "drain_b"), ("_a", "drain_a"))
    assert getattr(C.flush, contracts.DRAINS_ATTR) == ("_a", "_b")

    @contracts.absorbs_faults("server loop: absorption is the contract")
    def handler():
        pass

    assert getattr(handler, contracts.ABSORBS_ATTR).startswith("server")


def test_contracts_refuse_empty_declarations():
    with pytest.raises(ValueError):
        contracts.inflight_ring("", drained_by="x")
    with pytest.raises(ValueError):
        contracts.inflight_ring("_a", drained_by="")
    with pytest.raises(ValueError):
        contracts.drains()
    with pytest.raises(ValueError):
        contracts.absorbs_faults("")
    with pytest.raises(ValueError):
        contracts.absorbs_faults("   ")


def test_contracts_ast_extraction_matches_any_spelling():
    """The analyzer reads decorators off never-imported ASTs, matching
    the trailing name — bare, module-qualified, and aliased spellings."""
    tree = ast.parse(textwrap.dedent("""
        import flink_tpu.lint.contracts as _c
        from flink_tpu.lint import contracts

        @contracts.inflight_ring("_inflight", drained_by="_resolve")
        @_c.inflight_ring("_pending", drained_by="flush")
        class Op:
            @contracts.drains("_inflight")
            def flush_all(self):
                pass

            @_c.absorbs_faults("attributed reason")
            def eat(self):
                pass

            @contracts.absorbs_faults("")
            def eat_empty(self):
                pass

            def plain(self):
                pass
    """))
    cls = tree.body[2]
    decls = contracts.ring_decls(cls)
    assert [(d.attr, d.drained_by) for d in decls] == \
        [("_inflight", "_resolve"), ("_pending", "flush")]
    by_name = {f.name: f for f in cls.body
               if isinstance(f, ast.FunctionDef)}
    assert contracts.drain_decls(by_name["flush_all"]) == ("_inflight",)
    assert contracts.absorbs_reason(by_name["eat"]) == "attributed reason"
    # empty literal -> "" (reject-able), undecorated -> None (absent)
    assert contracts.absorbs_reason(by_name["eat_empty"]) == ""
    assert contracts.absorbs_reason(by_name["plain"]) is None
    dmap = contracts.class_drain_map(cls)
    assert dmap["_inflight"] == ["_resolve", "flush_all"]
    assert dmap["_pending"] == ["flush"]


# ---------------------------------------------------------------------------
# dataflow: summary-layer behaviors the rules lean on
# ---------------------------------------------------------------------------

def test_dotted_names_keeps_args_skips_callees():
    expr = ast.parse("f(self.x) + g.h(y.z)", mode="eval").body
    assert dataflow.dotted_names(expr) == {"self.x", "y.z"}


def test_shared_dataflow_index_is_cached_per_module_index(tmp_path):
    index = make_index(tmp_path, {"a.py": "X = 1\n"})
    assert dataflow.DataflowIndex.shared(index) is \
        dataflow.DataflowIndex.shared(index)
    other = make_index(tmp_path, {"b.py": "Y = 2\n"}, package="otherpkg")
    assert dataflow.DataflowIndex.shared(other) is not \
        dataflow.DataflowIndex.shared(index)


def test_fault_carrying_fixpoint_propagates_through_callers(tmp_path):
    index = make_index(tmp_path, {
        "runtime/seam.py": """
            from fixpkg.chaos import plan as _chaos

            def send(sock):
                hook = _chaos.HOOK
                if hook is not None:
                    hook("rpc", "send")
                sock.sendall(b"x")

            def relay(sock):
                send(sock)

            def unrelated(sock):
                sock.close()
        """})
    dfi = dataflow.DataflowIndex.shared(index)
    carrying = dfi.fault_carrying_names()
    assert "send" in carrying and "relay" in carrying
    assert "unrelated" not in carrying


# ---------------------------------------------------------------------------
# EXON001 quiescence-before-capture
# ---------------------------------------------------------------------------

OP_HEADER = """\
        from collections import deque

        from flink_tpu.lint.contracts import drains, inflight_ring

"""


def test_exon001_flags_snapshot_without_drain(tmp_path):
    vs = run_rule("EXON001", tmp_path, {"runtime/op.py": OP_HEADER + """
        @inflight_ring("_inflight", drained_by="_resolve")
        class Op:
            def __init__(self):
                self._inflight = deque()

            def _resolve(self):
                self._inflight.clear()

            def snapshot(self):
                return {}
    """})
    assert [v.symbol for v in vs] == ["undrained:_inflight"]
    assert vs[0].scope == "Op.snapshot"


def test_exon001_drain_reached_through_self_call_chain(tmp_path):
    """snapshot -> flush_all -> _resolve: interprocedural, not lexical."""
    vs = run_rule("EXON001", tmp_path, {"runtime/op.py": OP_HEADER + """
        @inflight_ring("_inflight", drained_by="_resolve")
        class Op:
            def __init__(self):
                self._inflight = deque()

            def _resolve(self):
                self._inflight.clear()

            def flush_all(self):
                self._resolve()

            def snapshot(self):
                self.flush_all()
                return {}
    """})
    assert vs == []


def test_exon001_ring_guard_accepted_other_guards_not(tmp_path):
    """`if self._pending: drain()` dominates; `if self._enabled:` does
    not — the capture can run with the ring full and the flag off."""
    files = {"runtime/op.py": OP_HEADER + """
        @inflight_ring("_pending", drained_by="_resolve")
        class RingGuard:
            def __init__(self):
                self._pending = []

            def _resolve(self):
                self._pending.clear()

            def snapshot(self):
                if self._pending:
                    self._resolve()
                return {}


        @inflight_ring("_pending", drained_by="_resolve")
        class FlagGuard:
            def __init__(self):
                self._pending = []
                self._enabled = True

            def _resolve(self):
                self._pending.clear()

            def snapshot(self):
                if self._enabled:
                    self._resolve()
                return {}
    """}
    vs = run_rule("EXON001", tmp_path, files)
    assert [(v.scope, v.symbol) for v in vs] == \
        [("FlagGuard.snapshot", "undrained:_pending")]


def test_exon001_missing_drain_method_and_stale_ring(tmp_path):
    vs = run_rule("EXON001", tmp_path, {"runtime/op.py": OP_HEADER + """
        @inflight_ring("_inflight", drained_by="_nope")
        class MissingDrain:
            def __init__(self):
                self._inflight = deque()

            def snapshot(self):
                return {}


        @inflight_ring("_ghost", drained_by="_resolve")
        class StaleRing:
            def _resolve(self):
                pass

            def snapshot(self):
                return {}
    """})
    assert sorted(v.symbol for v in vs) == \
        ["missing-drain:_inflight", "stale-ring:_ghost"]


def test_exon001_undeclared_inflight_container(tmp_path):
    vs = run_rule("EXON001", tmp_path, {"runtime/op.py": """
        class Op:
            def __init__(self):
                self._pending_dispatch = []
                self._future_rows = []       # held records: fine
                self._state = {}

            def snapshot(self):
                return dict(self._state)
    """})
    assert [v.symbol for v in vs] == ["undeclared:_pending_dispatch"]


def test_exon001_inherited_drain_passes(tmp_path):
    """A subclass draining through an inherited method must not trip the
    missing-drain/stale-ring checks (the analyzer sees one class body at
    a time; bases get the benefit of the doubt)."""
    vs = run_rule("EXON001", tmp_path, {"runtime/op.py": OP_HEADER + """
        class Base:
            def __init__(self):
                self._pending = []

            def flush(self):
                self._pending.clear()


        @inflight_ring("_pending", drained_by="flush")
        class Child(Base):
            def snapshot(self):
                self.flush()
                return {}
    """})
    assert vs == []


def test_exon001_only_capture_subtrees_are_checked(tmp_path):
    """The same undrained class outside runtime/parallel/joins is not on
    the capture path."""
    src = OP_HEADER + """
        @inflight_ring("_inflight", drained_by="_resolve")
        class Op:
            def __init__(self):
                self._inflight = deque()

            def _resolve(self):
                self._inflight.clear()

            def snapshot(self):
                return {}
    """
    assert run_rule("EXON001", tmp_path, {"metrics/op.py": src}) == []
    assert len(run_rule("EXON001", tmp_path, {"joins/op.py": src})) == 1


# ---------------------------------------------------------------------------
# EXON002 executable-cache-key-completeness
# ---------------------------------------------------------------------------

def test_exon002_lru_builder_missing_option_input(tmp_path):
    vs = run_rule("EXON002", tmp_path, {"ops/b.py": """
        import functools

        import jax

        _BACKEND = "cpu"

        def _k(x):
            return x

        @functools.lru_cache(maxsize=None)
        def build(shape, dtype):
            return jax.jit(_k, backend=_BACKEND)
    """})
    assert [v.symbol for v in vs] == ["lru-key-incomplete"]
    assert "_BACKEND" in vs[0].message


def test_exon002_lru_builder_clean_when_input_is_a_param(tmp_path):
    vs = run_rule("EXON002", tmp_path, {"ops/b.py": """
        import functools

        import jax

        def _k(x):
            return x

        @functools.lru_cache(maxsize=None)
        def build(shape, dtype, backend):
            return jax.jit(_k, backend=backend)
    """})
    assert vs == []


def test_exon002_derived_option_local_resolves_to_param(tmp_path):
    """Regression for the ops/segment_ops.make_ingest_fn false positive:
    donate_args is DERIVED from the donate parameter, so the cache key
    (the lru params) does see it — one derivation hop must resolve."""
    vs = run_rule("EXON002", tmp_path, {"ops/b.py": """
        import functools

        import jax

        @functools.lru_cache(maxsize=None)
        def make_ingest(n, donate=True):
            def ingest(ring, batch):
                return ring
            donate_args = (0, 1) if donate else ()
            return jax.jit(ingest, donate_argnums=donate_args)
    """})
    assert vs == []


def test_exon002_dict_memo_key_missing_self_attr(tmp_path):
    vs = run_rule("EXON002", tmp_path, {"ops/b.py": """
        import jax

        class Cache:
            def __init__(self, donate):
                self._donate = donate
                self._cache = {}

            def get(self, fn, shape):
                key = (shape,)
                if key in self._cache:
                    return self._cache[key]
                step = jax.jit(fn, donate_argnums=(0,) if self._donate
                               else ())
                self._cache[key] = step
                return step
    """})
    assert len(vs) == 1
    assert vs[0].symbol.startswith("key-incomplete:")
    assert "self._donate" in vs[0].message


def test_exon002_dict_memo_clean_when_key_covers_inputs(tmp_path):
    vs = run_rule("EXON002", tmp_path, {"ops/b.py": """
        import jax

        class Cache:
            def __init__(self, donate):
                self._donate = donate
                self._cache = {}

            def get(self, fn, shape):
                key = (shape, self._donate)
                if key in self._cache:
                    return self._cache[key]
                step = jax.jit(fn, donate_argnums=(0,) if self._donate
                               else ())
                self._cache[key] = step
                return step
    """})
    assert vs == []


# ---------------------------------------------------------------------------
# EXON003 fault-transparency
# ---------------------------------------------------------------------------

SEAM = """\
        from fixpkg.chaos import plan as _chaos

        def send(sock):
            hook = _chaos.HOOK
            if hook is not None:
                hook("rpc", "send")
            sock.sendall(b"x")

"""


def test_exon003_wide_handler_on_seam_fires(tmp_path):
    vs = run_rule("EXON003", tmp_path, {"runtime/s.py": SEAM + """
        def retry(sock):
            try:
                send(sock)
            except OSError:
                return False
            return True
    """})
    assert [v.symbol for v in vs] == ["except:OSError"]
    assert vs[0].scope == "retry"


def test_exon003_narrow_handler_and_no_seam_are_clean(tmp_path):
    vs = run_rule("EXON003", tmp_path, {"runtime/s.py": SEAM + """
        def narrow(sock):
            try:
                send(sock)
            except TimeoutError:       # cannot catch InjectedCrash
                return False
            return True

        def cleanup(sock):
            try:
                sock.close()            # no seam reachable
            except OSError:
                pass
            return True
    """})
    assert vs == []


def test_exon003_transparent_shapes_pass(tmp_path):
    vs = run_rule("EXON003", tmp_path, {"runtime/s.py": SEAM + """
        def explicit(sock):
            try:
                send(sock)
            except _chaos.InjectedCrash:
                raise
            except OSError:
                return False

        def bare(sock):
            try:
                send(sock)
            except OSError:
                raise

        def wrap(sock):
            try:
                send(sock)
            except OSError as e:
                raise RuntimeError("send failed") from e
    """})
    assert vs == []


def test_exon003_absorbs_faults_allowlists_with_reason(tmp_path):
    files = {"runtime/s.py": SEAM + """
        from flink_tpu.lint.contracts import absorbs_faults

        @absorbs_faults("peer death model: the caller retries the batch")
        def allowlisted(sock):
            try:
                send(sock)
            except OSError:
                return False

        @absorbs_faults("")
        def empty_reason(sock):
            try:
                send(sock)
            except OSError:
                return False
    """}
    vs = run_rule("EXON003", tmp_path, files)
    assert [v.scope for v in vs] == ["empty_reason"]
    assert "empty reason" in vs[0].message


def test_exon003_nested_def_honors_enclosing_decorator(tmp_path):
    """The rpc.py Handler.handle shape: the handler lives in a def nested
    inside a decorated ancestor."""
    vs = run_rule("EXON003", tmp_path, {"runtime/s.py": SEAM + """
        from flink_tpu.lint.contracts import absorbs_faults

        @absorbs_faults("server loop ships errors back as failed replies")
        def make_server(sock):
            def handle():
                try:
                    send(sock)
                except OSError:
                    return
            return handle
    """})
    assert vs == []
