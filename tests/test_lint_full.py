"""Tier-1 full-lint gate with a wall-clock budget (ISSUE-20).

Runs every registered rule individually over the real tree (one shared
ModuleIndex, like the engine), recording per-rule wall clock:

- **Coverage**: exactly 16 rules registered, every one exercised here and
  zero ACTIVE violations per rule against the checked-in baseline (the
  per-rule split means a regression names the rule, not just "lint
  failed").
- **Budget**: the 16-rule run must stay under a pinned multiple of the
  13 pre-EXON rules' time on the same machine/index — the interprocedural
  dataflow layer (summaries + fault fixpoint, shared across the three
  EXON rules via DataflowIndex.shared) must never quietly turn the lint
  gate into the slowest test in tier-1. Failure messages carry the
  per-rule timing table so the offender is named.
- **Stamp**: bench.lint_summary() (the `lint:` block next to `health` in
  every BENCH_*.json) reports the same shape this gate verifies.
"""

import pathlib
import time

import pytest

import flink_tpu
from flink_tpu.lint import Baseline, all_rules

PKG = pathlib.Path(flink_tpu.__file__).parent
BASELINE = PKG.parent / "lint_baseline.json"

#: the 16-rule run may cost at most this multiple of the 13 pre-existing
#: rules' time (measured on the same index in the same process, so the
#: ratio is machine-independent); the floor keeps a near-zero denominator
#: from flaking the assert on very fast machines
BUDGET_MULTIPLE = 3.0
BUDGET_FLOOR_S = 10.0

_PRE_EXISTING = ("ARCH", "CONC", "DEV", "DOC", "WIRE")


@pytest.fixture(scope="module")
def timed_run():
    """One shared index, every rule timed individually."""
    from flink_tpu.lint.index import ModuleIndex

    index = ModuleIndex(PKG)
    times = {}
    found = {}
    for rule in all_rules():
        t0 = time.perf_counter()
        found[rule.id] = list(rule.check(index))
        times[rule.id] = time.perf_counter() - t0
    return times, found


def _timing_table(times):
    return "\n".join(
        f"  {rid}: {t * 1e3:8.1f} ms"
        for rid, t in sorted(times.items(), key=lambda kv: -kv[1]))


def test_registry_holds_exactly_16_rules():
    ids = sorted(r.id for r in all_rules())
    assert len(ids) == 16, ids
    assert [i for i in ids if i.startswith("EXON")] == \
        ["EXON001", "EXON002", "EXON003"]


def test_zero_active_violations_per_rule(timed_run):
    """Every rule individually clean on the tree (baselined debt aside) —
    the per-rule split names the offender directly."""
    _, found = timed_run
    baseline = Baseline.load(BASELINE)
    for rule_id in sorted(found):
        active = [v for v in found[rule_id]
                  if baseline.match(v) is None]
        rendered = "\n".join(v.render() for v in active)
        assert not active, (
            f"{rule_id} has active violations on the tree (fix them or "
            f"baseline with a written justification):\n{rendered}")
    # with every rule's findings matched, no baseline entry may be stale
    stale = [e.fingerprint for e in baseline.stale_entries()]
    assert not stale, f"stale baseline entries: {stale}"


def test_full_run_within_time_budget(timed_run):
    times, _ = timed_run
    pre = sum(t for rid, t in times.items()
              if rid.startswith(_PRE_EXISTING))
    full = sum(times.values())
    budget = max(BUDGET_MULTIPLE * pre, BUDGET_FLOOR_S)
    assert full <= budget, (
        f"full 16-rule lint took {full:.2f}s — over budget "
        f"({BUDGET_MULTIPLE}x the 13 pre-EXON rules' {pre:.2f}s = "
        f"{budget:.2f}s). Per-rule timing (slowest first):\n"
        f"{_timing_table(times)}")


def test_bench_stamp_reports_the_same_verdict():
    """The `lint:` block bench.py stamps into BENCH_*.json next to
    `health` must carry the gate's shape and verdict."""
    import bench

    info = bench.lint_summary()
    assert set(info) == {"modules", "rules", "violations", "analysis_ms"}, (
        f"lint stamp shape drifted (or the run errored): {info}")
    assert info["rules"] == 16
    assert info["violations"] == 0, (
        f"bench stamp sees active violations the gate missed: {info}")
    assert info["modules"] > 100
    assert info["analysis_ms"] > 0
