"""Per-rule fixture tests for the flink_tpu.lint analyzer.

Every registered rule gets at least one violating and one clean fixture
snippet (ISSUE-5 acceptance criterion), synthesized as tiny packages in
tmp_path — the rules are package-relative by design, so the same code
paths run here and over the real flink_tpu tree. The trickier model
behaviors (helper-lock propagation, jax.jit(fn) resolution, deliberate
lock-order cycle, jit host-sync) get their own cases.
"""

import textwrap

import pytest

from flink_tpu.lint import ModuleIndex, all_rules, get_rule


def make_index(tmp_path, files, package="fixpkg"):
    """Materialize {relpath: source} as a package dir and index it."""
    root = tmp_path / package
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    (root / "__init__.py").touch()
    return ModuleIndex(root)


def run_rule(rule_id, tmp_path, files, package="fixpkg"):
    index = make_index(tmp_path, files, package)
    return list(get_rule(rule_id).check(index))


def test_registry_has_at_least_eight_rules():
    rules = all_rules()
    assert len(rules) >= 8
    assert len({r.id for r in rules}) == len(rules)
    families = {r.family for r in rules}
    assert {"concurrency", "device", "wire"} <= families


# ---------------------------------------------------------------------------
# CONC001 inconsistent-guard
# ---------------------------------------------------------------------------

def test_conc001_flags_attribute_written_locked_and_bare(tmp_path):
    vs = run_rule("CONC001", tmp_path, {"w.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0

            def add(self):
                with self._lock:
                    self._count += 1

            def reset(self):
                self._count = 0
    """})
    assert len(vs) == 1
    assert vs[0].symbol == "_count"
    assert "inconsistent guard" in vs[0].message
    assert "reset" in vs[0].message


def test_conc001_clean_when_every_write_is_guarded(tmp_path):
    vs = run_rule("CONC001", tmp_path, {"w.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0          # construction write: exempt

            def add(self):
                with self._lock:
                    self._count += 1

            def get(self):
                with self._lock:
                    return self._count
    """})
    assert vs == []


def test_conc001_lock_held_helper_is_not_a_false_positive(tmp_path):
    """The Meter._trim pattern: a helper ONLY called under the lock
    inherits the callers' held set (one hop)."""
    vs = run_rule("CONC001", tmp_path, {"w.py": """
        import threading

        class Meter:
            def __init__(self):
                self._lock = threading.Lock()
                self._events = []

            def mark(self, n):
                with self._lock:
                    self._events.append(n)
                    self._trim()

            def rate(self):
                with self._lock:
                    self._trim()
                    return len(self._events)

            def _trim(self):
                self._events.pop()       # runs under callers' lock
    """})
    assert vs == []


def test_conc001_container_mutation_counts_as_write(tmp_path):
    vs = run_rule("CONC001", tmp_path, {"w.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._ring = []

            def push(self, x):
                with self._lock:
                    self._ring.append(x)

            def drop_all(self):
                self._ring.clear()
    """})
    assert [v.symbol for v in vs] == ["_ring"]


def test_conc001_module_level_container_mutation(tmp_path):
    """A module-global dict mutated in place needs no `global` statement —
    the bare .pop() must still count as an unguarded write."""
    vs = run_rule("CONC001", tmp_path, {"reg.py": """
        import threading

        _LOCK = threading.Lock()
        _CACHE = {}

        def put(k, v):
            with _LOCK:
                _CACHE[k] = v

        def drop(k):
            _CACHE.pop(k, None)
    """})
    assert len(vs) == 1
    assert vs[0].symbol == "_CACHE"
    assert "drop" in vs[0].message


# ---------------------------------------------------------------------------
# CONC002 lock-order-cycle
# ---------------------------------------------------------------------------

def test_conc002_flags_deliberate_lock_order_cycle(tmp_path):
    vs = run_rule("CONC002", tmp_path, {"w.py": """
        import threading

        class W:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
    """})
    assert len(vs) == 1
    assert "lock-order cycle" in vs[0].message
    assert "_a" in vs[0].message and "_b" in vs[0].message


def test_conc002_clean_when_order_is_consistent(tmp_path):
    vs = run_rule("CONC002", tmp_path, {"w.py": """
        import threading

        class W:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
    """})
    assert vs == []


def test_conc002_self_reacquire_of_plain_lock_is_deadlock(tmp_path):
    vs = run_rule("CONC002", tmp_path, {"w.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    with self._lock:
                        pass
    """})
    assert len(vs) == 1
    assert "single-thread deadlock" in vs[0].message


def test_conc002_deadlock_through_lock_held_helper(tmp_path):
    """One-hop call-mediated edge: ab() holds _a and calls _grab_b()
    (which acquires _b), ba() nests the opposite way — a real a->b->a
    deadlock that pure lexical nesting misses."""
    vs = run_rule("CONC002", tmp_path, {"w.py": """
        import threading

        class W:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    self._grab_b()

            def _grab_b(self):
                with self._b:
                    pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
    """})
    assert len(vs) == 1
    assert "lock-order cycle" in vs[0].message


def test_conc002_self_deadlock_through_helper_call(tmp_path):
    """`with self._lock: self.close()` where close() re-acquires the same
    non-reentrant lock — single-thread deadlock via the call hop."""
    vs = run_rule("CONC002", tmp_path, {"w.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def stop(self):
                with self._lock:
                    self.close()

            def close(self):
                with self._lock:
                    pass
    """})
    assert len(vs) == 1
    assert "single-thread deadlock" in vs[0].message


def test_conc002_rlock_reentry_is_legal(tmp_path):
    vs = run_rule("CONC002", tmp_path, {"w.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    with self._lock:
                        pass
    """})
    assert vs == []


# ---------------------------------------------------------------------------
# CONC003 blocking-under-lock
# ---------------------------------------------------------------------------

def test_conc003_flags_sleep_under_lock(tmp_path):
    vs = run_rule("CONC003", tmp_path, {"w.py": """
        import threading
        import time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def nap(self):
                with self._lock:
                    time.sleep(1.0)
    """})
    assert len(vs) == 1
    assert "time.sleep()" in vs[0].message


def test_conc003_clean_when_sleep_is_outside_the_region(tmp_path):
    vs = run_rule("CONC003", tmp_path, {"w.py": """
        import threading
        import time

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def loop(self):
                with self._lock:
                    n = self._n
                time.sleep(1.0)
                return n
    """})
    assert vs == []


def test_conc003_flags_blocking_socket_call_on_local_name(tmp_path):
    """`sock.accept()` / `conn.recv()` on a plain local variable — the
    most common spelling — must match, not just attribute-chain
    receivers."""
    vs = run_rule("CONC003", tmp_path, {"w.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def serve(self, sock):
                with self._lock:
                    conn, addr = sock.accept()
                    return conn.recv(1024)
    """})
    assert len(vs) == 2
    assert any("sock.accept" in v.message for v in vs)
    assert any("conn.recv" in v.message for v in vs)


def test_conc003_distinct_fingerprints_per_site(tmp_path):
    """Two blocking calls in one scope must not collide on one
    fingerprint — otherwise one baseline entry suppresses both."""
    vs = run_rule("CONC003", tmp_path, {"w.py": """
        import threading
        import time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def nap_twice(self):
                with self._lock:
                    time.sleep(0.1)
                    time.sleep(0.2)
    """})
    assert len(vs) == 2
    assert len({v.fingerprint for v in vs}) == 2


def test_conc003_propagates_into_lock_held_helper(tmp_path):
    vs = run_rule("CONC003", tmp_path, {"w.py": """
        import threading
        import time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self._slow()

            def _slow(self):
                time.sleep(0.5)
    """})
    assert len(vs) == 1
    assert vs[0].scope == "W._slow"


# ---------------------------------------------------------------------------
# CONC004 thread-hygiene
# ---------------------------------------------------------------------------

def test_conc004_flags_thread_without_daemon_and_name(tmp_path):
    vs = run_rule("CONC004", tmp_path, {"w.py": """
        import threading

        def spawn(fn):
            threading.Thread(target=fn).start()
    """})
    assert len(vs) == 1
    assert "daemon=" in vs[0].message and "name=" in vs[0].message


def test_conc004_flags_missing_name_only(tmp_path):
    vs = run_rule("CONC004", tmp_path, {"w.py": """
        import threading

        def spawn(fn):
            threading.Thread(target=fn, daemon=True).start()
    """})
    assert len(vs) == 1
    assert "name=" in vs[0].message and "daemon=" not in vs[0].message


def test_conc004_distinct_fingerprints_per_site(tmp_path):
    vs = run_rule("CONC004", tmp_path, {"w.py": """
        import threading

        def spawn_two(fn):
            threading.Thread(target=fn).start()
            threading.Thread(target=fn).start()
    """})
    assert len(vs) == 2
    assert len({v.fingerprint for v in vs}) == 2


def test_conc004_clean_with_both_kwargs(tmp_path):
    vs = run_rule("CONC004", tmp_path, {"w.py": """
        import threading

        def spawn(fn):
            threading.Thread(target=fn, daemon=True, name="fix-worker").start()
    """})
    assert vs == []


# ---------------------------------------------------------------------------
# CONC005 no-silent-swallow
# ---------------------------------------------------------------------------

_SWALLOW_SRC = """
    def ship(gw):
        try:
            gw.send()
        except Exception:
            pass

    def drain(gw):
        try:
            gw.recv()
        except:
            pass
        try:
            gw.ack()
        except (ValueError, Exception):
            pass
"""


def test_conc005_flags_broad_silent_swallows_in_runtime(tmp_path):
    vs = run_rule("CONC005", tmp_path, {"runtime/hb.py": _SWALLOW_SRC})
    assert len(vs) == 3
    assert {v.symbol for v in vs} == {"swallow@ship", "swallow@drain",
                                      "swallow@drain#2"}
    assert any("bare except" in v.message for v in vs)


def test_conc005_checkpoint_subtree_is_scoped_too(tmp_path):
    vs = run_rule("CONC005", tmp_path, {"checkpoint/st.py": _SWALLOW_SRC})
    assert len(vs) == 3


def test_conc005_clean_outside_the_scoped_subtrees(tmp_path):
    # the same swallows in api/ are not this rule's business
    vs = run_rule("CONC005", tmp_path, {"api/ds.py": _SWALLOW_SRC})
    assert vs == []


def test_conc005_clean_when_narrow_or_logged(tmp_path):
    vs = run_rule("CONC005", tmp_path, {"runtime/hb.py": """
        import logging

        def ship(gw):
            try:
                gw.send()
            except OSError:
                pass                    # narrow type: a per-fault decision

        def drain(gw, counters):
            try:
                gw.recv()
            except Exception as e:      # broad but COUNTED, not silent
                counters["missed"] += 1
            try:
                gw.ack()
            except Exception as e:
                logging.getLogger(__name__).debug("swallowed %r", e)
    """})
    assert vs == []


# ---------------------------------------------------------------------------
# DEV001 host-sync-in-jit
# ---------------------------------------------------------------------------

def test_dev001_flags_host_sync_in_decorated_jit(tmp_path):
    vs = run_rule("DEV001", tmp_path, {"k.py": """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def step(x):
            y = jnp.cumsum(x)
            total = float(y[-1])
            arr = np.asarray(y)
            return total, arr
    """})
    labels = sorted(v.message for v in vs)
    assert len(vs) == 2
    assert any("float()" in m for m in labels)
    assert any("np.asarray()" in m for m in labels)


def test_dev001_resolves_jax_jit_of_local_function(tmp_path):
    vs = run_rule("DEV001", tmp_path, {"k.py": """
        import jax

        def build():
            def run(state, x):
                return state + x.item()
            return jax.jit(run)
    """})
    assert len(vs) == 1
    assert ".item()" in vs[0].message and "run()" in vs[0].message


def test_dev001_distinct_fingerprints_per_site(tmp_path):
    vs = run_rule("DEV001", tmp_path, {"k.py": """
        import jax

        @jax.jit
        def step(x, y):
            return x.item() + y.item()
    """})
    assert len(vs) == 2
    assert len({v.fingerprint for v in vs}) == 2


def test_dev001_clean_pure_jnp_body(tmp_path):
    vs = run_rule("DEV001", tmp_path, {"k.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            n = int(x.shape[0])          # static metadata: fine
            return jnp.cumsum(x) / n

        def readback(y):
            return float(y[-1])          # outside jit: fine
    """})
    assert vs == []


# ---------------------------------------------------------------------------
# DEV002 jit-in-loop
# ---------------------------------------------------------------------------

def test_dev002_flags_jit_inside_loop_body(tmp_path):
    vs = run_rule("DEV002", tmp_path, {"k.py": """
        import jax

        def apply_all(xs):
            outs = []
            for x in xs:
                outs.append(jax.jit(lambda v: v * 2)(x))
            return outs
    """})
    assert len(vs) == 1
    assert "for loop" in vs[0].message


def test_dev002_clean_cached_builder(tmp_path):
    vs = run_rule("DEV002", tmp_path, {"k.py": """
        import functools
        import jax

        @functools.lru_cache(maxsize=None)
        def _build(n):
            return jax.jit(lambda v: v[:n])

        def apply_all(xs, n):
            fn = _build(n)
            return [fn(x) for x in xs]
    """})
    assert vs == []


def test_dev002_def_inside_loop_is_not_flagged(tmp_path):
    """A builder *defined* in a loop runs later — only direct jit calls in
    the loop body are per-iteration hazards."""
    vs = run_rule("DEV002", tmp_path, {"k.py": """
        import jax

        def make(fs):
            builders = []
            for f in fs:
                def build(f=f):
                    return jax.jit(f)
                builders.append(build)
            return builders
    """})
    assert vs == []


# ---------------------------------------------------------------------------
# DEV003 jax-free-control-plane
# ---------------------------------------------------------------------------

def test_dev003_flags_module_level_jax_in_control_plane(tmp_path):
    vs = run_rule("DEV003", tmp_path, {"runtime/rpc.py": """
        import jax

        def call():
            return jax.devices()
    """})
    assert len(vs) == 1
    assert "imports jax at module level" in vs[0].message


def test_dev003_lazy_jax_import_is_the_sanctioned_path(tmp_path):
    vs = run_rule("DEV003", tmp_path, {"runtime/rpc.py": """
        def device_path():
            import jax

            return jax.devices()
    """})
    assert vs == []


# ---------------------------------------------------------------------------
# WIRE001 no-bare-pickle
# ---------------------------------------------------------------------------

def test_wire001_flags_pickle_loads_on_network_plane(tmp_path):
    vs = run_rule("WIRE001", tmp_path, {"runtime/blob.py": """
        import pickle

        def decode(b):
            return pickle.loads(b)
    """})
    assert len(vs) == 1
    assert "pickle.loads" in vs[0].message


def test_wire001_flags_from_import_spelling(tmp_path):
    vs = run_rule("WIRE001", tmp_path, {"fs/store.py": """
        from pickle import loads

        def decode(b):
            return loads(b)
    """})
    assert len(vs) == 1
    assert "import loads" in vs[0].message


def test_wire001_fingerprint_is_line_independent(tmp_path):
    """Prepending unrelated code must not orphan a baseline entry (the
    symbol is occurrence-indexed, never line-numbered)."""
    files = {"runtime/blob.py": """
        import pickle

        def decode(b):
            return pickle.loads(b)
    """}
    fp1 = run_rule("WIRE001", tmp_path / "a", files)[0].fingerprint
    files2 = {"runtime/blob.py": """
        import os
        import pickle

        HEADER = os.sep

        def decode(b):
            return pickle.loads(b)
    """}
    fp2 = run_rule("WIRE001", tmp_path / "b", files2)[0].fingerprint
    assert fp1 == fp2


def test_wire001_clean_outside_network_planes_and_dumps_ok(tmp_path):
    vs = run_rule("WIRE001", tmp_path, {
        "security/framing.py": """
            import pickle

            def restricted_loads(b):
                return pickle.loads(b)   # the sanctioned implementation site
        """,
        "runtime/blob.py": """
            import pickle

            def encode(obj):
                return pickle.dumps(obj)   # serialization out is fine
        """,
    })
    assert vs == []


# ---------------------------------------------------------------------------
# WIRE002 serialization-free-dataplane
# ---------------------------------------------------------------------------

def test_wire002_flags_dumps_call_in_dataplane(tmp_path):
    vs = run_rule("WIRE002", tmp_path, {"runtime/dataplane.py": """
        from pickle import dumps

        def frame(batch):
            return dumps(batch)
    """})
    assert len(vs) == 2      # the from-import AND the call
    assert any("dumps(...)" in v.message for v in vs)


def test_wire002_clean_dataplane(tmp_path):
    vs = run_rule("WIRE002", tmp_path, {"runtime/dataplane.py": """
        def send(transport, sock, batch):
            return transport.send_data_frame(sock, batch)
    """})
    assert vs == []


# ---------------------------------------------------------------------------
# ARCH001 layer-dag
# ---------------------------------------------------------------------------

def test_arch001_flags_upward_module_level_import(tmp_path):
    vs = run_rule("ARCH001", tmp_path, {"core/thing.py": """
        import fixpkg.runtime.executor
    """})
    assert len(vs) == 1
    assert "layer 'core'" in vs[0].message


def test_arch001_from_package_import_module_spelling(tmp_path):
    """`from fixpkg import runtime` binds fixpkg.runtime — the ordinary
    spelling of the violation must not bypass the banned-prefix check."""
    vs = run_rule("ARCH001", tmp_path, {"core/thing.py": """
        from fixpkg import runtime
    """})
    assert len(vs) == 1
    assert "fixpkg.runtime" in vs[0].message


def test_arch001_lazy_import_is_the_escape_hatch(tmp_path):
    vs = run_rule("ARCH001", tmp_path, {"core/thing.py": """
        def execute():
            from fixpkg.runtime import executor

            return executor
    """})
    assert vs == []


def test_arch001_resolves_relative_imports(tmp_path):
    vs = run_rule("ARCH001", tmp_path, {"core/thing.py": """
        from ..runtime import executor
    """})
    assert len(vs) == 1


def test_arch001_relative_import_from_package_init(tmp_path):
    """In pkg/core/__init__.py the dotted module name IS the package, so
    `from ..runtime.executor import X` resolves one level differently than
    in a plain module — and an in-layer sibling import must stay clean."""
    vs = run_rule("ARCH001", tmp_path, {
        "core/__init__.py": """
            from ..runtime.executor import X
        """,
        "utils/__init__.py": """
            from .arrays import Y      # sibling within the layer: fine
        """,
        "utils/arrays.py": "Y = 1\n",
    })
    assert len(vs) == 1
    assert "core" in vs[0].message and "runtime" in vs[0].message


# ---------------------------------------------------------------------------
# ARCH002 checkpoint-below-runtime
# ---------------------------------------------------------------------------

def test_arch002_flags_even_lazy_runtime_imports(tmp_path):
    vs = run_rule("ARCH002", tmp_path, {"checkpoint/coordinator.py": """
        def restore():
            from fixpkg.runtime import executor

            return executor
    """})
    assert len(vs) == 1
    assert "lazy imports included" in vs[0].message


def test_arch002_clean_callback_flow(tmp_path):
    vs = run_rule("ARCH002", tmp_path, {"checkpoint/coordinator.py": """
        class Coordinator:
            def __init__(self, state_bytes_fn):
                self.state_bytes_fn = state_bytes_fn
    """})
    assert vs == []


# ---------------------------------------------------------------------------
# DOC001 config-docs-complete
# ---------------------------------------------------------------------------

CONFIG_SRC = """
    class ConfigOptions:
        @staticmethod
        def key(k):
            return k


    OPT = ConfigOptions.key("lint.fixture.some-option")
"""


def test_doc001_flags_undocumented_option(tmp_path):
    vs = run_rule("DOC001", tmp_path, {"config.py": CONFIG_SRC})
    assert len(vs) == 1
    assert "lint.fixture.some-option" in vs[0].message


def test_doc001_clean_when_documented(tmp_path):
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "configuration.md").write_text(
        "| `lint.fixture.some-option` | ... |\n")
    vs = run_rule("DOC001", tmp_path, {"config.py": CONFIG_SRC})
    assert vs == []


# ---------------------------------------------------------------------------
# cross-rule sanity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rule_id",
                         sorted(r.id for r in all_rules()))
def test_every_rule_is_silent_on_an_empty_package(rule_id, tmp_path):
    vs = run_rule(rule_id, tmp_path, {"empty.py": "X = 1\n"})
    assert vs == []
