"""Combiner exactness property test (ISSUE-15): every builtin
DeviceAggregator run combined-then-exchanged (parallel.mesh.local-combine)
vs exchanged-raw vs the scalar host oracle — byte parity under uniform AND
zipf keys, ragged batches and dead lanes — plus proof that a
non-decomposable aggregate refuses the combine path and still matches.

Byte parity is the bar because the combine is exact BY CONSTRUCTION: the
per-(source shard, key, rel-slice) partials are pre-reduced by the same
add/min/max scatter combiners the ring ingest applies, so the ring holds
identical values regardless of which side of the interconnect did the
folding. Values are integer-valued f32 (the repo-wide convention for
exact-equality float parity)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
from flink_tpu.ops.aggregators import (
    AccField,
    DeviceAggregator,
    decomposable,
    resolve,
)
from flink_tpu.parallel.sharded_superscan import ShardedFusedPipeline
from flink_tpu.runtime.oracle_window_operator import OracleWindowOperator
from flink_tpu.utils.jax_compat import HAS_SHARD_MAP

pytestmark = pytest.mark.skipif(
    not HAS_SHARD_MAP, reason="this jax build lacks shard_map")

NUM_KEYS = 192
WINDOW_MS, SLIDE_MS = 2_000, 500


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("shards",))


def _zipf_keys(rng, size, num_keys, s=1.0):
    ranks = np.arange(1, num_keys + 1, dtype=np.float64)
    cdf = np.cumsum(1.0 / ranks ** s)
    return np.searchsorted(cdf / cdf[-1], rng.random(size)).astype(np.int32)


def _stream(seed, skewed, with_vals):
    """Ragged batches (including an EMPTY one): the planner pads the
    short steps with dead (-1) lanes, so raggedness IS the dead-lane
    case — the combine's partial scatter must drop them exactly like the
    raw exchange's receive side does."""
    rng = np.random.default_rng(seed)
    sizes = [600, 173, 0, 512, 41, 600, 257, 600]
    batches, wms = [], []
    t = 0.0
    for size in sizes:
        if skewed:
            keys = _zipf_keys(rng, size, NUM_KEYS)
        else:
            keys = rng.integers(0, NUM_KEYS, size).astype(np.int32)
        base = t + np.sort(rng.random(size)) * 400.0 if size else \
            np.empty(0, np.float64)
        ts = np.maximum(base.astype(np.int64)
                        - rng.integers(0, 120, size), 0)
        vals = (rng.integers(0, 9, size).astype(np.float32)
                if with_vals else None)
        batches.append((keys, vals, ts))
        wms.append(int(t + 400.0) - 150)
        t += 400.0
    return batches, wms


def _drain(pipe, batches, wms):
    out = []
    for lo in range(0, len(batches), 3):
        out.extend(pipe.process_superbatch(
            batches[lo:lo + 3], wms[lo:lo + 3]))
    return out


def _raw_rows(out):
    """(window start, counts, raw field arrays) — the byte-parity view."""
    rows = []
    for (w, counts, fields) in out:
        rows.append((w.start, np.asarray(counts),
                     {k: np.asarray(v) for k, v in fields.items()}))
    rows.sort(key=lambda r: r[0])
    return rows


def _assert_byte_equal(a, b):
    assert len(a) == len(b) > 0
    for (sa, ca, fa), (sb, cb, fb) in zip(a, b):
        assert sa == sb
        np.testing.assert_array_equal(ca, cb)
        assert fa.keys() == fb.keys()
        for name in fa:
            np.testing.assert_array_equal(fa[name], fb[name])


def _oracle(agg_name, batches, wms):
    op = OracleWindowOperator(
        SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS),
        resolve(agg_name).python_equivalent())
    for (kid, vals, ts), wm in zip(batches, wms):
        for i in range(len(ts)):
            v = 1.0 if vals is None else float(vals[i])
            op.process_record(int(kid[i]), v, int(ts[i]))
        op.process_watermark(wm)
    return {(key, w.start): value
            for key, w, value, _ts in op.drain_output()}


def _extract(agg_name, rows):
    """Pipeline rows -> {(key, window start): oracle-comparable value}."""
    out = {}
    field = {"sum": "sum", "min": "min", "max": "max"}.get(agg_name)
    for start, counts, fields in rows:
        for k in np.flatnonzero(counts > 0):
            if agg_name == "count":
                out[(int(k), start)] = int(counts[k])
            elif agg_name == "mean":
                out[(int(k), start)] = float(fields["sum"][k]) / counts[k]
            else:
                out[(int(k), start)] = float(fields[field][k])
    return out


def _pipe(aggregate, local_combine):
    return ShardedFusedPipeline(
        _mesh(), SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS), aggregate,
        key_capacity=NUM_KEYS, num_slices=16, nsb=4, fires_per_step=4,
        out_rows=16, chunk=512, local_combine=local_combine)


@pytest.mark.parametrize("skewed", [False, True], ids=["uniform", "zipf"])
@pytest.mark.parametrize("agg", ["count", "sum", "min", "max", "mean"])
def test_combined_matches_raw_and_oracle(agg, skewed):
    batches, wms = _stream(11 if skewed else 5, skewed, agg != "count")
    raw = _pipe(agg, False)
    combined = _pipe(agg, True)
    assert combined.local_combine, "combine flag did not engage"
    assert not raw.local_combine
    rows_raw = _raw_rows(_drain(raw, batches, wms))
    rows_comb = _raw_rows(_drain(combined, batches, wms))
    # byte parity: same windows, same counts, same field BYTES
    _assert_byte_equal(rows_raw, rows_comb)
    # and both equal the scalar host oracle's extracted values
    expect = _oracle(agg, batches, wms)
    got = _extract(agg, rows_comb)
    assert got.keys() == expect.keys()
    for key in expect:
        assert got[key] == pytest.approx(expect[key]), key


def test_non_decomposable_refuses_combine_and_still_matches():
    """A DeviceAggregator that opts out of pre-aggregation (modeling the
    closure tier — q5's top-K post-processing never resolves to a
    DeviceAggregator at all, but a custom spec can also declare its merge
    non-decomposable) must transparently keep the route-raw exchange
    under the flag, at identical results."""
    closed = DeviceAggregator(
        "sum_closed",
        (AccField("sum", np.float32, 0, "add"),),
        lambda f: f["sum"],
        combinable=False,
    )
    assert not decomposable(closed)
    assert decomposable(resolve("sum"))
    batches, wms = _stream(3, True, True)
    refused = _pipe(closed, True)
    assert not refused.local_combine, (
        "non-decomposable aggregate must refuse the combine path")
    rows_refused = _raw_rows(_drain(refused, batches, wms))
    rows_raw = _raw_rows(_drain(_pipe("sum", False), batches, wms))
    _assert_byte_equal(rows_raw, rows_refused)


def test_combine_under_routing_table_matches_raw():
    """Both layers composed: local combine OVER a non-identity routing
    table (hot groups remapped mid-stream) still produces the raw path's
    exact bytes — placement and pre-reduction are independent and neither
    changes a result."""
    batches, wms = _stream(17, True, True)
    raw = _pipe("sum", False)
    both = ShardedFusedPipeline(
        _mesh(), SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS), "sum",
        key_capacity=NUM_KEYS, num_slices=16, nsb=4, fires_per_step=4,
        out_rows=16, chunk=512, local_combine=True, skew_routing=True)
    out = []
    for i, lo in enumerate(range(0, len(batches), 3)):
        out.extend(both.process_superbatch(
            batches[lo:lo + 3], wms[lo:lo + 3]))
        if i == 0:
            loads = both.mesh_group_loads()
            from flink_tpu.parallel.routing import plan_balanced_assignment

            both.set_routing_assignment(
                plan_balanced_assignment(loads, both.n))
    _assert_byte_equal(_raw_rows(_drain(raw, batches, wms)),
                       _raw_rows(out))
    assert both.routing_version() == 1
