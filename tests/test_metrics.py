"""Observability tests: metric types, registry/groups, reporters, runtime
gauges, checkpoint spans (reference O1-O3)."""

import time

import numpy as np

from flink_tpu.api.datastream import StreamExecutionEnvironment
from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
from flink_tpu.checkpoint.coordinator import CheckpointCoordinator
from flink_tpu.checkpoint.storage import MemoryCheckpointStorage
from flink_tpu.config import CheckpointingOptions, Configuration, ExecutionOptions
from flink_tpu.connectors.source import Batch, DataGeneratorSource
from flink_tpu.core.watermarks import WatermarkStrategy
from flink_tpu.metrics.registry import (
    Counter,
    Histogram,
    InMemoryReporter,
    Meter,
    MetricRegistry,
    prometheus_text,
)
from flink_tpu.metrics.traces import InMemoryTraceReporter, TraceRegistry
from flink_tpu.runtime.minicluster import JobStatus, MiniCluster
from flink_tpu.utils.arrays import obj_array


def test_metric_types():
    c = Counter()
    c.inc()
    c.inc(4)
    assert c.count == 5

    clock = [0.0]
    m = Meter(clock=lambda: clock[0])
    m.mark(10)
    clock[0] = 2.0
    m.mark(10)
    assert m.count == 20
    assert m.rate() == 10.0  # 20 events over 2s

    h = Histogram(size=100)
    for i in range(100):
        h.update(i)
    stats = h.stats()
    assert stats["min"] == 0 and stats["max"] == 99
    assert 45 <= stats["p50"] <= 55
    assert stats["p99"] >= 95


def test_registry_groups_and_prometheus():
    reg = MetricRegistry()
    g = reg.group("job", "operator", "window@2")
    c = g.counter("numRecordsIn")
    c.inc(7)
    g.gauge("watermark", lambda: 123)
    rep = InMemoryReporter()
    reg.add_reporter(rep)
    reg.report()
    assert rep.last["job.operator.window@2.numRecordsIn"] == 7
    assert rep.last["job.operator.window@2.watermark"] == 123
    text = prometheus_text(reg.all_metrics())
    assert "job_operator_window_2_numRecordsIn 7" in text

    # re-registration returns the same metric (idempotent)
    assert reg.group("job", "operator", "window@2").counter("numRecordsIn") is c


def test_runtime_metrics_and_checkpoint_spans(tmp_path):
    config = Configuration()
    config.set(ExecutionOptions.BATCH_SIZE, 100)
    config.set(CheckpointingOptions.INTERVAL_MS, 1)
    config.set(CheckpointingOptions.DIRECTORY, str(tmp_path / "chk"))

    def gen(idx: np.ndarray) -> Batch:
        values = [(int(i % 5), 1.0, int(i * 10)) for i in idx]
        return Batch(obj_array(values), (idx * 10).astype(np.int64))

    env = StreamExecutionEnvironment(config)
    stream = env.from_source(
        DataGeneratorSource(gen, count=1000),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    sink = (
        stream.key_by(lambda x: x[0])
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .collect()
    )
    client = env.execute_async("metrics-job")
    spans = InMemoryTraceReporter()
    # traces registry is created by the cluster; attach reporter promptly
    deadline = time.time() + 10
    while not hasattr(client, "traces") and time.time() < deadline:
        time.sleep(0.005)
    client.traces.add_reporter(spans)
    assert client.wait(60) == JobStatus.FINISHED

    rep = InMemoryReporter()
    client.metrics.add_reporter(rep)
    client.metrics.report()
    assert rep.last["job.numRecordsIn"] == 1000
    assert rep.last["job.numRecordsInPerSecond"] > 0
    assert 0 < rep.last["job.busyTimeRatio"] <= 1.0
    win_key = next(k for k in rep.last if k.endswith("numLateRecordsDropped"))
    assert rep.last[win_key] == 0
    assert rep.last["job.stepLatencyMs"]["count"] >= 10
    # checkpoint spans were reported (attached early enough to catch some)
    assert any(s.name == "Checkpoint" for s in spans.spans)
    assert all(s.duration_ms >= 0 for s in spans.spans)


def test_flamegraph_sampling_and_tree():
    import threading
    import time

    from flink_tpu.metrics.flamegraph import flame_graph

    stop = threading.Event()

    def busy_loop():
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=busy_loop, name="hot-task-thread", daemon=True)
    t.start()
    try:
        fg = flame_graph(duration_s=0.3, hz=100, thread_filter="hot-task")
        assert fg["samples"] > 0
        names = {c["name"] for c in fg["tree"]["children"]}
        assert "hot-task-thread" in names
        flat = str(fg["folded"])
        assert "busy_loop" in flat
    finally:
        stop.set()


# ---------------------------------------------------------------------------
# OTel-shape trace export (OTLP/JSON)
# ---------------------------------------------------------------------------

def test_otlp_span_encoding():
    from flink_tpu.metrics.otel import span_to_otlp, spans_to_otlp
    from flink_tpu.metrics.traces import Span

    s = Span("checkpointing", "Checkpoint", 1000.0, 1250.5,
             {"checkpointId": 7, "status": "COMPLETED", "full": True,
              "ratio": 0.5})
    enc = span_to_otlp(s)
    assert len(enc["traceId"]) == 32 and len(enc["spanId"]) == 16
    assert enc["name"] == "checkpointing.Checkpoint"
    assert enc["startTimeUnixNano"] == str(int(1000.0 * 1e6))
    assert enc["endTimeUnixNano"] == str(int(1250.5 * 1e6))
    attrs = {a["key"]: a["value"] for a in enc["attributes"]}
    assert attrs["checkpointId"] == {"intValue": "7"}
    assert attrs["status"] == {"stringValue": "COMPLETED"}
    assert attrs["full"] == {"boolValue": True}
    assert attrs["ratio"] == {"doubleValue": 0.5}

    doc = spans_to_otlp([enc], "svc")
    rs = doc["resourceSpans"][0]
    assert rs["resource"]["attributes"][0]["value"]["stringValue"] == "svc"
    assert rs["scopeSpans"][0]["spans"] == [enc]


def test_otlp_reporter_buffers_and_flushes_to_file(tmp_path):
    import json

    from flink_tpu.metrics.otel import OtlpJsonTraceReporter
    from flink_tpu.metrics.traces import TraceRegistry

    path = str(tmp_path / "traces.otlp.jsonl")
    reg = TraceRegistry()
    rep = OtlpJsonTraceReporter(service_name="svc", path=path)
    reg.add_reporter(rep)
    for i in range(3):
        reg.report(reg.span("restart", "JobRestart")
                   .set_attribute("attempt", i).end())
    payload = rep.payload()
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert len(spans) == 3
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 3
    first = json.loads(lines[0])
    assert first["resourceSpans"][0]["scopeSpans"][0]["spans"][0][
        "name"] == "restart.JobRestart"


def test_pipeline_latency_markers_reach_sinks():
    """O3: LatencyMarker analogue — wall-clock markers from the source flow
    through chains/windows to sinks, whose histogram measures transit."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.config import Configuration, ExecutionOptions
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.executor import JobRuntime, SinkRunner

    conf = Configuration()
    conf.set(ExecutionOptions.BATCH_SIZE, 16)
    env = StreamExecutionEnvironment.get_execution_environment(conf)
    data = [(f"k{i % 3}", i * 100) for i in range(64)]
    (
        env.from_collection(
            data, timestamp_fn=lambda x: x[1],
            watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
        )
        .key_by(lambda x: x[0])
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .collect()
    )
    rt = JobRuntime(plan(env._sinks), conf)
    rt.run()
    sink = [r for r in rt.runners if isinstance(r, SinkRunner)][0]
    stats = sink._latency_hist.stats()
    assert stats["count"] >= 4              # one marker per source batch
    assert 0 <= stats["p50"] < 10_000       # sane wall-clock transit ms
